"""Mixing-matrix invariants (reference semantics: simulators.py:40-86)."""

import numpy as np
import pytest

from dopt.topology import (
    MixingMatrices,
    build_adjacency,
    build_mixing_matrices,
    shift_decomposition,
)


@pytest.mark.parametrize("topology", ["circle", "star", "complete", "dynamic"])
def test_adjacency_zero_diagonal_and_symmetry(topology):
    for g in build_adjacency(topology, 6):
        assert np.all(np.diag(g) == 0), "reference adjacency has zero diagonal"
        assert np.array_equal(g, g.T)


def test_circle_is_ring():
    (g,) = build_adjacency("circle", 5)
    for i in range(5):
        assert g[i, (i + 1) % 5] == 1 and g[i, (i - 1) % 5] == 1
    assert g.sum() == 10


def test_star_hub():
    (g,) = build_adjacency("star", 6)
    assert g[0].sum() == 5 and np.all(g[1:, 1:] == 0)


def test_complete_misspelling_accepted():
    (g,) = build_adjacency("compelete", 4)  # reference spelling, simulators.py:54
    assert g.sum() == 12


def test_dynamic_schedule_single_edges():
    graphs = build_adjacency("dynamic", 6)
    assert len(graphs) == 6
    for t, g in enumerate(graphs):
        assert g.sum() == 2
        assert g[t, (t + 1) % 6] == 1 and g[(t + 1) % 6, t] == 1


def test_random_schedule_connected_no_isolated():
    graphs = build_adjacency("random", 8, p=0.3, schedule_len=5, seed=3)
    assert len(graphs) == 5
    for g in graphs:
        assert np.all(g.sum(axis=1) >= 2), "Hamiltonian cycle guarantees degree >= 2"
        assert np.all(np.diag(g) == 0)


@pytest.mark.parametrize("topology", ["circle", "star", "complete"])
def test_stochastic_mode_row_stochastic_zero_diag(topology):
    mm = build_mixing_matrices(topology, "stochastic", 6, seed=1)
    assert mm.is_row_stochastic()
    for m in mm.matrices:
        assert np.all(np.diag(m) == 0), "faithful consensus excludes self (SURVEY §6.2)"


@pytest.mark.parametrize("topology", ["circle", "complete"])
def test_double_stochastic_mode(topology):
    mm = build_mixing_matrices(topology, "double_stochastic", 6, seed=1)
    assert mm.is_doubly_stochastic(tol=1e-8)
    for m in mm.matrices:
        assert np.all(np.diag(m) == 0)


@pytest.mark.parametrize("mode", ["stochastic", "double_stochastic"])
def test_dynamic_isolated_workers_keep_weights(mode):
    # Single-edge graphs leave n-2 workers isolated; they must keep their
    # own weights (identity row), not NaN/zero out like the reference does.
    mm = build_mixing_matrices("dynamic", mode, 6, seed=1)
    assert mm.is_row_stochastic()
    for t, m in enumerate(mm.matrices):
        edge = {t, (t + 1) % 6}
        for i in range(6):
            if i in edge:
                assert m[i, i] == 0
            else:
                assert m[i, i] == 1.0


def test_double_stochastic_star_infeasible():
    # A zero-diagonal doubly-stochastic star matrix does not exist for n>2;
    # the reference's Sinkhorn loop hangs here (its star/double CSVs are
    # empty). We raise instead.
    with pytest.raises(ValueError, match="doubly-stochastic"):
        build_mixing_matrices("star", "double_stochastic", 6, seed=1)


def test_metropolis_doubly_stochastic_with_self_loops():
    mm = build_mixing_matrices("circle", "metropolis", 8)
    assert mm.is_doubly_stochastic()
    for m in mm.matrices:
        assert np.all(np.diag(m) > 0)
    assert mm.spectral_gap() > 0


@pytest.mark.parametrize("topology", ["circle", "star", "dynamic"])
def test_uniform_mode_row_stochastic_even_when_isolated(topology):
    # Regression: uniform mode must give isolated workers (dynamic
    # single-edge rounds) an identity row, not an all-zero row.
    mm = build_mixing_matrices(topology, "uniform", 6)
    assert mm.is_row_stochastic()


def test_ones_mode_is_raw_adjacency():
    mm = build_mixing_matrices("complete", "ones", 4)
    assert np.array_equal(mm.matrices[0], np.ones((4, 4)) - np.eye(4))


def test_self_weight_lazy_gossip():
    mm = build_mixing_matrices("circle", "stochastic", 6, seed=1, self_weight=True)
    assert mm.is_row_stochastic()
    for m in mm.matrices:
        assert np.all(np.diag(m) == 0.5)


def test_for_round_cycles_schedule():
    mm = build_mixing_matrices("dynamic", "stochastic", 5, seed=0)
    assert len(mm.matrices) == 5
    assert np.array_equal(mm.for_round(7), mm.matrices[2])


def test_shift_decomposition_ring():
    mm = build_mixing_matrices("circle", "metropolis", 8)
    shifts = shift_decomposition(mm.matrices[0])
    shift_ids = sorted(s for s, _ in shifts)
    assert shift_ids == [0, 1, 7]  # self, +1, -1 (mod 8)
    # Reconstruct and compare.
    w = np.zeros((8, 8))
    for s, c in shifts:
        for i in range(8):
            w[i, (i + s) % 8] = c[i]
    np.testing.assert_allclose(w, mm.matrices[0])


def test_shift_decomposition_dense_bails():
    mm = build_mixing_matrices("complete", "stochastic", 8, seed=0)
    assert shift_decomposition(mm.matrices[0], max_shifts=3) is None


def test_spectral_gap_ordering():
    ring = build_mixing_matrices("circle", "metropolis", 16)
    complete = build_mixing_matrices("complete", "metropolis", 16)
    assert complete.spectral_gap() > ring.spectral_gap()


def test_spectral_gap_product_vs_mean_for_schedules():
    n = 8
    # Zero-diagonal single-edge rounds (reference 'dynamic' semantics)
    # are pure model SWAPS — permutation matrices, so the schedule never
    # contracts at all.  The per-period product exposes that (gap 0);
    # the round-mean claims a healthy positive gap.  This is the case
    # where the mean diagnostic actively misleads.
    dyn_swap = build_mixing_matrices("dynamic", "uniform", n)
    assert dyn_swap.spectral_gap(kind="mean") > 0.05
    assert dyn_swap.spectral_gap() == pytest.approx(0.0, abs=1e-9)

    # Self-inclusive dynamic rounds DO contract; per-round the schedule
    # is still slower than a static metropolis ring (one edge per round
    # vs all edges every round), and here the mean under-states it.
    dyn = build_mixing_matrices("dynamic", "metropolis", n)
    ring = build_mixing_matrices("circle", "metropolis", n)
    dyn_per_round = 1.0 - (1.0 - dyn.spectral_gap()) ** (1.0 / len(dyn.matrices))
    assert ring.spectral_gap() > dyn_per_round > 0

    # Static schedule: both kinds agree exactly.
    assert ring.spectral_gap() == pytest.approx(ring.spectral_gap(kind="mean"))

    with pytest.raises(ValueError, match="kind"):
        ring.spectral_gap(kind="nope")


def test_stacked_shape():
    mm = build_mixing_matrices("dynamic", "stochastic", 6, seed=0)
    assert mm.stacked().shape == (6, 6, 6)
    assert isinstance(mm, MixingMatrices)


def test_repair_for_dropout_invariants():
    from dopt.topology import repair_for_dropout

    for topo, mode in [("complete", "uniform"), ("circle", "metropolis"),
                       ("star", "stochastic")]:
        w = build_mixing_matrices(topo, mode, 8, seed=3).matrices[0]
        alive = np.array([1, 0, 1, 1, 0, 1, 1, 0], float)
        r = repair_for_dropout(w, alive)
        # rows still stochastic
        np.testing.assert_allclose(r.sum(axis=1), 1.0, atol=1e-12)
        # no edges INTO dead workers from live rows
        dead = np.nonzero(alive == 0)[0]
        live = np.nonzero(alive == 1)[0]
        assert np.all(r[np.ix_(live, dead)] == 0), (topo, mode)
        # dead rows frozen to identity
        for i in dead:
            row = np.zeros(8); row[i] = 1.0
            np.testing.assert_array_equal(r[i], row)


def test_repair_for_dropout_isolated_live_worker():
    from dopt.topology import repair_for_dropout

    # star, leaf workers only talk to the hub; kill the hub → every
    # zero-diagonal leaf row would be empty and must fall back to self.
    w = build_mixing_matrices("star", "stochastic", 6, seed=0).matrices[0]
    alive = np.ones(6); alive[0] = 0  # hub is worker 0
    r = repair_for_dropout(w, alive)
    np.testing.assert_allclose(r, np.eye(6))


def test_repair_for_dropout_all_alive_identity_op():
    from dopt.topology import repair_for_dropout

    w = build_mixing_matrices("circle", "stochastic", 8, seed=1).matrices[0]
    np.testing.assert_allclose(repair_for_dropout(w, np.ones(8)), w)


def test_hierarchical_schedule_structure():
    from dopt.topology import Topology, build_mixing_matrices
    from dopt.parallel.multihost import dcn_edge_count

    graphs = Topology.hierarchical(8, groups=2, period=4)
    assert len(graphs) == 4
    # rounds 0-2: intra-group only; round 3 (cycle end): global mix.
    # Global mixes LAST — a round-0 global mix would average the
    # workers' identical init, a no-op.
    assert dcn_edge_count(graphs[-1], 2) > 0
    for g in graphs[:-1]:
        assert dcn_edge_count(g, 2) == 0
        # block-diagonal complete: worker 0 sees 1-3 but not 4-7
        assert g[0, 1] == 1.0 and g[0, 4] == 0.0

    mm = build_mixing_matrices("hierarchical", "metropolis", 8,
                               groups=2, period=4)
    assert mm.is_row_stochastic()
    # for_round cycles with the global matrix at t % 4 == 3
    assert (mm.for_round(3) == mm.for_round(7)).all()
    assert not (mm.for_round(0) == mm.for_round(3)).all()


def test_hierarchical_validation():
    from dopt.topology import Topology

    with pytest.raises(ValueError):
        Topology.hierarchical(9, groups=2)
    with pytest.raises(ValueError):
        Topology.hierarchical(8, groups=2, period=1)


def test_one_peer_exp_matrix_invariants():
    mm = build_mixing_matrices("one_peer_exp", "metropolis", 8)
    assert len(mm.matrices) == 3  # log2(8) graphs, cycled per round
    for m in mm.matrices:
        # dyadic 0.5s sum EXACTLY in binary floating point
        assert np.all(m.sum(0) == 1.0) and np.all(m.sum(1) == 1.0)
        # every worker talks to exactly ONE peer: self + one off-diag
        assert np.all((m != 0).sum(axis=1) == 2)
        assert np.all(np.diag(m) == 0.5)


def test_one_peer_exp_period_product_is_uniform():
    # The union over a period is the exponential graph; the PRODUCT of
    # the period's matrices is exact uniform averaging — the finite-time
    # consensus property that makes one edge per round contract like a
    # well-connected topology.
    n = 8
    mm = build_mixing_matrices("one_peer_exp", "metropolis", n)
    prod = np.eye(n)
    for t in range(len(mm.matrices)):
        prod = mm.for_round(t) @ prod
    np.testing.assert_allclose(prod, np.ones((n, n)) / n, atol=1e-12)


def test_one_peer_exp_validation():
    with pytest.raises(ValueError, match="power-of-2"):
        build_mixing_matrices("one_peer_exp", "metropolis", 6)
    with pytest.raises(ValueError, match="self_weight"):
        build_mixing_matrices("one_peer_exp", "metropolis", 8,
                              self_weight=True)


def test_schedule_shift_union_one_peer_exp():
    from dopt.topology import schedule_shift_decomposition

    mm = build_mixing_matrices("one_peer_exp", "metropolis", 8)
    assert schedule_shift_decomposition(mm) == (0, 1, 2, 4)
    # extra_shifts forces the dropout-repair identity diagonal into the
    # compiled set; already present here, so it is a no-op — and
    # canonicalised mod n, so -1 means the n-1 diagonal.
    assert schedule_shift_decomposition(mm, extra_shifts=(0,)) == (0, 1, 2, 4)
    assert schedule_shift_decomposition(mm, extra_shifts=(-1,)) == \
        (0, 1, 2, 4, 7)


def test_schedule_shift_union_bail_never_mutates_extra_shifts():
    from dopt.topology import schedule_shift_decomposition

    mm = build_mixing_matrices("complete", "metropolis", 8)
    extra = [0]
    assert schedule_shift_decomposition(mm, max_shifts=3,
                                        extra_shifts=extra) is None
    assert extra == [0], "None bail mutated the caller's extra_shifts"


def test_schedule_shift_union_extra_shift_zero_for_repair():
    from dopt.topology import (coeffs_for_matrix, repair_for_dropout,
                               schedule_shift_decomposition)

    # Zero-diagonal reference modes have no shift-0 diagonal, but
    # dropout repair writes identity rows; the engine forces shift 0 so
    # the repaired matrix stays inside the compiled set.
    mm = build_mixing_matrices("circle", "stochastic", 8, seed=1)
    bare = schedule_shift_decomposition(mm)
    assert 0 not in bare
    ids = schedule_shift_decomposition(mm, extra_shifts=(0,))
    assert ids == tuple(sorted({0, *bare}))
    alive = np.ones(8)
    alive[3] = 0
    repaired = repair_for_dropout(mm.matrices[0], alive)
    coeffs = coeffs_for_matrix(repaired, ids)
    assert coeffs.shape == (len(ids), 8)
    with pytest.raises(ValueError):
        coeffs_for_matrix(repaired, bare)  # identity row not covered


def test_schedule_shift_union_dense_fallback():
    from dopt.topology import schedule_shift_decomposition

    # A time-varying schedule whose UNION collapses to (near-)dense must
    # bail to the all_gather path even though each round is sparse.
    mm = build_mixing_matrices("random", "metropolis", 8, p=0.6,
                               schedule_len=6, seed=2)
    assert schedule_shift_decomposition(mm, max_shifts=4) is None
    # and with no budget it returns the full union rather than bailing
    ids = schedule_shift_decomposition(mm)
    assert ids is not None and len(ids) > 4
