from dopt.engine.federated import FederatedTrainer
from dopt.engine.gossip import GossipTrainer

__all__ = ["FederatedTrainer", "GossipTrainer"]
