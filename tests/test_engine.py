"""End-to-end engine tests on the 8-device virtual CPU mesh.

These are the SURVEY §4 layer-3 tests: multi-worker semantics without a
cluster, on synthetic learnable data so accuracy movement is meaningful.
"""

import dataclasses

import numpy as np
import pytest

from dopt.config import DataConfig, ExperimentConfig, FederatedConfig, GossipConfig, ModelConfig, OptimizerConfig
from dopt.engine import FederatedTrainer, GossipTrainer


def _gossip_cfg(**kw):
    g = dict(algorithm="dsgd", topology="circle", mode="metropolis",
             rounds=3, local_ep=1, local_bs=32)
    g.update(kw.pop("gossip", {}))
    return ExperimentConfig(
        name="t",
        seed=7,
        data=DataConfig(dataset="synthetic", num_users=kw.pop("num_users", 8),
                        iid=kw.pop("iid", True), shards=2,
                        synthetic_train_size=512, synthetic_test_size=128,
                        **kw.pop("data_extra", {})),
        model=ModelConfig(model="mlp", input_shape=(28, 28, 1),
                          faithful=False),
        optim=OptimizerConfig(lr=0.1, momentum=0.5),
        gossip=GossipConfig(**g),
        **kw,
    )


def _fed_cfg(algorithm="fedavg", **kw):
    return ExperimentConfig(
        name="t",
        seed=7,
        data=DataConfig(dataset="synthetic", num_users=kw.pop("num_users", 8),
                        iid=True, synthetic_train_size=512,
                        synthetic_test_size=128),
        model=ModelConfig(model="mlp", input_shape=(28, 28, 1),
                          faithful=False),
        optim=OptimizerConfig(lr=0.1, momentum=0.5, rho=0.1),
        federated=FederatedConfig(algorithm=algorithm, frac=0.5, rounds=3,
                                  local_ep=1, local_bs=32),
        **kw,
    )


def test_dsgd_learns(devices):
    tr = GossipTrainer(_gossip_cfg())
    h = tr.run(rounds=4)
    accs = [r["avg_test_acc"] for r in h if "avg_test_acc" in r]
    assert accs[-1] > 0.6, accs
    assert accs[-1] > accs[0]


def test_dsgd_consensus_shrinks_disagreement(devices):
    # After many rounds of doubly-stochastic mixing, workers' params
    # should be closer together than under no consensus.
    import jax
    cfg = _gossip_cfg(iid=False)
    tr = GossipTrainer(cfg)
    tr.run(rounds=4)
    leaves = jax.tree.leaves(tr.params)
    spread_dsgd = max(float(np.std(np.asarray(l), axis=0).max()) for l in leaves)

    cfg2 = _gossip_cfg(iid=False, gossip={"algorithm": "nocons"})
    tr2 = GossipTrainer(cfg2)
    tr2.run(rounds=4)
    leaves2 = jax.tree.leaves(tr2.params)
    spread_nocons = max(float(np.std(np.asarray(l), axis=0).max()) for l in leaves2)
    assert spread_dsgd < spread_nocons


def test_nocons_noniid_worse_than_dsgd(devices):
    # The reference's headline qualitative result (BASELINE.md): without
    # consensus, non-IID workers stagnate vs D-SGD on a good topology.
    h_no = GossipTrainer(_gossip_cfg(iid=False, gossip={"algorithm": "nocons"})).run(rounds=5)
    h_ds = GossipTrainer(_gossip_cfg(iid=False, gossip={
        "algorithm": "dsgd", "topology": "complete", "mode": "uniform"})).run(rounds=5)
    assert h_ds["avg_test_acc"][-1] > h_no["avg_test_acc"][-1] - 0.05


def test_centralized_preset_single_worker(devices):
    cfg = _gossip_cfg(gossip={"algorithm": "centralized"})
    tr = GossipTrainer(cfg)
    assert tr.num_workers == 1
    # original config object untouched (reference mutates shared args)
    assert cfg.data.num_users == 8
    h = tr.run(rounds=2)
    assert len(h) == 2


def test_fedlcon_multi_sweep(devices):
    cfg = _gossip_cfg(gossip={"algorithm": "fedlcon", "eps": 3,
                              "topology": "circle", "mode": "metropolis"})
    tr = GossipTrainer(cfg)
    h = tr.run(rounds=2)
    assert len(h) == 2


def test_gossip_learning_pairwise(devices):
    cfg = _gossip_cfg(gossip={"algorithm": "gossip"})
    tr = GossipTrainer(cfg)
    h = tr.run(rounds=3)
    assert h["avg_test_acc"][-1] > 0.5


def test_workers_fold_onto_devices(devices):
    # 16 workers on 8 devices: 2 lanes per device.
    tr = GossipTrainer(_gossip_cfg(num_users=16))
    assert tr.mesh.size == 8
    h = tr.run(rounds=2)
    assert len(h) == 2


@pytest.mark.parametrize("algorithm", ["fedavg", "fedprox", "fedadmm",
                                       "scaffold"])
def test_federated_learns(devices, algorithm):
    tr = FederatedTrainer(_fed_cfg(algorithm))
    h = tr.run(rounds=4)
    assert h["test_acc"][-1] > 0.6, h["test_acc"]


def test_scaffold_first_round_matches_fedavg(devices):
    # With zero-initialised control variates the SCAFFOLD gradient edit
    # is exactly zero, so round 1 must be bit-compatible with FedAvg
    # (same seed → same client sample, same batch plan).
    import jax
    a = FederatedTrainer(_fed_cfg("fedavg"))
    b = FederatedTrainer(_fed_cfg("scaffold"))
    a.run(rounds=1)
    b.run(rounds=1)
    for x, y in zip(jax.tree.leaves(jax.device_get(a.theta)),
                    jax.tree.leaves(jax.device_get(b.theta))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6)


def test_scaffold_controls_mean_is_server_control(devices):
    # frac=1, zero init: after round 1, c = mean_i c_i⁺ exactly.
    import jax
    cfg = _fed_cfg("scaffold")
    cfg = dataclasses.replace(
        cfg, federated=dataclasses.replace(cfg.federated, frac=1.0))
    tr = FederatedTrainer(cfg)
    tr.run(rounds=1)
    ci = jax.device_get(tr.duals)
    c = jax.device_get(tr.c_global)
    for a, b in zip(jax.tree.leaves(ci), jax.tree.leaves(c)):
        np.testing.assert_allclose(np.asarray(a).mean(axis=0), np.asarray(b),
                                   atol=1e-5)
    # and the controls actually moved
    assert any(float(np.abs(np.asarray(l)).max()) > 0
               for l in jax.tree.leaves(c))


def test_federated_partial_participation_mask(devices):
    tr = FederatedTrainer(_fed_cfg("fedavg"))
    mask = tr.sample_clients(0.25)
    assert mask.sum() == 2  # max(int(0.25*8),1)
    mask = tr.sample_clients(0.01)
    assert mask.sum() == 1  # at least one client


def test_fedadmm_duals_update_only_sampled(devices):
    import jax
    tr = FederatedTrainer(_fed_cfg("fedadmm"))
    duals_before = jax.device_get(tr.duals)
    tr.run(rounds=1)
    duals_after = jax.device_get(tr.duals)
    # at least one dual leaf must have moved for sampled workers
    moved = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(duals_before), jax.tree.leaves(duals_after))
    )
    assert moved


def test_round_counter_persists_across_runs(devices):
    tr = GossipTrainer(_gossip_cfg())
    tr.run(rounds=2)
    tr.run(rounds=2)
    assert tr.round == 4
    assert [r["round"] for r in tr.history] == [0, 1, 2, 3]


def test_blocked_run_matches_per_round(devices):
    # The fused multi-round lax.scan block path must be bit-identical to
    # the per-round dispatch path (same plans, same matrices, same order).
    import jax

    a = GossipTrainer(_gossip_cfg())
    a.run(rounds=4)
    b = GossipTrainer(_gossip_cfg())
    b.run(rounds=4, block=2)
    fa = np.concatenate([np.ravel(x) for x in jax.tree.leaves(jax.device_get(a.params))])
    fb = np.concatenate([np.ravel(x) for x in jax.tree.leaves(jax.device_get(b.params))])
    np.testing.assert_array_equal(fa, fb)
    la = [r["avg_train_loss"] for r in a.history.rows]
    lb = [r["avg_train_loss"] for r in b.history.rows]
    np.testing.assert_allclose(la, lb, rtol=1e-6)
    # Same eval cadence AND same eval values (phase order matches:
    # consensus -> eval -> local update in both paths).
    ea = [r["avg_test_acc"] for r in a.history.rows if "avg_test_acc" in r]
    eb = [r["avg_test_acc"] for r in b.history.rows if "avg_test_acc" in r]
    np.testing.assert_allclose(ea, eb, rtol=1e-6)
    # Remainder blocks (4 rounds, block=3 -> 3+1) also line up.
    c = GossipTrainer(_gossip_cfg())
    c.run(rounds=4, block=3)
    fc = np.concatenate([np.ravel(x) for x in jax.tree.leaves(jax.device_get(c.params))])
    np.testing.assert_array_equal(fa, fc)


def test_gossip_dropout_runs_and_learns(devices):
    tr = GossipTrainer(_gossip_cfg(gossip={"dropout": 0.3}))
    h = tr.run(rounds=4)
    assert h["avg_test_acc"][-1] > 0.5


def test_gossip_full_dropout_freezes_state(devices):
    import jax
    tr = GossipTrainer(_gossip_cfg(gossip={"dropout": 1.0}))
    before = jax.device_get(tr.params)
    tr.run(rounds=2)
    after = jax.device_get(tr.params)
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gossip_dropout_blocked_matches_per_round(devices):
    import jax
    a = GossipTrainer(_gossip_cfg(gossip={"dropout": 0.4}))
    a.run(rounds=4)
    b = GossipTrainer(_gossip_cfg(gossip={"dropout": 0.4}))
    b.run(rounds=4, block=2)
    fa = np.concatenate([np.ravel(x) for x in jax.tree.leaves(jax.device_get(a.params))])
    fb = np.concatenate([np.ravel(x) for x in jax.tree.leaves(jax.device_get(b.params))])
    np.testing.assert_array_equal(fa, fb)


def test_fedlcon_faithful_bug_reproduces_single_sweep(devices):
    # The reference's FedLCon never clears new_weights across its eps
    # loop, so every sweep reloads sweep-0 results — effectively ONE
    # consensus sweep (simulators.py:189-196). faithful_bugs=True must
    # reproduce that exactly; the fixed path must differ.
    import jax

    def params_of(**gk):
        tr = GossipTrainer(_gossip_cfg(gossip=dict(
            algorithm="fedlcon", topology="circle", mode="metropolis", **gk)))
        tr.run(rounds=2)
        return np.concatenate([np.ravel(np.asarray(x))
                               for x in jax.tree.leaves(jax.device_get(tr.params))])

    buggy_eps3 = params_of(eps=3, faithful_bugs=True)
    one_sweep = params_of(eps=1)
    fixed_eps3 = params_of(eps=3)
    np.testing.assert_array_equal(buggy_eps3, one_sweep)
    assert not np.array_equal(fixed_eps3, one_sweep)


@pytest.mark.parametrize("algorithm", ["fedavg", "fedprox", "fedadmm",
                                       "scaffold"])
def test_compact_sampling_matches_full_width(devices, algorithm):
    # The gather-compact fast path must reproduce the full-width masked
    # path up to float summation order, for every algorithm, including
    # stale state on unsampled workers across rounds.
    import jax

    def run(compact):
        cfg = _fed_cfg(algorithm)
        cfg = cfg.replace(federated=dataclasses.replace(
            cfg.federated, compact=compact), mesh_devices=1)
        tr = FederatedTrainer(cfg)
        tr.run(rounds=3)
        return tr

    a = run(False)
    b = run(True)
    for x, y in zip(jax.tree.leaves(jax.device_get(a.theta)),
                    jax.tree.leaves(jax.device_get(b.theta))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=2e-5, rtol=1e-4)
    for x, y in zip(jax.tree.leaves(jax.device_get(a.params)),
                    jax.tree.leaves(jax.device_get(b.params))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=2e-5, rtol=1e-4)
    if a.duals is not None:
        for x, y in zip(jax.tree.leaves(jax.device_get(a.duals)),
                        jax.tree.leaves(jax.device_get(b.duals))):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(a.history["test_acc"], b.history["test_acc"],
                               atol=1e-3)


@pytest.mark.parametrize("algorithm", ["fedavg", "fedadmm", "scaffold"])
def test_federated_blocked_matches_per_round(devices, algorithm):
    # The fused multi-round block path (lax.scan over rounds in one jit)
    # must reproduce the per-round path exactly: same client-sampling
    # sequence, same history rows, same final state.  Covers both the
    # full-width (sharded mesh) and compact (single-device) paths via
    # the default mesh.
    import jax

    def run(block):
        tr = FederatedTrainer(_fed_cfg(algorithm))
        tr.run(rounds=4, block=block)
        return tr

    a = run(1)
    b = run(2)
    c = run(3)  # remainder block: 3 + 1
    for other in (b, c):
        for x, y in zip(jax.tree.leaves(jax.device_get(a.theta)),
                        jax.tree.leaves(jax.device_get(other.theta))):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       atol=1e-6, rtol=1e-5)
        np.testing.assert_allclose(a.history["test_acc"],
                                   other.history["test_acc"], atol=1e-5)
        np.testing.assert_allclose(a.history["train_loss"],
                                   other.history["train_loss"], atol=1e-5)
        np.testing.assert_allclose(a.history["local_loss"],
                                   other.history["local_loss"], atol=1e-5)


def test_federated_blocked_compact_single_device(devices):
    # Compact + blocked on one device: sel gates are [k, m] index arrays.
    import jax

    def run(block):
        cfg = _fed_cfg("fedavg")
        cfg = cfg.replace(federated=dataclasses.replace(
            cfg.federated, compact=True), mesh_devices=1)
        tr = FederatedTrainer(cfg)
        tr.run(rounds=4, block=block)
        return tr

    a = run(1)
    b = run(4)
    for x, y in zip(jax.tree.leaves(jax.device_get(a.theta)),
                    jax.tree.leaves(jax.device_get(b.theta))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(a.history["test_acc"],
                               b.history["test_acc"], atol=1e-5)


def test_engines_reject_transformer_model(devices):
    cfg = _gossip_cfg()
    cfg = cfg.replace(model=dataclasses.replace(cfg.model, model="transformer"))
    with pytest.raises(ValueError, match="sequence model"):
        GossipTrainer(cfg)
    fcfg = _fed_cfg()
    fcfg = fcfg.replace(model=dataclasses.replace(fcfg.model, model="transformer"))
    with pytest.raises(ValueError, match="sequence model"):
        FederatedTrainer(fcfg)


def test_gossip_comm_compression_trains(devices):
    # bf16 on-the-wire consensus: the run proceeds and the consensus
    # still contracts disagreement (approximate mixing is still mixing).
    cfg = _gossip_cfg(gossip=dict(comm_dtype="bfloat16", rounds=3))
    tr = GossipTrainer(cfg)
    h = tr.run()
    assert len(h) == 3
    ref = GossipTrainer(_gossip_cfg()).run()
    assert abs(h.last()["avg_test_acc"] - ref.last()["avg_test_acc"]) < 0.1


def test_hierarchical_gossip_on_hybrid_mesh(devices):
    # DCN-aware schedule: intra-host rounds + periodic global mix, on a
    # 2x4 (hosts x ici) hybrid mesh.  The periodic global mix must
    # actually pull the hosts together: cross-worker spread under the
    # hierarchical schedule stays well below the no-communication run's.
    import jax

    def spread_of(tr):
        leaves = jax.tree.leaves(jax.device_get(tr.params))
        return max(float(np.abs(np.asarray(x) - np.asarray(x)[0]).max())
                   for x in leaves)

    cfg = _gossip_cfg(
        gossip=dict(topology="hierarchical", mode="metropolis", rounds=4,
                    hier_groups=2, hier_period=2),
        mesh_hosts=2, iid=False,
    )
    tr = GossipTrainer(cfg)
    h = tr.run()
    assert len(h) == 4

    nocons = GossipTrainer(_gossip_cfg(
        gossip=dict(algorithm="nocons", rounds=4), iid=False))
    nocons.run()
    assert spread_of(tr) < 0.5 * spread_of(nocons)


def test_federated_comm_compression_trains(devices):
    cfg = _fed_cfg("fedavg")
    cfg = cfg.replace(federated=dataclasses.replace(
        cfg.federated, comm_dtype="bfloat16"))
    tr = FederatedTrainer(cfg)
    h = tr.run(rounds=3)
    ref = FederatedTrainer(_fed_cfg("fedavg")).run(rounds=3)
    assert abs(h.last()["test_acc"] - ref.last()["test_acc"]) < 0.1


# ---------------------------------------------------------------------
# comm_impl: the ppermute shift path vs the dense all_gather path
# ---------------------------------------------------------------------

def _leaves(tr):
    import jax

    return [np.asarray(x) for x in jax.tree.leaves(jax.device_get(tr.params))]


def _shift_cfg(comm_impl, **kw):
    g = dict(mode="uniform", rounds=6, comm_impl=comm_impl)
    g.update(kw.pop("gossip", {}))
    return _gossip_cfg(gossip=g, **kw)


def test_comm_impl_shift_bitwise_equals_dense_uniform_ring(devices):
    """Full GossipTrainer.run, 8 workers on the 8-device mesh: the
    ppermute path must be BIT-identical to the dense path.  Uniform ring
    weights (1/2, 1/2) make every per-row product exact, so the two
    paths' different accumulation (gemm FMA vs mul+add) cannot round
    differently — any bit difference is a real routing bug."""
    td = GossipTrainer(_shift_cfg("dense"))
    ts = GossipTrainer(_shift_cfg("shift"))
    assert ts._shift_ids == (1, 7)
    hd, hs = td.run(), ts.run()
    assert hd.rows == hs.rows
    for a, b in zip(_leaves(td), _leaves(ts)):
        assert np.array_equal(a, b)


def test_comm_impl_shift_bitwise_equals_dense_dynamic_dropout(devices):
    """Time-varying single-edge graphs + dropout repair: per-round
    matrices (repaired as data) must stay inside the compiled shift set
    {0, 1, n-1} and match the dense path bit-for-bit (each row has at
    most one neighbor term, so no accumulation-order freedom exists)."""
    g = dict(topology="dynamic", mode="stochastic", dropout=0.3)
    td = GossipTrainer(_shift_cfg("dense", gossip=g))
    ts = GossipTrainer(_shift_cfg("shift", gossip=g))
    assert ts._shift_ids == (0, 1, 7)
    hd, hs = td.run(), ts.run()
    assert hd.rows == hs.rows
    for a, b in zip(_leaves(td), _leaves(ts)):
        assert np.array_equal(a, b)


def test_comm_impl_shift_close_for_stochastic_ring(devices):
    """Random (non-dyadic) ring weights: dense gemm uses FMA so the last
    bit can differ; the paths must agree to float32 rounding noise and
    produce identical history metrics."""
    g = dict(mode="stochastic")
    td = GossipTrainer(_shift_cfg("dense", gossip=g))
    ts = GossipTrainer(_shift_cfg("shift", gossip=g))
    hd, hs = td.run(), ts.run()
    for rd, rs in zip(hd.rows, hs.rows):
        assert rd.keys() == rs.keys()
        for k in rd:
            assert rd[k] == pytest.approx(rs[k], abs=1e-5)
    for a, b in zip(_leaves(td), _leaves(ts)):
        np.testing.assert_allclose(a, b, atol=2e-6, rtol=1e-5)


def test_comm_impl_shift_blocked_matches_per_round(devices):
    """The fused lax.scan block path must dispatch the same compiled
    shift mix: blocked vs per-round bit-equality, through run()."""
    ts = GossipTrainer(_shift_cfg("shift"))
    ts.run()
    tb = GossipTrainer(_shift_cfg("shift"))
    tb.run(block=3)
    assert ts.history.rows == tb.history.rows
    for a, b in zip(_leaves(ts), _leaves(tb)):
        assert np.array_equal(a, b)


def test_comm_impl_shift_choco_and_fedlcon(devices):
    """choco mixes its public copies x̂ through the same mix_once; fedlcon
    applies eps sweeps inside one jit — both must match dense exactly on
    uniform weights."""
    for g in (dict(algorithm="choco", rounds=4),
              dict(algorithm="fedlcon", eps=3, rounds=4)):
        td = GossipTrainer(_shift_cfg("dense", gossip=g))
        ts = GossipTrainer(_shift_cfg("shift", gossip=g))
        td.run(), ts.run()
        assert ts._shift_ids is not None
        for a, b in zip(_leaves(td), _leaves(ts)):
            assert np.array_equal(a, b)


def test_comm_impl_auto_and_validation(devices):
    # auto picks shift when the schedule's ppermute bytes beat the
    # all_gather with a 2x margin.
    assert GossipTrainer(_shift_cfg("auto"))._shift_ids == (1, 7)
    # complete graph on 8 workers: 7 rotations -> dense.
    assert GossipTrainer(_shift_cfg(
        "auto", gossip=dict(topology="complete")))._shift_ids is None
    # folded lanes: 16 workers on 8 devices (2 lanes each) still routes
    # the ring onto ppermutes — the straddling shifts {1, 15} each
    # consume ONE lane of their neighbor block, so only 2 lane-shards
    # move per device (shift_comm_lanes) vs 14 for the dense gather.
    assert GossipTrainer(_shift_cfg(
        "auto", num_users=16))._shift_ids == (1, 15)
    # folded complete graph: every device rotation needed -> dense.
    assert GossipTrainer(_shift_cfg(
        "auto", num_users=16,
        gossip=dict(topology="complete")))._shift_ids is None
    # 1-device mesh: no wire to save — auto must stay dense (the shift
    # path would materialise one sliced copy of the stacked state per
    # diagonal; a 32-worker random graph OOMs a single chip that way).
    assert GossipTrainer(_shift_cfg(
        "auto", mesh_devices=1))._shift_ids is None
    # dense shift set (random graph): local mix work is linear in the
    # diagonal count -> dense even though lanes fold.
    assert GossipTrainer(_shift_cfg(
        "auto", num_users=32,
        gossip=dict(topology="random", local_bs=8)))._shift_ids is None
    # explicit shift honors an expensive decomposition (complete = all 7).
    tr = GossipTrainer(_shift_cfg("shift", gossip=dict(topology="complete")))
    assert tr._shift_ids == tuple(range(1, 8))
    # explicit shift on a hybrid (non-flat) mesh must fail loudly.
    with pytest.raises(ValueError, match="comm_impl='shift'"):
        GossipTrainer(_shift_cfg("shift", mesh_hosts=2))
    with pytest.raises(ValueError, match="mixing-schedule algorithm"):
        GossipTrainer(_shift_cfg("shift", gossip=dict(algorithm="gossip")))
    with pytest.raises(ValueError, match="comm_impl"):
        GossipTrainer(_shift_cfg("nonsense"))


def test_comm_impl_shift_folded_lanes_bitwise_equals_dense(devices):
    """The north-star shape: 32 workers folded 4-per-device onto the
    8-device mesh.  The block-circulant decomposition (device ppermutes
    + lane slice) must be BIT-identical to the dense path through
    GossipTrainer.run on uniform ring weights."""
    kw = dict(num_users=32, gossip=dict(local_bs=8, rounds=4))
    td = GossipTrainer(_shift_cfg("dense", **kw))
    ts = GossipTrainer(_shift_cfg("shift", **kw))
    assert ts._shift_ids == (1, 31)
    assert ts.mesh.size == 8 and ts.num_workers == 32
    hd, hs = td.run(), ts.run()
    assert hd.rows == hs.rows
    for a, b in zip(_leaves(td), _leaves(ts)):
        assert np.array_equal(a, b)
    # auto routes this shape onto the shift path (the VERDICT r2 gap:
    # the flagship collective now reaches the flagship config).
    assert GossipTrainer(_shift_cfg("auto", **kw))._shift_ids == (1, 31)


def test_comm_impl_shift_folded_dynamic_dropout(devices):
    """Folded lanes + time-varying single-edge graphs + dropout repair:
    per-round coefficient tables must stay inside the compiled shift set
    and match dense bit-for-bit (rows have at most one neighbor term)."""
    g = dict(topology="dynamic", mode="stochastic", dropout=0.3,
             local_bs=8, rounds=4)
    td = GossipTrainer(_shift_cfg("dense", num_users=16, gossip=g))
    ts = GossipTrainer(_shift_cfg("shift", num_users=16, gossip=g))
    assert ts._shift_ids == (0, 1, 15)
    hd, hs = td.run(), ts.run()
    assert hd.rows == hs.rows
    for a, b in zip(_leaves(td), _leaves(ts)):
        assert np.array_equal(a, b)


# ---------------------------------------------------------------------
# Local train/val holdout (reference train_val_test semantics)
# ---------------------------------------------------------------------

def _holdout_gossip_cfg(block=1, holdout=0.1):
    return _gossip_cfg(
        gossip=dict(mode="uniform", rounds=3, local_ep=2,
                    block_rounds=block),
        data_extra=dict(local_holdout=holdout, holdout_mode="random"),
    )


def test_gossip_holdout_trains_on_subshard_with_client_history(devices):
    tr = GossipTrainer(_holdout_gossip_cfg())
    tr.run()
    w, l = tr.index_matrix.shape
    val_size = max(int(l * 0.1), 1)
    assert tr._train_matrix.shape == (w, l - val_size)
    # every batch-plan index must come from the train sub-shard
    from dopt.data import make_batch_plan
    plan = make_batch_plan(tr._train_matrix, batch_size=32, local_ep=2,
                           seed=tr.cfg.seed, round_idx=0)
    for i in range(w):
        assert set(plan.idx[i].ravel()) <= set(tr._train_matrix[i])
    # per-epoch per-worker rows, P2 schema
    rows = tr.client_history.rows
    assert len(rows) == 3 * w * 2
    assert set(rows[0]) == {"round", "iter", "worker", "train_loss",
                            "train_acc", "val_acc", "val_loss"}
    # blocked run: identical history and client rows
    tb = GossipTrainer(_holdout_gossip_cfg(block=3))
    tb.run()
    assert tb.history.rows == tr.history.rows
    assert tb.client_history.rows == rows


def test_federated_holdout_client_history_sampled_only(devices):
    import dataclasses as _dc

    def fed(compact=None, mesh_devices=None):
        cfg = _fed_cfg("fedavg")
        cfg = cfg.replace(
            data=_dc.replace(cfg.data, local_holdout=0.1,
                             holdout_mode="deterministic"),
            federated=_dc.replace(cfg.federated, compact=compact),
            mesh_devices=mesh_devices,
        )
        return cfg

    tr = FederatedTrainer(fed())
    tr.run(rounds=3)
    rows = tr.client_history.rows
    m = max(int(0.5 * 8), 1)
    assert len(rows) == 3 * m * 1  # local_ep=1
    assert set(rows[0]) == {"global_round", "epoch", "worker", "train_loss",
                            "train_acc", "val_acc", "val_loss"}
    # only sampled workers appear per round
    for t in range(3):
        assert len([r for r in rows if r["global_round"] == t]) == m
    # compact path (1-device) produces the same rows
    tc = FederatedTrainer(fed(compact=True, mesh_devices=1))
    tc.run(rounds=3)
    assert [r["worker"] for r in tc.client_history.rows] == [
        r["worker"] for r in rows]
    for a, b in zip(tc.client_history.rows, rows):
        assert a["val_acc"] == pytest.approx(b["val_acc"], abs=1e-6)
        assert a["train_loss"] == pytest.approx(b["train_loss"], abs=1e-5)


def test_holdout_resume_preserves_client_history(devices, tmp_path):
    tr = GossipTrainer(_holdout_gossip_cfg())
    tr.run(rounds=2)
    tr.save(tmp_path / "ck")
    tr2 = GossipTrainer(_holdout_gossip_cfg())
    tr2.restore(tmp_path / "ck")
    assert tr2.client_history.rows == tr.client_history.rows
    tr2.run(rounds=1)
    tr.run(rounds=1)
    assert tr2.client_history.rows == tr.client_history.rows


# ---------------------------------------------------------------------
# No dead config knobs: every field changes behavior or raises
# ---------------------------------------------------------------------

def test_weight_decay_changes_training(devices):
    import jax

    def fed(wd):
        cfg = _fed_cfg("fedavg")
        return cfg.replace(optim=dataclasses.replace(cfg.optim,
                                                     weight_decay=wd))

    a = FederatedTrainer(fed(0.0)); a.run(rounds=2)
    b = FederatedTrainer(fed(0.1)); b.run(rounds=2)
    la = jax.tree.leaves(jax.device_get(a.theta))
    lb = jax.tree.leaves(jax.device_get(b.theta))
    assert any(not np.allclose(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))
    # the ℓ2 term shrinks the solution norm
    na = sum(float((np.asarray(x) ** 2).sum()) for x in la)
    nb = sum(float((np.asarray(x) ** 2).sum()) for x in lb)
    assert nb < na

    def gos(wd):
        cfg = _gossip_cfg()
        return cfg.replace(optim=dataclasses.replace(cfg.optim,
                                                     weight_decay=wd))

    ga = GossipTrainer(gos(0.0)); ga.run(rounds=2)
    gb = GossipTrainer(gos(0.1)); gb.run(rounds=2)
    assert any(
        not np.allclose(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(jax.device_get(ga.params)),
                        jax.tree.leaves(jax.device_get(gb.params))))


def test_unknown_optimizer_rejected(devices):
    cfg = _fed_cfg("fedavg")
    cfg = cfg.replace(optim=dataclasses.replace(cfg.optim, optimizer="adam"))
    with pytest.raises(ValueError, match="optimizer"):
        FederatedTrainer(cfg)
    cfg = _gossip_cfg()
    cfg = cfg.replace(optim=dataclasses.replace(cfg.optim, optimizer="adam"))
    with pytest.raises(ValueError, match="optimizer"):
        GossipTrainer(cfg)


def test_param_dtype_controls_state_storage(devices):
    import jax
    import jax.numpy as jnp

    cfg = _gossip_cfg()
    cfg = cfg.replace(model=dataclasses.replace(cfg.model,
                                                param_dtype="bfloat16"))
    tr = GossipTrainer(cfg)
    assert all(x.dtype == jnp.bfloat16 for x in jax.tree.leaves(tr.params))
    assert all(x.dtype == jnp.bfloat16 for x in jax.tree.leaves(tr.momentum))
    h = tr.run(rounds=2)
    assert len(h) == 2
    assert all(x.dtype == jnp.bfloat16 for x in jax.tree.leaves(tr.params))

    fcfg = _fed_cfg("fedadmm")
    fcfg = fcfg.replace(model=dataclasses.replace(fcfg.model,
                                                  param_dtype="bfloat16"))
    ft = FederatedTrainer(fcfg)
    assert all(x.dtype == jnp.bfloat16 for x in jax.tree.leaves(ft.theta))
    assert all(x.dtype == jnp.bfloat16 for x in jax.tree.leaves(ft.duals))
    ft.run(rounds=1)
    assert all(x.dtype == jnp.bfloat16 for x in jax.tree.leaves(ft.theta))


def test_from_reference_args_rejects_unequal(devices):
    from dopt.config import from_reference_args

    with pytest.raises(ValueError, match="unequal"):
        from_reference_args({"dataset": "mnist", "unequal": True})
    cfg = from_reference_args({"dataset": "mnist"})
    assert not hasattr(cfg.data, "unequal")


def test_sharded_eval_mode_tracks_full(devices):
    """eval_mode='sharded' must produce per-round fleet-mean metrics
    close to the full-set eval (unbiased 1/W-shard estimate) and leave
    trainer.evaluate() at reference full-set semantics."""
    accs = {}
    for mode in ("full", "sharded"):
        tr = GossipTrainer(_gossip_cfg(
            gossip={"eval_mode": mode}, iid=False))
        h = tr.run(rounds=4)
        accs[mode] = [r["avg_test_acc"] for r in h if "avg_test_acc" in r]
        # evaluate() is full-set in both modes: per-worker counts equal
        # the whole test split (128 in _gossip_cfg).
        ev = tr.evaluate()
        assert int(ev["count"][0]) == 128
    assert abs(accs["full"][-1] - accs["sharded"][-1]) < 0.12, accs


def test_sharded_eval_composes_with_dropout_and_choco(devices):
    """The sharded evaluator must slot into the same block program as
    fault injection and CHOCO compression (shape contract: [W]-dict)."""
    tr = GossipTrainer(_gossip_cfg(gossip={
        "eval_mode": "sharded", "dropout": 0.25}))
    h = tr.run(rounds=3)
    assert any("avg_test_acc" in r for r in h)
    tr2 = GossipTrainer(_gossip_cfg(gossip={
        "algorithm": "choco", "eval_mode": "sharded",
        "compression": "topk", "compression_ratio": 1.0}))
    h2 = tr2.run(rounds=3)
    assert any("avg_test_acc" in r for r in h2)


def test_eval_mode_validation():
    with pytest.raises(ValueError, match="eval_mode"):
        GossipTrainer(_gossip_cfg(gossip={"eval_mode": "bogus"}))
