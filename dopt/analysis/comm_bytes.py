"""Compiled-HLO bytes-on-wire probe: ``python -m dopt.analysis.comm_bytes``.

The r08 bench headline claims the bucket codec shrinks the consensus
wire by ≥4x — this CLI is where that number comes from.  It lowers the
SAME MLP gossip round program three ways via the engine's
``lower_round`` hook (the one ``_round_dispatch`` builder the real run
loop dispatches, so the measured program IS the shipped program):

* ``dense``   — ``update_sharding='off'``: the plain dense consensus
  (all_gather + [n, n] contraction at f32), the wire every mode spoke
  before the flat-bucket substrate.
* ``scatter`` — the uncompressed scatter path (reduce-scatter partial
  contractions over flat buckets).
* ``codec``   — scatter + ``CommConfig(codec='qsgd')`` with a byte
  budget priced by the lossy-link model: ``link_byte_budget`` gives one
  slab's per-round goodput under the baseline1-lossy preset's
  drop/delay rates, and the gathered wire fans (n − 1) remote slabs
  into every link per round, so the per-lane schedule must shrink by
  that fan-in factor to fit — the FusionLLM (arXiv:2410.12707) WAN
  argument, priced instead of hand-waved.

Each program's collective wire bytes come from
``dopt.parallel.collectives.hlo_collective_bytes`` over the COMPILED
HLO — per op kind and per dtype, so a compressed program shows its u8
payload + f32 scale sidecar, not a docstring claim.  The headline
``wire_compression`` is dense/codec: both legs materialise gathered
fleet buffers, so the accounting compares like with like (the
scatter leg's reduce-scatter result buffers are per-shard and NOT
comparable across op kinds — reported for transparency, never
ratioed against the gather legs).

On a 1-device mesh every collective compiles away and all counts are
honestly 0 — run under ``--devices N`` (forces
``--xla_force_host_platform_device_count`` before jax init, CPU hosts
only) or on a real multi-device backend.

Prints ONE JSON object; exit 0 on success.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# The lossy-link preset's fault rates (dopt.presets baseline1-lossy):
# the link model that MOTIVATES compression is the one that prices it.
LOSSY_LINK = {"msg_drop": 0.15, "msg_delay": 0.2, "msg_delay_max": 2}


def comm_modes_config(mode: str, *, workers: int = 8,
                      train_size: int = 2_048, test_size: int = 512,
                      rounds: int = 8, budget_mb: float = 0.0,
                      chunk: int = 64, min_codec_bytes: int = 256,
                      faults: bool = False):
    """The r08 comm-ablation workload, one config per wire mode:
    ``dense`` | ``scatter`` | ``codec``.  MLP so the leg is feasible on
    every backend the ledger sees (the r06/r07 precedent), f32 compute
    so the dense wire is the honest 4-byte baseline the codec is
    judged against.  ``faults=True`` arms the lossy preset's crash +
    churn legs (its ``msg_*`` knobs run the per-staleness link engine —
    a different wire; here they price the byte budget instead)."""
    from dopt.config import (CommConfig, DataConfig, ExperimentConfig,
                             FaultConfig, GossipConfig, ModelConfig,
                             OptimizerConfig)

    if mode not in ("dense", "scatter", "codec"):
        raise ValueError(f"unknown comm mode {mode!r}; "
                         "one of dense|scatter|codec")
    comm = None
    if mode == "codec":
        comm = CommConfig(codec="qsgd", byte_budget_mb=budget_mb,
                          chunk=chunk, min_codec_bytes=min_codec_bytes)
    return ExperimentConfig(
        name=f"bench-comm-{mode}",
        seed=2030,
        data=DataConfig(dataset="synthetic", num_users=workers, iid=True,
                        synthetic_train_size=train_size,
                        synthetic_test_size=test_size,
                        plan_impl="native"),
        model=ModelConfig(model="mlp", faithful=False),
        optim=OptimizerConfig(lr=0.05, momentum=0.9),
        gossip=GossipConfig(
            algorithm="dsgd", topology="complete", mode="metropolis",
            rounds=rounds, local_ep=1, local_bs=128,
            update_sharding="off" if mode == "dense" else "scatter"),
        faults=(FaultConfig(crash=0.05, churn=0.02, churn_span=3)
                if faults else None),
        comm=comm,
    )


def lossy_budget_bytes(dense_bytes: int, workers: int) -> int:
    """Per-lane byte budget the codec schedule must fit under the
    lossy-link preset: one slab's goodput (``link_byte_budget``)
    divided by the gathered wire's per-link fan-in (n − 1 remote
    slabs cross every link every round)."""
    from dopt.parallel.collectives import link_byte_budget

    goodput = link_byte_budget(dense_bytes, **LOSSY_LINK)
    return max(goodput // max(workers - 1, 1), 1)


def measure_comm_bytes(*, workers: int = 8, train_size: int = 2_048,
                       test_size: int = 512, chunk: int = 64,
                       min_codec_bytes: int = 256,
                       budget_mb: float | None = None) -> dict:
    """Lower + compile the three wire modes' round programs and account
    their collective bytes.  ``budget_mb=None`` derives the codec
    budget from the lossy-link preset (``lossy_budget_bytes``).  Each
    mode gets a FRESHLY constructed trainer: ``lower_round`` consumes
    the run loop's stateful host draws."""
    import jax

    from dopt.engine import GossipTrainer
    from dopt.parallel.collectives import hlo_collective_bytes

    def build(mode, bmb=0.0):
        return GossipTrainer(
            comm_modes_config(mode, workers=workers,
                              train_size=train_size, test_size=test_size,
                              budget_mb=bmb, chunk=chunk,
                              min_codec_bytes=min_codec_bytes),
            eval_every=1 << 20)

    def wire(trainer):
        _, lowered = trainer.lower_round()
        return hlo_collective_bytes(lowered.compile().as_text())

    scatter_tr = build("scatter")
    spec = scatter_tr._scatter_spec
    dense_bytes = (spec.bounds[-1] - spec.bounds[0]) * 4
    budget = (lossy_budget_bytes(dense_bytes, workers)
              if budget_mb is None else int(budget_mb * (1 << 20)))
    codec_tr = build("codec", bmb=budget / (1 << 20))
    plan = codec_tr._codec_plan
    out = {
        "workers": workers,
        "devices": jax.device_count(),
        "budget_bytes": int(budget),
        "plan_kinds": list(plan.kinds),
        "plan_chunk": plan.chunk,
        "plan_dense_bytes": plan.dense_bytes,
        "plan_wire_bytes": plan.wire_bytes,
        "plan_compression": round(plan.compression, 3),
        "dense": wire(build("dense")),
        "scatter": wire(scatter_tr),
        "codec": wire(codec_tr),
    }
    out["wire_compression"] = round(
        out["dense"]["total"] / max(out["codec"]["total"], 1), 3)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dopt.analysis.comm_bytes",
        description="compiled-HLO bytes-on-wire of the dense / scatter "
                    "/ codec round programs (one JSON object)")
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--devices", type=int, default=4,
                    help="forced CPU host device count (ignored when "
                         "XLA_FLAGS already pins one or a real "
                         "multi-device backend is attached)")
    ap.add_argument("--train-size", type=int, default=2_048)
    ap.add_argument("--test-size", type=int, default=512)
    ap.add_argument("--chunk", type=int, default=64)
    ap.add_argument("--min-codec-bytes", type=int, default=256)
    ap.add_argument("--budget-mb", type=float, default=None,
                    help="codec byte budget in MiB (default: derived "
                         "from the lossy-link preset)")
    args = ap.parse_args(argv)

    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.devices}").strip()
    result = measure_comm_bytes(
        workers=args.workers, train_size=args.train_size,
        test_size=args.test_size, chunk=args.chunk,
        min_codec_bytes=args.min_codec_bytes, budget_mb=args.budget_mb)
    json.dump(result, sys.stdout)
    print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
