"""dopt benchmark — gossip rounds/sec on the reference's P2 workload.

Reproduces the reference's gossip experiment shape (`Weighted
Average.ipynb` cell 11: 6 workers, Model1 1.66M params, MNIST-sized
data, non-IID 2 shards/user, local_ep=4, local_bs=128, circle topology,
stochastic mixing) and measures steady-state gossip rounds per second on
the available accelerator.

Baseline: the reference runs ~10 rounds in ~800s on Colab
(BASELINE.md: "Gossip throughput (derived) ~0.012 rounds/s").  Data is
synthetic at exactly MNIST scale (60,000 train / 10,000 test samples,
28x28x1) because this environment has no network egress; per-round
FLOPs and communication volume match the real workload.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "rounds/sec", "vs_baseline": N}
"""

from __future__ import annotations

import argparse
import json
import sys
import time

REFERENCE_ROUNDS_PER_SEC = 0.012  # BASELINE.md derived gossip throughput


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny data / few rounds (CI smoke, not a benchmark)")
    ap.add_argument("--rounds", type=int, default=None)
    ap.add_argument("--block", type=int, default=None,
                    help="rounds fused per jit dispatch (default: all "
                         "measured rounds in one fused lax.scan block)")
    args = ap.parse_args()

    from dopt.config import (DataConfig, ExperimentConfig, GossipConfig,
                             ModelConfig, OptimizerConfig)
    from dopt.engine import GossipTrainer

    train_size = 6_000 if args.smoke else 60_000
    test_size = 1_000 if args.smoke else 10_000
    measure_rounds = args.rounds or (3 if args.smoke else 10)

    cfg = ExperimentConfig(
        name="bench-dsgd-mnist",
        seed=2028,
        data=DataConfig(dataset="mnist", num_users=6, iid=False, shards=2,
                        synthetic_train_size=train_size,
                        synthetic_test_size=test_size),
        model=ModelConfig(model="model1", faithful=True),
        optim=OptimizerConfig(lr=0.01, momentum=0.5),
        gossip=GossipConfig(algorithm="dsgd", topology="circle",
                            mode="stochastic", rounds=10, local_ep=4,
                            local_bs=128),
    )
    trainer = GossipTrainer(cfg)
    block = args.block or measure_rounds

    # Warmup: compile the fused block step for every block size the
    # measured loop will dispatch (the remainder block retraces).
    trainer.run(rounds=block, block=block)
    if measure_rounds % block:
        # block > remainder keeps this on the blocked path (k=remainder),
        # compiling the same trace the measured loop's last dispatch uses.
        trainer.run(rounds=measure_rounds % block, block=block)

    t0 = time.time()
    trainer.run(rounds=measure_rounds, block=block)
    elapsed = time.time() - t0
    rounds_per_sec = measure_rounds / elapsed

    result = {
        "metric": "gossip_rounds_per_sec_dsgd_mnist_6workers_model1",
        "value": round(rounds_per_sec, 4),
        "unit": "rounds/sec",
        "vs_baseline": round(rounds_per_sec / REFERENCE_ROUNDS_PER_SEC, 2),
    }
    print(json.dumps(result))
    # Context to stderr so stdout stays one JSON line.
    last = trainer.history.last()
    print(f"# {measure_rounds} rounds in {elapsed:.2f}s; "
          f"last avg_test_acc={last.get('avg_test_acc')}", file=sys.stderr)


if __name__ == "__main__":
    main()
