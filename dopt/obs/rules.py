"""Declarative health rules over the telemetry event stream.

A rule is a small pure state machine: it consumes one event at a time
(``update(event, ctx)``) and returns zero or more alert payloads.  All
mutable rule state lives in ``rule.s`` — a plain JSON-able dict — so a
monitor can checkpoint and resume mid-stream (``HealthMonitor.state``)
and a resumed tail replays to the exact same alert sequence.

Determinism contract: rules read only the deterministic event kinds
(``round``/``gauge``/``fault``), the ``run`` segment headers, and the
(non-deterministic but cadence-only) ``checkpoint`` markers.  Because
per-round, fused-blocked and killed-and-resumed execution emit
bit-identical deterministic streams (dopt.obs), the alert sequence a
rule set produces is identical across execution paths of the same run
— pinned by tests/test_monitor.py and the chaos soak.

Firing is EDGE-TRIGGERED: a rule alerts when its condition first
becomes true and re-arms once the condition clears, so a 10k-round run
sitting in one bad regime yields one alert per episode, not 10k.

The rule set is declarative: ``build_rules([{"rule": "loss_divergence",
"factor": 2.0}, ...])`` instantiates from the ``RULES`` registry, and
``default_rules()`` is the conservative stock set (tuned to stay silent
on clean baseline runs — the chaos soak's false-positive gate).

Stdlib-only (no jax/numpy): the monitor must run anywhere the checker
does — laptops tailing a scp'd metrics file included.
"""

from __future__ import annotations

import statistics
from typing import Any

# Loss-key detection order: gossip rows carry avg_train_loss every
# round; federated rows carry train_loss (P1 schema); local_loss/loss
# are fallbacks for producer events outside the engines.
LOSS_KEYS = ("avg_train_loss", "train_loss", "local_loss", "loss")

SEVERITIES = ("warn", "critical")

# Fault-ledger kinds that mean "this worker's round contribution was
# lost" — the numerator of the drop-rate SLO.  Screening/quarantine
# rows are defenses doing their job, not losses, and get their own rule.
DROP_KINDS = ("crash", "straggle", "msg_drop", "partition", "churn")


def loss_of(metrics: dict) -> tuple[str | None, Any]:
    """(key, value) of the first known loss key present; value None
    means the producer sanitized a non-finite loss into null."""
    for k in LOSS_KEYS:
        if k in metrics:
            return k, metrics[k]
    return None, None


class RunContext:
    """What the monitor knows about the run segment being consumed:
    filled from ``run`` headers and denominator gauges, read by rules
    that need fleet-size denominators."""

    def __init__(self, workers: int | None = None):
        self.engine: str | None = None
        self.workers = workers
        self.cohort: float | None = None       # population cohort_size gauge
        self.population: float | None = None   # population_size gauge
        self.participating: float | None = None  # participating_lanes gauge
        # Configured checkpoint cadence in rounds: stamped on the run
        # header (serve daemons and --checkpoint-every runs) and
        # updated live by control events that change it — the
        # checkpoint_cadence rule's expected-cadence source.
        self.checkpoint_every: int | None = None
        self.round: int = -1

    def denominator(self) -> float | None:
        """Per-round participant denominator: the cohort size when a
        population registry is driving sampling, else the LIVE
        participating-lane count (lanes minus quarantined — the
        engines emit it every round), else the static lane count."""
        if self.cohort:
            return float(self.cohort)
        if self.participating:
            return float(self.participating)
        return float(self.workers) if self.workers else None


class Rule:
    """Base rule: subclasses set ``name``/``severity``, keep ALL
    mutable state in ``self.s`` (JSON-able), and implement
    ``update``."""

    name = "rule"
    severity = "warn"

    def __init__(self) -> None:
        self.s: dict[str, Any] = {}
        self.reset()

    def reset(self) -> None:
        """New logical run segment: drop windowed state, re-arm."""
        self.s = {"armed": True}

    def edge(self, violated: bool) -> bool:
        """Edge-trigger helper: True exactly once per violation
        episode; re-arms when the condition clears."""
        if violated and self.s.get("armed", True):
            self.s["armed"] = False
            return True
        if not violated:
            self.s["armed"] = True
        return False

    def update(self, ev: dict, ctx: RunContext) -> list[dict]:
        raise NotImplementedError  # pragma: no cover


class NonFiniteLossRule(Rule):
    """Loss went NaN/Inf (the producer sanitizes non-finite metrics to
    null, so a null loss after any finite one IS the NaN signal)."""

    name = "loss_nonfinite"
    severity = "critical"

    def update(self, ev: dict, ctx: RunContext) -> list[dict]:
        if ev.get("kind") != "round":
            return []
        key, v = loss_of(ev.get("metrics", {}))
        if key is None:
            return []
        if v is not None:
            self.s["seen_finite"] = True
        bad = v is None and self.s.get("seen_finite", False)
        if self.edge(bad):
            return [{"round": ev["round"],
                     "message": f"{key} is non-finite at round "
                                f"{ev['round']} (training diverged)"}]
        return []


class LossDivergenceRule(Rule):
    """Loss blew past ``factor`` × the trailing-window median (plus an
    absolute ``min_delta`` guard so near-zero-loss jitter cannot trip
    the ratio).  A non-finite loss counts as divergence too — +inf is
    past every threshold."""

    name = "loss_divergence"
    severity = "critical"

    def __init__(self, window: int = 8, factor: float = 3.0,
                 min_delta: float = 0.5, min_history: int = 3):
        self.window = int(window)
        self.factor = float(factor)
        self.min_delta = float(min_delta)
        self.min_history = int(min_history)
        super().__init__()

    def reset(self) -> None:
        self.s = {"armed": True, "hist": []}

    def update(self, ev: dict, ctx: RunContext) -> list[dict]:
        if ev.get("kind") != "round":
            return []
        key, v = loss_of(ev.get("metrics", {}))
        if key is None:
            return []
        hist = self.s["hist"]
        out: list[dict] = []
        if len(hist) >= self.min_history:
            med = statistics.median(hist)
            bar = self.factor * med + self.min_delta
            cur = float("inf") if v is None else float(v)
            if self.edge(cur > bar):
                shown = "inf" if v is None else f"{cur:.4g}"
                out.append({"round": ev["round"], "value": None if v is None
                            else cur,
                            "message": f"{key}={shown} at round "
                                       f"{ev['round']} exceeds "
                                       f"{self.factor}x trailing median "
                                       f"({med:.4g})"})
        if v is not None:
            hist.append(float(v))
            del hist[:-self.window]
        return out


class ConsensusStallRule(Rule):
    """The fleet-disagreement meter (``consensus_distance``) is RISING
    across ``patience``+1 consecutive observations by more than ``tol``
    relative — mixing is not contracting (partitioned topology,
    mis-weighted matrix, or adversaries pulling the fleet apart).

    Observation sources: the ``consensus_distance`` gauge — with
    ``diagnostics="on"`` the gossip engine emits a TRUE per-round one
    inside every round bundle (the rule finally gets more than one
    observation per ``run()`` call), otherwise the engines emit one
    per ``run()`` call — the federated engine's per-round
    ``lane_dispersion`` gauge (its diagnostics-mode dispersion meter;
    a stream carries one of the two names, never both) — and, with
    ``use_checkpoints=True`` — the ``consensus_distance`` field each
    ``checkpoint`` event carries (one per save, so a long soak with
    ``--checkpoint-every K`` observes every K rounds).  The checkpoint
    source is OPT-IN because checkpoint timing is call-pattern state:
    rules reading it trade the cross-execution-path alert-identity
    guarantee for cadence, exactly like ``checkpoint_cadence``."""

    name = "consensus_stall"
    severity = "warn"

    def __init__(self, patience: int = 3, tol: float = 0.25,
                 use_checkpoints: bool = False):
        self.patience = int(patience)
        self.tol = float(tol)
        self.use_checkpoints = bool(use_checkpoints)
        super().__init__()

    def reset(self) -> None:
        self.s = {"armed": True, "hist": []}

    def update(self, ev: dict, ctx: RunContext) -> list[dict]:
        kind = ev.get("kind")
        # lane_dispersion is the federated engine's dispersion meter
        # (mean_i ||p_i - theta||, diagnostics="on") — the same
        # is-the-fleet-contracting signal under another name; a stream
        # only ever carries one of the two, so one window serves both.
        if kind == "gauge" and ev.get("name") in ("consensus_distance",
                                                  "lane_dispersion"):
            v = ev["value"]
        elif (kind == "checkpoint" and self.use_checkpoints
              and isinstance(ev.get("consensus_distance"), (int, float))):
            v = ev["consensus_distance"]
        else:
            return []
        hist = self.s["hist"]
        hist.append(float(v))
        del hist[:-(self.patience + 1)]
        rising = (len(hist) == self.patience + 1
                  and all(b >= a for a, b in zip(hist, hist[1:]))
                  and hist[-1] > hist[0] * (1.0 + self.tol))
        if self.edge(rising):
            return [{"round": ev["round"], "value": hist[-1],
                     "message": f"consensus_distance rose "
                                f"{hist[0]:.4g} -> {hist[-1]:.4g} over "
                                f"{self.patience + 1} observations "
                                "(mixing is not contracting)"}]
        return []


class GradExplosionRule(Rule):
    """A convergence-diagnostic norm gauge (``grad_norm`` — the carried
    momentum/velocity — or ``update_norm``, the round's parameter
    displacement; both emitted per round by ``diagnostics="on"``) blew
    past ``factor`` × its trailing-window median plus the absolute
    ``min_delta`` guard: gradients are exploding, usually rounds before
    the loss shows it (the loss_divergence rule's trailing median needs
    the damage to reach the objective first).  Reads only ``gauge``
    events — deterministic, so the alert sequence stays identical
    across execution paths.  Each watched gauge keeps its own window
    and edge state."""

    name = "grad_explosion"
    severity = "critical"

    def __init__(self, window: int = 8, factor: float = 10.0,
                 min_delta: float = 1.0, min_history: int = 3,
                 gauges: tuple[str, ...] = ("grad_norm", "update_norm")):
        self.window = int(window)
        self.factor = float(factor)
        self.min_delta = float(min_delta)
        self.min_history = int(min_history)
        self.gauges = tuple(gauges)
        super().__init__()

    def reset(self) -> None:
        self.s = {"armed": {}, "hist": {}}

    def _edge_key(self, key: str, violated: bool) -> bool:
        armed = self.s["armed"]
        if violated and armed.get(key, True):
            armed[key] = False
            return True
        if not violated:
            armed[key] = True
        return False

    def update(self, ev: dict, ctx: RunContext) -> list[dict]:
        if ev.get("kind") != "gauge" or ev.get("name") not in self.gauges:
            return []
        name = str(ev["name"])
        v = float(ev["value"])
        hist = self.s["hist"].setdefault(name, [])
        out: list[dict] = []
        if len(hist) >= self.min_history:
            med = statistics.median(hist)
            bar = self.factor * med + self.min_delta
            if self._edge_key(name, v > bar):
                out.append({"round": ev["round"], "value": v,
                            "message": f"{name}={v:.4g} at round "
                                       f"{ev['round']} exceeds "
                                       f"{self.factor}x trailing median "
                                       f"({med:.4g}) — gradient "
                                       "explosion"})
        hist.append(v)
        del hist[:-self.window]
        return out


class RetraceStormRule(Rule):
    """The compiled round functions are retracing as the run goes: a
    ``compile`` event (``diagnostics="on"`` emits one whenever a round
    function's trace cache grew) landed at more than ``max_rounds``
    DISTINCT rounds inside the trailing ``window`` rounds.  Healthy
    runs compile each round program once at warmup (1-2 distinct
    rounds); a compile per round means a shape/dtype is leaking into
    the trace (survivor counts as shapes, a drifting remainder block)
    and every round pays seconds of XLA time.  ``compile`` is a
    NON-deterministic kind, so like checkpoint_cadence this rule trades
    the hard cross-execution-path alert-identity guarantee for the
    signal; to keep healthy paths IDENTICAL in practice the window is
    SEGMENT-scoped — every ``run`` header (resume continuations
    included) clears it, so a killed-and-resumed run's second warmup
    reads as a fresh segment's warmup, not as half a storm."""

    name = "retrace_storm"
    severity = "warn"

    def __init__(self, window: int = 8, max_rounds: int = 3):
        self.window = int(window)
        self.max_rounds = int(max_rounds)
        super().__init__()

    def reset(self) -> None:
        self.s = {"armed": True, "rounds": []}

    def update(self, ev: dict, ctx: RunContext) -> list[dict]:
        if ev.get("kind") == "run":
            # The monitor only resets rules on round-0 headers; this
            # rule's window is meaningless across a process restart, so
            # it also clears on resume CONTINUATION headers.
            self.reset()
            return []
        if ev.get("kind") != "compile":
            return []
        t = int(ev["round"])
        rounds = self.s["rounds"]
        if t not in rounds:
            rounds.append(t)
        self.s["rounds"] = rounds = [r for r in rounds
                                     if r > t - self.window]
        if self.edge(len(rounds) > self.max_rounds):
            return [{"round": t, "value": float(len(rounds)),
                     "message": f"compiled round functions retraced at "
                                f"{len(rounds)} distinct rounds within "
                                f"the last {self.window} (fn "
                                f"{ev.get('fn')!r}) — a shape/dtype is "
                                "leaking into the trace"}]
        return []


class HbmGrowthRule(Rule):
    """Device (or host-RSS fallback) LIVE memory is rising across
    ``patience``+1 consecutive ``resource`` samples by more than
    ``tol`` relative AND ``min_bytes`` absolute — the leak shape: a
    per-block allocation that never frees (e.g. an accumulating host
    mirror, an unbounded trace cache).  Warmup allocation noise does
    not satisfy strictly-monotonic growth over five samples plus both
    margins.  ``resource`` is a NON-deterministic kind (per-block
    sampling cadence), so like retrace_storm this rule is outside the
    hard alert-identity guarantee; its window is likewise
    SEGMENT-scoped (any ``run`` header clears it — occupancy samples
    are not comparable across a process restart), keeping healthy
    paths identical in practice."""

    name = "hbm_growth"
    severity = "warn"

    def __init__(self, patience: int = 4, tol: float = 0.5,
                 min_bytes: int = 64 << 20):
        self.patience = int(patience)
        self.tol = float(tol)
        self.min_bytes = int(min_bytes)
        super().__init__()

    def reset(self) -> None:
        self.s = {"armed": True, "hist": []}

    def update(self, ev: dict, ctx: RunContext) -> list[dict]:
        if ev.get("kind") == "run":
            self.reset()
            return []
        if ev.get("kind") != "resource":
            return []
        v = ev.get("live_bytes", ev.get("peak_bytes"))
        if not isinstance(v, (int, float)):
            return []
        hist = self.s["hist"]
        hist.append(float(v))
        del hist[:-(self.patience + 1)]
        rising = (len(hist) == self.patience + 1
                  and all(b > a for a, b in zip(hist, hist[1:]))
                  and hist[-1] > hist[0] * (1.0 + self.tol)
                  and hist[-1] - hist[0] > self.min_bytes)
        if self.edge(rising):
            return [{"round": ev["round"], "value": hist[-1],
                     "message": f"live device memory rose "
                                f"{hist[0] / 2**20:.0f} -> "
                                f"{hist[-1] / 2**20:.0f} MiB over "
                                f"{self.patience + 1} consecutive "
                                "samples (leak shape)"}]
        return []


class QuarantineStormRule(Rule):
    """More than ``frac`` of a quarantine universe is out at once: the
    detector is eating the fleet (threshold too tight, or a genuinely
    majority-Byzantine regime where robust aggregation's breakdown
    point is gone either way).  Two universes, each with its MATCHING
    denominator — ``quarantine_active`` counts LANES (vs the static
    lane count), ``population_quarantined`` counts CLIENTS (vs the
    ``population_size`` gauge the registry emits) — with independent
    edge state, so a lane storm and a client storm each alert once."""

    name = "quarantine_storm"
    severity = "warn"

    def __init__(self, frac: float = 0.5):
        self.frac = float(frac)
        super().__init__()

    def reset(self) -> None:
        self.s = {"armed": {}}

    def _edge_key(self, key: str, violated: bool) -> bool:
        armed = self.s["armed"]
        if violated and armed.get(key, True):
            armed[key] = False
            return True
        if not violated:
            armed[key] = True
        return False

    def update(self, ev: dict, ctx: RunContext) -> list[dict]:
        if ev.get("kind") != "gauge":
            return []
        name = ev.get("name")
        if name == "quarantine_active":
            denom, what = ctx.workers, "workers"
        elif name == "population_quarantined":
            denom, what = ctx.population, "clients"
        else:
            return []
        if not denom:
            return []
        v = float(ev["value"])
        if self._edge_key(name, v >= self.frac * float(denom)):
            return [{"round": ev["round"], "value": v,
                     "message": f"{int(v)}/{int(denom)} {what} "
                                f"quarantined (>= {self.frac:.0%} of the "
                                "fleet)"}]
        return []


class DropRateRule(Rule):
    """Rolling lost-contribution rate (crash/straggle/msg_drop/
    partition/churn ledger rows per round, per participant) exceeded
    the SLO over a ``window``-round trailing mean.  Fault events
    precede their round event in every bundle, so the round event is
    the commit point that seals a round's count."""

    name = "drop_rate"
    severity = "warn"

    def __init__(self, max_rate: float = 1.0, window: int = 8,
                 min_rounds: int = 4):
        self.max_rate = float(max_rate)
        self.window = int(window)
        self.min_rounds = int(min_rounds)
        super().__init__()

    def reset(self) -> None:
        self.s = {"armed": True, "pending": 0, "counts": []}

    def update(self, ev: dict, ctx: RunContext) -> list[dict]:
        kind = ev.get("kind")
        if kind == "fault" and ev.get("fault") in DROP_KINDS:
            self.s["pending"] += 1
            return []
        if kind != "round":
            return []
        counts = self.s["counts"]
        counts.append(self.s["pending"])
        self.s["pending"] = 0
        del counts[:-self.window]
        denom = ctx.denominator()
        if not denom or len(counts) < self.min_rounds:
            return []
        rate = sum(counts) / len(counts) / denom
        if self.edge(rate >= self.max_rate):
            return [{"round": ev["round"], "value": rate,
                     "message": f"drop rate {rate:.2f} faults/participant/"
                                f"round over the last {len(counts)} rounds "
                                f"(SLO {self.max_rate:.2f})"}]
        return []


class StalenessSaturationRule(Rule):
    """The one-slot late-update buffer is (nearly) full fleet-wide:
    ``stale_pending`` ≥ ``frac`` × workers means every further late
    update overwrites a buffered one — the admission window is too
    small for the observed lag."""

    name = "staleness_saturation"
    severity = "warn"

    def __init__(self, frac: float = 0.9):
        self.frac = float(frac)
        super().__init__()

    def update(self, ev: dict, ctx: RunContext) -> list[dict]:
        if ev.get("kind") != "gauge" or ev.get("name") != "stale_pending":
            return []
        denom = ctx.workers
        if not denom:
            return []
        v = float(ev["value"])
        if self.edge(v >= self.frac * denom):
            return [{"round": ev["round"], "value": v,
                     "message": f"staleness buffer saturated: {int(v)}/"
                                f"{denom} slots pending"}]
        return []


class HostGapRule(Rule):
    """The host pipeline is eating wall-clock: a ``host_gap_pct``
    gauge (bench.py emits it per measured leg) above ``max_pct`` —
    the regime the prefetch overlap exists to prevent."""

    name = "host_gap"
    severity = "warn"

    def __init__(self, max_pct: float = 25.0):
        self.max_pct = float(max_pct)
        super().__init__()

    def update(self, ev: dict, ctx: RunContext) -> list[dict]:
        if ev.get("kind") != "gauge" or ev.get("name") != "host_gap_pct":
            return []
        v = float(ev["value"])
        if self.edge(v > self.max_pct):
            return [{"round": ev["round"], "value": v,
                     "message": f"host_gap_pct={v:.1f} exceeds "
                                f"{self.max_pct:.1f}% (host pipeline on "
                                "the critical path)"}]
        return []


class CheckpointCadenceRule(Rule):
    """A run configured to checkpoint every K rounds went K +
    ``slack`` rounds without a ``checkpoint`` event — the crash-exact
    resume guarantee is silently eroding.

    The expected cadence comes from the RUN ITSELF: the ``run``
    segment header's ``checkpoint_every`` field (serve daemons and
    ``--checkpoint-every`` CLI runs stamp it) or a ``control`` event
    that changes it mid-run, both tracked in ``ctx.checkpoint_every``.
    An explicit ``every=`` construction kwarg overrides the stream's
    claim (the operator knows better); with neither, the rule is
    inactive — checkpoint timing is call-pattern state, not something
    a default rule can guess."""

    name = "checkpoint_cadence"
    severity = "warn"

    def __init__(self, every: int | None = None, slack: int = 1):
        self.every = None if every is None else int(every)
        self.slack = int(slack)
        super().__init__()

    def reset(self) -> None:
        self.s = {"armed": True, "last": None, "start": None}

    def update(self, ev: dict, ctx: RunContext) -> list[dict]:
        every = self.every if self.every is not None \
            else ctx.checkpoint_every
        if not every:
            return []
        kind = ev.get("kind")
        if kind == "checkpoint":
            self.s["last"] = int(ev["round"])
            return []
        if kind != "round":
            return []
        t = int(ev["round"])
        if self.s["start"] is None:
            self.s["start"] = t
        anchor = self.s["last"] if self.s["last"] is not None \
            else self.s["start"] - 1
        overdue = t - anchor > every + self.slack
        if self.edge(overdue):
            return [{"round": t,
                     "message": f"no checkpoint for {t - anchor} rounds "
                                f"(expected every {every})"}]
        return []


RULES: dict[str, type[Rule]] = {
    cls.name: cls for cls in (
        NonFiniteLossRule, LossDivergenceRule, ConsensusStallRule,
        GradExplosionRule, RetraceStormRule, HbmGrowthRule,
        QuarantineStormRule, DropRateRule, StalenessSaturationRule,
        HostGapRule, CheckpointCadenceRule,
    )
}


def default_rules(**overrides: dict) -> list[Rule]:
    """The stock rule set with conservative defaults (silent on clean
    baseline runs).  ``overrides`` maps rule name -> kwargs dict, e.g.
    ``default_rules(loss_divergence={"factor": 2.0})``; an override of
    ``None`` drops that rule."""
    rules: list[Rule] = []
    for name, cls in RULES.items():
        kw = overrides.get(name, {})
        if kw is None:
            continue
        rules.append(cls(**kw))
    return rules


def build_rules(specs: list[dict]) -> list[Rule]:
    """Declarative construction: each spec is ``{"rule": <name>,
    **params}`` (the shape a JSON config file carries)."""
    rules = []
    for spec in specs:
        spec = dict(spec)
        name = spec.pop("rule", None)
        if name not in RULES:
            raise ValueError(f"unknown rule {name!r} "
                             f"(known: {sorted(RULES)})")
        rules.append(RULES[name](**spec))
    return rules
