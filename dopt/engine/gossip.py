"""Serverless gossip/consensus training (the reference's project 2).

Re-creates ``Simulator``/``DecFedAvg``/``NoConsDecFedAvg``/``FedLCon``
(``Distributed Optimization/src/simulators.py``) — and implements
``GossipLearning``, which the reference declares but leaves an empty
stub (simulators.py:215-217) — as ONE stacked-worker engine:

* N workers = one [W, ...] pytree sharded over the mesh worker axis.
* Consensus  x_i ← Σ_j W_ij x_j  = a collective (``mix_dense`` /
  ``mix_shifts_shardmap``) instead of ``Neighbors()`` passing
  state_dicts (simulators.py:91-97).
* Faithful round order (SURVEY §3.2): consensus → eval → local update,
  with two-phase synchronous semantics for free (pure functions read
  round-t weights only).
* The dataset lives on device once; each round ships only the [W, S, B]
  int32 batch plan and gathers on-device — no per-round host copies of
  the data.

Round accounting follows the reference: ``self.round`` persists across
``run()`` calls (servers.py:18,78) and time-varying schedules select
``matrices[round % len]`` (simulators.py:141-142).
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from dopt.config import ExperimentConfig
from dopt.data import (PrefetchStager, eval_batches, load_dataset,
                       make_batch_plan, partition, sharded_eval_batches,
                       timed_build)
from dopt.engine.local import (_stacked_eval_scan, flat_input_apply,
                               flat_input_stacked_apply, make_evaluator,
                               make_stacked_evaluator, make_stacked_local_update,
                               make_stacked_local_update_epochs,
                               make_stacked_local_update_gather,
                               pick_gather_chunks, prepare_holdout,
                               validate_optimizer)
from dopt.models import build_model, count_params
from dopt.parallel.collectives import (buckets_to_stacked, make_codec_plan,
                                        make_update_shard_spec, mix_codec_gather,
                                        mix_dense, mix_shifts,
                                        mix_update_scatter, stacked_to_buckets,
                                        where_mask)
from dopt.parallel.mesh import (make_worker_mesh, shard_over_workers,
                                shard_worker_tree, worker_axes,
                                worker_sharding)
from dopt.faults import FaultPlan, churn_ledger_rows, corrupt_update
from dopt.robust import (byzantine_mix, clipped_gossip_mix,
                         finite_lane_mask, lane_sq_norms,
                         validate_robust_config)
from dopt.topology import (MixingMatrices, build_mixing_matrices,
                           coeffs_for_matrix, repair_for_dropout,
                           repair_for_partition,
                           schedule_shift_decomposition)
from dopt.utils.metrics import History
from dopt.utils.profiling import PhaseTimers
from dopt.utils.prng import host_rng


def _reject_sequence_model(cfg: ExperimentConfig) -> None:
    """The federated/gossip engines drive image/feature datasets with
    float inputs; sequence models need int32 token batches and a
    sequence-parallel mesh — fail early with a pointer instead of an
    obscure Embed dtype error deep inside model.init."""
    if cfg.model.model.lower() == "transformer":
        raise ValueError(
            "model='transformer' is a sequence model and is not drivable by "
            "the federated/gossip engines (their datasets are image/feature "
            "tensors); use the sequence-parallel LM engine instead: "
            "SeqLMConfig + dopt.engine.SeqLMTrainer "
            "(python -m dopt.run --preset seqlm)"
        )


def random_matching_matrix(n: int, rng: np.random.Generator) -> np.ndarray:
    """GossipLearning round matrix: a random perfect matching; matched
    pairs average (w=1/2 each), unmatched (odd n) keep their weights.
    This is classic pairwise gossip — the algorithm the reference's
    empty ``GossipLearning`` stub names."""
    w = np.zeros((n, n))
    perm = rng.permutation(n)
    for k in range(0, n - 1, 2):
        i, j = perm[k], perm[k + 1]
        w[i, i] = w[j, j] = 0.5
        w[i, j] = w[j, i] = 0.5
    if n % 2:
        i = perm[-1]
        w[i, i] = 1.0
    return w


class GossipTrainer:
    """D-SGD / no-consensus / FedLCon / GossipLearning on the mesh.

    algorithm (cfg.gossip.algorithm):
      'dsgd'        — consensus then local update (DecFedAvg, simulators.py:133-167)
      'nocons'      — local update only (NoConsDecFedAvg, :110-131)
      'centralized' — preset: force num_users=1, local_ep=1, iid (:169-174,
                      without mutating the caller's config object)
      'fedlcon'     — eps consensus sweeps per round (:176-212, bug fixed;
                      cfg.gossip.faithful_bugs=True reproduces the
                      effectively-one-sweep behaviour)
      'gossip'      — random pairwise matching per round (the stub, implemented)
      'choco'       — CHOCO-SGD (Koloskova et al. 2019): compressed-difference
                      gossip Q(x_i − x̂_i) with error feedback; consensus step
                      x_i += γ·((W x̂)_i − x̂_i).  Beyond the reference —
                      communication-efficient decentralized training.
    """

    engine_kind = "gossip"

    def __init__(self, cfg: ExperimentConfig, *, eval_every: int = 1,
                 membership=None):
        if cfg.gossip is None:
            raise ValueError("cfg.gossip must be set for GossipTrainer")
        if membership is not None and cfg.population is not None:
            raise ValueError(
                "the serve membership overlay does not compose with the "
                "client population registry (cohort sampling already "
                "models client join/leave; a lane-level overlay would "
                "silently fight the registry's shard assignment) — drop "
                "one of the two")
        g = cfg.gossip
        if g.algorithm not in ("dsgd", "nocons", "centralized", "fedlcon",
                               "gossip", "choco"):
            raise ValueError(
                f"unknown gossip algorithm {g.algorithm!r}; one of "
                "dsgd|nocons|centralized|fedlcon|gossip|choco"
            )
        if g.eval_mode not in ("full", "sharded"):
            raise ValueError(f"unknown eval_mode {g.eval_mode!r}; "
                             "one of full|sharded")
        _reject_sequence_model(cfg)
        validate_optimizer(cfg)
        if g.algorithm == "centralized":
            # The reference's Centeralized mutates the SHARED args object
            # (simulators.py:171-173) — we derive a new frozen config.
            cfg = cfg.replace(
                data=dataclasses.replace(cfg.data, num_users=1, iid=True),
                gossip=dataclasses.replace(g, local_ep=1, algorithm="nocons"),
            )
            g = cfg.gossip
        self.cfg = cfg
        self.eval_every = eval_every
        self.round = 0
        self.history = History(cfg.name)
        # Per-epoch per-worker rows (only filled when the local holdout
        # is on): the reference's Client.history
        # (P2 clients.py:52-57 {iter, train_loss, train_acc, val_acc,
        # val_loss}), plus a 'worker' column since all clients share one
        # engine.
        self.client_history = History(cfg.name + "-clients")
        self.timers = PhaseTimers()
        # Telemetry (dopt.obs): None (default) = the exact pre-telemetry
        # host loop; set via dopt.obs.attach.  All emission sites are
        # python-gated host code after the post-fetch boundary, so the
        # compiled device programs are independent of it either way.
        self.telemetry = None
        # Serve-mode hooks (dopt.serve): ``run_served`` drives the loop
        # one round per controller tick and defers the end-of-run
        # summary gauge to the drain boundary; followers of a
        # multi-process serve fleet participate in checkpoint
        # collectives but leave the write to the leader.
        self._suppress_run_summary = False
        self.checkpoint_writer = True

        w = cfg.data.num_users
        self.num_workers = w
        self.mesh = make_worker_mesh(w, cfg.mesh_devices, cfg.mesh_hosts)

        # Data: load, partition, upload once.
        self.dataset = load_dataset(
            cfg.data.dataset, data_dir=cfg.data.data_dir,
            train_size=cfg.data.synthetic_train_size,
            test_size=cfg.data.synthetic_test_size, seed=cfg.seed,
            input_shape=cfg.model.input_shape,
            num_classes=cfg.model.num_classes,
        )
        _, self.index_matrix = partition(
            self.dataset.train_y, w, iid=cfg.data.iid,
            shards_per_user=cfg.data.shards, seed=cfg.seed,
        )
        # Local train/val holdout (reference train_val_test, P2
        # clients.py:19-32): training runs on the 90% sub-shard only and
        # every local epoch evaluates the worker's own val split.
        self._holdout, self._train_matrix, self._val = prepare_holdout(
            cfg, self.index_matrix, self.mesh, batch_size=g.local_bs)
        # Resident train features stay FLAT on device: TPU row-gathers
        # from [N, H, W, C] with a tiny minor dim are far slower than
        # from [N, F], and the shaped layout contaminates downstream
        # ops (see flat_input_apply).  The local-update apply fns are
        # wrapped to reshape rows at use.
        self._sample_shape = self.dataset.train_x.shape[1:]
        ntr = self.dataset.train_x.shape[0]
        self._train_x = jnp.asarray(self.dataset.train_x.reshape(ntr, -1))
        self._train_y = jnp.asarray(self.dataset.train_y)
        if g.eval_mode == "sharded":
            # Per-worker round-robin test shards ([W, S, B] stacks of
            # FLAT feature rows): the fleet-mean metric costs |test|
            # sample-forwards per eval instead of W·|test| (the full
            # mode's per-round eval exceeded the baseline5 training
            # round itself — see GossipConfig.eval_mode).
            tn = len(self.dataset.test_y)
            si, sw = sharded_eval_batches(tn, w,
                                          batch_size=max(g.local_bs, 256))
            test_flat = self.dataset.test_x.reshape(tn, -1)
            self._eval = (jnp.asarray(test_flat[si]),
                          jnp.asarray(self.dataset.test_y[si]),
                          jnp.asarray(sw))
            self._eval_full = None     # built lazily by evaluate()
        else:
            ex, ey, ew = eval_batches(self.dataset.test_x,
                                      self.dataset.test_y,
                                      batch_size=max(g.local_bs, 256))
            self._eval = (jnp.asarray(ex), jnp.asarray(ey), jnp.asarray(ew))
            self._eval_full = self._eval

        # Model + stacked state (every worker starts from the same init —
        # the reference deepcopies one global model, simulators.py:23-24).
        self.model = build_model(
            cfg.model.model, num_classes=cfg.model.num_classes,
            faithful=cfg.model.faithful, dtype=cfg.model.compute_dtype,
            stage_sizes=cfg.model.stage_sizes,
        )
        key = jax.random.key(cfg.seed)
        dummy = jnp.zeros((1, *cfg.model.input_shape))
        params0 = self.model.init(key, dummy)["params"]
        # param_dtype: storage dtype of the stacked worker state (bf16
        # halves HBM + collective bytes; f32 is the parity mode).
        pdt = jnp.dtype(cfg.model.param_dtype)
        params0 = jax.tree.map(lambda x: x.astype(pdt), params0)
        self.param_count = count_params(params0)
        # Broadcast to the fleet HOST-SIDE from the single-worker init:
        # fetching only |θ| over the (slow) device→host tunnel instead
        # of round-tripping the full W·|θ| stacked tree (1.4 GB for the
        # 32-worker ResNet — construction-time, not training-time, but
        # minutes of wall-clock through a degraded link).
        p_host = jax.device_get(params0)
        stacked = jax.tree.map(
            lambda x: np.broadcast_to(x[None], (w,) + x.shape), p_host)
        self.params = shard_worker_tree(stacked, self.mesh)
        self.momentum = shard_worker_tree(
            jax.tree.map(np.zeros_like, stacked), self.mesh
        )
        # CHOCO-SGD "public copy" state x̂ (what the fleet believes each
        # worker's params are, updated only by compressed q exchanges).
        self.x_hat = (
            shard_worker_tree(jax.tree.map(np.zeros_like, stacked), self.mesh)
            if g.algorithm == "choco" else {}
        )

        # Mixing schedule (matrices are data).
        if g.algorithm in ("dsgd", "fedlcon", "choco"):
            self.mixing: MixingMatrices | None = build_mixing_matrices(
                g.topology, g.mode, w, seed=cfg.seed, self_weight=g.self_weight,
                groups=g.hier_groups, period=g.hier_period,
            )
        else:
            self.mixing = None

        self._matching_rng = host_rng(cfg.seed, 60551)
        # Fault injection (dopt.faults.FaultPlan): crashes, stragglers
        # and partitions drawn statelessly per round on the host; the
        # mixing matrix is repaired as data and dead lanes keep their
        # state via where_mask (elastic rejoin).  ``GossipConfig.dropout``
        # is the back-compat alias for crash-only faults.
        self.faults = FaultPlan(w, cfg.faults, seed=cfg.seed,
                                dropout=g.dropout, membership=membership)
        has_faults = self.faults.active
        may_straggle = self.faults.may_straggle

        # Client population registry (dopt.population): the gossip-side
        # integration is cohort→lane DATA binding — each round the
        # stateless sampler binds ``n`` population clients onto the n
        # lanes, so lane i trains client c_i's assigned shard under
        # client c_i's batch stream while the consensus state stays
        # lane-resident (a sampled client inherits the lane's current
        # model from its previous occupant, the decentralized-FL
        # hand-off).  Client-keyed FAULT identity is a federated-engine
        # feature: gossip's crash/corrupt/link machinery is lane-keyed
        # throughout, so composing it with a per-round client rebinding
        # would silently change what "worker i" means — rejected loudly
        # instead.  population=None compiles the exact pre-change
        # programs.
        self._registry = None
        if cfg.population is not None:
            from dopt.population import (ClientRegistry,
                                         validate_population_config)

            pop = cfg.population
            validate_population_config(pop)
            if pop.cohort != w:
                raise ValueError(
                    f"gossip population mode trains every lane every "
                    f"round: set cohort == data.num_users "
                    f"(cohort={pop.cohort}, num_users={w}); wave-looped "
                    "cohorts are a federated-engine feature")
            if pop.lanes not in (None, w):
                raise ValueError(
                    f"gossip population mode binds onto the fixed "
                    f"{w}-lane fleet; lanes={pop.lanes} is a federated-"
                    "engine knob")
            if has_faults or g.dropout > 0:
                raise ValueError(
                    "gossip population mode does not compose with fault "
                    "injection (gossip fault identity is lane-keyed; a "
                    "per-round client rebinding would silently change "
                    "what 'worker i' means) — use the federated engine "
                    "for client-keyed faults")
            if cfg.robust is not None and (cfg.robust.clip_radius > 0
                                           or cfg.robust.quarantine_after
                                           > 0):
                raise ValueError(
                    "gossip population mode does not compose with the "
                    "robust layer (screen/quarantine identity is lane-"
                    "keyed, and its ledger rows would interleave "
                    "differently under blocked execution) — the "
                    "federated engine is the client-keyed path")
            if cfg.data.local_holdout > 0:
                raise ValueError(
                    "gossip population mode is incompatible with the "
                    "local holdout (per-epoch client rows are lane-"
                    "keyed) — drop one of the two")
            self._registry = ClientRegistry(pop, num_shards=w,
                                            seed=cfg.seed, lanes=w)

        # Prefetched host pipeline (dopt.data.prefetch): "on" makes the
        # blocked loops stage block b+1's plans + fault inputs while
        # block b runs on device.  "off" (default) is the exact
        # pre-change host loop — the oracle-parity mode.
        if g.prefetch not in ("off", "on"):
            raise ValueError(
                f"unknown prefetch {g.prefetch!r}; one of off|on")
        self._prefetch = g.prefetch == "on"
        # Per-round convergence diagnostics (GossipConfig.diagnostics):
        # "on" computes the diag scalar block INSIDE the compiled round
        # (it rides the packed host-metrics vector, so the blocked scan
        # carries it as one more stacked output) and emits it as
        # deterministic gauges at the post-fetch boundary, plus the
        # non-deterministic resource/compile channel when telemetry is
        # attached.  "off" (default) compiles the exact pre-change
        # programs — every use below is python-gated on it.
        if g.diagnostics not in ("off", "on"):
            raise ValueError(
                f"unknown diagnostics {g.diagnostics!r}; one of off|on")
        self._diag = g.diagnostics == "on"
        from dopt.obs.events import DIAG_GAUGES

        # The packed block's emission names: the shared five + this
        # engine's dispersion meter (round_diag's stack order).
        self._diag_keys = DIAG_GAUGES + ("consensus_distance",)
        from dopt.utils.profiling import CompileWatcher

        self._compile_watch = CompileWatcher()
        self._last_step_total = 0.0
        if self._diag and self._registry is not None:
            raise ValueError(
                "diagnostics='on' does not compose with population mode "
                "(lanes rebind to a different client cohort every round, "
                "so round-over-round lane diagnostics would mix cohort "
                "resampling noise with actual contraction) — drop one of "
                "the two")
        if self._prefetch and self._registry is not None:
            raise ValueError(
                "prefetch='on' does not compose with gossip population "
                "mode (the cohort binding mutates the registry and "
                "appends its ledger row at plan time, which a staged "
                "build must not do) — the federated engine is the "
                "prefetch-eligible population path")

        # Byzantine threat model (dopt.robust): workers can LIE on the
        # wire — their broadcast state is corrupted inside the jitted
        # round — and the defense is clipped gossip (every neighbor
        # deviation norm-clipped before the mixing weights apply) plus
        # the detection/quarantine layer.  All of it is gated on
        # ``robust_active`` so clean runs compile the exact pre-robust
        # program.
        has_corrupt = self.faults.has_corrupt
        self._has_corrupt = has_corrupt
        corrupt_mode = cfg.faults.corrupt_mode if has_corrupt else "nan"
        corrupt_scale = cfg.faults.corrupt_scale if has_corrupt else 1.0
        rcfg = cfg.robust
        if rcfg is not None:
            validate_robust_config(rcfg)
            if rcfg.aggregator != "mean":
                raise ValueError(
                    "server-side robust aggregators are a federated-engine "
                    "knob; the gossip defense is clipped mixing "
                    "(RobustConfig.clip_radius)")
        clip_tau = rcfg.clip_radius if rcfg is not None else 0.0
        self._quarantine_on = bool(rcfg is not None
                                   and rcfg.quarantine_after > 0)
        self._quarantine_after = rcfg.quarantine_after if rcfg else 0
        self._quarantine_rounds = rcfg.quarantine_rounds if rcfg else 0
        self._screen_streak = np.zeros(w, np.int64)
        self._quarantine_until = np.zeros(w, np.int64)
        robust_active = has_corrupt or clip_tau > 0 or self._quarantine_on
        self._robust_active = robust_active
        if has_corrupt:
            if cfg.faults.corrupt_mode == "stale":
                raise ValueError(
                    "corrupt_mode='stale' needs the worker's previous "
                    "update, which only the federated engine carries; "
                    "use nan|inf|scale|signflip for gossip")
            if g.algorithm not in ("dsgd", "fedlcon", "gossip"):
                raise ValueError(
                    "corrupt faults need a mixing algorithm to lie "
                    f"through (dsgd|fedlcon|gossip), not {g.algorithm!r}")
        if robust_active and g.algorithm == "choco":
            raise ValueError(
                "the robust layer does not cover choco's compressed "
                "exchange; use dsgd|fedlcon|gossip")
        if robust_active and g.comm_dtype:
            # The robust consensus paths (clipped_gossip_mix /
            # byzantine_mix) run full-precision pairwise math and never
            # consult the wire-compression knob — reject rather than
            # silently run a different experiment than configured
            # (mirrors the federated aggregator+comm_dtype reject).
            raise ValueError(
                "comm_dtype wire compression only applies to the plain "
                "consensus collectives; the robust layer (corrupt "
                "faults / clip_radius / quarantine) runs full-precision "
                "pairwise mixing — drop one of the two")
        if (clip_tau > 0 or self._quarantine_on) and g.algorithm == "nocons":
            # No consensus step means no wire to clip and no screened
            # signal to quarantine on — reject loudly rather than run
            # with a defense the user believes is active.
            raise ValueError(
                "RobustConfig clip_radius/quarantine need a mixing "
                "algorithm to act on (dsgd|fedlcon|gossip); "
                f"{cfg.gossip.algorithm!r} never communicates")

        # Lossy-link network model (dopt.faults msg_drop / msg_delay) and
        # the push-sum bias correction (GossipConfig.correction).  Both
        # route consensus through the link-matrix path: the round's
        # effective mixing becomes a [D+1, n, n] per-staleness stack
        # (dopt.topology.split_by_delay) contracted against the current
        # sends plus up-to-D-rounds-stale buffered state carried as
        # engine state.  correction="push_sum" additionally carries a
        # scalar mass per worker through the SAME (column-stochastic,
        # mass-conserving) matrices and de-biases as params/mass —
        # ratio consensus / Stochastic Gradient Push.  Everything is
        # gated on _link_mode so clean runs compile the exact
        # pre-change program.
        if g.correction not in ("none", "push_sum"):
            raise ValueError(f"unknown gossip correction {g.correction!r}; "
                             "one of none|push_sum")
        self._push_sum = g.correction == "push_sum"
        self._has_link = self.faults.has_link
        self._link_mode = self._has_link or self._push_sum
        self._delay_max = self.faults.delay_max
        if self._link_mode:
            if g.algorithm not in ("dsgd", "gossip"):
                raise ValueError(
                    "link faults (msg_drop/msg_delay) and "
                    "correction='push_sum' need a single-sweep mixing "
                    "algorithm (dsgd|gossip), not "
                    f"{g.algorithm!r}")
            if g.comm_dtype:
                raise ValueError(
                    "comm_dtype wire compression only applies to the "
                    "plain consensus collectives; the link-fault / "
                    "push-sum path runs its own per-staleness "
                    "contractions — drop one of the two")
            if clip_tau > 0:
                raise ValueError(
                    "clipped gossip does not compose with the lossy-link "
                    "consensus path yet — run clip_radius and link "
                    "faults in separate experiments")
            # Quarantine DOES compose with link faults, via the alive
            # machinery: a quarantined worker's edges are repaired out
            # of the matrix before the link drops/delays apply.  The
            # link path emits no screened flags (only finite lies reach
            # it), so the quarantine state evolves purely by expiry —
            # which is what keeps its plan-time inputs exact under
            # blocked execution.
            if has_corrupt and cfg.faults.corrupt_mode in ("nan", "inf"):
                raise ValueError(
                    "corrupt_mode='nan'/'inf' under link faults would "
                    "need byzantine_mix's poison routing, which the "
                    "per-staleness link path does not implement; use "
                    "the finite lies (scale|signflip)")

        # Fused-quarantine execution (the "everything is scan carry"
        # model): on the dense robust path the quarantine streak/until
        # state is int32 DEVICE state riding the blocked scan as carry,
        # the alive mask combination + matrix repair happen inside the
        # compiled round (dopt.topology.repair_for_dropout_jnp), and
        # the host replays the identical integer update rule post-fetch
        # for the ledger rows — so quarantined runs are blocked-eligible
        # with bit-identical per-round/blocked traces.  Link-mode
        # quarantine stays host-side plan-time data: the link path
        # screens nothing, so its quarantine state evolves by expiry
        # alone and is exactly known when the block is planned.
        self._fused_quar = self._quarantine_on and not self._link_mode
        fused_quar = self._fused_quar
        q_after = self._quarantine_after
        q_rounds = self._quarantine_rounds

        # Compiled round step.
        update_impl = "pallas" if cfg.optim.fused_update else "jnp"
        l2 = cfg.optim.weight_decay
        # Big-gather chunking for the resident-data scan paths: per-step
        # gathers cost ~250 µs of fixed overhead each on a v5e (18% of
        # device time on the headline workload) — split the plan into the
        # fewest chunks whose materialised [W, S/k, B, sample] slab fits
        # the budget and gather each chunk in one op instead.
        l_shard = self._train_matrix.shape[1]
        bs_eff = min(g.local_bs, l_shard)
        spe = -(-l_shard // bs_eff)  # steps per epoch (ceil, padded plan)
        sample_bytes = (int(np.prod(self.dataset.train_x.shape[1:]))
                        * self.dataset.train_x.dtype.itemsize)
        self._gather_chunks = pick_gather_chunks(
            g.local_ep * spe, workers=w, batch=bs_eff,
            sample_bytes=sample_bytes)
        epoch_chunks = pick_gather_chunks(
            spe, workers=w, batch=bs_eff, sample_bytes=sample_bytes)
        # Straggler-deadline granularity: the holdout's epoch loop gates
        # per EPOCH, the flat path per SGD step over the whole plan.
        self._straggle_units = g.local_ep if self._holdout else g.local_ep * spe
        # Grouped stacked-forward fast path (make_stacked_apply): the
        # whole fleet's forward as one feature-grouped conv program
        # instead of vmap-over-workers (~3× step speedup on TPU).
        from dopt.models.zoo import resolve_stacked_apply

        self._stacked_apply = resolve_stacked_apply(self.model,
                                                    cfg.model.stacked_impl)
        s_apply = self._stacked_apply
        # Flat-row adapters for everything that trains from the resident
        # train arrays (the evaluators consume shaped host-built stacks
        # and keep the raw apply).  (A fast-layout param codec that
        # hoists the per-step kernel relayout out of the scan was
        # measured and REJECTED: carried grouped-layout kernels make
        # XLA pick worse conv layouts — headline 378→401 ms/round,
        # baseline5 2410→2572 ms/round device time.)
        app_f = flat_input_apply(self.model.apply, self._sample_shape)
        s_apply_f = (flat_input_stacked_apply(s_apply, self._sample_shape)
                     if s_apply is not None else None)
        # may_straggle keys the compiled local-update shape: the
        # with_limit variants thread a [W] work budget (epochs under the
        # holdout, SGD steps on the flat path) that freezes a straggler's
        # params/momentum at its deadline.  Fault-free configs compile
        # the exact pre-fault program.
        local = make_stacked_local_update(
            app_f, lr=cfg.optim.lr, momentum=cfg.optim.momentum,
            algorithm="sgd", l2=l2, update_impl=update_impl,
            stacked_apply=s_apply_f, clip_norm=cfg.optim.clip_norm,
            with_limit=may_straggle,
        )
        local_epochs = (
            make_stacked_local_update_epochs(
                app_f, lr=cfg.optim.lr,
                momentum=cfg.optim.momentum, algorithm="sgd", l2=l2,
                update_impl=update_impl, gather_chunks=epoch_chunks,
                stacked_apply=s_apply_f, clip_norm=cfg.optim.clip_norm,
                with_limit=may_straggle)
            if self._holdout else None
        )
        if s_apply_f is not None and self.mesh.size > 1:
            # The local phase is embarrassingly parallel across workers,
            # so on a multi-device mesh the grouped-stacked update runs
            # under shard_map (dopt.parallel.mesh.shard_over_workers):
            # per-device lanes, local feature-group count, zero
            # collectives.
            local = shard_over_workers(
                local, self.mesh, "w" * (6 if may_straggle else 5), "w" * 4)
            if local_epochs is not None:
                local_epochs = shard_over_workers(
                    local_epochs, self.mesh,
                    "wwwwwrrww" if may_straggle else "wwwwrrww", "www")
        use_holdout = self._holdout
        local_ep_n = g.local_ep
        full_evaluator = make_stacked_evaluator(self.model.apply,
                                                stacked_apply=s_apply)
        if s_apply is not None and self.mesh.size > 1:
            full_evaluator = shard_over_workers(full_evaluator, self.mesh,
                                                "wrrr", "w")
        if g.eval_mode == "sharded":
            # Per-worker-data eval over [W, S, B] flat-row stacks — the
            # same [W]-dict contract as the full evaluator, so the round
            # and block programs are mode-agnostic.
            if s_apply_f is not None:
                def evaluator(p, ex, ey, ew):
                    return _stacked_eval_scan(
                        s_apply_f, p, ex.swapaxes(0, 1), ey.swapaxes(0, 1),
                        ew.swapaxes(0, 1))
                if self.mesh.size > 1:
                    evaluator = shard_over_workers(evaluator, self.mesh,
                                                   "wwww", "w")
            else:
                evaluator = jax.vmap(make_evaluator(app_f))
        else:
            evaluator = full_evaluator
        self._full_evaluator = full_evaluator
        eps = 1 if (g.algorithm != "fedlcon" or g.faithful_bugs) else g.eps
        do_mix = g.algorithm in ("dsgd", "fedlcon", "gossip")
        is_choco = g.algorithm == "choco"
        mesh = self.mesh
        comm_dtype = jnp.dtype(g.comm_dtype) if g.comm_dtype else None

        # Communication substrate schedule (ExperimentConfig.comm): the
        # per-bucket wire codecs of dopt.parallel.collectives speak the
        # flat-bucket scatter representation, so CommConfig requires
        # update_sharding='scatter' — one substrate, one schedule,
        # shared with the federated engine.  None python-gates every
        # use below: default-off programs stay byte-identical.
        comm_cfg = cfg.comm
        codec_on = comm_cfg is not None and comm_cfg.codec != "none"
        if comm_cfg is not None:
            if g.update_sharding != "scatter":
                raise ValueError(
                    "the comm substrate schedule (ExperimentConfig.comm) "
                    "speaks the flat-bucket wire of "
                    "update_sharding='scatter'; set "
                    "gossip.update_sharding='scatter' to arm it (got "
                    f"update_sharding={g.update_sharding!r})")
            if g.comm_dtype and comm_cfg.wire_dtype:
                raise ValueError(
                    f"gossip.comm_dtype={g.comm_dtype!r} and "
                    f"comm.wire_dtype={comm_cfg.wire_dtype!r} both name "
                    "a wire dtype; set exactly one (comm.wire_dtype is "
                    "the substrate-schedule spelling of the same knob)")
            if codec_on and g.algorithm not in ("dsgd", "gossip"):
                raise ValueError(
                    f"comm.codec={comm_cfg.codec!r} carries a per-bucket "
                    "error-feedback residual across single-sweep "
                    "consensus rounds; use algorithm dsgd|gossip "
                    f"(got {g.algorithm!r}: fedlcon's eps sweeps would "
                    "re-encode mid-round, choco already quantizes its "
                    "own exchange, nocons|centralized|matching never "
                    "run the bucket wire)")
            if codec_on and g.comm_impl == "shift":
                raise ValueError(
                    "comm_impl='shift' ships circulant ppermute lanes; "
                    "the bucket codec speaks the gathered-bucket wire — "
                    "use comm_impl='auto'|'dense' with comm.codec")
            if codec_on and cfg.population is not None:
                raise ValueError(
                    "comm.codec with population mode would hand lane "
                    "i's quantization residual to a different client "
                    "after a cohort rebinding; run the codec on the "
                    "classic worker==lane engines (population=None)")
            if comm_cfg.wire_dtype:
                comm_dtype = jnp.dtype(comm_cfg.wire_dtype)

        # Consensus collective selection (GossipConfig.comm_impl): the
        # ppermute shift path replaces the reference's Neighbors()
        # state-dict passing (simulators.py:91-97) with O(k·|θ|) bytes of
        # ICI neighbor traffic per round instead of the dense path's
        # O(n·|θ|) all_gather.  The shift SET is static (compiled); the
        # per-round coefficients are data, so time-varying schedules and
        # dropout-repaired matrices reuse one compiled step.
        if g.comm_impl not in ("auto", "dense", "shift"):
            raise ValueError(
                f"unknown comm_impl {g.comm_impl!r}; one of auto|dense|shift")
        if g.comm_impl == "shift" and robust_active:
            raise ValueError(
                "comm_impl='shift' is incompatible with the robust layer: "
                "clipped mixing / corrupt sends need the dense pairwise "
                "path (the 'auto' default picks it)")
        if g.comm_impl == "shift" and self._link_mode:
            raise ValueError(
                "comm_impl='shift' is incompatible with link faults / "
                "push-sum: drop-repaired matrices leave the compiled "
                "shift set and the per-staleness stack needs the dense "
                "path (the 'auto' default picks it)")
        self._shift_ids: tuple[int, ...] | None = None
        if (g.comm_impl != "dense" and not robust_active
                and not self._link_mode and not codec_on
                and self.mixing is not None and (do_mix or is_choco)):
            flat_1d = len(mesh.axis_names) == 1
            extra = (0,) if self.faults.affects_matrix else ()
            ids = (schedule_shift_decomposition(self.mixing, max_shifts=None,
                                                extra_shifts=extra)
                   if flat_1d else None)
            if ids is not None and g.comm_impl == "auto":
                # Take the ppermute path only when it actually wins:
                # (a) there IS a wire — on a 1-device mesh every "shift"
                #     is a local lane slice and the dense tensordot is
                #     strictly better (one gemm vs one sliced copy of
                #     the stacked state PER shift, which OOMs ResNet-32
                #     on a single chip);
                # (b) the shift set is sparse (≤ max(3, w/2) diagonals —
                #     ring/dynamic/torus yes, complete/random no: the
                #     local mix work is linear in the shift count);
                # (c) its ICI bytes beat the all_gather with a 2× margin
                #     (shift_comm_lanes counts only the lanes shifts
                #     consume, vs the dense (n − L) remote lanes), with
                #     a floor of 3 shipped lanes so tiny rings — where
                #     the margin can't hold numerically — keep the
                #     stable ppermute routing.
                from dopt.parallel.collectives import shift_comm_lanes

                lanes = w // mesh.size
                shipped = shift_comm_lanes(ids, lanes, mesh.size)
                if (mesh.size == 1
                        or len(ids) > max(3, w // 2)
                        or (shipped > 3
                            and 2 * shipped > max(w - lanes, 1))):
                    ids = None
            if ids is not None:
                self._shift_ids = ids
            elif g.comm_impl == "shift":
                raise ValueError(
                    "comm_impl='shift' requires a flat 1-D worker mesh "
                    f"(workers={w}, mesh={mesh.shape}) and a mixing "
                    "schedule that decomposes into circulant shifts "
                    f"(topology={g.topology!r})")
        elif g.comm_impl == "shift":
            raise ValueError(
                "comm_impl='shift' needs a mixing-schedule algorithm "
                f"(dsgd|fedlcon|choco), not {g.algorithm!r}")

        shift_ids = self._shift_ids

        # Sharded weight-update/consensus hot path (ISSUE 5 tentpole):
        # update_sharding="scatter" flattens θ into size-bounded buckets
        # and runs the mixing as reduce-scatter partial contractions
        # (dense) or the sharded circulant contraction over the same
        # buckets (shift), with per-bucket collectives the XLA
        # latency-hiding scheduler can overlap with compute.  "off"
        # keeps every pre-change program byte-for-byte (python gating).
        if g.update_sharding not in ("off", "scatter"):
            raise ValueError(
                f"unknown update_sharding {g.update_sharding!r}; "
                "one of off|scatter")
        self._scatter_spec = None
        if g.update_sharding == "scatter":
            if g.algorithm not in ("dsgd", "fedlcon", "gossip", "choco"):
                raise ValueError(
                    "update_sharding='scatter' shards the consensus "
                    "mix; algorithm "
                    f"{g.algorithm!r} has no dense mixing step to "
                    "shard (dsgd|fedlcon|gossip|choco)")
            if robust_active:
                raise ValueError(
                    "update_sharding='scatter' does not compose with "
                    "the robust layer (corrupt faults / clip_radius / "
                    "quarantine run full-precision pairwise mixing on "
                    "the unsharded tree) — drop one of the two")
            if self._link_mode:
                raise ValueError(
                    "update_sharding='scatter' does not compose with "
                    "link faults / push-sum (the per-staleness "
                    "[D+1, n, n] contraction carries its own buffers) "
                    "— drop one of the two")
            if len(mesh.axis_names) != 1:
                raise ValueError(
                    "update_sharding='scatter' needs a flat 1-D worker "
                    f"mesh (got {mesh.shape}); hybrid (hosts × ici) "
                    "meshes keep the dense path")
            from dopt.parallel.mesh import enable_latency_hiding_scheduler

            # Best-effort: on TPU the overlap needs the scheduler
            # flags in XLA_FLAGS before backend init (bench.py sets
            # them up front; this warns when too late).  The helper
            # gates on the env/libtpu probe itself — calling
            # jax.default_backend() here would INITIALIZE the backend
            # and guarantee the too-late path.
            enable_latency_hiding_scheduler()
            self._scatter_spec = make_update_shard_spec(
                stacked, fold=mesh.size,
                bucket_bytes=int(g.update_bucket_mb * (1 << 20)))
        scatter_spec = self._scatter_spec

        # Per-bucket wire schedule + error-feedback residual.  The plan
        # is compiled structure (built once from the spec); the residual
        # is carried engine state ("comm_residual" in checkpoints) —
        # round −1's residual is defined as zero, so codec round 0
        # encodes exactly v = x.  Built from fresh zeros: round_fn
        # donates the carry, and a donated input must never alias the
        # init tree.
        self._codec_plan = None
        self._codec_on = codec_on
        self._comm_res: object = ()
        codec_plan = None
        comm_key = None
        comm_ef = True
        if comm_cfg is not None and scatter_spec is not None:
            self._codec_plan = make_codec_plan(
                scatter_spec, codec=comm_cfg.codec,
                wire_dtype=comm_cfg.wire_dtype,
                byte_budget=int(comm_cfg.byte_budget_mb * (1 << 20)),
                min_codec_bytes=comm_cfg.min_codec_bytes,
                chunk=comm_cfg.chunk)
            codec_plan = self._codec_plan
            comm_ef = comm_cfg.error_feedback == "on"
        if codec_on:
            comm_key = jax.random.key(cfg.seed ^ 0xC0DEC)
            widths = [b - a for a, b in zip(scatter_spec.bounds,
                                            scatter_spec.bounds[1:])]
            self._comm_res = shard_worker_tree(
                tuple(np.zeros((w, wd), np.float32) for wd in widths),
                self.mesh)

        # Asynchronous (staleness-1) gossip (GossipConfig.mixing): round
        # t's mix reads the PREVIOUS round's neighbor state — x_i ←
        # W_ii·x_i(t) + Σ_{j≠i} W_ij·x_j(t−1) — so round r's neighbor
        # communication fully overlaps round r+1's compute.  The
        # previous-round buffer is carried engine state ("async_prev"):
        # a double-buffered scan carry under blocked execution and a
        # checkpoint array on resume.  "sync" (default) python-gates
        # every use below, so it compiles the exact pre-change programs.
        if g.mixing not in ("sync", "async"):
            raise ValueError(
                f"unknown gossip mixing {g.mixing!r}; one of sync|async")
        self._async = g.mixing == "async"
        if self._async:
            if g.algorithm != "dsgd":
                raise ValueError(
                    "mixing='async' only applies to the single-sweep "
                    f"dsgd consensus, not {g.algorithm!r}: fedlcon's eps "
                    "sweeps and choco's compressed exchange have no "
                    "staleness-1 diag/off-diag split, and matching/"
                    "nocons have no static schedule to stale against")
            if robust_active:
                raise ValueError(
                    "mixing='async' does not compose with the robust "
                    "layer (corrupt faults / clip_radius / quarantine "
                    "screen the CURRENT round's sends; a stale mix has "
                    "no current wire to screen) — drop one of the two")
            if self._link_mode:
                raise ValueError(
                    "mixing='async' does not compose with link faults / "
                    "push-sum (the per-staleness [D+1, n, n] stack "
                    "already models delayed state; staleness-1 is its "
                    "D=1 special case) — drop one of the two")
            if g.update_sharding == "scatter":
                raise ValueError(
                    "mixing='async' does not compose with "
                    "update_sharding='scatter' (the bucketed partial "
                    "contractions assume one source tree; the async "
                    "diag/off-diag split reads two) — drop one of "
                    "the two")
            if cfg.population is not None:
                raise ValueError(
                    "mixing='async' does not compose with population "
                    "mode (a stale neighbor read would cross a cohort "
                    "rebinding — lane i's previous-round state belongs "
                    "to a different client) — drop one of the two")
        is_async = self._async
        # Round −1's state is defined as the shared init, so async
        # round 0 mixes exactly what sync round 0 mixes.  Built fresh
        # from the host tree: round_fn donates params, and the prev
        # buffer must never alias a donated input.
        self._async_prev: object = (
            shard_worker_tree(stacked, self.mesh) if self._async else {})

        # Fused mix+update epilogue (GossipConfig.fused_update): the
        # round's consensus contraction and the previous round's local
        # displacement land in ONE Pallas pass over the flat-bucket
        # UpdateShardSpec layout —  q_t = W_t·q_{t-1} − fbuf_{t-1}  with
        # fbuf_{t-1} = q_{t-1} − p'_{t-1}  carried engine state (the
        # D-PSGD update ordering, arXiv:1705.09056: the local step folds
        # in UNMIXED, so the trajectory is a documented variant of —
        # allclose to, not bit-equal with — the default mix(p')
        # ordering).  "off" (default) python-gates every use below and
        # compiles the exact pre-change programs.
        if g.fused_update not in ("off", "on"):
            raise ValueError(
                f"unknown fused_update {g.fused_update!r}; one of off|on")
        self._fused_on = g.fused_update == "on"
        if self._fused_on:
            if g.algorithm not in ("dsgd", "gossip"):
                raise ValueError(
                    "fused_update='on' fuses the single dense consensus "
                    f"sweep with the update; algorithm {g.algorithm!r} "
                    "has no such sweep to fuse (dsgd|gossip: fedlcon's "
                    "eps sweeps re-enter the matrix, choco exchanges "
                    "compressed deltas, nocons/centralized never mix)")
            if robust_active:
                raise ValueError(
                    "fused_update='on' does not compose with the robust "
                    "layer (corrupt faults / clip_radius / quarantine "
                    "screen the wire BEFORE mixing; the fused epilogue "
                    "contracts the carried state directly) — drop one "
                    "of the two")
            if self._link_mode:
                raise ValueError(
                    "fused_update='on' does not compose with link "
                    "faults / push-sum (the per-staleness [D+1, n, n] "
                    "contraction carries its own mass/staleness "
                    "buffers) — drop one of the two")
            if self._async:
                raise ValueError(
                    "fused_update='on' does not compose with "
                    "mixing='async' (the staleness-1 diag/off-diag "
                    "split reads two source trees; the fused "
                    "contraction reads one) — drop one of the two")
            if g.update_sharding == "scatter":
                raise ValueError(
                    "update_sharding='scatter' already restructures the "
                    "consensus/update hot path; fused_update='on' is "
                    "the single-device fusion of the same epilogue — "
                    "drop one of the two")
            if g.comm_dtype:
                raise ValueError(
                    "comm_dtype wire compression only applies to the "
                    "plain consensus collectives; the fused epilogue "
                    "contracts at f32 in one HBM pass — drop one of "
                    "the two")
            if g.comm_impl == "shift":
                raise ValueError(
                    "comm_impl='shift' is incompatible with "
                    "fused_update='on': the fused epilogue is one dense "
                    "[n, n] contraction, and the ppermute shift "
                    "decomposition has no single-pass fused form")
            if cfg.population is not None:
                raise ValueError(
                    "fused_update='on' does not compose with population "
                    "mode (the displacement buffer is lane state; a "
                    "per-round client rebinding would hand lane i's "
                    "displacement to a different client) — drop one of "
                    "the two")
            if self.mesh.size > 1:
                raise ValueError(
                    "fused_update='on' needs a single-device worker "
                    f"mesh (got {self.mesh.shape}): the Pallas epilogue "
                    "contracts the full worker axis in one kernel call; "
                    "multi-device meshes keep the dense or scatter "
                    "paths")
        fused_on = self._fused_on
        fused_spec = None
        fused_mix_update = None
        self._fused_spec = None
        # The displacement buffer: round −1's local step is defined as
        # zero, so fused round 0 contracts exactly what the default
        # round 0 mixes.  Built from fresh zeros — round_fn donates it,
        # and a donated input must never alias the init tree.
        self._fused_buf: object = {}
        if self._fused_on:
            from dopt.ops.fused_update import fused_mix_update

            self._fused_spec = make_update_shard_spec(
                stacked, fold=self.mesh.size,
                bucket_bytes=int(g.update_bucket_mb * (1 << 20)))
            self._fused_buf = shard_worker_tree(
                jax.tree.map(np.zeros_like, stacked), self.mesh)
            fused_spec = self._fused_spec

        def mix_once(x, arg):
            """One consensus sweep; ``arg`` is the [n, n] matrix (dense)
            or the [k, n] coefficient table (shift) for the round."""
            if scatter_spec is not None:
                return mix_update_scatter(x, arg, mesh, scatter_spec,
                                          shift_ids=shift_ids,
                                          comm_dtype=comm_dtype)
            if shift_ids is not None:
                return mix_shifts(x, shift_ids, arg, mesh, comm_dtype)
            return mix_dense(x, arg, mesh, comm_dtype)

        def codec_mix(params, cres, w_matrix, t):
            """One compressed consensus sweep over the flat buckets:
            per-bucket encode(v = x + e) → packed all-gather → local
            decode → mixing-row contraction (mix_codec_gather), with
            the quantization residual fed back next round.  Draws are a
            pure function of (round, bucket, global lane) — fold-in
            keyed, never split — so blocked, per-round, and resumed
            runs encode identical bits."""
            buckets = stacked_to_buckets(params, scatter_spec)
            key = jax.random.fold_in(comm_key, t)
            mixed, new_res = mix_codec_gather(buckets, list(cres),
                                              w_matrix, mesh, codec_plan,
                                              key)
            if not comm_ef:
                new_res = [jnp.zeros_like(r) for r in new_res]
            return buckets_to_stacked(mixed, scatter_spec), tuple(new_res)

        def mix_consensus(x, arg):
            """eps sweeps (FedLCon, with the stale-accumulation bug
            fixed: each sweep reads the previous sweep's output)."""
            if eps == 1:
                return mix_once(x, arg)

            def body(c, _):
                return mix_once(c, arg), None

            out, _ = jax.lax.scan(body, x, None, length=eps)
            return out

        def async_mix(params, prev, w_off, wdiag):
            """One staleness-1 consensus sweep: the self-term reads the
            CURRENT params, every neighbor term reads the PREVIOUS
            round's state.  ``w_off`` is the zero-diagonal mixing
            argument ([n, n] matrix or [k, n] shift-coefficient table)
            and ``wdiag`` the [n] diagonal weights, split host-side
            AFTER all matrix repairs so a departed lane degrades to
            diag=1 / off-diag=0 — a pure local step.  The off-diagonal
            contraction reuses the synchronous collective verbatim
            (dense or ppermute-shift); only its input tree is one round
            stale."""
            neighbors = mix_once(prev, w_off)

            def fold(p, nb):
                d = wdiag.astype(jnp.float32).reshape(
                    (-1,) + (1,) * (p.ndim - 1))
                return (d * p.astype(jnp.float32)
                        + nb.astype(jnp.float32)).astype(p.dtype)

            return jax.tree.map(fold, params, neighbors)

        if is_choco:
            from dopt.ops.compression import make_compressor

            compressor = make_compressor(g.compression, g.compression_ratio,
                                         qsgd_levels=g.qsgd_levels)
            real_compression = (g.compression == "qsgd"
                                or (g.compression in ("topk", "randk")
                                    and g.compression_ratio < 1.0))
            if g.choco_gamma >= 1.0 and real_compression:
                import warnings

                warnings.warn(
                    "choco_gamma >= 1 with a real compressor can diverge: "
                    "CHOCO-SGD theory scales γ down with the compressor's "
                    "contraction factor (try γ ≈ 0.1·compression_ratio)",
                    stacklevel=2)
            choco_gamma = g.choco_gamma
            choco_key = jax.random.key(cfg.seed ^ 0x0C0C0)

        def choco_mix(params, x_hat, w_matrix, alive, t):
            """One CHOCO-SGD gossip exchange (Koloskova et al. 2019).
            Communication object: q = Q(x_i − x̂_i) only (error feedback
            lives in the uncommunicated residual); every worker then
            advances the shared public-copy table and takes the
            consensus step  x_i += γ·((W x̂)_i − x̂_i)."""
            key = jax.random.fold_in(choco_key, t)
            diff = jax.tree.map(lambda a, b: a - b, params, x_hat)
            q = compressor(diff, key)
            if has_faults:
                # Dead workers send nothing: their public copy freezes.
                q = where_mask(alive, q, jax.tree.map(jnp.zeros_like, q))
            x_hat = jax.tree.map(lambda a, b: a + b, x_hat, q)
            mixed = mix_once(x_hat, w_matrix)
            new_p = jax.tree.map(
                lambda p, mx, xh: p + (choco_gamma * (mx - xh)).astype(p.dtype),
                params, mixed, x_hat)
            return new_p, x_hat

        def zeros_eval():
            z = jnp.zeros(self.num_workers)
            return {"acc": z, "loss_sum": z, "loss_mean": z, "count": z}

        def train_metrics(losses, accs, alive):
            """Mean over steps per worker, then over ALIVE workers only."""
            if not has_faults:
                return losses.mean(), accs.mean()
            denom = jnp.maximum(alive.sum(), 1.0)
            return ((losses.mean(axis=1) * alive).sum() / denom,
                    (accs.mean(axis=1) * alive).sum() / denom)

        diag_on = self._diag
        # [W] per-lane squared L2 over a lane-leading pytree — the same
        # f32-accumulated reduction the robust screen uses.
        _lane_sq = lane_sq_norms

        def round_diag(p_new, m_new, p_start, losses, alive):
            """[6] f32 per-round diagnostics (dopt.obs.events.DIAG_GAUGES
            + consensus_distance), computed ON DEVICE from the round's
            CARRIED state so per-round and blocked execution can never
            diverge: global L2 of the round's displacement
            ||p_new − p_start|| (dead lanes carry their state — zero
            displacement), of the carried momentum (the velocity — the
            smoothed-gradient meter), and of the carried params; the
            lane train-loss mean and max−min spread; and the true
            per-round consensus distance mean_i ||p_i − p̄||.

            All six reduce over the DIAGNOSABLE lanes: alive AND
            carrying finite state/loss.  A screened Byzantine liar
            keeps its poisoned params in its own lane (quarantine is
            the defense; the aggregation mask is the protection) — one
            NaN lane must not blind every fleet-health meter, so
            non-finite lanes drop out of the reductions.  The mask is
            computed from the same carried data on every execution
            path, so it is itself deterministic."""
            upd_sq = _lane_sq(jax.tree.map(
                lambda a, b: a.astype(jnp.float32) - b.astype(jnp.float32),
                p_new, p_start))
            m_sq = _lane_sq(m_new)
            p_sq = _lane_sq(p_new)
            lane = losses.mean(axis=1).astype(jnp.float32)
            ok = (alive * jnp.isfinite(upd_sq) * jnp.isfinite(m_sq)
                  * jnp.isfinite(p_sq) * jnp.isfinite(lane))
            denom = jnp.maximum(ok.sum(), 1.0)
            upd = jnp.sqrt((jnp.where(ok > 0, upd_sq, 0.0)).sum())
            gn = jnp.sqrt((jnp.where(ok > 0, m_sq, 0.0)).sum())
            pn = jnp.sqrt((jnp.where(ok > 0, p_sq, 0.0)).sum())
            lmean = (jnp.where(ok > 0, lane, 0.0)).sum() / denom
            lmax = jnp.where(ok > 0, lane, -jnp.inf).max()
            lmin = jnp.where(ok > 0, lane, jnp.inf).min()
            spread = jnp.where(ok.sum() > 0, lmax - lmin, 0.0)
            sq = None
            for x in jax.tree.leaves(p_new):
                xf = x.astype(jnp.float32)
                okx = ok.reshape((-1,) + (1,) * (xf.ndim - 1))
                xf0 = jnp.where(okx > 0, xf, 0.0)
                bar = xf0.sum(axis=0) / denom
                d = (xf0 - bar[None] * okx).reshape(xf.shape[0], -1)
                s = (d * d).sum(axis=1)
                sq = s if sq is None else sq + s
            cd = (jnp.where(ok > 0, jnp.sqrt(sq), 0.0)).sum() / denom
            return jnp.stack([upd, gn, pn, lmean, spread, cd])

        def local_phase(params, mom, idx, bweight, train_x, train_y,
                        vidx, vw, limits):
            """The per-round local-training phase: flat step scan on the
            full shard, or (holdout mode) the reference's epoch loop with
            per-epoch local-val eval.  Returns (p, m, losses, accs, em)
            where losses/accs are per-step [W, S] or per-epoch [W, E] —
            either way ``mean(axis=1)`` is the round's train metric —
            and em carries the per-epoch history arrays ({} when off).
            ``limits`` is the [W] straggler work budget, consumed only
            when the plan can straggle (ignored otherwise)."""
            if use_holdout:
                se = idx.shape[1] // local_ep_n
                idx_e = idx.reshape(idx.shape[0], local_ep_n, se, idx.shape[2])
                bw_e = bweight.reshape(idx_e.shape)
                if may_straggle:
                    p_t, m_t, em = local_epochs(params, mom, idx_e, bw_e,
                                                limits, train_x, train_y,
                                                vidx, vw)
                else:
                    p_t, m_t, em = local_epochs(params, mom, idx_e, bw_e,
                                                train_x, train_y, vidx, vw)
                return p_t, m_t, em["train_loss"], em["train_acc"], em
            bx = train_x[idx]
            by = train_y[idx]
            if may_straggle:
                p_t, m_t, losses, accs = local(params, mom, bx, by, bweight,
                                               limits)
            else:
                p_t, m_t, losses, accs = local(params, mom, bx, by, bweight)
            return p_t, m_t, losses, accs, {}

        def pack_host_metrics(tl, ta, evalm, em, screened, diag=None):
            """Everything the host reads per round, as ONE flat f32
            vector — on this hardware every device→host fetch pays a
            fixed ~100 ms tunnel round-trip, so the round's metrics
            (train loss/acc, fleet-mean eval, the robust layer's
            screened flags, and the per-epoch client-history block under
            the holdout) travel in a single transfer.  Layout (mirrored
            by ``_unpack_host_metrics``): [tl, ta, mean(acc),
            mean(loss_mean)] + [W] screened (robust runs only) +
            4×[W·E] em blocks."""
            parts = [tl[None], ta[None],
                     jnp.mean(evalm["acc"])[None],
                     jnp.mean(evalm["loss_mean"])[None]]
            if robust_active:
                parts.append(screened)
            if use_holdout:
                parts += [em["train_loss"].ravel(), em["train_acc"].ravel(),
                          em["val_acc"].ravel(),
                          em["val_loss_mean"].ravel()]
            if diag_on:
                # Diagnostics block travels LAST so every earlier
                # offset (_unpack_host_metrics) is layout-stable.
                parts.append(diag)
            return jnp.concatenate(
                [p.astype(jnp.float32) for p in parts])

        def consensus_phase(params, x_hat, w_matrix, alive, t, cmask,
                            prev=None, wdiag=None):
            """The round's consensus step, with the Byzantine sends
            injected and (when clip_tau > 0) clipped.  A liar corrupts
            only what it BROADCASTS (``x_send``) — its own carried state
            keeps training honestly, which is the Byzantine model: lies
            on the wire, not a crashed computation.  Returns (params,
            x_hat, [W] screened sender flags).

            Under ``mixing='async'`` (``prev`` is a traced tree, never
            None) the sweep is the staleness-1 split instead:
            ``w_matrix`` carries the off-diagonal argument, ``wdiag``
            the diagonal weights, and the neighbor terms read ``prev``
            — the previous round's entry state."""
            screened = jnp.zeros(w, jnp.float32)
            if prev is not None:
                return (async_mix(params, prev, w_matrix, wdiag), x_hat,
                        screened)
            if is_choco:
                params, x_hat = choco_mix(params, x_hat, w_matrix, alive, t)
                return params, x_hat, screened
            if not do_mix:
                return params, x_hat, screened
            if not robust_active:
                return mix_consensus(params, w_matrix), x_hat, screened
            x_send = (corrupt_update(params, cmask, corrupt_mode,
                                     corrupt_scale)
                      if has_corrupt else params)
            if clip_tau > 0:
                params, screened = clipped_gossip_mix(params, x_send,
                                                      w_matrix, clip_tau)
                # FedLCon's extra sweeps re-read honest current states
                # (the lie already entered — and was clipped — in sweep
                # one).
                for _ in range(eps - 1):
                    params, _ = clipped_gossip_mix(params, params,
                                                   w_matrix, clip_tau)
            else:
                # Undefended mixing of corrupted sends — the
                # plain-mean-diverges half of the threat model.
                # Self-terms read honest state (a liar poisons its
                # NEIGHBORS, not its own computation); FedLCon's extra
                # sweeps re-mix the already-absorbed result.
                screened = 1.0 - finite_lane_mask(x_send)
                params = byzantine_mix(params, x_send, w_matrix)
                for _ in range(eps - 1):
                    params = mix_once(params, w_matrix)
            return params, x_hat, screened

        def effective_inputs(w_matrix, alive, quar, cmask):
            """Fused-quarantine input adjustment, ON DEVICE (both
            execution paths run this, which is what makes them
            bit-identical): fold the quarantine mask into alive, mute
            quarantined liars, and repair the matrix for the combined
            dead set — skipping the repair division on all-alive
            rounds, mirroring the host path's ``alive.min() < 1``
            guard.  A no-op (python-level) without fused quarantine, so
            every other configuration compiles the pre-change
            program."""
            if not fused_quar:
                return w_matrix, alive, cmask
            from dopt.topology import repair_for_dropout_jnp

            alive = alive * (1.0 - quar)
            if has_corrupt:
                cmask = cmask * (1.0 - quar)
            rep = repair_for_dropout_jnp(w_matrix, alive)
            w_matrix = jnp.where(alive.min() >= 1.0, w_matrix, rep)
            return w_matrix, alive, cmask

        def quarantine_update(streak, until, scr, alive, t):
            """Post-round screen feedback as int32 device math — the
            exact jnp mirror of ``_apply_screen_feedback``: a screened
            round extends the streak (K in a row triggers the bench), a
            clean ALIVE round resets it."""
            flagged = scr > 0.5
            streak2 = jnp.where(flagged, streak + 1,
                                jnp.where(alive > 0, 0, streak))
            trigger = flagged & (streak2 >= q_after)
            until = jnp.where(trigger, t + 1 + q_rounds, until)
            streak = jnp.where(trigger, 0, streak2)
            return streak, until

        def round_fn(params, mom, x_hat, w_matrix, alive, limits, t, idx,
                     bweight, train_x, train_y, ex, ey, ew, vidx, vw,
                     do_eval, cmask=None, quar=None, prev=None,
                     wdiag=None, fbuf=None, cres=None):
            # Async: this round's ENTRY state is what the neighbors
            # read NEXT round — it becomes the new prev buffer.
            entry = params if prev is not None else None
            w_matrix, alive, cmask = effective_inputs(w_matrix, alive,
                                                      quar, cmask)
            if fused_on:
                # ONE HBM pass over the flat buckets:
                # q_t = W_t·q_{t-1} − fbuf_{t-1} (mix + pending local
                # displacement fused; ``params`` carries the POST-MIX
                # state q, the buffer its distance to the post-local
                # endpoint).
                params = fused_mix_update(params, fbuf, w_matrix,
                                          fused_spec, lr=1.0)
                screened = jnp.zeros(w, jnp.float32)
            elif codec_on:
                # Compressed wire: the codec replaces the round's one
                # consensus sweep (eps==1 — the validation pins it) and
                # threads the error-feedback residual carry.
                params, cres = codec_mix(params, cres, w_matrix, t)
                screened = jnp.zeros(w, jnp.float32)
            else:
                params, x_hat, screened = consensus_phase(
                    params, x_hat, w_matrix, alive, t, cmask, prev=prev,
                    wdiag=wdiag)
            evalm = jax.lax.cond(
                do_eval,
                lambda: evaluator(params, ex, ey, ew),
                zeros_eval,
            )
            p_t, m_t, losses, accs, em = local_phase(
                params, mom, idx, bweight, train_x, train_y, vidx, vw,
                limits)
            if has_faults:
                # Dead workers skip the local update (their lanes compute
                # and are discarded — static shapes).
                p_t = where_mask(alive, p_t, params)
                m_t = where_mask(alive, m_t, mom)
            tl, ta = train_metrics(losses, accs, alive)
            # ``params`` is the post-consensus state here, so the diag
            # update norm measures the local-training displacement.
            diag = (round_diag(p_t, m_t, params, losses, alive)
                    if diag_on else None)
            packed = pack_host_metrics(tl, ta, evalm, em, screened, diag)
            if fused_on:
                # Next round's contraction folds this displacement in.
                # Dead lanes carried q (p_t == params) → a zero row:
                # the lane freezes through the next repaired mix.
                new_fbuf = jax.tree.map(lambda a, b: a - b, params, p_t)
                return params, m_t, x_hat, new_fbuf, packed
            if codec_on:
                return p_t, m_t, x_hat, cres, packed
            if prev is not None:
                return p_t, m_t, x_hat, entry, packed
            return p_t, m_t, x_hat, packed

        # Donating the displacement/residual buffers (armed runs only —
        # the kwarg-name donation keeps the default path's jit params,
        # and therefore its fingerprinted programs, byte-identical)
        # lets XLA alias the new carry into the old carry's pages: the
        # round carry costs zero extra HBM over the plain path.
        _donate_names = (("fbuf",) if fused_on else ())
        _donate_names += (("cres",) if codec_on else ())
        _fused_donate = ({"donate_argnames": _donate_names}
                         if _donate_names else {})
        self._round_fn = jax.jit(round_fn, donate_argnums=(0, 1, 2),
                                 **_fused_donate)
        self._sharding = worker_sharding(self.mesh)

        # Fused multi-round block path (lax.scan over rounds in ONE jit).
        self._evaluator = evaluator
        self._do_mix, self._eps = do_mix, eps
        self._local_gather = make_stacked_local_update_gather(
            app_f, lr=cfg.optim.lr, momentum=cfg.optim.momentum,
            algorithm="sgd", l2=l2, update_impl=update_impl,
            gather_chunks=self._gather_chunks, stacked_apply=s_apply_f,
            clip_norm=cfg.optim.clip_norm, with_limit=may_straggle,
        )
        if s_apply_f is not None and self.mesh.size > 1:
            self._local_gather = shard_over_workers(
                self._local_gather, self.mesh,
                "wwwwwrr" if may_straggle else "wwwwrr", "w" * 4)
        local_g, ev = self._local_gather, self._evaluator

        def block_fn(params, mom, x_hat, w_mats, alive, limits, ts, idx, bw,
                     is_eval, train_x, train_y, ex, ey, ew, vidx, vw,
                     cmasks=None, streak=None, until=None, prev=None,
                     wdiags=None, fbuf=None, cres=None):
            """k rounds fused into one lax.scan dispatch (jit retraces per
            distinct k).  Each iteration is one full reference round with
            the SAME phase order as the per-round path — consensus →
            eval (on flagged rounds only) → local epochs — so history
            rows are directly comparable across block settings.  The
            minibatch gather happens inside the step scan from the
            resident train arrays; compile cost is O(1) in k.  Under
            corrupt faults the per-round corrupt masks ride the scan as
            one more stacked input; under fused quarantine the int32
            streak/until state rides the CARRY (readmission at round
            start, screen feedback after the round — the same order the
            per-round host loop applies), so quarantined runs fuse
            without surfacing flags to the host mid-block."""

            def body(carry, xs):
                pv = wd_t = fb = cr = None
                if fused_quar:
                    p, m, xh, stk, unt = carry
                elif is_async:
                    # Double-buffered staleness carry: pv is the
                    # previous round's entry state; this round's entry
                    # replaces it after the mix.
                    p, m, xh, pv = carry
                    stk = unt = None
                elif fused_on:
                    # Fused carry: p is the POST-MIX state q, fb the
                    # displacement to the post-local endpoint.
                    p, m, xh, fb = carry
                    stk = unt = None
                elif codec_on:
                    # Codec carry: cr is the per-bucket error-feedback
                    # residual the next round's encode folds back in.
                    p, m, xh, cr = carry
                    stk = unt = None
                else:
                    p, m, xh = carry
                    stk = unt = None
                if is_async:
                    (w_t, alive_t, lim_t, t_t, idx_t, bw_t, ev_t,
                     wd_t) = xs
                    cm_t = None
                elif has_corrupt:
                    w_t, alive_t, lim_t, t_t, idx_t, bw_t, ev_t, cm_t = xs
                else:
                    w_t, alive_t, lim_t, t_t, idx_t, bw_t, ev_t = xs
                    cm_t = None
                entry = p if is_async else None
                if fused_quar:
                    # Round-start readmission (mirrors _round_inputs):
                    # an expired sentence clears the bench + streak.
                    expired = (unt != 0) & (t_t >= unt)
                    unt = jnp.where(expired, 0, unt)
                    stk = jnp.where(expired, 0, stk)
                    quar_t = (unt > t_t).astype(jnp.float32)
                    w_t, alive_t, cm_t = effective_inputs(w_t, alive_t,
                                                          quar_t, cm_t)
                if fused_on:
                    p = fused_mix_update(p, fb, w_t, fused_spec, lr=1.0)
                    scr = jnp.zeros(w, jnp.float32)
                elif codec_on:
                    p, cr = codec_mix(p, cr, w_t, t_t)
                    scr = jnp.zeros(w, jnp.float32)
                else:
                    p, xh, scr = consensus_phase(p, xh, w_t, alive_t, t_t,
                                                 cm_t, prev=pv, wdiag=wd_t)
                evalm = jax.lax.cond(ev_t, lambda: ev(p, ex, ey, ew), zeros_eval)
                if use_holdout:
                    p_t, m_t, losses, accs, em = local_phase(
                        p, m, idx_t, bw_t, train_x, train_y, vidx, vw, lim_t)
                elif may_straggle:
                    p_t, m_t, losses, accs = local_g(p, m, idx_t, bw_t, lim_t,
                                                     train_x, train_y)
                    em = {}
                else:
                    p_t, m_t, losses, accs = local_g(p, m, idx_t, bw_t,
                                                     train_x, train_y)
                    em = {}
                if has_faults:
                    p_t = where_mask(alive_t, p_t, p)
                    m_t = where_mask(alive_t, m_t, m)
                tl, ta = train_metrics(losses, accs, alive_t)
                diag = (round_diag(p_t, m_t, p, losses, alive_t)
                        if diag_on else None)
                packed = pack_host_metrics(tl, ta, evalm, em, scr, diag)
                if fused_quar:
                    stk, unt = quarantine_update(stk, unt, scr, alive_t,
                                                 t_t)
                    return (p_t, m_t, xh, stk, unt), packed
                if is_async:
                    return (p_t, m_t, xh, entry), packed
                if fused_on:
                    new_fb = jax.tree.map(lambda a, b: a - b, p, p_t)
                    return (p, m_t, xh, new_fb), packed
                if codec_on:
                    return (p_t, m_t, xh, cr), packed
                return (p_t, m_t, xh), packed

            xs = [w_mats, alive, limits, ts, idx, bw, is_eval]
            if has_corrupt:
                xs.append(cmasks)
            if is_async:
                xs.append(wdiags)
            if fused_quar:
                carry0 = (params, mom, x_hat, streak, until)
            elif is_async:
                carry0 = (params, mom, x_hat, prev)
            elif fused_on:
                carry0 = (params, mom, x_hat, fbuf)
            elif codec_on:
                carry0 = (params, mom, x_hat, cres)
            else:
                carry0 = (params, mom, x_hat)
            carry, packed = jax.lax.scan(body, carry0, tuple(xs))
            if fused_quar:
                return (*carry, packed)
            if is_async:
                params, mom, x_hat, prev = carry
                return params, mom, x_hat, prev, packed
            if fused_on:
                params, mom, x_hat, fbuf = carry
                return params, mom, x_hat, fbuf, packed
            if codec_on:
                params, mom, x_hat, cres = carry
                return params, mom, x_hat, cres, packed
            params, mom, x_hat = carry
            return params, mom, x_hat, packed

        self._block_fn = jax.jit(block_fn, donate_argnums=(0, 1, 2),
                                 **_fused_donate)

        # ---- lossy-link / push-sum consensus path ---------------------
        # Engine state: `_mass` is the push-sum mass vector (ones —
        # exactly 1.0 forever under a doubly-stochastic fault-free
        # schedule); `_link_buf` is the bounded staleness buffer, [D, W,
        # ...] per leaf — under correction='none' it holds the fleet's
        # last D broadcast snapshots (a delayed edge mixes against one),
        # under push-sum the IN-FLIGHT packets (value mass en route,
        # slot d arrives in d+1 rounds) with `_link_buf_mass` the
        # matching scalar mass — so node mass + in-flight mass is
        # conserved at exactly n every round, the invariant
        # tests/test_network.py pins.  All of it is checkpointed;
        # link-mode runs execute per-round (the stack of per-staleness
        # matrices is host data per round).
        self._mass: object = {}
        self._link_buf: object = {}
        self._link_buf_mass: object = {}
        if self._link_mode:
            D = self._delay_max
            buf_sharding = jax.sharding.NamedSharding(
                self.mesh,
                jax.sharding.PartitionSpec(None, worker_axes(self.mesh)))
            if self._push_sum:
                self._mass = jax.device_put(np.ones(w, np.float32))
                if D > 0:
                    self._link_buf = jax.device_put(
                        jax.tree.map(
                            lambda x: np.zeros((D,) + x.shape, x.dtype),
                            stacked), buf_sharding)
                    self._link_buf_mass = jax.device_put(
                        np.zeros((D, w), np.float32))
            elif D > 0:
                # History snapshots: every slot starts at the common
                # init (what each worker would have broadcast before
                # round 0), so early-round staleness is well defined
                # and a resumed run reloads the exact carried history.
                self._link_buf = jax.device_put(
                    jax.tree.map(
                        lambda x: np.broadcast_to(
                            x[None], (D,) + x.shape).copy(), stacked),
                    buf_sharding)

            push_sum, D_link = self._push_sum, self._delay_max
            num_w = w

            def _tree_add(a, b):
                return jax.tree.map(jnp.add, a, b)

            def link_round_core(params, mom, mass, buf, buf_mass, mats,
                                alive, limits, t, idx, bweight, train_x,
                                train_y, ex, ey, ew, vidx, vw, do_eval,
                                cmask=None):
                """One round through the lossy-link consensus: ``mats``
                is the [D+1, n, n] per-staleness stack for the round
                (slot 0 immediate; row-stochastic overall for
                correction='none', column-stochastic overall for
                push-sum).  Under push-sum ``params`` carries the
                NUMERATOR x; the de-biased estimate z = x/mass is what
                trains and evaluates, and z·mass is carried back."""
                x_send = (corrupt_update(params, cmask, corrupt_mode,
                                         corrupt_scale)
                          if has_corrupt else params)
                new_buf, new_buf_mass = buf, buf_mass
                if push_sum:
                    now_x = mix_dense(x_send, mats[0], mesh)
                    now_m = jnp.tensordot(mats[0], mass, axes=[[1], [0]])
                    if D_link > 0:
                        now_x = _tree_add(
                            now_x, jax.tree.map(lambda b: b[0], buf))
                        now_m = now_m + buf_mass[0]
                        arr = [mix_dense(x_send, mats[d], mesh)
                               for d in range(1, D_link + 1)]
                        arr_m = jnp.stack(
                            [jnp.tensordot(mats[d], mass, axes=[[1], [0]])
                             for d in range(1, D_link + 1)])

                        def slot_upd(b, *sends):
                            shifted = jnp.concatenate(
                                [b[1:], jnp.zeros_like(b[:1])], axis=0)
                            return shifted + jnp.stack(sends, axis=0)

                        new_buf = jax.tree.map(slot_upd, buf, *arr)
                        new_buf_mass = jnp.concatenate(
                            [buf_mass[1:], jnp.zeros_like(buf_mass[:1])],
                            axis=0) + arr_m
                    safe_m = jnp.maximum(now_m, 1e-12)

                    def debias(xl):
                        mm = safe_m.reshape(
                            (-1,) + (1,) * (xl.ndim - 1))
                        return (xl.astype(jnp.float32)
                                / mm).astype(xl.dtype)

                    mixed = jax.tree.map(debias, now_x)
                    mass_out = now_m
                else:
                    mixed = mix_dense(x_send, mats[0], mesh)
                    if D_link > 0:
                        for d in range(1, D_link + 1):
                            snap = jax.tree.map(lambda b, _d=d: b[_d - 1],
                                                buf)
                            mixed = _tree_add(
                                mixed, mix_dense(snap, mats[d], mesh))
                        new_buf = jax.tree.map(
                            lambda b, s: jnp.concatenate(
                                [s[None], b[:-1]], axis=0),
                            buf, x_send)
                    mass_out = mass
                screened = jnp.zeros(num_w, jnp.float32)
                evalm = jax.lax.cond(
                    do_eval, lambda: evaluator(mixed, ex, ey, ew),
                    zeros_eval)
                p_t, m_t, losses, accs, em = local_phase(
                    mixed, mom, idx, bweight, train_x, train_y, vidx, vw,
                    limits)
                if has_faults:
                    p_t = where_mask(alive, p_t, mixed)
                    m_t = where_mask(alive, m_t, mom)
                tl, ta = train_metrics(losses, accs, alive)
                # Diagnostics on the DE-BIASED estimates (pre-rebias):
                # under push-sum the carried numerators scale with mass,
                # and z = x/mass is the quantity that converges — the
                # same convention the end-of-run consensus gauge uses.
                diag = (round_diag(p_t, m_t, mixed, losses, alive)
                        if diag_on else None)
                if push_sum:
                    def rebias(zl):
                        mm = mass_out.reshape(
                            (-1,) + (1,) * (zl.ndim - 1))
                        return (zl.astype(jnp.float32)
                                * mm).astype(zl.dtype)

                    p_t = jax.tree.map(rebias, p_t)
                return (p_t, m_t, mass_out, new_buf, new_buf_mass,
                        pack_host_metrics(tl, ta, evalm, em, screened,
                                          diag))

            self._link_round_fn = jax.jit(link_round_core,
                                          donate_argnums=(0, 1, 2, 3, 4))

            def link_block_fn(params, mom, mass, buf, buf_mass, mats,
                              alive, limits, ts, idx, bw, is_eval,
                              train_x, train_y, ex, ey, ew, vidx, vw,
                              cmasks=None):
                """k lossy-link rounds fused into one lax.scan: the
                push-sum mass + in-flight/staleness buffers (engine
                state) ride the CARRY, and the per-round [D+1, n, n]
                per-staleness matrix stacks ride the scan as one more
                stacked input ([k, D+1, n, n]) — exactly like the
                corrupt masks.  The body IS ``link_round_core``, so the
                per-round and blocked programs can never diverge."""

                def body(carry, xs):
                    p, m, ms, bf, bm = carry
                    if has_corrupt:
                        (mats_t, alive_t, lim_t, t_t, idx_t, bw_t, ev_t,
                         cm_t) = xs
                    else:
                        mats_t, alive_t, lim_t, t_t, idx_t, bw_t, ev_t = xs
                        cm_t = None
                    p, m, ms, bf, bm, packed = link_round_core(
                        p, m, ms, bf, bm, mats_t, alive_t, lim_t, t_t,
                        idx_t, bw_t, train_x, train_y, ex, ey, ew, vidx,
                        vw, ev_t, cm_t)
                    return (p, m, ms, bf, bm), packed

                xs = [mats, alive, limits, ts, idx, bw, is_eval]
                if has_corrupt:
                    xs.append(cmasks)
                (params, mom, mass, buf, buf_mass), packed = jax.lax.scan(
                    body, (params, mom, mass, buf, buf_mass), tuple(xs))
                return params, mom, mass, buf, buf_mass, packed

            self._link_block_fn = jax.jit(link_block_fn,
                                          donate_argnums=(0, 1, 2, 3, 4))

    # -- blocked staging: the stateful draw vs the pure build ----------
    def _draw_block(self, ts: list) -> dict:
        """The STATEFUL half of one block's host staging: the per-round
        fault/matrix/quarantine inputs.  Always runs on the main thread
        in block order — the 'gossip' matching-matrix RNG and the
        link-mode quarantine-expiry mutations must advance at exactly
        the sequence positions the unprefetched loop consumes them at
        (dopt.data.prefetch ordering contract)."""
        if self._fused_quar:
            statics = [self._round_inputs_static(t) for t in ts]
            return {"ts": ts,
                    "w_raws": [s[0] for s in statics],
                    "w_mats": np.stack([s[1] for s in statics]),
                    "alive": np.stack([s[2] for s in statics]),
                    "limits": np.stack([s[3] for s in statics]),
                    "cmasks": (np.stack([s[4] for s in statics])
                               if self._has_corrupt else None),
                    "frows": None}
        pairs = [self._round_inputs(t) for t in ts]
        meta = {"ts": ts,
                "w_raws": None,
                "w_mats": np.stack([(p[0][0] if self._async else p[0])
                                    for p in pairs]),
                "alive": np.stack([p[1] for p in pairs]),
                "limits": np.stack([p[2] for p in pairs]),
                "cmasks": (np.stack([p[3] for p in pairs])
                           if self._has_corrupt else None),
                "frows": [p[4] for p in pairs]}
        if self._async:
            meta["wdiags"] = np.stack([p[0][1] for p in pairs])
        return meta

    def _build_block(self, meta: dict) -> dict:
        """The PURE half of one block's host staging: the batch plans
        (the expensive O(W·S·B) host work) and their device staging.
        Touches no trainer state beyond stateless reads, so the
        prefetch stager may run it on its background thread."""
        ts = meta["ts"]
        block_sharding = jax.sharding.NamedSharding(
            self.mesh,
            jax.sharding.PartitionSpec(None, worker_axes(self.mesh)))
        plans = [self._round_plan(t) for t in ts]
        meta["idx"] = jax.device_put(np.stack([p.idx for p in plans]),
                                     block_sharding)
        meta["bw"] = jax.device_put(np.stack([p.weight for p in plans]),
                                    block_sharding)
        meta["is_eval"] = np.asarray(
            [(t % self.eval_every) == 0 for t in ts], dtype=bool)
        return meta

    def _stage_block(self, stager: PrefetchStager, ts: list) -> None:
        """Draw block ``ts``'s inputs now (main thread, in order) and
        hand the pure build to the stager's background thread."""
        with self.timers.phase("host_batch_plan"):
            meta = self._draw_block(ts)
        stager.stage(ts[0], timed_build(self._build_block, self.timers),
                     meta)

    def _run_blocked(self, rounds: int, block: int,
                     checkpoint_every: int = 0,
                     checkpoint_path=None) -> History:
        """Run ``rounds`` rounds in fused blocks of up to ``block``.
        Periodic auto-checkpoints land at block boundaries (the state
        only exists on the host there).

        EVERY gossip mode is blocked-eligible: clean/faulted runs fuse
        as before; link-mode runs (msg_drop/msg_delay/push-sum) scan
        with the mass + staleness buffers as carry and the per-round
        [D+1, n, n] matrix stacks as stacked inputs; fused-quarantine
        runs carry the streak/until state on device and the host
        REPLAYS the per-round ledger logic post-fetch (same rows, same
        order — the screened flags it needs only exist after the block
        lands).

        With ``prefetch='on'`` the loop runs dispatch → stage-next →
        fetch: block b's dispatch is asynchronous, block b+1's plans
        are drawn (main thread, in order) and built/staged (background
        thread) while b runs on device, and the fetch barrier lands
        after staging started.  Staging never crosses a scheduled
        checkpoint boundary — the block after a checkpoint builds
        inline from the committed state — so checkpoints capture
        exactly the committed rounds and resume stays bit-exact.
        ``prefetch='off'`` runs the exact pre-change host loop."""
        link = self._link_mode
        fused_quar = self._fused_quar
        t0 = time.time()  # dopt: allow-wallclock -- total_time wall meter, reporting only
        next_ckpt = (self.round // checkpoint_every + 1) * checkpoint_every \
            if checkpoint_every else None
        stager = PrefetchStager() if self._prefetch else None
        try:
            self._blocked_loop(rounds, block, next_ckpt, checkpoint_every,
                               checkpoint_path, stager, link, fused_quar)
        finally:
            if stager is not None:
                stager.discard()
        self.total_time = time.time() - t0  # dopt: allow-wallclock -- total_time wall meter, reporting only
        self._run_summary_telemetry()
        return self.history

    def _blocked_loop(self, rounds, block, next_ckpt, checkpoint_every,
                      checkpoint_path, stager, link, fused_quar) -> None:
        done = 0
        while done < rounds:
            k = min(block, rounds - done)
            ts = [self.round + j for j in range(k)]
            payload = stager.take(ts[0]) if stager is not None else None
            if payload is None:
                with self.timers.phase("host_batch_plan"):
                    payload = self._build_block(self._draw_block(ts))
            w_raws, frows = payload["w_raws"], payload["frows"]
            alive, is_eval = payload["alive"], payload["is_eval"]
            step_kw = ({"cmasks": jnp.asarray(payload["cmasks"])}
                       if self._has_corrupt else {})
            common = (payload["w_mats"], alive, payload["limits"],
                      jnp.asarray(ts, jnp.int32), payload["idx"],
                      payload["bw"], jnp.asarray(is_eval), self._train_x,
                      self._train_y, *self._eval, *self._val)
            if link:
                fn = self._link_block_fn
                args = (self.params, self.momentum, self._mass,
                        self._link_buf, self._link_buf_mass, *common)
            elif fused_quar:
                step_kw.update(
                    streak=jnp.asarray(
                        self._screen_streak.astype(np.int32)),
                    until=jnp.asarray(
                        self._quarantine_until.astype(np.int32)))
                fn = self._block_fn
                args = (self.params, self.momentum, self.x_hat, *common)
            else:
                if self._async:
                    step_kw.update(prev=self._async_prev,
                                   wdiags=jnp.asarray(payload["wdiags"]))
                if self._fused_on:
                    step_kw["fbuf"] = self._fused_buf
                if self._codec_on:
                    step_kw["cres"] = self._comm_res
                fn = self._block_fn
                args = (self.params, self.momentum, self.x_hat, *common)
            if stager is None:
                out = self.timers.measure("round_step", fn, *args,
                                          **step_kw)
            else:
                # dispatch → stage-next → fetch: the jit dispatch
                # returns before the device finishes, the next block's
                # staging overlaps this block's device time, and
                # block_until_ready is the fetch barrier the old
                # measure() call provided.
                with self.timers.phase("round_step"):
                    out = fn(*args, **step_kw)
                    end_round = ts[-1] + 1
                    remaining = rounds - (done + k)
                    if remaining > 0 and (next_ckpt is None
                                          or end_round < next_ckpt):
                        nk = min(block, remaining)
                        self._stage_block(
                            stager, [end_round + j for j in range(nk)])
                    jax.block_until_ready(out)
            dev_streak = dev_until = None
            if link:
                (self.params, self.momentum, self._mass, self._link_buf,
                 self._link_buf_mass, packed) = out
            elif fused_quar:
                (self.params, self.momentum, self.x_hat, dev_streak,
                 dev_until, packed) = out
            elif self._async:
                (self.params, self.momentum, self.x_hat,
                 self._async_prev, packed) = out
            elif self._fused_on:
                (self.params, self.momentum, self.x_hat,
                 self._fused_buf, packed) = out
            elif self._codec_on:
                (self.params, self.momentum, self.x_hat,
                 self._comm_res, packed) = out
            else:
                (self.params, self.momentum, self.x_hat, packed) = out
            packed = np.asarray(packed)  # ONE device→host fetch per block
            for j, t in enumerate(ts):
                tl, ta, acc, lm, scr, em, diag = self._unpack_host_metrics(
                    packed[j])
                if fused_quar:
                    # Post-fetch ledger replay: host state is now
                    # current through round t-1's flags, so this
                    # regenerates exactly the per-round path's rows
                    # (and host-mirror mutations) for round t.
                    (_w, alive_j, _lim, _cm, rows_j,
                     quar_j) = self._round_inputs(t, w_raw=w_raws[j])
                    alive_eff = alive_j * (1.0 - quar_j)
                    self._apply_screen_feedback(t, alive_eff, scr, rows_j)
                    self.history.faults.extend(rows_j)
                else:
                    if self._robust_active:
                        self._apply_screen_feedback(t, alive[j], scr,
                                                    frows[j])
                    self.history.faults.extend(frows[j])
                row = {
                    "round": t,
                    "avg_train_loss": tl,
                    "avg_train_acc": ta,
                }
                if is_eval[j]:
                    row["avg_test_acc"] = acc
                    row["avg_test_loss"] = lm
                self.history.append(**row)
                if self._holdout:
                    self._append_client_rows(t, em)
                self._round_telemetry(t, rows_j if fused_quar else frows[j],
                                      diag)
                self.round += 1
            if fused_quar:
                # The host replay and the device carry apply the same
                # integer rule to the same flags — drift here means a
                # real bug, caught loudly rather than as silent trace
                # divergence.
                if not (np.array_equal(np.asarray(dev_streak),
                                       self._screen_streak.astype(np.int32))
                        and np.array_equal(
                            np.asarray(dev_until),
                            self._quarantine_until.astype(np.int32))):
                    raise RuntimeError(
                        "fused-quarantine host replay diverged from the "
                        "device scan carry")
            self._device_telemetry(
                ts[-1], "link_block_fn" if link else "block_fn", fn)
            done += k
            if next_ckpt is not None and self.round >= next_ckpt:
                self.save(checkpoint_path)
                next_ckpt = (self.round // checkpoint_every + 1) \
                    * checkpoint_every

    # ------------------------------------------------------------------
    def _unpack_host_metrics(self, vec: np.ndarray):
        """Inverse of the round step's ``pack_host_metrics``: one fetched
        f32 vector → (train_loss, train_acc, mean_test_acc,
        mean_test_loss, [W] screened flags (robust runs; else None), em
        dict of [W, E] arrays or {}, [6] diagnostics block
        (diagnostics runs; else None))."""
        tl, ta, acc, lm = (float(vec[0]), float(vec[1]), float(vec[2]),
                           float(vec[3]))
        off = 4
        scr = None
        if self._robust_active:
            scr = vec[off:off + self.num_workers]
            off += self.num_workers
        em: dict[str, np.ndarray] = {}
        if self._holdout:
            w, e = self.num_workers, self.cfg.gossip.local_ep
            n = w * e
            body = vec[off:]
            for i, k in enumerate(("train_loss", "train_acc", "val_acc",
                                   "val_loss")):
                em[k] = body[i * n:(i + 1) * n].reshape(w, e)
        diag = vec[-len(self._diag_keys):] if self._diag else None
        return tl, ta, acc, lm, scr, em, diag

    def _append_client_rows(self, t: int, em: dict) -> None:
        """Per-epoch per-worker history rows (P2 Client.history schema,
        clients.py:52-57: {iter, train_loss, train_acc, val_acc,
        val_loss} with val_loss in P2's mean-per-batch flavour), one row
        per (worker, epoch)."""
        tl, ta = em["train_loss"], em["train_acc"]
        va, vl = em["val_acc"], em["val_loss"]
        for i in range(self.num_workers):
            for e in range(tl.shape[1]):
                self.client_history.append(
                    round=t, iter=e, worker=i,
                    train_loss=float(tl[i, e]), train_acc=float(ta[i, e]),
                    val_acc=float(va[i, e]), val_loss=float(vl[i, e]),
                )

    # -- telemetry (dopt.obs) ------------------------------------------
    def _round_telemetry(self, t: int, frows: list, diag=None) -> None:
        """Emit round t's telemetry bundle: the fault-ledger rows as
        typed events, the history row just appended as the ``round``
        event, and the host-mirror state (quarantine streaks, the
        population registry) plus the fetched on-device diagnostics
        block (``diagnostics="on"``) as ``gauge`` events.  Derived only
        from post-fetch host-replay data at the identical point of the
        per-round and blocked loops, so the streams are bit-identical
        across execution paths; ``telemetry=None`` skips it."""
        tele = self.telemetry
        if tele is None:
            return
        quarantined = int((self._quarantine_until > t).sum())
        gauges = {
            "quarantine_active": float(quarantined),
            "screen_streak_max": float(self._screen_streak.max()),
            # Denominator gauge for the monitor's fleet-fraction rules
            # (dopt.obs.rules): lanes eligible to contribute this round.
            "participating_lanes": float(self.num_workers - quarantined),
        }
        if diag is not None:
            from dopt.obs.events import finite_diag_gauges

            gauges.update(finite_diag_gauges(self._diag_keys, diag))
        if self._registry is not None:
            reg = self._registry
            gauges["cohort_size"] = float(reg.cohort_size)
            # Denominator for the monitor's client-keyed quarantine
            # storm (population_quarantined / population_size).
            gauges["population_size"] = float(reg.clients)
            gauges["population_quarantined"] = float(
                (reg.quarantine_until > t).sum())
            gauges["population_sampled_total"] = float(
                (reg.participation > 0).sum())
        tele.emit_round_bundle(t, engine=self.engine_kind,
                               metrics=self.history.rows[-1],
                               faults=frows, gauges=gauges)

    def _device_telemetry(self, t: int, fn_name: str, fn) -> None:
        """Non-deterministic resource/compile channel — shared impl in
        ``dopt.utils.profiling.emit_device_resource``."""
        from dopt.utils.profiling import emit_device_resource

        emit_device_resource(self, t, fn_name, fn)

    def _consensus_value(self) -> float | None:
        """Mean over workers of ‖xᵢ − x̄‖₂ on the de-biased estimates
        (push-sum runs measure the ratio estimates — the quantity that
        actually converges), or None when there is nothing to report
        (round 0, or a diverged fleet)."""
        if self.round == 0:
            return None
        if jax.process_count() > 1:
            # Multi-process fleet: the reduction below is a COLLECTIVE
            # over cross-process-sharded params, but only the telemetry
            #-attached leader reaches this call site — computing it
            # would strand the leader in a collective the followers
            # never join.  Fleets report consensus via diagnostics="on"
            # (inside the compiled round, all processes) instead.
            return None
        import math

        from dopt.obs import consensus_distance

        cd = consensus_distance(self._debiased_params())
        return cd if math.isfinite(cd) else None

    def _run_summary_telemetry(self) -> None:
        """End-of-``run()`` consensus-distance gauge — one fetch per
        run() call; identical across execution paths for an identical
        call pattern.  Suppressed under ``diagnostics="on"``: the diag
        block already carries a TRUE per-round consensus distance in
        every round bundle (watermark-suppressed on resume), and the
        end-of-run gauge is per-``run()``-CALL state — a killed-and-
        resumed run would emit an extra one mid-stream, breaking the
        gauges-included canonical equality diagnostics guarantees."""
        tele = self.telemetry
        if tele is None or self._diag or self._suppress_run_summary:
            return
        cd = self._consensus_value()
        if cd is not None:
            tele.emit("gauge", round=self.round - 1,
                      name="consensus_distance", value=cd,
                      engine=self.engine_kind)

    def _matrix_for_round(self, t: int) -> np.ndarray:
        g = self.cfg.gossip
        if g.algorithm == "gossip":
            return random_matching_matrix(self.num_workers, self._matching_rng)
        if self.mixing is not None:
            return self.mixing.for_round(t)
        return np.eye(self.num_workers)

    def _round_inputs_static(self, t: int):
        """Quarantine-INDEPENDENT per-round inputs for the fused-
        quarantine blocked path: (raw matrix draw, partition-cut f32
        matrix, alive mask from crash/churn only, straggler limits,
        raw corrupt mask).  Draws the round's matrix — the only
        stateful draw — and touches NO quarantine state and emits NO
        ledger rows; the blocked loop replays ``_round_inputs(t,
        w_raw=...)`` post-fetch for the rows + host-mirror updates,
        once the block's screened flags are back."""
        w_raw = self._matrix_for_round(t)
        rf = self.faults.for_round(t)
        alive = (~rf.crashed).astype(np.float32)
        if self.faults.has_churn:
            away = self.faults.away_for_round(t)
            alive = alive * (~away).astype(np.float32)
        limits = FaultPlan.limits_for(rf, self._straggle_units)
        w_t = w_raw
        if rf.partition is not None:
            w_t = repair_for_partition(w_t, rf.partition)
        cmask = np.zeros(self.num_workers, np.float32)
        if self._has_corrupt and rf.corrupt is not None:
            cmask = (rf.corrupt & (alive > 0)).astype(np.float32)
        return w_raw, w_t.astype(np.float32), alive, limits, cmask

    def _round_inputs(
            self, t: int, w_raw: np.ndarray | None = None
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, list,
               np.ndarray]:
        """(mixing argument, alive mask, straggler limits, corrupt mask,
        ledger rows, quarantine mask) for round t, with the matrix
        repaired for any failed or quarantined workers.

        The mixing argument is the [n, n] matrix on the dense path or
        its [k, n] circulant coefficient table on the shift/ppermute
        path (same math: ``coeffs_for_matrix`` raises if the matrix
        ever leaves the compiled shift set, so the two paths can never
        silently diverge).  Faults are drawn statelessly per round
        (dopt.faults.FaultPlan) and ledger rows are RETURNED (not
        appended) so both execution paths interleave them with the
        device-side screened rows in the identical order — per-round,
        blocked, and killed-and-resumed execution log the same trace.

        Under FUSED quarantine (dense robust path) the contract shifts:
        the returned matrix is NOT dropout-repaired and ``alive``
        excludes crash/churn only — the device folds the quarantine
        mask in and repairs (``effective_inputs``), identically on the
        per-round and blocked paths.  ``w_raw`` lets the blocked replay
        reuse the plan-time matrix draw (the matching RNG is stateful).
        """
        rows: list[dict] = []
        w_t = self._matrix_for_round(t) if w_raw is None else w_raw
        rf = self.faults.for_round(t)
        alive = (~rf.crashed).astype(np.float32)
        away = self.faults.away_for_round(t)
        if self.faults.has_churn:
            rows.extend(churn_ledger_rows(self.faults, t, away))
            alive = alive * (~away).astype(np.float32)
        quar = np.zeros(self.num_workers, np.float32)
        if self._quarantine_on:
            expired = ((self._quarantine_until != 0)
                       & (t >= self._quarantine_until))
            for i in np.nonzero(expired)[0]:
                rows.append({"round": int(t), "worker": int(i),
                             "kind": "quarantine", "action": "readmitted"})
                self._quarantine_until[i] = 0
                self._screen_streak[i] = 0
            quarantined = self._quarantine_until > t
            quar = quarantined.astype(np.float32)
            if quarantined.any() and not self._fused_quar:
                # Quarantine rides the existing alive machinery: the
                # matrix is repaired around the worker (neighbors stop
                # listening) and its lane freezes for the span.  On the
                # fused path this fold happens ON DEVICE instead.
                alive = alive * (~quarantined).astype(np.float32)
        units = self._straggle_units
        limits = FaultPlan.limits_for(rf, units)
        if rf.partition is not None:
            # Cut cross-group edges FIRST, then repair for crashes: a
            # crashed worker is down regardless of which side it is on.
            w_t = repair_for_partition(w_t, rf.partition)
            for i, gid in enumerate(rf.partition):
                rows.append({"round": int(t), "worker": int(i),
                             "kind": "partition",
                             "action": f"cut_to_group_{int(gid)}"})
        if alive.min() < 1.0 and not self._fused_quar:
            w_t = repair_for_dropout(w_t, alive)
        for i in np.nonzero(rf.crashed)[0]:
            rows.append({"round": int(t), "worker": int(i), "kind": "crash",
                         "action": "skipped_round"})
        for i in np.nonzero(rf.straggler)[0]:
            rows.append({"round": int(t), "worker": int(i),
                         "kind": "straggler",
                         "action": f"truncated_to_{int(limits[i])}_of_{units}"})
        cmask = np.zeros(self.num_workers, np.float32)
        if self._has_corrupt and rf.corrupt is not None:
            # A down (or quarantined) worker sends nothing to corrupt.
            # Fused path: the returned cmask keeps quarantined liars
            # (the device mutes them), the LEDGER excludes them — same
            # effective set either way.
            liars = rf.corrupt & (alive > 0)
            cmask = liars.astype(np.float32)
            row_liars = liars & (quar <= 0) if self._fused_quar else liars
            mode = self.cfg.faults.corrupt_mode
            for i in np.nonzero(row_liars)[0]:
                rows.append({"round": int(t), "worker": int(i),
                             "kind": "corrupt",
                             "action": f"injected_{mode}"})
        if self._link_mode:
            # Per-edge link faults + the per-staleness matrix stack.
            # Drops/delays apply to the surviving off-diagonal edges of
            # the (crash/partition/churn-)repaired matrix; push-sum gets
            # the mass-conserving column-stochastic effective matrix,
            # plain gossip the row-renormalised (biased) one.
            from dopt.topology import (push_sum_link_matrix,
                                       repair_for_link_drop,
                                       split_by_delay)

            keep, delay = self.faults.link_for_round(t)
            if self._has_link:
                edges = (w_t * (1.0 - np.eye(self.num_workers))) > 0.0
                for i, j in zip(*np.nonzero(edges & ~keep)):
                    rows.append({"round": int(t), "worker": int(i),
                                 "kind": "msg_drop",
                                 "action": f"dropped_from_{int(j)}"})
                for i, j in zip(*np.nonzero(edges & keep & (delay > 0))):
                    rows.append({
                        "round": int(t), "worker": int(i),
                        "kind": "msg_delay",
                        "action": f"delayed_from_{int(j)}_by_"
                                  f"{int(delay[i, j])}"})
            m_eff = (push_sum_link_matrix(w_t, keep) if self._push_sum
                     else repair_for_link_drop(w_t, keep))
            mats = split_by_delay(m_eff, delay, self._delay_max)
            return mats, alive, limits, cmask, rows, quar
        if self._async:
            # Diag/off-diag split AFTER every repair above: a departed
            # (crashed/churned/partition-isolated) lane's identity row
            # becomes diag=1 / off-diag=0 — a pure local step with no
            # stale read from, or into, the dead lane.  The off-diag
            # support is a subset of the full support, so the compiled
            # shift set always covers it.
            wdiag = np.diag(w_t).astype(np.float32)
            w_off = (w_t * (1.0 - np.eye(self.num_workers))).astype(
                np.float32)
            arg = (coeffs_for_matrix(w_off, self._shift_ids)
                   if self._shift_ids is not None else w_off)
            return (arg, wdiag), alive, limits, cmask, rows, quar
        if self._shift_ids is not None:
            return (coeffs_for_matrix(w_t, self._shift_ids), alive, limits,
                    cmask, rows, quar)
        return w_t.astype(np.float32), alive, limits, cmask, rows, quar

    def _plan_matrix_for_round(self, t: int) -> np.ndarray:
        return self.faults.plan_matrix_for(t, self._train_matrix)

    def _round_plan(self, t: int):
        """Round t's batch plan: the classic per-lane plan, or — in
        population mode — the sampled cohort bound onto the lanes (lane
        i trains client c_i's shard under client c_i's batch stream;
        sampling is stateless per (seed, round), so blocked and resumed
        runs bind identical cohorts).  Appends the round's ``cohort``
        audit row and updates the registry's participation counters as
        a side effect."""
        cfg, g = self.cfg, self.cfg.gossip
        if self._registry is None:
            return make_batch_plan(
                self._plan_matrix_for_round(t), batch_size=g.local_bs,
                local_ep=g.local_ep, seed=cfg.seed, round_idx=t,
                impl=cfg.data.plan_impl)
        reg = self._registry
        cohort = reg.sample_cohort(t)
        binding = reg.bind(t, cohort, cohort)
        ids = binding.lane_ids[0]
        reg.record_participation(t, binding.survivors)
        self.history.faults.append(binding.ledger_row(reg.clients))
        return make_batch_plan(
            self._train_matrix, batch_size=g.local_bs,
            local_ep=g.local_ep, seed=cfg.seed, round_idx=t,
            impl=cfg.data.plan_impl, workers=ids,
            rows=reg.shard_of[ids])

    def _apply_screen_feedback(self, t: int, alive, flags,
                               rows: list) -> None:
        """Fold the device step's screened-sender flags (non-finite or
        majority-clipped broadcasts) into the ledger and the quarantine
        streaks: K consecutive screened rounds quarantine the worker for
        ``quarantine_rounds``; one clean alive round resets the
        streak."""
        for i in range(self.num_workers):
            if float(flags[i]) > 0.5:
                self._screen_streak[i] += 1
                rows.append({"round": int(t), "worker": i,
                             "kind": "corrupt", "action": "screened"})
                if (self._quarantine_on and self._screen_streak[i]
                        >= self._quarantine_after):
                    until = int(t) + 1 + self._quarantine_rounds
                    self._quarantine_until[i] = until
                    self._screen_streak[i] = 0
                    rows.append({"round": int(t), "worker": i,
                                 "kind": "quarantine",
                                 "action": f"quarantined_until_{until}"})
            elif float(alive[i]) > 0:
                self._screen_streak[i] = 0

    def run(self, rounds: int | None = None, eps: int | None = None,
            block: int | None = None, checkpoint_every: int = 0,
            checkpoint_path=None) -> History:
        """Train; mirrors ``Simulator.run(rounds)`` / ``FedLCon.run(rounds, eps)``.

        ``block`` (default ``cfg.gossip.block_rounds``) > 1 fuses that
        many rounds into one jit dispatch (``_run_blocked``) — same
        math, same phase order, same eval cadence; only the host/device
        round-trip count changes.

        ``checkpoint_every=K`` (with ``checkpoint_path``) auto-saves a
        full checkpoint every K rounds; a run killed at any point and
        resumed from the latest checkpoint is bit-identical to a
        continuous run (stateless fault/batch streams + persisted host
        RNG state)."""
        cfg, g = self.cfg, self.cfg.gossip
        rounds = g.rounds if rounds is None else rounds
        if eps is not None and eps != g.eps and g.algorithm == "fedlcon":
            raise ValueError("set eps in GossipConfig (static for compilation)")
        if checkpoint_every and checkpoint_path is None:
            raise ValueError("checkpoint_every requires checkpoint_path")
        block = g.block_rounds if block is None else block
        if block > 1:
            # Every mode is blocked-eligible: quarantine rides the scan
            # carry (streak/until on device, ledger replayed post-fetch),
            # link-mode (msg_drop/msg_delay/push-sum) carries its mass +
            # staleness buffers through the scan with the per-round
            # [D+1, n, n] matrix stacks as stacked inputs.
            return self._run_blocked(rounds, block,
                                     checkpoint_every=checkpoint_every,
                                     checkpoint_path=checkpoint_path)
        t0 = time.time()  # dopt: allow-wallclock -- total_time wall meter, reporting only
        for _ in range(rounds):
            t = self.round
            with self.timers.phase("host_batch_plan"):
                (fn_name, step_fn, args, step_kw, alive, quar, frows,
                 do_eval) = self._round_dispatch(t)
            out = self.timers.measure("round_step", step_fn, *args,
                                      **step_kw)
            if self._link_mode:
                (self.params, self.momentum, self._mass, self._link_buf,
                 self._link_buf_mass, packed) = out
            elif self._async:
                (self.params, self.momentum, self.x_hat,
                 self._async_prev, packed) = out
            elif self._fused_on:
                (self.params, self.momentum, self.x_hat,
                 self._fused_buf, packed) = out
            elif self._codec_on:
                (self.params, self.momentum, self.x_hat,
                 self._comm_res, packed) = out
            else:
                self.params, self.momentum, self.x_hat, packed = out
            tl, ta, acc, lm, scr, em, diag = self._unpack_host_metrics(
                np.asarray(packed))  # ONE device→host fetch per round
            if self._robust_active:
                alive_eff = (alive * (1.0 - quar) if self._fused_quar
                             else alive)
                self._apply_screen_feedback(t, alive_eff, scr, frows)
            self.history.faults.extend(frows)
            row = {
                "round": t,
                "avg_train_loss": tl,
                "avg_train_acc": ta,
            }
            if do_eval:
                row["avg_test_acc"] = acc
                row["avg_test_loss"] = lm
            self.history.append(**row)
            if self._holdout:
                self._append_client_rows(t, em)
            self._round_telemetry(t, frows, diag)
            self._device_telemetry(t, fn_name, step_fn)
            self.round += 1
            if (checkpoint_every and
                    self.round % checkpoint_every == 0):
                self.save(checkpoint_path)
        self.total_time = time.time() - t0  # dopt: allow-wallclock -- total_time wall meter, reporting only
        self._run_summary_telemetry()
        return self.history

    def run_served(self, controller) -> str:
        """Resident serve-mode entry (``dopt.serve``): train one round
        at a time until the round-boundary ``controller`` says
        otherwise — the "run until told otherwise" loop a daemon owns
        instead of a ``--rounds N`` script.

        ``controller.boundary(trainer)`` is called BEFORE each round
        with the trainer at a consistent round boundary; it may apply
        control-plane effects (membership directives, checkpoints,
        ledgered ``control`` rows) and returns ``"run"`` to train one
        more round or a stop verdict: ``"drain"`` (graceful stop —
        the one end-of-run summary gauge is emitted here, matching a
        scripted ``run()``'s cadence), ``"restart"`` (checkpoint and
        hand control back for a process re-exec; NO summary gauge —
        the resumed daemon's drain emits it, so an interrupted and an
        uninterrupted serve emit identical streams), or ``"rebuild"``
        (the daemon must reconstruct the trainer from an updated
        config, restore, and call ``run_served`` again)."""
        self._suppress_run_summary = True
        try:
            while True:
                verdict = controller.boundary(self)
                if verdict != "run":
                    if verdict == "drain":
                        self._suppress_run_summary = False
                        self._run_summary_telemetry()
                    return verdict
                self.run(rounds=1)
        finally:
            self._suppress_run_summary = False

    def _round_dispatch(self, t: int):
        """Round ``t``'s device dispatch, fully built: ``(fn_name,
        step_fn, args, kwargs, alive, quar, frows, do_eval)``.  The ONE
        builder both the per-round ``run`` loop and ``lower_round``
        consume — which is what makes the program-fingerprint gate
        (``dopt.analysis.fingerprint``) pin the program the real loop
        actually dispatches, with no mirror to drift.  Advances the
        same stateful host draws (matching RNG, ledger rows) the run
        loop would."""
        w_t, alive, limits, cmask, frows, quar = self._round_inputs(t)
        plan = self._round_plan(t)
        idx = jax.device_put(plan.idx, self._sharding)
        bweight = jax.device_put(plan.weight, self._sharding)
        do_eval = (t % self.eval_every) == 0
        step_kw = ({"cmask": jnp.asarray(cmask)}
                   if self._has_corrupt else {})
        if self._fused_quar:
            # The quarantine fold + matrix repair happen ON DEVICE
            # (effective_inputs), identically to the blocked path.
            step_kw["quar"] = jnp.asarray(quar)
        if self._link_mode:
            args = (self.params, self.momentum, self._mass,
                    self._link_buf, self._link_buf_mass,
                    jnp.asarray(w_t), alive, limits,
                    jnp.asarray(t, jnp.int32), idx, bweight,
                    self._train_x, self._train_y, *self._eval,
                    *self._val, do_eval)
            return ("link_round_fn", self._link_round_fn, args, step_kw,
                    alive, quar, frows, do_eval)
        if self._async:
            w_t, wdiag = w_t
            step_kw["prev"] = self._async_prev
            step_kw["wdiag"] = jnp.asarray(wdiag)
        if self._fused_on:
            step_kw["fbuf"] = self._fused_buf
        if self._codec_on:
            step_kw["cres"] = self._comm_res
        args = (self.params, self.momentum, self.x_hat, w_t, alive,
                limits, jnp.asarray(t, jnp.int32), idx, bweight,
                self._train_x, self._train_y, *self._eval, *self._val,
                do_eval)
        return ("round_fn", self._round_fn, args, step_kw, alive, quar,
                frows, do_eval)

    def lower_round(self, t: int | None = None):
        """Lower (without executing) round ``t``'s device step exactly
        as the per-round ``run`` loop would dispatch it — same
        ``_round_dispatch`` builder, so the two cannot diverge — and
        return ``(fn_name, jax.stages.Lowered)``.  The program-
        fingerprint hook; call it on a FRESHLY CONSTRUCTED trainer only
        (building the inputs consumes the run loop's stateful draws)."""
        t = self.round if t is None else t
        fn_name, step_fn, args, step_kw, *_ = self._round_dispatch(t)
        return fn_name, step_fn.lower(*args, **step_kw)

    # ------------------------------------------------------------------
    def save(self, path) -> None:
        """Checkpoint full training state: params, momentum, round,
        history, AND host RNG state (the matching RNG is stateful — a
        resumed 'gossip' run must not replay round-0 matchings)."""
        with self.timers.phase("checkpoint"):
            self._save(path)
        if self.telemetry is not None:
            # Cadence telemetry for the monitor's checkpoint-cadence
            # rule (dopt.obs.rules) — emitted AFTER the atomic save
            # landed, so the stream never claims a checkpoint a kill
            # could have torn.  The consensus snapshot rides the
            # checkpoint event (params are being fetched for
            # serialization anyway), NOT a gauge: checkpoint timing is
            # call-pattern state, and gauges must stay identical across
            # execution paths (ConsensusStallRule(use_checkpoints=True)
            # opts in).
            ev = {"round": int(self.round)}
            cd = self._consensus_value()
            if cd is not None:
                ev["consensus_distance"] = cd
            self.telemetry.emit("checkpoint", **ev)  # dopt: allow-nondet-event -- checkpoint cadence is an execution-path property, documented non-deterministic

    def _save(self, path) -> None:
        from dopt.utils.checkpoint import save_checkpoint

        arrays = {"params": self.params, "momentum": self.momentum}
        if self.cfg.gossip.algorithm == "choco":
            arrays["x_hat"] = self.x_hat
        if self._async:
            # The staleness-1 buffer is carried engine state: without
            # it a resumed async run would mix round t against the
            # wrong previous-round snapshot.
            arrays["async_prev"] = self._async_prev
        if self._fused_on:
            # The displacement buffer is carried engine state — and the
            # carried "params" are the POST-MIX q, not the post-local
            # endpoint — so a fused resume needs both trees to contract
            # round t exactly as the unkilled run would.
            arrays["fused_buf"] = self._fused_buf
        if self._codec_on:
            # The per-bucket error-feedback residual is carried engine
            # state: a resumed codec run must fold back exactly the
            # quantization error the unkilled run would have.
            arrays["comm_residual"] = {
                f"b{i}": r for i, r in enumerate(self._comm_res)}
        if self._link_mode:
            # Push-sum mass and the staleness buffers are carried engine
            # state: without them a resumed lossy-link run would replay
            # round t against the wrong in-flight/history snapshots.
            if self._push_sum:
                arrays["push_mass"] = {"mass": self._mass}
            if self._delay_max > 0:
                arrays["link_buf"] = self._link_buf
                if self._push_sum:
                    arrays["link_buf_mass"] = {"mass": self._link_buf_mass}
        meta = {"round": self.round, "name": self.cfg.name,
                "algorithm": self.cfg.gossip.algorithm,
                "history": self.history.rows,
                "client_history": self.client_history.rows,
                "fault_ledger": self.history.faults,
                "screen_streak": self._screen_streak.tolist(),
                "quarantine_until": self._quarantine_until.tolist(),
                "matching_rng_state": self._matching_rng.bit_generator.state}
        if self._registry is not None:
            meta["population_registry"] = self._registry.state_dict()
        save_checkpoint(path, arrays=arrays, meta=meta,
                        write=self.checkpoint_writer)

    def restore(self, path) -> None:
        """Resume from a checkpoint written by ``save`` (same config)."""
        from dopt.utils.checkpoint import load_checkpoint

        arrays, meta = load_checkpoint(path)
        if meta.get("algorithm") != self.cfg.gossip.algorithm:
            raise ValueError(
                f"checkpoint is for algorithm {meta.get('algorithm')!r}, "
                f"trainer runs {self.cfg.gossip.algorithm!r}"
            )
        self.params = shard_worker_tree(arrays["params"], self.mesh)
        self.momentum = shard_worker_tree(arrays["momentum"], self.mesh)
        if self.cfg.gossip.algorithm == "choco":
            if "x_hat" not in arrays:
                raise ValueError(
                    "choco trainer requires its public-copy state "
                    "('x_hat') in the checkpoint")
            self.x_hat = shard_worker_tree(arrays["x_hat"], self.mesh)
        if self._async:
            if "async_prev" not in arrays:
                raise ValueError(
                    "mixing='async' trainer requires its previous-round "
                    "state ('async_prev') in the checkpoint")
            self._async_prev = shard_worker_tree(arrays["async_prev"],
                                                 self.mesh)
        if self._fused_on:
            if "fused_buf" not in arrays:
                raise ValueError(
                    "fused_update='on' trainer requires its displacement "
                    "buffer ('fused_buf') in the checkpoint — this "
                    "checkpoint is from a fused_update='off' run, whose "
                    "carried params are the post-local endpoint, not "
                    "the (post-mix, displacement) pair")
            self._fused_buf = shard_worker_tree(arrays["fused_buf"],
                                                self.mesh)
        elif "fused_buf" in arrays:
            raise ValueError(
                "checkpoint carries a fused displacement buffer "
                "('fused_buf') but this trainer runs fused_update='off' "
                "— the checkpoint's 'params' are the post-mix state q, "
                "not the post-local endpoint; restore with "
                "fused_update='on'")
        if self._codec_on:
            if "comm_residual" not in arrays:
                raise ValueError(
                    "comm.codec trainer requires its per-bucket "
                    "error-feedback residual ('comm_residual') in the "
                    "checkpoint — this checkpoint is from an "
                    "uncompressed run, whose rounds never accumulated "
                    "a quantization error to feed back")
            res = arrays["comm_residual"]
            self._comm_res = shard_worker_tree(
                tuple(res[f"b{i}"] for i in range(len(res))), self.mesh)
        elif "comm_residual" in arrays:
            raise ValueError(
                "checkpoint carries a comm error-feedback residual "
                "('comm_residual') but this trainer runs without the "
                "bucket codec — the residual's pending correction "
                "would be silently dropped; restore with the same "
                "CommConfig codec armed")
        if self._link_mode:
            if self._push_sum:
                if "push_mass" not in arrays:
                    raise ValueError(
                        "push-sum trainer requires its mass vector "
                        "('push_mass') in the checkpoint")
                self._mass = jnp.asarray(arrays["push_mass"]["mass"])
            if self._delay_max > 0:
                if "link_buf" not in arrays:
                    raise ValueError(
                        "link-delay trainer requires its staleness "
                        "buffer ('link_buf') in the checkpoint")
                # Restore with the constructor's placement ([D, W, ...]
                # sharded over the worker axis) so a resumed run feeds
                # the compiled round fn identically-sharded inputs —
                # a bare asarray would leave D full-model snapshots
                # replicated per device.
                buf_sharding = jax.sharding.NamedSharding(
                    self.mesh,
                    jax.sharding.PartitionSpec(None,
                                               worker_axes(self.mesh)))
                self._link_buf = jax.device_put(arrays["link_buf"],
                                                buf_sharding)
                if self._push_sum:
                    if "link_buf_mass" not in arrays:
                        raise ValueError(
                            "push-sum + delay trainer requires the "
                            "in-flight mass buffer ('link_buf_mass') in "
                            "the checkpoint")
                    self._link_buf_mass = jnp.asarray(
                        arrays["link_buf_mass"]["mass"])
        self.round = int(meta["round"])
        self.history.rows = list(meta.get("history", []))
        self.history.faults = list(meta.get("fault_ledger", []))
        self.client_history.rows = list(meta.get("client_history", []))
        w = self.num_workers
        self._screen_streak = np.asarray(
            meta.get("screen_streak", [0] * w), np.int64)
        self._quarantine_until = np.asarray(
            meta.get("quarantine_until", [0] * w), np.int64)
        if meta.get("matching_rng_state"):
            self._matching_rng.bit_generator.state = meta["matching_rng_state"]
        if self._registry is not None:
            state = meta.get("population_registry")
            if state is None:
                raise ValueError(
                    "population-mode trainer requires its registry state "
                    "('population_registry') in the checkpoint — this "
                    "checkpoint is from a lane-engine run")
            self._registry.load_state(state)
        if meta.get("dropout_rng_state"):
            # Checkpoint from before dropout joined FaultPlan, whose
            # draws are stateless per round: the resumed run's failure
            # sequence is deterministic but NOT the one the stateful
            # stream would have produced.
            import warnings

            warnings.warn(
                "checkpoint carries the legacy stateful dropout RNG; "
                "dropout faults now draw statelessly per round "
                "(dopt.faults.FaultPlan), so this run's failure "
                "sequence will differ from the original pre-upgrade "
                "run", stacklevel=2)

    def _debiased_params(self):
        """Device-resident per-worker parameter estimates: the carried
        params, or — under ``correction='push_sum'``, where the carried
        state is the NUMERATOR — the de-biased ratio estimates
        params/mass (the quantity that converges to the true average
        under lossy links).  The divide runs on device so callers never
        pay a host round-trip for it."""
        if self._fused_on:
            # Fused carry holds the POST-MIX state q and the pending
            # displacement; the round's semantic endpoint — what the
            # default path carries as params — is q − fbuf.
            return jax.tree.map(lambda a, b: a - b, self.params,
                                self._fused_buf)
        if not self._push_sum:
            return self.params
        mass = self._mass

        def debias(x):
            mm = jnp.maximum(mass, 1e-12).reshape(
                (-1,) + (1,) * (x.ndim - 1))
            return (x.astype(jnp.float32) / mm).astype(x.dtype)

        return jax.tree.map(debias, self.params)

    def worker_params(self):
        """Host copy of ``_debiased_params`` ([W, ...] pytree)."""
        return jax.device_get(self._debiased_params())

    # Convenience: per-worker eval of the current state (reuses the
    # round step's evaluator — same wrapping, same jit cache).
    def evaluate(self) -> dict[str, np.ndarray]:
        """Reference-semantics eval: EVERY worker on the FULL test set,
        regardless of ``eval_mode`` (the sharded mode only changes the
        in-training per-round metric).  Push-sum runs evaluate the
        de-biased estimates."""
        if self._eval_full is None:
            ex, ey, ew = eval_batches(self.dataset.test_x,
                                      self.dataset.test_y,
                                      batch_size=max(self.cfg.gossip.local_bs,
                                                     256))
            self._eval_full = (jnp.asarray(ex), jnp.asarray(ey),
                               jnp.asarray(ew))
        out = jax.jit(self._full_evaluator)(self._debiased_params(),
                                            *self._eval_full)
        return {k: np.asarray(v) for k, v in out.items()}
