from dopt.data.datasets import Dataset, load_dataset
from dopt.data.partition import iid_split, noniid_split, partition
from dopt.data.pipeline import BatchPlan, eval_batches, make_batch_plan, gather_batches

__all__ = [
    "Dataset",
    "load_dataset",
    "iid_split",
    "noniid_split",
    "partition",
    "BatchPlan",
    "eval_batches",
    "make_batch_plan",
    "gather_batches",
]
