"""Byzantine corruption & robust-aggregation tests (dopt.robust).

Four layers, all inside the tier-1 budget (tiny MLPs, <= 6 rounds):

* aggregator unit properties — trimmed mean / median / Krum against
  hand-computed masked statistics, outlier resistance, the non-finite
  lane screen, norm clipping, clipped-gossip algebra;
* the convergence acceptance criterion — under a corrupt FaultPlan with
  f adversaries, trimmed-mean/median/Krum (federated) and clipped
  gossip stay within 2x of the fault-free baseline while the plain mean
  diverges or NaNs;
* engine integration — clean-path bit-identity, the always-on
  non-finite guard on the default mean path, execution-path parity
  (compact/full-width, per-round/blocked) under corruption, and the
  quarantine lifecycle surviving checkpoint/resume bit-exactly;
* artifact hardening — atomic History/ledger writes survive a
  simulated mid-write kill.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from dopt.config import (DataConfig, ExperimentConfig, FaultConfig,
                         FederatedConfig, GossipConfig, ModelConfig,
                         OptimizerConfig, RobustConfig)
from dopt.faults import CORRUPT_MODES, FaultPlan, parse_corrupt_spec
from dopt.robust import (clip_to_ball, clipped_gossip_mix, finite_lane_mask,
                         krum_aggregate, masked_median, masked_trimmed_mean,
                         validate_robust_config)

pytestmark = pytest.mark.byzantine


# ---------------------------------------------------------------------------
# FaultPlan: corrupt draws
# ---------------------------------------------------------------------------

def test_corrupt_draws_stateless_and_capped():
    cfg = FaultConfig(corrupt=0.5, corrupt_mode="signflip")
    a, b = FaultPlan(16, cfg, seed=3), FaultPlan(16, cfg, seed=3)
    for t in (4, 0, 4, 2):
        np.testing.assert_array_equal(a.for_round(t).corrupt,
                                      b.for_round(t).corrupt)
    assert a.has_corrupt and a.active
    # corrupt=1 + corrupt_max=f pins workers 0..f-1 as the persistent
    # adversary set (the fixed-f Byzantine setting).
    pinned = FaultPlan(16, FaultConfig(corrupt=1.0, corrupt_max=3), seed=0)
    for t in range(4):
        np.testing.assert_array_equal(
            np.nonzero(pinned.for_round(t).corrupt)[0], [0, 1, 2])


def test_corrupt_crash_ties_and_validation():
    # A crashed worker sends nothing — crash wins the tie.
    rf = FaultPlan(8, FaultConfig(corrupt=1.0, crash=1.0), seed=0).for_round(0)
    assert rf.crashed.all() and not rf.corrupt.any()
    for bad in ({"corrupt": 1.5}, {"corrupt_mode": "gaslight"},
                {"corrupt_scale": 0.0}, {"corrupt_max": -1}):
        with pytest.raises(ValueError):
            FaultPlan(8, FaultConfig(**bad), seed=0)


def test_parse_corrupt_spec():
    cfg = parse_corrupt_spec("p=0.25,mode=signflip,scale=50,max=2")
    assert cfg.corrupt == 0.25 and cfg.corrupt_mode == "signflip"
    assert cfg.corrupt_scale == 50 and cfg.corrupt_max == 2
    assert parse_corrupt_spec("0.4").corrupt == 0.4
    # bare mode spec implies p=1 ("make them lie")
    assert parse_corrupt_spec("mode=nan").corrupt == 1.0
    base = FaultConfig(crash=0.1)
    merged = parse_corrupt_spec("p=0.2", base=base)
    assert merged.crash == 0.1 and merged.corrupt == 0.2
    with pytest.raises(ValueError, match="unknown field"):
        parse_corrupt_spec("prob=0.2")
    assert set(CORRUPT_MODES) == {"nan", "inf", "scale", "signflip", "stale"}


# ---------------------------------------------------------------------------
# Robust aggregator units (host-level, jit-free semantics)
# ---------------------------------------------------------------------------

def _tree(x):
    return {"w": np.asarray(x, np.float32)}


def test_finite_lane_mask():
    x = {"a": np.ones((4, 3), np.float32),
         "b": np.ones((4, 2), np.float32)}
    x["a"][1, 0] = np.nan
    x["b"][3, 1] = np.inf
    np.testing.assert_array_equal(np.asarray(finite_lane_mask(x)),
                                  [1.0, 0.0, 1.0, 0.0])


def test_trimmed_mean_matches_manual_and_resists_outliers():
    rng = np.random.default_rng(0)
    vals = rng.normal(size=(10, 5)).astype(np.float32)
    mask = np.ones(10, np.float32)
    mask[7] = 0.0                       # one dead lane, excluded entirely
    vals[7] = 1e9                       # ...whatever it holds is ignored
    vals[0] = 1e6                       # one live outlier, trimmed
    out = np.asarray(masked_trimmed_mean(_tree(vals), mask, 0.2)["w"])
    alive = np.delete(vals, 7, axis=0)
    k = int(0.2 * 9)                    # floor(trim_frac * n_alive)
    manual = np.sort(alive, axis=0)[k:9 - k].mean(axis=0)
    np.testing.assert_allclose(out, manual, rtol=1e-5)
    assert np.abs(out).max() < 10       # the 1e6 outlier never leaks


def test_median_matches_manual_odd_and_even():
    rng = np.random.default_rng(1)
    vals = rng.normal(size=(9, 4)).astype(np.float32)
    mask = np.ones(9, np.float32)
    out = np.asarray(masked_median(_tree(vals), mask)["w"])
    np.testing.assert_allclose(out, np.median(vals, axis=0), rtol=1e-5)
    mask[4] = 0.0                       # even alive count -> mid-pair mean
    out = np.asarray(masked_median(_tree(vals), mask)["w"])
    np.testing.assert_allclose(out, np.median(np.delete(vals, 4, 0), axis=0),
                               rtol=1e-5)


def test_krum_selects_honest_cluster():
    rng = np.random.default_rng(2)
    honest = rng.normal(0.0, 0.1, size=(6, 8)).astype(np.float32)
    liars = rng.normal(50.0, 0.1, size=(2, 8)).astype(np.float32)
    vals = np.concatenate([honest, liars])
    mask = np.ones(8, np.float32)
    out = np.asarray(krum_aggregate(_tree(vals), mask, 2, 1)["w"])
    # Krum picks ONE honest update — never a mixture touched by liars.
    assert np.abs(out).max() < 1.0
    assert any(np.allclose(out, h, atol=1e-6) for h in honest)
    # multi-Krum (m=0 -> n_alive - f = 6) averages the honest cluster.
    out_m = np.asarray(krum_aggregate(_tree(vals), mask, 2, 0)["w"])
    np.testing.assert_allclose(out_m, honest.mean(axis=0), atol=1e-4)
    # dead lanes can't be selected even when closest together
    mask2 = np.ones(8, np.float32)
    mask2[6:] = 0.0
    out_d = np.asarray(krum_aggregate(_tree(vals), mask2, 1, 1)["w"])
    assert np.abs(out_d).max() < 1.0
    # degenerate round: a lone survivor at a nonzero index (every score
    # is the +inf sentinel) must return ITS value, not zeros
    mask3 = np.zeros(8, np.float32)
    mask3[3] = 1.0
    out_s = np.asarray(krum_aggregate(_tree(vals), mask3, 2, 1)["w"])
    np.testing.assert_allclose(out_s, vals[3], rtol=1e-6)


def test_clip_to_ball_bounds_deviations():
    center = {"w": np.zeros(4, np.float32)}
    x = {"w": np.stack([np.full(4, 100.0, np.float32),
                        np.full(4, 0.1, np.float32)])}
    out = np.asarray(clip_to_ball(x, center, 1.0)["w"])
    assert np.linalg.norm(out[0]) <= 1.0 + 1e-5     # blown lane clipped
    np.testing.assert_allclose(out[1], 0.1, rtol=1e-5)  # inlier untouched


def test_clipped_gossip_reduces_to_plain_mix_and_ignores_nan():
    from dopt.parallel.collectives import mix_dense
    from dopt.topology import build_mixing_matrices

    rng = np.random.default_rng(3)
    w_m = build_mixing_matrices("circle", "metropolis", 6, seed=0).matrices[0]
    x = {"w": rng.normal(size=(6, 5)).astype(np.float32)}
    # tau far above any deviation: exactly the plain consensus step
    mixed, screened = clipped_gossip_mix(x, x, w_m, 1e9)
    np.testing.assert_allclose(np.asarray(mixed["w"]),
                               np.asarray(mix_dense(x, w_m)["w"]), atol=1e-5)
    assert not np.asarray(screened).any()
    # a NaN sender is ignored outright (its mixing weight returns to
    # each receiver's self-term), and the liar is the one flagged.
    x_send = {"w": x["w"].copy()}
    x_send["w"][2] = np.nan
    mixed, screened = clipped_gossip_mix(x, x_send, w_m, 1e9)
    assert np.isfinite(np.asarray(mixed["w"])).all()
    np.testing.assert_array_equal(np.asarray(screened),
                                  [0, 0, 1, 0, 0, 0])
    c = w_m * (1.0 - np.eye(6))
    c[:, 2] = 0.0                       # the poisoned column is dropped
    manual = (np.diag(1.0 - c.sum(axis=1)) + c) @ x["w"]
    np.testing.assert_allclose(np.asarray(mixed["w"])[np.arange(6) != 2],
                               manual[np.arange(6) != 2], atol=1e-5)
    # a norm-blown sender shifts each honest receiver by at most
    # 2·W_ij·tau relative to the honest sweep (its own clipped term
    # plus the honest term it displaced)
    x_send2 = {"w": x["w"].copy()}
    x_send2["w"][2] += 1e6
    tau = 0.5
    mixed2, screened2 = clipped_gossip_mix(x, x_send2, w_m, tau)
    honest_mix, _ = clipped_gossip_mix(x, x, w_m, tau)
    delta = np.linalg.norm(np.asarray(mixed2["w"]) - np.asarray(honest_mix["w"]),
                           axis=1)
    assert (delta <= 2 * w_m[:, 2] * tau + 1e-4).all()
    assert screened2[2] == 1.0


def test_byzantine_mix_spreads_to_neighbors_only_and_spares_liar():
    from dopt.robust import byzantine_mix
    from dopt.parallel.collectives import mix_dense
    from dopt.topology import build_mixing_matrices

    rng = np.random.default_rng(4)
    w_m = build_mixing_matrices("circle", "metropolis", 6, seed=0).matrices[0]
    x = {"w": rng.normal(size=(6, 5)).astype(np.float32)}
    # honest sends: exactly the dense consensus step
    np.testing.assert_allclose(
        np.asarray(byzantine_mix(x, x, w_m)["w"]),
        np.asarray(mix_dense(x, w_m)["w"]), atol=1e-5)
    # a NaN liar at lane 2 poisons exactly its ring neighbors (1, 3);
    # its OWN carried state stays finite (it lied on the wire only)
    x_send = {"w": x["w"].copy()}
    x_send["w"][2] = np.nan
    out = np.asarray(byzantine_mix(x, x_send, w_m)["w"])
    finite_rows = np.isfinite(out).all(axis=1)
    np.testing.assert_array_equal(finite_rows, [1, 0, 1, 0, 1, 1])


def test_validate_robust_config():
    validate_robust_config(RobustConfig())
    for bad in ({"aggregator": "mode"}, {"trim_frac": 0.5},
                {"krum_f": -1}, {"clip_radius": -1.0},
                {"quarantine_rounds": 0}):
        with pytest.raises(ValueError):
            validate_robust_config(RobustConfig(**bad))


# ---------------------------------------------------------------------------
# Engine integration (tiny models, synthetic data)
# ---------------------------------------------------------------------------

_DATA = DataConfig(dataset="synthetic", num_users=8, iid=True,
                   synthetic_train_size=256, synthetic_test_size=64)
_MODEL = ModelConfig(model="mlp", input_shape=(28, 28, 1), faithful=False)
_OPTIM = OptimizerConfig(lr=0.1, momentum=0.5, rho=0.1)
# 2 persistent adversaries blowing their update norm up 50x each round.
_ATTACK = FaultConfig(corrupt=1.0, corrupt_max=2, corrupt_mode="scale",
                      corrupt_scale=50.0)


def _fed_cfg(faults=None, robust=None, **fkw):
    f = dict(algorithm="fedavg", frac=1.0, rounds=4, local_ep=1, local_bs=32)
    f.update(fkw)
    return ExperimentConfig(name="t", seed=7, data=_DATA, model=_MODEL,
                            optim=_OPTIM, federated=FederatedConfig(**f),
                            faults=faults, robust=robust)


def _gossip_cfg(faults=None, robust=None, **gkw):
    g = dict(algorithm="dsgd", topology="circle", mode="metropolis",
             rounds=4, local_ep=1, local_bs=32)
    g.update(gkw)
    return ExperimentConfig(name="t", seed=7, data=_DATA, model=_MODEL,
                            optim=_OPTIM, gossip=GossipConfig(**g),
                            faults=faults, robust=robust)


def test_clean_paths_bit_identical_with_robust_defaults(devices):
    # robust=None vs all-default RobustConfig (aggregator='mean', no
    # clip, no quarantine): identical History on both engines — the
    # acceptance criterion that wiring the robust layer never perturbs
    # clean runs.
    from dopt.engine import FederatedTrainer, GossipTrainer

    h0 = FederatedTrainer(_fed_cfg(frac=0.5)).run(rounds=1)
    h1 = FederatedTrainer(_fed_cfg(frac=0.5, robust=RobustConfig())).run(rounds=1)
    assert h0.rows == h1.rows and h1.faults == []
    g0 = GossipTrainer(_gossip_cfg()).run(rounds=1)
    g1 = GossipTrainer(_gossip_cfg(robust=RobustConfig())).run(rounds=1)
    assert g0.rows == g1.rows and g1.faults == []


def test_nan_lane_no_longer_poisons_global_loss(devices):
    # Regression for the non-finite guard on the DEFAULT mean path: a
    # worker emitting NaN updates is screened (ledger corrupt/screened)
    # and every global metric stays finite.  Pre-guard, one NaN lane
    # NaN'd theta — and the global loss — from its first round on.
    from dopt.engine import FederatedTrainer

    fc = FaultConfig(corrupt=1.0, corrupt_max=1, corrupt_mode="nan")
    tr = FederatedTrainer(_fed_cfg(fc))
    h = tr.run(rounds=3)
    for row in h.rows:
        for k in ("test_loss", "test_acc", "train_loss", "local_loss"):
            assert np.isfinite(row[k]), (k, row)
    acts = {(r["kind"], r["action"]) for r in h.faults}
    assert ("corrupt", "injected_nan") in acts
    assert ("corrupt", "screened_nonfinite") in acts
    assert np.isfinite(tr.evaluate_global()["loss_mean"])


# The fault-free and mean-under-attack reference runs are shared by
# every aggregator case (identical configs -> identical deterministic
# results) — memoized so the tier-1 sweep pays for them once.
_LOSS_MEMO: dict = {}


def _final_test_loss(key, cfg):
    if key not in _LOSS_MEMO:
        from dopt.engine import FederatedTrainer

        _LOSS_MEMO[key] = FederatedTrainer(cfg).run(
            rounds=4).rows[-1]["test_loss"]
    return _LOSS_MEMO[key]


@pytest.mark.parametrize("aggregator", [
    "trimmed_mean", "median",
    pytest.param("krum", marks=pytest.mark.slow),
    pytest.param("multi_krum", marks=pytest.mark.slow),
])
def test_robust_aggregators_converge_where_mean_diverges(aggregator, devices):
    # THE acceptance criterion: with f=2 adversaries out of 8, each
    # robust aggregator ends within 2x of its fault-free baseline's
    # eval loss; the plain mean diverges (or NaNs) by orders of
    # magnitude.  Fully deterministic (seeded corrupt draws, frac=1).
    # The averaging aggregators are held to the plain-mean baseline;
    # Krum selects a SINGLE update per round — its information cost is
    # paid with or without an attack — so its tolerance is measured
    # against its own fault-free trajectory (plus a same-order sanity
    # bound vs the plain baseline).
    base = _final_test_loss("base", _fed_cfg())
    mean_loss = _final_test_loss("mean_attack", _fed_cfg(_ATTACK))
    assert not np.isfinite(mean_loss) or mean_loss > 2 * base
    rc = RobustConfig(aggregator=aggregator, trim_frac=0.25, krum_f=2)
    from dopt.engine import FederatedTrainer

    robust_loss = FederatedTrainer(
        _fed_cfg(_ATTACK, robust=rc)).run(rounds=4).rows[-1]["test_loss"]
    if aggregator == "krum":
        ref = _final_test_loss("krum_base", _fed_cfg(robust=rc))
        assert robust_loss <= 10 * base, (robust_loss, base)
    else:
        ref = base
    assert np.isfinite(robust_loss) and robust_loss <= 2 * ref, (
        aggregator, robust_loss, ref)


def test_clipped_gossip_converges_where_plain_mean_diverges(devices):
    # The decentralized half of the criterion: 1 liar on an 8-ring.
    from dopt.engine import GossipTrainer

    atk = dataclasses.replace(_ATTACK, corrupt_max=1)
    base = GossipTrainer(_gossip_cfg()).run(rounds=4).rows[-1]["avg_test_loss"]
    plain = GossipTrainer(
        _gossip_cfg(atk)).run(rounds=4).rows[-1]["avg_test_loss"]
    assert not np.isfinite(plain) or plain > 2 * base
    clipped = GossipTrainer(
        _gossip_cfg(atk, robust=RobustConfig(clip_radius=1.0))
    ).run(rounds=4).rows[-1]["avg_test_loss"]
    assert np.isfinite(clipped) and clipped <= 2 * base, (clipped, base)


@pytest.mark.slow
def test_signflip_and_stale_modes_run_and_ledger(devices):
    from dopt.engine import FederatedTrainer

    for mode in ("signflip", "stale"):
        fc = FaultConfig(corrupt=1.0, corrupt_max=2, corrupt_mode=mode)
        rc = RobustConfig(aggregator="median")
        h = FederatedTrainer(_fed_cfg(fc, robust=rc)).run(rounds=2)
        assert any(r["action"] == f"injected_{mode}" for r in h.faults)
        assert all(np.isfinite(r["test_loss"]) for r in h.rows)


@pytest.mark.slow
def test_scaffold_companion_channel_is_corrupted_too(devices):
    # A liar lies on every channel it reports: under SCAFFOLD its
    # control-variate update is corrupted under the same mask, so
    # c_global differs from the clean run's (the documented
    # SCAFFOLD-under-Byzantine exposure), while nan-mode lanes stay
    # screened out of both theta and the companion state.
    import jax
    from dopt.engine import FederatedTrainer

    fc = FaultConfig(corrupt=1.0, corrupt_max=2, corrupt_mode="signflip")
    clean = FederatedTrainer(_fed_cfg(algorithm="scaffold"))
    clean.run(rounds=2)
    lied = FederatedTrainer(_fed_cfg(fc, algorithm="scaffold"))
    lied.run(rounds=2)
    diff = sum(float(np.abs(np.asarray(a) - np.asarray(b)).sum())
               for a, b in zip(jax.tree.leaves(clean.c_global),
                               jax.tree.leaves(lied.c_global)))
    assert diff > 0.0
    fcn = FaultConfig(corrupt=1.0, corrupt_max=2, corrupt_mode="nan")
    h = FederatedTrainer(_fed_cfg(fcn, algorithm="scaffold")).run(rounds=2)
    assert all(np.isfinite(r["test_loss"]) for r in h.rows)


@pytest.mark.slow
def test_compact_full_width_parity_under_corrupt(devices):
    # NaN liars + crashes: the compact path (survivor lanes + lane
    # screen) and the full-width path (mask x finite screen) must form
    # the same aggregate, ledger, and metrics.
    from dopt.engine import FederatedTrainer

    fc = FaultConfig(corrupt=0.4, corrupt_mode="nan", crash=0.3)
    hc = FederatedTrainer(dataclasses.replace(
        _fed_cfg(fc, frac=0.5, compact=True), mesh_devices=1)).run(rounds=3)
    hf = FederatedTrainer(dataclasses.replace(
        _fed_cfg(fc, frac=0.5, compact=False), mesh_devices=1)).run(rounds=3)
    assert hc.faults == hf.faults and hc.faults
    for rc_, rf_ in zip(hc.rows, hf.rows):
        assert set(rc_) == set(rf_)
        for k in rc_:
            np.testing.assert_allclose(rc_[k], rf_[k], rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_blocked_matches_per_round_under_corrupt(devices):
    # The corrupt masks ride the fused scan as data: per-round and
    # blocked execution produce identical History AND ledger on both
    # engines (full-width federated; clipped gossip).
    from dopt.engine import FederatedTrainer, GossipTrainer

    fc = FaultConfig(corrupt=0.5, corrupt_mode="signflip")
    ha = FederatedTrainer(_fed_cfg(fc, frac=0.5)).run(rounds=2, block=1)
    hb = FederatedTrainer(_fed_cfg(fc, frac=0.5)).run(rounds=2, block=2)
    assert ha.rows == hb.rows and ha.faults == hb.faults and ha.faults
    rc = RobustConfig(clip_radius=1.0)
    ga = GossipTrainer(_gossip_cfg(fc, robust=rc)).run(rounds=2, block=1)
    gb = GossipTrainer(_gossip_cfg(fc, robust=rc)).run(rounds=2, block=2)
    assert ga.rows == gb.rows and ga.faults == gb.faults and ga.faults


def test_quarantine_lifecycle_federated(devices):
    # Worker 0 NaNs every round: screened twice -> quarantined (masked
    # out of the sample) -> readmitted after the backoff -> reoffends.
    # Global metrics stay finite throughout.
    from dopt.engine import FederatedTrainer

    fc = FaultConfig(corrupt=1.0, corrupt_max=1, corrupt_mode="nan")
    rc = RobustConfig(quarantine_after=2, quarantine_rounds=2)
    h = FederatedTrainer(_fed_cfg(fc, robust=rc)).run(rounds=8)
    acts = [(r["round"], r["worker"], r["action"]) for r in h.faults
            if r["worker"] == 0]
    assert (1, 0, "quarantined_until_4") in acts
    assert (2, 0, "excluded_while_quarantined") in acts
    assert (4, 0, "readmitted") in acts
    assert (5, 0, "quarantined_until_8") in acts   # reoffended
    assert all(np.isfinite(r["test_loss"]) for r in h.rows)


@pytest.mark.slow
def test_quarantine_lifecycle_gossip(devices):
    from dopt.engine import GossipTrainer

    fc = FaultConfig(corrupt=1.0, corrupt_max=1, corrupt_mode="nan")
    rc = RobustConfig(clip_radius=1.0, quarantine_after=2,
                      quarantine_rounds=2)
    h = GossipTrainer(_gossip_cfg(fc, robust=rc)).run(rounds=6)
    acts = [r["action"] for r in h.faults if r["worker"] == 0]
    assert "quarantined_until_4" in acts and "readmitted" in acts
    assert all(np.isfinite(r["avg_test_loss"]) for r in h.rows
               if "avg_test_loss" in r)


@pytest.mark.parametrize("engine", [
    pytest.param("federated", marks=pytest.mark.slow),
    pytest.param("gossip", marks=pytest.mark.slow),
])
def test_byzantine_resume_bit_exact_with_quarantine(engine, tmp_path,
                                                    devices):
    # Satellite: the ledger (corrupt + quarantine rows) and the
    # quarantine streak state survive save/restore — a killed-and-
    # resumed adversarial run is bit-identical to a continuous one.
    from dopt.engine import FederatedTrainer, GossipTrainer

    fc = FaultConfig(corrupt=1.0, corrupt_max=2, corrupt_mode="nan",
                     crash=0.2)
    if engine == "federated":
        rc = RobustConfig(aggregator="trimmed_mean", trim_frac=0.25,
                          quarantine_after=2, quarantine_rounds=2)
        mk = lambda: FederatedTrainer(_fed_cfg(fc, robust=rc, frac=0.5))
    else:
        rc = RobustConfig(clip_radius=1.0, quarantine_after=2,
                          quarantine_rounds=2)
        mk = lambda: GossipTrainer(_gossip_cfg(fc, robust=rc))
    path = os.fspath(tmp_path / engine)
    hc = mk().run(rounds=6)
    part = mk()
    part.run(rounds=3, checkpoint_every=3, checkpoint_path=path)
    res = mk()
    res.restore(path)
    assert res.round == 3
    hr = res.run(rounds=3)
    assert hr.rows == hc.rows
    assert hr.faults == hc.faults
    assert any(r["kind"] == "quarantine" for r in hc.faults)
    assert any(r["kind"] == "corrupt" for r in hc.faults)


def test_robust_rejections(devices):
    from dopt.engine import FederatedTrainer, GossipTrainer

    with pytest.raises(ValueError, match="comm_dtype"):
        FederatedTrainer(_fed_cfg(
            robust=RobustConfig(aggregator="median"), comm_dtype="bfloat16"))
    with pytest.raises(ValueError, match="clip_radius"):
        GossipTrainer(_gossip_cfg(robust=RobustConfig(aggregator="krum")))
    with pytest.raises(ValueError, match="stale"):
        GossipTrainer(_gossip_cfg(FaultConfig(corrupt=0.5,
                                              corrupt_mode="stale")))
    with pytest.raises(ValueError, match="mixing algorithm"):
        GossipTrainer(_gossip_cfg(FaultConfig(corrupt=0.5),
                                  algorithm="nocons"))
    with pytest.raises(ValueError, match="never communicates"):
        GossipTrainer(_gossip_cfg(robust=RobustConfig(clip_radius=1.0),
                                  algorithm="nocons"))
    with pytest.raises(ValueError, match="comm_dtype"):
        GossipTrainer(_gossip_cfg(robust=RobustConfig(clip_radius=1.0),
                                  comm_dtype="bfloat16"))
    with pytest.raises(ValueError, match="choco"):
        GossipTrainer(_gossip_cfg(FaultConfig(corrupt=0.5),
                                  algorithm="choco"))
    with pytest.raises(ValueError, match="shift"):
        GossipTrainer(_gossip_cfg(FaultConfig(corrupt=0.5),
                                  comm_impl="shift"))


@pytest.mark.slow
def test_cli_byzantine_flags(devices, capsys):
    from dopt.run import main

    rc = main(["--preset", "baseline1", "--rounds", "2",
               "--synthetic-scale", "0.01",
               "--corrupt", "p=1,max=1,mode=scale,scale=50",
               "--aggregator", "mean",
               "--set", "robust.clip_radius=1.0"])
    assert rc == 0
    out = capsys.readouterr()
    assert "fault ledger" in out.err


# ---------------------------------------------------------------------------
# Fault-ledger round-trip & atomic artifact writes
# ---------------------------------------------------------------------------

def test_ledger_roundtrip_through_checkpoint(tmp_path, devices):
    # Ledger rows (including corrupt/quarantine kinds) survive
    # save/restore verbatim.
    from dopt.engine import FederatedTrainer

    fc = FaultConfig(corrupt=1.0, corrupt_max=1, corrupt_mode="nan",
                     crash=0.3)
    rc = RobustConfig(quarantine_after=1, quarantine_rounds=2)
    tr = FederatedTrainer(_fed_cfg(fc, robust=rc, frac=0.5))
    tr.run(rounds=4)
    path = os.fspath(tmp_path / "ck")
    tr.save(path)
    tr2 = FederatedTrainer(_fed_cfg(fc, robust=rc, frac=0.5))
    tr2.restore(path)
    assert tr2.history.faults == tr.history.faults
    kinds = {r["kind"] for r in tr2.history.faults}
    assert "corrupt" in kinds and "quarantine" in kinds
    # and the JSON export round-trips
    out = tmp_path / "ledger.json"
    tr2.history.faults_to_json(out)
    assert json.loads(out.read_text()) == tr2.history.faults


def test_atomic_writes_survive_midwrite_kill(tmp_path, monkeypatch):
    # Satellite: History exports (--faults-json, results CSV/JSON) are
    # temp-file + os.replace.  A kill mid-write (simulated by making the
    # final replace explode) leaves the previous complete artifact
    # intact and no truncated JSON behind.
    from dopt.utils import metrics as m

    h = m.History("t")
    h.append(round=0, test_acc=0.5)
    h.log_fault(round=0, worker=1, kind="corrupt", action="screened")
    jpath, cpath, fpath = (tmp_path / "h.json", tmp_path / "h.csv",
                           tmp_path / "f.json")
    h.to_json(jpath), h.to_csv(cpath), h.faults_to_json(fpath)
    before = {p: p.read_text() for p in (jpath, cpath, fpath)}

    def boom(src, dst):
        raise OSError("killed mid-write")

    h.append(round=1, test_acc=0.9)
    monkeypatch.setattr(m.os, "replace", boom)
    for fn, p in ((h.to_json, jpath), (h.to_csv, cpath),
                  (h.faults_to_json, fpath)):
        with pytest.raises(OSError):
            fn(p)
    monkeypatch.undo()
    for p, text in before.items():
        assert p.read_text() == text          # old artifact untouched
        json.loads(p.read_text()) if p.suffix == ".json" else None
    assert not list(tmp_path.glob(".*tmp*"))  # no orphaned temp files
