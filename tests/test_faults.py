"""Fault-injection & recovery subsystem tests (dopt.faults.FaultPlan).

Three layers, all inside the tier-1 budget (tiny models, <= 4 rounds):

* host-only FaultPlan semantics — stateless per-round draws, the
  dropout back-compat alias, validation, the ``--faults`` CLI parser;
* mixing-matrix repair properties (``repair_for_dropout`` /
  ``repair_for_partition``) as seeded sweeps — the invariants every
  engine path relies on (row-stochastic, identity rows for the
  isolated/dead, all-down degenerates to identity) without a
  hypothesis dependency;
* engine integration — fault-free runs bit-identical to a no-faults
  config, faulted runs deterministic with an auditable ledger,
  compact/full-width parity under crashes, and crash-exact
  checkpoint/resume for both engines.
"""

import dataclasses
import os

import numpy as np
import pytest

from dopt.config import (DataConfig, ExperimentConfig, FaultConfig,
                         FederatedConfig, GossipConfig, ModelConfig,
                         OptimizerConfig)
from dopt.faults import KINDS, FaultPlan, RoundFaults, parse_fault_spec
from dopt.topology import (build_mixing_matrices, repair_for_dropout,
                           repair_for_partition)

pytestmark = pytest.mark.faults


# ---------------------------------------------------------------------------
# FaultPlan: stateless draws, aliasing, validation
# ---------------------------------------------------------------------------

def test_faultplan_stateless_and_order_independent():
    cfg = FaultConfig(crash=0.3, straggle=0.4, straggle_frac=0.5,
                      partition=0.3, partition_span=2)
    a = FaultPlan(8, cfg, seed=11)
    b = FaultPlan(8, cfg, seed=11)
    # Draw rounds in different orders from different instances: traces
    # must match exactly (this is what makes per-round and blocked
    # execution — and killed-and-resumed runs — see identical faults).
    for t in (5, 0, 3, 5):
        ra, rb = a.for_round(t), b.for_round(t)
        np.testing.assert_array_equal(ra.crashed, rb.crashed)
        np.testing.assert_array_equal(ra.straggler, rb.straggler)
        np.testing.assert_array_equal(ra.epoch_frac, rb.epoch_frac)
        if ra.partition is None:
            assert rb.partition is None
        else:
            np.testing.assert_array_equal(ra.partition, rb.partition)


def test_faultplan_seeds_change_trace():
    cfg = FaultConfig(crash=0.5)
    a = FaultPlan(32, cfg, seed=1)
    b = FaultPlan(32, cfg, seed=2)
    assert any(
        not np.array_equal(a.for_round(t).crashed, b.for_round(t).crashed)
        for t in range(4))
    # cfg.seed overrides the experiment seed
    c = FaultPlan(32, dataclasses.replace(cfg, seed=1), seed=2)
    for t in range(4):
        np.testing.assert_array_equal(a.for_round(t).crashed,
                                      c.for_round(t).crashed)


def test_faultplan_inactive_and_fault_free():
    for plan in (FaultPlan(6, None, seed=3),
                 FaultPlan(6, FaultConfig(), seed=3)):
        assert not plan.active and not plan.may_straggle
        assert not plan.affects_matrix
        rf = plan.for_round(9)
        assert not rf.any_fault
        assert not rf.crashed.any() and not rf.straggler.any()
        np.testing.assert_array_equal(rf.epoch_frac, np.ones(6, np.float32))
        assert rf.partition is None


def test_faultplan_dropout_alias():
    with pytest.warns(DeprecationWarning, match="dropout is deprecated"):
        plan = FaultPlan(8, None, seed=5, dropout=0.25)
    assert plan.active and plan.cfg.crash == 0.25
    with pytest.raises(ValueError, match="not both"):
        FaultPlan(8, FaultConfig(crash=0.1), seed=5, dropout=0.25)


def test_dropout_alias_deprecation_and_trace_parity(devices):
    # Retirement contract for the GossipConfig.dropout alias: trainer
    # construction warns ONCE (DeprecationWarning) NAMING the removal
    # release, and the run's History + fault ledger are identical to
    # the explicit FaultConfig(crash=p) spelling — so the alias can be
    # dropped in release 0.2.0 with a pure find-and-replace migration.
    import warnings

    from dopt.engine import GossipTrainer

    with pytest.warns(DeprecationWarning,
                      match="dropout is deprecated") as rec:
        legacy = GossipTrainer(_gossip_cfg(None, dropout=0.3))
    assert any("0.2.0" in str(w.message) for w in rec), \
        "deprecation warning must name the removal release"
    with warnings.catch_warnings():
        warnings.simplefilter("error", DeprecationWarning)
        explicit = GossipTrainer(_gossip_cfg(FaultConfig(crash=0.3)))
    hl = legacy.run(rounds=2)
    he = explicit.run(rounds=2)
    assert hl.rows == he.rows
    assert hl.faults == he.faults and hl.faults
    # The alias routes through the link-fault model: a crashed worker is
    # the degenerate all-links-down case, so the crash repair the alias
    # triggers equals cutting every in/out edge on the per-edge path.
    from dopt.topology import repair_for_dropout, repair_for_link_drop

    w_t = legacy._matrix_for_round(0)
    rf = legacy.faults.for_round(0)
    alive = (~rf.crashed).astype(np.float32)
    dead = rf.crashed
    keep = ~(dead[:, None] | dead[None, :])
    np.testing.assert_allclose(repair_for_dropout(w_t, alive),
                               repair_for_link_drop(w_t, keep),
                               atol=1e-12)


@pytest.mark.parametrize("bad", [
    {"crash": 1.5}, {"straggle": -0.1}, {"straggle_frac": 2.0},
    {"straggle": 0.5, "straggle_frac": 0.0},
    {"straggler_policy": "retry"}, {"over_select": -1.0},
    {"partition_span": 0}, {"partition_groups": 1},
    {"msg_drop": -0.1}, {"msg_drop": 1.0}, {"msg_delay": 1.5},
    {"msg_delay": 0.2, "msg_delay_max": 0}, {"churn": 2.0},
    {"churn": 0.1, "churn_span": 0},
])
def test_faultplan_validation(bad):
    with pytest.raises(ValueError):
        FaultPlan(8, FaultConfig(**bad), seed=0)


def test_crash_wins_ties_and_limits():
    cfg = FaultConfig(crash=1.0, straggle=1.0, straggle_frac=0.5)
    rf = FaultPlan(8, cfg, seed=0).for_round(0)
    assert rf.crashed.all() and not rf.straggler.any()
    # limits: healthy workers get the full budget, stragglers
    # ceil(frac * total) >= 1 for frac > 0
    rf2 = RoundFaults(0, np.zeros(4, bool),
                      np.array([False, True, True, True]),
                      np.array([1.0, 0.5, 0.26, 0.01], np.float32), None)
    np.testing.assert_array_equal(FaultPlan.limits_for(rf2, 4),
                                  [4, 2, 2, 1])


def test_partition_membership_stable_over_span():
    cfg = FaultConfig(partition=0.4, partition_span=3, partition_groups=3)
    plan = FaultPlan(10, cfg, seed=123)
    # Find a start round: the draw keyed at s fires.
    active = {t: plan.for_round(t).partition for t in range(40)}
    starts = [t for t in range(40)
              if active[t] is not None
              and (t == 0 or active[t - 1] is None)]
    assert starts, "expected at least one partition in 40 rounds"
    for s in starts:
        g = active[s]
        assert g.min() >= 0 and g.max() < 3
        # A start at s keeps SOME partition active for the whole span;
        # membership keyed by the start round holds until a newer start
        # supersedes it (the most recent start wins).
        for t in range(s, min(s + 3, 40)):
            assert active[t] is not None
            newer_start = any(
                FaultPlan(10, cfg, seed=123)._rng(3, u).random() < 0.4
                for u in range(s + 1, t + 1))
            if not newer_start:
                np.testing.assert_array_equal(active[t], g)


def test_parse_fault_spec():
    cfg = parse_fault_spec(
        "crash=0.1, straggle=0.2,straggle_frac=0.5,partition=0.05,"
        "partition_span=3,straggler_policy=drop,over_select=0.3")
    assert cfg.crash == 0.1 and cfg.straggle == 0.2
    assert cfg.partition_span == 3 and cfg.straggler_policy == "drop"
    assert cfg.over_select == 0.3
    with pytest.raises(ValueError, match="unknown field"):
        parse_fault_spec("crush=0.1")
    with pytest.raises(ValueError, match="expects"):
        parse_fault_spec("crash=lots")
    assert set(KINDS) == {"crash", "straggler", "partition", "overselect",
                          "corrupt", "quarantine", "msg_drop", "msg_delay",
                          "churn", "staleness", "cohort", "control"}
    # the lossy-link / elastic-membership fields parse like any other
    cfg2 = parse_fault_spec(
        "msg_drop=0.1,msg_delay=0.2,msg_delay_max=3,churn=0.05,churn_span=2")
    assert cfg2.msg_drop == 0.1 and cfg2.msg_delay_max == 3
    assert cfg2.churn == 0.05 and cfg2.churn_span == 2


# ---------------------------------------------------------------------------
# Mixing-matrix repair properties (seeded sweeps; hypothesis-free)
# ---------------------------------------------------------------------------

def _matrices(seed):
    rng = np.random.default_rng(seed)
    for topology, mode in (("circle", "metropolis"), ("complete", "uniform"),
                           ("torus", "double_stochastic")):
        n = int(rng.integers(4, 12))
        yield (build_mixing_matrices(topology, mode, n, seed=seed)
               .matrices[0], rng)


def test_repair_for_dropout_properties():
    for seed in range(8):
        for w, rng in _matrices(seed):
            n = w.shape[0]
            alive = (rng.random(n) < 0.6).astype(np.float32)
            r = repair_for_dropout(w, alive)
            # every row stays stochastic; dead workers get EXACT
            # identity rows (frozen, stale-but-valid rejoin)
            np.testing.assert_allclose(r.sum(axis=1), 1.0, atol=1e-6)
            for i in range(n):
                if not alive[i]:
                    expect = np.zeros(n); expect[i] = 1.0
                    np.testing.assert_array_equal(r[i], expect)
                else:
                    assert np.all(r[i][alive == 0.0] == 0.0)


def test_repair_for_dropout_all_down_is_identity():
    for w, _ in _matrices(3):
        n = w.shape[0]
        r = repair_for_dropout(w, np.zeros(n, np.float32))
        np.testing.assert_array_equal(r, np.eye(n))


def test_repair_for_dropout_doubly_stochastic_symmetric_failures():
    # A SYMMETRIC doubly-stochastic matrix under a failure pattern that
    # isolates the survivors pairwise-symmetrically stays symmetric:
    # masking w by outer(alive, alive) is symmetric, and the surviving
    # rows' renormalisers are equal whenever their masked rows are
    # permutations of each other.  The regular ring is the canonical
    # case: any alive pattern keeps w masked symmetric, and rows
    # renormalise by their own (equal-by-symmetry) sums only when the
    # surviving neighbourhood is symmetric — assert the symmetric cases.
    # Metropolis weights are the canonical SYMMETRIC doubly-stochastic
    # construction (the 'double_stochastic' mode is doubly stochastic
    # but directed).
    mm = build_mixing_matrices("circle", "metropolis", 8, seed=0)
    w = mm.matrices[0]
    np.testing.assert_allclose(w, w.T, atol=1e-12)
    np.testing.assert_allclose(w.sum(axis=0), 1.0, atol=1e-6)
    # Failure patterns that preserve the ring's symmetry group: all
    # alive, alternating (every survivor isolated -> identity rows),
    # and paired blocks (every survivor keeps exactly one neighbour
    # with circulant-equal weights).
    for alive in ([1, 1, 1, 1, 1, 1, 1, 1], [1, 0, 1, 0, 1, 0, 1, 0],
                  [1, 1, 0, 0, 1, 1, 0, 0]):
        a = np.asarray(alive, np.float32)
        r = repair_for_dropout(w, a)
        # symmetry of the repaired matrix over the alive-alive block
        live = np.nonzero(a)[0]
        sub = r[np.ix_(live, live)]
        np.testing.assert_allclose(sub, sub.T, atol=1e-6)


def test_repair_for_partition_properties():
    for seed in range(8):
        for w, rng in _matrices(seed):
            n = w.shape[0]
            groups = rng.integers(0, 2, size=n).astype(np.int32)
            r = repair_for_partition(w, groups)
            np.testing.assert_allclose(r.sum(axis=1), 1.0, atol=1e-6)
            # no weight crosses the cut
            cross = groups[:, None] != groups[None, :]
            assert np.all(r[cross] == 0.0)
            # a worker isolated by the cut keeps exactly its own weights
            masked = w * (~cross).astype(w.dtype)
            for i in np.nonzero(masked.sum(axis=1) <= 0)[0]:
                expect = np.zeros(n); expect[i] = 1.0
                np.testing.assert_array_equal(r[i], expect)
    with pytest.raises(ValueError, match="entries"):
        repair_for_partition(np.eye(4), np.zeros(3, np.int32))


# ---------------------------------------------------------------------------
# Engine integration (tiny models, synthetic data)
# ---------------------------------------------------------------------------

_DATA = DataConfig(dataset="synthetic", num_users=8, iid=True,
                   synthetic_train_size=256, synthetic_test_size=64)
_MODEL = ModelConfig(model="mlp", input_shape=(28, 28, 1), faithful=False)
_OPTIM = OptimizerConfig(lr=0.1, momentum=0.5, rho=0.1)
_FAULTS = FaultConfig(crash=0.3, straggle=0.3, straggle_frac=0.5)


def _fed_cfg(faults=None, **fkw):
    f = dict(algorithm="fedavg", frac=0.5, rounds=4, local_ep=1, local_bs=32)
    f.update(fkw)
    return ExperimentConfig(name="t", seed=7, data=_DATA, model=_MODEL,
                            optim=_OPTIM, federated=FederatedConfig(**f),
                            faults=faults)


def _gossip_cfg(faults=None, **gkw):
    g = dict(algorithm="dsgd", topology="circle", mode="metropolis",
             rounds=4, local_ep=1, local_bs=32)
    g.update(gkw)
    return ExperimentConfig(name="t", seed=7, data=_DATA, model=_MODEL,
                            optim=_OPTIM, gossip=GossipConfig(**g),
                            faults=faults)


def test_fault_free_runs_bit_identical(devices):
    # No FaultPlan vs an all-zero FaultConfig: same History, empty
    # ledger, and the sampling stream undisturbed — the acceptance
    # criterion that enabling the subsystem never perturbs clean runs.
    from dopt.engine import FederatedTrainer, GossipTrainer

    h0 = FederatedTrainer(_fed_cfg()).run(rounds=2)
    h1 = FederatedTrainer(_fed_cfg(FaultConfig())).run(rounds=2)
    assert h0.rows == h1.rows and h1.faults == []
    g0 = GossipTrainer(_gossip_cfg()).run(rounds=2)
    g1 = GossipTrainer(_gossip_cfg(FaultConfig())).run(rounds=2)
    assert g0.rows == g1.rows and g1.faults == []


def test_federated_faulted_deterministic_with_ledger(devices):
    from dopt.engine import FederatedTrainer

    fc = dataclasses.replace(_FAULTS, over_select=0.5, partition=0.3,
                             partition_span=2)
    ha = FederatedTrainer(_fed_cfg(fc)).run(rounds=3)
    hb = FederatedTrainer(_fed_cfg(fc)).run(rounds=3)
    assert ha.rows == hb.rows
    assert ha.faults == hb.faults and ha.faults
    for row in ha.faults:
        assert set(row) == {"round", "worker", "kind", "action"}
        assert row["kind"] in KINDS


def test_federated_compact_full_width_parity_under_faults(devices):
    # Sampled clients crash mid-round: the compact path (survivor lanes
    # only) and the full-width path (mask-discard) must form the same
    # masked average — identical ledgers, metrics equal to float
    # summation order.
    from dopt.engine import FederatedTrainer

    # The compact path exists on single-device meshes only.
    hc = FederatedTrainer(dataclasses.replace(
        _fed_cfg(_FAULTS, compact=True), mesh_devices=1)).run(rounds=3)
    hf = FederatedTrainer(dataclasses.replace(
        _fed_cfg(_FAULTS, compact=False), mesh_devices=1)).run(rounds=3)
    assert hc.faults == hf.faults and hc.faults
    for rc, rf in zip(hc.rows, hf.rows):
        assert set(rc) == set(rf)
        for k in rc:
            np.testing.assert_allclose(rc[k], rf[k], rtol=2e-4, atol=2e-5)


def test_gossip_blocked_matches_per_round_under_faults(devices):
    from dopt.engine import GossipTrainer

    fc = dataclasses.replace(_FAULTS, partition=0.3, partition_span=2)
    ha = GossipTrainer(_gossip_cfg(fc)).run(rounds=3, block=1)
    hb = GossipTrainer(_gossip_cfg(fc)).run(rounds=3, block=3)
    assert ha.rows == hb.rows
    assert ha.faults == hb.faults and ha.faults


def test_gossip_dropout_alias_back_compat(devices):
    from dopt.engine import GossipTrainer

    tr = GossipTrainer(_gossip_cfg(None, dropout=0.3))
    assert tr.faults.active and tr.faults.cfg.crash == 0.3
    h = tr.run(rounds=2)
    assert all(r["kind"] == "crash" for r in h.faults)


@pytest.mark.parametrize("engine", ["federated", "gossip"])
def test_crash_exact_resume(engine, tmp_path, devices):
    # Save at round 2 via checkpoint_every, restore into a FRESH
    # trainer, run to round 4: History rows AND fault ledger must be
    # bit-identical to an uninterrupted run (catches the round-offset
    # RNG replay bug the engine comments warn about).
    from dopt.engine import FederatedTrainer, GossipTrainer

    mk, cls = ((_fed_cfg, FederatedTrainer) if engine == "federated"
               else (_gossip_cfg, GossipTrainer))
    path = os.fspath(tmp_path / engine)
    cont = cls(mk(_FAULTS))
    hc = cont.run(rounds=4)
    part = cls(mk(_FAULTS))
    part.run(rounds=2, checkpoint_every=2, checkpoint_path=path)
    res = cls(mk(_FAULTS))
    res.restore(path)
    assert res.round == 2
    hr = res.run(rounds=2)
    assert hr.rows == hc.rows
    assert hr.faults == hc.faults


def test_checkpoint_every_requires_path(devices):
    from dopt.engine import FederatedTrainer, GossipTrainer

    with pytest.raises(ValueError, match="checkpoint_path"):
        FederatedTrainer(_fed_cfg()).run(rounds=1, checkpoint_every=1)
    with pytest.raises(ValueError, match="checkpoint_path"):
        GossipTrainer(_gossip_cfg()).run(rounds=1, checkpoint_every=1)


# ---------------------------------------------------------------------------
# Checkpoint hardening: truncation is detected, never loaded as garbage
# ---------------------------------------------------------------------------

def test_truncated_checkpoint_raises_clear_error(tmp_path):
    from dopt.utils.checkpoint import (IncompleteCheckpointError,
                                       load_checkpoint, save_checkpoint)

    path = tmp_path / "ckpt"
    arrays = {"theta": {"w": np.arange(64, dtype=np.float32)}}
    save_checkpoint(path, arrays=arrays, meta={"round": 3})
    a, m = load_checkpoint(path)          # intact: round-trips
    assert m["round"] == 3
    np.testing.assert_array_equal(a["theta"]["w"], arrays["theta"]["w"])

    # Truncate the state payload mid-file (a mid-write crash / partial
    # copy): the size manifest cross-check must reject it loudly.
    state_files = [p for p in path.rglob("*")
                   if p.is_file() and p.name not in ("meta.json",
                                                     "complete.json")]
    assert state_files
    biggest = max(state_files, key=lambda p: p.stat().st_size)
    biggest.write_bytes(biggest.read_bytes()[: biggest.stat().st_size // 2])
    with pytest.raises(IncompleteCheckpointError, match="truncated"):
        load_checkpoint(path)


def test_half_written_checkpoint_falls_back_then_errors(tmp_path):
    from dopt.utils.checkpoint import (IncompleteCheckpointError,
                                       load_checkpoint, save_checkpoint)

    path = tmp_path / "ckpt"
    save_checkpoint(path, arrays={"x": np.ones(4)}, meta={"round": 1})
    save_checkpoint(path, arrays={"x": np.full(4, 2.0)}, meta={"round": 2})
    # Simulate a crash after the save deleted meta but before the swap:
    # the primary is incomplete and there is no .old left.
    (path / "meta.json").unlink()
    with pytest.raises(IncompleteCheckpointError):
        load_checkpoint(path)
