"""Device mesh and worker-axis sharding.

The engine's whole layout hinges on one idea (SURVEY §7): the reference's
N sequentially-stepped client objects become ONE stacked pytree with a
leading ``workers`` axis, sharded over a 1-D ``jax.sharding.Mesh``.
``num_workers`` need not equal the device count: workers fold onto
devices (``workers = devices × workers_per_device``) and per-device
lanes are vmapped — that is how 32 workers run on a v5e-8
(mesh plan "(cores=8, workers_per_core=4)").
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORKER_AXIS = "workers"


def make_mesh(num_devices: int | None = None, *, devices=None) -> Mesh:
    """1-D mesh over the worker axis."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if len(devices) < num_devices:
            raise ValueError(f"need {num_devices} devices, have {len(devices)}")
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (WORKER_AXIS,))


def fit_mesh_devices(num_workers: int, requested: int | None = None) -> int:
    """Largest device count <= min(workers, available) that divides the
    worker count evenly (workers fold onto devices in equal lanes)."""
    avail = len(jax.devices()) if requested is None else requested
    d = min(num_workers, avail)
    while num_workers % d:
        d -= 1
    return d


def worker_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (worker) axis across the mesh; everything else
    replicated within a worker shard."""
    return NamedSharding(mesh, P(WORKER_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_worker_tree(tree, mesh: Mesh):
    """Place a stacked [W, ...] pytree with the worker axis sharded.

    W must divide evenly by the mesh size (pad the worker count or pick
    a divisor worker total — the engine validates this upstream)."""
    sh = worker_sharding(mesh)

    def put(x):
        if x.shape[0] % mesh.size:
            raise ValueError(
                f"worker axis {x.shape[0]} not divisible by mesh size {mesh.size}"
            )
        return jax.device_put(x, sh)

    return jax.tree.map(put, tree)
