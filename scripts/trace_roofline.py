"""Profiler-trace-backed roofline evidence for the benchmark configs.

Captures a real XLA profiler trace (``dopt.utils.profiling.trace``) of a
steady-state fused round block, then reduces the xplane to a committed
JSON summary: per-op-category self time, the top ops, and the
device/host split.  This is the evidence layer behind the MFU numbers
in ``results/bench_suite.json`` and ``BENCH_r*.json`` — the prose
roofline claims ("activation-bandwidth-bound", "conv1 has 1 input
channel") become checkable op-level timings.

Targets: ``--preset baseline5`` (32-worker ResNet-18 gossip, the north
star) and ``--preset headline`` (bench.py's 6-worker Model1 workload).

Writes results/trace_<name>.json (the raw xplane stays out of git — it
is hundreds of KB of protobuf; the summary carries the numbers).

Usage: python scripts/trace_roofline.py --preset baseline5 [--rounds 3]
"""

from __future__ import annotations

import argparse
import dataclasses
import glob
import json
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def build_trainer(preset: str):
    from dopt.engine import FederatedTrainer, GossipTrainer

    if preset == "headline":
        import bench

        cfg = bench._config(fast=True, train_size=60_000, test_size=10_000)
    else:
        from dopt.presets import get_preset

        cfg = get_preset(preset)
        cfg = cfg.replace(
            model=dataclasses.replace(cfg.model, compute_dtype="bfloat16"),
            data=dataclasses.replace(cfg.data, plan_impl="native"),
        )
    is_gossip = cfg.gossip is not None
    trainer = (GossipTrainer if is_gossip else FederatedTrainer)(
        cfg, eval_every=10_000)   # no eval inside the traced window
    return cfg, trainer


def summarize_xplane(trace_dir: str) -> dict:
    """Reduce the captured xplane to category/op-level self times."""
    from xprof.convert import raw_to_tool_data

    paths = glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True)
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    data, _ = raw_to_tool_data.xspace_to_tool_data(paths,
                                                   "framework_op_stats", {})
    table = json.loads(data if isinstance(data, str) else data.decode())
    if isinstance(table, list):
        table = table[0]
    cols = [c["id"] for c in table["cols"]]
    idx = {c: i for i, c in enumerate(cols)}

    def val(row, col):
        cell = row["c"][idx[col]]
        return None if cell is None else cell.get("v")

    by_cat: dict[str, float] = {}
    device_total = host_total = 0.0
    ops = []
    for row in table.get("rows", []):
        side = val(row, "host_or_device")
        self_us = float(val(row, "total_self_time") or 0.0)
        cat = val(row, "type") or "?"
        if side == "Device":
            device_total += self_us
            by_cat[cat] = by_cat.get(cat, 0.0) + self_us
            ops.append({
                "op_type": cat,
                "operation": val(row, "operation"),
                "occurrences": val(row, "occurrences"),
                "total_self_time_us": round(self_us, 1),
            })
        else:
            host_total += self_us
    ops.sort(key=lambda o: -o["total_self_time_us"])
    cat_rows = sorted(by_cat.items(), key=lambda kv: -kv[1])
    return {
        "device_self_time_us": round(device_total, 1),
        "host_self_time_us": round(host_total, 1),
        "device_categories": [
            {"op_type": k, "self_time_us": round(v, 1),
             "pct_of_device": round(100.0 * v / max(device_total, 1e-9), 2)}
            for k, v in cat_rows
        ],
        "top_device_ops": ops[:20],
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="baseline5",
                    help="baseline1..5 or 'headline' (bench.py workload)")
    ap.add_argument("--rounds", type=int, default=3,
                    help="rounds inside the traced fused block")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from dopt.utils.profiling import trace

    cfg, trainer = build_trainer(args.preset)
    rounds = args.rounds
    trainer.run(rounds=rounds, block=rounds)          # compile + warmup
    import jax

    with tempfile.TemporaryDirectory(prefix="dopt-trace-") as td:
        t0 = time.perf_counter()
        with trace(td):
            trainer.run(rounds=rounds, block=rounds)
            jax.block_until_ready(trainer.params)
        elapsed = time.perf_counter() - t0
        summary = summarize_xplane(td)

    payload = {
        "preset": args.preset,
        "config_name": cfg.name,
        "model": cfg.model.model,
        "workers": cfg.data.num_users,
        "rounds_traced": rounds,
        "wall_seconds_traced": round(elapsed, 3),
        "device": str(jax.devices()[0]),
        **summary,
    }
    out = Path(args.out or f"results/trace_{args.preset}.json")
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(payload, indent=2) + "\n")
    top = payload["device_categories"][:5]
    print(f"{args.preset}: {rounds} rounds traced in {elapsed:.2f}s; "
          f"device self-time {payload['device_self_time_us']/1e6:.3f}s")
    for c in top:
        print(f"  {c['op_type']:<28s} {c['pct_of_device']:6.2f}%")
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
