"""Control plane for the resident trainer (``dopt serve``).

A served run is driven by three small, file-backed pieces:

* **Command queue** (``<state>/commands.jsonl``) — the append-only
  inbound channel.  One versioned JSON command per line; the admin
  endpoint appends here, scripts can pre-seed it, and the daemon
  ingests new complete lines at every round boundary (byte-offset
  tail, so a million-round run never re-parses the file).
* **Applied ledger** (``<state>/applied.jsonl``) — the durable record
  of what the daemon DID with each command: ``status`` applied or
  rejected, the boundary ``round`` it took effect, and the full
  command payload.  This is the replay source: a restarted daemon
  reconstructs its effective config, membership overlay and admission
  state by replaying this file, which is what makes a served run
  resumable AND bit-reproducible — the run is a pure function of
  (base config, applied ledger).
* **Ledgered control rows** — every applied command also lands in the
  trainer's fault ledger (``kind="control"``) and the telemetry stream
  (the deterministic ``control`` event kind), at the boundary round,
  so the run's own artifacts carry the replay script.

Command schema (v1), one object per line::

    {"v": 1, "cmd": "config",     "key": "optim.lr", "value": 0.05,
     "at_round": 12, "id": "lr-decay"}
    {"v": 1, "cmd": "membership", "worker": 3, "action": "leave"}
    {"v": 1, "cmd": "checkpoint"}
    {"v": 1, "cmd": "drain", "restart": false}
    {"v": 1, "cmd": "pause"}   /   {"v": 1, "cmd": "resume"}

``at_round`` pins the FIRST eligible boundary (the command applies at
the first boundary whose round is >= at_round); without it the command
applies at the next boundary after ingestion.  ``id`` defaults to the
queue position (``q<N>``), so re-scans after a restart recognise
already-processed commands.

Config changes are WHITELISTED: only keys whose mid-run mutation has
well-defined checkpoint/rebuild/restore semantics are accepted —
everything else is rejected (recorded, never ledgered).  Membership
commands ride ``dopt.faults.MembershipLog`` → the existing churn /
shard-reassignment machinery.

Stdlib-only (no jax): the control plane must be drivable from any
operator laptop.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Iterable

COMMAND_VERSION = 1


def _terminate_torn_tail(f) -> None:
    """Append-side hygiene for the control-plane JSONL files: if a
    hard-killed writer left the file without a trailing newline,
    terminate the torn line BEFORE appending — gluing a new record
    onto partial bytes would merge them into one malformed line and
    silently lose the new record.  The terminated torn line itself is
    handled downstream (queue poll → reject record; ledger replay →
    skipped, so its command reprocesses from the queue)."""
    f.seek(0, os.SEEK_END)
    if f.tell() == 0:
        return
    f.seek(f.tell() - 1)
    if f.read(1) != "\n":
        f.write("\n")

COMMANDS = ("config", "membership", "checkpoint", "drain", "pause",
            "resume")

# The whitelisted mid-run config surface.  "optim.lr" and
# "population.cohort" apply via checkpoint → rebuild → restore (the
# trainer is reconstructed under the new config and restored from the
# boundary checkpoint — the same bit-exact path a kill-and-resume
# takes); "checkpoint_every" is daemon-level state (the streaming
# checkpoint cadence) and applies in place.
CONFIG_WHITELIST = {
    "optim.lr": float,
    "population.cohort": int,
    "checkpoint_every": int,
}

MEMBERSHIP_ACTIONS = ("join", "leave")


def make_command(cmd: str, **fields: Any) -> dict[str, Any]:
    """Build one schema-stamped command (None fields dropped)."""
    obj: dict[str, Any] = {"v": COMMAND_VERSION, "cmd": cmd}
    obj.update({k: v for k, v in fields.items() if v is not None})
    return validate_command(obj)


def _fail(msg: str, obj: Any) -> None:
    raise ValueError(f"{msg}: {obj!r}")


def validate_command(obj: Any) -> dict[str, Any]:
    """Validate one command against the v1 schema; returns it, raises
    ``ValueError`` otherwise.  Whitelist membership of config keys is
    checked here too — a bad key fails at submission time with a clean
    message instead of at the boundary."""
    if not isinstance(obj, dict):
        _fail("command is not an object", obj)
    if obj.get("v") != COMMAND_VERSION:
        _fail(f"unknown command version (want v={COMMAND_VERSION})", obj)
    cmd = obj.get("cmd")
    if cmd not in COMMANDS:
        _fail(f"unknown command (want one of {COMMANDS})", obj)
    if "id" in obj and (not isinstance(obj["id"], str) or not obj["id"]):
        _fail("command id must be a non-empty string", obj)
    if "at_round" in obj:
        r = obj["at_round"]
        if not isinstance(r, int) or isinstance(r, bool) or r < 0:
            _fail("at_round must be an int >= 0", obj)
    if "ts" in obj:
        # The enqueue wall-clock stamp (CommandQueue.submit adds it):
        # the start point of the command_apply SLO latency.  Advisory
        # metadata, never replay data.
        t = obj["ts"]
        if isinstance(t, bool) or not isinstance(t, (int, float)) \
                or t < 0:
            _fail("ts must be a number >= 0", obj)
    if cmd == "config":
        key = obj.get("key")
        if key not in CONFIG_WHITELIST:
            _fail(f"config key not whitelisted (serve accepts "
                  f"{sorted(CONFIG_WHITELIST)})", obj)
        v = obj.get("value")
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            _fail("config value must be numeric", obj)
        if CONFIG_WHITELIST[key] is int and int(v) != v:
            _fail(f"config key {key!r} takes an integer", obj)
        if key == "checkpoint_every" and int(v) < 0:
            _fail("checkpoint_every must be >= 0 (0 disables the "
                  "cadence)", obj)
        if key == "optim.lr" and not float(v) > 0:
            _fail("optim.lr must be > 0", obj)
        if key == "population.cohort" and int(v) < 1:
            _fail("population.cohort must be >= 1", obj)
    elif cmd == "membership":
        w = obj.get("worker")
        if not isinstance(w, int) or isinstance(w, bool) or w < 0:
            _fail("membership command needs int worker >= 0", obj)
        if obj.get("action") not in MEMBERSHIP_ACTIONS:
            _fail(f"membership action must be one of "
                  f"{MEMBERSHIP_ACTIONS}", obj)
    elif cmd == "drain":
        if "restart" in obj and not isinstance(obj["restart"], bool):
            _fail("drain restart must be a bool", obj)
    return obj


class CommandQueue:
    """Append-only JSONL inbound queue with an incremental tail.

    ``submit`` appends one validated command (thread-safe within the
    process; whole-line ``O_APPEND`` writes keep concurrent external
    writers line-atomic).  ``poll`` returns the complete lines appended
    since the last poll as ``(commands, rejects)`` — a malformed line
    becomes a reject record instead of desynchronizing the daemon (the
    queue is operator input, not trusted telemetry).  ``ids`` are
    assigned from the queue position (``q<N>``) when absent, so a
    restarted daemon re-scanning from offset 0 derives the same ids."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.offset = 0
        self._lines_seen = 0
        self._lock = threading.Lock()

    def submit(self, command: dict[str, Any]) -> dict[str, Any]:
        command = validate_command(dict(command))
        # Enqueue stamp for the command_apply SLO latency (enqueue ts →
        # applied ts); pre-stamped commands (a replayed script) keep
        # their own.
        command.setdefault("ts", round(time.time(), 6))  # dopt: allow-wallclock -- command_apply SLO latency enqueue stamp, advisory metadata
        with self._lock:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            with open(self.path, "a+", encoding="utf-8") as f:
                # flock makes the count-assign-append atomic ACROSS
                # processes too (the admin endpoint and an external
                # pre-seeding script share this file): two writers must
                # never mint the same queue-position id — the applied
                # ledger's last-record-per-id replay would silently
                # drop one command's effect on resume.
                import fcntl

                fcntl.flock(f.fileno(), fcntl.LOCK_EX)
                try:
                    _terminate_torn_tail(f)
                    if "id" not in command:
                        f.seek(0)
                        n = sum(1 for _ in f)
                        command["id"] = f"q{n + 1}"
                    f.seek(0, os.SEEK_END)
                    f.write(json.dumps(command, sort_keys=True) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                finally:
                    fcntl.flock(f.fileno(), fcntl.LOCK_UN)
        return command

    def poll(self) -> tuple[list[dict[str, Any]], list[dict[str, Any]]]:
        if not self.path.exists():
            return [], []
        with self._lock, open(self.path, "rb") as f:
            f.seek(0, 2)
            size = f.tell()
            if size < self.offset:
                self.offset = size   # truncated externally: clamp
            f.seek(self.offset)
            chunk = f.read()
        if not chunk:
            return [], []
        end = chunk.rfind(b"\n")
        if end < 0:
            return [], []
        commands: list[dict[str, Any]] = []
        rejects: list[dict[str, Any]] = []
        for raw in chunk[:end + 1].splitlines():
            raw = raw.strip()
            if not raw:
                continue
            self._lines_seen += 1
            qid = f"q{self._lines_seen}"
            try:
                obj = json.loads(raw)
            except ValueError:
                rejects.append({"id": qid, "cmd": None,
                                "reason": f"not JSON: {raw[:80]!r}"})
                continue
            try:
                obj = validate_command(obj)
            except ValueError as e:
                rejects.append({"id": (obj.get("id") if isinstance(obj, dict)
                                       else None) or qid,
                                "cmd": (obj.get("cmd") if isinstance(obj, dict)
                                        else None),
                                "reason": str(e)})
                continue
            obj.setdefault("id", qid)
            commands.append(obj)
        self.offset += end + 1
        return commands, rejects


class ControlLedger:
    """The applied-command ledger (``applied.jsonl``): one line-flushed
    record per terminal command decision.  ``replay`` returns the
    records in order — with the LAST record per command id winning, so
    a re-applied command (a crash between apply and checkpoint)
    supersedes its stale first record."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._fh = None

    def append(self, record: dict[str, Any]) -> dict[str, Any]:
        if self._fh is None:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            self._fh = open(self.path, "a+", encoding="utf-8")
            # A hard kill mid-append can leave a torn final line;
            # terminate it so the records this process writes stay
            # parseable (replay skips the torn one and the queue
            # re-supplies its command).
            _terminate_torn_tail(self._fh)
        self._fh.write(json.dumps(record, sort_keys=True) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())
        return record

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    @classmethod
    def replay(cls, path: str | Path) -> list[dict[str, Any]]:
        path = Path(path)
        if not path.exists():
            return []
        by_id: dict[str, dict[str, Any]] = {}
        order: list[str] = []
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    # A torn record from a hard kill (terminated by the
                    # next writer): SKIP it — its effect died with the
                    # writer's memory and the command, still absent
                    # from the processed set, reprocesses from the
                    # queue.  Breaking here would also discard every
                    # later (valid) record.
                    continue
                rid = str(rec.get("id"))
                if rid not in by_id:
                    order.append(rid)
                by_id[rid] = rec
        return [by_id[rid] for rid in order]


def applied_record(command: dict[str, Any], *, status: str, round_idx: int,
                   reason: str | None = None,
                   auto: bool = False) -> dict[str, Any]:
    rec = dict(command)
    rec["status"] = status
    rec["round"] = int(round_idx)
    if reason:
        rec["reason"] = reason
    if auto:
        rec["auto"] = True
    return rec


def apply_config_change(cfg, key: str, value) -> Any:
    """Return ``cfg`` with the whitelisted dotted ``key`` replaced —
    the same coercion/validation path as the CLI's ``--set`` (so the
    control plane cannot set anything the CLI could not)."""
    if key not in CONFIG_WHITELIST:
        raise ValueError(f"config key {key!r} not whitelisted "
                         f"(serve accepts {sorted(CONFIG_WHITELIST)})")
    from dopt.run import apply_override

    want = CONFIG_WHITELIST[key]
    rendered = repr(want(value)) if want is float else str(int(value))
    return apply_override(cfg, f"{key}={rendered}")


def control_ledger_row(command: dict[str, Any],
                       round_idx: int) -> dict[str, Any]:
    """The fault-ledger row for one APPLIED command: worker is the
    membership target (fleet-level commands use -1), the action string
    encodes the payload — together with the base config this makes the
    ledger a complete replay script for the served run."""
    cmd = command["cmd"]
    worker = -1
    if cmd == "config":
        action = (f"applied_config_{command['key']}="
                  f"{command['value']}")
    elif cmd == "membership":
        worker = int(command["worker"])
        action = f"applied_membership_{command['action']}"
    elif cmd == "drain":
        action = ("applied_drain_restart" if command.get("restart")
                  else "applied_drain")
    else:
        action = f"applied_{cmd}"
    return {"round": int(round_idx), "worker": worker, "kind": "control",
            "action": action}


def control_event_fields(command: dict[str, Any], round_idx: int, *,
                         auto: bool = False) -> dict[str, Any]:
    """The telemetry ``control`` event payload for one applied
    command (None fields are dropped by ``make_event``)."""
    return {
        "round": int(round_idx),
        "cmd": str(command["cmd"]),
        "id": command.get("id"),
        "key": command.get("key"),
        "value": command.get("value"),
        "worker": command.get("worker"),
        "action": command.get("action"),
        "auto": True if auto else None,
    }


def replay_effects(records: Iterable[dict[str, Any]], *,
                   up_to_round: int) -> dict[str, Any]:
    """Fold the applied ledger into the daemon's resumable state:
    config overrides (in order), membership directives, the cadence
    override, admission-pause state, and the set of terminally
    processed command ids.  Records with ``round > up_to_round`` were
    applied at a boundary the checkpoint never reached (a hard kill
    between apply and save): they are EXCLUDED — the daemon re-ingests
    them from the queue and re-applies at the next boundary."""
    out: dict[str, Any] = {"config": [], "membership": [],
                           "checkpoint_every": None, "paused": False,
                           "processed": set(), "drained": False}
    for rec in records:
        if rec.get("status") == "rejected":
            out["processed"].add(str(rec.get("id")))
            continue
        if rec.get("status") != "applied":
            continue
        r = int(rec.get("round", 0))
        if r > up_to_round:
            continue
        out["processed"].add(str(rec.get("id")))
        cmd = rec.get("cmd")
        if cmd == "config":
            if rec["key"] == "checkpoint_every":
                out["checkpoint_every"] = int(rec["value"])
            else:
                out["config"].append((r, rec["key"], rec["value"]))
        elif cmd == "membership":
            out["membership"].append(
                (r, int(rec["worker"]), rec["action"] == "join"))
        elif cmd == "pause":
            out["paused"] = True
        elif cmd == "resume":
            out["paused"] = False
        elif cmd == "drain":
            out["drained"] = True
    # Ledger order is first-seen COMMAND order, but a crash-window
    # re-apply can move a command's effective round PAST a later
    # command's (its superseding record keeps its original position):
    # MembershipLog.add requires nondecreasing rounds, so sort by
    # round (stable — same-round directives keep ledger order).
    out["membership"].sort(key=lambda e: e[0])
    return out
