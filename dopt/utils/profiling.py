"""Tracing / profiling (absent in the reference — SURVEY §5).

The reference's only instrumentation is ``time.time()`` around
``run()`` printed as "Total Run Time" plus tqdm bars (servers.py:51,79;
simulators.py:115-137).  dopt provides:

* ``PhaseTimers`` — named wall-clock accumulators for the round phases
  (consensus vs local step vs eval vs host batch-planning); rounds/sec
  is a north-star metric so phase attribution is first-class.
* ``trace()`` — context manager wrapping ``jax.profiler`` to dump an
  XLA trace viewable in TensorBoard/Perfetto.

Note on async dispatch: jax returns before device work finishes, so a
``phase()`` context around a jit call measures dispatch only.  Use
``measure(name, fn, *args)`` to attribute device time — it blocks on
the function's result via ``block_until_ready``.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Any, Iterator

import jax


class PhaseTimers:
    """Accumulates wall-clock per named phase."""

    def __init__(self) -> None:
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        """Host wall-clock for the block (dispatch-only for jit calls —
        use ``measure`` to include device time)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def measure(self, name: str, fn, *args, **kwargs):
        """Run fn, block on its result, attribute the time to ``name``."""
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        self.totals[name] += time.perf_counter() - t0
        self.counts[name] += 1
        return out

    def summary(self) -> dict[str, dict[str, float]]:
        return {
            name: {
                "total_s": round(self.totals[name], 4),
                "count": self.counts[name],
                "mean_s": round(self.totals[name] / max(self.counts[name], 1), 5),
            }
            for name in self.totals
        }

    def report(self) -> str:
        rows = ["phase                total_s   count   mean_s"]
        for name, s in sorted(self.summary().items(),
                              key=lambda kv: -kv[1]["total_s"]):
            rows.append(f"{name:20s} {s['total_s']:8.3f} {s['count']:7d} {s['mean_s']:9.5f}")
        return "\n".join(rows)


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """XLA profiler trace (TensorBoard/Perfetto-viewable)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# ---------------------------------------------------------------------
# FLOP accounting (MFU meters for the benchmark harnesses)
# ---------------------------------------------------------------------

# Public per-chip peak throughput (bf16 matmul peak).  MFU for f32 runs
# is reported against the same bf16 peak so modes stay comparable — the
# hardware ceiling is the MXU's.
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e, bf16
    "TPU v5": 459e12,        # v5p, bf16
    "TPU v4": 275e12,
}


def device_peak_flops() -> tuple[str, float | None]:
    """(device_kind, bf16 peak FLOP/s or None when unknown, e.g. CPU)."""
    kind = jax.devices()[0].device_kind
    for k, v in PEAK_FLOPS.items():
        if kind.startswith(k):
            return kind, v
    return kind, None


def fwd_flops_per_sample(fn, params, input_shape, *, batch: int = 8,
                         dtype=None) -> float:
    """Forward-pass FLOPs per sample from XLA's compiled cost analysis.

    ``fn(params, x)`` is the forward callable (e.g. ``lambda p, x:
    model.apply({'params': p}, x)``).  Generic across the zoo — no
    per-model analytic tables — and counts what XLA actually lowers
    (convs at 2·MACs, elementwise, norms), so it is the right numerator
    for MFU accounting.  Uses a small batch and divides, which washes
    out fixed per-call ops."""
    import jax.numpy as jnp

    x = jnp.zeros((batch, *input_shape), dtype or jnp.float32)
    compiled = jax.jit(fn).lower(params, x).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else None
    if not ca or "flops" not in ca:
        # Some backends/jax versions return None or omit the key; NaN
        # lets callers (bench_suite) keep their throughput numbers and
        # skip the MFU fields instead of aborting the whole suite.
        return float("nan")
    return float(ca["flops"]) / batch


def train_flops_per_sample(fn, params, input_shape, *, batch: int = 8,
                           dtype=None) -> float:
    """Training FLOPs per sample ≈ 3 × forward (fwd + ~2× in backward)
    — the standard accounting used by the MFU literature."""
    return 3.0 * fwd_flops_per_sample(fn, params, input_shape, batch=batch,
                                      dtype=dtype)
