"""update_sharding="scatter": the sharded consensus/weight-update path.

Contract (ISSUE 5 tentpole):

* ``off`` (default) touches ZERO code paths — python-level gating, so
  baseline1/baseline3 programs stay byte-identical to pre-change.
* ``scatter`` agrees with the dense path to f32 summation order
  (allclose, NOT bit-equal: reduce-scatter reassociates the sum), and
  scatter-vs-scatter is bit-reproducible, blocked-exact and
  resume-exact.
* Ineligible compositions (robust layer, link faults/push-sum,
  staleness, compact, hybrid meshes) are rejected LOUDLY at trainer
  construction — never silently run a different experiment.  The
  comm_dtype/choco wire-treatment rejections were LIFTED by the
  communication substrate (tests/test_comm_substrate.py pins the
  composed behaviour).

Collective-level tests run on the 8-device virtual CPU mesh; engine
tests use the tiny synthetic MLP configs from ``test_engine``.  The
gossip parity/repro/blocked test is the tier-1 scatter signal; the
resume-exactness, faults-composition and federated engine tests are
marked ``slow`` (they run in the unfiltered suite) to keep the tier-1
sweep inside its 870s wall-clock budget.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dopt.parallel.collectives import (buckets_to_stacked, buckets_to_tree,
                                        hlo_collective_bytes,
                                        make_update_shard_spec,
                                        masked_average, masked_average_scatter,
                                        mix_dense, mix_shifts,
                                        mix_update_scatter, shift_comm_lanes,
                                        stacked_to_buckets)
from dopt.parallel.mesh import make_mesh, shard_worker_tree
from dopt.topology import build_mixing_matrices, coeffs_for_matrix

from tests.test_engine import _fed_cfg, _gossip_cfg


def _tree(w, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "a": jnp.asarray(rng.normal(size=(w, 5, 3)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(w, 7)).astype(np.float32)),
    }


def _flat(tree):
    return np.concatenate([np.ravel(np.asarray(x))
                           for x in jax.tree.leaves(jax.device_get(tree))])


# ---------------------------------------------------------------------
# Bucketing spec
# ---------------------------------------------------------------------

def test_spec_roundtrip_bit_exact():
    tree = _tree(8)
    # Tiny bucket budget forces multiple buckets; fold=8 forces padding
    # (22 elements → 24).
    spec = make_update_shard_spec(tree, fold=8, bucket_bytes=64)
    assert spec.num_buckets > 1
    assert spec.padded % spec.fold == 0
    sizes = [b - a for a, b in zip(spec.bounds, spec.bounds[1:])]
    assert all(s % spec.fold == 0 and s > 0 for s in sizes)
    buckets = stacked_to_buckets(tree, spec)
    assert [b.shape[1] for b in buckets] == sizes
    back = buckets_to_stacked(buckets, spec)
    for k in tree:
        assert np.array_equal(np.asarray(tree[k]), np.asarray(back[k]))
    # Single-tree (theta) inverse: feed per-worker rows of a known tree.
    one = {k: v[3] for k, v in tree.items()}
    ob = [b[3] for b in stacked_to_buckets(tree, spec)]
    back1 = buckets_to_tree(ob, spec)
    for k in one:
        assert np.array_equal(np.asarray(one[k]), np.asarray(back1[k]))


def test_spec_rejects_mixed_dtypes():
    tree = {"a": jnp.zeros((4, 3), jnp.float32),
            "b": jnp.zeros((4, 3), jnp.bfloat16)}
    with pytest.raises(ValueError, match="uniform leaf dtype"):
        make_update_shard_spec(tree, fold=4)


# ---------------------------------------------------------------------
# Scatter collectives vs ground truth
# ---------------------------------------------------------------------

def _np_mix(w_matrix, tree):
    return {k: np.tensordot(w_matrix, np.asarray(v),
                            axes=[[1], [0]]).astype(np.float32)
            for k, v in tree.items()}


def test_mix_scatter_matches_numpy(devices):
    mesh = make_mesh(8)
    mm = build_mixing_matrices("circle", "metropolis", 8)
    tree = shard_worker_tree(_tree(8), mesh)
    spec = make_update_shard_spec(tree, fold=mesh.size, bucket_bytes=64)
    want = _np_mix(mm.matrices[0], tree)
    # Dense reduce-scatter formulation.
    out = jax.jit(lambda t, w: mix_update_scatter(t, w, mesh, spec))(
        tree, mm.matrices[0])
    for k in tree:
        np.testing.assert_allclose(np.asarray(out[k]), want[k],
                                   rtol=2e-5, atol=1e-6)
    # Sharded circulant contraction (the ppermute path over buckets).
    ids = (0, 1, 7)
    coeffs = coeffs_for_matrix(mm.matrices[0], ids)
    out2 = jax.jit(lambda t, c: mix_update_scatter(t, c, mesh, spec,
                                                   shift_ids=ids))(
        tree, coeffs)
    for k in tree:
        np.testing.assert_allclose(np.asarray(out2[k]), want[k],
                                   rtol=2e-5, atol=1e-6)


def test_masked_average_scatter_matches_dense(devices):
    mesh = make_mesh(8)
    tree = shard_worker_tree(_tree(8), mesh)
    spec = make_update_shard_spec(tree, fold=mesh.size, bucket_bytes=64)
    mask = np.array([1, 0, 1, 1, 0, 1, 1, 1], np.float32)
    got = jax.jit(lambda t: masked_average_scatter(t, mask, mesh, spec))(tree)
    want = masked_average(tree, mask)
    for k in tree:
        assert got[k].shape == tree[k].shape[1:]
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=2e-5, atol=1e-6)


def test_scatter_requires_flat_mesh(devices):
    from dopt.parallel.multihost import make_hybrid_mesh

    mesh = make_hybrid_mesh(2)
    tree = shard_worker_tree(_tree(8), mesh)
    spec = make_update_shard_spec(tree, fold=8)
    with pytest.raises(ValueError, match="hybrid"):
        masked_average_scatter(tree, np.ones(8, np.float32), mesh, spec)


# ---------------------------------------------------------------------
# Compiled-HLO collective byte accounting (VERDICT round-5 open ask:
# the folded-lane ICI byte-savings claim, counted from the compiled
# program instead of asserted in a docstring)
# ---------------------------------------------------------------------

def test_hlo_collective_bytes_parser():
    txt = """
  %x = f32[4,7]{1,0} add(f32[4,7] %a, f32[4,7] %b)
  %ag = f32[32,7]{1,0} all-gather(f32[4,7]{1,0} %x), dimensions={0}
  %cp = f32[1,7]{1,0} collective-permute(f32[1,7]{1,0} %y), source_target_pairs={{0,1}}
  %ags = (f32[4,7], f32[32,7]) all-gather-start(f32[4,7] %x)
  %agd = f32[32,7]{1,0} all-gather-done((f32[4,7], f32[32,7]) %ags)
"""
    got = hlo_collective_bytes(txt)
    # plain all-gather result 32*7*4 = 896; the start op counts its
    # (operand, result) tuple once (1008) and the done op not at all.
    assert got["all-gather"] == 896 + (112 + 896)
    assert got["collective-permute"] == 28
    assert got["all-reduce"] == 0
    assert got["total"] == got["all-gather"] + got["collective-permute"]


def test_shift_vs_dense_compiled_collective_bytes(devices):
    """The mix_shifts docstring claim, measured: a folded ring (n=32 on
    8 devices) ships 2 single-lane shards per device per round through
    ``collective-permute`` while the dense path all-gathers the full
    fleet — counted from the compiled HLO of both programs."""
    n, d = 32, 8
    mesh = make_mesh(d)
    lanes = n // d
    mm = build_mixing_matrices("circle", "metropolis", n)
    ids = (0, 1, n - 1)
    coeffs = coeffs_for_matrix(mm.matrices[0], ids)
    tree = shard_worker_tree(_tree(n, seed=3), mesh)
    per_worker_bytes = sum(
        int(np.prod(x.shape[1:])) * x.dtype.itemsize
        for x in jax.tree.leaves(tree))

    f_shift = jax.jit(lambda t, c: mix_shifts(t, ids, c, mesh))
    b_shift = hlo_collective_bytes(
        f_shift.lower(tree, coeffs).compile().as_text())
    f_dense = jax.jit(lambda t, w: mix_dense(t, w, mesh))
    b_dense = hlo_collective_bytes(
        f_dense.lower(tree, mm.matrices[0]).compile().as_text())

    # Shift path: ppermute only, carrying exactly the lane unions the
    # consuming shifts need — shift_comm_lanes(...) worker-lane shards.
    shipped = shift_comm_lanes(ids, lanes, d)
    assert shipped == 2          # the folded-ring headline number
    assert b_shift["all-gather"] == 0
    assert b_shift["collective-permute"] == shipped * per_worker_bytes
    # Dense path: all_gather materialises all n lanes on every device.
    assert b_dense["collective-permute"] == 0
    assert b_dense["all-gather"] == n * per_worker_bytes
    # The byte-savings claim itself: n gathered lanes vs `shipped`.
    assert b_dense["total"] == (n // shipped) * b_shift["total"]


# ---------------------------------------------------------------------
# Engine-level parity / determinism / resume
# ---------------------------------------------------------------------

def _gossip_sc(us="scatter", **kw):
    base = _gossip_cfg(**kw)
    return base.replace(gossip=dataclasses.replace(
        base.gossip, update_sharding=us, update_bucket_mb=0.05))


def test_gossip_scatter_parity_repro_blocked(devices):
    from dopt.engine import GossipTrainer

    t_off = GossipTrainer(_gossip_sc("off"))
    h_off = t_off.run(rounds=3)
    t_sc = GossipTrainer(_gossip_sc())
    h_sc = t_sc.run(rounds=3)
    # Dense-parity: f32 allclose (reduce-scatter reassociates the sum,
    # so bit-equality vs dense is not required).
    np.testing.assert_allclose(_flat(t_off.params), _flat(t_sc.params),
                               rtol=2e-5, atol=1e-6)
    for ra, rb in zip(h_off.rows, h_sc.rows):
        for k in ra:
            if isinstance(ra[k], float):
                assert abs(ra[k] - rb[k]) < 5e-4, (k, ra[k], rb[k])
    # Run-to-run bit-reproducibility of the scatter path.
    t_sc2 = GossipTrainer(_gossip_sc())
    t_sc2.run(rounds=3)
    assert np.array_equal(_flat(t_sc.params), _flat(t_sc2.params))
    # Blocked execution composes: same bits as per-round.
    t_blk = GossipTrainer(_gossip_sc())
    t_blk.run(rounds=3, block=3)
    assert np.array_equal(_flat(t_sc.params), _flat(t_blk.params))


@pytest.mark.slow
def test_gossip_scatter_resume_exact(devices, tmp_path):
    from dopt.engine import GossipTrainer

    cont = GossipTrainer(_gossip_sc())
    cont.run(rounds=4)
    killed = GossipTrainer(_gossip_sc())
    killed.run(rounds=2)
    killed.save(tmp_path / "ck")
    resumed = GossipTrainer(_gossip_sc())
    resumed.restore(tmp_path / "ck")
    resumed.run(rounds=2)
    assert np.array_equal(_flat(cont.params), _flat(resumed.params))
    assert cont.history.rows == resumed.history.rows


@pytest.mark.slow
def test_gossip_scatter_composes_with_faults_blocked(devices):
    """Crash/straggler faults stay data under scatter (repaired
    matrices feed the same reduce-scatter), so faulted scatter runs
    keep the fused blocked scan bit-exact."""
    from dopt.config import FaultConfig
    from dopt.engine import GossipTrainer

    cfg = _gossip_sc().replace(
        faults=FaultConfig(crash=0.3, straggle=0.3, straggle_frac=0.5))
    a = GossipTrainer(cfg)
    a.run(rounds=4)
    b = GossipTrainer(cfg)
    b.run(rounds=4, block=4)
    assert np.array_equal(_flat(a.params), _flat(b.params))
    assert a.history.faults == b.history.faults


def _fed_sc(us="scatter", **kw):
    base = _fed_cfg(**kw)
    return base.replace(federated=dataclasses.replace(
        base.federated, update_sharding=us, update_bucket_mb=0.05))


@pytest.mark.slow
def test_federated_scatter_parity_and_repro(devices):
    from dopt.engine import FederatedTrainer

    t_off = FederatedTrainer(_fed_sc("off"))
    t_off.run(rounds=3)
    t_sc = FederatedTrainer(_fed_sc())
    t_sc.run(rounds=3)
    np.testing.assert_allclose(_flat(t_off.theta), _flat(t_sc.theta),
                               rtol=2e-5, atol=1e-6)
    t_sc2 = FederatedTrainer(_fed_sc())
    t_sc2.run(rounds=3, block=3)   # blocked scatter, same bits
    assert np.array_equal(_flat(t_sc.theta), _flat(t_sc2.theta))


# ---------------------------------------------------------------------
# Eligibility: ineligible compositions are rejected loudly
# ---------------------------------------------------------------------

def test_scatter_rejections(devices):
    from dopt.config import FaultConfig, RobustConfig
    from dopt.engine import FederatedTrainer, GossipTrainer

    with pytest.raises(ValueError, match="unknown update_sharding"):
        GossipTrainer(_gossip_sc("sliced"))
    with pytest.raises(ValueError, match="robust layer"):
        GossipTrainer(_gossip_sc().replace(
            robust=RobustConfig(clip_radius=1.0)))
    with pytest.raises(ValueError, match="link faults"):
        GossipTrainer(_gossip_sc().replace(
            faults=FaultConfig(msg_drop=0.2)))
    # comm_dtype × scatter used to be rejected here; the communication
    # substrate made scatter the wire path for dtype narrowing, so the
    # composition now constructs.
    GossipTrainer(_gossip_sc(
        gossip={"comm_dtype": "bfloat16", "update_sharding": "scatter"}))
    with pytest.raises(ValueError, match="no dense mixing"):
        GossipTrainer(_gossip_sc(
            gossip={"algorithm": "nocons", "update_sharding": "scatter"}))
    fed = _fed_sc()
    with pytest.raises(ValueError, match="masked-MEAN"):
        FederatedTrainer(fed.replace(
            robust=RobustConfig(aggregator="median")))
    with pytest.raises(ValueError, match="staleness"):
        FederatedTrainer(fed.replace(
            federated=dataclasses.replace(fed.federated, staleness_max=2),
            faults=FaultConfig(msg_delay=0.2)))
    with pytest.raises(ValueError, match="compact"):
        FederatedTrainer(fed.replace(
            federated=dataclasses.replace(fed.federated, compact=True)))


# ---------------------------------------------------------------------
# Phase attribution + bench hardening helpers (pure units)
# ---------------------------------------------------------------------

def test_phase_classification():
    from dopt.utils.profiling import classify_phase, phase_totals

    assert classify_phase("convolution", "jit(f)/conv_general") == "conv"
    # dtype casts must NOT count as conv — the bf16 leg is full of
    # convert ops and conv_fraction is the acceptance metric.
    assert classify_phase("convert", "jit(f)/convert.5") == "other"
    assert classify_phase("all-gather", None) == "comm"
    assert classify_phase("fusion", "jit(f)/dopt_mix/dot_general") == "comm"
    assert classify_phase("fusion",
                          "jit(f)/dopt_update/sub") == "update"
    # update tag wins over the enclosing mix scope (the sharded update
    # nests inside the scatter collective's scope).
    assert classify_phase(
        "fusion", "jit(f)/dopt_mix/dopt_update/div") == "update"
    assert classify_phase("fusion", "jit(f)/add") == "other"
    got = phase_totals([("convolution", "conv", 60.0),
                        ("all-gather", "ag", 20.0),
                        ("fusion", "x/dopt_update/sub", 20.0)])
    assert got["conv_fraction"] == pytest.approx(0.6)
    assert got["comm_fraction"] == pytest.approx(0.2)
    assert got["update_fraction"] == pytest.approx(0.2)
    assert got["other_us"] == 0.0


def test_bench_trimmed_stats():
    import bench

    # >= 4 samples: min and max are discarded before median/spread.
    med, spread, kept = bench._trimmed_stats([10.0, 9.9, 10.1, 0.1, 50.0])
    assert kept == [9.9, 10.0, 10.1]
    assert med == 10.0
    assert spread == pytest.approx(100.0 * 0.2 / 10.0)
    # < 4 samples: plain median/spread.
    med2, spread2, kept2 = bench._trimmed_stats([2.0, 4.0])
    assert med2 == 3.0 and kept2 == [2.0, 4.0]
    assert spread2 == pytest.approx(100.0 * 2.0 / 3.0)
