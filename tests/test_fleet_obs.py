"""Fleet-scope observability (PR 15): cross-process aggregation, SLO
latency histograms, on-demand profiling, and the stream differ.

Synthetic-stream tests cover the histogram math (bucket boundaries,
JSON state round-trip, Prometheus histogram exposition), the
``latency`` event schema, the monitor's latency/lag accounting, the
``FleetAggregator`` (merge ordering on the round watermark,
cross-process divergence detection with both events reported,
per-process torn-tail tolerance), the fleet endpoint's 503 contract
(Retry-After + JSON body) and ``dopt.obs.diff``.  One real-engine test
pins the profiling guarantee: arming ``/admin/profile`` mid-run writes
a loadable Chrome trace while History, fault ledger and canonical
stream stay bit-identical to an unprofiled run.
"""

from __future__ import annotations

import json
import urllib.request
from pathlib import Path
from types import SimpleNamespace

import pytest

from dopt.obs import (HealthMonitor, JsonlSink, LatencyHistogram,
                      PrometheusSink, make_event, summarize_latency_events,
                      validate_event)
from dopt.obs.aggregate import (FleetAggregator, FleetMetricsServer,
                                fleet_metric_paths)
from dopt.obs.diff import first_divergence
from dopt.obs.diff import main as diff_main
from dopt.obs.latency import DEFAULT_BUCKETS

REPO = Path(__file__).resolve().parent.parent


# ------------------------------------------------------- histogram math

def test_histogram_bucket_boundaries_and_counts():
    h = LatencyHistogram(bounds=(0.01, 0.1, 1.0))
    for v in (0.0, 0.01, 0.05, 0.5, 2.0):
        h.observe(v)
    # 0.0 and 0.01 land in (0, 0.01]; 0.05 in (0.01, 0.1]; 0.5 in
    # (0.1, 1.0]; 2.0 overflows to +Inf.
    assert h.counts == [2, 1, 1, 1]
    assert h.count == 5 and h.min == 0.0 and h.max == 2.0
    with pytest.raises(ValueError, match="finite"):
        h.observe(-1.0)
    with pytest.raises(ValueError, match="increasing"):
        LatencyHistogram(bounds=(1.0, 1.0))


def test_histogram_quantiles_and_summary():
    h = LatencyHistogram()
    for _ in range(99):
        h.observe(0.02)
    h.observe(50.0)
    s = h.summary()
    assert s["count"] == 100
    assert 0.01 <= s["p50"] <= 0.025       # inside 0.02's bucket
    # The 99th of 100 samples is still the 0.02 mass; only past it
    # does the estimate jump into the outlier's bucket.
    assert s["p99"] <= 0.025
    assert h.quantile(0.999) > 1.0
    assert s["min"] == 0.02 and s["max"] == 50.0
    assert LatencyHistogram().summary()["p50"] is None


def test_histogram_state_json_round_trip_and_merge():
    a, b = LatencyHistogram(), LatencyHistogram()
    for v in (0.001, 0.3, 7.0):
        a.observe(v)
    b.observe(0.3)
    st = json.loads(json.dumps(a.state()))
    a2 = LatencyHistogram.from_state(st)
    assert a2.counts == a.counts and a2.summary() == a.summary()
    a2.merge(b)
    assert a2.count == 4 and a2.min == 0.001 and a2.max == 7.0
    with pytest.raises(ValueError, match="bounds"):
        a2.merge(LatencyHistogram(bounds=(1.0, 2.0)))


def test_prometheus_histogram_exposition():
    p = PrometheusSink()
    for secs in (0.002, 0.002, 5.0):
        p.emit(make_event("latency", round=1, name="boundary_tick",
                          seconds=secs))
    out = p.render()
    assert "# TYPE dopt_latency_seconds histogram" in out
    # Cumulative le buckets, then the exact +Inf/sum/count triplet.
    assert ('dopt_latency_seconds_bucket{name="boundary_tick",'
            'le="+Inf"} 3') in out
    assert 'dopt_latency_seconds_count{name="boundary_tick"} 3' in out
    assert 'dopt_latency_seconds_sum{name="boundary_tick"}' in out
    # Cumulative counts never decrease across the le series.
    counts = [int(line.rsplit(" ", 1)[1]) for line in out.splitlines()
              if line.startswith("dopt_latency_seconds_bucket")]
    assert counts == sorted(counts)
    assert len(counts) == len(DEFAULT_BUCKETS) + 1


def test_latency_event_schema():
    validate_event(make_event("latency", round=0, name="checkpoint_save",
                              seconds=0.25))
    with pytest.raises(ValueError, match="seconds"):
        validate_event(make_event("latency", round=0, name="x",
                                  seconds=float("nan")))
    with pytest.raises(ValueError, match="name"):
        validate_event({"v": 1, "kind": "latency", "ts": 1.0, "round": 0,
                        "seconds": 0.1})


def test_summarize_latency_events_skips_garbage():
    evs = [make_event("latency", round=0, name="a", seconds=0.1),
           make_event("round", round=0, engine="g", metrics={}),
           {"kind": "latency", "name": "a", "seconds": "nope"},
           make_event("latency", round=1, name="a", seconds=0.3)]
    s = summarize_latency_events(evs)
    assert s["a"]["count"] == 2 and s["a"]["max"] == 0.3


# ------------------------------------------- monitor latency + lag

def _round_ev(t, loss=1.0):
    return make_event("round", round=t, engine="gossip",
                      metrics={"avg_train_loss": loss})


def test_monitor_accumulates_latency_and_reports():
    mon = HealthMonitor()
    mon.feed([make_event("run", engine="gossip", name="t", round=0),
              _round_ev(0),
              make_event("latency", round=0, name="boundary_tick",
                         seconds=0.01),
              make_event("latency", round=0, name="boundary_tick",
                         seconds=0.02)])
    rep = mon.report()
    assert rep.latency["boundary_tick"]["count"] == 2
    assert rep.latency["boundary_tick"]["p50"] is not None
    assert mon.lag_seconds() is not None and mon.lag_seconds() < 120
    # State round-trips the histograms AND the staleness meters.
    st = json.loads(json.dumps(mon.state()))
    mon2 = HealthMonitor(state=st)
    assert mon2.report().latency == rep.latency
    assert mon2.last_event_ts == mon.last_event_ts
    assert HealthMonitor().lag_seconds() is None


def test_monitor_measures_alert_latency_on_fire():
    # loss_nonfinite fires when the loss goes null after a finite one;
    # an ATTACHED (live fan-out) monitor self-observes the alert's
    # latency vs the triggering round bundle's ts and forwards the
    # latency event to the other sinks.
    from dopt.obs import MemorySink, Telemetry

    mem = MemorySink()
    mon = HealthMonitor().attach(Telemetry([mem]))
    mon.feed([make_event("run", engine="gossip", name="t", round=0),
              _round_ev(0, loss=1.0)])
    fired = mon.feed([_round_ev(1, loss=None)])
    assert [a["rule"] for a in fired] == ["loss_nonfinite"]
    s = mon.report().latency["alert_latency"]
    assert s["count"] == 1 and 0.0 <= s["max"] < 60.0
    assert mem.events[-1]["name"] == "alert_latency"
    # A tail/replay-fed monitor (no telemetry) must NOT self-measure:
    # "alert now minus round then" would report poll cadence, not
    # alert latency (it still folds embedded latency events).
    cold = HealthMonitor()
    cold.feed([make_event("run", engine="gossip", name="t", round=0),
               _round_ev(0, loss=1.0)])
    cold.feed([_round_ev(1, loss=None)])
    assert "alert_latency" not in cold.report().latency


# --------------------------------------------------- fleet aggregation

def _bundle(t, *, lanes=8.0, latency=None, engine="gossip"):
    evs = [make_event("gauge", round=t, name="participating_lanes",
                      value=lanes, engine=engine),
           make_event("round", round=t, engine=engine,
                      metrics={"avg_train_loss": 1.0 - 0.01 * t})]
    if latency is not None:
        evs.append(make_event("latency", round=t, name="boundary_tick",
                              seconds=latency))
    return evs


def _write_stream(path: Path, events) -> None:
    path.write_text("".join(json.dumps(e) + "\n" for e in events))


def _fleet_dir(tmp_path, rounds=5, mutate=None):
    hdr = make_event("run", engine="gossip", name="t", round=0, workers=8)
    a = [hdr] + [e for t in range(rounds)
                 for e in _bundle(t, latency=0.01)]
    b = [hdr] + [e for t in range(rounds)
                 for e in _bundle(t, latency=0.03)]
    if mutate is not None:
        mutate(b)
    _write_stream(tmp_path / "metrics.jsonl", a)
    _write_stream(tmp_path / "metrics-p1.jsonl", b)
    return tmp_path


def test_fleet_paths_discovery(tmp_path):
    (tmp_path / "metrics.jsonl").write_text("")
    (tmp_path / "metrics-p1.jsonl").write_text("")
    (tmp_path / "metrics-p2.jsonl").write_text("")
    assert sorted(fleet_metric_paths(tmp_path)) == [0, 1, 2]
    expect = fleet_metric_paths(tmp_path, 4)
    assert sorted(expect) == [0, 1, 2, 3]   # expected, not yet existing


def test_aggregator_merges_and_stamps_provenance(tmp_path):
    from dopt.obs import check_stream

    _fleet_dir(tmp_path)
    agg = FleetAggregator(tmp_path, num_processes=2)
    agg.poll()
    agg.flush_trailing()
    assert agg.divergence is None and agg.rounds_merged == 5
    summary = check_stream(agg.merged)
    assert summary["rounds"] == 5
    lat = [e for e in agg.merged if e["kind"] == "latency"]
    assert {e["process"] for e in lat} == {0, 1}
    # Deterministic events appear ONCE (the leader's copy).
    rounds = [e for e in agg.merged if e["kind"] == "round"]
    assert len(rounds) == 5 and all(e["process"] == 0 for e in rounds)


def test_aggregator_holds_merge_at_min_watermark(tmp_path):
    hdr = make_event("run", engine="gossip", name="t", round=0, workers=8)
    a = [hdr] + [e for t in range(6) for e in _bundle(t)]
    b = [hdr] + [e for t in range(2) for e in _bundle(t)]   # p1 behind
    _write_stream(tmp_path / "metrics.jsonl", a)
    _write_stream(tmp_path / "metrics-p1.jsonl", b)
    agg = FleetAggregator(tmp_path, num_processes=2)
    agg.poll()
    assert agg.rounds_merged == 2          # never past p1's watermark
    st = agg.stats()
    assert st["processes"][0]["sealed_ahead"] == 4
    assert st["fleet_round"] == 1
    # p1 catches up: the merge resumes without reprocessing.
    with open(tmp_path / "metrics-p1.jsonl", "a") as f:
        for t in range(2, 6):
            for e in _bundle(t):
                f.write(json.dumps(e) + "\n")
    agg.poll()
    assert agg.rounds_merged == 6 and agg.divergence is None


def test_aggregator_reports_first_divergence_with_both_events(tmp_path):
    def mutate(b):
        for e in b:
            if e["kind"] == "gauge" and e.get("round") == 3:
                e["value"] = 7.0
    _fleet_dir(tmp_path, mutate=mutate)
    agg = FleetAggregator(tmp_path, num_processes=2)
    agg.poll()
    d = agg.divergence
    assert d is not None and d["round"] == 3 and d["process"] == 1
    assert d["leader"]["value"] == 8.0 and d["other"]["value"] == 7.0
    assert agg.rounds_merged == 3          # merge stopped at the fault
    # Strict mode raises with the same record.
    from dopt.obs.aggregate import FleetDivergenceError

    agg2 = FleetAggregator(tmp_path, num_processes=2, strict=True)
    with pytest.raises(FleetDivergenceError) as ei:
        agg2.poll()
    assert ei.value.record["round"] == 3


def test_aggregator_divergence_on_round_sequence_skew(tmp_path):
    def mutate(b):
        # p1 skips round 2 entirely: its round sequence diverges.
        b[:] = [e for e in b if e.get("round") != 2]
    _fleet_dir(tmp_path, mutate=mutate)
    agg = FleetAggregator(tmp_path, num_processes=2)
    agg.poll()
    d = agg.divergence
    assert d is not None and "round sequence mismatch" in d["reason"]


def test_aggregator_tolerates_torn_tail_per_process(tmp_path):
    _fleet_dir(tmp_path)
    # Tear p1's final line mid-write: the tail holds, no divergence.
    raw = (tmp_path / "metrics-p1.jsonl").read_text().splitlines()
    (tmp_path / "metrics-p1.jsonl").write_text(
        "\n".join(raw[:-1]) + "\n" + raw[-1][:17])
    agg = FleetAggregator(tmp_path, num_processes=2)
    agg.poll()
    assert agg.divergence is None
    assert agg.rounds_merged == 5          # p1's last latency line torn
    # The writer finishes the line: consumed on the next poll.
    with open(tmp_path / "metrics-p1.jsonl", "a") as f:
        f.write(raw[-1][17:] + "\n")
    agg.poll()
    agg.flush_trailing()
    assert agg.divergence is None


def test_aggregator_clears_pending_on_file_shrink(tmp_path):
    """repair_tail on a resumed daemon SHRINKS a stream (orphans of an
    unsealed bundle dropped); the aggregator must drop its own pending
    copy of those orphans or the re-emitted bundle double-counts."""
    _fleet_dir(tmp_path, rounds=3)
    agg = FleetAggregator(tmp_path, num_processes=2)
    agg.poll()
    # p1 appends an orphan gauge (bundle never sealed)...
    orphan = make_event("gauge", round=3, name="participating_lanes",
                        value=8.0, engine="gossip")
    with open(tmp_path / "metrics-p1.jsonl", "a") as f:
        f.write(json.dumps(orphan) + "\n")
    agg.poll()
    # ...then "repair_tail" removes it and the resumed daemon re-emits
    # the whole bundle.
    raw = (tmp_path / "metrics-p1.jsonl").read_text().splitlines()
    (tmp_path / "metrics-p1.jsonl").write_text(
        "\n".join(raw[:-1]) + "\n")
    with open(tmp_path / "metrics-p1.jsonl", "a") as f:
        for e in _bundle(3):
            f.write(json.dumps(e) + "\n")
    with open(tmp_path / "metrics.jsonl", "a") as f:
        for e in _bundle(3):
            f.write(json.dumps(e) + "\n")
    agg.poll()
    assert agg.divergence is None, agg.divergence
    assert agg.rounds_merged == 4


def test_aggregator_resyncs_on_shrink_then_regrow(tmp_path):
    """repair_tail truncates a stream and the resumed daemon appends
    PAST the old byte offset before the next poll: size alone cannot
    see it, but the guard bytes changed — the aggregator must resync
    from byte 0 (skipping fleet-sealed rounds) instead of reading from
    mid-line and poisoning the merge with a ValueError."""
    _fleet_dir(tmp_path, rounds=3)
    agg = FleetAggregator(tmp_path, num_processes=2)
    agg.poll()
    assert agg.rounds_merged == 3
    # p1's tail is rewritten: drop its last bundle entirely, then
    # re-emit it plus two more rounds — by the next poll the file is
    # LONGER than the old offset.
    lines = (tmp_path / "metrics-p1.jsonl").read_text().splitlines()
    keep = lines[:-3]   # drop round 2's bundle (gauge+round+latency)
    regrown = keep + [json.dumps(e) for t in (2, 3, 4)
                      for e in _bundle(t, latency=0.05)]
    (tmp_path / "metrics-p1.jsonl").write_text(
        "\n".join(regrown) + "\n")
    with open(tmp_path / "metrics.jsonl", "a") as f:
        for t in (3, 4):
            for e in _bundle(t, latency=0.01):
                f.write(json.dumps(e) + "\n")
    agg.poll()
    agg.flush_trailing()
    assert agg.divergence is None, agg.divergence
    assert agg.rounds_merged == 5
    # Round 2 was fleet-sealed before the rewrite: its replayed copy
    # must not re-merge (no duplicate round events).
    rounds = [e["round"] for e in agg.merged if e["kind"] == "round"]
    assert rounds == [0, 1, 2, 3, 4]


def test_aggregator_cli_json(tmp_path, capsys):
    from dopt.obs.aggregate import main as agg_main

    _fleet_dir(tmp_path)
    merged = tmp_path / "merged.jsonl"
    rc = agg_main(["--state-dir", str(tmp_path), "--processes", "2",
                   "--merged-out", str(merged), "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0 and report["ok"] and report["divergence"] is None
    assert report["merged_check"]["rounds"] == 5
    assert merged.exists()
    evs = JsonlSink.read(merged)
    assert {e.get("process") for e in evs} == {0, 1}


def test_fleet_metrics_server_healthz_and_retry_after(tmp_path):
    def mutate(b):
        for e in b:
            if e["kind"] == "round" and e.get("round") == 4:
                e["metrics"] = {"avg_train_loss": 0.5}
    _fleet_dir(tmp_path, mutate=mutate)
    server = FleetMetricsServer(tmp_path, num_processes=2).start()
    try:
        port = server.port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            body = r.read().decode()
        assert "dopt_fleet_processes 2" in body
        assert "dopt_fleet_divergent 1" in body
        assert 'dopt_latency_seconds_bucket{name="boundary_tick"' in body
        # Diverged fleet: /healthz is 503 with Retry-After + JSON body.
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10)
            raise AssertionError("expected HTTP 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert e.headers.get("Retry-After") is not None
            payload = json.loads(e.read())
        assert payload["fleet"]["divergence"]["round"] == 4
        assert "lag_seconds" in payload
    finally:
        server.shutdown()


# --------------------------------------------------------- stream diff

def test_diff_identical_and_seeded_divergence(tmp_path, capsys):
    hdr = make_event("run", engine="gossip", name="t", round=0)
    evs = [hdr] + [e for t in range(4) for e in _bundle(t)]
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _write_stream(a, evs)
    _write_stream(b, evs)
    assert diff_main([str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert "identical" in out
    # Seeded mutation: flip one round metric — diff reports exactly it.
    mut = [json.loads(json.dumps(e)) for e in evs]
    for e in mut:
        if e["kind"] == "round" and e["round"] == 2:
            e["metrics"]["avg_train_loss"] = 9.9
    _write_stream(b, mut)
    assert diff_main([str(a), str(b), "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    d = report["divergence"]
    assert d["kind"] == "round" and d["round"] == 2
    assert d["a"]["metrics"]["avg_train_loss"] != \
        d["b"]["metrics"]["avg_train_loss"]
    # Prefix streams: the longer side is named.
    _write_stream(b, evs[:-2])
    assert diff_main([str(a), str(b)]) == 1
    assert first_divergence(evs, evs[:-2])["reason"].startswith(
        "stream b ends")


def test_diff_kinds_filter(tmp_path):
    evs = [_round_ev(0),
           make_event("latency", round=0, name="x", seconds=0.1)]
    other = [_round_ev(0),
             make_event("latency", round=0, name="x", seconds=0.9)]
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _write_stream(a, evs)
    _write_stream(b, other)
    # Latency differs but is non-deterministic: default diff passes...
    assert diff_main([str(a), str(b)]) == 0
    # ...and --all-kinds sees it.
    assert diff_main([str(a), str(b), "--all-kinds"]) == 1


# --------------------------------------------------- check / watch / serve

def test_check_state_dir_glob(tmp_path, capsys):
    from dopt.obs.check import main as check_main

    fleet = tmp_path / "run"
    fleet.mkdir()
    _write_stream(fleet / "metrics.jsonl",
                  [make_event("run", engine="g", name="t", round=0),
                   _round_ev(0)])
    _write_stream(fleet / "metrics-p1.jsonl",
                  [make_event("run", engine="g", name="t", round=0),
                   _round_ev(0)])
    assert check_main(["--state-dir", str(fleet), "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["checked"] == 2 and report["clean"]
    # One corrupt stream fails the whole invocation (shared exit code).
    (fleet / "metrics-p1.jsonl").write_text("not json\nstill not\n")
    assert check_main(["--state-dir", str(fleet), "--json"]) == 1
    report = json.loads(capsys.readouterr().out)
    oks = {f["path"]: f["ok"] for f in report["files"]}
    assert oks[str(fleet / "metrics.jsonl")] is True
    assert oks[str(fleet / "metrics-p1.jsonl")] is False
    assert check_main(["--state-dir", str(tmp_path / "empty")]) == 1


def test_obs_serve_healthz_lag_and_retry_after(tmp_path):
    from dopt.obs.serve import MetricsServer

    metrics = tmp_path / "metrics.jsonl"
    _write_stream(metrics,
                  [make_event("run", engine="g", name="t", round=0),
                   _round_ev(0, loss=1.0), _round_ev(1, loss=None)])
    server = MetricsServer(metrics, port=0).start()
    try:
        # loss going null after a finite value = loss_nonfinite
        # critical -> 503 now carries Retry-After + the lag fields.
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/healthz", timeout=10)
            raise AssertionError("expected HTTP 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert e.headers.get("Retry-After") is not None
            body = json.loads(e.read())
        assert body["verdict"] == "critical"
        assert isinstance(body["lag_seconds"], float)
        assert body["last_event_ts"] is not None
    finally:
        server.shutdown()


def test_watch_fleet_renders_processes_and_alert_provenance(tmp_path):
    from dopt.obs.watch import FleetWatchState

    def mutate(b):
        b.append(make_event("alert", round=4, rule="drop_rate",
                            severity="warn", message="x"))
    _fleet_dir(tmp_path, mutate=mutate)
    (tmp_path / "serve.json").write_text(json.dumps(
        {"status": "serving", "admin_port": 12345}))
    watch = FleetWatchState(str(tmp_path), processes=2)
    watch.poll()
    out = watch.render()
    assert "p0" in out and "p1" in out
    assert "admin :12345" in out
    assert "consistency ok" in out
    assert "ALERT [warn] p1 drop_rate @ round 4" in out
    assert not watch.critical()


# ----------------------------------------------- command-queue ts stamp

def test_command_queue_stamps_enqueue_ts(tmp_path):
    from dopt.serve.control import (CommandQueue, make_command,
                                    validate_command)

    q = CommandQueue(tmp_path / "commands.jsonl")
    cmd = q.submit(make_command("checkpoint", id="c1"))
    assert isinstance(cmd["ts"], float) and cmd["ts"] > 0
    cmds, rejects = q.poll()
    assert cmds[0]["ts"] == cmd["ts"] and not rejects
    with pytest.raises(ValueError, match="ts"):
        validate_command({"v": 1, "cmd": "checkpoint", "ts": -3})
    # Pre-stamped commands keep their own stamp (replayed scripts).
    cmd2 = q.submit({"v": 1, "cmd": "checkpoint", "id": "c2", "ts": 5.0})
    assert cmd2["ts"] == 5.0


# --------------------------------------- admin profile endpoint wiring

def test_admin_profile_endpoint_wiring():
    from dopt.serve.admin import AdminServer

    calls = {}

    def request_profile(rounds):
        if rounds == 0:
            raise ValueError("profile rounds must be in [1, 10000]")
        calls["rounds"] = rounds
        return {"pending_rounds": rounds, "active": None,
                "artifacts": []}

    daemon = SimpleNamespace(request_profile=request_profile,
                             profile_status=lambda: {
                                 "pending_rounds": 0, "active": None,
                                 "artifacts": ["x.trace.json"]})
    srv = AdminServer(daemon, port=0)
    try:
        code, body = srv._post("/admin/profile", {"rounds": 3})
        assert code == 202 and json.loads(body)["pending_rounds"] == 3
        assert calls["rounds"] == 3
        code, body = srv._post("/admin/profile", {"rounds": 0})
        assert code == 400 and "error" in json.loads(body)
        code, body, _ = srv._get("/admin/profile")
        assert code == 200
        assert json.loads(body)["artifacts"] == ["x.trace.json"]
    finally:
        srv._httpd.server_close()


# ------------------------------------ real-engine: profiling + latency

def _tiny_cfg(rounds=4):
    from dopt.config import (DataConfig, ExperimentConfig, GossipConfig,
                             ModelConfig, OptimizerConfig)

    return ExperimentConfig(
        name="fleet-obs-test", seed=7,
        data=DataConfig(dataset="synthetic", num_users=8, iid=True,
                        synthetic_train_size=256, synthetic_test_size=64),
        model=ModelConfig(model="mlp", input_shape=(28, 28, 1),
                          faithful=False),
        optim=OptimizerConfig(lr=0.1, momentum=0.5),
        gossip=GossipConfig(algorithm="dsgd", topology="circle",
                            mode="metropolis", rounds=rounds, local_ep=1,
                            local_bs=32))


def test_profile_bit_identity_and_slo_latencies(tmp_path):
    """The tentpole acceptance pin, in-process: a served run with
    profiling armed mid-run writes a loadable Chrome trace (device
    events + host spans) while History, fault ledger and canonical
    stream stay bit-identical to an unprofiled run; both runs stream
    the SLO latency channel and the drain artifact summarizes it."""
    from dopt.obs import canonical, check_stream
    from dopt.serve import CommandQueue, ServeDaemon, make_command

    def leg(name, profile):
        d = tmp_path / name
        CommandQueue(d / "commands.jsonl").submit(
            make_command("config", key="optim.lr", value=0.05,
                         at_round=2, id="lr"))
        daemon = ServeDaemon(_tiny_cfg(), d, checkpoint_every=2,
                             max_rounds=4, admin_port=None).start()
        if profile:
            daemon.request_profile(2)
        assert daemon.serve() == 0
        return daemon, JsonlSink.read(d / "metrics.jsonl"), \
            json.loads((d / "final.json").read_text())

    da, ev_a, final_a = leg("plain", profile=False)
    db, ev_b, final_b = leg("profiled", profile=True)

    # Bit-identity: profiling must not perturb anything deterministic.
    assert canonical(ev_a) == canonical(ev_b)
    assert db.trainer.history.rows == da.trainer.history.rows
    assert db.trainer.history.faults == da.trainer.history.faults
    check_stream(ev_a)
    check_stream(ev_b)

    # SLO latency channel: events in the stream, summary in final.json.
    names = {e["name"] for e in ev_a if e["kind"] == "latency"}
    assert {"boundary_tick", "command_apply", "checkpoint_save",
            "checkpoint_restore"} <= names, names
    for key in ("boundary_tick", "command_apply", "checkpoint_save"):
        s = final_a["slo"][key]
        assert s["count"] >= 1 and isinstance(s["p50"], float)
        assert isinstance(s["p99"], float)

    # The profile artifact: one loadable Chrome trace, device events
    # merged with the host span track.
    assert len(final_b["profiles"]) == 1
    trace = json.loads(Path(final_b["profiles"][0]).read_text())
    events = trace["traceEvents"]
    assert len(events) > 0
    assert any(e.get("pid") == 900_000 for e in events), \
        "host spans missing from the merged trace"
    assert final_a["profiles"] == []
    # Double-arming is refused.
    db2 = ServeDaemon(_tiny_cfg(), tmp_path / "plain2",
                      admin_port=None)
    db2._profile_pending = 3
    with pytest.raises(ValueError, match="already armed"):
        db2.request_profile(1)


def test_follower_stream_naming_and_rules_file(tmp_path):
    from dopt.serve import ServeDaemon, serve_rules

    d = ServeDaemon(_tiny_cfg(), tmp_path, process_id=1,
                    num_processes=2, admin_port=None)
    assert d.metrics_path.name == "metrics-p1.jsonl"
    assert not d.is_leader
    d0 = ServeDaemon(_tiny_cfg(), tmp_path, admin_port=None)
    assert d0.metrics_path.name == "metrics.jsonl"
    # serve_rules(specs=...) replaces the stock set but ALWAYS appends
    # the escalated auto-pause rule.
    rules = serve_rules(specs=[{"rule": "drop_rate", "max_rate": 0.02,
                                "window": 4, "min_rounds": 2}])
    assert [r.name for r in rules] == ["drop_rate", "drop_rate_critical"]
    assert rules[-1].severity == "critical"
