"""Test bootstrap: force an 8-device virtual CPU platform.

This is the TPU-world answer to "test distributed without a cluster"
(SURVEY §4): jax's ``--xla_force_host_platform_device_count`` gives N
fake devices on the host, so every mesh/collective codepath runs under
pytest exactly as it would on an N-chip slice.

Note the axon sitecustomize pins ``jax_platforms`` to the TPU tunnel at
interpreter startup; ``jax.config.update`` after import wins, and must
happen before any backend is initialised.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _no_real_data_dir(monkeypatch):
    """Synthetic-fallback tests must not pick up a machine-local dataset
    directory via $DOPT_DATA_DIR."""
    monkeypatch.delenv("DOPT_DATA_DIR", raising=False)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual CPU devices, got {devs}"
    return devs
