"""Sequence-parallel LM training — the long-context substrate as a
driveable component.

The reference has no attention and no sequence axis anywhere (SURVEY
§2.3: 2-layer CNNs on small images), so nothing here is owed for parity;
this engine exists so ``dopt.parallel.sequence`` (ring attention via
``lax.ppermute`` KV rotation; Ulysses via ``all_to_all`` head
resharding) is a trained component rather than a tested demo:
``python -m dopt.run --preset seqlm`` trains a decoder-only
``TransformerLM`` with the SEQUENCE axis sharded over the mesh.

Design (TPU-first):

* One 1-D mesh over the sequence axis (``make_seq_mesh``); token
  batches [B, L] are placed with L sharded, parameters replicated.
  Every position-wise op (embeddings, MLPs, LayerNorm, logits) runs on
  the local L/D shard under XLA SPMD with zero communication; only
  attention crosses shards, through the injected ``attn_fn``.
* The next-token shift ``logits[:, :-1] vs tokens[:, 1:]`` is written
  in the global view; XLA inserts the one-position halo exchange.
* Training data is a deterministic synthetic order-1 Markov token
  stream (seeded sparse transition table): a next-token model can cut
  loss far below the uniform baseline exactly when it learns the
  transitions, so loss-goes-down is a meaningful signal, offline.
* SGD + momentum (the framework's one optimizer) on the mean CE.

The trainer exposes the same surface as the other engines (``run``,
``history``, ``total_time``, ``save``/``restore``, ``timers``) so the
CLI, checkpoint, and plotting machinery drive it unchanged.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from dopt.config import ExperimentConfig
from dopt.models import build_model, count_params
from dopt.optim import SGDState, sgd_step
from dopt.parallel.sequence import (SEQ_AXIS, make_seq_mesh, ring_attention,
                                    ulysses_attention)
from dopt.utils.metrics import History
from dopt.utils.profiling import PhaseTimers


def markov_token_stream(vocab: int, n_tokens: int, *, seed: int,
                        branching: int = 4) -> np.ndarray:
    """Deterministic synthetic corpus: an order-1 Markov chain where
    each token has ``branching`` permitted successors (seeded uniform
    choice among them).  Perfect next-token prediction reaches
    ``log(branching)`` nats; an untrained model sits at ``log(vocab)``
    — the gap is what training closes."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, 94_227]))
    table = np.stack([rng.choice(vocab, branching, replace=False)
                      for _ in range(vocab)])
    out = np.empty(n_tokens, np.int32)
    out[0] = rng.integers(vocab)
    draws = rng.integers(branching, size=n_tokens)
    for t in range(1, n_tokens):
        out[t] = table[out[t - 1], draws[t]]
    return out


class SeqLMTrainer:
    """Train ``TransformerLM`` with sequence-parallel attention."""

    def __init__(self, cfg: ExperimentConfig, *, mesh_devices: int | None = None):
        if cfg.seqlm is None:
            raise ValueError("cfg.seqlm must be set for SeqLMTrainer")
        s = cfg.seqlm
        if s.attn not in ("ring", "ulysses", "dense"):
            raise ValueError(
                f"unknown attn {s.attn!r}; one of ring|ulysses|dense")
        from dopt.engine.local import validate_optimizer

        validate_optimizer(cfg)
        self.cfg = cfg
        self.step = 0
        self.history = History(cfg.name)
        self.timers = PhaseTimers()

        n = mesh_devices if mesh_devices is not None else cfg.mesh_devices
        self.mesh = make_seq_mesh(n)
        d = self.mesh.size
        if s.attn == "dense" and d != 1:
            raise ValueError(
                "attn='dense' is the single-device path; use ring/ulysses "
                f"on a {d}-device mesh")
        if s.seq_len % d:
            raise ValueError(f"seq_len {s.seq_len} not divisible by the "
                             f"{d}-device mesh")
        if s.attn == "ulysses" and s.heads % d:
            raise ValueError(f"ulysses needs heads ({s.heads}) divisible by "
                             f"the mesh size ({d})")

        mesh = self.mesh
        if s.kv_chunk and s.attn != "ring":
            raise ValueError("kv_chunk only applies to attn='ring'")
        if s.attn == "ring":
            kv_chunk = s.kv_chunk or None
            attn_fn = lambda q, k, v: ring_attention(q, k, v, mesh,
                                                     causal=True,
                                                     kv_chunk=kv_chunk)
        elif s.attn == "ulysses":
            attn_fn = lambda q, k, v: ulysses_attention(q, k, v, mesh,
                                                        causal=True)
        else:
            attn_fn = None  # model falls back to dense causal attention

        self.model = build_model(
            "transformer", num_classes=s.vocab,
            dtype=cfg.model.compute_dtype,
        ).clone(dim=s.dim, depth=s.depth, heads=s.heads, max_len=s.seq_len)

        # Data: one resident token stream, sliced into [B, L] windows by
        # a deterministic per-step plan.
        # The stream stays HOST-side (numpy): batch assembly is pure
        # host slicing + one device_put per step; a device-resident
        # stream would force a device->host sync per window gather.
        self._stream = markov_token_stream(
            s.vocab, max(s.batch * s.seq_len * 8, 65_536), seed=cfg.seed)
        self._n_windows = len(self._stream) - s.seq_len - 1

        key = jax.random.key(cfg.seed)
        params = self.model.init(key, jnp.zeros((1, s.seq_len), jnp.int32),
                                 attn_fn=attn_fn)["params"]
        self.param_count = count_params(params)
        # Params replicated; token batches sequence-sharded.
        from jax.sharding import NamedSharding, PartitionSpec as P

        self._tok_sharding = NamedSharding(mesh, P(None, SEQ_AXIS))
        rep = NamedSharding(mesh, P())
        self.params = jax.device_put(params, rep)
        self.momentum = jax.device_put(
            jax.tree.map(np.zeros_like, jax.device_get(params)), rep)

        lr, mu = cfg.optim.lr, cfg.optim.momentum
        apply_fn = self.model.apply

        def loss_fn(p, tokens):
            logits = apply_fn({"params": p}, tokens, attn_fn=attn_fn)
            logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32))
            tgt = tokens[:, 1:]
            nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)
            return nll.mean()

        def train_step(p, m, tokens):
            loss, g = jax.value_and_grad(loss_fn)(p, tokens)
            p, st = sgd_step(p, SGDState(m), g, lr=lr, momentum=mu)
            return p, st.momentum, loss

        self._train_step = jax.jit(train_step, donate_argnums=(0, 1))
        self._rng = np.random.default_rng(
            np.random.SeedSequence([cfg.seed, 777_001]))

    def _batch(self) -> jnp.ndarray:
        s = self.cfg.seqlm
        starts = self._rng.integers(self._n_windows, size=s.batch)
        toks = np.stack([self._stream[a:a + s.seq_len] for a in starts])
        return jax.device_put(toks, self._tok_sharding)

    def run(self, rounds: int | None = None, steps: int | None = None) -> History:
        """Train ``steps`` steps (``rounds`` is accepted as an alias so
        the CLI driver's --rounds flag works unchanged)."""
        s = self.cfg.seqlm
        n = steps if steps is not None else (rounds if rounds is not None
                                             else s.steps)
        t0 = time.time()  # dopt: allow-wallclock -- total_time wall meter, reporting only
        logged: list[tuple[int, jnp.ndarray]] = []
        for i in range(n):
            with self.timers.phase("host_batch_plan"):
                toks = self._batch()
            self.params, self.momentum, loss = self.timers.measure(
                "round_step", self._train_step, self.params, self.momentum,
                toks)
            # i (run-relative) decides the always-log-final-step rule so
            # resumed/continued runs still close with a loss row.  Losses
            # stay ON DEVICE until the run ends — each device→host fetch
            # pays a fixed ~100 ms tunnel round-trip on this hardware, so
            # the whole run's logged losses travel as one stacked array.
            if self.step % s.log_every == 0 or i == n - 1:
                logged.append((self.step, loss))
            self.step += 1
        jax.block_until_ready(self.params)
        self.total_time = time.time() - t0  # dopt: allow-wallclock -- total_time wall meter, reporting only
        if logged:
            vals = np.asarray(jnp.stack([l for _, l in logged]))
            for (st, _), v in zip(logged, vals):
                self.history.append(round=st, step=st, loss=float(v))
        return self.history

    @property
    def round(self) -> int:  # CLI-driver surface parity
        return self.step

    def save(self, path) -> None:
        from dopt.utils.checkpoint import save_checkpoint

        save_checkpoint(
            path,
            arrays={"params": self.params, "momentum": self.momentum},
            meta={"round": self.step, "name": self.cfg.name,
                  "algorithm": "seqlm", "history": self.history.rows,
                  "data_rng_state": self._rng.bit_generator.state},
        )

    def restore(self, path) -> None:
        from dopt.utils.checkpoint import load_checkpoint
        from jax.sharding import NamedSharding, PartitionSpec as P

        arrays, meta = load_checkpoint(path)
        if meta.get("algorithm") != "seqlm":
            raise ValueError(
                f"checkpoint is for {meta.get('algorithm')!r}, not seqlm")
        rep = NamedSharding(self.mesh, P())
        self.params = jax.device_put(arrays["params"], rep)
        self.momentum = jax.device_put(arrays["momentum"], rep)
        self.step = int(meta["round"])
        self.history.rows = list(meta.get("history", []))
        if meta.get("data_rng_state"):
            self._rng.bit_generator.state = meta["data_rng_state"]
