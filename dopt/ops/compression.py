"""Compression operators for communication-efficient gossip (CHOCO-SGD).

The reference has no notion of communication cost at all (its "network"
is Python object passing — SURVEY §2.4); these operators exist for the
framework's own communication-efficient algorithms
(``GossipConfig.algorithm='choco'``): each worker communicates a
compressed *difference* ``Q(x_i − x̂_i)`` instead of full parameters,
with the error kept in ``x_i − x̂_i`` and fed back next round (error
feedback is what makes aggressive compression convergent).

All operators are pure, shape-static (XLA-friendly: ``top_k`` with a
compile-time k, seeded masks instead of data-dependent sparsity), and
act per worker on stacked [W, ...] pytrees.

Contract: an operator maps (tree, key) → tree of the same structure
where each worker's leaf slice retains ``ratio`` of its mass per the
operator's rule and the rest is zero.  ``ratio=1.0`` must be the exact
identity — that invariant is what the choco≡dsgd reduction test pins.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def _per_worker_topk(flat: jnp.ndarray, k: int) -> jnp.ndarray:
    """flat: [W, N] — keep the k largest-|·| entries per row."""
    n = flat.shape[1]
    if k >= n:
        return flat
    _, idx = jax.lax.top_k(jnp.abs(flat), k)          # [W, k]
    mask = jnp.zeros_like(flat).at[
        jnp.arange(flat.shape[0])[:, None], idx].set(1.0)
    return flat * mask


def top_k_compress(tree, ratio: float):
    """Magnitude top-k sparsification, per worker per leaf.  k is
    static: ceil(ratio · leaf_size) — jit-stable shapes."""
    if ratio >= 1.0:
        return tree

    def comp(x):
        w = x.shape[0]
        n = math.prod(x.shape[1:]) or 1
        k = max(int(math.ceil(ratio * n)), 1)
        flat = x.reshape(w, n).astype(jnp.float32)
        return _per_worker_topk(flat, k).reshape(x.shape).astype(x.dtype)

    return jax.tree.map(comp, tree)


def rand_k_compress(tree, ratio: float, key):
    """Random-k sparsification with 1/ratio rescaling (unbiased).  The
    mask is drawn from ``key`` per leaf — pass a per-round key so
    workers/rounds decorrelate."""
    if ratio >= 1.0:
        return tree
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(key, len(leaves))

    def comp(x, k):
        mask = (jax.random.uniform(k, x.shape) < ratio).astype(x.dtype)
        return x * mask / jnp.asarray(ratio, x.dtype)

    return jax.tree_util.tree_unflatten(
        treedef, [comp(x, k) for x, k in zip(leaves, keys)])


def make_compressor(name: str, ratio: float):
    """Operator factory: (tree, key) → compressed tree.

    'topk'  — deterministic magnitude top-k (ignores the key)
    'randk' — unbiased random-k with rescaling
    'none'  — identity (ratio ignored)
    """
    if name not in ("none", "topk", "randk"):
        raise ValueError(f"unknown compressor {name!r}; one of none|topk|randk")
    if name != "none" and not 0.0 < ratio <= 1.0:
        # ratio=0 would divide by zero in randk (NaN params on round 0)
        # and negative ratios would silently zero all communication.
        raise ValueError(f"compression_ratio must be in (0, 1], got {ratio}")
    if name == "none" or ratio >= 1.0:
        return lambda tree, key: tree
    if name == "topk":
        return lambda tree, key: top_k_compress(tree, ratio)
    return lambda tree, key: rand_k_compress(tree, ratio, key)
