"""Serve soak: the resident trainer, end-to-end, with invariants.

The ``dopt serve`` acceptance harness — a scripted single-host
resident run (real daemon subprocesses, real signals) that survives

* a live **membership change** (leave + later rejoin through the
  control plane → the churn/shard-reassignment machinery),
* a live **config change** (an ``optim.lr`` step applied at a round
  boundary via checkpoint → rebuild → restore),
* a **SIGTERM rolling restart** (drain to the boundary → checkpoint →
  re-exec in place → resume),

and asserts the four things a resident trainer owes you:

1. **Bit-exact elasticity** — the interrupted leg's History, fault
   ledger (``control`` + ``churn`` rows included) and canonical
   telemetry stream are IDENTICAL to an uninterrupted leg driven by
   the same command schedule: zero non-ledgered divergence.
2. **Ledgered control** — every applied command appears once in the
   ledger and once as a deterministic ``control`` event, at the same
   boundary round in both legs.
3. **Stream integrity** — both metrics streams pass
   ``dopt.obs.check`` (schema + gapless duplicate-free rounds across
   the restart's segment headers).
4. **Zero false positives** — the STOCK rule set raises no alert on
   either leg, and the daemon's own in-process monitor (stock set +
   the escalated drop-rate rule) reports healthy.

    python scripts/serve_soak.py --rounds 48 --min-seconds 60
    python scripts/serve_soak.py --engine federated --rounds 24
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from dopt.serve.control import CommandQueue, make_command  # noqa: E402

# Reuse the chaos soak's ledger-invariant checker (the serve ledger
# adds fleet-level control rows, which it now accepts).
from scripts.chaos_soak import check_ledger  # noqa: E402


def serve_args(engine: str, rounds: int, seed: int,
               checkpoint_every: int) -> list[str]:
    """The CLI argv for one soak leg (tiny synthetic workload — the
    soak exercises the runtime, not the model)."""
    preset = "baseline1" if engine == "gossip" else "baseline3"
    args = ["--preset", preset, "--num-users", "8",
            "--max-rounds", str(rounds),
            "--checkpoint-every", str(checkpoint_every),
            "--set", "seed=%d" % seed,
            "--set", "data.dataset=synthetic",
            "--set", "data.synthetic_train_size=256",
            "--set", "data.synthetic_test_size=64",
            "--set", "model.model=mlp",
            "--set", "model.faithful=false"]
    if engine == "gossip":
        args += ["--set", "gossip.local_ep=1", "--set", "gossip.local_bs=32"]
    else:
        args += ["--set", "federated.local_ep=1",
                 "--set", "federated.local_bs=32"]
    return args


def seed_commands(state_dir: Path, rounds: int) -> dict[str, int]:
    """The scripted command schedule, pinned to round boundaries so
    both legs apply identically: leave at ~N/4, lr step at ~N/2,
    rejoin at ~5N/8."""
    marks = {"leave": max(rounds // 4, 1),
             "lr": max(rounds // 2, 2),
             "join": max(5 * rounds // 8, 3)}
    q = CommandQueue(state_dir / "commands.jsonl")
    q.submit(make_command("membership", worker=3, action="leave",
                          at_round=marks["leave"], id="soak-leave"))
    q.submit(make_command("config", key="optim.lr", value=0.05,
                          at_round=marks["lr"], id="soak-lr"))
    q.submit(make_command("membership", worker=3, action="join",
                          at_round=marks["join"], id="soak-join"))
    return marks


def run_leg(name: str, state_dir: Path, argv: list[str], *,
            on_term: str, kill_at: int | None = None,
            timeout_s: float = 900.0) -> dict:
    """Run one daemon subprocess to drain; with ``kill_at``, SIGTERM it
    once the status file reports that round (the daemon drains to the
    boundary, checkpoints, re-execs IN PLACE — same pid — and resumes
    to the configured max)."""
    state_dir.mkdir(parents=True, exist_ok=True)
    cmd = [sys.executable, "-m", "dopt.serve", *argv,
           "--state-dir", str(state_dir), "--on-term", on_term]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    t0 = time.time()
    proc = subprocess.Popen(cmd, env=env, cwd=REPO)
    status_path = state_dir / "serve.json"
    killed = False
    while True:
        try:
            rc = proc.wait(timeout=0.5)
            break
        except subprocess.TimeoutExpired:
            pass
        if time.time() - t0 > timeout_s:
            proc.kill()
            raise AssertionError(f"[{name}] leg timed out after "
                                 f"{timeout_s:.0f}s")
        if kill_at is not None and not killed and status_path.exists():
            try:
                st = json.loads(status_path.read_text())
            except ValueError:
                continue
            if st.get("status") == "serving" and st.get("round", 0) \
                    >= kill_at:
                print(f"[{name}] SIGTERM at round {st['round']} "
                      f"(pid {proc.pid}) -> rolling restart", flush=True)
                os.kill(proc.pid, signal.SIGTERM)
                killed = True
    elapsed = time.time() - t0
    assert rc == 0, f"[{name}] daemon exited rc={rc}"
    if kill_at is not None:
        assert killed, (f"[{name}] never reached round {kill_at} to "
                        "deliver the SIGTERM")
    final = json.loads((state_dir / "final.json").read_text())
    if kill_at is not None:
        assert final.get("restarts", 0) >= 1, \
            f"[{name}] daemon drained without surviving a restart"
    print(f"[{name}] drained at round {final['round']} in {elapsed:.1f}s "
          f"(restarts={final.get('restarts', 0)})", flush=True)
    final["_elapsed_s"] = elapsed
    return final


def check_streams(path_a: Path, path_b: Path, rounds: int) -> None:
    from dopt.obs import HealthMonitor, JsonlSink, canonical, check_stream

    ev_a = JsonlSink.read(path_a)
    ev_b = JsonlSink.read(path_b)
    sa, sb = check_stream(ev_a), check_stream(ev_b)
    assert sa["rounds"] == sb["rounds"] == rounds, (sa, sb)
    assert sb["segments"] >= sa["segments"] + 1, \
        "restarted leg should carry at least one extra segment header"
    ca, cb = canonical(ev_a), canonical(ev_b)
    assert ca == cb, "canonical streams diverged between legs"
    n_ctl = sum(1 for e in ca if e["kind"] == "control")
    assert n_ctl == 3, f"expected 3 applied control events, saw {n_ctl}"
    print(f"[streams] canonical equality ok: {sa['events']} vs "
          f"{sb['events']} events, {n_ctl} control events each", flush=True)
    # Zero false positives under the STOCK rule set, on both legs.
    for name, evs in (("uninterrupted", ev_a), ("restarted", ev_b)):
        mon = HealthMonitor()
        mon.feed(evs)
        rep = mon.report()
        assert rep.alerts == 0 and rep.verdict == "healthy", \
            (f"false-positive gate: {name} leg raised {rep.alerts} "
             f"alerts: {mon.canonical_alerts()}")
    print("[streams] zero stock-rule alerts on both legs", flush=True)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", type=int, default=48)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--engine", choices=("gossip", "federated"),
                    default="gossip")
    ap.add_argument("--checkpoint-every", type=int, default=8)
    ap.add_argument("--min-seconds", type=float, default=0.0,
                    help="assert the restarted leg stayed resident at "
                         "least this long (the ROADMAP's >=60s soak bar)")
    ap.add_argument("--state-root", default=None,
                    help="scratch root (default: a temp dir)")
    ap.add_argument("--report-out", default=None, metavar="PATH",
                    help="write both legs' final reports as one JSON "
                         "artifact here")
    args = ap.parse_args(argv)

    import tempfile

    # Resolved: the daemon subprocess runs with cwd=REPO, so a relative
    # --state-root would otherwise name a different directory for the
    # harness and the daemon.
    root = Path(args.state_root
                or tempfile.mkdtemp(prefix="dopt-soak-")).resolve()
    rounds = args.rounds
    attempt = 0
    dir_a = root / "uninterrupted"
    while True:
        base = serve_args(args.engine, rounds, args.seed,
                          args.checkpoint_every)
        kill_at = max(3 * rounds // 8, 2)
        if dir_a.exists():
            import shutil

            shutil.rmtree(dir_a)
        marks_a = seed_commands(dir_a, rounds)
        print(f"[soak] engine={args.engine} rounds={rounds} "
              f"commands at {marks_a}, SIGTERM at >= {kill_at}", flush=True)
        final_a = run_leg("uninterrupted", dir_a, base, on_term="drain")
        # Self-calibration: round throughput varies 10x across CI
        # hardware, and the bar is RESIDENT SECONDS, not rounds —
        # rescale and redo the reference leg until it clears the bar
        # with margin (the restarted leg only ever runs longer: it
        # pays the re-exec warmup on top).
        if args.min_seconds <= 0 \
                or final_a["_elapsed_s"] >= args.min_seconds * 1.1:
            break
        scale = max(2, int(args.min_seconds * 1.3
                           // max(final_a["_elapsed_s"], 1.0)) + 1)
        rounds *= scale
        attempt += 1
        assert attempt <= 3, "soak calibration did not converge"
        print(f"[soak] {final_a['_elapsed_s']:.1f}s < "
              f"{args.min_seconds:.0f}s bar: rescaling to {rounds} "
              "rounds", flush=True)

    dir_b = root / "restarted"
    if dir_b.exists():
        # A persistent --state-root may hold a previous invocation's
        # leg: resuming its drained state would end immediately and
        # fail the comparison with a misleading message.
        import shutil

        shutil.rmtree(dir_b)
    marks_b = seed_commands(dir_b, rounds)
    assert marks_a == marks_b
    final_b = run_leg("restarted", dir_b, base, on_term="restart",
                      kill_at=kill_at)

    assert final_b["history"] == final_a["history"], \
        "History diverged between uninterrupted and restarted legs"
    assert final_b["fault_ledger"] == final_a["fault_ledger"], \
        "fault ledger diverged between uninterrupted and restarted legs"
    rows = final_a["fault_ledger"]
    check_ledger_rows = [r for r in rows]

    class _H:  # check_ledger wants a History-shaped object
        faults = check_ledger_rows

    n = check_ledger(_H, rounds, 8)
    kinds = sorted({r["kind"] for r in rows})
    assert "control" in kinds and "churn" in kinds, kinds
    print(f"[ledger] {n} rows identical across legs, kinds {kinds}",
          flush=True)

    check_streams(dir_a / "metrics.jsonl", dir_b / "metrics.jsonl",
                  rounds)

    for name, final in (("uninterrupted", final_a), ("restarted", final_b)):
        rep = final.get("report") or {}
        assert rep.get("verdict") == "healthy", \
            f"{name} leg's in-process monitor: {rep}"
    print("[monitor] in-process verdicts healthy on both legs", flush=True)

    if args.min_seconds > 0:
        assert final_b["_elapsed_s"] >= args.min_seconds, \
            (f"restarted leg stayed resident only "
             f"{final_b['_elapsed_s']:.1f}s < {args.min_seconds:.0f}s — "
             "raise --rounds")

    if args.report_out:
        from dopt.utils.metrics import atomic_write_text

        atomic_write_text(args.report_out, json.dumps({
            "engine": args.engine, "rounds": rounds,
            "commands": marks_a, "kill_at": kill_at,
            "uninterrupted": {k: v for k, v in final_a.items()
                              if k not in ("history", "fault_ledger")},
            "restarted": {k: v for k, v in final_b.items()
                          if k not in ("history", "fault_ledger")},
        }, indent=2))
        print(f"wrote soak report to {args.report_out}", flush=True)

    print("serve soak passed: live membership + config change + SIGTERM "
          "rolling restart with bit-exact resume, zero non-ledgered "
          "divergence, zero false-positive alerts", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
