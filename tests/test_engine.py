"""End-to-end engine tests on the 8-device virtual CPU mesh.

These are the SURVEY §4 layer-3 tests: multi-worker semantics without a
cluster, on synthetic learnable data so accuracy movement is meaningful.
"""

import dataclasses

import numpy as np
import pytest

from dopt.config import DataConfig, ExperimentConfig, FederatedConfig, GossipConfig, ModelConfig, OptimizerConfig
from dopt.engine import FederatedTrainer, GossipTrainer


def _gossip_cfg(**kw):
    g = dict(algorithm="dsgd", topology="circle", mode="metropolis",
             rounds=3, local_ep=1, local_bs=32)
    g.update(kw.pop("gossip", {}))
    return ExperimentConfig(
        name="t",
        seed=7,
        data=DataConfig(dataset="synthetic", num_users=kw.pop("num_users", 8),
                        iid=kw.pop("iid", True), shards=2,
                        synthetic_train_size=512, synthetic_test_size=128),
        model=ModelConfig(model="mlp", input_shape=(28, 28, 1),
                          faithful=False),
        optim=OptimizerConfig(lr=0.1, momentum=0.5),
        gossip=GossipConfig(**g),
        **kw,
    )


def _fed_cfg(algorithm="fedavg", **kw):
    return ExperimentConfig(
        name="t",
        seed=7,
        data=DataConfig(dataset="synthetic", num_users=kw.pop("num_users", 8),
                        iid=True, synthetic_train_size=512,
                        synthetic_test_size=128),
        model=ModelConfig(model="mlp", input_shape=(28, 28, 1),
                          faithful=False),
        optim=OptimizerConfig(lr=0.1, momentum=0.5, rho=0.1),
        federated=FederatedConfig(algorithm=algorithm, frac=0.5, rounds=3,
                                  local_ep=1, local_bs=32),
        **kw,
    )


def test_dsgd_learns(devices):
    tr = GossipTrainer(_gossip_cfg())
    h = tr.run(rounds=4)
    accs = [r["avg_test_acc"] for r in h if "avg_test_acc" in r]
    assert accs[-1] > 0.6, accs
    assert accs[-1] > accs[0]


def test_dsgd_consensus_shrinks_disagreement(devices):
    # After many rounds of doubly-stochastic mixing, workers' params
    # should be closer together than under no consensus.
    import jax
    cfg = _gossip_cfg(iid=False)
    tr = GossipTrainer(cfg)
    tr.run(rounds=4)
    leaves = jax.tree.leaves(tr.params)
    spread_dsgd = max(float(np.std(np.asarray(l), axis=0).max()) for l in leaves)

    cfg2 = _gossip_cfg(iid=False, gossip={"algorithm": "nocons"})
    tr2 = GossipTrainer(cfg2)
    tr2.run(rounds=4)
    leaves2 = jax.tree.leaves(tr2.params)
    spread_nocons = max(float(np.std(np.asarray(l), axis=0).max()) for l in leaves2)
    assert spread_dsgd < spread_nocons


def test_nocons_noniid_worse_than_dsgd(devices):
    # The reference's headline qualitative result (BASELINE.md): without
    # consensus, non-IID workers stagnate vs D-SGD on a good topology.
    h_no = GossipTrainer(_gossip_cfg(iid=False, gossip={"algorithm": "nocons"})).run(rounds=5)
    h_ds = GossipTrainer(_gossip_cfg(iid=False, gossip={
        "algorithm": "dsgd", "topology": "complete", "mode": "uniform"})).run(rounds=5)
    assert h_ds["avg_test_acc"][-1] > h_no["avg_test_acc"][-1] - 0.05


def test_centralized_preset_single_worker(devices):
    cfg = _gossip_cfg(gossip={"algorithm": "centralized"})
    tr = GossipTrainer(cfg)
    assert tr.num_workers == 1
    # original config object untouched (reference mutates shared args)
    assert cfg.data.num_users == 8
    h = tr.run(rounds=2)
    assert len(h) == 2


def test_fedlcon_multi_sweep(devices):
    cfg = _gossip_cfg(gossip={"algorithm": "fedlcon", "eps": 3,
                              "topology": "circle", "mode": "metropolis"})
    tr = GossipTrainer(cfg)
    h = tr.run(rounds=2)
    assert len(h) == 2


def test_gossip_learning_pairwise(devices):
    cfg = _gossip_cfg(gossip={"algorithm": "gossip"})
    tr = GossipTrainer(cfg)
    h = tr.run(rounds=3)
    assert h["avg_test_acc"][-1] > 0.5


def test_workers_fold_onto_devices(devices):
    # 16 workers on 8 devices: 2 lanes per device.
    tr = GossipTrainer(_gossip_cfg(num_users=16))
    assert tr.mesh.size == 8
    h = tr.run(rounds=2)
    assert len(h) == 2


@pytest.mark.parametrize("algorithm", ["fedavg", "fedprox", "fedadmm",
                                       "scaffold"])
def test_federated_learns(devices, algorithm):
    tr = FederatedTrainer(_fed_cfg(algorithm))
    h = tr.run(rounds=4)
    assert h["test_acc"][-1] > 0.6, h["test_acc"]


def test_scaffold_first_round_matches_fedavg(devices):
    # With zero-initialised control variates the SCAFFOLD gradient edit
    # is exactly zero, so round 1 must be bit-compatible with FedAvg
    # (same seed → same client sample, same batch plan).
    import jax
    a = FederatedTrainer(_fed_cfg("fedavg"))
    b = FederatedTrainer(_fed_cfg("scaffold"))
    a.run(rounds=1)
    b.run(rounds=1)
    for x, y in zip(jax.tree.leaves(jax.device_get(a.theta)),
                    jax.tree.leaves(jax.device_get(b.theta))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6)


def test_scaffold_controls_mean_is_server_control(devices):
    # frac=1, zero init: after round 1, c = mean_i c_i⁺ exactly.
    import jax
    cfg = _fed_cfg("scaffold")
    cfg = dataclasses.replace(
        cfg, federated=dataclasses.replace(cfg.federated, frac=1.0))
    tr = FederatedTrainer(cfg)
    tr.run(rounds=1)
    ci = jax.device_get(tr.duals)
    c = jax.device_get(tr.c_global)
    for a, b in zip(jax.tree.leaves(ci), jax.tree.leaves(c)):
        np.testing.assert_allclose(np.asarray(a).mean(axis=0), np.asarray(b),
                                   atol=1e-5)
    # and the controls actually moved
    assert any(float(np.abs(np.asarray(l)).max()) > 0
               for l in jax.tree.leaves(c))


def test_federated_partial_participation_mask(devices):
    tr = FederatedTrainer(_fed_cfg("fedavg"))
    mask = tr.sample_clients(0.25)
    assert mask.sum() == 2  # max(int(0.25*8),1)
    mask = tr.sample_clients(0.01)
    assert mask.sum() == 1  # at least one client


def test_fedadmm_duals_update_only_sampled(devices):
    import jax
    tr = FederatedTrainer(_fed_cfg("fedadmm"))
    duals_before = jax.device_get(tr.duals)
    tr.run(rounds=1)
    duals_after = jax.device_get(tr.duals)
    # at least one dual leaf must have moved for sampled workers
    moved = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(duals_before), jax.tree.leaves(duals_after))
    )
    assert moved


def test_round_counter_persists_across_runs(devices):
    tr = GossipTrainer(_gossip_cfg())
    tr.run(rounds=2)
    tr.run(rounds=2)
    assert tr.round == 4
    assert [r["round"] for r in tr.history] == [0, 1, 2, 3]


def test_blocked_run_matches_per_round(devices):
    # The fused multi-round lax.scan block path must be bit-identical to
    # the per-round dispatch path (same plans, same matrices, same order).
    import jax

    a = GossipTrainer(_gossip_cfg())
    a.run(rounds=4)
    b = GossipTrainer(_gossip_cfg())
    b.run(rounds=4, block=2)
    fa = np.concatenate([np.ravel(x) for x in jax.tree.leaves(jax.device_get(a.params))])
    fb = np.concatenate([np.ravel(x) for x in jax.tree.leaves(jax.device_get(b.params))])
    np.testing.assert_array_equal(fa, fb)
    la = [r["avg_train_loss"] for r in a.history.rows]
    lb = [r["avg_train_loss"] for r in b.history.rows]
    np.testing.assert_allclose(la, lb, rtol=1e-6)
    # Same eval cadence AND same eval values (phase order matches:
    # consensus -> eval -> local update in both paths).
    ea = [r["avg_test_acc"] for r in a.history.rows if "avg_test_acc" in r]
    eb = [r["avg_test_acc"] for r in b.history.rows if "avg_test_acc" in r]
    np.testing.assert_allclose(ea, eb, rtol=1e-6)
    # Remainder blocks (4 rounds, block=3 -> 3+1) also line up.
    c = GossipTrainer(_gossip_cfg())
    c.run(rounds=4, block=3)
    fc = np.concatenate([np.ravel(x) for x in jax.tree.leaves(jax.device_get(c.params))])
    np.testing.assert_array_equal(fa, fc)


def test_gossip_dropout_runs_and_learns(devices):
    tr = GossipTrainer(_gossip_cfg(gossip={"dropout": 0.3}))
    h = tr.run(rounds=4)
    assert h["avg_test_acc"][-1] > 0.5


def test_gossip_full_dropout_freezes_state(devices):
    import jax
    tr = GossipTrainer(_gossip_cfg(gossip={"dropout": 1.0}))
    before = jax.device_get(tr.params)
    tr.run(rounds=2)
    after = jax.device_get(tr.params)
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gossip_dropout_blocked_matches_per_round(devices):
    import jax
    a = GossipTrainer(_gossip_cfg(gossip={"dropout": 0.4}))
    a.run(rounds=4)
    b = GossipTrainer(_gossip_cfg(gossip={"dropout": 0.4}))
    b.run(rounds=4, block=2)
    fa = np.concatenate([np.ravel(x) for x in jax.tree.leaves(jax.device_get(a.params))])
    fb = np.concatenate([np.ravel(x) for x in jax.tree.leaves(jax.device_get(b.params))])
    np.testing.assert_array_equal(fa, fb)


def test_fedlcon_faithful_bug_reproduces_single_sweep(devices):
    # The reference's FedLCon never clears new_weights across its eps
    # loop, so every sweep reloads sweep-0 results — effectively ONE
    # consensus sweep (simulators.py:189-196). faithful_bugs=True must
    # reproduce that exactly; the fixed path must differ.
    import jax

    def params_of(**gk):
        tr = GossipTrainer(_gossip_cfg(gossip=dict(
            algorithm="fedlcon", topology="circle", mode="metropolis", **gk)))
        tr.run(rounds=2)
        return np.concatenate([np.ravel(np.asarray(x))
                               for x in jax.tree.leaves(jax.device_get(tr.params))])

    buggy_eps3 = params_of(eps=3, faithful_bugs=True)
    one_sweep = params_of(eps=1)
    fixed_eps3 = params_of(eps=3)
    np.testing.assert_array_equal(buggy_eps3, one_sweep)
    assert not np.array_equal(fixed_eps3, one_sweep)


@pytest.mark.parametrize("algorithm", ["fedavg", "fedprox", "fedadmm",
                                       "scaffold"])
def test_compact_sampling_matches_full_width(devices, algorithm):
    # The gather-compact fast path must reproduce the full-width masked
    # path up to float summation order, for every algorithm, including
    # stale state on unsampled workers across rounds.
    import jax

    def run(compact):
        cfg = _fed_cfg(algorithm)
        cfg = cfg.replace(federated=dataclasses.replace(
            cfg.federated, compact=compact), mesh_devices=1)
        tr = FederatedTrainer(cfg)
        tr.run(rounds=3)
        return tr

    a = run(False)
    b = run(True)
    for x, y in zip(jax.tree.leaves(jax.device_get(a.theta)),
                    jax.tree.leaves(jax.device_get(b.theta))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=2e-5, rtol=1e-4)
    for x, y in zip(jax.tree.leaves(jax.device_get(a.params)),
                    jax.tree.leaves(jax.device_get(b.params))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=2e-5, rtol=1e-4)
    if a.duals is not None:
        for x, y in zip(jax.tree.leaves(jax.device_get(a.duals)),
                        jax.tree.leaves(jax.device_get(b.duals))):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(a.history["test_acc"], b.history["test_acc"],
                               atol=1e-3)


@pytest.mark.parametrize("algorithm", ["fedavg", "fedadmm", "scaffold"])
def test_federated_blocked_matches_per_round(devices, algorithm):
    # The fused multi-round block path (lax.scan over rounds in one jit)
    # must reproduce the per-round path exactly: same client-sampling
    # sequence, same history rows, same final state.  Covers both the
    # full-width (sharded mesh) and compact (single-device) paths via
    # the default mesh.
    import jax

    def run(block):
        tr = FederatedTrainer(_fed_cfg(algorithm))
        tr.run(rounds=4, block=block)
        return tr

    a = run(1)
    b = run(2)
    c = run(3)  # remainder block: 3 + 1
    for other in (b, c):
        for x, y in zip(jax.tree.leaves(jax.device_get(a.theta)),
                        jax.tree.leaves(jax.device_get(other.theta))):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       atol=1e-6, rtol=1e-5)
        np.testing.assert_allclose(a.history["test_acc"],
                                   other.history["test_acc"], atol=1e-5)
        np.testing.assert_allclose(a.history["train_loss"],
                                   other.history["train_loss"], atol=1e-5)
        np.testing.assert_allclose(a.history["local_loss"],
                                   other.history["local_loss"], atol=1e-5)


def test_federated_blocked_compact_single_device(devices):
    # Compact + blocked on one device: sel gates are [k, m] index arrays.
    import jax

    def run(block):
        cfg = _fed_cfg("fedavg")
        cfg = cfg.replace(federated=dataclasses.replace(
            cfg.federated, compact=True), mesh_devices=1)
        tr = FederatedTrainer(cfg)
        tr.run(rounds=4, block=block)
        return tr

    a = run(1)
    b = run(4)
    for x, y in zip(jax.tree.leaves(jax.device_get(a.theta)),
                    jax.tree.leaves(jax.device_get(b.theta))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(a.history["test_acc"],
                               b.history["test_acc"], atol=1e-5)


def test_engines_reject_transformer_model(devices):
    cfg = _gossip_cfg()
    cfg = cfg.replace(model=dataclasses.replace(cfg.model, model="transformer"))
    with pytest.raises(ValueError, match="sequence model"):
        GossipTrainer(cfg)
    fcfg = _fed_cfg()
    fcfg = fcfg.replace(model=dataclasses.replace(fcfg.model, model="transformer"))
    with pytest.raises(ValueError, match="sequence model"):
        FederatedTrainer(fcfg)


def test_gossip_comm_compression_trains(devices):
    # bf16 on-the-wire consensus: the run proceeds and the consensus
    # still contracts disagreement (approximate mixing is still mixing).
    cfg = _gossip_cfg(gossip=dict(comm_dtype="bfloat16", rounds=3))
    tr = GossipTrainer(cfg)
    h = tr.run()
    assert len(h) == 3
    ref = GossipTrainer(_gossip_cfg()).run()
    assert abs(h.last()["avg_test_acc"] - ref.last()["avg_test_acc"]) < 0.1


def test_hierarchical_gossip_on_hybrid_mesh(devices):
    # DCN-aware schedule: intra-host rounds + periodic global mix, on a
    # 2x4 (hosts x ici) hybrid mesh.  The periodic global mix must
    # actually pull the hosts together: cross-worker spread under the
    # hierarchical schedule stays well below the no-communication run's.
    import jax

    def spread_of(tr):
        leaves = jax.tree.leaves(jax.device_get(tr.params))
        return max(float(np.abs(np.asarray(x) - np.asarray(x)[0]).max())
                   for x in leaves)

    cfg = _gossip_cfg(
        gossip=dict(topology="hierarchical", mode="metropolis", rounds=4,
                    hier_groups=2, hier_period=2),
        mesh_hosts=2, iid=False,
    )
    tr = GossipTrainer(cfg)
    h = tr.run()
    assert len(h) == 4

    nocons = GossipTrainer(_gossip_cfg(
        gossip=dict(algorithm="nocons", rounds=4), iid=False))
    nocons.run()
    assert spread_of(tr) < 0.5 * spread_of(nocons)


def test_federated_comm_compression_trains(devices):
    cfg = _fed_cfg("fedavg")
    cfg = cfg.replace(federated=dataclasses.replace(
        cfg.federated, comm_dtype="bfloat16"))
    tr = FederatedTrainer(cfg)
    h = tr.run(rounds=3)
    ref = FederatedTrainer(_fed_cfg("fedavg")).run(rounds=3)
    assert abs(h.last()["test_acc"] - ref.last()["test_acc"]) < 0.1
