"""Sequence-parallel LM throughput: tokens/sec on the current device(s).

Measures steady-state training throughput of the ``seqlm`` preset
(decoder-only TransformerLM, ring attention, sequence axis sharded over
all devices).  On a single chip the ring degenerates to one block (same
code path, no hops); on an N-device mesh the KV pairs rotate over ICI.
There is no reference counterpart (the reference has no sequence axis);
the number is the framework's own long-context baseline.

Point mode prints one JSON line:
    python scripts/bench_seqlm.py [--steps N] [--seq-len L] [--kv-chunk C]

Sweep mode (``--sweep``) doubles seq_len until the chip OOMs, with and
without flash-style KV chunking (``SeqLMConfig.kv_chunk`` — the knob
that turns the per-block score memory from O(block²) into
O(block·chunk)), records tokens/sec + peak HBM per point, and writes
``results/seqlm_bench.json`` with the longest trainable context per
branch.  Each point runs in a SUBPROCESS so an OOM cannot poison the
sweep's runtime state.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def run_point(args) -> int:
    import jax

    from dopt.engine import SeqLMTrainer
    from dopt.presets import get_preset
    from dopt.utils.metrics import trimmed_stats

    cfg = get_preset("seqlm")
    cfg = cfg.replace(seqlm=dataclasses.replace(
        cfg.seqlm, steps=args.steps, seq_len=args.seq_len, batch=args.batch,
        attn=args.attn, kv_chunk=args.kv_chunk,
        log_every=max(args.steps // 3, 1)))
    tr = SeqLMTrainer(cfg)
    tr.run(steps=3)                       # compile + warmup
    tokens = args.steps * args.batch * args.seq_len
    tps = []
    total = 0.0
    for _ in range(max(args.repeats, 1)):
        t0 = time.time()
        tr.run(steps=args.steps)
        jax.block_until_ready(tr.params)
        elapsed = time.time() - t0
        total += elapsed
        tps.append(tokens / elapsed)
    med, spread, _ = trimmed_stats(tps)
    # Standard bench JSON-line schema (metric/value/unit/device_kind +
    # the trimmed-median wall reduction), so the ring-attention LM line
    # drops into the same tooling as bench.py's headline lines
    # (ROADMAP lever 4 groundwork: the seqlm workload as a first-class
    # headline bench).
    out = {
        "metric": "seqlm_tokens_per_sec",
        "value": round(med, 1),
        "unit": "tokens/sec",
        "device_kind": str(jax.devices()[0].device_kind),
        "spread_pct": round(spread, 2),
        "measured_windows": len(tps),
        "measured_seconds": round(total, 2),
        "steps_per_window": args.steps,
        "attn": args.attn,
        "seq_len": args.seq_len,
        "batch": args.batch,
        "kv_chunk": args.kv_chunk,
        "mesh_devices": tr.mesh.size,
        "params": tr.param_count,
        "final_loss": round(tr.history.last()["loss"], 4),
        # Back-compat alias for pre-schema consumers of this script.
        "device": str(jax.devices()[0].device_kind),
    }
    # Shared occupancy helper (dopt.utils.profiling.device_memory_stats:
    # backend allocator stats on TPU/GPU, host-RSS fallback on CPU) —
    # the same peak-HBM column bench.py's headline line carries, so the
    # seqlm line is always comparable and always present.
    from dopt.utils.profiling import device_memory_stats

    mem = device_memory_stats()
    if mem is not None:
        out["peak_hbm_gb"] = round(mem["peak_bytes"] / 2**30, 3)
        out["hbm_source"] = mem["source"]
    print(json.dumps(out))
    return 0


def run_sweep(args) -> int:
    """Double seq_len until OOM, for kv_chunk in (0, --kv-chunk)."""
    if args.attn != "ring":
        print(f"--sweep requires --attn ring (kv_chunk only applies to "
              f"ring attention, got {args.attn!r})", file=sys.stderr)
        return 2
    points, longest = [], {}
    for kv in (0, args.kv_chunk):
        label = f"kv_chunk={kv}" if kv else "no chunking (O(block²) scores)"
        for exp in range(100):
            seq = args.seq_len << exp
            if seq > args.max_seq_len:
                break
            cmd = [sys.executable, __file__, "--steps", str(args.steps),
                   "--seq-len", str(seq), "--batch", str(args.batch),
                   "--attn", args.attn, "--kv-chunk", str(kv)]
            try:
                r = subprocess.run(cmd, capture_output=True, text=True,
                                   timeout=1800)
            except subprocess.TimeoutExpired as e:
                # A wedged point (e.g. runtime hang at the OOM boundary)
                # ends its branch but must not lose the sweep so far.
                points.append({"seq_len": seq, "kv_chunk": kv,
                               "status": "timeout",
                               "stderr_tail": str(e)[-400:]})
                print(f"[sweep] {label} seq_len={seq}: TIMEOUT", flush=True)
                break
            line = next((ln for ln in r.stdout.splitlines()
                         if ln.startswith("{")), None)
            if r.returncode != 0 or line is None:
                oom = ("RESOURCE_EXHAUSTED" in r.stderr
                       or "out of memory" in r.stderr.lower())
                points.append({"seq_len": seq, "kv_chunk": kv,
                               "status": "oom" if oom else "failed",
                               "stderr_tail": r.stderr.strip()[-400:]})
                print(f"[sweep] {label} seq_len={seq}: "
                      f"{'OOM' if oom else 'FAILED'}", flush=True)
                break
            p = json.loads(line)
            p["status"] = "ok"
            points.append(p)
            longest[f"kv_chunk_{kv}"] = seq
            print(f"[sweep] {label} seq_len={seq}: "
                  f"{p['value']:,.0f} tok/s"
                  + (f", peak HBM {p['peak_hbm_gb']} GB"
                     if "peak_hbm_gb" in p else ""), flush=True)
    payload = {
        "suite": "seqlm long-context sweep",
        "attn": args.attn,
        "batch": args.batch,
        "steps_per_point": args.steps,
        "longest_trainable_seq_len": longest,
        "points": points,
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(payload, indent=2) + "\n")
    print(f"wrote {out}")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--repeats", type=int, default=1,
                    help="independent measured windows; the reported "
                         "value is their min/max-trimmed median "
                         "(dopt.utils.metrics.trimmed_stats, the same "
                         "variance hardening bench.py uses)")
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--attn", default="ring", choices=["ring", "ulysses"])
    ap.add_argument("--kv-chunk", type=int, default=0,
                    help="flash-style KV chunk (0 = full-block scores); "
                         "in --sweep mode, the chunked branch's size")
    ap.add_argument("--sweep", action="store_true")
    ap.add_argument("--max-seq-len", type=int, default=1 << 20)
    ap.add_argument("--out", default="results/seqlm_bench.json")
    args = ap.parse_args()
    if args.sweep:
        if not args.kv_chunk:
            args.kv_chunk = 512
        return run_sweep(args)
    return run_point(args)


if __name__ == "__main__":
    raise SystemExit(main())
