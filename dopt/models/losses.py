"""Loss and metric functions shared by both backends.

``cross_entropy`` reproduces the reference objective exactly:
``CrossEntropyLoss`` applied to the model output.  With the faithful
head the model output is already softmax probabilities, so the loss is
``-log_softmax(probs)[y]`` — the double softmax the reference's
published accuracies were produced with (SURVEY §3.4).  With the
corrected head the output is logits and this is the standard softmax CE.

The per-sample weights come from the batch-plan padding masks
(``dopt.data.pipeline``); a weighted mean with ``Σw`` in the denominator
makes padded samples mathematically invisible.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy(outputs: jnp.ndarray, labels: jnp.ndarray,
                  weights: jnp.ndarray | None = None) -> jnp.ndarray:
    """Mean CE over the batch, exactly ``nn.CrossEntropyLoss(outputs, y)``.

    ``outputs`` is whatever the model head emits (probabilities in
    faithful mode, logits otherwise) — CrossEntropyLoss semantics apply
    log_softmax to its input regardless, which is what makes the
    faithful path a double softmax.
    """
    logp = jax.nn.log_softmax(outputs.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    if weights is None:
        return jnp.mean(nll)
    w = weights.astype(jnp.float32)
    return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)


def accuracy(outputs: jnp.ndarray, labels: jnp.ndarray,
             weights: jnp.ndarray | None = None) -> jnp.ndarray:
    """Fraction of correct argmax predictions (softmax is monotone, so
    faithful vs corrected head give identical argmax)."""
    pred = jnp.argmax(outputs, axis=-1)
    correct = (pred == labels).astype(jnp.float32)
    if weights is None:
        return jnp.mean(correct)
    w = weights.astype(jnp.float32)
    return jnp.sum(correct * w) / jnp.maximum(jnp.sum(w), 1.0)


def l2_regulariser(params, lam: float) -> jnp.ndarray:
    """ℓ2 penalty for the a9a logistic-regression ADMM config."""
    sq = sum(jnp.sum(p.astype(jnp.float32) ** 2)
             for p in jax.tree_util.tree_leaves(params))
    return 0.5 * lam * sq


def cross_entropy_stacked(outputs: jnp.ndarray, labels: jnp.ndarray,
                          weights: jnp.ndarray) -> jnp.ndarray:
    """Per-worker ``cross_entropy``: [W, B, C] outputs → [W] losses.
    Same math as the vmapped per-worker call, reduced over the batch
    axis only — used by the grouped stacked-forward fast path."""
    logp = jax.nn.log_softmax(outputs.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(
        logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    w = weights.astype(jnp.float32)
    return jnp.sum(nll * w, axis=-1) / jnp.maximum(jnp.sum(w, axis=-1), 1.0)


def accuracy_stacked(outputs: jnp.ndarray, labels: jnp.ndarray,
                     weights: jnp.ndarray) -> jnp.ndarray:
    """Per-worker ``accuracy``: [W, B, C] outputs → [W] fractions."""
    correct = (jnp.argmax(outputs, axis=-1) == labels).astype(jnp.float32)
    w = weights.astype(jnp.float32)
    return (jnp.sum(correct * w, axis=-1)
            / jnp.maximum(jnp.sum(w, axis=-1), 1.0))


def l2_stacked(params, lam: float) -> jnp.ndarray:
    """Per-worker ℓ2 penalty over a [W, ...]-stacked pytree → [W]."""
    tot = 0.0
    for p in jax.tree_util.tree_leaves(params):
        tot = tot + (p.astype(jnp.float32) ** 2).reshape(p.shape[0], -1).sum(axis=1)
    return 0.5 * lam * tot
