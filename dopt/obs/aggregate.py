"""Fleet-scope telemetry aggregation: ``python -m dopt.obs.aggregate``.

A ``dopt serve --num-processes N`` fleet emits one metrics JSONL
stream per process (the leader's ``metrics.jsonl`` plus
``metrics-p<i>.jsonl`` per follower).  Followers replay the leader's
boundary directives verbatim, so the DETERMINISTIC_KINDS of every
stream — ``round``/``fault``/``gauge``/``control`` — must be
bit-identical across processes: divergence means a follower applied a
different command schedule, trained a different round, or fetched
different values than the leader, which is exactly the replay drift
the serve contract forbids.  ``FleetAggregator`` turns that invariant
into a live meter:

* **tails** every process's stream (``JsonlTail`` byte-offset
  watermarks, torn-tail tolerant per process — a writer mid-flush
  never desynchronizes the merge);
* **merges** on the deterministic round watermark: a round is
  *fleet-sealed* once every process has sealed it (emitted its
  ``round`` event), events are keyed by (process, segment, round), and
  the merged stream advances only to the minimum sealed round — it
  never claims a round some process hasn't confirmed;
* **verifies** cross-process consistency of the deterministic kinds at
  every fleet-sealed round — the FIRST divergence is reported with
  both events (leader's and the diverging process's), the round, and
  the canonical index, then the merge stops consuming (everything
  after a divergence is noise);
* **exposes** one merged view: the leader's stream verbatim (already a
  valid checkable stream) with each event stamped ``process``, plus
  every follower's non-deterministic events (``latency``/``resource``/
  ``compile``/``checkpoint``/``alert``/``warning``) with THEIR process
  stamp — so fleet latency histograms aggregate across processes and
  alert provenance survives the merge.

``FleetMetricsServer`` mounts the merged view as the supervisor's one
fleet scrape surface: ``GET /metrics`` (PrometheusSink over the merged
stream — SLO latency histograms included) and ``GET /healthz`` (the
merged ``HealthMonitor`` report plus per-process watermarks/lag and
any divergence; 503 with a ``Retry-After`` header and a JSON body once
critical or diverged).

Stdlib-only (no jax): aggregate a fleet's streams from any laptop.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any

from dopt.obs.events import DETERMINISTIC_KINDS, check_stream
from dopt.obs.monitor import HealthMonitor, JsonlTail
from dopt.obs.sinks import PrometheusSink

# Window (round-event wall clocks) for the per-process rounds/sec
# estimate the fleet watch renders.
_RATE_WINDOW = 32

# Non-deterministic kinds a FOLLOWER contributes to the merged stream
# (its deterministic kinds are byte-identical to the leader's — one
# copy suffices — and its `run` headers would duplicate segment
# structure the leader's stream already carries).
_FOLLOWER_KINDS = ("latency", "resource", "compile", "checkpoint",
                   "alert", "warning")


def fleet_metric_paths(state_dir: str | Path,
                       num_processes: int | None = None,
                       ) -> dict[int, Path]:
    """Per-process metrics stream paths under a serve state dir:
    process 0 writes ``metrics.jsonl``, follower ``i`` writes
    ``metrics-p<i>.jsonl``.  With ``num_processes`` the full expected
    map is returned (files may not exist yet — tails wait for them);
    otherwise followers are discovered by glob."""
    state = Path(state_dir)
    paths = {0: state / "metrics.jsonl"}
    if num_processes is not None:
        for i in range(1, int(num_processes)):
            paths[i] = state / f"metrics-p{i}.jsonl"
        return paths
    for p in sorted(state.glob("metrics-p*.jsonl")):
        stem = p.name[len("metrics-p"):-len(".jsonl")]
        if stem.isdigit():
            paths[int(stem)] = p
    return paths


# How many alert events each process state retains for the provenance
# feed (totals stay exact; a resident supervisor must not grow without
# bound).
_ALERT_RING = 256

# Bytes of already-consumed stream re-read before each poll to detect
# a shrink-then-regrow rewrite (JsonlSink.repair_tail truncates, the
# resumed daemon appends past the old offset before the next poll —
# size alone cannot see it, but the dropped tail's bytes change).
_TAIL_GUARD = 64


class _ProcessState:
    """One tailed process stream: byte-offset tail, the pending events
    of the not-yet-sealed round, the sealed-round queue awaiting
    fleet-wide verification, and the live stats the watch renders."""

    def __init__(self, process: int, path: Path):
        self.process = int(process)
        self.path = Path(path)
        self.tail = JsonlTail(self.path)
        self.pending: list[dict[str, Any]] = []
        # (round, canonical det bundle, full chunk) per sealed round.
        self.sealed: deque[tuple[int, list, list]] = deque()
        self.watermark: int | None = None   # last FLEET-sealed round
        self.segments = 0
        self.last_metrics: dict[str, Any] = {}
        self.last_event_ts: float | None = None
        self.alerts: deque[dict[str, Any]] = deque(maxlen=_ALERT_RING)
        self.alerts_total = 0
        self.guard = b""   # last consumed bytes (rewrite detector)
        # After a resync replay, events at or before this ts were
        # already counted once — display counters skip them.
        self.replay_cut: float | None = None
        self._round_ts: deque[float] = deque(maxlen=_RATE_WINDOW)

    def counted(self, ts) -> bool:
        return (self.replay_cut is not None
                and isinstance(ts, (int, float))
                and float(ts) <= self.replay_cut)

    def rounds_per_sec(self) -> float | None:
        ts = self._round_ts
        if len(ts) < 2 or ts[-1] <= ts[0]:
            return None
        return (len(ts) - 1) / (ts[-1] - ts[0])

    def lag_seconds(self, now: float) -> float | None:
        if self.last_event_ts is None:
            return None
        return max(0.0, float(now) - self.last_event_ts)

    def snapshot(self, now: float) -> dict[str, Any]:
        from dopt.obs.rules import loss_of

        return {"path": str(self.path),
                "round": self.watermark,
                "sealed_ahead": len(self.sealed),
                "segments": self.segments,
                "rounds_per_sec": self.rounds_per_sec(),
                "lag_seconds": self.lag_seconds(now),
                "loss": loss_of(self.last_metrics)[1],
                "alerts": self.alerts_total}


def _canon(ev: dict[str, Any]) -> dict[str, Any]:
    return {k: v for k, v in ev.items() if k != "ts"}


class FleetDivergenceError(AssertionError):
    """Raised (strict mode) when two processes' deterministic streams
    disagree; carries the structured ``record``."""

    def __init__(self, record: dict[str, Any]):
        self.record = record
        super().__init__(format_fleet_divergence(record))


def format_fleet_divergence(d: dict[str, Any]) -> str:
    return "\n".join([
        f"fleet streams diverge at round {d['round']} "
        f"(process {d['process']} vs leader, canonical event "
        f"{d['index']}): {d['reason']}",
        f"  leader:  {json.dumps(d['leader'], sort_keys=True)}",
        f"  p{d['process']}:      "
        f"{json.dumps(d['other'], sort_keys=True)}",
    ])


class FleetAggregator:
    """Merge + verify a serve fleet's per-process telemetry streams.

    ``poll()`` consumes whatever every tail has appended, fleet-seals
    rounds confirmed by all processes, verifies deterministic-kind
    consistency at each, and extends ``merged``.  ``divergence`` holds
    the first inconsistency (then the merge stops consuming; strict
    mode raises instead).  ``flush_trailing()`` settles the events
    after the last round (the drain boundary's control rows, the
    end-of-run summary gauge) once the run is over.
    """

    def __init__(self, state_dir: str | Path | None = None, *,
                 num_processes: int | None = None,
                 paths: dict[int, str | Path] | None = None,
                 strict: bool = False):
        if paths is None:
            if state_dir is None:
                raise ValueError(
                    "FleetAggregator needs a state_dir or explicit "
                    "paths")
            paths = fleet_metric_paths(state_dir, num_processes)
        self.strict = bool(strict)
        self._procs: dict[int, _ProcessState] = {
            int(p): _ProcessState(int(p), Path(path))
            for p, path in sorted(paths.items())}
        if 0 not in self._procs:
            raise ValueError("the fleet needs a process-0 (leader) "
                             f"stream, got processes {sorted(paths)}")
        self.merged: list[dict[str, Any]] = []
        self.merged_total = 0
        self.divergence: dict[str, Any] | None = None
        self.rounds_merged = 0

    @property
    def processes(self) -> list[int]:
        return sorted(self._procs)

    # -- consumption ---------------------------------------------------
    def poll(self) -> int:
        """Consume every tail's new complete lines, fleet-seal what all
        processes confirm; returns the number of newly merged events."""
        if self.divergence is not None:
            # Everything after a divergence is noise; stop reading so a
            # resident endpoint's buffers stop growing too.
            return 0
        before = len(self.merged)
        for st in self._procs.values():
            self._poll_proc(st)
        self._drain_sealed()
        return len(self.merged) - before

    def _poll_proc(self, st: _ProcessState) -> None:
        try:
            size = st.path.stat().st_size
        except OSError:
            size = 0
        if size < st.tail.offset or not self._guard_ok(st):
            # The file SHRANK — or shrank and REGREW past our offset
            # between polls (the guard bytes changed): JsonlSink.
            # repair_tail dropped the torn tail / unsealed-bundle
            # orphans before a resume appended.  Our pending buffer
            # holds exactly those dropped orphans (and on a regrow the
            # byte offset may now point mid-line): resync from byte 0,
            # skipping the rounds already fleet-sealed.
            self._resync(st)
        for ev in st.tail.poll():
            self._ingest(st, ev)
        self._update_guard(st)

    def _guard_ok(self, st: _ProcessState) -> bool:
        """True while the bytes just before our offset still match what
        we consumed — a truncate-then-append rewrite changes them even
        when the file size already grew past the old offset."""
        if not st.guard or st.tail.offset == 0:
            return True
        try:
            with open(st.path, "rb") as f:
                f.seek(st.tail.offset - len(st.guard))
                return f.read(len(st.guard)) == st.guard
        except OSError:
            return True   # absent/racing file: the poll handles it

    def _update_guard(self, st: _ProcessState) -> None:
        off = st.tail.offset
        if off == 0:
            st.guard = b""
            return
        try:
            with open(st.path, "rb") as f:
                f.seek(max(0, off - _TAIL_GUARD))
                st.guard = f.read(min(off, _TAIL_GUARD))
        except OSError:
            pass

    def _resync(self, st: _ProcessState) -> None:
        """Re-read ``st``'s stream from byte 0 after a rewrite.  Rounds
        at or below the fleet watermark were already verified and
        merged — ``_ingest`` drops their re-read chunks — and locally
        sealed-but-unmerged rounds re-seal from the fresh bytes."""
        st.tail = JsonlTail(st.path)
        st.pending = []
        st.sealed.clear()
        st.guard = b""
        st.replay_cut = st.last_event_ts

    def _ingest(self, st: _ProcessState, ev: dict[str, Any]) -> None:
        kind = ev.get("kind")
        ts = ev.get("ts")
        if isinstance(ts, (int, float)):
            st.last_event_ts = (float(ts) if st.last_event_ts is None
                                else max(st.last_event_ts, float(ts)))
        st.pending.append(ev)
        if kind == "round":
            t = int(ev.get("round", -1))
            if st.watermark is not None and t <= st.watermark:
                # A resync replayed a round the fleet already sealed
                # and merged: drop the chunk (it was verified when it
                # first sealed).
                st.pending = []
                return
            st.last_metrics = dict(ev.get("metrics", {}))
            if isinstance(ts, (int, float)):
                st._round_ts.append(float(ts))
            det = [_canon(e) for e in st.pending
                   if e.get("kind") in DETERMINISTIC_KINDS]
            st.sealed.append((t, det, st.pending))
            st.pending = []
        elif kind == "run":
            if not st.counted(ts):
                st.segments += 1
        elif kind == "alert":
            if not st.counted(ts):
                st.alerts.append({**ev, "process": st.process})
                st.alerts_total += 1

    def _drain_sealed(self) -> None:
        while self.divergence is None:
            heads = []
            for p in self.processes:
                st = self._procs[p]
                if not st.sealed:
                    return   # a process hasn't confirmed the round yet
                heads.append((p, st.sealed[0]))
            r0, det0, chunk0 = heads[0][1]
            for p, (r, det, chunk) in heads[1:]:
                rec = self._compare(p, r0, det0, r, det)
                if rec is not None:
                    self._diverge(rec)
                    return
            # Verified: leader's chunk verbatim (stamped), followers'
            # non-deterministic events with their own provenance.
            self._append_merged({**ev, "process": 0} for ev in chunk0)
            for p, (r, det, chunk) in heads[1:]:
                self._append_merged(
                    {**ev, "process": p} for ev in chunk
                    if ev.get("kind") in _FOLLOWER_KINDS)
            for p in self.processes:
                st = self._procs[p]
                st.sealed.popleft()
                st.watermark = r0
            self.rounds_merged += 1

    def _compare(self, process: int, r0: int, det0: list,
                 r: int, det: list) -> dict[str, Any] | None:
        if r != r0:
            return {"round": r0, "process": process, "index": 0,
                    "leader": {"kind": "round", "round": r0},
                    "other": {"kind": "round", "round": r},
                    "reason": f"round sequence mismatch: leader sealed "
                              f"round {r0}, process {process} sealed "
                              f"round {r}"}
        for i in range(min(len(det0), len(det))):
            if det0[i] != det[i]:
                return {"round": r0, "process": process, "index": i,
                        "leader": det0[i], "other": det[i],
                        "reason": "deterministic payload mismatch"}
        if len(det0) != len(det):
            i = min(len(det0), len(det))
            longer = det0 if len(det0) > len(det) else det
            return {"round": r0, "process": process, "index": i,
                    "leader": det0[i] if i < len(det0) else None,
                    "other": det[i] if i < len(det) else None,
                    "reason": f"bundle length mismatch at round {r0}: "
                              f"leader {len(det0)} deterministic events,"
                              f" process {process} {len(det)} "
                              f"(next unmatched: {longer[i].get('kind')})"}
        return None

    def _diverge(self, record: dict[str, Any]) -> None:
        self.divergence = record
        if self.strict:
            raise FleetDivergenceError(record)

    def flush_trailing(self) -> None:
        """End-of-run settlement: the events after the last ``round``
        (the drain boundary's control events, the end-of-run summary
        gauge, the final checkpoint marker) never fleet-seal through a
        round event — verify their deterministic subset across
        processes and append them to the merge.  Call once the run is
        over (CLI ``--once`` mode); a live endpoint never flushes."""
        if self.divergence is not None:
            return
        st0 = self._procs[0]
        det0 = [_canon(e) for e in st0.pending
                if e.get("kind") in DETERMINISTIC_KINDS]
        for p in self.processes[1:]:
            st = self._procs[p]
            det = [_canon(e) for e in st.pending
                   if e.get("kind") in DETERMINISTIC_KINDS]
            tail_round = st0.watermark if st0.watermark is not None else -1
            rec = self._compare(p, tail_round, det0, tail_round, det)
            if rec is not None:
                rec["reason"] = "trailing (post-last-round) " \
                    + rec["reason"]
                self._diverge(rec)
                return
        self._append_merged({**ev, "process": 0} for ev in st0.pending)
        st0.pending = []
        for p in self.processes[1:]:
            st = self._procs[p]
            self._append_merged({**ev, "process": p} for ev in st.pending
                                if ev.get("kind") in _FOLLOWER_KINDS)
            st.pending = []

    def _append_merged(self, events) -> None:
        for ev in events:
            self.merged.append(ev)
            self.merged_total += 1

    def drain_merged(self) -> list[dict[str, Any]]:
        """Hand over (and forget) the merged events accumulated since
        the last drain — the streaming-consumer mode: a resident fleet
        endpoint feeds its sinks from the drain so supervisor memory
        stays flat over days, while batch callers (the CLI) read
        ``merged`` whole.  ``merged_total`` keeps the lifetime count."""
        out, self.merged = self.merged, []
        return out

    # -- results -------------------------------------------------------
    def alerts(self) -> list[dict[str, Any]]:
        """Every process's stream-embedded alerts, process-stamped,
        in (process, observation) order."""
        out: list[dict[str, Any]] = []
        for p in self.processes:
            out.extend(self._procs[p].alerts)
        return out

    def stats(self, now: float | None = None) -> dict[str, Any]:
        if now is None:
            now = time.time()  # dopt: allow-wallclock -- lag meter vs event ts stamps, reporting only
        return {
            "processes": {p: self._procs[p].snapshot(now)
                          for p in self.processes},
            "fleet_round": min(
                (st.watermark for st in self._procs.values()
                 if st.watermark is not None), default=None),
            "rounds_merged": self.rounds_merged,
            "merged_events": self.merged_total,
            "divergence": self.divergence,
        }

    def write_merged(self, path: str | Path) -> Path:
        """Write the merged stream as JSONL — the artifact
        ``python -m dopt.obs.check`` validates in the soak."""
        from dopt.utils.metrics import atomic_write_text

        return atomic_write_text(Path(path), "".join(
            json.dumps(ev, separators=(",", ":"), sort_keys=True) + "\n"
            for ev in self.merged))


class FleetMetricsServer:
    """The supervisor's one fleet scrape surface over a serve state
    dir: ``/metrics`` (PrometheusSink over the merged stream — the
    fleet's SLO latency histograms aggregate across processes) and
    ``/healthz`` (merged HealthMonitor report + per-process
    watermark/lag + divergence; 503 with ``Retry-After`` and a JSON
    body once critical or diverged)."""

    def __init__(self, state_dir: str | Path, *,
                 num_processes: int | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 rules=None, workers: int | None = None):
        self.state_dir = Path(state_dir)
        self.agg = FleetAggregator(self.state_dir,
                                   num_processes=num_processes)
        self.monitor = HealthMonitor(rules, workers=workers)
        self.prom = PrometheusSink()
        self._error: str | None = None
        # RLock held for whole request bodies (refresh AND render):
        # ThreadingHTTPServer serves scrapes concurrently, and a
        # render iterating the sink's dicts while another request's
        # refresh mutates them would tear the exposition.
        self._lock = threading.RLock()
        self._httpd = ThreadingHTTPServer((host, port), self._handler())
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def refresh(self) -> None:
        with self._lock:
            try:
                self.agg.poll()
                self._error = None
            except ValueError as e:
                # Mid-file garbage in one stream: surface it through
                # /healthz instead of crashing the request handler.
                self._error = str(e)
            # Drain, don't slice: the supervisor is resident for days
            # and must not retain the whole run's event history.
            for ev in self.agg.drain_merged():
                self.prom.emit(ev)
                # The fleet monitor re-derives alerts from the merged
                # stream for ITS verdict only — the stream's embedded
                # alert events (the leader monitor's, just emitted
                # above) are the fleet's alert COUNT; counting the
                # re-derivation too would double dopt_alerts_total.
                self.monitor.observe(ev)

    def render_metrics(self) -> str:
        with self._lock:
            return self._render_metrics_locked()

    def _render_metrics_locked(self) -> str:
        self.refresh()
        stats = self.agg.stats()
        lines = [self.prom.render().rstrip("\n")]
        lines.append("# HELP dopt_fleet_processes processes whose "
                     "streams the aggregator tails")
        lines.append("# TYPE dopt_fleet_processes gauge")
        lines.append(f"dopt_fleet_processes {len(self.agg.processes)}")
        lines.append("# HELP dopt_fleet_round last fleet-sealed round "
                     "per process stream")
        lines.append("# TYPE dopt_fleet_round gauge")
        lines.append("# HELP dopt_fleet_lag_seconds wall seconds since "
                     "each process stream's newest event")
        lines.append("# TYPE dopt_fleet_lag_seconds gauge")
        for p, snap in sorted(stats["processes"].items()):
            if snap["round"] is not None:
                lines.append(f'dopt_fleet_round{{process="{p}"}} '
                             f'{snap["round"]}')
            if snap["lag_seconds"] is not None:
                lines.append(f'dopt_fleet_lag_seconds{{process="{p}"}} '
                             f'{snap["lag_seconds"]:.3f}')
        lines.append("# HELP dopt_fleet_divergent 1 once any process's "
                     "deterministic stream diverged from the leader's")
        lines.append("# TYPE dopt_fleet_divergent gauge")
        lines.append("dopt_fleet_divergent "
                     f"{1 if self.agg.divergence else 0}")
        return "\n".join(lines) + "\n"

    def render_health(self) -> tuple[int, str]:
        with self._lock:
            return self._render_health_locked()

    def _render_health_locked(self) -> tuple[int, str]:
        self.refresh()
        report = self.monitor.report()
        body = report.to_dict()
        body["fleet"] = self.agg.stats()
        body["lag_seconds"] = self.monitor.lag_seconds()
        body["state_dir"] = str(self.state_dir)
        body["alerts_by_process"] = [
            {"process": a.get("process"), "rule": a.get("rule"),
             "severity": a.get("severity"), "round": a.get("round")}
            for a in self.agg.alerts()]
        body["error"] = self._error
        ok = (report.ok and self.agg.divergence is None
              and self._error is None)
        return (200 if ok else 503), json.dumps(body, indent=2)

    def _handler(self) -> type[BaseHTTPRequestHandler]:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path == "/metrics":
                    body = server.render_metrics().encode()
                    self._reply(200, body,
                                "text/plain; version=0.0.4; charset=utf-8")
                elif path == "/healthz":
                    code, text = server.render_health()
                    self._reply(code, text.encode(), "application/json")
                elif path == "/":
                    self._reply(200,
                                b"dopt fleet metrics: /metrics /healthz\n",
                                "text/plain")
                else:
                    self._reply(404, b'{"error": "not found"}\n',
                                "application/json")

            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                from dopt.obs.serve import http_reply

                http_reply(self, code, body, ctype)

            def log_message(self, fmt: str, *args: Any) -> None:
                pass   # scrapes would flood the supervisor's stderr

        return Handler

    def start(self) -> "FleetMetricsServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--state-dir", required=True,
                    help="serve state dir holding metrics.jsonl (+ "
                         "metrics-p<i>.jsonl per follower)")
    ap.add_argument("--processes", type=int, default=None, metavar="N",
                    help="expected fleet size (default: discover "
                         "follower streams by glob)")
    ap.add_argument("--merged-out", default=None, metavar="PATH",
                    help="write the merged, process-stamped stream "
                         "here (the artifact dopt.obs.check validates)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)

    agg = FleetAggregator(args.state_dir, num_processes=args.processes)
    try:
        agg.poll()
        agg.flush_trailing()
    except ValueError as e:   # mid-file garbage from a corrupt stream
        print(f"FAIL {e}", file=sys.stderr)
        return 1
    summary = None
    error = None
    if agg.divergence is None:
        try:
            summary = check_stream(
                [{k: v for k, v in ev.items() if k != "process"}
                 for ev in agg.merged])
        except ValueError as e:
            error = str(e)
    if args.merged_out:
        agg.write_merged(args.merged_out)
    stats = agg.stats()
    if args.json:
        json.dump({"tool": "dopt.obs.aggregate",
                   "state_dir": args.state_dir,
                   "ok": agg.divergence is None and error is None,
                   "divergence": agg.divergence, "error": error,
                   "stats": stats, "merged_check": summary},
                  sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    elif agg.divergence is not None:
        print(format_fleet_divergence(agg.divergence), file=sys.stderr)
    elif error is not None:
        print(f"FAIL merged stream: {error}", file=sys.stderr)
    else:
        procs = " ".join(
            f"p{p}@{snap['round']}"
            for p, snap in sorted(stats["processes"].items()))
        print(f"fleet consistent: {stats['rounds_merged']} rounds "
              f"verified across {len(agg.processes)} processes "
              f"({procs}), {len(agg.merged)} merged events")
    return 0 if (agg.divergence is None and error is None) else 1


if __name__ == "__main__":
    raise SystemExit(main())
