"""Compression operators + CHOCO-SGD engine semantics."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dopt.ops.compression import (make_compressor, rand_k_compress,
                                  top_k_compress)
from tests.test_engine import _gossip_cfg
from dopt.engine import GossipTrainer


def _tree(seed=0):
    rng = np.random.default_rng(seed)
    return {"a": jnp.asarray(rng.normal(size=(4, 10)).astype(np.float32)),
            "b": jnp.asarray(rng.normal(size=(4, 3, 5)).astype(np.float32))}


def test_topk_keeps_largest_per_worker():
    tree = _tree()
    out = top_k_compress(tree, 0.3)
    for k in tree:
        x = np.asarray(tree[k]).reshape(4, -1)
        y = np.asarray(out[k]).reshape(4, -1)
        n = x.shape[1]
        keep = int(np.ceil(0.3 * n))
        for w in range(4):
            nz = np.nonzero(y[w])[0]
            assert len(nz) == keep
            # kept entries are exactly the top-|.| ones
            thresh = np.sort(np.abs(x[w]))[-keep]
            assert np.all(np.abs(x[w][nz]) >= thresh - 1e-12)
            np.testing.assert_array_equal(y[w][nz], x[w][nz])


def test_ratio_one_is_identity():
    tree = _tree()
    for name in ("topk", "randk", "none"):
        comp = make_compressor(name, 1.0)
        out = comp(tree, jax.random.key(0))
        for k in tree:
            np.testing.assert_array_equal(np.asarray(out[k]),
                                          np.asarray(tree[k]))


def test_randk_unbiased_rescaling():
    tree = {"a": jnp.ones((2, 2000), jnp.float32)}
    out = rand_k_compress(tree, 0.25, jax.random.key(3))
    y = np.asarray(out["a"])
    kept = y != 0
    # kept entries rescaled by 1/ratio; empirical mean ~= original mean
    np.testing.assert_allclose(y[kept], 4.0)
    assert abs(y.mean() - 1.0) < 0.15


def test_choco_identity_compression_equals_dsgd(devices):
    # Q = identity, gamma = 1: CHOCO reduces exactly to D-SGD.
    def run(algorithm, **extra):
        cfg = _gossip_cfg(gossip=dict(algorithm=algorithm, rounds=3, **extra))
        tr = GossipTrainer(cfg)
        tr.run()
        return tr

    a = run("dsgd")
    b = run("choco", compression="none", choco_gamma=1.0)
    for x, y in zip(jax.tree.leaves(jax.device_get(a.params)),
                    jax.tree.leaves(jax.device_get(b.params))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(a.history["avg_test_acc"],
                               b.history["avg_test_acc"], atol=1e-5)


def test_choco_topk_learns_and_contracts(devices):
    # 20% top-k compressed gossip still learns and keeps workers close.
    cfg = _gossip_cfg(gossip=dict(algorithm="choco", rounds=6,
                                  compression="topk",
                                  compression_ratio=0.2,
                                  choco_gamma=0.8))
    tr = GossipTrainer(cfg)
    h = tr.run()
    assert h.last()["avg_test_acc"] > 0.5
    # public copies track params: residual shrinks below the raw scale
    p = jax.device_get(tr.params)
    xh = jax.device_get(tr.x_hat)
    num = sum(float(np.abs(np.asarray(a) - np.asarray(b)).sum())
              for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(xh)))
    den = sum(float(np.abs(np.asarray(a)).sum()) for a in jax.tree.leaves(p))
    assert num / den < 1.0


def test_choco_blocked_matches_per_round(devices):
    def run(block):
        cfg = _gossip_cfg(gossip=dict(algorithm="choco", rounds=4,
                                      compression="topk",
                                      compression_ratio=0.3,
                                      choco_gamma=0.9))
        tr = GossipTrainer(cfg)
        tr.run(rounds=4, block=block)
        return tr

    a = run(1)
    b = run(2)
    for x, y in zip(jax.tree.leaves(jax.device_get(a.params)),
                    jax.tree.leaves(jax.device_get(b.params))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(a.history["avg_train_loss"],
                               b.history["avg_train_loss"], atol=1e-5)


def test_choco_checkpoint_roundtrip(devices, tmp_path):
    cfg = _gossip_cfg(gossip=dict(algorithm="choco", rounds=2,
                                  compression="topk",
                                  compression_ratio=0.5))
    a = GossipTrainer(cfg)
    a.run(rounds=2)
    a.save(tmp_path / "ck")
    a.run(rounds=2)  # continuous: 4 rounds total

    b = GossipTrainer(cfg)
    b.restore(tmp_path / "ck")
    assert b.round == 2
    b.run(rounds=2)  # resumed: rounds 2-3
    for x, y in zip(jax.tree.leaves(jax.device_get(a.params)),
                    jax.tree.leaves(jax.device_get(b.params))):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=1e-6, rtol=1e-5)


def test_compressor_rejects_bad_ratio():
    for bad in (0.0, -0.5, 1.5):
        with pytest.raises(ValueError):
            make_compressor("randk", bad)
    with pytest.raises(ValueError):
        make_compressor("signsgd", 0.5)


def test_qsgd_unbiased_and_bounded():
    from dopt.ops.compression import qsgd_compress

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 4000)).astype(np.float32))
    tree = {"a": x}
    # Average many independent quantizations -> unbiased estimate of x.
    acc = np.zeros((2, 4000), np.float64)
    trials = 50
    for i in range(trials):
        out = qsgd_compress(tree, 0.25, jax.random.key(i), bucket_size=256)
        acc += np.asarray(out["a"], np.float64)
    mean = acc / trials
    err = np.abs(mean - np.asarray(x)).mean()
    assert err < 0.03
    # zero input stays exactly zero
    z = qsgd_compress({"a": jnp.zeros((2, 8))}, 0.25, jax.random.key(0))
    np.testing.assert_array_equal(np.asarray(z["a"]), 0.0)


def test_choco_qsgd_learns(devices):
    cfg = _gossip_cfg(gossip=dict(algorithm="choco", rounds=5,
                                  compression="qsgd",
                                  compression_ratio=0.1,
                                  choco_gamma=0.8))
    tr = GossipTrainer(cfg)
    h = tr.run()
    assert h.last()["avg_test_acc"] > 0.5


def test_randk_fixed_cardinality():
    """rand-k keeps EXACTLY ceil(ratio·n) entries per worker per leaf
    (fixed wire size), uniformly without replacement, unbiased."""
    import jax

    x = {"a": jnp.ones((4, 100)), "b": jnp.ones((4, 7))}
    out = rand_k_compress(x, 0.25, jax.random.key(0))
    for name, n, k in (("a", 100, 25), ("b", 7, 2)):
        nz = np.count_nonzero(np.asarray(out[name]), axis=1)
        np.testing.assert_array_equal(nz, k)
        # surviving entries carry the n/k unbiasedness rescale
        vals = np.asarray(out[name])
        assert np.allclose(vals[vals != 0], n / k, rtol=1e-6)
    # unbiased in expectation over keys
    means = np.mean([np.asarray(
        rand_k_compress(x, 0.25, jax.random.key(s))["a"]).mean()
        for s in range(64)])
    assert abs(means - 1.0) < 0.05


def test_qsgd_levels_knob():
    import jax

    from dopt.ops.compression import make_compressor

    x = {"w": jnp.asarray(np.random.default_rng(0).normal(size=(2, 512)),
                          jnp.float32)}
    # explicit coarse level count quantizes more harshly than 256 levels
    c4 = make_compressor("qsgd", 1.0, qsgd_levels=4)
    c256 = make_compressor("qsgd", 1.0)
    e4 = float(jnp.abs(c4(x, jax.random.key(1))["w"] - x["w"]).mean())
    e256 = float(jnp.abs(c256(x, jax.random.key(1))["w"] - x["w"]).mean())
    assert e4 > 3 * e256 > 0
    with pytest.raises(ValueError, match="qsgd_levels"):
        make_compressor("topk", 0.5, qsgd_levels=8)
    with pytest.raises(ValueError, match="qsgd_levels"):
        make_compressor("qsgd", 1.0, qsgd_levels=-1)


def test_choco_gamma_warning(devices):
    import dataclasses
    import warnings

    from dopt.config import (DataConfig, ExperimentConfig, GossipConfig,
                             ModelConfig, OptimizerConfig)
    from dopt.engine import GossipTrainer

    def cfg(gamma, ratio):
        return ExperimentConfig(
            name="t", seed=0,
            data=DataConfig(dataset="synthetic", num_users=8,
                            synthetic_train_size=256,
                            synthetic_test_size=64),
            model=ModelConfig(model="mlp", faithful=False),
            optim=OptimizerConfig(lr=0.05),
            gossip=GossipConfig(algorithm="choco", topology="circle",
                                mode="metropolis", rounds=1, local_ep=1,
                                local_bs=32, choco_gamma=gamma,
                                compression="topk",
                                compression_ratio=ratio),
        )

    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        GossipTrainer(cfg(1.0, 0.1))
    assert any("choco_gamma" in str(w.message) for w in rec)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        GossipTrainer(cfg(1.0, 1.0))   # identity compressor: fine
        GossipTrainer(cfg(0.05, 0.1))  # scaled-down gamma: fine
    assert not any("choco_gamma" in str(w.message) for w in rec)
