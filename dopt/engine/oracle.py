"""Faithful torch-CPU oracle backend.

A from-scratch PyTorch implementation of the reference's exact training
numerics (NOT a copy of the reference code — same math, written against
SURVEY.md's semantics inventory), used as the step-level ground truth
for the jax engine:

* Models: the faithful architectures (conv stack with NO activations,
  ReLU only between the Dense layers, Softmax head —
  ``models.py:6-27`` / ``:31-51``), NCHW like torch wants.
* Local update: ``torch.optim.SGD(lr, momentum)`` epochs over the SAME
  deterministic batch plan the jax engine consumes
  (``clients.py:36-53`` P1 / ``:34-59`` P2).
* FedProx/FedADMM: the reference's in-place ``param.grad`` edits
  (``clients.py:111``, ``:135``, ``:141-144``).
* Consensus: weighted state-dict sum ``w_i ← Σ_j a_ij w_j``
  (``clients.py:61-69`` P2).

Precision note: parity is validated jax-CPU vs torch-CPU (agreement
~1e-5).  On TPU, fp32 matmuls/convs use reduced internal precision by
default (bf16 passes), so TPU-vs-oracle agreement is ~5e-4 on
probabilities; set ``jax_default_matmul_precision=highest`` for strict
TPU-side comparisons at a throughput cost.

Parameter conversion handles the NHWC↔NCHW layout difference: flax conv
kernels are [H, W, I, O] vs torch [O, I, H, W], flax dense [in, out] vs
torch [out, in], and the first dense layer's input ordering differs
because the reference flattens NCHW channel-major while the flax model
flattens NHWC (``models.py:24`` vs ``dopt.models.zoo``).
"""

from __future__ import annotations

from typing import Mapping

import numpy as np

try:
    import torch
    import torch.nn as nn
    import torch.nn.functional as F

    HAVE_TORCH = True
except ImportError:  # pragma: no cover - torch is in the image
    HAVE_TORCH = False


# ---------------------------------------------------------------------
# Faithful torch models (NCHW)
# ---------------------------------------------------------------------

def torch_reference_cnn(in_channels: int, spatial: int, hidden: int,
                        num_classes: int = 10, faithful: bool = True):
    """The reference CNN shape: conv(k5,p2)→pool→conv(k5,p2)→pool→
    Dense(hidden)→ReLU→Dense(classes)[→Softmax]."""
    flat = (spatial // 4) ** 2 * 64

    class _Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(in_channels, 32, 5, padding=2)
            self.conv2 = nn.Conv2d(32, 64, 5, padding=2)
            self.fc1 = nn.Linear(flat, hidden)
            self.fc2 = nn.Linear(hidden, num_classes)

        def forward(self, x):
            x = self.conv1(x)
            if not faithful:
                x = F.relu(x)
            x = F.max_pool2d(x, 2)
            x = self.conv2(x)
            if not faithful:
                x = F.relu(x)
            x = F.max_pool2d(x, 2)
            x = x.reshape(x.shape[0], -1)
            x = F.relu(self.fc1(x))
            x = self.fc2(x)
            return F.softmax(x, dim=-1) if faithful else x

    return _Net()


def torch_mlp(flat: int, hidden=(200, 200), num_classes: int = 10,
              faithful: bool = False):
    """Torch twin of ``dopt.models.zoo.MLP`` (same layer names, so
    ``flax_dense_params_to_torch`` maps state dicts 1:1).  Input NCHW;
    only C=1 (or already-flat) inputs flatten identically to the flax
    NHWC model."""

    class _MLP(nn.Module):
        def __init__(self):
            super().__init__()
            dims = [flat, *hidden]
            for i, (a, b) in enumerate(zip(dims[:-1], dims[1:])):
                setattr(self, f"fc{i + 1}", nn.Linear(a, b))
            self.head = nn.Linear(dims[-1], num_classes)
            self.n_hidden = len(hidden)

        def forward(self, x):
            x = x.reshape(x.shape[0], -1)
            for i in range(self.n_hidden):
                x = F.relu(getattr(self, f"fc{i + 1}")(x))
            x = self.head(x)
            return F.softmax(x, dim=-1) if faithful else x

    return _MLP()


def torch_logistic(flat: int, num_classes: int = 2, faithful: bool = False):
    """Torch twin of ``dopt.models.zoo.LogisticRegression``."""

    class _Log(nn.Module):
        def __init__(self):
            super().__init__()
            self.linear = nn.Linear(flat, num_classes)

        def forward(self, x):
            x = self.linear(x.reshape(x.shape[0], -1))
            return F.softmax(x, dim=-1) if faithful else x

    return _Log()


def flax_dense_params_to_torch(params: Mapping) -> dict:
    """Dense-only flax tree {name: {kernel, bias}} → torch state_dict
    {name.weight, name.bias} (kernel [in, out] → weight [out, in])."""
    out = {}
    for name, leaf in params.items():
        out[f"{name}.weight"] = torch.from_numpy(
            np.asarray(leaf["kernel"]).T.copy())
        out[f"{name}.bias"] = torch.from_numpy(np.asarray(leaf["bias"]).copy())
    return out


def torch_dense_params_to_flax(state: Mapping) -> dict:
    """Inverse of ``flax_dense_params_to_torch``."""
    out: dict = {}
    for key, v in state.items():
        name, kind = key.rsplit(".", 1)
        leaf = out.setdefault(name, {})
        arr = v.detach().cpu().numpy()
        leaf["kernel" if kind == "weight" else "bias"] = (
            arr.T.copy() if kind == "weight" else arr.copy())
    return out


# ---------------------------------------------------------------------
# Parameter conversion (flax pytree <-> torch state_dict)
# ---------------------------------------------------------------------

def _conv_to_torch(k: np.ndarray) -> np.ndarray:
    return np.transpose(k, (3, 2, 0, 1))  # [H,W,I,O] -> [O,I,H,W]


def _dense_to_torch(k: np.ndarray) -> np.ndarray:
    return np.transpose(k)  # [in,out] -> [out,in]


def _fc1_to_torch(k: np.ndarray, spatial: int, channels: int = 64) -> np.ndarray:
    """First dense after flatten: reorder flax's HWC input ordering to
    torch's CHW before transposing."""
    s = spatial // 4
    out = k.shape[1]
    k = k.reshape(s, s, channels, out)          # [H,W,C,out]
    k = np.transpose(k, (2, 0, 1, 3))           # [C,H,W,out]
    return np.transpose(k.reshape(s * s * channels, out))  # [out, CHW]


def flax_cnn_params_to_torch(params: Mapping, spatial: int) -> dict[str, "torch.Tensor"]:
    """Convert a dopt Model1/Model3 flax param tree into the faithful
    torch model's state_dict."""
    t = torch.from_numpy
    p = {k: np.asarray(v) for k, v in _flatten2(params).items()}
    return {
        "conv1.weight": t(_conv_to_torch(p["conv1.kernel"]).copy()),
        "conv1.bias": t(p["conv1.bias"].copy()),
        "conv2.weight": t(_conv_to_torch(p["conv2.kernel"]).copy()),
        "conv2.bias": t(p["conv2.bias"].copy()),
        "fc1.weight": t(_fc1_to_torch(p["fc1.kernel"], spatial).copy()),
        "fc1.bias": t(p["fc1.bias"].copy()),
        "fc2.weight": t(_dense_to_torch(p["fc2.kernel"]).copy()),
        "fc2.bias": t(p["fc2.bias"].copy()),
    }


def torch_cnn_params_to_flax(state: Mapping[str, "torch.Tensor"], spatial: int):
    """Inverse conversion, for loading oracle results back into jax."""
    s = spatial // 4

    def fc1_to_flax(w: np.ndarray) -> np.ndarray:
        out = w.shape[0]
        k = w.T.reshape(64, s, s, out)          # [C,H,W,out]
        k = np.transpose(k, (1, 2, 0, 3))       # [H,W,C,out]
        return k.reshape(s * s * 64, out)

    g = {k: v.detach().cpu().numpy() for k, v in state.items()}
    return {
        "conv1": {"kernel": np.transpose(g["conv1.weight"], (2, 3, 1, 0)),
                  "bias": g["conv1.bias"]},
        "conv2": {"kernel": np.transpose(g["conv2.weight"], (2, 3, 1, 0)),
                  "bias": g["conv2.bias"]},
        "fc1": {"kernel": fc1_to_flax(g["fc1.weight"]), "bias": g["fc1.bias"]},
        "fc2": {"kernel": np.transpose(g["fc2.weight"]), "bias": g["fc2.bias"]},
    }


def _flatten2(tree, prefix="") -> dict[str, np.ndarray]:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{k}" if not prefix else f"{prefix}.{k}"
        if isinstance(v, Mapping):
            out.update(_flatten2(v, key))
        else:
            out[key] = v
    return out


# ---------------------------------------------------------------------
# Oracle worker: reference-exact local training
# ---------------------------------------------------------------------

class OracleWorker:
    """One reference client: model + persistent SGD optimizer.

    The optimizer lives for the worker's lifetime (its momentum buffers
    survive consensus/theta loads), matching ``Client.__init__``
    creating the optimizer once.
    """

    def __init__(self, model: "nn.Module", *, lr: float, momentum: float,
                 rho: float = 0.0, algorithm: str = "sgd", l2: float = 0.0):
        assert HAVE_TORCH
        self.model = model
        self.optimizer = torch.optim.SGD(model.parameters(), lr=lr,
                                         momentum=momentum)
        self.rho = rho
        self.l2 = l2  # explicit λ‖θ‖²/2 loss term (dopt l2_regulariser)
        self.algorithm = algorithm
        if algorithm == "fedadmm":
            self.alpha = {n: torch.zeros_like(p)
                          for n, p in model.named_parameters()}
        if algorithm == "scaffold":
            # Client control variate c_i (SCAFFOLD; the reference's
            # commented-out sketch, clients.py:146-170, done properly).
            self.control = {n: torch.zeros_like(p)
                            for n, p in model.named_parameters()}

    def load(self, state: Mapping[str, "torch.Tensor"]) -> None:
        self.model.load_state_dict({k: v.clone() for k, v in state.items()})

    def state(self) -> dict[str, "torch.Tensor"]:
        return {k: v.clone() for k, v in self.model.state_dict().items()}

    def local_update(self, bx: np.ndarray, by: np.ndarray, bw: np.ndarray,
                     theta: Mapping | None = None,
                     c_global: Mapping | None = None) -> float:
        """Run the batch-plan steps: bx [S,B,C,H,W] (NCHW), by [S,B],
        bw [S,B] padding weights.  Returns mean loss."""
        if self.algorithm == "scaffold" and c_global is None:
            raise ValueError("scaffold local_update requires c_global")
        losses: list[float] = []
        self._epoch_steps(bx, by, bw, theta, c_global, losses, [0.0, 0.0])
        return float(np.mean(losses))

    def inference(self, bx: np.ndarray, by: np.ndarray,
                  bw: np.ndarray) -> tuple[float, float, float]:
        """Reference ``Client.inference`` over a static [S, B, ...] NCHW
        eval stack (P1 clients.py:61-75 / P2 clients.py:71-86): returns
        (accuracy, summed-batch-loss [P1 flavour], mean-per-batch loss
        [P2 flavour]); padding rows carry weight 0."""
        self.model.eval()
        losses, correct, total = [], 0.0, 0.0
        with torch.no_grad():
            for s in range(bx.shape[0]):
                x = torch.from_numpy(np.ascontiguousarray(bx[s]))
                y = torch.from_numpy(np.ascontiguousarray(by[s])).long()
                w = torch.from_numpy(np.ascontiguousarray(bw[s]))
                out = self.model(x)
                per = F.cross_entropy(out, y, reduction="none")
                losses.append(float((per * w).sum() / w.sum().clamp(min=1.0)))
                pred = out.argmax(dim=1)
                correct += float(((pred == y).float() * w).sum())
                total += float(w.sum())
        self.model.train()
        acc = correct / max(total, 1.0)
        return acc, float(np.sum(losses)), float(np.mean(losses))

    def local_update_epochs(self, bx, by, bw, vx, vy, vw,
                            theta: Mapping | None = None,
                            c_global: Mapping | None = None,
                            val_flavor: str = "mean") -> list[dict]:
        """The reference's epoch-structured ``update_weights`` /
        ``local_update`` (P1 clients.py:38-50, P2 clients.py:37-57):
        bx is [E, S', B, ...] epoch-major; after each epoch's steps the
        local validation stack (vx, vy, vw) is evaluated and a history
        row {train_loss, train_acc, val_acc, val_loss} recorded
        (val_loss in the P1 'sum' or P2 'mean' flavour)."""
        if self.algorithm == "scaffold" and c_global is None:
            raise ValueError("scaffold local_update requires c_global")
        rows = []
        for e in range(bx.shape[0]):
            correct_total = [0.0, 0.0]
            losses: list[float] = []
            loss_mean = self._epoch_steps(bx[e], by[e], bw[e], theta,
                                          c_global, losses, correct_total)
            vacc, vsum, vmean = self.inference(vx, vy, vw)
            rows.append({
                "epoch": e,
                "train_loss": loss_mean,
                "train_acc": correct_total[0] / max(correct_total[1], 1.0),
                "val_acc": vacc,
                "val_loss": vsum if val_flavor == "sum" else vmean,
            })
        return rows

    def _epoch_steps(self, bx, by, bw, theta, c_global, losses,
                     correct_total) -> float:
        """One run of SGD steps over a [S, B, ...] stack (the shared
        training body of ``local_update`` and ``local_update_epochs``),
        appending per-batch losses and accumulating the weighted correct
        count into ``correct_total``; returns the mean batch loss
        (``sum(train_loss)/len(train_loss)``)."""
        theta_t = ({k: v.detach().clone() for k, v in theta.items()}
                   if theta is not None else None)
        for s in range(bx.shape[0]):
            x = torch.from_numpy(np.ascontiguousarray(bx[s]))
            y = torch.from_numpy(np.ascontiguousarray(by[s])).long()
            w = torch.from_numpy(np.ascontiguousarray(bw[s]))
            self.optimizer.zero_grad()
            out = self.model(x)
            per = F.cross_entropy(out, y, reduction="none")
            loss = (per * w).sum() / w.sum().clamp(min=1.0)
            if self.l2:
                loss = loss + 0.5 * self.l2 * sum(
                    (p ** 2).sum() for p in self.model.parameters())
            loss.backward()
            if self.algorithm in ("fedprox", "fedadmm"):
                for n, p in self.model.named_parameters():
                    if p.grad is None:
                        continue
                    extra = self.rho * (p.detach() - theta_t[n])
                    if self.algorithm == "fedadmm":
                        extra = extra + self.alpha[n]
                    p.grad = p.grad + extra
            elif self.algorithm == "scaffold":
                for n, p in self.model.named_parameters():
                    if p.grad is None:
                        continue
                    p.grad = p.grad - self.control[n] + c_global[n]
            self.optimizer.step()
            losses.append(float(loss.detach()))
            with torch.no_grad():
                pred = out.argmax(dim=1)
                correct_total[0] += float(((pred == y).float() * w).sum())
                correct_total[1] += float(w.sum())
        return float(np.mean(losses[-bx.shape[0]:]))

    def update_duals(self, theta: Mapping) -> None:
        """ADMM dual ascent after the local epochs (clients.py:141-144)."""
        with torch.no_grad():
            for n, p in self.model.named_parameters():
                self.alpha[n] = self.alpha[n] + self.rho * (p - theta[n])

    def update_controls(self, theta: Mapping, c_global: Mapping,
                        lr: float, num_steps: int) -> dict:
        """SCAFFOLD option-II refresh c_i⁺ = c_i − c + (theta − y)/(K·lr);
        returns the delta c_i⁺ − c_i the server accumulates into c."""
        scale = 1.0 / (lr * max(num_steps, 1))
        delta = {}
        with torch.no_grad():
            for n, p in self.model.named_parameters():
                new = (self.control[n] - c_global[n]
                       + scale * (theta[n] - p.detach()))
                delta[n] = new - self.control[n]
                self.control[n] = new
        return delta


def consensus(neighbor_states: list[tuple[float, Mapping]]) -> dict:
    """w ← Σ_j a_j · state_j (reference ``Client.consensus``,
    clients.py:61-69): plain weighted sum, NO implicit self term."""
    out: dict = {}
    for a, st in neighbor_states:
        for k, v in st.items():
            acc = out.get(k)
            out[k] = a * v if acc is None else acc + a * v
    return out


def nhwc_to_nchw(x: np.ndarray) -> np.ndarray:
    """Batch-plan features [..., H, W, C] -> [..., C, H, W]."""
    return np.moveaxis(x, -1, -3)
