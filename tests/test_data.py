"""Data layer: parsers, synthetic fallback, partitioners, batch plans."""

import numpy as np
import pytest

from dopt.data import (
    BatchPlan,
    gather_batches,
    iid_split,
    load_dataset,
    make_batch_plan,
    noniid_split,
    partition,
)
from dopt.data.datasets import make_synthetic
from dopt.data.pipeline import eval_batches


def test_synthetic_deterministic_and_learnable():
    a = make_synthetic(seed=3, train_size=256, test_size=64)
    b = make_synthetic(seed=3, train_size=256, test_size=64)
    np.testing.assert_array_equal(a.train_x, b.train_x)
    assert a.train_x.shape == (256, 28, 28, 1)
    assert a.num_classes == 10
    # Nearest-prototype classification must beat chance by a wide margin
    # (the data is learnable by construction).
    protos = np.stack([a.train_x[a.train_y == c].mean(0).ravel() for c in range(10)])
    d = ((a.test_x.reshape(len(a.test_y), -1)[:, None, :] - protos[None]) ** 2).sum(-1)
    acc = (d.argmin(1) == a.test_y).mean()
    assert acc > 0.8


def test_load_dataset_synthetic_fallback():
    ds = load_dataset("mnist", data_dir=None, train_size=128, test_size=32)
    assert ds.name == "synthetic[mnist]"
    assert ds.input_shape == (28, 28, 1)
    ds = load_dataset("cifar10", train_size=64, test_size=16)
    assert ds.input_shape == (32, 32, 3)
    ds = load_dataset("a9a", train_size=64, test_size=16)
    assert ds.input_shape == (123,) and ds.num_classes == 2


def test_load_dataset_no_fallback_raises():
    with pytest.raises(FileNotFoundError):
        load_dataset("mnist", synthetic_fallback=False)


def test_iid_split_disjoint_equal():
    labels = np.arange(1000) % 10
    groups = iid_split(labels, 8, seed=0)
    all_idx = np.concatenate(list(groups.values()))
    assert len(all_idx) == len(set(all_idx)), "no sample assigned twice"
    assert all(len(v) == 125 for v in groups.values())


def test_noniid_split_label_concentration():
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 10, size=2000)
    groups = noniid_split(labels, 10, shards_per_user=2, seed=1)
    all_idx = np.concatenate(list(groups.values()))
    assert len(all_idx) == len(set(all_idx))
    # With 2 shards/user each user sees at most 4 distinct labels (each
    # contiguous label-sorted shard can straddle one label boundary).
    for v in groups.values():
        assert len(np.unique(labels[v])) <= 4


def test_partition_matrix_shape():
    labels = np.arange(1024) % 10
    groups, mat = partition(labels, 8, iid=True, seed=0)
    assert mat.shape == (8, 128)
    assert mat.dtype == np.int32


def test_batch_plan_shapes_and_mask():
    mat = np.arange(8 * 100, dtype=np.int64).reshape(8, 100)
    plan = make_batch_plan(mat, batch_size=32, local_ep=2, seed=0, round_idx=0)
    # ceil(100/32)=4 steps/epoch, 2 epochs
    assert plan.idx.shape == (8, 8, 32)
    assert plan.weight.shape == (8, 8, 32)
    # each epoch covers every sample exactly once among mask-1 entries
    for wi in range(8):
        ep0 = plan.idx[wi, :4][plan.weight[wi, :4] == 1.0]
        assert sorted(ep0.tolist()) == mat[wi].tolist()
    # padding count = 4*32-100 = 28 per epoch
    assert (plan.weight[0] == 0).sum() == 2 * 28


def test_batch_plan_deterministic_and_round_varying():
    mat = np.arange(4 * 64).reshape(4, 64)
    a = make_batch_plan(mat, batch_size=16, local_ep=1, seed=5, round_idx=3)
    b = make_batch_plan(mat, batch_size=16, local_ep=1, seed=5, round_idx=3)
    c = make_batch_plan(mat, batch_size=16, local_ep=1, seed=5, round_idx=4)
    np.testing.assert_array_equal(a.idx, b.idx)
    assert not np.array_equal(a.idx, c.idx)


def test_batch_plan_drop_last():
    mat = np.arange(2 * 100).reshape(2, 100)
    plan = make_batch_plan(mat, batch_size=32, local_ep=1, drop_last=True)
    assert plan.idx.shape == (2, 3, 32)
    assert np.all(plan.weight == 1.0)


def test_gather_batches():
    ds = make_synthetic(seed=0, train_size=200, test_size=50)
    _, mat = partition(ds.train_y, 4, iid=True, seed=0)
    plan = make_batch_plan(mat, batch_size=10, local_ep=1, seed=0)
    bx, by, bw = gather_batches(ds.train_x, ds.train_y, plan)
    assert bx.shape == (4, 5, 10, 28, 28, 1)
    assert by.shape == (4, 5, 10)
    assert isinstance(plan, BatchPlan)
    # labels round-trip through the gather
    np.testing.assert_array_equal(by[0, 0], ds.train_y[plan.idx[0, 0]])


def test_eval_batches_mask():
    ds = make_synthetic(seed=0, train_size=64, test_size=50)
    ex, ey, ew = eval_batches(ds.test_x, ds.test_y, batch_size=32)
    assert ex.shape == (2, 32, 28, 28, 1)
    assert ew.sum() == 50


def test_finder_prefers_matching_dataset_dir(tmp_path):
    # torchvision-style shared root: MNIST/raw and FashionMNIST/raw hold
    # identically-named IDX files; 'mnist' must resolve to MNIST's.
    from dopt.data.datasets import _Finder
    for d in ("MNIST/raw", "FashionMNIST/raw"):
        p = tmp_path / d
        p.mkdir(parents=True)
        (p / "train-images-idx3-ubyte").write_bytes(b"x")
    f = _Finder(tmp_path, prefer=("mnist",), avoid=("fashion", "fmnist"))
    hit = f.find(["train-images-idx3-ubyte"])
    assert "FashionMNIST" not in str(hit)
    f2 = _Finder(tmp_path, prefer=("fashion", "fmnist"))
    hit2 = f2.find(["train-images-idx3-ubyte"])
    assert "FashionMNIST" in str(hit2)


def test_batch_plan_worker_subset_matches_full_plan_rows():
    """Compact-sampling planning: workers=[ids] must be bit-identical to
    the matching rows of the full plan (RNG keyed by true worker id)."""
    mat = np.arange(8 * 100, dtype=np.int64).reshape(8, 100)
    full = make_batch_plan(mat, batch_size=32, local_ep=2, seed=7, round_idx=3)
    sel = np.array([1, 4, 6])
    sub = make_batch_plan(mat, batch_size=32, local_ep=2, seed=7, round_idx=3,
                          workers=sel)
    assert sub.idx.shape == (3, 8, 32)
    np.testing.assert_array_equal(sub.idx, full.idx[sel])
    np.testing.assert_array_equal(sub.weight, full.weight[sel])


def test_holdout_split_deterministic_first_tenth():
    """P1 train_val_test: val = FIRST max(int(L/10),1) indices of the
    shard, train = the rest (clients.py:25-28)."""
    from dopt.data import holdout_split

    im = np.arange(200).reshape(4, 50)
    train, val = holdout_split(im, fraction=0.1, mode="deterministic")
    assert val.shape == (4, 5) and train.shape == (4, 45)
    np.testing.assert_array_equal(val, im[:, :5])
    np.testing.assert_array_equal(train, im[:, 5:])


def test_holdout_split_random_properties():
    """P2: seeded random val choice — disjoint, exhaustive, val_size
    rows, deterministic in seed, different across workers."""
    from dopt.data import holdout_split

    im = np.sort(np.random.default_rng(0).choice(10_000, (6, 120),
                                                 replace=False), axis=1)
    train, val = holdout_split(im, fraction=0.1, mode="random", seed=9)
    assert val.shape == (6, 12) and train.shape == (6, 108)
    for i in range(6):
        t, v = set(train[i]), set(val[i])
        assert not t & v
        assert t | v == set(im[i])
    train2, val2 = holdout_split(im, fraction=0.1, mode="random", seed=9)
    np.testing.assert_array_equal(val, val2)
    # different workers draw different val positions
    assert not all(
        set(np.searchsorted(im[i], val[i])) ==
        set(np.searchsorted(im[0], val[0])) for i in range(1, 6))


def test_holdout_split_validation():
    from dopt.data import holdout_split

    im = np.arange(40).reshape(4, 10)
    with pytest.raises(ValueError, match="fraction"):
        holdout_split(im, fraction=0.0)
    with pytest.raises(ValueError, match="holdout_mode"):
        holdout_split(im, mode="nope")
    with pytest.raises(ValueError, match="no training data"):
        holdout_split(np.arange(4).reshape(4, 1), fraction=0.5)


def test_stacked_eval_batches_padding():
    from dopt.data import stacked_eval_batches

    im = np.arange(42).reshape(2, 21)
    idx, w = stacked_eval_batches(im, batch_size=8)
    assert idx.shape == (2, 3, 8) and w.shape == (2, 3, 8)
    assert w.sum() == 42  # every real sample weighted once
    np.testing.assert_array_equal(idx[0].ravel()[:21], im[0])


def test_sharded_eval_batches_partition_properties():
    """Sharded per-worker eval: every test index appears in exactly one
    worker's weighted region, pads carry weight 0, and shard sizes are
    balanced to within one sample."""
    import numpy as np

    from dopt.data import sharded_eval_batches

    n, w = 1003, 7           # deliberately non-divisible
    idx, wt = sharded_eval_batches(n, w, batch_size=64)
    assert idx.shape == wt.shape and idx.shape[0] == w
    counted = np.zeros(n, np.int32)
    for i in range(w):
        real = idx[i][wt[i] > 0]
        np.add.at(counted, real, 1)
    assert (counted == 1).all(), "shards must partition the eval set"
    sizes = [(wt[i] > 0).sum() for i in range(w)]
    assert max(sizes) - min(sizes) <= 1, sizes
    # round-robin: worker i holds indices congruent to i mod w
    for i in range(w):
        real = idx[i][wt[i] > 0]
        assert (real % w == i).all()


def test_trim_compute_dtype_table_is_valid():
    """The per-preset trim dtype table names real presets and valid
    dtypes (it drives bench_suite and time_to_target)."""
    import jax.numpy as jnp

    from dopt.presets import PRESETS, TRIM_COMPUTE_DTYPE

    for name, dtype in TRIM_COMPUTE_DTYPE.items():
        assert name in PRESETS, name
        jnp.dtype(dtype)
