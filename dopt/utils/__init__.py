from dopt.utils.metrics import History
from dopt.utils.prng import setup_seed

__all__ = ["History", "setup_seed"]
