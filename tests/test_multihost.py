"""Multi-host (DCN × ICI hybrid mesh) tests on virtual CPU devices.

SURVEY §4's "multi-node without a cluster": the 8 virtual devices are
partitioned into virtual hosts; the program is identical to a real
multi-slice job (only device locality differs).
"""

import numpy as np
import pytest

from dopt.parallel.mesh import make_worker_mesh, shard_worker_tree
from dopt.parallel.multihost import (HOST_AXIS, ICI_AXIS, dcn_edge_count,
                                     initialize_distributed, make_hybrid_mesh)
from dopt.topology import build_mixing_matrices

from tests.test_engine import _gossip_cfg


def test_make_hybrid_mesh_shape(devices):
    mesh = make_hybrid_mesh(2)
    assert mesh.shape[HOST_AXIS] == 2 and mesh.shape[ICI_AXIS] == 4
    assert mesh.size == 8


def test_hybrid_mesh_indivisible_raises(devices):
    with pytest.raises(ValueError, match="divisible"):
        make_hybrid_mesh(3)


def test_initialize_distributed_noop_without_env(devices, monkeypatch):
    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
        monkeypatch.delenv(k, raising=False)
    assert initialize_distributed() is False


def test_shard_worker_tree_hybrid_roundtrip(devices):
    import jax
    mesh = make_hybrid_mesh(2)
    x = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    tree = shard_worker_tree({"p": x}, mesh)
    np.testing.assert_array_equal(np.asarray(jax.device_get(tree["p"])), x)
    # worker axis folded over BOTH mesh axes
    spec = tree["p"].sharding.spec
    assert tuple(spec)[0] == (HOST_AXIS, ICI_AXIS)


def test_make_worker_mesh_host_divisibility(devices):
    # 6 workers, <=4 devices, 2 virtual hosts: must pick d=2 (3 lanes
    # per device), not crash on d=3.
    mesh = make_worker_mesh(6, 4, 2)
    assert mesh.shape[HOST_AXIS] == 2 and mesh.size == 2
    with pytest.raises(ValueError, match="folds"):
        make_worker_mesh(5, 4, 2)  # 5 workers can't split over 2 hosts


def test_coordinator_handoff_roundtrip(tmp_path):
    from dopt.parallel.multihost import coordinator_handoff

    path = tmp_path / "coordinator.json"
    addr = coordinator_handoff(path, 0)
    host, port = addr.rsplit(":", 1)
    assert host == "127.0.0.1" and 0 < int(port) < 65536
    # Followers read the published address back verbatim.
    assert coordinator_handoff(path, 1) == addr
    assert coordinator_handoff(path, 7) == addr


def test_wait_handoff_bounded(tmp_path):
    from dopt.parallel.multihost import wait_handoff

    with pytest.raises(TimeoutError, match="handoff"):
        wait_handoff(tmp_path / "missing.json", poll_s=0.001, max_polls=3)


def test_dcn_edge_count_ring():
    w = build_mixing_matrices("circle", "metropolis", 8).matrices[0]
    # zero-diagonal ring over 2 hosts: 2 boundary cuts x 2 directions
    assert dcn_edge_count(w, 2) == 4
    assert dcn_edge_count(w, 1) == 0
    dense = build_mixing_matrices("complete", "uniform", 8).matrices[0]
    assert dcn_edge_count(dense, 2) == 2 * 4 * 4  # all cross pairs, both dirs


def test_gossip_trainer_on_hybrid_mesh_matches_flat(devices):
    import jax
    from dopt.engine import GossipTrainer

    flat = _gossip_cfg()
    hybrid = flat.replace(mesh_hosts=2)
    ta = GossipTrainer(flat)
    ta.run(rounds=3)
    tb = GossipTrainer(hybrid)
    assert tb.mesh.shape[HOST_AXIS] == 2
    tb.run(rounds=3)
    fa = np.concatenate([np.ravel(np.asarray(x))
                         for x in jax.tree.leaves(jax.device_get(ta.params))])
    fb = np.concatenate([np.ravel(np.asarray(x))
                         for x in jax.tree.leaves(jax.device_get(tb.params))])
    np.testing.assert_allclose(fa, fb, atol=1e-6)
    la = [r["avg_test_acc"] for r in ta.history.rows if "avg_test_acc" in r]
    lb = [r["avg_test_acc"] for r in tb.history.rows if "avg_test_acc" in r]
    np.testing.assert_allclose(la, lb, atol=1e-6)


def test_federated_trainer_on_hybrid_mesh(devices):
    from tests.test_engine import _fed_cfg
    from dopt.engine import FederatedTrainer

    tr = FederatedTrainer(_fed_cfg("fedavg").replace(mesh_hosts=2))
    h = tr.run(rounds=3)
    assert h["test_acc"][-1] > 0.6


def test_real_multiprocess_jax_distributed():
    """GENUINE multi-process execution: 2 OS processes × 2 virtual CPU
    devices against one jax.distributed coordinator (gloo collectives),
    one gossip round each, identical trajectories.  This is the only
    test that executes initialize_distributed's coordinator path for
    real (everything else uses in-process virtual hosts).

    The historical ``xfail(strict=False)`` is RETIRED: the dominant
    flake was the parent-probed coordinator port racing the whole
    child-interpreter startup, which the port-0 + handoff-file
    bootstrap (``coordinator_handoff``) eliminated; the residual gloo
    tcp-transport message-interleave race is handled by the demo's
    narrowly-matched 3× retry on a fresh coordinator."""
    import subprocess
    import sys
    from pathlib import Path

    demo = Path(__file__).parent.parent / "scripts" / "multiprocess_demo.py"
    r = subprocess.run(
        [sys.executable, str(demo), "--num-processes", "2",
         "--devices-per-proc", "2", "--rounds", "1"],
        capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "identical trajectories" in r.stdout
