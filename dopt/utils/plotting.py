"""History plotting (reference ``plot``/``servers_plot`` equivalents).

Recreates the reference's comparison plots — ``Server.plot`` per-client
grids (servers.py:95-120) and ``servers_plot`` cross-experiment curves
(P1 utils.py:29-51, P2 utils.py:26-48) — from ``History`` objects.
Matplotlib only; import is deferred so headless/metric-only use never
pays for it.
"""

from __future__ import annotations

from pathlib import Path
from typing import Mapping, Sequence

from dopt.utils.metrics import History


def compare_histories(
    histories: Mapping[str, History] | Sequence[tuple[str, History]],
    *,
    metrics: Sequence[str] = ("avg_test_acc", "avg_test_loss", "avg_train_loss"),
    title: str = "",
    save: str | Path | None = None,
):
    """Cross-experiment comparison grid (the ``servers_plot`` shape:
    one panel per metric, one labelled curve per experiment)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    items = list(histories.items()) if isinstance(histories, Mapping) else list(histories)
    n = len(metrics)
    fig, axes = plt.subplots(1, n, figsize=(5 * n, 4))
    if n == 1:
        axes = [axes]
    for ax, metric in zip(axes, metrics):
        for label, h in items:
            xs = [r["round"] for r in h if metric in r]
            ys = [r[metric] for r in h if metric in r]
            if xs:
                ax.plot(xs, ys, marker="o", markersize=3, label=label)
        ax.set_xlabel("round")
        ax.set_ylabel(metric)
        ax.grid(alpha=0.3)
        ax.legend(fontsize=8)
    if title:
        fig.suptitle(title)
    fig.tight_layout()
    if save is not None:
        fig.savefig(save, dpi=120)
        plt.close(fig)
        return Path(save)
    return fig


def client_grid_plot(
    client_history: History,
    *,
    num_workers: int | None = None,
    title: str = "",
    save: str | Path | None = None,
):
    """Per-client loss/accuracy subplot grid — ``Server.plot``
    (servers.py:95-120): for each client a loss panel (train + val
    curves) stacked above an accuracy panel, laid out ceil(sqrt(N))
    wide.  Input is a trainer's ``client_history`` (per-epoch rows with
    a 'worker' column, produced when ``DataConfig.local_holdout`` is
    on); the x-axis is the flattened (round, epoch) sequence, matching
    the reference's concatenated per-epoch client history.  Unlike the
    reference's plot (which hard-codes a 100-client grid offset,
    servers.py:105), the layout adapts to any N."""
    import math

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rows = client_history.rows
    if not rows:
        raise ValueError(
            "client_history is empty — per-client curves need "
            "DataConfig.local_holdout > 0 (the reference's 90/10 "
            "train/val split)")
    workers = sorted({r["worker"] for r in rows})
    n = num_workers or (max(workers) + 1)
    s = math.ceil(math.sqrt(n))
    rows_of_panels = 2 * math.ceil(n / s)
    fig, axs = plt.subplots(rows_of_panels, s,
                            figsize=(3 * s, 2.2 * rows_of_panels),
                            sharex=True, squeeze=False)
    per_worker: dict[int, list[dict]] = {w: [] for w in range(n)}
    for r in rows:
        per_worker.setdefault(r["worker"], []).append(r)
    for w in range(n):
        block, col = divmod(w, s)
        ax_loss = axs[2 * block][col]
        ax_acc = axs[2 * block + 1][col]
        hist = per_worker.get(w, [])
        xs = range(len(hist))
        ax_loss.set_title(f"Client #{w + 1}", fontsize=8)
        if hist:
            ax_loss.plot(xs, [r["train_loss"] for r in hist], "b",
                         label="train")
            ax_loss.plot(xs, [r["val_loss"] for r in hist], "r", label="val")
            ax_acc.plot(xs, [r["train_acc"] for r in hist], "k",
                        label="train")
            ax_acc.plot(xs, [r["val_acc"] for r in hist], "g", label="val")
            if w == 0:
                ax_loss.legend(fontsize=6)
                ax_acc.legend(fontsize=6)
        ax_loss.set_ylabel("loss", fontsize=7)
        ax_acc.set_ylabel("accuracy", fontsize=7)
        ax_acc.set_xlabel("epochs", fontsize=7)
        ax_loss.label_outer()
        ax_acc.label_outer()
    if title:
        fig.suptitle(title)
    fig.tight_layout()
    if save is not None:
        fig.savefig(save, dpi=120)
        plt.close(fig)
        return Path(save)
    return fig
