"""Full-horizon sequential torch-CPU oracle for baseline2.

Runs the same oracle as scripts/time_to_target.py's truncated column,
but for the full horizon the TPU run needed (57 rounds + the 58th
consensus, matching acc_by_round[57] on the TPU side), and writes
results/oracle_full_baseline2.json.  ~70 min of single-core torch —
run once, merge into time_to_target.json via --merge.

Usage:
    python scripts/oracle_full.py [--rounds 57] [--merge]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=57,
                    help="oracle horizon k; compares vs TPU acc_by_round[k]")
    ap.add_argument("--out", default="results/oracle_full_baseline2.json")
    ap.add_argument("--merge", action="store_true",
                    help="merge an existing --out into time_to_target.json")
    args = ap.parse_args()

    from time_to_target import oracle_baseline

    from dopt.presets import get_preset

    out = Path(args.out)
    ttt_path = Path("results/time_to_target.json")

    if not args.merge:
        om = oracle_baseline(get_preset("baseline2"), args.rounds)
        payload = {"preset": "baseline2",
                   "oracle_rounds_full": om["oracle_rounds"],
                   "oracle_final_acc_full": om["oracle_final_acc"],
                   "oracle_seconds_full": om["oracle_seconds"]}
        out.write_text(json.dumps(payload, indent=2) + "\n")
        print(f"wrote {out}: {payload}")

    # Merge into the time_to_target artifact (idempotent).
    payload = json.loads(out.read_text())
    ttt = json.loads(ttt_path.read_text())
    for r in ttt["results"]:
        if r["preset"] == "baseline2":
            r.update({k: v for k, v in payload.items() if k != "preset"})
            k = payload["oracle_rounds_full"]
            acc = r.get("acc_by_round", [])
            # Written unconditionally: a horizon beyond the TPU run's
            # trajectory yields an explicit null, never a stale value.
            r["tpu_acc_at_full_oracle_round"] = (
                acc[k] if len(acc) > k else None)
            if len(acc) <= k:
                print(f"warning: TPU trajectory has {len(acc)} rounds "
                      f"<= oracle horizon {k}; same-round comparison "
                      "unavailable", file=sys.stderr)
            fa = r.get("final_acc")
            # final_acc can be None (run ended before any eval row);
            # the delta is then an explicit null, not a TypeError.
            r["tpu_final_minus_full_oracle"] = (
                round(fa - payload["oracle_final_acc_full"], 4)
                if fa is not None else None)
    ttt_path.write_text(json.dumps(ttt, indent=2) + "\n")
    print(f"merged into {ttt_path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
