"""Multi-host distributed backend: DCN × ICI hybrid meshes.

The reference has NO communication backend at all — its "multi-node"
story is N objects in one Python process (SURVEY §2.4).  dopt's
equivalent of a NCCL/MPI launcher is the jax runtime itself:

* ``initialize_distributed()`` wires ``jax.distributed`` from standard
  cluster environment variables (one call per host process; afterwards
  ``jax.devices()`` spans every host and collectives ride ICI within a
  slice and DCN across slices).
* ``make_hybrid_mesh()`` builds a 2-D ``Mesh`` with a slow outer axis
  (``hosts`` — DCN) and a fast inner axis (``ici``), so shardings can
  keep bandwidth-hungry collectives on ICI.
* the generic ``dopt.parallel.mesh.worker_sharding`` folds the engine's
  single logical worker axis over BOTH mesh axes (workers = hosts × ici
  lanes): neighboring workers land on the same slice, which means
  ring/dynamic gossip topologies cross DCN only at slice boundaries —
  exactly 2 of N edges for a ring, the minimum possible.

Single-process this degrades gracefully: ``initialize_distributed`` is a
no-op without cluster env vars, and the hybrid mesh reshapes the local
devices, which is also how the 8-virtual-CPU-device tests exercise the
full multi-host code path without a cluster (SURVEY §4's answer to
"test distributed without one").
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh

HOST_AXIS = "hosts"   # slow axis: crosses DCN on a real multi-slice job
ICI_AXIS = "ici"      # fast axis: stays on-slice


def _distributed_initialized() -> bool:
    """Whether ``jax.distributed`` is already wired, across jax
    versions: new jax exposes ``jax.distributed.is_initialized``; 0.4.x
    only carries the module-level client state.  Double-initialising
    raises, so this probe gates ``initialize_distributed``."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:  # pragma: no cover - jax internals moved
        return False


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialise ``jax.distributed`` for a multi-host job.

    Explicit args win; otherwise standard env vars are used
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``,
    or the TPU-pod metadata jax autodetects).  Returns True if the
    distributed runtime was (or already is) initialised, False when
    nothing indicates a multi-process job (single-host: no-op).
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        return False
    if _distributed_initialized():
        return True   # a launcher/framework already wired the runtime
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def make_hybrid_mesh(num_hosts: int | None = None, *, devices=None) -> Mesh:
    """2-D (hosts × ici) mesh.

    On a real multi-host job ``num_hosts`` defaults to
    ``jax.process_count()`` and rows follow device locality (each row =
    one host's devices, so the inner axis is pure ICI).  Single-process,
    ``num_hosts`` partitions the local devices into virtual hosts —
    bit-identical program, no cluster needed.
    """
    if devices is None:
        devices = jax.devices()
    if num_hosts is None:
        num_hosts = max(jax.process_count(), 1)
    n = len(devices)
    if n % num_hosts:
        raise ValueError(f"{n} devices not divisible into {num_hosts} hosts")
    per_host = n // num_hosts
    # jax.devices() orders by process index first, so a row-major reshape
    # groups each host's devices into one row.
    grid = np.asarray(devices).reshape(num_hosts, per_host)
    return Mesh(grid, (HOST_AXIS, ICI_AXIS))


def dcn_edge_count(w_matrix: np.ndarray, num_hosts: int) -> int:
    """Diagnostic: how many nonzero mixing-matrix edges cross a host
    (DCN) boundary under the contiguous worker→host fold.  A ring over
    H hosts should report exactly 2·H·(H>1) directed crossings; dense
    graphs report O(N²·(1−1/H)) — use it to pick topologies that keep
    gossip on ICI."""
    n = w_matrix.shape[0]
    if n % num_hosts:
        raise ValueError(f"{n} workers not divisible into {num_hosts} hosts")
    per = n // num_hosts
    host_of = np.arange(n) // per
    i, j = np.nonzero(w_matrix)
    return int(np.sum(host_of[i] != host_of[j]))
