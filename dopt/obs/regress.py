"""Perf-regression ledger over the bench trajectory.

``results/bench_history.jsonl`` is an append-only ledger: every
bench.py headline JSON line lands as one entry stamped with the git sha
and a run id (``append_entry`` — deduped on ``(run_id, metric)``, so a
re-run replaces its prior entry instead of stacking duplicates that
skew the trailing trimmed median, while one run's several metric lines
— headline + seqlm — coexist), so the r01→r05 trajectory the committed
``BENCH_r*.json`` files hold becomes data a regressor can watch — per
run, not per postmortem.

``check_regression`` compares a candidate entry against the trailing
window of earlier entries with the same ``(metric, device_kind)`` key
(a CPU --quick artifact never gets judged against TPU history), one
tracked throughput/efficiency key at a time:

* baseline = min/max-trimmed median of the trailing window
  (``dopt.utils.metrics.trimmed_stats`` — the same outlier hardening
  the bench wall measurement uses);
* noise band = max(``min_band_pct``, half the trimmed spread): a
  trajectory that historically wobbles ±13% does not alarm at −8%, a
  flat one alarms past the 5% floor;
* only ADVERSE deltas flag (throughput down, ``host_gap_pct`` up) —
  an improvement is never a regression.

CLI (stdlib-only, no jax):

    python -m dopt.obs.regress results/bench_history.jsonl
    python -m dopt.obs.regress results/bench_history.jsonl \
        --candidate bench-quick.json --advisory

Exit 1 when any tracked metric regresses (``--advisory`` reports but
always exits 0 — the CI annotation mode).
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path
from typing import Any

from dopt.utils.metrics import trimmed_stats

LEDGER_VERSION = 1

# Headline keys the regressor watches, with the adverse direction:
# "higher" means higher is better (a drop regresses), "lower" the
# opposite (host_gap_pct growing back means the overlap eroded).
TRACKED_METRICS: dict[str, str] = {
    "value": "higher",
    "device_rounds_per_sec": "higher",
    "samples_per_sec": "higher",
    "model_tflops_per_sec": "higher",
    "mfu_vs_bf16_peak": "higher",
    "faithful_f32_rounds_per_sec": "higher",
    "gossip_rounds_per_sec_chaos": "higher",
    "chaos_speedup_vs_per_round": "higher",
    "clients_per_sec_1k": "higher",
    "clients_per_sec_10k": "higher",
    "host_gap_pct": "lower",
    "fused_rounds_per_sec": "higher",
    "fused_speedup": "higher",
    "seqlm_tokens_per_sec": "higher",
    # Comm-substrate headline (r08): compiled-HLO wire bytes of the
    # round program (less is better — the codec's whole point) and the
    # compressed leg's throughput (the codec must not buy bytes with
    # a dispatch-bound round).  NO_BASELINE on first appearance.
    "bytes_on_wire": "lower",
    "compressed_rounds_per_sec": "higher",
}


def git_sha(cwd: str | Path | None = None) -> str | None:
    """Current commit sha, or None outside a repo / without git."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=cwd, capture_output=True,
            text=True, timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None


def make_entry(headline: dict[str, Any], *, run_id: str | None = None,
               sha: str | None = None,
               ts: float | None = None) -> dict[str, Any]:
    """Wrap one bench headline dict into a ledger entry."""
    if not isinstance(headline, dict) or "metric" not in headline:
        raise ValueError(f"not a bench headline line: {headline!r}")
    if ts is None:
        ts = round(time.time(), 3)  # dopt: allow-wallclock -- ledger entry timestamp, never judged by the regression math
    if run_id is None:
        run_id = (sha[:9] if sha else "run") + f"-{int(ts)}"
    return {"v": LEDGER_VERSION, "run_id": run_id, "git_sha": sha,
            "ts": ts, "device_kind": headline.get("device_kind", "unknown"),
            "bench": dict(headline)}


def append_entry(path: str | Path, headline: dict[str, Any], *,
                 run_id: str | None = None, sha: str | None = None,
                 ts: float | None = None) -> dict[str, Any]:
    """Append one headline to the ledger (sha auto-detected when not
    given); returns the entry written.

    DEDUPED on ``(run_id, metric)``: a re-run at the same run id
    REPLACES its prior entry for that metric (the ledger is atomically
    rewritten without the duplicates) instead of stacking copies — N
    retries of one run would otherwise occupy N slots of the trailing
    window and drag the trimmed median toward that single run's value.
    One run's SEVERAL metric lines (the gossip headline plus the seqlm
    leg) land as separate entries under the shared run id.  Fresh
    slots take the plain-append fast path.

    The pre-append scan parses TOLERANTLY (unlike ``read_ledger``'s
    strict contract): the plain-append path is not atomic, so a crash
    mid-write can leave a torn final line — a strict read here would
    make every future append raise until the ledger is hand-repaired.
    Any torn line triggers the atomic-rewrite (repair) path, which
    drops it: the ledger stays ``read_ledger``-clean, so the
    regressor CLI keeps working after a crash."""
    if sha is None:
        sha = git_sha(Path(path).resolve().parent)
    entry = make_entry(headline, run_id=run_id, sha=sha, ts=ts)
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    if path.exists():
        torn = False
        existing = []
        for line in path.read_text().splitlines():
            try:
                e = json.loads(line)
            except ValueError:
                torn = True
                continue
            if isinstance(e, dict):
                existing.append(e)
            else:
                torn = True

        def _same_slot(e):
            # Dedup key is (run_id, metric): one run legitimately
            # appends several metric lines (headline + seqlm), and
            # only a re-run of the SAME metric replaces its entry.
            return (e.get("run_id") == entry["run_id"]
                    and (e.get("bench") or {}).get("metric")
                    == entry["bench"]["metric"])

        if torn or any(_same_slot(e) for e in existing):
            from dopt.utils.metrics import atomic_write_text

            kept = [e for e in existing if not _same_slot(e)]
            kept.append(entry)
            atomic_write_text(path, "".join(
                json.dumps(e, separators=(",", ":")) + "\n"
                for e in kept))
            return entry
    with open(path, "a") as f:
        f.write(json.dumps(entry, separators=(",", ":")) + "\n")
    return entry


def read_ledger(path: str | Path) -> list[dict[str, Any]]:
    """Load the ledger; every line must parse (this file is written a
    whole line at a time — garbage means hand-editing went wrong)."""
    entries = []
    for i, line in enumerate(Path(path).read_text().splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            e = json.loads(line)
        except ValueError:
            raise ValueError(f"{path}: line {i + 1} is not JSON: "
                             f"{line[:80]!r}")
        if not isinstance(e, dict) or "bench" not in e:
            raise ValueError(f"{path}: line {i + 1} is not a ledger "
                             f"entry: {line[:80]!r}")
        entries.append(e)
    return entries


def _key(entry: dict[str, Any]) -> tuple[str, str]:
    return (str(entry["bench"].get("metric", "?")),
            str(entry.get("device_kind", "unknown")))


def check_regression(entries: list[dict[str, Any]],
                     candidate: dict[str, Any] | None = None, *,
                     window: int = 8, min_history: int = 3,
                     min_band_pct: float = 5.0) -> dict[str, Any]:
    """Judge ``candidate`` (default: the ledger's newest entry) against
    the trailing ``window`` earlier entries sharing its
    ``(metric, device_kind)`` key.  Returns::

        {"status": "ok"|"regression"|"no_baseline",
         "key": [metric, device_kind], "run_id": ...,
         "checks": [{"metric", "candidate", "baseline_median",
                     "delta_pct", "band_pct", "n_baseline",
                     "direction", "regressed"}, ...]}
    """
    if candidate is None:
        if not entries:
            raise ValueError("empty ledger and no candidate")
        entries, candidate = entries[:-1], entries[-1]
    key = _key(candidate)
    baseline = [e for e in entries if _key(e) == key][-window:]
    result: dict[str, Any] = {
        "status": "ok", "key": list(key),
        "run_id": candidate.get("run_id"), "checks": [],
    }
    if len(baseline) < min_history:
        result["status"] = "no_baseline"
        result["n_baseline"] = len(baseline)
        return result
    cand = candidate["bench"]
    for name, direction in TRACKED_METRICS.items():
        cv = cand.get(name)
        if not isinstance(cv, (int, float)) or isinstance(cv, bool):
            continue
        hist = [e["bench"][name] for e in baseline
                if isinstance(e["bench"].get(name), (int, float))
                and not isinstance(e["bench"].get(name), bool)]
        if len(hist) < min_history:
            # The candidate CARRIES this metric but the trailing window
            # does not (a newly-promoted headline field, e.g. the fused
            # or seqlm legs) — report NO_BASELINE explicitly instead of
            # silently passing, so a first-seen metric starts an honest
            # window the reader can see filling up.
            result["checks"].append({
                "metric": name, "candidate": float(cv),
                "baseline_median": None, "delta_pct": None,
                "band_pct": None, "n_baseline": len(hist),
                "direction": direction, "regressed": False,
                "no_baseline": True,
            })
            continue
        med, spread, _ = trimmed_stats(hist)
        if med == 0:
            continue
        delta = 100.0 * (float(cv) - med) / abs(med)
        band = max(float(min_band_pct), spread / 2.0)
        adverse = -delta if direction == "higher" else delta
        regressed = adverse > band
        result["checks"].append({
            "metric": name, "candidate": float(cv),
            "baseline_median": med, "delta_pct": round(delta, 2),
            "band_pct": round(band, 2), "n_baseline": len(hist),
            "direction": direction, "regressed": regressed,
        })
        if regressed:
            result["status"] = "regression"
    return result


def format_report(result: dict[str, Any]) -> str:
    """Human-readable per-metric delta report."""
    key = result.get("key", ["?", "?"])
    lines = [f"bench regression check: {key[0]} @ {key[1]} "
             f"(run {result.get('run_id')}) -> {result['status'].upper()}"]
    if result["status"] == "no_baseline":
        lines.append(f"  only {result.get('n_baseline', 0)} prior "
                     "entries with this (metric, device_kind) key — "
                     "nothing to judge against yet")
    for c in result.get("checks", []):
        if c.get("no_baseline"):
            lines.append(
                f"  {c['metric']:<28} {c['candidate']:>12.4g} "
                f"NO_BASELINE (n={c['n_baseline']} prior entries carry "
                "this metric — window still filling)")
            continue
        arrow = "REGRESSED" if c["regressed"] else "ok"
        lines.append(
            f"  {c['metric']:<28} {c['candidate']:>12.4g} vs median "
            f"{c['baseline_median']:>12.4g} ({c['delta_pct']:+7.2f}% | "
            f"band ±{c['band_pct']:.1f}%, n={c['n_baseline']}) {arrow}")
    return "\n".join(lines)


def _load_candidate(path: str) -> dict[str, Any]:
    """A candidate file is either a ledger entry line, a bench stdout
    capture (comment lines + JSON lines — the first JSON line is the
    headline), or a bare headline JSON object."""
    text = Path(path).read_text()
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        obj = json.loads(line)
        if "bench" in obj and "run_id" in obj:
            return obj
        return make_entry(obj, run_id=f"candidate:{Path(path).name}",
                          sha=None)
    raise ValueError(f"{path}: no JSON object line found")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("ledger", metavar="BENCH_HISTORY_JSONL")
    ap.add_argument("--candidate", default=None, metavar="PATH",
                    help="judge this bench output / ledger-entry file "
                         "instead of the ledger's newest entry")
    ap.add_argument("--window", type=int, default=8,
                    help="trailing entries forming the baseline")
    ap.add_argument("--min-history", type=int, default=3,
                    help="baseline entries required before judging")
    ap.add_argument("--min-band", type=float, default=5.0,
                    help="noise-band floor (%%) when the trailing "
                         "spread is tighter")
    ap.add_argument("--advisory", action="store_true",
                    help="report but always exit 0 (CI annotation mode)")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="also write the check result as JSON here")
    args = ap.parse_args(argv)

    try:
        entries = read_ledger(args.ledger)
        candidate = (_load_candidate(args.candidate)
                     if args.candidate else None)
        result = check_regression(entries, candidate,
                                  window=args.window,
                                  min_history=args.min_history,
                                  min_band_pct=args.min_band)
    except (OSError, ValueError) as e:
        print(f"regress: FAIL {e}", file=sys.stderr)
        return 2
    print(format_report(result))
    if args.json:
        from dopt.utils.metrics import atomic_write_text

        atomic_write_text(args.json, json.dumps(result, indent=2))
    if result["status"] == "regression" and not args.advisory:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
