"""Device mesh and worker-axis sharding.

The engine's whole layout hinges on one idea (SURVEY §7): the reference's
N sequentially-stepped client objects become ONE stacked pytree with a
leading ``workers`` axis, sharded over a 1-D ``jax.sharding.Mesh``.
``num_workers`` need not equal the device count: workers fold onto
devices (``workers = devices × workers_per_device``) and per-device
lanes are vmapped — that is how 32 workers run on a v5e-8
(mesh plan "(cores=8, workers_per_core=4)").
"""

from __future__ import annotations

import os
import warnings

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORKER_AXIS = "workers"

# XLA latency-hiding scheduler: lets the compiler hoist collective
# starts ahead of independent compute so a bucketed consensus step
# (update_sharding="scatter", dopt.parallel.collectives) overlaps
# bucket b's wire time with bucket b+1's contraction.  TPU-only flags
# are ignored by other backends; async-collective conversion is what
# turns each per-bucket psum_scatter/all_gather into a start/done pair
# the scheduler can move.
LATENCY_HIDING_XLA_FLAGS: tuple[str, ...] = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
)


def _backend_initialized() -> bool:
    """True once any XLA backend exists (XLA_FLAGS edits no longer
    apply).  Best-effort across jax versions; assumes initialised when
    the probe fails (the safe direction: we then warn instead of
    silently setting dead flags)."""
    try:
        from jax._src import xla_bridge

        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:  # pragma: no cover - jax internals moved
        return True


def _tpu_expected() -> bool:
    """Whether this process will (or did) target a TPU backend — the
    only backend whose XLA build knows the ``--xla_tpu_*`` flags (the
    CPU build FATALs on unknown XLA_FLAGS, so setting them blindly
    would kill every CPU run)."""
    plat = os.environ.get("JAX_PLATFORMS",
                          os.environ.get("JAX_PLATFORM_NAME", ""))
    if plat:
        return "tpu" in plat.lower()
    try:
        import libtpu  # noqa: F401  (present only where a TPU runtime is)

        return True
    except ImportError:
        return False


def enable_latency_hiding_scheduler() -> bool:
    """Append ``LATENCY_HIDING_XLA_FLAGS`` to ``XLA_FLAGS`` so the
    scatter path's per-bucket collectives overlap with compute.

    Must run BEFORE the first jax backend initialisation (XLA reads the
    env once); returns True when the flags are (already) in effect,
    False when they cannot be applied — silently on non-TPU targets
    (the flags are TPU-only and the CPU XLA build aborts on unknown
    flags), with a warning when a TPU backend beat us to it.
    ``bench.py`` calls this before importing the engines; trainer
    construction calls it too as a best-effort for scripts that
    configure scatter mode late."""
    flags = os.environ.get("XLA_FLAGS", "")
    missing = [f for f in LATENCY_HIDING_XLA_FLAGS if f not in flags]
    if not missing:
        return True
    if not _tpu_expected():
        return False
    if _backend_initialized():
        warnings.warn(
            "update_sharding='scatter' wants the XLA latency-hiding "
            "scheduler, but an XLA backend is already initialised so "
            "XLA_FLAGS can no longer be amended — start the process "
            "with dopt.parallel.mesh.LATENCY_HIDING_XLA_FLAGS in "
            "XLA_FLAGS (bench.py does this) to overlap the bucketed "
            "collectives with compute", stacklevel=2)
        return False
    os.environ["XLA_FLAGS"] = " ".join([flags] + missing).strip()
    return True


def compat_shard_map(fn, *, mesh, in_specs, out_specs, check=True):
    """``jax.shard_map`` across jax versions: new jax exposes it at the
    top level with the static-varying-axes check named ``check_vma``;
    0.4.x has it under ``jax.experimental.shard_map`` as ``check_rep``.
    Every shard_map in dopt routes through here so the engines run on
    both."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check)


def make_mesh(num_devices: int | None = None, *, devices=None) -> Mesh:
    """1-D mesh over the worker axis."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if len(devices) < num_devices:
            raise ValueError(f"need {num_devices} devices, have {len(devices)}")
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (WORKER_AXIS,))


def fit_mesh_devices(num_workers: int, requested: int | None = None) -> int:
    """Largest device count <= min(workers, available) that divides the
    worker count evenly (workers fold onto devices in equal lanes)."""
    avail = len(jax.devices()) if requested is None else requested
    d = min(num_workers, avail)
    while num_workers % d:
        d -= 1
    return d


def worker_axes(mesh: Mesh) -> tuple[str, ...]:
    """The mesh axis names the logical worker axis folds over: just
    ``workers`` on a 1-D mesh, ``(hosts, ici)`` on a hybrid mesh
    (dopt.parallel.multihost)."""
    return tuple(mesh.axis_names)


def worker_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (worker) axis across ALL mesh axes; everything
    else replicated within a worker shard."""
    return NamedSharding(mesh, P(worker_axes(mesh)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_worker_tree(tree, mesh: Mesh):
    """Place a stacked [W, ...] pytree with the worker axis sharded.

    W must divide evenly by the mesh size (pad the worker count or pick
    a divisor worker total — the engine validates this upstream).

    On a multi-process fleet (``dopt serve``) the placement goes
    through ``make_array_from_callback``: every process holds the FULL
    host array (checkpoint restores read the same file), so each can
    slice out its addressable shards locally — zero collectives.  A
    bare ``device_put`` against a non-addressable sharding would run a
    cross-process ``assert_equal`` broadcast PER LEAF, a pile of tiny
    gloo collectives on the restore path that the tcp transport's
    message-interleave race loves."""
    sh = worker_sharding(mesh)
    multiprocess = jax.process_count() > 1

    def put(x):
        if x.shape[0] % mesh.size:
            raise ValueError(
                f"worker axis {x.shape[0]} not divisible by mesh size {mesh.size}"
            )
        if multiprocess:
            x = np.asarray(x)
            return jax.make_array_from_callback(
                x.shape, sh, lambda idx: x[idx])
        return jax.device_put(x, sh)

    return jax.tree.map(put, tree)


def shard_over_workers(fn, mesh: Mesh, in_specs, out_specs):
    """``shard_map`` a stacked-worker function over the mesh.

    Specs are strings with one character per argument/output — ``w``
    (leading worker axis sharded over all mesh axes) or ``r``
    (replicated); each character acts as a pytree prefix for its
    argument.  A single-character string means ONE spec (e.g. an
    evaluator returning a metrics dict uses out_specs="w").  Used by
    the engines to run the grouped stacked-forward local phase as pure
    per-device computation (workers are independent — zero
    collectives), which also keeps the worker-in-channels grouped conv
    out of the SPMD partitioner's hands (it cannot split that conv's
    feature groups itself).
    """
    w_, r_ = P(worker_axes(mesh)), P()

    def one(c):
        if c == "w":
            return w_
        if c == "r":
            return r_
        raise ValueError(f"spec characters are 'w' or 'r', got {c!r}")

    def resolve(spec):
        if len(spec) == 1:
            return one(spec)
        return tuple(one(c) for c in spec)

    return compat_shard_map(fn, mesh=mesh, in_specs=resolve(in_specs),
                            out_specs=resolve(out_specs), check=False)


def make_worker_mesh(num_workers: int, mesh_devices: int | None = None,
                     mesh_hosts: int | None = None) -> Mesh:
    """The engines' mesh factory: 1-D worker mesh by default, 2-D
    (hosts × ici) hybrid mesh when ``mesh_hosts`` is set
    (dopt.parallel.multihost)."""
    if not mesh_hosts:
        return make_mesh(fit_mesh_devices(num_workers, mesh_devices))

    from dopt.parallel.multihost import make_hybrid_mesh

    devices = jax.devices()
    if jax.process_count() > 1:
        # On a real multi-controller job every process's devices must be
        # in the mesh, and slicing would break the host-row alignment
        # make_hybrid_mesh relies on — use all devices or nothing.
        n = len(devices)
        if mesh_devices not in (None, n):
            raise ValueError(
                f"multi-host jobs must use all {n} devices "
                f"(got mesh_devices={mesh_devices})")
        if num_workers % n:
            raise ValueError(
                f"{num_workers} workers do not fold evenly onto the "
                f"{n} devices of this multi-host job")
        return make_hybrid_mesh(mesh_hosts, devices=devices)

    # Single process (incl. virtual-host testing): largest device count
    # that divides the workers AND splits evenly into the virtual hosts.
    avail = len(devices) if mesh_devices is None else mesh_devices
    d = min(num_workers, avail)
    while d > 0 and (num_workers % d or d % mesh_hosts):
        d -= 1
    if d <= 0:
        raise ValueError(
            f"no device count <= {avail} folds {num_workers} workers "
            f"onto {mesh_hosts} hosts")
    return make_hybrid_mesh(mesh_hosts, devices=devices[:d])
