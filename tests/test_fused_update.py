"""Engine-level fused mix+update epilogue (``fused_update='on'``).

Gossip carries (post-mix params, displacement) and contracts the round
epilogue as ONE ``fused_mix_update`` pass — the D-PSGD update ordering
(arXiv:1705.09056), a documented variant of (allclose to, not bit-equal
with) the default mix-then-local trace.  Federated carries the theta
broadcast slab and fuses the masked average with the theta step — equal
to the default trace up to f32 reassociation.  Both must be
bit-reproducible across per-round / blocked / prefetched execution and
across kill-and-resume mid-block, and every mode the fused epilogue
cannot yet speak must be rejected loudly at construction.

Kernel-level parity (the Pallas pass vs the jnp composition) lives in
``tests/test_ops.py``; this file owns the engine wiring.
"""

import jax
import numpy as np
import pytest

from dopt.config import (DataConfig, ExperimentConfig, FederatedConfig,
                         GossipConfig, ModelConfig, OptimizerConfig,
                         RobustConfig)
from dopt.engine import FederatedTrainer, GossipTrainer


def _flat(tree):
    return np.concatenate([np.ravel(np.asarray(x))
                           for x in jax.tree.leaves(jax.device_get(tree))])


def _gossip_cfg(fused="on", lr=0.05, rounds=6, robust=None, population=None,
                **gossip_kw):
    g = dict(algorithm="dsgd", topology="circle", mode="metropolis",
             rounds=rounds, local_ep=1, local_bs=32, fused_update=fused)
    g.update(gossip_kw)
    return ExperimentConfig(
        name="fused-g", seed=11,
        data=DataConfig(dataset="synthetic", num_users=4,
                        synthetic_train_size=256, synthetic_test_size=64),
        model=ModelConfig(model="mlp", input_shape=(28, 28, 1),
                          faithful=False),
        optim=OptimizerConfig(lr=lr, momentum=0.9),
        gossip=GossipConfig(**g),
        robust=robust, population=population,
        # The fused epilogue contracts the full worker axis in one
        # kernel call — single-device mesh by construction.
        mesh_devices=1,
    )


def _fed_cfg(fused="on", algorithm="fedavg", rounds=4, robust=None,
             **fed_kw):
    f = dict(algorithm=algorithm, frac=0.5, rounds=rounds, local_ep=1,
             local_bs=32, fused_update=fused)
    f.update(fed_kw)
    return ExperimentConfig(
        name="fused-f", seed=13,
        data=DataConfig(dataset="synthetic", num_users=4,
                        synthetic_train_size=256, synthetic_test_size=64),
        model=ModelConfig(model="mlp", input_shape=(28, 28, 1),
                          faithful=False),
        optim=OptimizerConfig(lr=0.05, momentum=0.9),
        federated=FederatedConfig(**f),
        robust=robust,
        mesh_devices=1,
    )


# ---------------------------------------------------------------------
# Gossip: parity with the reference trace
# ---------------------------------------------------------------------

def test_gossip_fused_first_round_matches_off_exactly(devices):
    # Round 0 contracts a zero displacement, so mix-then-local is the
    # SAME computation in both orderings: the fused trainer's debiased
    # params (q_0 − fbuf_0 = the post-local iterate) must match the off
    # path to kernel-reassociation tolerance.
    a = GossipTrainer(_gossip_cfg(fused="off", rounds=1))
    a.run(rounds=1)
    b = GossipTrainer(_gossip_cfg(fused="on", rounds=1))
    b.run(rounds=1)
    np.testing.assert_allclose(_flat(b._debiased_params()), _flat(a.params),
                               rtol=1e-6, atol=1e-6)


def test_gossip_fused_lr0_is_pure_consensus_parity(devices):
    # With lr=0 the local step is the identity, every displacement is
    # zero, and BOTH orderings degenerate to repeated mixing — the
    # fused multi-round trajectory must agree with the off path to
    # kernel tolerance (a true end-to-end parity check of the Pallas
    # contraction inside the engine).
    a = GossipTrainer(_gossip_cfg(fused="off", lr=0.0, rounds=4))
    a.run(rounds=4)
    b = GossipTrainer(_gossip_cfg(fused="on", lr=0.0, rounds=4))
    b.run(rounds=4)
    np.testing.assert_allclose(_flat(b.params), _flat(a.params),
                               rtol=1e-6, atol=1e-6)


def test_gossip_fused_is_bounded_variant_of_default_ordering(devices):
    # lr > 0: the D-PSGD ordering folds the local step in unmixed, so
    # the trajectory is a VARIANT of the default — close (the
    # displacement re-enters through the next round's contraction) but
    # not bit-equal.  Both halves of that contract are asserted.
    a = GossipTrainer(_gossip_cfg(fused="off", rounds=4))
    a.run(rounds=4)
    b = GossipTrainer(_gossip_cfg(fused="on", rounds=4))
    b.run(rounds=4)
    fa, fb = _flat(a.params), _flat(b._debiased_params())
    assert np.max(np.abs(fa - fb)) > 0.0  # genuinely a different ordering
    np.testing.assert_allclose(fb, fa, rtol=0.0, atol=0.1)


# ---------------------------------------------------------------------
# Gossip: execution-path bit-identity + resume
# ---------------------------------------------------------------------

def test_gossip_fused_blocked_and_prefetched_bit_identical(devices):
    a = GossipTrainer(_gossip_cfg())
    a.run(rounds=6)
    b = GossipTrainer(_gossip_cfg())
    b.run(rounds=6, block=3)
    c = GossipTrainer(_gossip_cfg(prefetch="on"))
    c.run(rounds=6, block=3)
    fa = _flat(a.params)
    np.testing.assert_array_equal(fa, _flat(b.params))
    np.testing.assert_array_equal(fa, _flat(c.params))
    assert a.history.rows == b.history.rows == c.history.rows


def test_gossip_fused_resume_mid_block_bit_identical(devices, tmp_path):
    cont = GossipTrainer(_gossip_cfg())
    cont.run(rounds=6, block=2)
    a = GossipTrainer(_gossip_cfg())
    a.run(rounds=3, block=2)  # ends on a remainder (mid-block) round
    a.save(tmp_path / "ck")
    b = GossipTrainer(_gossip_cfg())
    b.restore(tmp_path / "ck")
    b.run(rounds=3, block=2)
    np.testing.assert_array_equal(_flat(cont.params), _flat(b.params))
    np.testing.assert_array_equal(_flat(cont._fused_buf),
                                  _flat(b._fused_buf))
    assert cont.history.rows == b.history.rows


def test_gossip_fused_checkpoint_direction_guards(devices, tmp_path):
    # The displacement buffer is load-bearing state: a fused trainer
    # cannot silently adopt an unfused checkpoint, nor the reverse.
    on = GossipTrainer(_gossip_cfg())
    on.run(rounds=2)
    on.save(tmp_path / "on")
    off = GossipTrainer(_gossip_cfg(fused="off"))
    off.run(rounds=2)
    off.save(tmp_path / "off")
    with pytest.raises(ValueError, match="fused"):
        GossipTrainer(_gossip_cfg()).restore(tmp_path / "off")
    with pytest.raises(ValueError, match="fused"):
        GossipTrainer(_gossip_cfg(fused="off")).restore(tmp_path / "on")


# ---------------------------------------------------------------------
# Gossip: eligibility — loud construction rejections
# ---------------------------------------------------------------------

@pytest.mark.parametrize("kw,pattern", [
    (dict(algorithm="nocons"), "no such sweep"),
    (dict(mixing="async"), "does not compose"),
    (dict(update_sharding="scatter"), "drop one of the two"),
    (dict(comm_dtype="bfloat16"), "comm_dtype"),
    (dict(comm_impl="shift"), "incompatible"),
], ids=["algorithm", "async", "scatter", "comm_dtype", "shift"])
def test_gossip_fused_rejections(devices, kw, pattern):
    with pytest.raises(ValueError, match=pattern):
        GossipTrainer(_gossip_cfg(**kw))


def test_gossip_fused_rejects_robust_layer(devices):
    with pytest.raises(ValueError, match="robust"):
        GossipTrainer(_gossip_cfg(robust=RobustConfig(clip_radius=1.0)))


def test_gossip_fused_off_accepts_everything(devices):
    # The default must not reject anything: "off" is byte-identical to
    # the pre-change construction.
    GossipTrainer(_gossip_cfg(fused="off", update_sharding="scatter"))
    GossipTrainer(_gossip_cfg(fused="off",
                              robust=RobustConfig(clip_radius=1.0)))


# ---------------------------------------------------------------------
# Federated: parity with the reference trace
# ---------------------------------------------------------------------

@pytest.mark.parametrize("algorithm", ["fedavg", "fedprox"])
def test_federated_fused_matches_off_allclose(devices, algorithm):
    # The fused masked-mean contraction equals the default
    # masked_average + assign up to f32 reassociation — theta AND the
    # worker lanes must track the off path through partial
    # participation (frac=0.5).
    a = FederatedTrainer(_fed_cfg(fused="off", algorithm=algorithm))
    a.run(rounds=3)
    b = FederatedTrainer(_fed_cfg(fused="on", algorithm=algorithm))
    b.run(rounds=3)
    np.testing.assert_allclose(_flat(b._theta_single()), _flat(a.theta),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(_flat(b.params), _flat(a.params),
                               rtol=1e-5, atol=1e-5)


def test_federated_fused_slab_rows_bit_identical(devices):
    # Every row of the carried theta slab is the same global model —
    # the invariant that makes row-0 checkpointing exact.
    tr = FederatedTrainer(_fed_cfg())
    tr.run(rounds=3)
    for leaf in jax.tree.leaves(jax.device_get(tr.theta)):
        row0 = np.asarray(leaf)[0]
        for r in range(1, np.asarray(leaf).shape[0]):
            np.testing.assert_array_equal(np.asarray(leaf)[r], row0)


def test_federated_fused_blocked_and_prefetched_bit_identical(devices):
    a = FederatedTrainer(_fed_cfg())
    a.run(rounds=6)
    b = FederatedTrainer(_fed_cfg())
    b.run(rounds=6, block=3)
    c = FederatedTrainer(_fed_cfg(prefetch="on"))
    c.run(rounds=6, block=3)
    fa = _flat(a.theta)
    np.testing.assert_array_equal(fa, _flat(b.theta))
    np.testing.assert_array_equal(fa, _flat(c.theta))
    assert a.history.rows == b.history.rows == c.history.rows


def test_federated_fused_resume_mid_block_bit_identical(devices, tmp_path):
    cont = FederatedTrainer(_fed_cfg())
    cont.run(rounds=6, block=2)
    a = FederatedTrainer(_fed_cfg())
    a.run(rounds=3, block=2)
    a.save(tmp_path / "ck")
    b = FederatedTrainer(_fed_cfg())
    b.restore(tmp_path / "ck")
    b.run(rounds=3, block=2)
    np.testing.assert_array_equal(_flat(cont.theta), _flat(b.theta))
    assert cont.history.rows == b.history.rows


def test_federated_fused_checkpoints_interchangeable(devices, tmp_path):
    # The federated checkpoint stores the single-tree theta (slab
    # row 0), so fused and unfused trainers can adopt each other's
    # checkpoints — resume trajectories agree to reassociation.
    on = FederatedTrainer(_fed_cfg())
    on.run(rounds=2)
    on.save(tmp_path / "on")
    off = FederatedTrainer(_fed_cfg(fused="off"))
    off.restore(tmp_path / "on")
    off.run(rounds=2)
    on.run(rounds=2)
    np.testing.assert_allclose(_flat(on._theta_single()), _flat(off.theta),
                               rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------
# Federated: eligibility — loud construction rejections
# ---------------------------------------------------------------------

@pytest.mark.parametrize("kw,pattern", [
    (dict(algorithm="scaffold"), "companion state"),
    (dict(staleness_max=2), "staleness"),
    (dict(compact=True), "compact"),
    (dict(comm_dtype="bfloat16"), "comm_dtype"),
], ids=["algorithm", "staleness", "compact", "comm_dtype"])
def test_federated_fused_rejections(devices, kw, pattern):
    with pytest.raises(ValueError, match=pattern):
        FederatedTrainer(_fed_cfg(**kw))


@pytest.mark.parametrize("robust,pattern", [
    (RobustConfig(aggregator="trimmed_mean", trim_frac=0.25),
     "masked-mean"),
    (RobustConfig(clip_radius=1.0), "clip_radius"),
], ids=["aggregator", "clip_radius"])
def test_federated_fused_rejects_robust(devices, robust, pattern):
    with pytest.raises(ValueError, match=pattern):
        FederatedTrainer(_fed_cfg(robust=robust))


def test_federated_fused_allows_quarantine_only_robust(devices):
    # Quarantine acts through the participation mask, which the fused
    # contraction already reads — mask-side robustness stays eligible.
    tr = FederatedTrainer(_fed_cfg(
        robust=RobustConfig(quarantine_after=2, quarantine_rounds=2)))
    tr.run(rounds=2)
