"""Presets: all construct valid configs; a sample runs end-to-end via CLI."""

import pytest

from dopt.presets import PRESETS, get_preset


def test_all_presets_construct():
    for name in PRESETS:
        cfg = get_preset(name)
        assert (cfg.federated is None) != (cfg.gossip is None), name


def test_unknown_preset():
    with pytest.raises(ValueError, match="unknown preset"):
        get_preset("nope")


def test_reference_grid_params():
    # P1 notebook cells 8/10 parameters.
    cfg = get_preset("reference-fedavg")
    assert cfg.data.num_users == 100 and cfg.seed == 2022
    assert cfg.federated.frac == 0.1 and cfg.federated.local_ep == 10
    assert cfg.optim.lr == 0.1 and cfg.model.faithful
    # P2 notebook cell 11 parameters.
    cfg = get_preset("reference-dsgd-circle")
    assert cfg.data.num_users == 6 and cfg.seed == 2028
    assert cfg.gossip.local_bs == 128 and not cfg.data.iid


def test_cli_end_to_end(devices, tmp_path, capsys):
    from dopt.run import main
    rc = main(["--preset", "baseline1", "--rounds", "2",
               "--synthetic-scale", "0.01",
               "--csv", str(tmp_path / "h.csv"),
               "--checkpoint", str(tmp_path / "ck")])
    assert rc == 0
    out = capsys.readouterr().out
    assert '"round": 1' in out
    assert (tmp_path / "h.csv").exists()
    assert (tmp_path / "ck" / "meta.json").exists()


def test_cli_resume(devices, tmp_path, capsys):
    from dopt.run import main
    main(["--preset", "baseline1", "--rounds", "1", "--synthetic-scale", "0.01",
          "--checkpoint", str(tmp_path / "ck")])
    rc = main(["--preset", "baseline1", "--rounds", "1",
               "--synthetic-scale", "0.01", "--resume", str(tmp_path / "ck")])
    assert rc == 0
    out = capsys.readouterr().out
    assert '"round": 1' in out  # continued from round 1
