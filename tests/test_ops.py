"""Pallas fused-update kernel: exact parity with the jnp SGD path.

Runs in interpret mode on the CPU test mesh — the identical kernel code
compiles on TPU.  SURVEY §4 layer-1: algorithm steps as pure functions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dopt.ops import fused_sgd_momentum, fused_sgd_momentum_tree
from dopt.optim import SGDState, sgd_step


@pytest.mark.parametrize("shape", [(7,), (128,), (513,), (32, 33), (4, 100, 17)])
def test_fused_matches_sgd_step_exact(shape, devices):
    rng = np.random.default_rng(0)
    p = jnp.asarray(rng.normal(size=shape).astype(np.float32))
    m = jnp.asarray(rng.normal(size=shape).astype(np.float32))
    g = jnp.asarray(rng.normal(size=shape).astype(np.float32))
    want_p, want_st = sgd_step(p, SGDState(m), g, lr=0.1, momentum=0.5)
    got_p, got_m = fused_sgd_momentum(p, m, g, lr=0.1, mu=0.5, interpret=True)
    # Same fp32 ops; only fused-multiply-add association may differ.
    np.testing.assert_allclose(np.asarray(got_p), np.asarray(want_p),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_array_equal(np.asarray(got_m), np.asarray(want_st.momentum))


def test_fused_tree_under_vmap_scan(devices):
    # The kernel must survive the engine's composition: vmap over the
    # worker axis, scan over steps, jit outside.
    rng = np.random.default_rng(1)
    W, S, D = 4, 3, 300
    tree = {
        "a": jnp.asarray(rng.normal(size=(W, D)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(W, 5, 7)).astype(np.float32)),
    }
    mom = jax.tree.map(jnp.zeros_like, tree)
    gs = {
        "a": jnp.asarray(rng.normal(size=(S, W, D)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(S, W, 5, 7)).astype(np.float32)),
    }

    def one_worker(p, m, g_steps):
        def step(carry, g):
            p, m = carry
            p, m = fused_sgd_momentum_tree(p, m, g, lr=0.05, mu=0.9,
                                           interpret=True)
            return (p, m), None

        (p, m), _ = jax.lax.scan(step, (p, m), g_steps)
        return p, m

    @jax.jit
    def run(tree, mom, gs):
        gs_w = jax.tree.map(lambda x: jnp.moveaxis(x, 0, 1), gs)  # [W,S,...]
        return jax.vmap(one_worker)(tree, mom, gs_w)

    got_p, got_m = run(tree, mom, gs)

    # Reference: plain sgd_step in the same composition.
    def one_worker_ref(p, m, g_steps):
        def step(carry, g):
            p, m = carry
            p, st = sgd_step(p, SGDState(m), g, lr=0.05, momentum=0.9)
            return (p, st.momentum), None

        (p, m), _ = jax.lax.scan(step, (p, m), g_steps)
        return p, m

    @jax.jit
    def run_ref(tree, mom, gs):
        gs_w = jax.tree.map(lambda x: jnp.moveaxis(x, 0, 1), gs)
        return jax.vmap(one_worker_ref)(tree, mom, gs_w)

    want_p, want_m = run_ref(tree, mom, gs)
    for k in tree:
        np.testing.assert_allclose(np.asarray(got_p[k]), np.asarray(want_p[k]),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(np.asarray(got_m[k]), np.asarray(want_m[k]),
                                   rtol=1e-6, atol=1e-6)


def test_engine_with_fused_update(devices):
    # End-to-end: GossipTrainer with fused_update=True learns and matches
    # the jnp-update run exactly (interpret mode on CPU).
    import dataclasses

    from dopt.config import (DataConfig, ExperimentConfig, GossipConfig,
                             ModelConfig, OptimizerConfig)
    from dopt.engine import GossipTrainer

    def mk(fused):
        return ExperimentConfig(
            name="t", seed=9,
            data=DataConfig(dataset="synthetic", num_users=4,
                            synthetic_train_size=256, synthetic_test_size=64),
            model=ModelConfig(model="mlp", input_shape=(28, 28, 1),
                              faithful=False),
            optim=OptimizerConfig(lr=0.1, momentum=0.5, fused_update=fused),
            gossip=GossipConfig(algorithm="dsgd", topology="circle",
                                mode="metropolis", rounds=2, local_ep=1,
                                local_bs=32),
        )

    a = GossipTrainer(mk(False)); a.run(rounds=2)
    b = GossipTrainer(mk(True)); b.run(rounds=2)
    fa = np.concatenate([np.ravel(x) for x in jax.tree.leaves(jax.device_get(a.params))])
    fb = np.concatenate([np.ravel(x) for x in jax.tree.leaves(jax.device_get(b.params))])
    np.testing.assert_allclose(fa, fb, rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# Fused mix + update (the gossip epilogue, ROADMAP raw-speed lever 3)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n,f", [(6, 137), (5, 1000), (8, 128), (3, 1)])
def test_fused_mix_sgd_matches_reference(n, f, devices):
    # One HBM pass of W @ p − lr·buf on a flat bucket must agree with
    # the jnp composition (f32 matrix + accumulation — the scatter-path
    # numerics contract) to reassociation tolerance.
    from dopt.ops import fused_mix_sgd

    rng = np.random.default_rng(3)
    p = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
    m = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
    w = jnp.asarray(rng.dirichlet(np.ones(n), size=n).astype(np.float32))
    got = fused_mix_sgd(p, m, w, lr=0.05, interpret=True)
    want = (jnp.tensordot(w, p, axes=[[1], [0]]) - 0.05 * m)
    assert got.shape == p.shape and got.dtype == p.dtype
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_fused_mix_sgd_bf16_storage(devices):
    # bf16 leaf storage: matrix + accumulation stay f32, only the final
    # store rounds — same contract as mix_dense_scatter.
    from dopt.ops import fused_mix_sgd

    rng = np.random.default_rng(4)
    p32 = rng.normal(size=(4, 300)).astype(np.float32)
    m32 = rng.normal(size=(4, 300)).astype(np.float32)
    p = jnp.asarray(p32).astype(jnp.bfloat16)
    m = jnp.asarray(m32).astype(jnp.bfloat16)
    w = jnp.asarray(rng.dirichlet(np.ones(4), size=4).astype(np.float32))
    got = fused_mix_sgd(p, m, w, lr=0.1, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = (jnp.tensordot(w, p.astype(jnp.float32), axes=[[1], [0]])
            - 0.1 * m.astype(jnp.float32)).astype(jnp.bfloat16)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_fused_mix_update_tree_over_buckets(devices):
    # The engine-facing wrapper rides the UpdateShardSpec flat-bucket
    # layout: multi-bucket round trip, identical to the tree-level jnp
    # reference.
    from dopt.ops import fused_mix_update, mix_sgd_reference
    from dopt.parallel.collectives import make_update_shard_spec

    rng = np.random.default_rng(5)
    tree = {"a": jnp.asarray(rng.normal(size=(6, 33)).astype(np.float32)),
            "b": jnp.asarray(rng.normal(size=(6, 5, 7)).astype(np.float32))}
    mom = jax.tree.map(
        lambda x: jnp.asarray(
            rng.normal(size=x.shape).astype(np.float32)), tree)
    spec = make_update_shard_spec(tree, fold=2, bucket_bytes=64)
    assert spec.num_buckets > 1  # exercise the per-bucket loop
    w = rng.dirichlet(np.ones(6), size=6).astype(np.float32)
    got = fused_mix_update(tree, mom, w, spec, lr=0.1, interpret=True)
    want = mix_sgd_reference(tree, mom, w, lr=0.1)
    assert jax.tree.structure(got) == jax.tree.structure(tree)
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        assert a.shape == b.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
