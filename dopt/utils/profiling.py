"""Tracing / profiling (absent in the reference — SURVEY §5).

The reference's only instrumentation is ``time.time()`` around
``run()`` printed as "Total Run Time" plus tqdm bars (servers.py:51,79;
simulators.py:115-137).  dopt provides:

* ``PhaseTimers`` — named wall-clock accumulators for the round phases
  (consensus vs local step vs eval vs host batch-planning); rounds/sec
  is a north-star metric so phase attribution is first-class.
* ``trace()`` — context manager wrapping ``jax.profiler`` to dump an
  XLA trace viewable in TensorBoard/Perfetto.

Note on async dispatch: jax returns before device work finishes, so a
``phase()`` context around a jit call measures dispatch only.  Use
``measure(name, fn, *args)`` to attribute device time — it blocks on
the function's result via ``block_until_ready``.
"""

from __future__ import annotations

import contextlib
import re
import time
from collections import defaultdict
from typing import Any, Iterator

import jax


class PhaseTimers:
    """Accumulates wall-clock per named phase.

    ``tracer`` is the telemetry hook (``dopt.obs.SpanTracer`` — or
    anything with a ``span(name)`` context manager): when set, every
    ``phase``/``measure`` additionally records a nested host span, so
    attaching telemetry to a trainer instruments all its existing
    timer sites (host batch planning, the fused block dispatch,
    checkpoint writes) with zero run-loop changes.  None (default)
    keeps the exact pre-telemetry accounting."""

    def __init__(self, tracer=None) -> None:
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)
        self.tracer = tracer

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        """Host wall-clock for the block (dispatch-only for jit calls —
        use ``measure`` to include device time)."""
        span = (self.tracer.span(name) if self.tracer is not None
                else contextlib.nullcontext())
        t0 = time.perf_counter()  # dopt: allow-wallclock -- phase span timing, not training math
        try:
            with span:
                yield
        finally:
            self.totals[name] += time.perf_counter() - t0  # dopt: allow-wallclock -- phase span timing, not training math
            self.counts[name] += 1

    def measure(self, name: str, fn, *args, **kwargs):
        """Run fn, block on its result, attribute the time to ``name``."""
        span = (self.tracer.span(name) if self.tracer is not None
                else contextlib.nullcontext())
        t0 = time.perf_counter()  # dopt: allow-wallclock -- measure span timing, not training math
        with span:
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
        self.totals[name] += time.perf_counter() - t0  # dopt: allow-wallclock -- measure span timing, not training math
        self.counts[name] += 1
        return out

    def summary(self) -> dict[str, dict[str, float]]:
        return {
            name: {
                "total_s": round(self.totals[name], 4),
                "count": self.counts[name],
                "mean_s": round(self.totals[name] / max(self.counts[name], 1), 5),
            }
            for name in self.totals
        }

    def report(self) -> str:
        rows = ["phase                total_s   count   mean_s"]
        for name, s in sorted(self.summary().items(),
                              key=lambda kv: -kv[1]["total_s"]):
            rows.append(f"{name:20s} {s['total_s']:8.3f} {s['count']:7d} {s['mean_s']:9.5f}")
        return "\n".join(rows)


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """XLA profiler trace (TensorBoard/Perfetto-viewable)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


# ---------------------------------------------------------------------
# Round-phase attribution: conv / mixing-comm / update / other
# ---------------------------------------------------------------------
# The round's device time decomposes into the conv stack (the actual
# training math), the consensus/aggregation phase (collectives + the
# mixing contraction, tagged ``dopt_mix`` at the source), and the
# optimizer/weight-update phase (tagged ``dopt_update``).  bench.py
# surfaces these fractions in its JSON line so "conv fraction >= X%"
# claims are measured from the trace, not guessed.

_COMM_MARKERS = ("all-reduce", "all-gather", "reduce-scatter",
                 "collective-permute", "all-to-all", "allreduce",
                 "allgather", "reducescatter", "collectivepermute",
                 "alltoall")

# "conv" but NOT "convert": dtype-conversion ops are everywhere on the
# bf16 fast leg and must not inflate the conv fraction (the acceptance
# metric) with cast overhead.
_CONV_RE = re.compile(r"conv(?!ert)")

PHASES = ("conv", "comm", "update", "other")


def classify_phase(op_type: str | None, operation: str | None = None) -> str:
    """Classify one profiled op into conv | comm | update | other.

    ``op_type`` is the framework-op-stats category, ``operation`` the
    op's name (which carries the jax name stack, so the engines'
    ``dopt_update``/``dopt_mix`` named scopes land here).  Precedence:
    the update tag wins (a sharded update nests inside the mix scope),
    then cross-device collectives and anything in the mixing scope
    (the consensus contraction is comm-phase work even when it lowers
    to a local gemm), then convolutions."""
    t = (op_type or "").lower()
    n = (operation or "").lower()
    if "dopt_update" in n:
        return "update"
    if any(k in t for k in _COMM_MARKERS) or any(k in n for k in _COMM_MARKERS):
        return "comm"
    if "dopt_mix" in n:
        return "comm"
    if _CONV_RE.search(t) or _CONV_RE.search(n):
        return "conv"
    return "other"


def phase_totals(rows) -> dict[str, Any]:
    """Reduce ``(op_type, operation, self_time_us)`` rows to per-phase
    totals + fractions: ``{conv_us, ..., conv_fraction, ...}``.  Pure
    (no profiler dependency) so the classification is unit-testable."""
    tot = {k: 0.0 for k in PHASES}
    for op_type, operation, self_us in rows:
        tot[classify_phase(op_type, operation)] += float(self_us)
    dev = sum(tot.values())
    out: dict[str, Any] = {f"{k}_us": round(v, 1) for k, v in tot.items()}
    for k, v in tot.items():
        out[f"{k}_fraction"] = round(v / dev, 4) if dev > 0 else 0.0
    return out


def xplane_op_stats(trace_dir: str) -> dict[str, Any]:
    """Reduce a captured xplane to op-level self times (the shared
    reduction behind ``scripts/trace_roofline.py`` and ``bench.py``'s
    device-basis rounds/sec).

    Returns ``{device_self_time_us, host_self_time_us,
    device_categories: [{op_type, self_time_us, pct_of_device}],
    device_phases: {conv_us, comm_us, update_us, other_us,
    *_fraction}, top_device_ops: [...]}``.
    """
    import glob
    import json

    from xprof.convert import raw_to_tool_data

    paths = glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True)
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    data, _ = raw_to_tool_data.xspace_to_tool_data(paths,
                                                   "framework_op_stats", {})
    table = json.loads(data if isinstance(data, str) else data.decode())
    if isinstance(table, list):
        table = table[0]
    cols = [c["id"] for c in table["cols"]]
    idx = {c: i for i, c in enumerate(cols)}

    def val(row, col):
        cell = row["c"][idx[col]]
        return None if cell is None else cell.get("v")

    by_cat: dict[str, float] = {}
    device_total = host_total = 0.0
    ops = []
    phase_rows = []
    for row in table.get("rows", []):
        side = val(row, "host_or_device")
        self_us = float(val(row, "total_self_time") or 0.0)
        cat = val(row, "type") or "?"
        if side == "Device":
            device_total += self_us
            by_cat[cat] = by_cat.get(cat, 0.0) + self_us
            phase_rows.append((cat, val(row, "operation"), self_us))
            ops.append({
                "op_type": cat,
                "operation": val(row, "operation"),
                "occurrences": val(row, "occurrences"),
                "total_self_time_us": round(self_us, 1),
            })
        else:
            host_total += self_us
    ops.sort(key=lambda o: -o["total_self_time_us"])
    cat_rows = sorted(by_cat.items(), key=lambda kv: -kv[1])
    return {
        "device_self_time_us": round(device_total, 1),
        "host_self_time_us": round(host_total, 1),
        "device_categories": [
            {"op_type": k, "self_time_us": round(v, 1),
             "pct_of_device": round(100.0 * v / max(device_total, 1e-9), 2)}
            for k, v in cat_rows
        ],
        "device_phases": phase_totals(phase_rows),
        "top_device_ops": ops[:20],
    }


def device_stats_of(fn, *, trace_prefix: str = "dopt-devtime-",
                    telemetry=None) -> dict:
    """Run ``fn()`` under a profiler trace and return the full
    ``xplane_op_stats`` reduction (device self time + the
    conv/comm/update phase split).

    Degrades instead of raising mid-bench: if the profiler cannot
    start/stop or the xplane/tensorboard reduction fails (missing
    xprof stack, parse error), the returned dict carries NaN device
    time, empty breakdowns and a ``warning`` field describing the
    failure — and a ``warning`` telemetry event when ``telemetry``
    (``dopt.obs.Telemetry``) is supplied.  ``fn()``'s own exceptions
    still propagate (a failing workload is a real error).  The temp
    trace directory is removed on every path."""
    import shutil
    import tempfile

    td = tempfile.mkdtemp(prefix=trace_prefix)
    warning = None
    try:
        started = True
        try:
            jax.profiler.start_trace(td)
        except Exception as e:
            started = False
            warning = f"profiler start failed: {e!r}"
        try:
            fn()
        finally:
            if started:
                try:
                    jax.profiler.stop_trace()
                except Exception as e:
                    warning = warning or f"profiler stop failed: {e!r}"
        stats = None
        if warning is None:
            try:
                stats = xplane_op_stats(td)
            except Exception as e:
                warning = f"xplane reduction failed: {e!r}"
        if stats is None:
            stats = {"device_self_time_us": float("nan"),
                     "host_self_time_us": float("nan"),
                     "device_categories": [], "device_phases": {},
                     "top_device_ops": []}
        if warning is not None:
            stats["warning"] = warning
            if telemetry is not None:
                telemetry.emit("warning", message=warning,  # dopt: allow-nondet-event -- degraded-profiler warning, outside DETERMINISTIC_KINDS by design
                               source="device_stats_of")
        return stats
    finally:
        shutil.rmtree(td, ignore_errors=True)


def device_memory_stats(device=None) -> dict | None:
    """Device-memory occupancy snapshot: ``{live_bytes, peak_bytes,
    source}``.

    Uses the backend allocator's stats where the runtime exposes them
    (TPU/GPU ``Device.memory_stats``: ``bytes_in_use`` /
    ``peak_bytes_in_use`` — ``source="device"``); on backends without
    them (CPU jax returns None) falls back to the PROCESS resident set
    (live = current RSS from ``/proc/self/statm``, peak =
    ``ru_maxrss`` — ``source="host_rss"``), so callers always get a
    finite occupancy signal to report/alert on.  Returns None only when
    even the host fallback is unavailable.  This is the shared helper
    behind ``scripts/bench_seqlm.py``'s peak-HBM column, bench.py's
    ``hbm_peak_gb`` field and the engines' ``resource`` telemetry
    events (``diagnostics="on"``)."""
    if device is None:
        devs = jax.local_devices()
        device = devs[0] if devs else None
    stats = None
    if device is not None:
        stats = getattr(device, "memory_stats", lambda: None)()
    if stats and stats.get("peak_bytes_in_use") is not None:
        return {"live_bytes": int(stats.get("bytes_in_use", 0)),
                "peak_bytes": int(stats["peak_bytes_in_use"]),
                "source": "device"}
    try:
        import os
        import resource

        # Linux ru_maxrss is KiB (macOS reports bytes; this repo's
        # runtime surface is Linux — documented, not branched).
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        try:
            with open("/proc/self/statm") as f:
                live = int(f.read().split()[1]) * os.sysconf("SC_PAGESIZE")
        except (OSError, ValueError, IndexError):
            live = peak
        return {"live_bytes": int(live), "peak_bytes": int(peak),
                "source": "host_rss"}
    except Exception:  # pragma: no cover - non-POSIX fallback
        return None


def emit_device_resource(trainer, t: int, fn_name: str, fn) -> None:
    """The NON-deterministic device-resource channel, shared by both
    engines (``diagnostics="on"`` + telemetry attached): an HBM/RSS
    occupancy sample per block at the post-fetch boundary
    (``resource``) and a ``compile`` event whenever the dispatched
    round function (re)traced since the last block.  Both kinds stay
    outside ``DETERMINISTIC_KINDS`` — sampling cadence is an
    execution-path property, like ``alert``/``checkpoint`` — so a
    diagnosed stream still compares canonically equal across paths.

    Reads/advances the trainer's ``_last_step_total`` watermark over
    its ``round_step`` phase-timer total (the dispatch wall that
    absorbed any compile — an upper bound on compile seconds) and its
    ``_compile_watch`` trace-cache watermark."""
    tele = trainer.telemetry
    if tele is None or not trainer._diag:
        return
    step_total = trainer.timers.totals.get("round_step", 0.0)
    seconds = max(step_total - trainer._last_step_total, 0.0)
    trainer._last_step_total = step_total
    comp = trainer._compile_watch.observe(fn_name, fn)
    if comp is not None:
        tele.emit("compile", round=int(t), fn=fn_name,  # dopt: allow-nondet-event -- retrace channel is execution-path state, documented non-deterministic
                  count=comp["count"], total=comp["total"],
                  seconds=round(seconds, 6))
    stats = device_memory_stats()
    if stats is not None:
        tele.emit("resource", round=int(t), engine=trainer.engine_kind,  # dopt: allow-nondet-event -- HBM occupancy sampling cadence is execution-path state, documented non-deterministic
                  **stats)


class CompileWatcher:
    """Retrace detector for jitted round functions.

    ``observe(name, fn)`` snapshots ``fn``'s trace-cache size and
    returns ``{"count": new_entries, "total": size}`` when the cache
    GREW since the previous observation of ``name`` — i.e. the last
    dispatch (re)traced — else None.  A healthy blocked run compiles
    each round function once at warmup; a compile event on every
    observation is the retrace storm the ``retrace_storm`` health rule
    (dopt.obs.rules) alerts on.  Tolerant of jit wrappers without
    ``_cache_size`` (returns None — no signal rather than a crash)."""

    def __init__(self) -> None:
        self._seen: dict[str, int] = {}

    def observe(self, name: str, fn) -> dict | None:
        size = getattr(fn, "_cache_size", None)
        if size is None:
            return None
        try:
            n = int(size())
        except Exception:
            return None
        prev = self._seen.get(name, 0)
        self._seen[name] = n
        if n > prev:
            return {"count": n - prev, "total": n}
        return None


def device_time_of(fn, *, trace_prefix: str = "dopt-devtime-",
                   telemetry=None) -> float:
    """Run ``fn()`` under a profiler trace and return the device self
    time in microseconds — the tunnel-immune basis for rounds/sec.
    NaN (plus a warning event, see ``device_stats_of``) when the
    profiler stack degrades."""
    return device_stats_of(fn, trace_prefix=trace_prefix,
                           telemetry=telemetry)["device_self_time_us"]


# ---------------------------------------------------------------------
# FLOP accounting (MFU meters for the benchmark harnesses)
# ---------------------------------------------------------------------

# Public per-chip peak throughput (bf16 matmul peak).  MFU for f32 runs
# is reported against the same bf16 peak so modes stay comparable — the
# hardware ceiling is the MXU's.
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e, bf16
    "TPU v5": 459e12,        # v5p, bf16
    "TPU v4": 275e12,
}


def device_peak_flops() -> tuple[str, float | None]:
    """(device_kind, bf16 peak FLOP/s or None when unknown, e.g. CPU)."""
    kind = jax.devices()[0].device_kind
    for k, v in PEAK_FLOPS.items():
        if kind.startswith(k):
            return kind, v
    return kind, None


def fwd_flops_per_sample(fn, params, input_shape, *, batch: int = 8,
                         dtype=None) -> float:
    """Forward-pass FLOPs per sample from XLA's compiled cost analysis.

    ``fn(params, x)`` is the forward callable (e.g. ``lambda p, x:
    model.apply({'params': p}, x)``).  Generic across the zoo — no
    per-model analytic tables — and counts what XLA actually lowers
    (convs at 2·MACs, elementwise, norms), so it is the right numerator
    for MFU accounting.  Uses a small batch and divides, which washes
    out fixed per-call ops."""
    import jax.numpy as jnp

    x = jnp.zeros((batch, *input_shape), dtype or jnp.float32)
    compiled = jax.jit(fn).lower(params, x).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else None
    if not ca or "flops" not in ca:
        # Some backends/jax versions return None or omit the key; NaN
        # lets callers (bench_suite) keep their throughput numbers and
        # skip the MFU fields instead of aborting the whole suite.
        return float("nan")
    return float(ca["flops"]) / batch


def train_flops_per_sample(fn, params, input_shape, *, batch: int = 8,
                           dtype=None) -> float:
    """Training FLOPs per sample ≈ 3 × forward (fwd + ~2× in backward)
    — the standard accounting used by the MFU literature."""
    return 3.0 * fwd_flops_per_sample(fn, params, input_shape, batch=batch,
                                      dtype=dtype)
