"""Local optimizer as pure functions (torch-SGD semantics).

The reference trains every client with ``torch.optim.SGD(lr, momentum)``
(``Decentralized Optimization/src/clients.py:13``,
``Distributed Optimization/src/clients.py:15-16``).  Torch's momentum
update is

    buf ← momentum·buf + grad        (buf starts at grad on first step)
    p   ← p − lr·buf

(no dampening, no Nesterov) — note this differs from the classic
"velocity" form ``v ← mu·v − lr·g``; optax's ``trace`` matches torch,
but we implement the two-liner directly so the oracle comparison has no
third-party indirection.  Zero-initialised buffers are exactly
equivalent to torch's lazy buf-starts-at-grad initialisation.

FedProx / FedADMM enter as *gradient edits* before the momentum update,
exactly where the reference mutates ``param.grad``
(``clients.py:111`` prox, ``clients.py:135`` admm):

    prox:  g ← g + rho·(p − theta)
    admm:  g ← g + alpha + rho·(p − theta)
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class SGDState(NamedTuple):
    momentum: jax.Array | dict  # pytree matching params


def init_sgd(params) -> SGDState:
    return SGDState(momentum=jax.tree.map(jnp.zeros_like, params))


def sgd_step(params, state: SGDState, grads, *, lr: float, momentum: float):
    """One torch-semantics SGD step. Returns (new_params, new_state).

    Tagged ``dopt_update`` so profiler traces attribute the optimizer
    phase separately from conv compute and mixing collectives
    (``dopt.utils.profiling.classify_phase``)."""
    with jax.named_scope("dopt_update"):
        new_buf = jax.tree.map(lambda m, g: momentum * m + g,
                               state.momentum, grads)
        new_params = jax.tree.map(lambda p, b: p - lr * b, params, new_buf)
    return new_params, SGDState(momentum=new_buf)


def clip_by_global_norm(grads, max_norm: float):
    """Scale ``grads`` so their global ℓ2 norm is at most ``max_norm``
    (torch.nn.utils.clip_grad_norm_ semantics).  Norm accumulates in
    f32 regardless of the leaf dtype."""
    sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
             for g in jax.tree.leaves(grads))
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(jnp.sqrt(sq), 1e-12))
    return jax.tree.map(lambda g: g * scale.astype(g.dtype), grads)


def clip_by_global_norm_stacked(grads, max_norm: float):
    """Per-worker ``clip_by_global_norm`` over a [W, ...]-stacked pytree:
    each worker's gradient is clipped by its OWN global norm — identical
    to vmapping the per-worker clip."""
    sq = 0.0
    for g in jax.tree.leaves(grads):
        sq = sq + jnp.sum(
            jnp.square(g.astype(jnp.float32)).reshape(g.shape[0], -1), axis=1)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(jnp.sqrt(sq), 1e-12))

    def app(g):
        return g * scale.reshape((-1,) + (1,) * (g.ndim - 1)).astype(g.dtype)

    return jax.tree.map(app, grads)


def prox_grad_edit(grads, params, theta, rho: float):
    """FedProx: g + rho*(p - theta)  (reference clients.py:111)."""
    return jax.tree.map(lambda g, p, t: g + rho * (p - t), grads, params, theta)


def admm_grad_edit(grads, params, theta, alpha, rho: float):
    """FedADMM: g + alpha + rho*(p - theta)  (reference clients.py:135)."""
    return jax.tree.map(
        lambda g, p, t, a: g + a + rho * (p - t), grads, params, theta, alpha
    )


def admm_dual_ascent(alpha, params, theta, rho: float):
    """After local epochs: alpha + rho*(p - theta)  (reference clients.py:141-144)."""
    return jax.tree.map(lambda a, p, t: a + rho * (p - t), alpha, params, theta)


def scaffold_grad_edit(grads, c_global, c_local):
    """SCAFFOLD variance-reduced step: g − c_i + c.

    The reference sketches SCAFFOLD as commented-out dead code
    (``Decentralized Optimization/src/clients.py:146-170``); this is the
    standard algorithm (Karimireddy et al. 2020) implemented properly:
    the client drifts toward the server optimum by correcting its local
    gradient with the difference of server (c) and client (c_i) control
    variates.
    """
    return jax.tree.map(
        lambda g, c, ci: g - ci + c, grads, c_global, c_local
    )


def scaffold_control_update(c_local, c_global, theta, params, *,
                            lr: float, num_steps: int):
    """Option-II client control-variate refresh after K local steps:

        c_i⁺ = c_i − c + (theta − y_i) / (K·lr)

    where theta is the server model the client started from and y_i its
    params after the K local steps.  ``lr`` must be the EFFECTIVE step
    size of the local optimizer: for plain SGD that is the learning rate;
    for heavy-ball momentum the displacement after K steps is
    ≈ (lr/(1−μ))·Σg, so the caller passes lr/(1−momentum) (the engine
    does this, and starts sampled workers from a zero momentum buffer so
    no stale-round momentum leaks into theta − y_i).
    """
    if isinstance(num_steps, (int, float)):
        scale = 1.0 / (lr * max(num_steps, 1))
    else:
        # Traced per-lane step counts (straggler fault injection: each
        # lane refreshes with ITS executed step count).
        scale = 1.0 / (lr * jnp.maximum(num_steps, 1).astype(jnp.float32))
    return jax.tree.map(
        lambda ci, c, t, y: ci - c + scale * (t - y),
        c_local, c_global, theta, params,
    )
