from dopt.parallel.collectives import masked_average, mix_dense, mix_shifts_shardmap
from dopt.parallel.mesh import (make_mesh, make_worker_mesh, shard_worker_tree,
                                worker_sharding)
from dopt.parallel.multihost import (dcn_edge_count, initialize_distributed,
                                     make_hybrid_mesh)
from dopt.parallel.sequence import (dense_attention, make_seq_mesh,
                                    ring_attention, ulysses_attention)

__all__ = [
    "dense_attention",
    "make_seq_mesh",
    "ring_attention",
    "ulysses_attention",
    "make_mesh",
    "make_worker_mesh",
    "shard_worker_tree",
    "worker_sharding",
    "masked_average",
    "mix_dense",
    "mix_shifts_shardmap",
    "initialize_distributed",
    "make_hybrid_mesh",
    "dcn_edge_count",
]
