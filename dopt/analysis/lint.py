"""Trace-safety & determinism linter: ``python -m dopt.analysis.lint dopt/``.

A stdlib-``ast`` pass over library code enforcing the determinism
contract the engines are built on (stateless per-round draws, one
compiled program per shape, telemetry that cannot perturb replay):

``wallclock``
    Wall-clock reads (``time.time``/``monotonic``/``perf_counter``,
    ``datetime.now``) in library code.  Deterministic paths must not
    consult the clock; span timing and telemetry timestamps are the
    audited exceptions (pragma).

``unseeded-rng``
    Global-state RNG: the legacy ``np.random.*`` module-level API,
    stdlib ``random.*`` module functions, seedless
    ``np.random.default_rng()`` / ``random.Random()``.  Library draws
    must come from explicit seeded generators
    (``dopt.utils.prng.host_rng``) so fault traces, cohorts and batch
    plans replay from the config alone.

``trace-hazard``
    Retrace/trace hazards inside functions reachable from
    ``jax.jit`` / ``lax.scan`` / ``lax.cond`` / ``vmap`` /
    ``shard_map`` call sites: ``.item()`` / ``.tolist()`` host syncs,
    ``int()/float()/bool()`` coercion of traced arguments (each one a
    retrace-per-value or concretization error), and data-dependent
    output shapes (``nonzero`` / ``flatnonzero`` / ``unique`` — the
    survivor-counts-as-shapes class PR 4/PR 7 eliminated).
    Reachability is a per-module approximation: functions named at a
    jit/scan/cond/vmap call site or decorated with a jit wrapper,
    plus everything they transitively call through local names.

``nondet-event``
    Emission of non-``DETERMINISTIC_KINDS`` telemetry outside
    ``dopt/obs`` — the canonical-stream guarantee says engine code
    emits only ``round``/``fault``/``gauge`` (plus the ``run``
    header); ``alert``/``checkpoint``/``resource``/``compile`` sites
    in engine code are deliberate exceptions and carry pragmas.

Suppression: ``# dopt: allow-<rule> -- <justification>`` on any line
of the flagged statement (multi-line calls included) or the line
directly above it.  The justification is mandatory; a
bare pragma or an unknown rule name is itself a finding (rule
``pragma``, not suppressible).  Exit codes: 0 clean, 1 findings, 2
usage error; ``--json`` prints the machine-readable report.
"""

from __future__ import annotations

import argparse
import ast
import sys
from pathlib import Path

from dopt.analysis.common import (EXIT_USAGE, Finding, emit_report,
                                  iter_py_files, parse_pragmas, pragma_for)
from dopt.obs.events import DETERMINISTIC_KINDS

RULES = ("wallclock", "unseeded-rng", "trace-hazard", "nondet-event")

# time.* attributes that read a clock.
_CLOCK_ATTRS = {"time", "time_ns", "monotonic", "monotonic_ns",
                "perf_counter", "perf_counter_ns", "localtime", "gmtime"}
# datetime.* / datetime.datetime.* constructors that read a clock.
_DATETIME_NOW = {"now", "utcnow", "today"}
# Legacy numpy global-state RNG API (np.random.<fn> mutates or draws
# from the hidden global RandomState).
_NP_GLOBAL_RNG = {
    "seed", "rand", "randn", "randint", "random", "random_sample",
    "ranf", "sample", "choice", "permutation", "shuffle", "normal",
    "uniform", "standard_normal", "binomial", "poisson", "beta",
    "gamma", "exponential", "bytes", "get_state", "set_state",
}
# stdlib random module-level functions (the hidden global Random()).
_PY_GLOBAL_RNG = {
    "seed", "random", "randint", "randrange", "choice", "choices",
    "shuffle", "sample", "uniform", "gauss", "normalvariate",
    "getrandbits", "betavariate", "expovariate", "triangular",
}
# Call sites whose function-valued arguments enter a traced context.
_JIT_ENTRY_ATTRS = {"jit", "scan", "cond", "while_loop", "fori_loop",
                    "switch", "vmap", "pmap", "checkpoint", "remat",
                    "shard_map", "grad", "value_and_grad"}
# Data-dependent output shapes: nonzero(mask) makes the survivor count
# a SHAPE — a retrace (or concretization error) per distinct count.
_SHAPE_POLY = {"nonzero", "flatnonzero", "unique", "argwhere"}

# Kinds engine code may emit directly; everything else is the obs
# subsystem's job (or a pragma'd, documented exception).
_ALLOWED_KINDS = set(DETERMINISTIC_KINDS) | {"run"}


def _dotted(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_decorator(dec: ast.AST) -> bool:
    """``@jit`` / ``@jax.jit`` / ``@partial(jax.jit, ...)`` /
    ``@jax.checkpoint`` — anything that puts the decorated body in a
    traced context."""
    if isinstance(dec, ast.Call):
        head = _dotted(dec.func)
        if head is not None and head.split(".")[-1] == "partial":
            return any(_is_jit_decorator(a) for a in dec.args)
        dec = dec.func
    name = _dotted(dec)
    return name is not None and name.split(".")[-1] in _JIT_ENTRY_ATTRS


def _static_params(call: ast.AST, params_in_order: list[str]) -> set[str]:
    """Parameter names declared static in a jit wrapper call
    (``static_argnames=(...)`` / ``static_argnums=(...)``): static args
    are Python values, so coercing them is NOT a trace hazard."""
    out: set[str] = set()
    if not isinstance(call, ast.Call):
        return out
    for kw in call.keywords:
        try:
            val = ast.literal_eval(kw.value)
        except (ValueError, SyntaxError):
            continue
        if kw.arg == "static_argnames":
            names = [val] if isinstance(val, str) else list(val)
            out.update(str(n) for n in names)
        elif kw.arg == "static_argnums":
            nums = [val] if isinstance(val, int) else list(val)
            out.update(params_in_order[n] for n in nums
                       if 0 <= n < len(params_in_order))
    return out


class _FuncInfo:
    """One lexical scope (module / class / function / lambda)."""

    def __init__(self, node: ast.AST | None, qualname: str,
                 parent: "_FuncInfo | None") -> None:
        self.node = node
        self.qualname = qualname
        self.parent = parent
        self.children: dict[str, "_FuncInfo"] = {}
        self.calls: set[str] = set()          # locally-called names
        self.params: set[str] = set()
        self.params_in_order: list[str] = []
        self.static: set[str] = set()         # static_argnames/argnums
        self.is_function = isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
        if self.is_function:
            a = node.args
            self.params_in_order = [p.arg for p in (a.posonlyargs
                                                    + a.args)]
            self.params = set(self.params_in_order) | {
                p.arg for p in a.kwonlyargs}
            if a.vararg:
                self.params.add(a.vararg.arg)
            if a.kwarg:
                self.params.add(a.kwarg.arg)


class _Analyzer(ast.NodeVisitor):
    """One pass per module: builds the function scope tree, records
    jit-entry roots and local call edges, and collects rule hits
    (trace hazards held back until reachability is known)."""

    def __init__(self, path: str, source: str) -> None:
        self.path = path
        # dopt/obs IS the telemetry subsystem — the sanctioned producer
        # of the non-deterministic kinds.
        self.in_obs = "dopt/obs" in Path(path).as_posix()
        self.imports: dict[str, str] = {}
        self.root = _FuncInfo(None, "<module>", None)
        self.scope = self.root
        self.jit_roots: set[_FuncInfo] = set()
        self.findings: list[Finding] = []
        # (rule, line, end_line, message, scope, names) — names, when
        # non-None, must intersect the scope's NON-STATIC params for
        # the finding to fire (checked at resolve time, once
        # static_argnames from later jit call sites are known).
        self.deferred: list[
            tuple[str, int, int | None, str, _FuncInfo,
                  set[str] | None]] = []
        self.pragmas = parse_pragmas(source)

    # -- scope handling -------------------------------------------------
    def _enter(self, node: ast.AST, name: str) -> _FuncInfo:
        qn = (name if self.scope is self.root
              else f"{self.scope.qualname}.{name}")
        info = _FuncInfo(node, qn, self.scope)
        self.scope.children[name] = info
        self.scope = info
        return info

    def _exit(self) -> None:
        assert self.scope.parent is not None
        self.scope = self.scope.parent

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._handle_func(node, node.name)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._handle_func(node, node.name)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._enter(node, node.name)
        self.generic_visit(node)
        self._exit()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        info = self._enter(node, f"<lambda:{node.lineno}>")
        if getattr(node, "_dopt_jit_root", False):
            self.jit_roots.add(info)
        self.generic_visit(node)
        self._exit()

    def _handle_func(self, node, name: str) -> None:
        jit_decs = [d for d in node.decorator_list
                    if _is_jit_decorator(d)]
        info = self._enter(node, name)
        if jit_decs:
            self.jit_roots.add(info)
            for d in jit_decs:
                info.static |= _static_params(d, info.params_in_order)
        self.generic_visit(node)
        self._exit()

    def _resolve(self, name: str,
                 scope: "_FuncInfo") -> "_FuncInfo | None":
        s: _FuncInfo | None = scope
        while s is not None:
            if name in s.children:
                return s.children[name]
            s = s.parent
        return None

    # -- imports --------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.asname:
                self.imports[a.asname] = a.name
            else:
                # `import numpy.random` binds the TOP-LEVEL name
                # `numpy`; references then spell the full dotted path
                # themselves, so the head maps to itself (mapping it to
                # the submodule would corrupt canonicalization).
                head = a.name.split(".")[0]
                self.imports[head] = head

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module is not None:
            for a in node.names:
                self.imports[a.asname or a.name] = \
                    f"{node.module}.{a.name}"

    def _canonical(self, dotted: str) -> str:
        head, _, rest = dotted.partition(".")
        base = self.imports.get(head, head)
        return f"{base}.{rest}" if rest else base

    # -- the rules ------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Name):
            self.scope.calls.add(node.func.id)
        dotted = _dotted(node.func)
        canon = self._canonical(dotted) if dotted else None
        if canon is not None:
            self._check_wallclock(node, canon)
            self._check_unseeded_rng(node, canon)
        self._check_nondet_event(node, dotted)
        self._check_jit_entry_call(node, dotted)
        self._check_trace_hazard_call(node, canon)
        self.generic_visit(node)

    def _finding(self, rule: str, line: int, message: str,
                 end: int | None = None) -> None:
        # Any matching pragma suppresses the underlying finding; a
        # BARE one still fails via the unconditional justification
        # sweep in lint_source, whether or not it suppressed anything.
        if pragma_for(self.pragmas, rule, line, end) is None:
            self.findings.append(Finding(rule, self.path, line, message))

    def _check_wallclock(self, node: ast.Call, canon: str) -> None:
        mod, _, attr = canon.rpartition(".")
        hit = ((mod == "time" and attr in _CLOCK_ATTRS)
               or (mod in ("datetime", "datetime.datetime",
                           "datetime.date") and attr in _DATETIME_NOW))
        if hit:
            self._finding(
                "wallclock", node.lineno,
                f"wall-clock read `{canon}()` in library code — "
                "deterministic paths must not consult the clock",
                end=node.end_lineno)

    def _check_unseeded_rng(self, node: ast.Call, canon: str) -> None:
        mod, _, attr = canon.rpartition(".")
        if mod == "numpy.random" and attr in _NP_GLOBAL_RNG:
            self._finding(
                "unseeded-rng", node.lineno,
                f"global-state RNG `np.random.{attr}()` — draw from an "
                "explicit seeded generator (dopt.utils.prng.host_rng)",
                end=node.end_lineno)
        elif canon == "numpy.random.default_rng" and not (
                node.args or node.keywords):
            self._finding(
                "unseeded-rng", node.lineno,
                "seedless `np.random.default_rng()` draws from OS "
                "entropy — pass an explicit seed", end=node.end_lineno)
        elif mod == "random" and attr in _PY_GLOBAL_RNG:
            self._finding(
                "unseeded-rng", node.lineno,
                f"stdlib global RNG `random.{attr}()` — use an explicit "
                "seeded generator", end=node.end_lineno)
        elif canon == "random.Random" and not (node.args or node.keywords):
            self._finding(
                "unseeded-rng", node.lineno,
                "seedless `random.Random()` — pass an explicit seed",
                end=node.end_lineno)

    def _check_nondet_event(self, node: ast.Call,
                            dotted: str | None) -> None:
        is_emit = (isinstance(node.func, ast.Attribute)
                   and node.func.attr == "emit")
        is_make = (dotted is not None
                   and dotted.split(".")[-1] == "make_event")
        if self.in_obs or not (is_emit or is_make):
            return
        kind = (node.args[0] if node.args
                else next((kw.value for kw in node.keywords
                           if kw.arg == "kind"), None))
        if (isinstance(kind, ast.Constant) and isinstance(kind.value, str)
                and kind.value not in _ALLOWED_KINDS):
            self._finding(
                "nondet-event", node.lineno,
                f"emission of non-deterministic kind {kind.value!r} "
                f"outside dopt/obs — only {sorted(_ALLOWED_KINDS)} "
                "keep the canonical-stream guarantee",
                end=node.end_lineno)

    def _check_jit_entry_call(self, node: ast.Call,
                              dotted: str | None) -> None:
        if dotted is None or dotted.split(".")[-1] not in _JIT_ENTRY_ATTRS:
            return
        for arg in node.args:
            if isinstance(arg, ast.Name):
                info = self._resolve(arg.id, self.scope)
                if info is not None:
                    self.jit_roots.add(info)
                    info.static |= _static_params(
                        node, info.params_in_order)
            elif isinstance(arg, ast.Lambda):
                # Visited (after this call returns) as a child scope;
                # the marker survives into visit_Lambda.
                arg._dopt_jit_root = True  # type: ignore[attr-defined]

    def _enclosing_function(self) -> _FuncInfo | None:
        s: _FuncInfo | None = self.scope
        while s is not None and not s.is_function:
            s = s.parent
        return s

    def _check_trace_hazard_call(self, node: ast.Call,
                                 canon: str | None) -> None:
        scope = self._enclosing_function()
        if scope is None:
            return
        if isinstance(node.func, ast.Attribute):
            if node.func.attr in ("item", "tolist") and not node.args:
                self.deferred.append((
                    "trace-hazard", node.lineno, node.end_lineno,
                    f"`.{node.func.attr}()` forces a host sync / "
                    "concretization inside a jit-reachable function",
                    scope, None))
            elif node.func.attr in _SHAPE_POLY:
                self.deferred.append((
                    "trace-hazard", node.lineno, node.end_lineno,
                    f"data-dependent output shape `{node.func.attr}` "
                    "inside a jit-reachable function — survivor counts "
                    "must stay data, not shapes", scope, None))
        if canon in ("int", "float", "bool") and len(node.args) == 1:
            arg = node.args[0]
            names = {n.id for n in ast.walk(arg)
                     if isinstance(n, ast.Name)}
            if not isinstance(arg, ast.Constant) and names & scope.params:
                self.deferred.append((
                    "trace-hazard", node.lineno, node.end_lineno,
                    f"`{canon}()` coercion of a traced argument inside "
                    "a jit-reachable function concretizes (or retraces "
                    "per value)", scope, names))

    # -- resolution -----------------------------------------------------
    def resolve(self) -> list[Finding]:
        reachable: set[_FuncInfo] = set()
        frontier = list(self.jit_roots)
        while frontier:
            fn = frontier.pop()
            if fn in reachable:
                continue
            reachable.add(fn)
            for name in fn.calls:
                callee = self._resolve(name, fn)
                if callee is not None and callee not in reachable:
                    frontier.append(callee)
        for rule, line, end, message, scope, names in self.deferred:
            if names is not None and not (
                    names & (scope.params - scope.static)):
                continue
            s: _FuncInfo | None = scope
            while s is not None:
                if s in reachable:
                    self._finding(rule, line, message, end=end)
                    break
                s = s.parent
        return self.findings


def lint_source(source: str, path: str = "<string>",
                rules: tuple[str, ...] = RULES) -> list[Finding]:
    """Lint one module's source; returns surviving findings."""
    tree = ast.parse(source, filename=path)
    an = _Analyzer(path, source)
    an.visit(tree)
    findings = an.resolve()
    known = set(RULES) | {"pragma"}
    for line, pragmas in an.pragmas.items():
        for p in pragmas:
            if p.rule not in known:
                findings.append(Finding(
                    "pragma", path, line,
                    f"unknown pragma rule `allow-{p.rule}` (rules: "
                    f"{', '.join(RULES)})"))
            elif not p.justification:
                # Unconditional: a bare pragma is a finding whether or
                # not it currently suppresses anything — stale and
                # pre-placed pragmas must not erode the audit trail.
                findings.append(Finding(
                    "pragma", path, line,
                    f"allow-{p.rule} pragma without a justification "
                    f"(write `# dopt: allow-{p.rule} -- <why>`)"))
    return [f for f in findings if f.rule == "pragma" or f.rule in rules]


def lint_paths(paths: list[str],
               rules: tuple[str, ...] = RULES) -> tuple[list[Finding], int]:
    findings: list[Finding] = []
    checked = 0
    for p in iter_py_files(paths):
        checked += 1
        try:
            src = p.read_text()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding("io", str(p), 0, str(e)))
            continue
        try:
            findings.extend(lint_source(src, str(p), rules))
        except SyntaxError as e:
            findings.append(Finding("io", str(p), e.lineno or 0,
                                    f"syntax error: {e.msg}"))
    return findings, checked


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dopt.analysis.lint",
        description="Trace-safety & determinism linter for dopt "
                    "library code.")
    ap.add_argument("paths", nargs="*", metavar="PATH",
                    help="files/directories to lint (default: dopt)")
    ap.add_argument("--rules", default=",".join(RULES),
                    help="comma-separated rule subset "
                         f"(default: {','.join(RULES)})")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)
    rules = tuple(r for r in args.rules.split(",") if r)
    unknown = set(rules) - set(RULES)
    if unknown:
        print(f"unknown rule(s): {', '.join(sorted(unknown))}; "
              f"valid: {', '.join(RULES)}", file=sys.stderr)
        return EXIT_USAGE
    paths = args.paths or ["dopt"]
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return EXIT_USAGE
    findings, checked = lint_paths(paths, rules)
    return emit_report(findings, as_json=args.json,
                       tool="dopt.analysis.lint", checked=checked)


if __name__ == "__main__":
    raise SystemExit(main())
