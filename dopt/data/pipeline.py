"""Host-side batch planning for the stacked-worker TPU engine.

The reference gives each client its own ``DataLoader(shuffle=True)``
(``Decentralized Optimization/src/clients.py:16-34``).  The TPU engine
instead runs ONE program over a ``[workers, ...]`` stacked state, so
batching becomes data: a deterministic per-(round, epoch, worker)
shuffled index tensor, gathered host-side into
``[workers, steps, batch, ...]`` arrays and sharded along the worker
mesh axis (SURVEY §7 hard part: per-worker data feeding one program).

Static shapes for XLA: the last partial batch is padded by wraparound
with a 0/1 sample-weight mask; losses and metrics are mask-weighted so
padding never changes the math.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class BatchPlan:
    """Index plan for one round of local training on every worker.

    idx:    [W, S, B] int32 — S = local_ep * steps_per_epoch gather indices
    weight: [W, S, B] float32 — 1.0 for real samples, 0.0 for padding
    """

    idx: np.ndarray
    weight: np.ndarray

    @property
    def num_workers(self) -> int:
        return self.idx.shape[0]

    @property
    def steps(self) -> int:
        return self.idx.shape[1]

    @property
    def batch_size(self) -> int:
        return self.idx.shape[2]


def make_batch_plan(
    index_matrix: np.ndarray,
    *,
    batch_size: int,
    local_ep: int = 1,
    seed: int = 0,
    round_idx: int = 0,
    drop_last: bool = False,
    impl: str = "numpy",
    workers: np.ndarray | None = None,
    rows: np.ndarray | None = None,
) -> BatchPlan:
    """Build the shuffled batch plan for one round.

    ``index_matrix`` is [W, L] per-worker dataset indices (from
    ``dopt.data.partition``).  Shuffling is deterministic in
    (seed, round_idx, epoch, worker) so the torch oracle and the jax
    engine consume byte-identical batches — that determinism is what
    makes step-level numerics parity testable at all.

    ``workers`` (optional [m] int array of worker ids) plans only those
    workers' rows, returning an [m, S, B] plan bit-identical to the
    matching rows of the full plan — the RNG is keyed by the TRUE worker
    id, not the row position.  This keeps the compact-sampling fast path
    O(m) on the host instead of O(W).

    ``rows`` (optional [m] int array, requires ``workers``) decouples
    the DATA rows gathered from the RNG identities: row ``rows[i]`` of
    ``index_matrix`` is shuffled under worker key ``workers[i]``.  The
    client-population path (``dopt.population``) uses this to bind a
    cohort of clients onto their assigned data shards — two clients
    sharing a shard still draw DISTINCT client-keyed batch streams,
    and when client ids equal shard ids the plan is bit-identical to
    the classic per-worker plan (the cohort-vs-flat parity contract).

    ``impl='native'`` fills the plan with the C++ host runtime
    (``dopt.native``) — same contract and determinism key, different
    (xoshiro) RNG stream, so it is the throughput mode, not the
    oracle-parity mode; silently falls back to numpy when the native
    library is unavailable.
    """
    worker_ids = None
    if rows is not None and workers is None:
        raise ValueError("make_batch_plan: rows= requires workers= "
                         "(the RNG identity keys)")
    if workers is not None:
        worker_ids = np.asarray(workers, dtype=np.int64)
        sel = (np.asarray(rows, dtype=np.int64) if rows is not None
               else worker_ids)
        index_matrix = index_matrix[sel]
    if impl == "native":
        from dopt.native import fill_batch_plan_native

        out = fill_batch_plan_native(
            index_matrix, batch_size=batch_size, local_ep=local_ep,
            seed=seed, round_idx=round_idx, drop_last=drop_last,
            worker_ids=worker_ids,
        )
        if out is not None:
            return BatchPlan(idx=out[0], weight=out[1])
    w, l = index_matrix.shape
    bs = min(batch_size, l)
    if drop_last:
        steps_per_epoch = l // bs
        padded = steps_per_epoch * bs
    else:
        steps_per_epoch = -(-l // bs)  # ceil
        padded = steps_per_epoch * bs
    s = local_ep * steps_per_epoch

    # Per-(worker, epoch) permutations keep their SeedSequence keys —
    # the (seed, round, ep, wid) keying is the byte-identity contract
    # with the torch oracle and every historical plan — but everything
    # downstream of the draws (wraparound padding, the gather from
    # index_matrix, the [W, S, B] reshape) runs as batched numpy ops
    # over the whole fleet instead of an O(W) python loop: the RNG
    # draws are the only remaining per-worker python work, and they are
    # one C call each.
    pad = padded - l
    perms = np.empty((w, local_ep, padded), dtype=np.int64)
    for wi in range(w):
        wid = int(worker_ids[wi]) if worker_ids is not None else wi
        for ep in range(local_ep):
            rng = np.random.default_rng(
                np.random.SeedSequence([seed, round_idx, ep, wid])
            )
            perm = rng.permutation(l)
            if drop_last:
                perms[wi, ep] = perm[:padded]
            elif pad:
                perms[wi, ep, :l] = perm
                perms[wi, ep, l:] = perm[:pad]
            else:
                perms[wi, ep] = perm
    # One gather for the fleet: [W, 1, L] rows indexed by [W, E, padded].
    gathered = np.take_along_axis(index_matrix[:, None, :], perms, axis=2)
    idx = np.ascontiguousarray(
        gathered.reshape(w, s, bs).astype(np.int32, copy=False))
    if drop_last or pad == 0:
        weight = np.ones((w, s, bs), np.float32)
    else:
        epoch_mask = np.concatenate(
            [np.ones(l, np.float32), np.zeros(pad, np.float32)]
        ).reshape(steps_per_epoch, bs)
        weight = np.tile(epoch_mask[None], (w, local_ep, 1)).reshape(w, s, bs)
    return BatchPlan(idx=idx, weight=weight)


def gather_batches(
    x: np.ndarray, y: np.ndarray, plan: BatchPlan
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Materialise [W, S, B, ...] feature / label / weight arrays from a
    plan — the host→device transfer payload for one round."""
    bx = x[plan.idx]            # [W, S, B, ...]
    by = y[plan.idx].astype(np.int32)
    return bx, by, plan.weight


def stacked_eval_batches(
    index_matrix: np.ndarray, *, batch_size: int
) -> tuple[np.ndarray, np.ndarray]:
    """Per-worker static-shape eval stacks over index rows: [W, S, B]
    gather indices + 0/1 wraparound-padding weights.  Used for the
    local-validation holdout eval (the reference's per-client val
    loader) and the per-client train-split eval
    (``avg_trainig_calculator``)."""
    w, l = index_matrix.shape
    bs = min(batch_size, l)
    steps = -(-l // bs)
    pad = steps * bs - l
    idx = (index_matrix if pad == 0
           else np.concatenate([index_matrix, index_matrix[:, :pad]], axis=1))
    weight = np.concatenate(
        [np.ones((w, l), np.float32), np.zeros((w, pad), np.float32)], axis=1)
    return (idx.reshape(w, steps, bs).astype(np.int32),
            weight.reshape(w, steps, bs))


def sharded_eval_batches(
    n: int, workers: int, *, batch_size: int
) -> tuple[np.ndarray, np.ndarray]:
    """Round-robin 1/W shard of an n-sample eval set per worker:
    [W, S, B] gather indices + 0/1 padding weights.

    The throughput-trim alternative to every worker evaluating the FULL
    set (``GossipConfig.eval_mode='sharded'``): the fleet-MEAN metric is
    an unbiased estimate built from n total sample-forwards instead of
    W·n (measured 3.1 s/round of the baseline5 wall — more than the
    training step itself), at the price of noisier PER-WORKER rows
    (~n/W samples each).  Shards are round-robin so class mix is
    near-uniform across workers for shuffled eval sets."""
    l = -(-n // workers)
    idx = np.zeros((workers, l), np.int64)
    wt = np.zeros((workers, l), np.float32)
    for i in range(workers):
        r = np.arange(i, n, workers)
        idx[i, :len(r)] = r
        wt[i, :len(r)] = 1.0
        if 0 < len(r) < l:
            # Wraparound padding from the shard's own rows; a worker
            # with NO shard rows at all (workers > n) keeps the zero
            # indices at weight 0 — valid gathers, zero contribution.
            idx[i, len(r):] = r[:l - len(r)]
    bs = min(batch_size, l)
    steps = -(-l // bs)
    pad = steps * bs - l
    if pad:
        idx = np.concatenate([idx, idx[:, :pad]], axis=1)
        wt = np.concatenate([wt, np.zeros((workers, pad), np.float32)],
                            axis=1)
    return (idx.reshape(workers, steps, bs).astype(np.int32),
            wt.reshape(workers, steps, bs))


def eval_batches(
    x: np.ndarray, y: np.ndarray, *, batch_size: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Static-shape eval split: [S, B, ...] with wraparound padding mask
    (shared by all workers — evaluation uses the full test set, matching
    the reference's per-client test loader over the whole test split)."""
    n = len(y)
    bs = min(batch_size, n)
    steps = -(-n // bs)
    padded = steps * bs
    pad = padded - n
    idx = np.arange(n)
    if pad:
        idx = np.concatenate([idx, idx[:pad]])
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    return (
        x[idx].reshape(steps, bs, *x.shape[1:]),
        y[idx].reshape(steps, bs).astype(np.int32),
        mask.reshape(steps, bs),
    )
