"""Compression operators for communication-efficient gossip (CHOCO-SGD).

The reference has no notion of communication cost at all (its "network"
is Python object passing — SURVEY §2.4); these operators exist for the
framework's own communication-efficient algorithms
(``GossipConfig.algorithm='choco'``): each worker communicates a
compressed *difference* ``Q(x_i − x̂_i)`` instead of full parameters,
with the error kept in ``x_i − x̂_i`` and fed back next round (error
feedback is what makes aggressive compression convergent).

All operators are pure, shape-static (XLA-friendly: ``top_k`` with a
compile-time k, seeded masks instead of data-dependent sparsity), and
act per worker on stacked [W, ...] pytrees.

Contract: an operator maps (tree, key) → tree of the same structure.
For the SPARSIFIERS (``topk``, ``randk``) ``ratio`` is the fraction of
entries communicated and ``ratio=1.0`` is the exact identity — that
invariant is what the choco≡dsgd reduction test pins.  ``qsgd`` is a
QUANTIZER with different ratio semantics: ratio sets the level count
(ratio=1 → 256-level stochastic quantization, NOT the identity); use
``compression='none'`` for the exact D-SGD reduction.

Key handling is STATELESS, FaultPlan-style: the caller folds the round
into a base key once (``fold_in(base, t)``) and every leaf/lane draw
here derives from it by a further ``fold_in`` on the leaf index (tree
operators) or the GLOBAL worker-lane id (flat-slab codecs).  No split
chains, no carried RNG state — the bits for (round, leaf/bucket, lane)
are a pure function of those coordinates, which is what makes
compressed runs bit-reproducible, blocked-exact and resume-exact, and
what lets the sharded scatter path and the dense reference path draw
IDENTICAL bits (each device folds its own global lane ids).

The flat-slab codecs (``qint_encode``/``qint_decode``) are the wire
format of the per-bucket communication substrate
(``dopt.parallel.collectives.mix_codec_gather``): per-chunk max-abs
scaled stochastic integer quantization at 8 or 4 bits, nibble-packed
at 4 — the payload that actually crosses ICI/DCN is the int8/uint8
level tensor plus the tiny f32 scale sidecar.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def _per_worker_topk(flat: jnp.ndarray, k: int) -> jnp.ndarray:
    """flat: [W, N] — keep the k largest-|·| entries per row."""
    n = flat.shape[1]
    if k >= n:
        return flat
    _, idx = jax.lax.top_k(jnp.abs(flat), k)          # [W, k]
    mask = jnp.zeros_like(flat).at[
        jnp.arange(flat.shape[0])[:, None], idx].set(1.0)
    return flat * mask


def top_k_compress(tree, ratio: float):
    """Magnitude top-k sparsification, per worker per leaf.  k is
    static: ceil(ratio · leaf_size) — jit-stable shapes."""
    if ratio >= 1.0:
        return tree

    def comp(x):
        w = x.shape[0]
        n = math.prod(x.shape[1:]) or 1
        k = max(int(math.ceil(ratio * n)), 1)
        flat = x.reshape(w, n).astype(jnp.float32)
        return _per_worker_topk(flat, k).reshape(x.shape).astype(x.dtype)

    return jax.tree.map(comp, tree)


def rand_k_compress(tree, ratio: float, key):
    """Fixed-cardinality random-k sparsification with n/k rescaling
    (unbiased): EXACTLY k = ceil(ratio · leaf_size) entries per worker
    per leaf survive, drawn uniformly without replacement (top-k over a
    random-score tensor — a static-shape permutation draw), matching
    the rand-k operator of the compression literature so a packed
    transport has a FIXED wire size per round.  The index set is drawn
    from ``key`` per leaf — pass a per-round key so workers/rounds
    decorrelate."""
    if ratio >= 1.0:
        return tree
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = [jax.random.fold_in(key, i) for i in range(len(leaves))]

    def comp(x, k_):
        w = x.shape[0]
        n = math.prod(x.shape[1:]) or 1
        k = max(int(math.ceil(ratio * n)), 1)
        flat = x.reshape(w, n)
        scores = jax.random.uniform(k_, (w, n))
        _, idx = jax.lax.top_k(scores, k)                 # k uniform w/o repl.
        mask = jnp.zeros((w, n), x.dtype).at[
            jnp.arange(w)[:, None], idx].set(1)
        scale = jnp.asarray(n / k, x.dtype)               # E[x̂] = x
        return (flat * mask * scale).reshape(x.shape)

    return jax.tree_util.tree_unflatten(
        treedef, [comp(x, k) for x, k in zip(leaves, keys)])


def qsgd_compress(tree, ratio: float, key, *, bucket_size: int = 2048,
                  levels: int | None = None):
    """QSGD stochastic quantization (Alistarh et al. 2017), per worker
    per leaf: x → ‖x‖₂ · sign(x) · ξ(x)/s with ξ an unbiased stochastic
    rounding of s·|x|/‖x‖₂ to integer levels.  The level count s comes
    from ``levels`` directly when given (``GossipConfig.qsgd_levels``),
    else from ``ratio`` as s = max(round(ratio · 256), 1) — the fraction
    of an 8-bit range used; smaller s = coarser quantization = fewer
    wire bits in a real packed transport.

    Norms are per ``bucket_size`` chunk (standard QSGD bucketing):
    without it the quantization step scales with the WHOLE leaf's norm
    (~√N · rms) and the noise swamps million-parameter models."""
    s = levels if levels else max(int(round(ratio * 256)), 1)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = [jax.random.fold_in(key, i) for i in range(len(leaves))]

    def comp(x, k):
        w = x.shape[0]
        n = math.prod(x.shape[1:]) or 1
        b = min(bucket_size, n)
        nb = -(-n // b)
        pad = nb * b - n
        flat = x.reshape(w, n).astype(jnp.float32)
        if pad:
            flat = jnp.pad(flat, ((0, 0), (0, pad)))
        bk = flat.reshape(w, nb, b)
        norm = jnp.linalg.norm(bk, axis=2, keepdims=True)
        safe = jnp.maximum(norm, 1e-12)
        level = s * jnp.abs(bk) / safe                     # in [0, s]
        floor = jnp.floor(level)
        frac = level - floor
        up = (jax.random.uniform(k, bk.shape) < frac).astype(jnp.float32)
        q = jnp.sign(bk) * (floor + up) * safe / s
        q = jnp.where(norm > 0, q, 0.0)
        q = q.reshape(w, nb * b)[:, :n]
        return q.reshape(x.shape).astype(x.dtype)

    return jax.tree_util.tree_unflatten(
        treedef, [comp(x, k) for x, k in zip(leaves, keys)])


# ---------------------------------------------------------------------
# Flat-slab wire codecs (the per-bucket communication substrate)
# ---------------------------------------------------------------------
# Operate on [L, F] lane slabs (L worker lanes, F flat bucket elements
# — the dopt.parallel.collectives UpdateShardSpec layout).  Per-chunk
# max-abs scaling keeps the quantization step local (QSGD bucketing,
# Alistarh et al. 2017); stochastic rounding keeps the codec unbiased;
# per-GLOBAL-lane fold-in keys keep the draws identical whether a lane
# is encoded on its owning device (shard_map) or in the dense
# reference view.

QINT_QMAX = {8: 127, 4: 7}


def lane_fold_keys(key, lane_ids):
    """[L] per-lane keys: ``fold_in(key, global_lane_id)`` vectorised.
    ``lane_ids`` may be traced (``axis_index·L + arange(L)`` inside a
    shard_map) — the bits depend only on (key, global lane), never on
    the device that computes them."""
    return jax.vmap(lambda i: jax.random.fold_in(key, i))(lane_ids)


def _chunk_pad(f: int, chunk: int) -> tuple[int, int]:
    nc = -(-f // chunk)
    return nc, nc * chunk - f


def qint_encode(v, lane_ids, key, *, chunk: int = 1024, bits: int = 8):
    """Stochastically round a [L, F] f32 slab to ``bits``-bit integer
    levels with per-(lane, chunk) max-abs scales.

    Returns ``(payload, scale)`` — the two tensors that cross the wire:

    * bits=8 — ``payload`` int8 [L, Fp] (levels in [-127, 127]),
    * bits=4 — ``payload`` uint8 [L, Fp/2] (two sign-magnitude nibbles
      per byte, level + 8 biased into [1, 15]),

    with ``scale`` f32 [L, Fp/chunk] and Fp = F rounded up to a chunk
    multiple (``chunk`` must be even so nibble pairs never straddle).
    Rounding is unbiased: level = floor(v/scale + u) with u ~ U[0, 1)
    drawn from the per-global-lane fold-in key, and |v/scale| ≤ qmax by
    construction so the clip never bites."""
    if bits not in QINT_QMAX:
        raise ValueError(f"qint codec supports bits in {{8, 4}}, got {bits}")
    if chunk % 2:
        raise ValueError(f"qint chunk must be even, got {chunk}")
    qmax = QINT_QMAX[bits]
    l, f = v.shape
    nc, pad = _chunk_pad(f, chunk)
    vf = v.astype(jnp.float32)
    if pad:
        vf = jnp.pad(vf, ((0, 0), (0, pad)))
    bk = vf.reshape(l, nc, chunk)
    scale = jnp.abs(bk).max(axis=2) / qmax                 # [L, nc]
    safe = jnp.where(scale > 0, scale, 1.0)
    y = bk / safe[:, :, None]                              # |y| <= qmax
    keys = lane_fold_keys(key, lane_ids)
    u = jax.vmap(lambda k: jax.random.uniform(k, (nc, chunk)))(keys)
    lv = jnp.clip(jnp.floor(y + u), -qmax, qmax).astype(jnp.int32)
    lv = lv.reshape(l, nc * chunk)
    if bits == 8:
        return lv.astype(jnp.int8), scale
    biased = (lv + 8).astype(jnp.uint8)                    # [1, 15]
    packed = biased[:, 0::2] | (biased[:, 1::2] << 4)
    return packed, scale


def qint_decode(payload, scale, f: int, *, chunk: int = 1024,
                bits: int = 8, out_dtype=jnp.float32):
    """Inverse of ``qint_encode``: levels · per-chunk scale, sliced back
    to the true bucket width ``f``.  Works on gathered payloads too —
    the leading axis is whatever the wire carried ([L] local or [n]
    fleet-wide)."""
    if bits == 8:
        lv = payload.astype(jnp.float32)
    else:
        lo = (payload & 0xF).astype(jnp.int32)
        hi = ((payload >> 4) & 0xF).astype(jnp.int32)
        lv = jnp.stack([lo, hi], axis=-1).reshape(
            payload.shape[0], -1).astype(jnp.float32) - 8.0
    nc = scale.shape[-1]
    safe = jnp.where(scale > 0, scale, 1.0)
    bk = lv.reshape(lv.shape[0], nc, -1) * safe[:, :, None]
    return bk.reshape(lv.shape[0], nc * bk.shape[2])[:, :f].astype(out_dtype)


def qint_wire_bytes(f: int, *, chunk: int = 1024, bits: int = 8) -> int:
    """Per-lane wire bytes of one encoded bucket: the packed level
    payload plus the f32 scale sidecar (the analytic mirror of what
    ``hlo_collective_bytes`` measures from the compiled program)."""
    nc, pad = _chunk_pad(f, chunk)
    fp = f + pad
    return fp * bits // 8 + nc * 4


def make_compressor(name: str, ratio: float, *, qsgd_levels: int = 0):
    """Operator factory: (tree, key) → compressed tree.

    'topk'  — deterministic magnitude top-k (ignores the key)
    'randk' — unbiased fixed-cardinality random-k with rescaling
    'qsgd'  — unbiased stochastic quantization; level count from
              ``qsgd_levels`` when > 0, else from ratio (ratio·256)
    'none'  — identity (ratio ignored)
    """
    if name not in ("none", "topk", "randk", "qsgd"):
        raise ValueError(
            f"unknown compressor {name!r}; one of none|topk|randk|qsgd")
    if name != "none" and not 0.0 < ratio <= 1.0:
        # ratio=0 would divide by zero in randk (NaN params on round 0)
        # and negative ratios would silently zero all communication.
        raise ValueError(f"compression_ratio must be in (0, 1], got {ratio}")
    if qsgd_levels and name != "qsgd":
        raise ValueError(
            f"qsgd_levels only applies to compression='qsgd' (got {name!r})")
    if qsgd_levels < 0:
        raise ValueError(f"qsgd_levels must be >= 0, got {qsgd_levels}")
    if name == "none" or (name != "qsgd" and ratio >= 1.0):
        return lambda tree, key: tree
    if name == "topk":
        return lambda tree, key: top_k_compress(tree, ratio)
    if name == "qsgd":
        return lambda tree, key: qsgd_compress(tree, ratio, key,
                                               levels=qsgd_levels or None)
    return lambda tree, key: rand_k_compress(tree, ratio, key)
