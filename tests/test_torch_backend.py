"""backend='torch': the sequential reference oracle as a driveable
trainer, compared trajectory-for-trajectory against the jax engines on
identical inputs (same flax init, plans, sampling streams, holdout)."""

import dataclasses

import jax
import numpy as np
import pytest

import dopt
from dopt.run import build_trainer


def _gossip(backend, algorithm="dsgd", holdout=0.0, **gkw):
    g = dict(algorithm=algorithm, topology="circle", mode="uniform",
             rounds=3, local_ep=1, local_bs=32)
    g.update(gkw)
    return dopt.ExperimentConfig(
        name="tb", seed=11, backend=backend,
        data=dopt.DataConfig(dataset="synthetic", num_users=4, iid=False,
                             shards=2, synthetic_train_size=256,
                             synthetic_test_size=64, local_holdout=holdout,
                             holdout_mode="random"),
        model=dopt.ModelConfig(model="mlp", faithful=False),
        optim=dopt.OptimizerConfig(lr=0.05, momentum=0.5),
        gossip=dopt.GossipConfig(**g),
    )


def _fed(backend, algorithm="fedavg", holdout=0.0):
    return dopt.ExperimentConfig(
        name="tb", seed=11, backend=backend,
        data=dopt.DataConfig(dataset="synthetic", num_users=4, iid=True,
                             synthetic_train_size=256, synthetic_test_size=64,
                             local_holdout=holdout),
        model=dopt.ModelConfig(model="mlp", faithful=False),
        optim=dopt.OptimizerConfig(lr=0.05, momentum=0.5, rho=0.2),
        federated=dopt.FederatedConfig(algorithm=algorithm, frac=0.5,
                                       rounds=3, local_ep=2, local_bs=32),
    )


def _max_rel(tree_a, tree_b):
    la = sorted(jax.tree.leaves(tree_a), key=lambda x: x.shape)
    lb = sorted(jax.tree.leaves(tree_b), key=lambda x: x.shape)
    return max(
        float(np.abs(np.asarray(a) - np.asarray(b)).max()
              / max(float(np.abs(np.asarray(a)).max()), 1e-9))
        for a, b in zip(la, lb))


def test_gossip_trajectory_matches_jax(devices):
    tj = build_trainer(_gossip("jax"))
    tt = build_trainer(_gossip("torch"))
    hj, ht = tj.run(), tt.run()
    for rj, rt in zip(hj.rows, ht.rows):
        assert rj["avg_test_acc"] == pytest.approx(rt["avg_test_acc"],
                                                   abs=1e-4)
        assert rj["avg_train_loss"] == pytest.approx(rt["avg_train_loss"],
                                                     abs=1e-3)
    assert _max_rel(jax.device_get(tj.params), tt.params_as_flax()) < 1e-4


@pytest.mark.parametrize("algorithm", ["fedavg", "fedprox", "fedadmm",
                                       "scaffold"])
def test_federated_trajectory_matches_jax(devices, algorithm):
    fj = build_trainer(_fed("jax", algorithm))
    ft = build_trainer(_fed("torch", algorithm))
    hj, ht = fj.run(), ft.run()
    for rj, rt in zip(hj.rows, ht.rows):
        assert rj["test_acc"] == pytest.approx(rt["test_acc"], abs=1e-3)
        assert rj["local_loss"] == pytest.approx(rt["local_loss"], abs=2e-3)
    assert _max_rel(jax.device_get(fj.theta), ft.theta_as_flax()) < 5e-4


def test_holdout_client_history_matches_jax(devices):
    fj = build_trainer(_fed("jax", holdout=0.1))
    ft = build_trainer(_fed("torch", holdout=0.1))
    fj.run(), ft.run()
    assert len(fj.client_history.rows) == len(ft.client_history.rows) > 0
    for rj, rt in zip(fj.client_history.rows, ft.client_history.rows):
        assert (rj["global_round"], rj["epoch"], rj["worker"]) == \
            (rt["global_round"], rt["epoch"], rt["worker"])
        for k in ("train_loss", "train_acc", "val_acc", "val_loss"):
            assert rj[k] == pytest.approx(rt[k], abs=2e-3), (k, rj, rt)


def test_gossip_holdout_client_history_matches_jax(devices):
    tj = build_trainer(_gossip("jax", holdout=0.1, local_ep=2))
    tt = build_trainer(_gossip("torch", holdout=0.1, local_ep=2))
    tj.run(), tt.run()
    assert len(tj.client_history.rows) == len(tt.client_history.rows) > 0
    for rj, rt in zip(tj.client_history.rows, tt.client_history.rows):
        assert (rj["round"], rj["iter"], rj["worker"]) == \
            (rt["round"], rt["iter"], rt["worker"])
        # all four metric keys, pinning the P2 mean-per-batch val flavour
        for k in ("train_loss", "train_acc", "val_acc", "val_loss"):
            assert rj[k] == pytest.approx(rt[k], abs=2e-3), (k, rj, rt)


def test_fedlcon_and_nocons_supported(devices):
    t = build_trainer(_gossip("torch", algorithm="fedlcon", eps=2))
    assert len(t.run(rounds=2)) == 2
    t = build_trainer(_gossip("torch", algorithm="nocons"))
    assert len(t.run(rounds=2)) == 2


def test_torch_backend_validation(devices):
    with pytest.raises(ValueError, match="dsgd|nocons|fedlcon"):
        build_trainer(_gossip("torch", algorithm="choco"))
    with pytest.raises(ValueError, match="dropout"):
        build_trainer(_gossip("torch", dropout=0.5))
    with pytest.raises(ValueError, match="backend"):
        build_trainer(_gossip("tensorflow"))
    cfg = _gossip("torch")
    cfg = cfg.replace(model=dataclasses.replace(cfg.model, model="resnet18"))
    with pytest.raises(ValueError, match="torch reference twin"):
        build_trainer(cfg)
    with pytest.raises(ValueError, match="checkpoint"):
        build_trainer(_gossip("torch")).save("/tmp/nope")
    cfg = dopt.ExperimentConfig(backend="torch",
                                seqlm=dopt.SeqLMConfig())
    with pytest.raises(ValueError, match="seqlm"):
        build_trainer(cfg)


def test_cli_backend_torch(tmp_path, capsys):
    from dopt.run import main

    rc = main(["--preset", "baseline1", "--rounds", "2",
               "--synthetic-scale", "0.02", "--set", "backend=torch",
               "--set", "gossip.local_ep=1",
               "--csv", str(tmp_path / "h.csv")])
    assert rc == 0
    assert (tmp_path / "h.csv").exists()
    assert '"round": 1' in capsys.readouterr().out


def test_flat_feature_models_supported(devices):
    """a9a-style flat features (the review's repro): logistic on a 1-D
    input shape must run on backend='torch' without layout mangling and
    match the jax engine."""
    def cfg(backend):
        return dopt.ExperimentConfig(
            name="tb", seed=5, backend=backend,
            data=dopt.DataConfig(dataset="a9a", num_users=4, iid=True,
                                 synthetic_train_size=256,
                                 synthetic_test_size=64),
            model=dopt.ModelConfig(model="logistic", num_classes=2,
                                   input_shape=(123,), faithful=False),
            optim=dopt.OptimizerConfig(lr=0.05, momentum=0.0,
                                       weight_decay=1e-4),
            federated=dopt.FederatedConfig(algorithm="fedavg", frac=1.0,
                                           rounds=2, local_ep=1, local_bs=32),
        )

    fj = build_trainer(cfg("jax"))
    ft = build_trainer(cfg("torch"))
    hj, ht = fj.run(), ft.run()
    for rj, rt in zip(hj.rows, ht.rows):
        assert rj["test_acc"] == pytest.approx(rt["test_acc"], abs=1e-3)
    assert _max_rel(jax.device_get(fj.theta), ft.theta_as_flax()) < 1e-4


def test_centralized_and_native_plans_and_eps_guard(devices):
    # centralized: same frozen-config rewrite as the jax engine
    t = build_trainer(_gossip("torch", algorithm="centralized"))
    assert t.num_workers == 1
    assert len(t.run(rounds=2)) == 2
    # plan_impl is honored (native stream plans feed the oracle too,
    # keeping cross-backend batches byte-identical for any impl)
    cfgn = _gossip("torch")
    cfgn = cfgn.replace(data=dataclasses.replace(cfgn.data,
                                                 plan_impl="native"))
    cfgj = _gossip("jax")
    cfgj = cfgj.replace(data=dataclasses.replace(cfgj.data,
                                                 plan_impl="native"))
    tn = build_trainer(cfgn)
    tj = build_trainer(cfgj)
    hn, hj = tn.run(rounds=2), tj.run(rounds=2)
    for rn, rj in zip(hn.rows, hj.rows):
        assert rn["avg_test_acc"] == pytest.approx(rj["avg_test_acc"],
                                                   abs=1e-4)
    # explicit eps through run() is rejected like the jax engine
    t = build_trainer(_gossip("torch", algorithm="fedlcon", eps=2))
    with pytest.raises(ValueError, match="GossipConfig"):
        t.run(rounds=1, eps=5)
