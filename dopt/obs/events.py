"""Versioned telemetry event schema (the stream ``dopt serve`` will speak).

Every telemetry record is one JSON object with a ``v`` schema version,
a ``kind``, and a wall-clock ``ts``.  The kinds:

``run``      stream segment header — emitted once per attached run (and
             again on resume, with ``round`` = the resume watermark),
             so one physical JSONL file can carry several logical
             segments (a resumed run, bench's multiple legs) and the
             checker knows where each round sequence restarts.
``round``    one per training round: ``metrics`` carries the engine's
             history row (loss/acc/local_loss/...); optional
             ``consensus_distance`` / ``phase`` / ``collective_bytes``
             fields when the producer has them (bench attaches phase
             fractions; the engines emit consensus distance as an
             end-of-run gauge instead — see dopt.obs docstring).
``gauge``    a named scalar lifted from host-mirror state at the same
             post-fetch boundary the ledger replay uses: quarantine
             streaks, staleness-buffer occupancy, population-registry
             counters, end-of-run consensus distance.
``fault``    one per fault-ledger row, typed: ``fault`` is the ledger
             kind (dopt.faults.KINDS), ``action`` the action string.
``phase``    device-time phase attribution (conv/comm/update/other
             fractions) from a profiler-traced window (bench.py).
``bench``    a benchmark result line (bench.py's JSON dict) re-emitted
             through the same stream.
``warning``  a degraded-but-continuing condition (e.g. the xplane
             profiler reduction failed mid-bench).
``alert``    a health-rule firing (dopt.obs.monitor): ``rule``,
             ``severity`` (warn|critical), ``message``, optional
             numeric ``value``, the triggering ``round``.  Derived
             exclusively from the deterministic kinds, so the alert
             sequence is identical across execution paths — but alerts
             are OUTPUT, not replay data, so they stay outside
             ``DETERMINISTIC_KINDS`` (a stream with a monitor attached
             must stay canonically equal to one without).
``control``  a control-plane command APPLIED at a round boundary
             (``dopt.serve``): ``cmd`` (config|membership|checkpoint|
             drain|pause|resume), the boundary ``round``, and the
             command's payload (``key``/``value`` for config rows,
             ``worker``/``action`` for membership rows, ``id`` — the
             queue id — and ``auto: true`` when the daemon
             self-applied it, e.g. the drop_rate-critical admission
             pause).  DETERMINISTIC: applied commands are ledgered
             with their boundary round, so an interrupted-and-resumed
             served run re-emits exactly the uninterrupted run's
             control sequence — the stream stays a replay script.
``checkpoint`` an auto-checkpoint committed at ``round`` (engines emit
             it after the atomic save lands), optionally carrying a
             ``consensus_distance`` snapshot (params are fetched for
             serialization anyway).  Cadence telemetry for the
             checkpoint-cadence and opt-in consensus-stall rules; NOT
             deterministic — blocked execution checkpoints at block
             boundaries.
``resource`` a device-resource occupancy sample (``diagnostics="on"``):
             ``peak_bytes`` / ``live_bytes`` from the backend allocator
             (``dopt.utils.profiling.device_memory_stats`` — host RSS
             on backends without memory stats, marked by ``source``),
             taken per block at the post-fetch boundary.  NOT
             deterministic — sampling cadence is an execution-path
             property (per-round paths sample every round, blocked
             paths every block), so like ``alert``/``checkpoint`` it
             stays outside ``DETERMINISTIC_KINDS``.
``compile``  a (re)trace of a compiled round function (``fn``,
             ``count`` new cache entries, ``total`` cache size,
             ``seconds`` — the dispatch wall that absorbed the
             compile, an upper bound).  NOT deterministic: the
             per-round and blocked paths trace different programs at
             different times; the retrace-storm rule consumes it.
``latency``  one SLO latency observation (``dopt.obs.latency``):
             ``name`` (boundary_tick | command_apply |
             checkpoint_save | checkpoint_restore | alert_latency),
             ``seconds``, the boundary ``round``.  NOT deterministic —
             wall-clock durations, like ``resource``/``compile`` —
             so a stream carrying them still compares canonically
             equal across execution paths; ``PrometheusSink`` folds
             them into fixed-bucket histograms and the monitor's
             ``HealthReport`` summarizes p50/p95/p99.

The v1 schema evolves additively: new kinds and new optional fields
appear under the same ``v`` (consumers ignore unknown kinds/keys);
``v`` itself bumps only if an existing field changes meaning.

Deterministic kinds (``DETERMINISTIC_KINDS``) are derived exclusively
from post-fetch host-replay data, so per-round, blocked and
killed-and-resumed execution emit bit-identical sequences of them —
``canonical()`` (drop ``ts``, filter kinds) is the comparison form the
chaos soak and tests/test_obs.py pin.

This module is stdlib-only (no jax/numpy) so ``python -m dopt.obs.check``
stays importable anywhere.
"""

from __future__ import annotations

import math
import time
from typing import Any, Iterable

SCHEMA_VERSION = 1

KINDS = ("run", "round", "gauge", "fault", "phase", "bench", "warning",
         "alert", "checkpoint", "resource", "compile", "control",
         "latency")

ALERT_SEVERITIES = ("warn", "critical")

# Kinds whose content is a pure function of the round's host-replay
# data: streams filtered to these (ts dropped) are bit-identical across
# per-round / blocked / resumed execution of the same config.
# ``control`` joins them for served runs: a command is emitted at the
# ledgered round it was applied, so the same command schedule produces
# the same control sequence whether or not the daemon was restarted
# in between (scripted runs simply never carry the kind).
DETERMINISTIC_KINDS = ("round", "fault", "gauge", "control")

# The per-round convergence diagnostics the engines emit as gauges with
# ``diagnostics="on"`` (dopt.config), in packed order.  The sixth gauge
# is the engine's dispersion meter: ``consensus_distance`` (gossip —
# mean_i ||p_i - p_bar||) or ``lane_dispersion`` (federated —
# mean_i ||p_i - theta||).  All six are DETERMINISTIC (computed inside
# the compiled round from the same data on every execution path).
DIAG_GAUGES = ("update_norm", "grad_norm", "param_norm",
               "lane_loss_mean", "lane_loss_spread")


def finite_diag_gauges(keys: Iterable[str], block) -> dict[str, float]:
    """Zip a fetched diagnostics block into a gauge dict, dropping
    non-finite values: a diverged fleet's norms go NaN/Inf, gauge
    values must stay finite (schema) — absent beats unparsable, and
    finiteness is itself deterministic across execution paths."""
    out: dict[str, float] = {}
    for name, value in zip(keys, block):
        v = float(value)
        if math.isfinite(v):
            out[name] = v
    return out


def make_event(kind: str, **fields: Any) -> dict[str, Any]:
    """Build one schema-stamped event; top-level ``None`` fields are
    dropped (absent beats null for optional fields)."""
    ev: dict[str, Any] = {"v": SCHEMA_VERSION, "kind": kind,
                          "ts": round(time.time(), 6)}  # dopt: allow-wallclock -- the schema ts stamp; canonical() drops it before any replay comparison
    ev.update({k: v for k, v in fields.items() if v is not None})
    return ev


def sanitize_metrics(metrics) -> dict[str, Any]:
    """Non-finite floats become null: NaN is not JSON (jq and every
    strict parser reject it), and a divergence under Byzantine stress
    is a legitimate thing for the stream to carry — as an explicit
    absent value, not a parse error."""
    return {k: (None if isinstance(v, float) and not math.isfinite(v)
                else v) for k, v in dict(metrics).items()}


def _fail(msg: str, ev: Any) -> None:
    raise ValueError(f"{msg}: {ev!r}")


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _req_int(ev: dict, key: str, *, lo: int = 0) -> int:
    v = ev.get(key)
    if not isinstance(v, int) or isinstance(v, bool) or v < lo:
        _fail(f"event needs int {key!r} >= {lo}", ev)
    return v


def _req_str(ev: dict, key: str) -> str:
    v = ev.get(key)
    if not isinstance(v, str) or not v:
        _fail(f"event needs non-empty str {key!r}", ev)
    return v


def validate_event(ev: Any) -> dict[str, Any]:
    """Validate one event against the schema; returns it, raises
    ``ValueError`` with the offending object otherwise.  Unknown extra
    keys are allowed (forward compatibility); known keys are typed."""
    if not isinstance(ev, dict):
        _fail("event is not an object", ev)
    if ev.get("v") != SCHEMA_VERSION:
        _fail(f"unknown schema version (want v={SCHEMA_VERSION})", ev)
    kind = ev.get("kind")
    if kind not in KINDS:
        _fail(f"unknown event kind (want one of {KINDS})", ev)
    ts = ev.get("ts")
    if not _is_num(ts) or ts < 0:
        _fail("event needs numeric ts >= 0", ev)
    if kind == "run":
        _req_str(ev, "engine")
        _req_str(ev, "name")
        _req_int(ev, "round")
        if "workers" in ev:
            _req_int(ev, "workers", lo=1)
        if "checkpoint_every" in ev:
            # The run's configured checkpoint cadence in rounds (served
            # runs and --checkpoint-every CLI runs stamp it); the
            # checkpoint_cadence health rule reads it from here instead
            # of guessing a default.
            _req_int(ev, "checkpoint_every")
    elif kind == "round":
        _req_int(ev, "round")
        _req_str(ev, "engine")
        m = ev.get("metrics")
        if not isinstance(m, dict):
            _fail("round event needs a metrics object", ev)
        for k, v in m.items():
            if not isinstance(k, str):
                _fail("round metrics keys must be strings", ev)
            if v is None or isinstance(v, (str, bool)):
                continue
            if not _is_num(v) or not math.isfinite(v):
                _fail(f"round metric {k!r} must be finite", ev)
        if "consensus_distance" in ev and not _is_num(
                ev["consensus_distance"]):
            _fail("consensus_distance must be numeric", ev)
        if "collective_bytes" in ev:
            _req_int(ev, "collective_bytes")
    elif kind == "gauge":
        _req_int(ev, "round")
        _req_str(ev, "name")
        v = ev.get("value")
        if not _is_num(v) or not math.isfinite(v):
            _fail("gauge event needs a finite numeric value", ev)
        if "engine" in ev:
            _req_str(ev, "engine")
    elif kind == "fault":
        _req_int(ev, "round")
        # worker -1 = fleet-level row (the population registry's
        # ``cohort`` audit rows are not about one worker).
        _req_int(ev, "worker", lo=-1)
        _req_str(ev, "fault")
        _req_str(ev, "action")
    elif kind == "phase":
        fr = ev.get("fractions")
        if not isinstance(fr, dict) or not fr:
            _fail("phase event needs a fractions object", ev)
        for k, v in fr.items():
            if not isinstance(k, str) or not _is_num(v) or not (
                    0.0 <= v <= 1.0):
                _fail(f"phase fraction {k!r} must be in [0, 1]", ev)
        if "round" in ev:
            _req_int(ev, "round")
    elif kind == "bench":
        m = ev.get("metrics")
        if not isinstance(m, dict):
            _fail("bench event needs a metrics object", ev)
        for k, v in m.items():
            if not isinstance(k, str):
                _fail("bench metrics keys must be strings", ev)
            if _is_num(v) and not math.isfinite(v):
                _fail(f"bench metric {k!r} must be finite", ev)
    elif kind == "warning":
        _req_str(ev, "message")
    elif kind == "alert":
        _req_int(ev, "round")
        _req_str(ev, "rule")
        _req_str(ev, "message")
        if ev.get("severity") not in ALERT_SEVERITIES:
            _fail(f"alert severity must be one of {ALERT_SEVERITIES}", ev)
        if "value" in ev and not _is_num(ev["value"]):
            _fail("alert value must be numeric", ev)
    elif kind == "checkpoint":
        _req_int(ev, "round")
        if "consensus_distance" in ev:
            v = ev["consensus_distance"]
            if not _is_num(v) or not math.isfinite(v):
                _fail("checkpoint consensus_distance must be finite", ev)
    elif kind == "resource":
        _req_int(ev, "round")
        v = ev.get("peak_bytes")
        if not _is_num(v) or not math.isfinite(v) or v < 0:
            _fail("resource event needs finite peak_bytes >= 0", ev)
        if "live_bytes" in ev:
            v = ev["live_bytes"]
            if not _is_num(v) or not math.isfinite(v) or v < 0:
                _fail("resource live_bytes must be finite >= 0", ev)
        if "source" in ev:
            _req_str(ev, "source")
    elif kind == "control":
        _req_int(ev, "round")
        _req_str(ev, "cmd")
        if "key" in ev:
            _req_str(ev, "key")
        if "action" in ev:
            _req_str(ev, "action")
        if "worker" in ev:
            _req_int(ev, "worker")
        if "id" in ev:
            _req_str(ev, "id")
        if "value" in ev:
            v = ev["value"]
            if isinstance(v, float) and not math.isfinite(v):
                _fail("control value must be finite", ev)
            if not isinstance(v, (int, float, str, bool)):
                _fail("control value must be a scalar", ev)
    elif kind == "compile":
        _req_int(ev, "round")
        _req_str(ev, "fn")
        _req_int(ev, "count", lo=1)
        if "total" in ev:
            _req_int(ev, "total", lo=1)
        v = ev.get("seconds")
        if not _is_num(v) or not math.isfinite(v) or v < 0:
            _fail("compile event needs finite seconds >= 0", ev)
    elif kind == "latency":
        _req_int(ev, "round")
        _req_str(ev, "name")
        v = ev.get("seconds")
        if not _is_num(v) or not math.isfinite(v) or v < 0:
            _fail("latency event needs finite seconds >= 0", ev)
    return ev


def check_stream(events: Iterable[Any]) -> dict[str, Any]:
    """Validate a whole stream and its continuity invariant: within
    each segment (opened by a ``run`` event, whose ``round`` declares
    the segment's watermark start), the ``round``-event sequence must
    be gapless and duplicate-free.  Returns a summary dict; raises
    ``ValueError`` on the first violation."""
    kinds: dict[str, int] = {}
    expected: int | None = None
    rounds = segments = total = 0
    for ev in events:
        validate_event(ev)
        total += 1
        kinds[ev["kind"]] = kinds.get(ev["kind"], 0) + 1
        if ev["kind"] == "run":
            expected = int(ev["round"])
            segments += 1
        elif ev["kind"] == "round":
            t = int(ev["round"])
            if expected is None:
                # Headerless stream: the first round event anchors it.
                expected = t
                segments += 1
            if t != expected:
                _fail(f"round sequence broken: expected round {expected}",
                      ev)
            expected = t + 1
            rounds += 1
    return {"events": total, "rounds": rounds, "segments": segments,
            "kinds": kinds}


def canonical(events: Iterable[dict],
              kinds: tuple[str, ...] = DETERMINISTIC_KINDS,
              drop: tuple[str, ...] = ("ts",)) -> list[dict[str, Any]]:
    """The comparison form for stream-equality invariants: events
    filtered to the deterministic kinds with wall-clock fields
    dropped.  ``canonical(a) == canonical(b)`` is the blocked-vs-
    per-round (and resume) contract."""
    return [{k: v for k, v in ev.items() if k not in drop}
            for ev in events if ev.get("kind") in kinds]
