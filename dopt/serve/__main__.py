"""CLI entry for the resident trainer: ``python -m dopt.serve``.

Single process (the default)::

    python -m dopt.serve --preset baseline1 --state-dir run/ \\
        --checkpoint-every 8

runs forever (or to ``--max-rounds``), serving the admin endpoint on
an ephemeral port (read it from ``run/serve.json``).  SIGTERM drains
to the next round boundary, checkpoints, and — with the default
``--on-term restart`` — re-execs in place and resumes bit-exactly;
``--on-term drain`` exits 0 instead.  Re-running the same command
against the same ``--state-dir`` always resumes.

Multi-process fleet (real ``jax.distributed`` process groups, gloo CPU
collectives — the supported successor of
``scripts/multiprocess_demo.py``)::

    python -m dopt.serve --preset baseline1 --state-dir run/ \\
        --num-processes 2 --devices-per-proc 4

spawns one daemon per process under a supervisor: process 0 leads
(queue, telemetry, admin, checkpoint writes), followers replay its
per-boundary directives.  SIGTERM any CHILD for a rolling restart (the
fleet quiesces at the boundary, checkpoints once, every process
re-execs on a fresh port-0 coordinator, training resumes bit-exactly);
SIGTERM the SUPERVISOR to drain the whole run gracefully (it files a
``drain`` command and waits).

Decoupled fleet (no cross-process collectives — the zero-paused-rounds
rolling restart, built for ``gossip.topology=one_peer_exp`` +
``gossip.mixing=async``)::

    python -m dopt.serve --preset baseline1 --state-dir run/ \\
        --num-processes 2 --decoupled \\
        --set gossip.topology=one_peer_exp --set gossip.mixing=async

spawns N INDEPENDENT single-process daemons (child i leads its own
``run/p<i>/`` state subdir), linked only by per-process liveness
heartbeat files in ``run/``: a peer that drains or goes stale is
auto-``leave``d from each survivor's membership (identity mixing rows
— the round proceeds without it) and auto-``join``ed back when its
heartbeat returns.  SIGTERM a CHILD and only THAT child drains,
checkpoints and is respawned — the survivors' round watermark never
pauses; SIGTERM the SUPERVISOR to drain every child gracefully.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from pathlib import Path

from dopt.serve.daemon import EX_RESTART, ServeDaemon


def build_cfg(args):
    from dopt.presets import get_preset
    from dopt.run import apply_override

    cfg = get_preset(args.preset)
    for spec in args.overrides:
        cfg = apply_override(cfg, spec)
    import dataclasses

    if args.num_users is not None:
        cfg = cfg.replace(data=dataclasses.replace(
            cfg.data, num_users=args.num_users))
    if args.synthetic_scale is not None:
        cfg = cfg.replace(data=dataclasses.replace(
            cfg.data,
            synthetic_train_size=max(int(cfg.data.synthetic_train_size
                                         * args.synthetic_scale),
                                     cfg.data.num_users * 8),
            synthetic_test_size=max(int(cfg.data.synthetic_test_size
                                        * args.synthetic_scale), 64),
        ))
    if args.num_processes > 1:
        cfg = cfg.replace(mesh_hosts=args.num_processes)
    return cfg


def run_daemon(args, argv: list[str]) -> int:
    if args.process_id is not None:
        # Fleet child: the shared bootstrap (dopt.parallel.multihost)
        # pins device flags + gloo before backend init and rendezvous
        # on the port-0 handoff coordinator — no fixed ports, no
        # parent-probed TOCTOU window.
        from dopt.parallel.multihost import bootstrap_child_backend

        bootstrap_child_backend(args.handoff, args.process_id,
                                args.num_processes,
                                args.devices_per_proc)
    cfg = build_cfg(args)
    rules = None
    if args.rules_file:
        from dopt.serve.daemon import serve_rules

        specs = json.loads(Path(args.rules_file).read_text())
        if not isinstance(specs, list):
            raise SystemExit(f"--rules-file {args.rules_file}: expected "
                             "a JSON list of rule specs "
                             '([{"rule": <name>, ...}, ...])')
        rules = serve_rules(specs=specs)
    daemon = ServeDaemon(
        cfg, args.state_dir,
        checkpoint_every=args.checkpoint_every,
        max_rounds=args.max_rounds,
        on_term=args.on_term,
        admin_host=args.admin_host,
        admin_port=None if args.no_admin else args.admin_port,
        process_id=args.process_id or 0,
        num_processes=args.num_processes,
        rules=rules,
        fleet_rank=args.fleet_rank or 0,
        fleet_size=args.fleet_size or 1,
        fleet_dir=args.fleet_dir,
        peer_timeout_s=args.peer_timeout,
    ).start()
    if daemon.is_leader and daemon.admin is not None:
        print(f"dopt serve: admin on http://{args.admin_host}:"
              f"{daemon.admin.port} (state {args.state_dir})",
              file=sys.stderr, flush=True)
    rc = daemon.serve()
    if rc == EX_RESTART and args.process_id is None \
            and args.fleet_rank is None:
        # Self-managed single process: the drain checkpointed, now
        # become a fresh process image and resume — the rolling
        # restart with a fleet of one.  Supervised children return the
        # code instead and the parent respawns the generation.
        print("dopt serve: re-exec for rolling restart", file=sys.stderr,
              flush=True)
        sys.stderr.flush()
        sys.stdout.flush()
        os.execv(sys.executable,
                 [sys.executable, "-m", "dopt.serve", *argv])
    return rc


def run_supervisor(args, argv: list[str]) -> int:
    """Parent of a multi-process fleet: spawn one child per process,
    respawn the whole generation when any child asks for a restart
    (exit ``EX_RESTART``), stop when the fleet drains."""
    state = Path(args.state_dir)
    state.mkdir(parents=True, exist_ok=True)
    term = {"fired": False}

    def _term(signum, frame):
        # Graceful whole-run drain: file a drain command; the leader
        # applies it at the next boundary and the fleet exits 0.  The
        # id is unique per invocation — a reused fixed id would sit in
        # the resumed daemon's processed set (prior run's applied
        # ledger) and a SECOND drain of the same state dir would be
        # silently ignored.
        if not term["fired"]:
            term["fired"] = True
            import uuid

            from dopt.serve.control import CommandQueue, make_command

            CommandQueue(state / "commands.jsonl").submit(
                make_command("drain",
                             id=f"supervisor-term-{uuid.uuid4().hex[:8]}"))

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)

    # The ONE fleet observability surface: every process streams its
    # own metrics file; the supervisor mounts the merged + verified
    # view (dopt.obs.aggregate) as /metrics + /healthz, port announced
    # in <state>/fleet.json.  Stdlib-only — the supervisor never
    # imports jax.
    fleet_server = None
    if not args.no_admin:
        from dopt.obs.aggregate import FleetMetricsServer
        from dopt.utils.metrics import atomic_write_text

        fleet_server = FleetMetricsServer(
            state, num_processes=args.num_processes,
            host=args.admin_host, port=args.fleet_port).start()
        atomic_write_text(state / "fleet.json", json.dumps(
            {"host": args.admin_host, "port": fleet_server.port,
             "pid": os.getpid(),
             "num_processes": args.num_processes}, indent=2))
        print(f"dopt serve: fleet metrics on http://{args.admin_host}:"
              f"{fleet_server.port} (/metrics, /healthz)",
              file=sys.stderr, flush=True)

    try:
        return _supervise(args, argv, state)
    finally:
        if fleet_server is not None:
            fleet_server.shutdown()
            (state / "fleet.json").unlink(missing_ok=True)


def _supervise(args, argv: list[str], state: Path) -> int:
    log_dir = state / "logs"
    log_dir.mkdir(parents=True, exist_ok=True)
    generation = 0
    transport_retries = 0
    while True:
        # Directives are per-generation: a resumed fleet revisits the
        # same round indices, and a follower must never replay the
        # PREVIOUS generation's boundary decisions (the stale restart
        # directive would make it exit while the new leader waits in a
        # collective).  Children only spawn after the sweep, so there
        # is no reader to race.
        import shutil

        shutil.rmtree(state / "epoch", ignore_errors=True)
        (state / "restart-requested").unlink(missing_ok=True)
        handoff = Path(tempfile.mkdtemp(prefix="dopt-serve-")) / \
            f"coordinator-{generation}.json"
        procs, logs = [], []
        for i in range(args.num_processes):
            child_argv = [a for a in argv]
            child_argv += ["--process-id", str(i),
                           "--handoff", str(handoff)]
            log = open(log_dir / f"gen{generation}-p{i}.log", "w")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "dopt.serve", *child_argv],
                stdout=log, stderr=subprocess.STDOUT))
        rcs = [p.wait() for p in procs]
        for log in logs:
            log.close()
        if all(rc == 0 for rc in rcs):
            print(f"dopt serve: fleet drained (generation {generation})",
                  file=sys.stderr)
            return 0
        if all(rc in (0, EX_RESTART) for rc in rcs):
            generation += 1
            transport_retries = 0
            print(f"dopt serve: rolling restart -> generation "
                  f"{generation}", file=sys.stderr)
            continue
        if _gloo_transport_flake(log_dir, generation) \
                and transport_retries < 3:
            # gloo's tcp transport occasionally interleaves two
            # collectives' messages on one pair under host load
            # (preamble/buffer length mismatch -> SIGABRT) — the same
            # narrowly-matched race multiprocess_demo retries.  State
            # is durable (checkpoint + applied ledger + stream
            # watermark), so respawning the generation resumes
            # bit-exactly; matched on the specific signature only, so
            # deterministic failures still fail.
            transport_retries += 1
            generation += 1
            print(f"dopt serve: gloo transport race, retry "
                  f"{transport_retries}/3 -> generation {generation}",
                  file=sys.stderr)
            continue
        print(f"dopt serve: fleet failed, child exit codes {rcs} "
              f"(logs in {log_dir})", file=sys.stderr)
        return 1


def run_decoupled_supervisor(args, argv: list[str]) -> int:
    """Parent of a DECOUPLED fleet: N independent single-process
    daemons, each leading its own ``<state>/p<i>/`` subdir, linked only
    by liveness heartbeats in ``<state>/``.  Respawn ONLY the child
    that asked (exit ``EX_RESTART``) — the survivors keep ticking
    through it: the zero-paused-rounds rolling restart."""
    state = Path(args.state_dir)
    state.mkdir(parents=True, exist_ok=True)
    term = {"fired": False}

    def _term(signum, frame):
        # Whole-run drain: one drain command PER child queue (each
        # daemon is its own leader — there is no fleet queue).  Unique
        # ids for the same reason run_supervisor's handler uses them.
        if not term["fired"]:
            term["fired"] = True
            import uuid

            from dopt.serve.control import CommandQueue, make_command

            for i in range(args.num_processes):
                sub = state / f"p{i}"
                sub.mkdir(parents=True, exist_ok=True)
                CommandQueue(sub / "commands.jsonl").submit(
                    make_command(
                        "drain",
                        id=f"supervisor-term-{uuid.uuid4().hex[:8]}"))

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    return _supervise_decoupled(args, argv, state, term)


def _supervise_decoupled(args, argv: list[str], state: Path,
                         term: dict) -> int:
    log_dir = state / "logs"
    log_dir.mkdir(parents=True, exist_ok=True)
    base = _strip_decoupled_flags(argv)
    gens = [0] * args.num_processes

    def spawn(i: int):
        child_argv = base + [
            "--state-dir", str(state / f"p{i}"),
            "--fleet-rank", str(i),
            "--fleet-size", str(args.num_processes),
            "--fleet-dir", str(state)]
        log = open(log_dir / f"p{i}-gen{gens[i]}.log", "w")
        proc = subprocess.Popen(
            [sys.executable, "-m", "dopt.serve", *child_argv],
            stdout=log, stderr=subprocess.STDOUT)
        return [proc, log]

    procs = {i: spawn(i) for i in range(args.num_processes)}
    failed = False
    while procs:
        time.sleep(0.2)
        for i in list(procs):
            proc, log = procs[i]
            rc = proc.poll()
            if rc is None:
                continue
            log.close()
            del procs[i]
            if rc == EX_RESTART and not failed:
                gens[i] += 1
                print(f"dopt serve: process {i} rolling restart -> "
                      f"gen {gens[i]} (peers keep ticking)",
                      file=sys.stderr, flush=True)
                procs[i] = spawn(i)
            elif rc not in (0, EX_RESTART):
                # One child failed hard: drain the survivors (SIGINT
                # always drains) rather than training a degraded fleet
                # forever under an absent supervisor verdict.
                failed = True
                print(f"dopt serve: process {i} failed (exit {rc}, "
                      f"log {log_dir / f'p{i}-gen{gens[i]}.log'}); "
                      "draining survivors", file=sys.stderr, flush=True)
                for other, _ in procs.values():
                    try:
                        other.send_signal(signal.SIGINT)
                    except OSError:
                        pass
    if failed:
        return 1
    print("dopt serve: decoupled fleet drained", file=sys.stderr)
    return 0


def _strip_decoupled_flags(argv: list[str]) -> list[str]:
    """Child argv for a decoupled spawn: drop the supervisor-level
    flags (the spawn appends the per-child ones)."""
    out, skip = [], False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in ("--state-dir", "--num-processes", "--fleet-port",
                 "--fleet-rank", "--fleet-size", "--fleet-dir"):
            skip = True
            continue
        if a == "--decoupled":
            continue
        out.append(a)
    return out


def _gloo_transport_flake(log_dir: Path, generation: int) -> bool:
    for log in log_dir.glob(f"gen{generation}-p*.log"):
        try:
            if "op.preamble.length" in log.read_text(errors="replace"):
                return True
        except OSError:
            continue
    return False


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    ap = argparse.ArgumentParser(prog="python -m dopt.serve",
                                 description=__doc__)
    ap.add_argument("--preset", required=True,
                    help="preset name (dopt.presets); federated/gossip "
                         "jax engines only")
    ap.add_argument("--state-dir", required=True,
                    help="the daemon's durable state: command queue, "
                         "applied ledger, metrics stream, checkpoints, "
                         "status file — re-running with the same dir "
                         "RESUMES")
    ap.add_argument("--set", action="append", default=[],
                    metavar="PATH=VAL", dest="overrides",
                    help="config override by dotted path (same semantics "
                         "as dopt.run --set)")
    ap.add_argument("--num-users", type=int, default=None)
    ap.add_argument("--synthetic-scale", type=float, default=None)
    ap.add_argument("--checkpoint-every", type=int, default=8, metavar="K",
                    help="streaming atomic checkpoint cadence in rounds "
                         "(0 disables; boundaries that apply commands "
                         "checkpoint regardless); changeable live via "
                         "the control plane")
    ap.add_argument("--max-rounds", type=int, default=None,
                    help="drain after this many rounds (default: run "
                         "until a drain command or signal)")
    ap.add_argument("--on-term", choices=("restart", "drain"),
                    default="restart",
                    help="SIGTERM behavior: drain-checkpoint then "
                         "re-exec and resume (restart, default) or exit "
                         "0 (drain); SIGINT always drains")
    ap.add_argument("--admin-host", default="127.0.0.1")
    ap.add_argument("--admin-port", type=int, default=0,
                    help="admin/metrics endpoint port (default 0 = "
                         "ephemeral; the bound port lands in "
                         "<state>/serve.json)")
    ap.add_argument("--no-admin", action="store_true",
                    help="run without the HTTP endpoints (file-queue "
                         "control only; also disables the supervisor's "
                         "fleet metrics endpoint)")
    ap.add_argument("--fleet-port", type=int, default=0,
                    help="multi-process supervisor's fleet /metrics + "
                         "/healthz port (default 0 = ephemeral; the "
                         "bound port lands in <state>/fleet.json)")
    ap.add_argument("--rules-file", default=None, metavar="PATH",
                    help="JSON list of monitor rule specs "
                         '([{"rule": <name>, ...}]; dopt.obs.rules.'
                         "build_rules shape) REPLACING the stock rule "
                         "set — the escalated drop_rate_critical "
                         "auto-pause rule is always appended")
    ap.add_argument("--num-processes", type=int, default=1,
                    help="multi-process fleet size (real "
                         "jax.distributed + gloo CPU collectives)")
    ap.add_argument("--decoupled", action="store_true",
                    help="with --num-processes N: run N INDEPENDENT "
                         "single-process daemons (child i leads "
                         "<state>/p<i>/) linked only by liveness "
                         "heartbeats — no cross-process collectives, so "
                         "a peer's restart never pauses the survivors; "
                         "built for gossip.topology=one_peer_exp + "
                         "gossip.mixing=async")
    ap.add_argument("--peer-timeout", type=float, default=10.0,
                    metavar="SECONDS",
                    help="decoupled fleets: a peer whose liveness "
                         "heartbeat is older than this is auto-left "
                         "from the membership until it returns")
    ap.add_argument("--devices-per-proc", type=int, default=4,
                    help="virtual CPU devices per fleet process")
    ap.add_argument("--process-id", type=int, default=None,
                    help="(internal) run as fleet child with this id")
    ap.add_argument("--handoff", default=None,
                    help="(internal) coordinator handoff file path")
    ap.add_argument("--fleet-rank", type=int, default=None,
                    help="(internal) run as decoupled-fleet child with "
                         "this rank")
    ap.add_argument("--fleet-size", type=int, default=None,
                    help="(internal) decoupled-fleet size")
    ap.add_argument("--fleet-dir", default=None,
                    help="(internal) shared liveness-heartbeat dir")
    args = ap.parse_args(argv)

    if args.decoupled and args.process_id is not None:
        ap.error("--decoupled and --process-id are mutually exclusive")
    if args.decoupled and args.fleet_rank is None:
        if args.num_processes < 2:
            ap.error("--decoupled requires --num-processes >= 2")
        return run_decoupled_supervisor(args, argv)
    if args.num_processes > 1 and args.process_id is None:
        return run_supervisor(args, argv)
    if args.process_id is not None and args.handoff is None:
        ap.error("--process-id requires --handoff")
    # Strip the internal child flags from the re-exec argv: a restarted
    # child gets fresh ones from the next generation's supervisor.
    return run_daemon(args, _strip_child_flags(argv))


def _strip_child_flags(argv: list[str]) -> list[str]:
    out, skip = [], False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in ("--process-id", "--handoff"):
            skip = True
            continue
        out.append(a)
    return out


def status_of(state_dir) -> dict:
    """Read the daemon's status file (operator convenience)."""
    return json.loads((Path(state_dir) / "serve.json").read_text())


if __name__ == "__main__":
    raise SystemExit(main())
