"""Real-data accuracy parity vs the reference's published numbers.

The replay grid (``scripts/replay_reference.py``) proves the machinery
end-to-end but runs on synthetic data in this egress-free environment,
so its absolute accuracies are not comparable to the reference's
committed results.  THIS script is the quantitative parity harness: if
raw MNIST is available (IDX files under ``$DOPT_DATA_DIR`` — see
``dopt/data/datasets.py`` for the accepted layouts), it replays the
reference's experiments on the real data and asserts the headline
numbers from BASELINE.md within tolerance:

* P1 federated trio (100 users, frac 0.1, 20 rounds, IID, seed 2022 —
  ``Primal and Dual Decomposition.ipynb`` cells 8-25):
  FedAvg 97.82%, FedProx 97.68%, FedADMM 97.47% (abs tol 1.5pt —
  run-to-run seed/order effects; the reference's own reruns vary ~1pt).
* P2 gossip grid (6 users, 10 rounds, non-IID shards 2, seed 2028 —
  ``Weighted Average.ipynb`` cells 14-36): the qualitative ordering
  star < circle < complete for stochastic mixing, complete-stochastic
  >= 0.70 (reference 0.82), no-consensus-non-IID <= 0.35 (reference
  0.23), centralized >= 0.95 (reference 0.97).  Gossip runs are
  chaotic under the faithful double-softmax objective, so the grid is
  asserted on ordering + bands, not point values.

Without raw data it exits 0 with ``skipped: no real data`` so CI can
always invoke it — a skip is visible, not a silent pass.

Usage: python scripts/parity_real.py [--fed-only|--gossip-only]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def have_real_mnist() -> bool:
    from dopt.data import load_dataset

    try:
        ds = load_dataset("mnist", synthetic_fallback=False)
    except (FileNotFoundError, ValueError):
        return False
    return ds.train_x.shape[0] >= 60_000


def run_preset(name: str):
    from dopt.presets import get_preset
    from dopt.run import build_trainer

    trainer = build_trainer(get_preset(name))
    trainer.run()
    return trainer.history.last()


def check(rows: list[dict], name: str, ok: bool, detail: str) -> None:
    rows.append({"check": name, "ok": bool(ok), "detail": detail})
    print(f"{'PASS' if ok else 'FAIL'}  {name:40s} {detail}", flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fed-only", action="store_true")
    ap.add_argument("--gossip-only", action="store_true")
    ap.add_argument("--out", default="results/parity_real.json")
    args = ap.parse_args()

    if not have_real_mnist():
        print("skipped: no real data (set DOPT_DATA_DIR to raw MNIST IDX "
              "files to run the quantitative parity harness)")
        return 0

    rows: list[dict] = []

    if not args.gossip_only:
        # P1 trio — point values from the notebook cell outputs
        # (BASELINE.md rows 1-3).
        for preset, ref in (("reference-fedavg", 0.9782),
                            ("reference-fedprox", 0.9768),
                            ("reference-fedadmm", 0.9747)):
            last = run_preset(preset)
            acc = float(last["test_acc"])
            check(rows, f"{preset} final acc", abs(acc - ref) <= 0.015,
                  f"got {acc:.4f}, reference {ref:.4f} (tol 1.5pt)")

    if not args.fed_only:
        accs = {}
        for preset in ("reference-centralized", "reference-nocons-noniid",
                       "reference-dsgd-star", "reference-dsgd-circle",
                       "reference-dsgd-complete"):
            last = run_preset(preset)
            accs[preset] = float(last["avg_test_acc"])
        check(rows, "centralized band", accs["reference-centralized"] >= 0.95,
              f"got {accs['reference-centralized']:.4f}, reference 0.97")
        check(rows, "nocons non-IID collapses",
              accs["reference-nocons-noniid"] <= 0.35,
              f"got {accs['reference-nocons-noniid']:.4f}, reference 0.23")
        check(rows, "ordering star < circle < complete",
              accs["reference-dsgd-star"] < accs["reference-dsgd-circle"]
              < accs["reference-dsgd-complete"],
              f"star {accs['reference-dsgd-star']:.3f} / circle "
              f"{accs['reference-dsgd-circle']:.3f} / complete "
              f"{accs['reference-dsgd-complete']:.3f}")
        check(rows, "complete-stochastic band",
              accs["reference-dsgd-complete"] >= 0.70,
              f"got {accs['reference-dsgd-complete']:.4f}, reference 0.82")

    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(rows, indent=2) + "\n")
    failed = [r for r in rows if not r["ok"]]
    print(f"{len(rows) - len(failed)}/{len(rows)} checks passed; wrote {out}")
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
