"""Compression operators for communication-efficient gossip (CHOCO-SGD).

The reference has no notion of communication cost at all (its "network"
is Python object passing — SURVEY §2.4); these operators exist for the
framework's own communication-efficient algorithms
(``GossipConfig.algorithm='choco'``): each worker communicates a
compressed *difference* ``Q(x_i − x̂_i)`` instead of full parameters,
with the error kept in ``x_i − x̂_i`` and fed back next round (error
feedback is what makes aggressive compression convergent).

All operators are pure, shape-static (XLA-friendly: ``top_k`` with a
compile-time k, seeded masks instead of data-dependent sparsity), and
act per worker on stacked [W, ...] pytrees.

Contract: an operator maps (tree, key) → tree of the same structure.
For the SPARSIFIERS (``topk``, ``randk``) ``ratio`` is the fraction of
entries communicated and ``ratio=1.0`` is the exact identity — that
invariant is what the choco≡dsgd reduction test pins.  ``qsgd`` is a
QUANTIZER with different ratio semantics: ratio sets the level count
(ratio=1 → 256-level stochastic quantization, NOT the identity); use
``compression='none'`` for the exact D-SGD reduction.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def _per_worker_topk(flat: jnp.ndarray, k: int) -> jnp.ndarray:
    """flat: [W, N] — keep the k largest-|·| entries per row."""
    n = flat.shape[1]
    if k >= n:
        return flat
    _, idx = jax.lax.top_k(jnp.abs(flat), k)          # [W, k]
    mask = jnp.zeros_like(flat).at[
        jnp.arange(flat.shape[0])[:, None], idx].set(1.0)
    return flat * mask


def top_k_compress(tree, ratio: float):
    """Magnitude top-k sparsification, per worker per leaf.  k is
    static: ceil(ratio · leaf_size) — jit-stable shapes."""
    if ratio >= 1.0:
        return tree

    def comp(x):
        w = x.shape[0]
        n = math.prod(x.shape[1:]) or 1
        k = max(int(math.ceil(ratio * n)), 1)
        flat = x.reshape(w, n).astype(jnp.float32)
        return _per_worker_topk(flat, k).reshape(x.shape).astype(x.dtype)

    return jax.tree.map(comp, tree)


def rand_k_compress(tree, ratio: float, key):
    """Fixed-cardinality random-k sparsification with n/k rescaling
    (unbiased): EXACTLY k = ceil(ratio · leaf_size) entries per worker
    per leaf survive, drawn uniformly without replacement (top-k over a
    random-score tensor — a static-shape permutation draw), matching
    the rand-k operator of the compression literature so a packed
    transport has a FIXED wire size per round.  The index set is drawn
    from ``key`` per leaf — pass a per-round key so workers/rounds
    decorrelate."""
    if ratio >= 1.0:
        return tree
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(key, len(leaves))

    def comp(x, k_):
        w = x.shape[0]
        n = math.prod(x.shape[1:]) or 1
        k = max(int(math.ceil(ratio * n)), 1)
        flat = x.reshape(w, n)
        scores = jax.random.uniform(k_, (w, n))
        _, idx = jax.lax.top_k(scores, k)                 # k uniform w/o repl.
        mask = jnp.zeros((w, n), x.dtype).at[
            jnp.arange(w)[:, None], idx].set(1)
        scale = jnp.asarray(n / k, x.dtype)               # E[x̂] = x
        return (flat * mask * scale).reshape(x.shape)

    return jax.tree_util.tree_unflatten(
        treedef, [comp(x, k) for x, k in zip(leaves, keys)])


def qsgd_compress(tree, ratio: float, key, *, bucket_size: int = 2048,
                  levels: int | None = None):
    """QSGD stochastic quantization (Alistarh et al. 2017), per worker
    per leaf: x → ‖x‖₂ · sign(x) · ξ(x)/s with ξ an unbiased stochastic
    rounding of s·|x|/‖x‖₂ to integer levels.  The level count s comes
    from ``levels`` directly when given (``GossipConfig.qsgd_levels``),
    else from ``ratio`` as s = max(round(ratio · 256), 1) — the fraction
    of an 8-bit range used; smaller s = coarser quantization = fewer
    wire bits in a real packed transport.

    Norms are per ``bucket_size`` chunk (standard QSGD bucketing):
    without it the quantization step scales with the WHOLE leaf's norm
    (~√N · rms) and the noise swamps million-parameter models."""
    s = levels if levels else max(int(round(ratio * 256)), 1)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(key, len(leaves))

    def comp(x, k):
        w = x.shape[0]
        n = math.prod(x.shape[1:]) or 1
        b = min(bucket_size, n)
        nb = -(-n // b)
        pad = nb * b - n
        flat = x.reshape(w, n).astype(jnp.float32)
        if pad:
            flat = jnp.pad(flat, ((0, 0), (0, pad)))
        bk = flat.reshape(w, nb, b)
        norm = jnp.linalg.norm(bk, axis=2, keepdims=True)
        safe = jnp.maximum(norm, 1e-12)
        level = s * jnp.abs(bk) / safe                     # in [0, s]
        floor = jnp.floor(level)
        frac = level - floor
        up = (jax.random.uniform(k, bk.shape) < frac).astype(jnp.float32)
        q = jnp.sign(bk) * (floor + up) * safe / s
        q = jnp.where(norm > 0, q, 0.0)
        q = q.reshape(w, nb * b)[:, :n]
        return q.reshape(x.shape).astype(x.dtype)

    return jax.tree_util.tree_unflatten(
        treedef, [comp(x, k) for x, k in zip(leaves, keys)])


def make_compressor(name: str, ratio: float, *, qsgd_levels: int = 0):
    """Operator factory: (tree, key) → compressed tree.

    'topk'  — deterministic magnitude top-k (ignores the key)
    'randk' — unbiased fixed-cardinality random-k with rescaling
    'qsgd'  — unbiased stochastic quantization; level count from
              ``qsgd_levels`` when > 0, else from ratio (ratio·256)
    'none'  — identity (ratio ignored)
    """
    if name not in ("none", "topk", "randk", "qsgd"):
        raise ValueError(
            f"unknown compressor {name!r}; one of none|topk|randk|qsgd")
    if name != "none" and not 0.0 < ratio <= 1.0:
        # ratio=0 would divide by zero in randk (NaN params on round 0)
        # and negative ratios would silently zero all communication.
        raise ValueError(f"compression_ratio must be in (0, 1], got {ratio}")
    if qsgd_levels and name != "qsgd":
        raise ValueError(
            f"qsgd_levels only applies to compression='qsgd' (got {name!r})")
    if qsgd_levels < 0:
        raise ValueError(f"qsgd_levels must be >= 0, got {qsgd_levels}")
    if name == "none" or (name != "qsgd" and ratio >= 1.0):
        return lambda tree, key: tree
    if name == "topk":
        return lambda tree, key: top_k_compress(tree, ratio)
    if name == "qsgd":
        return lambda tree, key: qsgd_compress(tree, ratio, key,
                                               levels=qsgd_levels or None)
    return lambda tree, key: rand_k_compress(tree, ratio, key)
