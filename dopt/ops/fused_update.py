"""Pallas TPU kernels for the bandwidth-bound hot op: the SGD update.

The per-step parameter update (torch semantics, ``dopt.optim.sgd_step``)

    buf ← μ·buf + g ;  p ← p − lr·buf

reads three arrays and writes two with zero FLOP reuse — pure HBM
bandwidth.  This kernel pins the fusion into ONE pass over memory
(in-place via ``input_output_aliases``) instead of trusting XLA's fusion
heuristics, and is the template for further pallas work (quantised
gossip payloads, ring-reduce mixing).

Numerics match the jnp path to fused-multiply-add association (the same
fp32 ops in the same order; only FMA contraction may differ between the
two compiled programs — ``tests/test_ops.py`` asserts 1e-6 agreement),
so the fast path stays oracle-comparable.

Layout: each leaf is viewed as a padded [rows, 128] fp32 tile grid
(lane = 128, sublane multiple of 8 — the fp32 VMEM tile), gridded over
row blocks.  On non-TPU backends the kernel runs in interpret mode, so
CPU tests exercise the identical code path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANE = 128
_SUBLANE = 8
_BLOCK_ROWS = 512  # 512×128 fp32 = 256 KiB per operand block in VMEM


def pallas_available() -> bool:
    """True when a real TPU backend is present (compiled kernels);
    otherwise callers fall back to interpret mode or pure jnp."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend probing
        return False


def _make_kernel(lr: float, mu: float):
    def kernel(p_ref, m_ref, g_ref, p_out, m_out):
        buf = mu * m_ref[:] + g_ref[:]
        m_out[:] = buf
        p_out[:] = p_ref[:] - lr * buf

    return kernel


@partial(jax.jit, static_argnames=("lr", "mu", "interpret"))
def fused_sgd_momentum(p, m, g, *, lr: float, mu: float,
                       interpret: bool = False):
    """Fused momentum-SGD update of ONE array (any shape/dtype).

    Returns (new_p, new_buf) with p's shape/dtype, computed in fp32
    exactly like ``sgd_step``'s two tree.maps but in a single memory
    pass.
    """
    shape, dtype = p.shape, p.dtype
    n = p.size
    rows = -(-n // _LANE)
    if rows <= _BLOCK_ROWS:
        rows_pad = -(-rows // _SUBLANE) * _SUBLANE
        grid = 1
        block_rows = rows_pad
    else:
        rows_pad = -(-rows // _BLOCK_ROWS) * _BLOCK_ROWS
        grid = rows_pad // _BLOCK_ROWS
        block_rows = _BLOCK_ROWS

    def tile(x):
        x = x.astype(jnp.float32).reshape(-1)
        return jnp.pad(x, (0, rows_pad * _LANE - n)).reshape(rows_pad, _LANE)

    pt, mt, gt = tile(p), tile(m), tile(g)
    spec = pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    new_p, new_m = pl.pallas_call(
        _make_kernel(float(lr), float(mu)),
        out_shape=(jax.ShapeDtypeStruct(pt.shape, jnp.float32),
                   jax.ShapeDtypeStruct(mt.shape, jnp.float32)),
        grid=(grid,),
        in_specs=[spec, spec, spec],
        out_specs=(spec, spec),
        input_output_aliases={0: 0, 1: 1},
        interpret=interpret,
    )(pt, mt, gt)

    def untile(x):
        return x.reshape(-1)[:n].reshape(shape).astype(dtype)

    return untile(new_p), untile(new_m)


# ---------------------------------------------------------------------
# Fused mix + update: the gossip epilogue in one HBM pass
# ---------------------------------------------------------------------
# The D-PSGD-style gossip epilogue
#
#     p ← mix(p) − lr·buf      (mix = the [n, n] consensus contraction)
#
# reads two model-sized arrays and writes one with the only FLOPs being
# the tiny [n, n] contraction over the worker axis — like the SGD
# update above, it is pure HBM bandwidth, but XLA materialises the
# mixed intermediate between the two ops (one extra full write + read
# of |θ|).  This kernel fuses both into ONE pass over memory on the
# flat-bucket layout of ``dopt.parallel.collectives.UpdateShardSpec``
# (ROADMAP "raw speed" lever 3, the follow-on this file's header
# names): each [n, Fb] bucket slab is gridded over its flat axis, the
# f32 mixing matrix rides VMEM-resident across grid steps, and the MXU
# contraction + VPU subtract write the updated slab in place
# (``input_output_aliases``).  Numerics: matrix and accumulation in
# f32 regardless of leaf dtype — the same contract as the scatter mix
# path (tests/test_ops.py pins 1e-6 agreement with the jnp
# composition).


def _make_mix_kernel(lr: float):
    def kernel(w_ref, p_ref, m_ref, p_out):
        mixed = jnp.dot(w_ref[:], p_ref[:],
                        preferred_element_type=jnp.float32)
        p_out[:] = mixed - lr * m_ref[:]

    return kernel


@partial(jax.jit, static_argnames=("lr", "interpret"))
def fused_mix_sgd(p, buf, w, *, lr: float, interpret: bool = False):
    """Fused gossip epilogue on ONE flat bucket: ``W @ p − lr·buf``.

    ``p``/``buf`` are [n, F] stacked flat slabs (any dtype), ``w`` the
    [n, n] mixing matrix.  Returns the updated slab with p's
    shape/dtype, computed with the matrix and accumulation in f32 (the
    scatter-path numerics contract) in a single memory pass.
    """
    n, f = p.shape
    shape, dtype = p.shape, p.dtype
    n_pad = -(-n // _SUBLANE) * _SUBLANE
    # Column-block size: bound the three (n_pad, BF) f32 slabs to ~2 MiB
    # each in VMEM (the [n_pad, n_pad] matrix block is tiny beside
    # them), with the lane-multiple floor.
    bf = max((1 << 19) // max(n_pad, 1) // _LANE, 1) * _LANE
    f_pad = -(-f // bf) * bf
    grid = f_pad // bf

    def tile(x):
        x = x.astype(jnp.float32)
        return jnp.pad(x, ((0, n_pad - n), (0, f_pad - f)))

    w_t = jnp.pad(jnp.asarray(w, jnp.float32),
                  ((0, n_pad - n), (0, n_pad - n)))
    pt, mt = tile(p), tile(buf)
    w_spec = pl.BlockSpec((n_pad, n_pad), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    spec = pl.BlockSpec((n_pad, bf), lambda i: (0, i),
                        memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _make_mix_kernel(float(lr)),
        out_shape=jax.ShapeDtypeStruct(pt.shape, jnp.float32),
        grid=(grid,),
        in_specs=[w_spec, spec, spec],
        out_specs=spec,
        input_output_aliases={1: 0},
        interpret=interpret,
    )(w_t, pt, mt)
    return out[:n, :f].reshape(shape).astype(dtype)


def fused_mix_update(params, momentum, w_matrix, spec, *, lr: float,
                     interpret: bool | None = None):
    """The tree-level fused mix+update epilogue: flatten the stacked
    [W, ...] ``params``/``momentum`` trees into ``spec``'s buckets
    (``dopt.parallel.collectives.stacked_to_buckets``), run the fused
    ``W @ p − lr·buf`` kernel per bucket, and restore the tree.  The
    single-pass form of the D-PSGD round epilogue ``x ← Wx − lr·v`` on
    the same flat-bucket substrate the scatter hot path uses.  Both
    engines wire it behind ``fused_update="on"`` with a restructured
    scan carry: gossip carries (post-mix params, displacement buffer)
    and calls this with ``lr=1.0`` (``q_t = W·q − fbuf``); federated
    carries the theta broadcast slab and calls it with the masked-mean
    contraction matrix and ``lr=-1.0`` (``θ'_b = M·disp + θ_b``).  The
    default ``"off"`` compiles the exact pre-change programs, so the
    oracle-parity trace is untouched.

    ``interpret=None`` auto-selects: compiled on TPU, interpret mode
    elsewhere (same code path, testable on CPU).
    """
    from dopt.parallel.collectives import (buckets_to_stacked,
                                           stacked_to_buckets)

    if interpret is None:
        interpret = not pallas_available()
    w = jnp.asarray(w_matrix, jnp.float32)
    pb = stacked_to_buckets(params, spec)
    mb = stacked_to_buckets(momentum, spec)
    with jax.named_scope("dopt_update"):
        out = [fused_mix_sgd(p, m, w, lr=float(lr), interpret=interpret)
               for p, m in zip(pb, mb)]
    return buckets_to_stacked(out, spec)


def mix_sgd_reference(params, momentum, w_matrix, *, lr: float):
    """Pure-jnp reference for ``fused_mix_update`` (same f32 matrix +
    accumulation; XLA materialises the mixed intermediate): the parity
    oracle the kernel is tested against."""
    w = jnp.asarray(w_matrix, jnp.float32)

    def leaf(p, m):
        mixed = jnp.tensordot(w, p.astype(jnp.float32), axes=[[1], [0]])
        return (mixed - lr * m.astype(jnp.float32)).astype(p.dtype)

    return jax.tree.map(leaf, params, momentum)


def fused_sgd_momentum_tree(params, momentum, grads, *, lr: float, mu: float,
                            interpret: bool | None = None):
    """Tree-map the fused kernel over a params pytree.

    ``interpret=None`` auto-selects: compiled on TPU, interpret mode
    elsewhere (same code path, testable on CPU).
    """
    if interpret is None:
        interpret = not pallas_available()
    new_p, new_m = [], []
    p_leaves, treedef = jax.tree.flatten(params)
    m_leaves = treedef.flatten_up_to(momentum)
    g_leaves = treedef.flatten_up_to(grads)
    # dopt_update scope: phase attribution for the profiler's
    # conv/comm/update split (dopt.utils.profiling.classify_phase).
    with jax.named_scope("dopt_update"):
        for p, m, g in zip(p_leaves, m_leaves, g_leaves):
            np_, nm_ = fused_sgd_momentum(p, m, g, lr=lr, mu=mu,
                                          interpret=interpret)
            new_p.append(np_)
            new_m.append(nm_)
    return treedef.unflatten(new_p), treedef.unflatten(new_m)
