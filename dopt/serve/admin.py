"""Admin + observability HTTP endpoint for the resident trainer.

One stdlib ``ThreadingHTTPServer`` per daemon (leader process), bound
``port=0``-ephemeral by default (the chosen port lands in the status
file and ``GET /admin/status``):

* ``GET  /metrics``          Prometheus exposition from the IN-PROCESS
                             sink (no file tailing — the PR 10 serve
                             endpoint promoted into the daemon);
* ``GET  /healthz``          the in-process ``HealthMonitor``'s live
                             verdict; 200 healthy/warn, 503 critical;
* ``GET  /admin/status``     daemon snapshot (round, paused, cadence,
                             restarts, pending command ids);
* ``GET  /admin/config``     the effective whitelisted config;
* ``GET  /admin/membership`` present/away workers + the directive log;
* ``POST /admin/config``     ``{"key": "optim.lr", "value": 0.05,
                             "at_round": 12?}`` — queue a whitelisted
                             config change;
* ``POST /admin/membership`` ``{"worker": 3, "action": "leave"}``;
* ``POST /admin/checkpoint`` checkpoint at the next boundary;
* ``POST /admin/drain``      ``{"restart": true?}`` — drain the run
                             (optionally asking for a re-exec);
* ``POST /admin/pause`` / ``POST /admin/resume``  — admission control;
* ``POST /admin/profile``    ``{"rounds": K}`` — arm an on-demand
                             ``jax.profiler`` capture for the next K
                             rounds; the daemon writes a Chrome-trace
                             artifact (device trace merged with the
                             host spans) under ``<state>/profile/``.
                             GET returns the capture status +
                             artifact paths.  Pure observability: NOT
                             a queued command, never ledgered, and
                             pinned to leave History / fault ledger /
                             canonical stream bit-identical;
* every 503 carries a ``Retry-After`` header and a JSON body, and
  ``/healthz`` includes the monitor's own ``lag_seconds`` (wall since
  the newest event) so a stalled producer is distinguishable from a
  healthy idle one.

Command POSTs append to the command queue and return 202 with the
command id; commands take effect at the next eligible round boundary
and are ledgered there — the endpoint never mutates training state
directly, so everything it does is replayable from the applied ledger.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from dopt.serve.control import make_command

_POST_COMMANDS = {
    "/admin/config": "config",
    "/admin/membership": "membership",
    "/admin/checkpoint": "checkpoint",
    "/admin/drain": "drain",
    "/admin/pause": "pause",
    "/admin/resume": "resume",
}

_HELP = (b"dopt serve admin: GET /metrics /healthz /admin/status "
         b"/admin/config /admin/membership /admin/profile; POST "
         b"/admin/config /admin/membership /admin/checkpoint "
         b"/admin/drain /admin/pause /admin/resume /admin/profile\n")


class AdminServer:
    """The daemon's HTTP surface; lifecycle owned by ``ServeDaemon``."""

    def __init__(self, daemon, *, host: str = "127.0.0.1", port: int = 0):
        self.daemon = daemon
        self._httpd = ThreadingHTTPServer((host, port), self._handler())
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> "AdminServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- request handling ---------------------------------------------
    def _get(self, path: str) -> tuple[int, bytes, str]:
        d = self.daemon
        if path == "/":
            return 200, _HELP, "text/plain"
        if path == "/metrics":
            if d.prom is None:
                return (503, b'{"error": "telemetry not attached"}\n',
                        "application/json")
            return (200, d.prom.render().encode(),
                    "text/plain; version=0.0.4; charset=utf-8")
        if path == "/healthz":
            if d.monitor is None:
                return 503, b'{"error": "monitor not attached"}\n', \
                    "application/json"
            report = self._report()
            body = report.to_dict()
            body["serve"] = d.snapshot()
            # The monitor's own staleness (wall seconds since the
            # newest event): a stalled producer and a healthy idle one
            # report the same verdict — the lag tells them apart.
            body["last_event_ts"] = d.monitor.last_event_ts
            body["lag_seconds"] = d.monitor.lag_seconds()
            return (200 if report.ok else 503,
                    json.dumps(body, indent=2).encode(), "application/json")
        if path == "/admin/status":
            return (200, json.dumps(d.snapshot(), indent=2).encode(),
                    "application/json")
        if path == "/admin/config":
            return (200, json.dumps(d.config_snapshot(), indent=2).encode(),
                    "application/json")
        if path == "/admin/membership":
            return (200, json.dumps(d.membership_snapshot(),
                                    indent=2).encode(), "application/json")
        if path == "/admin/profile":
            return (200, json.dumps(d.profile_status(),
                                    indent=2).encode(), "application/json")
        return 404, b"not found\n", "text/plain"

    def _report(self):
        # The monitor is fed from the training thread; a dict resize
        # mid-copy is survivable by retrying (GIL makes each op atomic,
        # just not the aggregate).
        for _ in range(3):
            try:
                return self.daemon.monitor.report()
            except RuntimeError:
                continue
        return self.daemon.monitor.report()

    def _post(self, path: str, body: dict[str, Any]) -> tuple[int, bytes]:
        if path == "/admin/profile":
            # NOT a queued command: profiling is observability, must
            # never enter the applied ledger (a profiled run replays
            # identically to an unprofiled one).
            try:
                status = self.daemon.request_profile(
                    body.get("rounds", 1))
            except (TypeError, ValueError) as e:
                return 400, json.dumps({"error": str(e)}).encode() + b"\n"
            return 202, json.dumps(
                {"armed": True, **status}).encode() + b"\n"
        cmd_kind = _POST_COMMANDS.get(path)
        if cmd_kind is None:
            return 404, b'{"error": "not found"}\n'
        try:
            cmd = make_command(
                cmd_kind,
                id=body.get("id"),
                at_round=body.get("at_round"),
                key=body.get("key"),
                value=body.get("value"),
                worker=body.get("worker"),
                action=body.get("action"),
                restart=body.get("restart"),
            )
            cmd = self.daemon.submit(cmd)
        except ValueError as e:
            return 400, json.dumps({"error": str(e)}).encode() + b"\n"
        return 202, json.dumps(
            {"queued": cmd.get("id"),
             "applies": ("at the first boundary >= round "
                         f"{cmd['at_round']}" if "at_round" in cmd
                         else "at the next round boundary")}).encode() + b"\n"

    def _handler(self) -> type[BaseHTTPRequestHandler]:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                code, body, ctype = server._get(path)
                self._reply(code, body, ctype)

            def do_POST(self) -> None:  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                n = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(n) if n else b"{}"
                try:
                    body = json.loads(raw or b"{}")
                    if not isinstance(body, dict):
                        raise ValueError("body must be a JSON object")
                except ValueError as e:
                    self._reply(400, json.dumps(
                        {"error": f"bad JSON body: {e}"}).encode() + b"\n",
                        "application/json")
                    return
                code, out = server._post(path, body)
                self._reply(code, out, "application/json")

            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                from dopt.obs.serve import http_reply

                http_reply(self, code, body, ctype)

            def log_message(self, fmt: str, *args: Any) -> None:
                pass   # scrapes would flood the daemon's stderr

        return Handler
