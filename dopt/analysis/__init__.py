"""Static gates for the invariants the test suite cannot cheaply pin.

Every PR in this repo ends by re-proving the same three properties by
hand: default-off knobs compile the exact pre-change programs, library
code never reads wall clocks or unseeded RNG on deterministic paths,
and the construction-time eligibility rejections still match the
ARCHITECTURE.md composition matrix.  ``dopt.analysis`` turns each
ritual into a commit-time gate:

``python -m dopt.analysis.lint dopt/``
    Trace-safety & determinism linter — a stdlib-``ast`` pass flagging
    wall-clock reads, global-state RNG, retrace/trace hazards inside
    jit-reachable functions, and non-deterministic telemetry emission
    outside ``dopt.obs``.  Audited legitimate uses carry a
    ``# dopt: allow-<rule> -- <justification>`` pragma.

``python -m dopt.analysis.eligibility``
    Eligibility-matrix extractor — statically harvests every
    construction-time ``raise ValueError`` across the config/engine
    constructors into ``results/eligibility.json`` and cross-checks
    the composition rejections against the ARCHITECTURE.md
    eligibility-matrix table, so feature×feature drift fails CI
    instead of rotting in the docs.

``python -m dopt.analysis.fingerprint``
    Program-fingerprint registry — lowers the canonical default-off
    round programs (both engines, tiny CPU shapes, the
    baseline1/baseline3 matrix), hashes the canonicalized IR, and
    diffs against the committed ``results/program_fingerprints.json``;
    ``--bless --reason "..."`` regenerates with a recorded
    justification.

All three CLIs share the ``dopt.obs.check`` conventions: exit 0 clean,
1 findings, 2 usage error; ``--json`` emits machine output for CI
annotation (``dopt.analysis.common``).
"""

from dopt.analysis.common import (EXIT_CLEAN, EXIT_FINDINGS, EXIT_USAGE,
                                  Finding, parse_pragmas)

__all__ = ["EXIT_CLEAN", "EXIT_FINDINGS", "EXIT_USAGE", "Finding",
           "parse_pragmas"]
