"""Aux subsystems: checkpoint/resume, metrics sink, profiling, plotting."""

import json

import numpy as np
import pytest

from dopt.config import (DataConfig, ExperimentConfig, FederatedConfig,
                         GossipConfig, ModelConfig, OptimizerConfig)
from dopt.engine import FederatedTrainer, GossipTrainer
from dopt.utils.metrics import History
from dopt.utils.profiling import PhaseTimers


def _cfg(**kw):
    return ExperimentConfig(
        name="aux", seed=5,
        data=DataConfig(dataset="synthetic", num_users=4,
                        synthetic_train_size=256, synthetic_test_size=64),
        model=ModelConfig(model="mlp", input_shape=(28, 28, 1), faithful=False),
        optim=OptimizerConfig(lr=0.1, momentum=0.5),
        **kw,
    )


def test_gossip_checkpoint_resume_bitexact(devices, tmp_path):
    import jax
    cfg = _cfg(gossip=GossipConfig(algorithm="dsgd", topology="circle",
                                   mode="metropolis", local_ep=1, local_bs=32))
    a = GossipTrainer(cfg)
    a.run(rounds=2)
    a.save(tmp_path / "ckpt")

    # Fresh trainer resumes and must produce the identical continuation.
    b = GossipTrainer(cfg)
    b.restore(tmp_path / "ckpt")
    assert b.round == 2
    assert len(b.history) == 2
    a.run(rounds=2)
    b.run(rounds=2)
    for la, lb in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_federated_checkpoint_roundtrip_with_duals(devices, tmp_path):
    import jax
    cfg = _cfg(federated=FederatedConfig(algorithm="fedadmm", frac=0.5,
                                         local_ep=1, local_bs=32))
    a = FederatedTrainer(cfg)
    a.run(rounds=2)
    a.save(tmp_path / "ck")
    b = FederatedTrainer(cfg)
    b.restore(tmp_path / "ck")
    assert b.round == 2
    for la, lb in zip(jax.tree.leaves(a.duals), jax.tree.leaves(b.duals)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    for la, lb in zip(jax.tree.leaves(a.theta), jax.tree.leaves(b.theta)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_history_csv_roundtrip(tmp_path):
    h = History("x")
    h.append(round=0, avg_test_acc=0.5, avg_test_loss=2.0, avg_train_loss=1.9)
    h.append(round=1, avg_test_acc=0.6, avg_test_loss=1.5, avg_train_loss=1.2)
    p = h.to_csv(tmp_path / "r.csv")
    # Reference results/*.csv layout: leading unnamed index column.
    first = p.read_text().splitlines()[0]
    assert first.startswith(",round,")
    back = History.from_csv(p)
    assert back["avg_test_acc"] == [0.5, 0.6]
    jp = h.to_json(tmp_path / "r.json")
    assert json.loads(jp.read_text())[1]["round"] == 1


def test_phase_timers():
    import time
    t = PhaseTimers()
    with t.phase("a"):
        time.sleep(0.01)
    with t.phase("a"):
        time.sleep(0.01)
    out = t.measure("b", lambda: np.zeros(3))
    assert out.shape == (3,)
    s = t.summary()
    assert s["a"]["count"] == 2 and s["a"]["total_s"] >= 0.02
    assert "a" in t.report() and "b" in t.report()


def test_compare_histories_plot(tmp_path):
    pytest.importorskip("matplotlib")
    from dopt.utils.plotting import compare_histories
    h1, h2 = History("a"), History("b")
    for r in range(3):
        h1.append(round=r, avg_test_acc=0.1 * r, avg_test_loss=2 - r * 0.1,
                  avg_train_loss=2 - r * 0.2)
        h2.append(round=r, avg_test_acc=0.2 * r, avg_test_loss=2 - r * 0.2,
                  avg_train_loss=2 - r * 0.3)
    p = compare_histories({"a": h1, "b": h2}, save=tmp_path / "cmp.png")
    assert p.exists() and p.stat().st_size > 1000


def test_federated_resume_continues_sampling_stream(devices, tmp_path):
    # A resumed run must draw the SAME client samples a continuous run
    # would (RNG state is checkpointed), so trajectories are identical.
    import jax
    cfg = _cfg(federated=FederatedConfig(algorithm="fedavg", frac=0.5,
                                         local_ep=1, local_bs=32))
    a = FederatedTrainer(cfg)
    a.run(rounds=4)

    b = FederatedTrainer(cfg)
    b.run(rounds=2)
    b.save(tmp_path / "ck")
    c = FederatedTrainer(cfg)
    c.restore(tmp_path / "ck")
    c.run(rounds=2)
    for la, lc in zip(jax.tree.leaves(a.theta), jax.tree.leaves(c.theta)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lc))


def test_restore_rejects_wrong_algorithm(devices, tmp_path):
    cfg = _cfg(federated=FederatedConfig(algorithm="fedavg", frac=1.0,
                                         local_ep=1, local_bs=32))
    a = FederatedTrainer(cfg)
    a.run(rounds=1)
    a.save(tmp_path / "ck")
    cfg2 = _cfg(federated=FederatedConfig(algorithm="fedadmm", frac=1.0,
                                          local_ep=1, local_bs=32))
    b = FederatedTrainer(cfg2)
    with pytest.raises(ValueError, match="algorithm"):
        b.restore(tmp_path / "ck")


def test_timers_populated_by_run(devices):
    cfg = _cfg(gossip=GossipConfig(algorithm="dsgd", topology="circle",
                                   mode="metropolis", local_ep=1, local_bs=32))
    tr = GossipTrainer(cfg)
    tr.run(rounds=2)
    s = tr.timers.summary()
    assert s["round_step"]["count"] == 2
    assert s["host_batch_plan"]["count"] == 2


def test_flops_accounting_model1(devices):
    """XLA cost-analysis FLOPs must agree with Model1's analytic MAC
    count (bench.py's documented 12,273,152 MACs/sample forward) to
    within compiler-accounting slack — this pins the generic MFU meter
    the bench suite uses for every zoo model."""
    import jax
    import jax.numpy as jnp

    from dopt.models import build_model
    from dopt.utils.profiling import (fwd_flops_per_sample,
                                      train_flops_per_sample)

    model = build_model("model1")
    params = model.init(jax.random.key(0), jnp.zeros((1, 28, 28, 1)))["params"]
    fn = lambda p, x: model.apply({"params": p}, x)  # noqa: E731
    f = fwd_flops_per_sample(fn, params, (28, 28, 1))
    analytic = 2 * 12_273_152
    assert 0.6 * analytic < f < 1.6 * analytic, f
    assert train_flops_per_sample(fn, params, (28, 28, 1)) == pytest.approx(3 * f)


def test_time_to_target():
    from dopt.utils.metrics import History, time_to_target

    h = History("t")
    h.append(round=0, avg_test_acc=0.2)
    h.append(round=1)                      # eval-skipped row
    h.append(round=2, avg_test_acc=0.85)
    h.append(round=3, avg_test_acc=0.95)
    hit = time_to_target(h, target=0.9, seconds_per_round=2.0)
    assert hit == {"reached": True, "round": 3, "rounds": 4, "seconds": 8.0}
    miss = time_to_target(h, target=0.99)
    assert miss["reached"] is False and miss["seconds"] is None


def test_client_grid_plot(tmp_path, devices):
    pytest.importorskip("matplotlib")
    from dopt.utils.plotting import client_grid_plot
    from tests.test_engine import _holdout_gossip_cfg
    from dopt.engine import GossipTrainer

    tr = GossipTrainer(_holdout_gossip_cfg())
    tr.run(rounds=2)
    out = client_grid_plot(tr.client_history, num_workers=tr.num_workers,
                           title="per-client", save=tmp_path / "grid.png")
    assert out.exists() and out.stat().st_size > 0
    # empty history: loud error pointing at the holdout knob
    from dopt.utils.metrics import History
    with pytest.raises(ValueError, match="local_holdout"):
        client_grid_plot(History("empty"))


def test_checkpoint_atomic_crash_before_promote(tmp_path, monkeypatch):
    """A save that dies while materialising the new checkpoint (e.g.
    between the state write and the meta write) must leave the previous
    checkpoint fully loadable — the old dir is never touched in place."""
    import dopt.utils.checkpoint as ckpt

    path = tmp_path / "ck"
    ckpt.save_checkpoint(path, arrays={"w": {"a": np.arange(4.0)}},
                         meta={"round": 1})

    def boom(dest, meta):
        raise RuntimeError("simulated crash before meta write")

    monkeypatch.setattr(ckpt, "_write_meta", boom)
    with pytest.raises(RuntimeError, match="simulated crash"):
        ckpt.save_checkpoint(path, arrays={"w": {"a": np.arange(4.0) * 2}},
                             meta={"round": 2})
    monkeypatch.undo()
    arrays, meta = ckpt.load_checkpoint(path)
    assert meta["round"] == 1
    np.testing.assert_array_equal(np.asarray(arrays["w"]["a"]), np.arange(4.0))


def test_checkpoint_atomic_crash_between_renames(tmp_path, monkeypatch):
    """Worst case: the old checkpoint is parked at <path>.old but the
    promotion rename never happens.  load_checkpoint must fall back."""
    import os as _os

    import dopt.utils.checkpoint as ckpt

    path = tmp_path / "ck"
    ckpt.save_checkpoint(path, arrays={"w": {"a": np.arange(3.0)}},
                         meta={"round": 7})

    real_replace = _os.replace
    calls = {"n": 0}

    def crashy_replace(src, dst):
        calls["n"] += 1
        if calls["n"] == 2:  # first = park old, second = promote tmp
            raise RuntimeError("simulated crash mid-swap")
        return real_replace(src, dst)

    monkeypatch.setattr(ckpt.os, "replace", crashy_replace)
    with pytest.raises(RuntimeError, match="mid-swap"):
        ckpt.save_checkpoint(path, arrays={"w": {"a": np.arange(3.0) * 5}},
                             meta={"round": 8})
    monkeypatch.undo()
    assert not (path / "meta.json").exists()  # primary really is gone
    arrays, meta = ckpt.load_checkpoint(path)
    assert meta["round"] == 7
    np.testing.assert_array_equal(np.asarray(arrays["w"]["a"]), np.arange(3.0))

    # Save-after-crash: with only <path>.old alive, the NEXT save must
    # keep it loadable through its whole window — in particular .old may
    # not be deleted before the promotion rename lands.
    calls["n"] = 10  # disarm
    monkeypatch.setattr(ckpt.os, "replace", crashy_replace)
    real_rmtree = ckpt.shutil.rmtree

    def guarded_rmtree(p, *a, **kw):
        if str(p).endswith(".old") and not (path / "meta.json").exists():
            raise AssertionError(".old deleted while no primary exists")
        return real_rmtree(p, *a, **kw)

    monkeypatch.setattr(ckpt.shutil, "rmtree", guarded_rmtree)
    ckpt.save_checkpoint(path, arrays={"w": {"a": np.arange(3.0) * 9}},
                         meta={"round": 9})
    monkeypatch.undo()
    arrays, meta = ckpt.load_checkpoint(path)
    assert meta["round"] == 9
    assert not path.with_name(path.name + ".old").exists()


def test_csv_column_order_matches_reference_schema(tmp_path):
    """Shared columns must come out in the reference's committed-CSV
    order (P2: round, avg_test_acc, avg_test_loss, avg_train_loss) with
    extras after, and the column set is the union over all rows (rounds
    without eval carry fewer keys)."""
    from dopt.utils.metrics import History

    h = History("t")
    h.append(round=0, avg_train_loss=1.0, avg_train_acc=0.5,
             avg_test_acc=0.1, avg_test_loss=2.0)
    h.append(round=1, avg_train_loss=0.9, avg_train_acc=0.6)  # no-eval round
    p = h.to_csv(tmp_path / "h.csv")
    header = p.read_text().splitlines()[0]
    assert header == (",round,avg_test_acc,avg_test_loss,avg_train_loss,"
                      "avg_train_acc")
    # round-trip keeps all rows
    h2 = History.from_csv(p)
    assert len(h2.rows) == 2
