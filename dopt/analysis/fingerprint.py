"""Program-fingerprint gate: ``python -m dopt.analysis.fingerprint``.

Every default-off knob in this repo ships with the same promise:
"off compiles the exact pre-change programs".  Until now that promise
was re-proven per PR by hand (lower the round function, diff the HLO).
This gate turns it into a commit-time check: the canonical DEFAULT
round programs — both engines, tiny CPU shapes, the
baseline1/baseline3 config matrix — are lowered via the engines'
``lower_round`` hook (which consumes the same ``_round_dispatch``
builder the real ``run`` loop dispatches, so the pinned program IS the
shipped program), their StableHLO text canonicalized and hashed, and
the hashes diffed against the committed
``results/program_fingerprints.json``.

* A PR that does not touch the default path leaves every hash intact —
  the gate is green with zero effort.
* A PR that changes what the default path compiles (a new op inside
  ``round_fn``, a knob that leaks into the off program, a changed
  constant) flips a hash and FAILS until the change is blessed:
  ``--bless --reason "<why the default program legitimately changed>"``
  regenerates the registry with the justification recorded — the
  off-path byte-identity ritual becomes one reviewed line in the diff.

Fingerprints are environment-sensitive (StableHLO text varies across
jax versions and backends), so the registry records the environment it
was blessed under; on mismatch the gate SKIPS (exit 0, reported) unless
``--strict`` — CI pins ``JAX_PLATFORMS=cpu`` and one jax version, so
the gate is always live there.

Exit codes: 0 clean/skipped, 1 drift, 2 usage error; ``--json`` prints
the machine-readable report.
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import re
import sys
from pathlib import Path
from typing import Any, Callable, Mapping

from dopt.analysis.common import (EXIT_CLEAN, EXIT_USAGE, Finding,
                                  emit_report)

DEFAULT_REGISTRY = "results/program_fingerprints.json"

# Tiny-shape overrides: the fingerprint pins program STRUCTURE (ops,
# routing, constants baked by the config), not workload scale — small
# synthetic data keeps the gate seconds-cheap on one CPU.
_TINY_TRAIN, _TINY_TEST = 256, 64


def _tiny(cfg):
    return cfg.replace(data=dataclasses.replace(
        cfg.data, dataset="synthetic", data_dir=None,
        synthetic_train_size=_TINY_TRAIN, synthetic_test_size=_TINY_TEST))


def canonical_matrix() -> dict[str, Callable[[], Any]]:
    """The default-off config matrix the gate pins, name → config
    builder.  baseline1 exercises the gossip dense consensus round,
    baseline3 the federated engine on BOTH execution paths (frac=1 →
    full-width ``round_fn``; its preset frac=0.5 on one CPU device →
    auto-compact ``compact_fn``)."""
    from dopt.presets import (baseline_1_ring_mnist_mlp,
                              baseline_3_fedavg_noniid)

    def b1():
        return _tiny(baseline_1_ring_mnist_mlp())

    def b3_full():
        cfg = _tiny(baseline_3_fedavg_noniid())
        cfg = cfg.replace(data=dataclasses.replace(cfg.data,
                                                   num_users=4))
        return cfg.replace(federated=dataclasses.replace(
            cfg.federated, frac=1.0))

    def b3_compact():
        cfg = _tiny(baseline_3_fedavg_noniid())
        return cfg.replace(data=dataclasses.replace(cfg.data,
                                                    num_users=4))

    return {"baseline1-tiny": b1,
            "baseline3-tiny-full": b3_full,
            "baseline3-tiny-compact": b3_compact}


_LOC_RE = re.compile(r'\s*loc\([^()]*\)|^#loc.*$', re.MULTILINE)


def canonicalize(text: str) -> str:
    """Strip source-location debris so the hash tracks the PROGRAM:
    plain line shifts in engine files must not flip fingerprints."""
    text = _LOC_RE.sub("", text)
    return "\n".join(line.rstrip() for line in text.splitlines()) + "\n"


def current_env() -> dict[str, Any]:
    """The fingerprint environment key.  Device COUNT is part of it:
    the same config lowers a different (sharded) module on an 8-device
    virtual mesh than on one chip, so registries only compare within
    an identical (jax, backend, devices) triple."""
    import jax

    return {"jax": jax.__version__, "backend": jax.default_backend(),
            "devices": jax.device_count()}


def _build_trainer(cfg):
    if cfg.gossip is not None:
        from dopt.engine.gossip import GossipTrainer

        return "gossip", GossipTrainer(cfg)
    from dopt.engine.federated import FederatedTrainer

    return "federated", FederatedTrainer(cfg)


def compute_fingerprints(
        configs: Mapping[str, Callable[[], Any]] | None = None,
) -> dict[str, dict[str, Any]]:
    """Lower each config's round-0 program on a fresh trainer and hash
    the canonicalized module text."""
    configs = canonical_matrix() if configs is None else configs
    out: dict[str, dict[str, Any]] = {}
    for name in sorted(configs):
        engine, trainer = _build_trainer(configs[name]())
        fn_name, lowered = trainer.lower_round(0)
        text = canonicalize(lowered.as_text())
        out[name] = {
            "engine": engine,
            "fn": fn_name,
            "sha256": hashlib.sha256(text.encode()).hexdigest(),
            "chars": len(text),
        }
    return out


def diff(current: Mapping[str, dict], committed: Mapping[str, dict],
         registry_path: str) -> list[Finding]:
    findings: list[Finding] = []
    for name in sorted(set(current) - set(committed)):
        findings.append(Finding(
            "fingerprint-new", registry_path, 0,
            f"{name}: canonical program not in the registry — bless it "
            f"(--bless --reason ...)"))
    for name in sorted(set(committed) - set(current)):
        findings.append(Finding(
            "fingerprint-removed", registry_path, 0,
            f"{name}: registered program no longer in the canonical "
            f"matrix — bless the removal"))
    for name in sorted(set(current) & set(committed)):
        cur, old = current[name], committed[name]
        if cur["sha256"] != old["sha256"]:
            findings.append(Finding(
                "fingerprint-mismatch", registry_path, 0,
                f"{name} ({cur['engine']}/{cur['fn']}): the DEFAULT "
                f"round program changed — {old['sha256'][:12]} → "
                f"{cur['sha256'][:12]} ({old['chars']} → "
                f"{cur['chars']} chars).  If intended, re-bless with "
                f"--bless --reason '<why>'"))
        elif (cur["fn"], cur["engine"]) != (old["fn"], old["engine"]):
            findings.append(Finding(
                "fingerprint-mismatch", registry_path, 0,
                f"{name}: dispatch routing changed "
                f"({old['engine']}/{old['fn']} → "
                f"{cur['engine']}/{cur['fn']})"))
    return findings


def load_registry(path: str | Path) -> dict[str, Any] | None:
    try:
        return json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return None


def write_registry(path: str | Path, fingerprints: Mapping[str, dict],
                   env: Mapping[str, str], reason: str) -> None:
    doc = {"v": 1, "env": dict(env), "bless": {"reason": reason},
           "fingerprints": {k: dict(v)
                            for k, v in sorted(fingerprints.items())}}
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dopt.analysis.fingerprint",
        description="Off-path program-fingerprint gate for the "
                    "canonical default round programs.")
    ap.add_argument("names", nargs="*", metavar="NAME",
                    help="subset of canonical programs to check "
                         "(default: all)")
    ap.add_argument("--registry", default=DEFAULT_REGISTRY,
                    help=f"committed registry (default: "
                         f"{DEFAULT_REGISTRY})")
    ap.add_argument("--bless", action="store_true",
                    help="regenerate the registry from the current "
                         "tree (requires --reason)")
    ap.add_argument("--reason", default="",
                    help="justification recorded with --bless — why "
                         "the default programs legitimately changed")
    ap.add_argument("--strict", action="store_true",
                    help="fail (instead of skip) on environment "
                         "mismatch with the blessed registry")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)
    if args.bless and not args.reason.strip():
        print("--bless requires --reason '<why the default programs "
              "changed>'", file=sys.stderr)
        return EXIT_USAGE
    matrix = canonical_matrix()
    if args.names:
        unknown = set(args.names) - set(matrix)
        if unknown:
            print(f"unknown program(s): {', '.join(sorted(unknown))}; "
                  f"canonical: {', '.join(sorted(matrix))}",
                  file=sys.stderr)
            return EXIT_USAGE
        matrix = {k: matrix[k] for k in args.names}

    env = current_env()
    if args.bless:
        full = set(matrix) == set(canonical_matrix())
        if not full:
            # Partial bless: merge over the committed registry — only
            # sound when the kept entries were computed under THIS
            # environment, since the registry carries one env for all.
            old = load_registry(args.registry) or {"fingerprints": {}}
            if old.get("fingerprints") and old.get("env") != env:
                print(
                    f"partial bless refused: {args.registry} is "
                    f"blessed under {old.get('env')}, this is {env} — "
                    "merging would stamp stale hashes with the wrong "
                    "env.  Bless the full matrix instead (no NAME "
                    "args).", file=sys.stderr)
                return EXIT_USAGE
            merged = dict(old.get("fingerprints", {}))
            merged.update(compute_fingerprints(matrix))
            fps = merged
        else:
            fps = compute_fingerprints(matrix)
        # The recorded reason describes the MOST RECENT bless.
        write_registry(args.registry, fps, env, args.reason.strip())
        print(f"blessed {len(fps)} fingerprint(s) into "
              f"{args.registry} (reason: {args.reason.strip()})")
        return EXIT_CLEAN

    committed = load_registry(args.registry)
    if committed is None:
        return emit_report(
            [Finding("registry-missing", args.registry, 0,
                     "no committed fingerprint registry — run "
                     "`python -m dopt.analysis.fingerprint --bless "
                     "--reason 'initial registry'`")],
            as_json=args.json, tool="dopt.analysis.fingerprint",
            checked=0, unit="program")
    if committed.get("env") != env:
        skip = {"status": "skipped", "reason": "environment mismatch",
                "blessed_env": committed.get("env"), "current_env": env}
        if args.strict:
            return emit_report(
                [Finding("environment-mismatch", args.registry, 0,
                         f"registry blessed under "
                         f"{committed.get('env')}, running under "
                         f"{env}")],
                as_json=args.json, tool="dopt.analysis.fingerprint",
                checked=0, unit="program", extra=skip)
        if args.json:
            return emit_report([], as_json=True,
                               tool="dopt.analysis.fingerprint",
                               checked=0, unit="program", extra=skip)
        print("dopt.analysis.fingerprint: SKIPPED — environment "
              f"mismatch (registry blessed under {committed.get('env')}, "
              f"running under {env}); 0 programs compared.  Use "
              "--strict to fail instead.")
        return EXIT_CLEAN
    fps = compute_fingerprints(matrix)
    committed_fps = committed.get("fingerprints", {})
    if args.names:
        committed_fps = {k: v for k, v in committed_fps.items()
                         if k in args.names}
    findings = diff(fps, committed_fps, args.registry)
    return emit_report(findings, as_json=args.json,
                       tool="dopt.analysis.fingerprint",
                       checked=len(fps), unit="program",
                       extra={"fingerprints": fps})


if __name__ == "__main__":
    raise SystemExit(main())
