"""Real multi-process ``jax.distributed`` execution — the launcher leg.

The reference has no communication backend at all (SURVEY §2.4: its
"multi-node" story is N objects in one process).  dopt's backend is the
jax runtime: ``dopt.parallel.multihost.initialize_distributed`` wires
the coordinator, and the hybrid (hosts × ici) mesh lays workers out so
gossip edges stay on the fast axis.  Everything below the mesh is
identical single- or multi-process — this script proves it by actually
running the same GossipTrainer round in N OS processes against one
coordination service and asserting every process converges to the SAME
trajectory (the determinism the in-process tests pin, now across a real
process boundary with gloo CPU collectives standing in for ICI/DCN).

Parent mode (default): spawns N children of this script sharing a
coordinator HANDOFF file, collects their output, and checks they all
report the same final metrics.  Child mode (``--process-id I``)
self-organises the coordinator: child 0 binds a port-0 ephemeral port
in its own process and publishes ``host:port`` through the handoff
file (atomic rename), the others wait on it — no parent-probed fixed
port, so the bind race window shrinks from the whole child-interpreter
startup to microseconds inside one process
(``dopt.parallel.multihost.coordinator_handoff``).

Usage:
    python scripts/multiprocess_demo.py                # 2 procs × 4 devices
    python scripts/multiprocess_demo.py --num-processes 2 --rounds 2
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
OK_MARK = "MULTIPROC-ROUND-OK"


def child_main(args) -> int:
    # Platform + virtual-device setup must precede backend init; the
    # whole dance (flag replace, gloo pin, handoff rendezvous,
    # jax.distributed init, topology asserts) is the shared
    # bootstrap_child_backend — ONE implementation for this demo and
    # the dopt.serve fleet children.
    sys.path.insert(0, str(REPO))
    from dopt.parallel.multihost import HOST_AXIS, bootstrap_child_backend

    bootstrap_child_backend(args.handoff, args.process_id,
                            args.num_processes, args.devices_per_proc)
    import jax

    assert jax.device_count() == args.num_processes * args.devices_per_proc

    from dopt.config import (DataConfig, ExperimentConfig, GossipConfig,
                             ModelConfig, OptimizerConfig)
    from dopt.engine import GossipTrainer

    num_workers = jax.device_count()
    cfg = ExperimentConfig(
        name="multiproc-demo", seed=3,
        data=DataConfig(dataset="synthetic", num_users=num_workers,
                        synthetic_train_size=32 * num_workers,
                        synthetic_test_size=64),
        model=ModelConfig(model="mlp", input_shape=(28, 28, 1),
                          faithful=False),
        optim=OptimizerConfig(lr=0.1, momentum=0.5),
        gossip=GossipConfig(algorithm="dsgd", topology="circle",
                            mode="metropolis", local_ep=1, local_bs=8),
        mesh_hosts=args.num_processes,
    )
    tr = GossipTrainer(cfg)
    assert tr.mesh.shape[HOST_AXIS] == args.num_processes, tr.mesh
    h = tr.run(rounds=args.rounds)
    acc = h.last().get("avg_test_acc")
    loss = h.last().get("avg_train_loss")
    print(f"[p{args.process_id}] {OK_MARK} procs={args.num_processes} "
          f"mesh={dict(tr.mesh.shape)} rounds={args.rounds} "
          f"acc={acc:.6f} train_loss={loss:.6f}", flush=True)
    return 0


def parent_main(args) -> int:
    # Child 0 picks its own ephemeral port and hands it off through a
    # file, so the historical parent-probe TOCTOU is gone; the retry
    # loop stays for the one remaining non-dopt flake — gloo's tcp
    # transport interleaving two collectives' messages under host load.
    diag = ""
    for attempt in range(3):
        rc, diag = _parent_attempt(args)
        if rc != 3:  # 3 = retryable (residual bind race / gloo transport)
            return rc
        print(f"retryable launch failure (attempt {attempt + 1}/3), "
              "respawning with a fresh coordinator handoff",
              file=sys.stderr)
    # Out of retries: surface the last attempt's child output so a
    # non-retryable failure that happened to match the heuristics is
    # still diagnosable from the logs.
    sys.stderr.write(f"--- last attempt child output ---\n{diag}\n")
    print("FAIL: retryable launch failure persisted after 3 attempts",
          file=sys.stderr)
    return 1


def _parent_attempt(args) -> tuple[int, str]:
    import tempfile

    handoff = os.path.join(tempfile.mkdtemp(prefix="dopt-mpdemo-"),
                           "coordinator.json")
    # No env surgery here: each child's bootstrap_child_backend
    # REPLACES any inherited device-count flag itself.
    procs = [
        subprocess.Popen(
            [sys.executable, __file__, "--process-id", str(i),
             "--num-processes", str(args.num_processes),
             "--devices-per-proc", str(args.devices_per_proc),
             "--handoff", handoff, "--rounds", str(args.rounds)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for i in range(args.num_processes)
    ]
    outs, rcs = [], []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=args.timeout)
            outs.append(out)
            rcs.append(p.returncode)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        print("TIMEOUT: children killed", file=sys.stderr)
        return 2, ""

    ok_lines = []
    for i, (rc, out) in enumerate(zip(rcs, outs)):
        marks = [ln for ln in out.splitlines() if OK_MARK in ln]
        ok_lines += marks
        if rc != 0 or not marks:
            low = out.lower()
            if "failed to bind" in low or "address already in use" in low:
                # Retryable: another process grabbed the probed port.
                # The caller prints this output if retries run out.
                return 3, out
            if "op.preamble.length" in low:
                # Retryable: gloo's tcp transport occasionally
                # interleaves two collectives' messages on one pair
                # under host load (preamble/buffer length mismatch →
                # SIGABRT).  A transport-layer race, not a dopt bug —
                # respawn the whole attempt on a fresh coordinator.
                # (Matched on the specific signature only: a generic
                # 'gloo' match would retry — and mask — deterministic
                # failures whose logs merely mention the transport.)
                return 3, out
            sys.stderr.write(f"--- child {i} (rc={rc}) output ---\n{out}\n")
            print(f"FAIL: child {i} rc={rc} ok={bool(marks)}", file=sys.stderr)
            return 1, out
        print(marks[0])

    # Determinism across the process boundary: every process must report
    # the identical trajectory (same metrics to the printed digit).
    metrics = {ln.split(OK_MARK, 1)[1] for ln in ok_lines}
    if len(metrics) != 1:
        print(f"FAIL: processes disagree: {sorted(metrics)}", file=sys.stderr)
        return 1, ""
    print(f"multiprocess demo OK: {args.num_processes} processes × "
          f"{args.devices_per_proc} devices, identical trajectories")
    return 0, ""


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-processes", type=int, default=2)
    ap.add_argument("--devices-per-proc", type=int, default=4)
    ap.add_argument("--rounds", type=int, default=2)
    ap.add_argument("--timeout", type=float, default=420.0)
    ap.add_argument("--process-id", type=int, default=None,
                    help="(internal) run as child with this process id")
    ap.add_argument("--handoff", default=None,
                    help="(internal) coordinator handoff file")
    args = ap.parse_args(argv)
    if args.process_id is not None:
        return child_main(args)
    return parent_main(args)


if __name__ == "__main__":
    raise SystemExit(main())
