"""Telemetry sinks: where the event stream lands.

Three consumers, one ``emit(event)`` contract:

* ``JsonlSink`` — one JSON object per line, flushed per event so a
  SIGKILLed run leaves a complete prefix (at worst one truncated final
  line, which ``read`` tolerates at EOF).  Opened in append mode for
  resumed runs; ``scan_watermark`` recovers the monotonic round
  watermark from an existing file.
* ``MemorySink`` — bounded in-memory ring for tests and live
  inspection.
* ``PrometheusSink`` — maintains the LATEST value of every numeric
  round metric and gauge plus per-kind fault counters, and renders a
  Prometheus text-exposition snapshot (the scrape surface the future
  ``dopt serve`` will mount).
"""

from __future__ import annotations

import json
import re
from collections import deque
from pathlib import Path
from typing import Any, Iterator


def _jsonable(v: Any):
    """json.dumps fallback: unwrap numpy/jax scalars without importing
    either (telemetry must not drag device deps into the host path)."""
    item = getattr(v, "item", None)
    if callable(item):
        return item()
    raise TypeError(f"event field {v!r} is not JSON-serialisable")


class Sink:
    def emit(self, event: dict[str, Any]) -> None:  # pragma: no cover
        raise NotImplementedError

    def emit_many(self, events: list[dict[str, Any]]) -> None:
        """Batch emission; file sinks override it to make one round's
        bundle a single flushed write (crash leaves whole bundles, not
        a torn one — the resume watermark depends on it)."""
        for ev in events:
            self.emit(ev)

    def close(self) -> None:
        pass


class JsonlSink(Sink):
    """Append-structured JSONL file sink (line-flushed, crash-safe
    prefix)."""

    def __init__(self, path: str | Path, *, append: bool = False):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        if append:
            JsonlSink.repair_tail(self.path)
        self._f = open(self.path, "a" if append else "w")

    def emit(self, event: dict[str, Any]) -> None:
        self._f.write(json.dumps(event, separators=(",", ":"),
                                 default=_jsonable) + "\n")
        self._f.flush()

    def emit_many(self, events: list[dict[str, Any]]) -> None:
        """One round's bundle as ONE write + flush.  For bundles within
        the stdio buffer this reaches the OS as one write, so a kill
        leaves the whole bundle or none of it; a bundle large enough to
        straddle buffer flushes CAN tear, which is why ``repair_tail``
        drops unsealed fault/gauge events before a resume appends."""
        self._f.write("".join(
            json.dumps(ev, separators=(",", ":"), default=_jsonable) + "\n"
            for ev in events))
        self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    @staticmethod
    def read(path: str | Path) -> list[dict[str, Any]]:
        """Load a JSONL stream.  A truncated FINAL line (the one a kill
        can leave) is dropped; garbage anywhere else raises."""
        lines = Path(path).read_text().splitlines()
        events: list[dict[str, Any]] = []
        for i, line in enumerate(lines):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                if i == len(lines) - 1:
                    break
                raise ValueError(
                    f"{path}: line {i + 1} is not JSON: {line[:80]!r}")
        return events

    @staticmethod
    def repair_tail(path: str | Path) -> None:
        """Repair what a SIGKILL mid-write can leave, BEFORE a resumed
        segment appends.  An unterminated final line is healed (a
        newline appended) when it parses — JSON self-delimits, so the
        event is complete and only the terminator was torn — and
        dropped when it does not (once appended events follow it the
        garbage would sit MID-file, where ``read`` rightly raises).
        Then any trailing complete ``fault``/``gauge``/``control``
        events whose round was never sealed by a ``round`` event are
        dropped: the resumed run re-emits that round's whole bundle
        (and the serve daemon re-emits the resume boundary's applied
        control events), so keeping the orphans would silently
        double-count faults or duplicate control records.  Every decision is
        made against the repaired bytes, so the watermark
        ``scan_watermark`` recovers (before OR after the repair) always
        agrees with what survives on disk."""
        path = Path(path)
        if not path.exists():
            return
        orig = raw = path.read_bytes()
        if raw and not raw.endswith(b"\n"):
            nl = raw.rfind(b"\n") + 1
            try:
                json.loads(raw[nl:].strip())
            except ValueError:
                raw = raw[:nl]
            else:
                raw = raw + b"\n"
        sealed = -1
        for line in raw.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue  # mid-file garbage: left for read() to report
            if ev.get("kind") == "round" and isinstance(ev.get("round"), int):
                sealed = max(sealed, ev["round"])
        keep = len(raw)
        while keep > 0:
            prev = raw.rfind(b"\n", 0, keep - 1) + 1
            line = raw[prev:keep].strip()
            if line:
                try:
                    ev = json.loads(line)
                except ValueError:
                    break
                if not (ev.get("kind") in ("fault", "gauge", "control")
                        and isinstance(ev.get("round"), int)
                        and ev["round"] > sealed):
                    break
            keep = prev
        if raw[:keep] != orig:
            from dopt.utils.metrics import atomic_write_text

            atomic_write_text(path, raw[:keep].decode("utf-8"))

    @staticmethod
    def scan_watermark(path: str | Path) -> int | None:
        """Highest round already streamed to ``path`` (round events
        only), or None when the file is absent/empty — the resume
        watermark source."""
        path = Path(path)
        if not path.exists():
            return None
        best: int | None = None
        for ev in JsonlSink.read(path):
            if ev.get("kind") == "round" and isinstance(ev.get("round"), int):
                best = ev["round"] if best is None else max(best, ev["round"])
        return best


class MemorySink(Sink):
    """Bounded in-memory ring (capacity=None keeps everything)."""

    def __init__(self, capacity: int | None = None):
        self._ring: deque[dict[str, Any]] = deque(maxlen=capacity)

    def emit(self, event: dict[str, Any]) -> None:
        self._ring.append(event)

    @property
    def events(self) -> list[dict[str, Any]]:
        return list(self._ring)

    def __len__(self) -> int:
        return len(self._ring)

    def __iter__(self) -> Iterator[dict[str, Any]]:
        return iter(self.events)


# The Prometheus exposition charset: metric names must match
# [a-zA-Z_:][a-zA-Z0-9_:]*.  Dotted/hyphenated event keys (e.g. a
# producer gauge named "host.gap-pct") must be sanitized or the scrape
# is rejected wholesale by a strict parser.  Colons are legal but
# reserved by convention for recording rules, so we map them away too.
_METRIC_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
_LABEL_ESC_RE = re.compile(r'(["\\\n])')


def _metric_name(name: str) -> str:
    n = _METRIC_NAME_RE.sub("_", str(name))
    # The "dopt_" prefix also guarantees a legal first character, so a
    # leading digit in the event key cannot produce an invalid name.
    return "dopt_" + (n or "metric")


def _label_value(v: str) -> str:
    """Escape per the exposition format: backslash, quote, newline."""
    return _LABEL_ESC_RE.sub(
        lambda m: {"\\": r"\\", '"': r"\"", "\n": r"\n"}[m.group(1)],
        str(v))


class PrometheusSink(Sink):
    """Latest-value snapshot in Prometheus text-exposition format.

    Gauge names are sanitized to the Prometheus charset, every family
    gets ``# HELP``/``# TYPE`` lines, and the producing engine rides
    an ``engine_kind`` LABEL (one metric family per signal, one series
    per engine) instead of being baked into names — the shape scrapers
    can aggregate across."""

    def __init__(self, path: str | Path | None = None):
        self.path = Path(path) if path is not None else None
        # family name -> (help text, {engine_label_or_None: value})
        self._gauges: dict[str, tuple[str, dict[str | None, float]]] = {}
        self._faults: dict[str, int] = {}
        self._alerts: dict[tuple[str, str], int] = {}
        self._compiles: dict[str, int] = {}
        # latency name -> fixed-bucket histogram (dopt.obs.latency);
        # rendered as one proper Prometheus *histogram* family with the
        # latency name as a label.
        self._latency: dict[str, Any] = {}

    def _set(self, name: str, help_: str, engine: str | None,
             value: float) -> None:
        fam = self._gauges.setdefault(_metric_name(name), (help_, {}))
        fam[1][engine] = float(value)

    def emit(self, event: dict[str, Any]) -> None:
        kind = event.get("kind")
        if kind == "round":
            eng = event.get("engine")
            self._set("round", "latest completed training round", eng,
                      float(event["round"]))
            for k, v in event.get("metrics", {}).items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    self._set(k, f"latest value of round metric {k!r}",
                              eng, float(v))
        elif kind == "gauge":
            self._set(event["name"],
                      f"latest value of gauge {event['name']!r}",
                      event.get("engine"), float(event["value"]))
        elif kind == "fault":
            f = str(event["fault"])
            self._faults[f] = self._faults.get(f, 0) + 1
        elif kind == "alert":
            key = (str(event["rule"]), str(event.get("severity", "warn")))
            self._alerts[key] = self._alerts.get(key, 0) + 1
        elif kind == "resource":
            # Device-resource samples (diagnostics="on"): latest HBM/RSS
            # occupancy as gauges, like the round metrics.
            eng = event.get("engine")
            for key in ("live_bytes", "peak_bytes"):
                v = event.get(key)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    self._set(f"hbm_{key}",
                              f"latest device-memory {key} sample "
                              "(resource events)", eng, float(v))
        elif kind == "compile":
            fn = str(event.get("fn", "?"))
            c = event.get("count")
            self._compiles[fn] = self._compiles.get(fn, 0) + (
                int(c) if isinstance(c, int) else 1)
        elif kind == "latency":
            v = event.get("seconds")
            if isinstance(v, (int, float)) and not isinstance(v, bool) \
                    and v >= 0:
                from dopt.obs.latency import LatencyHistogram

                name = str(event.get("name", "?"))
                self._latency.setdefault(
                    name, LatencyHistogram()).observe(float(v))

    def render(self) -> str:
        lines = []
        for name in sorted(self._gauges):
            help_, series = self._gauges[name]
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} gauge")
            for eng in sorted(series, key=lambda e: e or ""):
                label = (f'{{engine_kind="{_label_value(eng)}"}}'
                         if eng else "")
                lines.append(f"{name}{label} {series[eng]!r}")
        if self._faults:
            lines.append("# HELP dopt_faults_total fault-ledger rows "
                         "observed, by ledger kind")
            lines.append("# TYPE dopt_faults_total counter")
            for kind in sorted(self._faults):
                lines.append(
                    f'dopt_faults_total{{kind="{_label_value(kind)}"}} '
                    f'{self._faults[kind]}')
        if self._alerts:
            lines.append("# HELP dopt_alerts_total health-rule alerts "
                         "fired, by rule and severity")
            lines.append("# TYPE dopt_alerts_total counter")
            for rule, sev in sorted(self._alerts):
                lines.append(
                    f'dopt_alerts_total{{rule="{_label_value(rule)}",'
                    f'severity="{_label_value(sev)}"}} '
                    f'{self._alerts[(rule, sev)]}')
        if self._compiles:
            lines.append("# HELP dopt_compiles_total round-function "
                         "(re)trace events observed, by function")
            lines.append("# TYPE dopt_compiles_total counter")
            for fn in sorted(self._compiles):
                lines.append(
                    f'dopt_compiles_total{{fn="{_label_value(fn)}"}} '
                    f'{self._compiles[fn]}')
        if self._latency:
            lines.append("# HELP dopt_latency_seconds SLO latency "
                         "observations (latency events), by name")
            lines.append("# TYPE dopt_latency_seconds histogram")
            for name in sorted(self._latency):
                lines.extend(self._latency[name].exposition(
                    "dopt_latency_seconds",
                    f'name="{_label_value(name)}"'))
        return "\n".join(lines) + "\n"

    def write(self, path: str | Path | None = None) -> Path:
        from dopt.utils.metrics import atomic_write_text

        target = Path(path) if path is not None else self.path
        if target is None:
            raise ValueError("PrometheusSink needs a path to write to")
        return atomic_write_text(target, self.render())

    def close(self) -> None:
        if self.path is not None:
            self.write()
