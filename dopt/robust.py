"""Byzantine-robust aggregation: tolerate workers that lie, not just die.

``dopt.faults`` models workers that *die* (crash/straggle/partition);
this module is the defense against workers that *lie*
(``FaultConfig.corrupt``): a single NaN, sign-flipped or norm-blown
update silently corrupts a plain mean — the steady state for
geo-distributed fleets with flaky or adversarial participants
(FusionLLM, arXiv:2410.12707; "From promise to practice",
arXiv:2410.11998).

Everything here is a jittable pure function over the engines' stacked
[W, ...] pytrees plus a 0/1 participation mask, so robust runs keep all
the execution-path guarantees of the fault subsystem (bit-reproducible,
blocked-exact, resume-exact).  Alive-counts are *data*, never shapes:
the trimmed mean / median / Krum handle a dynamic survivor count via
sorted-position weighting, so one compiled program serves every round.
That counts-are-data discipline is load-bearing beyond this module: it
is what the federated engine's fixed-width compact fault lanes and the
fused-quarantine scan carry (PR 4) reuse to keep every degraded mode
on the blocked execution path — the detection/quarantine layer's
streak state now lives on device as int32 scan carry, with the host
replaying the identical rule post-fetch for the ledger.

* ``finite_lane_mask`` — non-finite screening: a lane with ANY NaN/Inf
  leaf entry is flagged, and the engines treat it as failed for the
  round (always on for the federated mean — the non-finite guard).
* ``clip_to_ball`` — per-lane L2 clip of updates around a reference
  point (norm-bounded contribution).
* ``masked_trimmed_mean`` / ``masked_median`` — coordinate-wise robust
  statistics over the alive lanes (breakdown points trim_frac and 1/2).
* ``krum_aggregate`` — Krum / multi-Krum (Blanchard et al. 2017):
  distance-based selection, tolerates f Byzantine with n > 2f + 2.
* ``clipped_gossip_mix`` — the decentralized defense (He et al.,
  ClippedGossip): clip every neighbor deviation before applying the
  mixing weights; composes with crash/partition matrix repair because
  it consumes the already-repaired matrix as data.
"""

from __future__ import annotations

import functools
import operator

import jax
import jax.numpy as jnp
import numpy as np

AGGREGATORS = ("mean", "trimmed_mean", "median", "krum", "multi_krum")


def validate_robust_config(cfg) -> None:
    """Range/enum checks for ``RobustConfig`` — fail at trainer
    construction with a clean message, not deep inside a trace."""
    if cfg.aggregator not in AGGREGATORS:
        raise ValueError(f"unknown aggregator {cfg.aggregator!r}; one of "
                         f"{AGGREGATORS}")
    if not 0.0 <= cfg.trim_frac < 0.5:
        raise ValueError(
            f"RobustConfig.trim_frac={cfg.trim_frac} must be in [0, 0.5) "
            "(trimming half from each end leaves nothing)")
    if cfg.krum_f < 0:
        raise ValueError("RobustConfig.krum_f must be >= 0")
    if cfg.multi_krum_m < 0:
        raise ValueError("RobustConfig.multi_krum_m must be >= 0")
    if cfg.clip_radius < 0:
        raise ValueError("RobustConfig.clip_radius must be >= 0")
    if cfg.quarantine_after < 0:
        raise ValueError("RobustConfig.quarantine_after must be >= 0")
    if cfg.quarantine_rounds < 1:
        raise ValueError("RobustConfig.quarantine_rounds must be >= 1")


# ---------------------------------------------------------------------
# Screening & clipping
# ---------------------------------------------------------------------

def finite_lane_mask(stacked):
    """[W] float32 flag per lane: 1.0 iff EVERY leaf entry is finite.

    The non-finite screen — one NaN anywhere in a worker's update marks
    the whole lane, because a partially-poisoned update is exactly as
    untrustworthy as a fully-poisoned one."""
    flags = [
        jnp.isfinite(leaf).all(axis=tuple(range(1, leaf.ndim)))
        if leaf.ndim > 1 else jnp.isfinite(leaf)
        for leaf in jax.tree.leaves(stacked)
    ]
    return functools.reduce(operator.and_, flags).astype(jnp.float32)


def lane_sq_norms(stacked):
    """[W] float32 squared L2 norm of each lane across all leaves."""
    parts = [
        (leaf.astype(jnp.float32) ** 2).reshape(leaf.shape[0], -1).sum(axis=1)
        for leaf in jax.tree.leaves(stacked)
    ]
    return functools.reduce(operator.add, parts)


def global_norm_f32(tree):
    """Global L2 norm of a pytree, f32-accumulated."""
    parts = [(leaf.astype(jnp.float32) ** 2).sum()
             for leaf in jax.tree.leaves(tree)]
    return jnp.sqrt(functools.reduce(operator.add, parts))


def clip_to_ball(stacked, center, radius: float):
    """Clip each lane's deviation from ``center`` to an L2 ball of
    ``radius`` (whole-model norm, like gradient clipping): a liar's
    contribution to any aggregate is bounded by the radius however it
    scales its update.  ``radius=0`` is the caller's 'off' sentinel —
    do not call with it."""
    dev = jax.tree.map(lambda x, c: x - c, stacked, center)
    n = jnp.sqrt(jnp.maximum(lane_sq_norms(dev), 1e-24))
    s = jnp.minimum(1.0, radius / n)                      # [W]
    s = jnp.where(jnp.isfinite(s), s, 0.0)

    def leaf(x, c, d):
        sc = s.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
        return (c + sc * d).astype(x.dtype)

    return jax.tree.map(leaf, stacked, center, dev)


# ---------------------------------------------------------------------
# Robust aggregators (stacked [W, ...] + mask -> global tree, no W axis)
# ---------------------------------------------------------------------

def masked_mean(stacked, mask):
    """The reference masked average (``collectives.masked_average``
    without the mesh/wire knobs) — breakdown point 0, kept here so the
    dispatcher covers the full aggregator enum."""
    m = jnp.asarray(mask, jnp.float32)
    denom = jnp.maximum(m.sum(), 1.0)

    def leaf(x):
        mm = m.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
        return (x * mm).sum(axis=0) / denom.astype(x.dtype)

    return jax.tree.map(leaf, stacked)


def masked_trimmed_mean(stacked, mask, trim_frac: float):
    """Coordinate-wise trimmed mean over the alive lanes.

    Per coordinate, the alive values are sorted and the k largest and k
    smallest dropped, k = floor(trim_frac · n_alive) clamped so at
    least one value survives.  Dead lanes are pushed past the alive
    block with a +inf sentinel and position-weighted out, so the
    survivor count is pure data — no dynamic shapes, one compiled
    program for every round."""
    m = jnp.asarray(mask, jnp.float32)
    n_alive = m.sum().astype(jnp.int32)
    k = jnp.minimum((trim_frac * n_alive.astype(jnp.float32))
                    .astype(jnp.int32),
                    jnp.maximum((n_alive - 1) // 2, 0))

    def leaf(x):
        mb = m.astype(bool).reshape((-1,) + (1,) * (x.ndim - 1))
        xs = jnp.sort(jnp.where(mb, x, jnp.asarray(jnp.inf, x.dtype)),
                      axis=0)
        pos = jnp.arange(x.shape[0]).reshape((-1,) + (1,) * (x.ndim - 1))
        sel = (pos >= k) & (pos < n_alive - k)
        kept = jnp.where(sel, xs, jnp.zeros((), x.dtype))  # inf·0-safe
        denom = jnp.maximum(n_alive - 2 * k, 1).astype(x.dtype)
        return kept.sum(axis=0) / denom

    return jax.tree.map(leaf, stacked)


def masked_median(stacked, mask):
    """Coordinate-wise median over the alive lanes (breakdown point
    1/2): sort with dead lanes pushed to the end, average the middle
    one/two alive positions via dynamic indexing (data, not shape)."""
    m = jnp.asarray(mask, jnp.float32)
    n_alive = jnp.maximum(m.sum().astype(jnp.int32), 1)
    lo = (n_alive - 1) // 2
    hi = n_alive // 2

    def leaf(x):
        mb = m.astype(bool).reshape((-1,) + (1,) * (x.ndim - 1))
        xs = jnp.sort(jnp.where(mb, x, jnp.asarray(jnp.inf, x.dtype)),
                      axis=0)
        a = jnp.take(xs, lo, axis=0)
        b = jnp.take(xs, hi, axis=0)
        return ((a + b) / jnp.asarray(2, x.dtype)).astype(x.dtype)

    return jax.tree.map(leaf, stacked)


def krum_scores(stacked, mask, f: int):
    """[W] Krum scores: each alive lane's summed squared distance to its
    n_alive − f − 2 closest alive peers (Blanchard et al. 2017).  Dead
    lanes and non-finite pairs score +inf."""
    leaves = jax.tree.leaves(stacked)
    flat = jnp.concatenate(
        [leaf.reshape(leaf.shape[0], -1).astype(jnp.float32)
         for leaf in leaves], axis=1)
    w = flat.shape[0]
    mb = jnp.asarray(mask, jnp.float32).astype(bool)
    n_alive = jnp.asarray(mask, jnp.float32).sum().astype(jnp.int32)
    gram = flat @ flat.T
    n2 = jnp.diagonal(gram)
    d2 = n2[:, None] + n2[None, :] - 2.0 * gram
    valid = (mb[:, None] & mb[None, :] & ~jnp.eye(w, dtype=bool)
             & jnp.isfinite(d2))
    d2 = jnp.where(valid, jnp.maximum(d2, 0.0), jnp.inf)
    ds = jnp.sort(d2, axis=1)
    c = jnp.clip(n_alive - f - 2, 1, w - 1)
    pos = jnp.arange(w)[None, :]
    score = jnp.where(pos < c, ds, 0.0).sum(axis=1)
    return jnp.where(mb, score, jnp.inf)


def krum_aggregate(stacked, mask, f: int, m: int = 1):
    """Krum (m=1) / multi-Krum selection + average.

    The m best-scored alive lanes are averaged (m=0 derives the
    multi-Krum default n_alive − f, clamped to [1, n_alive]).  Requires
    n > 2f + 2 for the selection guarantee; with fewer alive lanes the
    neighbor count clamps to 1 and the scheme degrades gracefully to
    nearest-neighbor selection."""
    scores = krum_scores(stacked, mask, f)
    mask_f = jnp.asarray(mask, jnp.float32)
    n_alive = jnp.maximum(mask_f.sum().astype(jnp.int32), 1)
    if m > 0:
        m_eff = jnp.minimum(jnp.asarray(m, jnp.int32), n_alive)
    else:
        m_eff = jnp.clip(n_alive - f, 1, n_alive)
    # rank[i] = position of lane i in the score order; +inf (dead)
    # lanes sort last, so rank < m_eff only ever selects alive lanes
    # while m_eff <= n_alive.
    rank = jnp.argsort(jnp.argsort(scores))
    sel = (rank < m_eff).astype(jnp.float32) * mask_f
    # Degenerate rounds (e.g. a lone survivor, whose only "distances"
    # are the +inf sentinels) can leave every alive lane scored +inf —
    # the index-ranked selection then misses them all.  Fall back to
    # the masked mean over the alive lanes rather than averaging an
    # empty set to zeros.
    sel = jnp.where(sel.sum() > 0, sel, mask_f)
    return masked_mean(stacked, sel)


def make_aggregator(name: str, *, trim_frac: float = 0.1, krum_f: int = 1,
                    multi_krum_m: int = 0):
    """Aggregator dispatch for the ``aggregator=`` config knob: returns
    fn(stacked, mask) -> global tree.  'mean' is NOT served here — the
    engines keep their exact pre-robust masked-average call for it, so
    the clean path stays bit-identical."""
    if name == "trimmed_mean":
        return lambda s, m: masked_trimmed_mean(s, m, trim_frac)
    if name == "median":
        return masked_median
    if name == "krum":
        return lambda s, m: krum_aggregate(s, m, krum_f, 1)
    if name == "multi_krum":
        return lambda s, m: krum_aggregate(s, m, krum_f, multi_krum_m)
    raise ValueError(f"unknown robust aggregator {name!r}; one of "
                     f"{AGGREGATORS[1:]}")


# ---------------------------------------------------------------------
# Gossip under Byzantine sends
# ---------------------------------------------------------------------

def byzantine_mix(x, x_send, w_matrix):
    """One UNDEFENDED consensus sweep under Byzantine sends:

        x_i ← W_ii · x_i + Σ_{j≠i} W_ij · x_send_j

    Receivers absorb whatever their neighbors broadcast (this is the
    plain-mean-diverges half of the threat model), but each worker's
    SELF-term reads its true state — a liar lies on the wire, its own
    carried state keeps training honestly, so it can keep lying round
    after round instead of one NaN send becoming a permanent
    self-crash.  Non-finite poison reaches exactly the senders' actual
    out-edges (a plain contraction would NaN every row via 0·NaN).
    With honest sends (x_send == x) this is exactly the dense
    consensus step."""
    wm = jnp.asarray(w_matrix, jnp.float32)
    n = wm.shape[0]
    off = wm * (1.0 - jnp.eye(n))
    diag = jnp.diagonal(wm)
    fin = finite_lane_mask(x_send)
    # Receivers with a weighted edge from a non-finite sender absorb
    # the poison; everyone else contracts over the zeroed column.
    poisoned = (off @ (1.0 - fin)) > 0.0

    def leaf(xr, xs):
        fb = fin.reshape((-1,) + (1,) * (xs.ndim - 1)).astype(bool)
        xs_z = jnp.where(fb, xs, jnp.zeros((), xs.dtype))
        keep = diag.reshape((-1,) + (1,) * (xr.ndim - 1)).astype(jnp.float32)
        y = (keep * xr.astype(jnp.float32)
             + jnp.tensordot(off, xs_z.astype(jnp.float32), axes=[[1], [0]]))
        pb = poisoned.reshape((-1,) + (1,) * (xr.ndim - 1))
        y = jnp.where(pb, jnp.nan, y)
        return y.astype(xr.dtype)

    return jax.tree.map(leaf, x, x_send)


# ---------------------------------------------------------------------
# Clipped gossip (the decentralized defense)
# ---------------------------------------------------------------------

def clipped_gossip_mix(x, x_send, w_matrix, tau: float):
    """One clipped-gossip consensus sweep (He et al., ClippedGossip):

        x_i ← x_i + Σ_{j≠i} W_ij · s_ij · (x_send_j − x_i),
        s_ij = min(1, τ / ‖x_send_j − x_i‖)   (0 for non-finite sends)

    ``x`` is each worker's TRUE state, ``x_send`` what each worker
    broadcast (a Byzantine worker lies on the wire but keeps computing
    honestly — corruption never touches its own carried state).  A liar
    moves an honest worker at most W_ij·τ per round; a NaN/Inf send is
    ignored outright, its mixing weight returning to the receiver's
    self-term.  The rule consumes the round's (possibly crash- or
    partition-repaired) matrix as data, so it composes with
    ``repair_for_dropout`` / ``repair_for_partition`` unchanged.

    Returns ``(mixed, screened)``: the post-sweep states and a [W]
    float flag per SENDER — 1.0 when the send was non-finite or clipped
    by a majority of its neighbors (the quarantine layer's detection
    signal)."""
    leaves_r = jax.tree.leaves(x)
    leaves_s = jax.tree.leaves(x_send)
    flat_r = jnp.concatenate(
        [leaf.reshape(leaf.shape[0], -1).astype(jnp.float32)
         for leaf in leaves_r], axis=1)
    n = flat_r.shape[0]
    fin = finite_lane_mask(x_send)
    # Zero non-finite sends BEFORE any contraction: 0-weighted NaN
    # columns would still poison a tensordot (0 · NaN = NaN).
    x_send_z = jax.tree.map(
        lambda s: jnp.where(
            fin.reshape((-1,) + (1,) * (s.ndim - 1)).astype(bool),
            s, jnp.zeros((), s.dtype)),
        x_send)
    flat_s = jnp.concatenate(
        [leaf.reshape(leaf.shape[0], -1).astype(jnp.float32)
         for leaf in jax.tree.leaves(x_send_z)], axis=1)
    # d2[i, j] = ‖x_send_j − x_i‖² via the gram trick (no [W, W, F]).
    d2 = ((flat_r ** 2).sum(1)[:, None] + (flat_s ** 2).sum(1)[None, :]
          - 2.0 * flat_r @ flat_s.T)
    dist = jnp.sqrt(jnp.maximum(d2, 0.0))
    s = jnp.minimum(1.0, tau / jnp.maximum(dist, 1e-12))
    s = jnp.where(jnp.isfinite(s), s, 0.0)
    eye = jnp.eye(n)
    s = s * (1.0 - eye) * fin[None, :]   # no self-deviation, no poison
    wm = jnp.asarray(w_matrix, jnp.float32)
    c = wm * s                           # trust-scaled off-diag weights
    rowsum = c.sum(axis=1)               # weight actually given away

    def leaf(xr, xs):
        keep = (1.0 - rowsum).reshape(
            (-1,) + (1,) * (xr.ndim - 1)).astype(jnp.float32)
        y = (keep * xr.astype(jnp.float32)
             + jnp.tensordot(c, xs.astype(jnp.float32), axes=[[1], [0]]))
        return y.astype(xr.dtype)

    mixed = jax.tree.map(leaf, x, x_send_z)
    # Sender screening: fraction of its actual (off-diagonal) neighbor
    # edges that clipped it.
    edges = (wm * (1.0 - eye)) > 0.0
    clipped = edges & (s < 1.0)
    frac = (clipped.sum(axis=0)
            / jnp.maximum(edges.sum(axis=0), 1).astype(jnp.float32))
    screened = jnp.maximum((frac > 0.5).astype(jnp.float32), 1.0 - fin)
    return mixed, screened


# ---------------------------------------------------------------------
# Quarantine bookkeeping (shared streak/sentence rule)
# ---------------------------------------------------------------------

def quarantine_step(streak: np.ndarray, until: np.ndarray,
                    ids: np.ndarray, flags: np.ndarray, t: int, *,
                    after: int, rounds: int) -> list[tuple[int, int]]:
    """One host-side detection/quarantine update over identity arrays:
    K consecutive screened participations → benched for ``rounds``; one
    clean participation resets the streak.  The same rule the engines'
    lane-keyed machinery applies (their inline copies are load-bearing
    — each is mirrored by a jnp scan-carry twin and pinned to exact
    ledger row ORDER, so they stay hand-rolled); the client registry's
    population-keyed state (``dopt.population``) calls this directly.

    ``streak``/``until`` are the identity-indexed int arrays (mutated
    in place); ``ids`` the identities that PARTICIPATED this round with
    their 0/1 ``flags``.  ``after`` <= 0 disables sentencing (streaks
    still track).  Returns [(id, until)] for the identities quarantined
    THIS call, so the caller can ledger them."""
    sentenced: list[tuple[int, int]] = []
    for j, wid in enumerate(np.asarray(ids).reshape(-1)):
        wid = int(wid)
        if float(flags[j]) > 0.5:
            streak[wid] += 1
            if after > 0 and streak[wid] >= after:
                until[wid] = int(t) + 1 + int(rounds)
                streak[wid] = 0
                sentenced.append((wid, int(until[wid])))
        else:
            streak[wid] = 0
    return sentenced
