"""Step-level numerics parity: jax engine vs faithful torch oracle.

SURVEY §4 test layer 2: the jax backend must match a faithful CPU
reference implementation step-by-step on fixed seeds.  Both sides start
from the SAME converted parameters and consume the SAME deterministic
batch plan, so every divergence is a numerics bug, not noise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from dopt.data import make_batch_plan, gather_batches
from dopt.data.datasets import make_synthetic
from dopt.engine.local import make_local_update
from dopt.engine.oracle import (
    OracleWorker,
    consensus,
    flax_cnn_params_to_torch,
    nhwc_to_nchw,
    torch_cnn_params_to_flax,
    torch_reference_cnn,
)
from dopt.models import build_model
from dopt.topology import build_mixing_matrices

ATOL = 2e-5


def _setup_model1(seed=0):
    model = build_model("model1", faithful=True)
    params = model.init(jax.random.key(seed), jnp.zeros((1, 28, 28, 1)))["params"]
    tmodel = torch_reference_cnn(1, 28, 512, faithful=True)
    tmodel.load_state_dict(
        {k: v for k, v in flax_cnn_params_to_torch(params, 28).items()}
    )
    return model, params, tmodel


def test_forward_parity_model1():
    model, params, tmodel = _setup_model1()
    x = np.random.default_rng(0).normal(size=(4, 28, 28, 1)).astype(np.float32)
    out_j = np.asarray(model.apply({"params": params}, jnp.asarray(x)))
    with torch.no_grad():
        out_t = tmodel(torch.from_numpy(nhwc_to_nchw(x).copy())).numpy()
    np.testing.assert_allclose(out_j, out_t, atol=ATOL, rtol=1e-4)


def test_param_conversion_roundtrip():
    _, params, tmodel = _setup_model1()
    back = torch_cnn_params_to_flax(tmodel.state_dict(), 28)
    flat_a = jax.tree_util.tree_leaves(params)
    flat_b = jax.tree_util.tree_leaves(back)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(a), b, atol=1e-7)


def _run_both(algorithm, local_ep=2, lr=0.05, momentum=0.5, rho=0.3, seed=3):
    """Train jax and torch sides on identical batches (local_ep epochs of
    4 steps each); return final params."""
    model, params, tmodel = _setup_model1(seed)
    ds = make_synthetic(seed=seed, train_size=64, test_size=8)
    plan = make_batch_plan(np.arange(64)[None, :], batch_size=16,
                           local_ep=local_ep, seed=seed)
    bx, by, bw = gather_batches(ds.train_x, ds.train_y, plan)
    bx, by, bw = bx[0], by[0], bw[0]  # single worker

    theta = params  # global model = init
    # --- jax side
    local = make_local_update(model.apply, lr=lr, momentum=momentum,
                              algorithm=algorithm, rho=rho)
    mom0 = jax.tree.map(jnp.zeros_like, params)
    alpha0 = jax.tree.map(jnp.zeros_like, params)
    if algorithm == "sgd":
        p_j, _, losses_j, _ = jax.jit(local)(params, mom0, bx, by, bw)
    elif algorithm == "fedprox":
        p_j, _, losses_j, _ = jax.jit(local)(params, mom0, bx, by, bw, theta=theta)
    else:
        p_j, _, losses_j, _ = jax.jit(
            lambda p, m, a, b, c, t, al: local(p, m, a, b, c, theta=t, alpha=al)
        )(params, mom0, bx, by, bw, theta, alpha0)

    # --- torch side
    worker = OracleWorker(tmodel, lr=lr, momentum=momentum, rho=rho,
                          algorithm=algorithm)
    theta_t = flax_cnn_params_to_torch(theta, 28)
    loss_t = worker.local_update(nhwc_to_nchw(bx), by, bw,
                                 theta=theta_t if algorithm != "sgd" else None)
    p_t = torch_cnn_params_to_flax(worker.model.state_dict(), 28)
    return p_j, p_t, float(np.mean(np.asarray(losses_j))), loss_t, worker, theta


@pytest.mark.parametrize("algorithm", ["sgd", "fedprox", "fedadmm"])
def test_local_update_parity(algorithm):
    p_j, p_t, loss_j, loss_t, _, _ = _run_both(algorithm)
    assert abs(loss_j - loss_t) < 1e-4, (loss_j, loss_t)
    for (ka, a), (kb, b) in zip(
        sorted(_flat(p_j).items()), sorted(_flat(p_t).items()), strict=True
    ):
        assert ka == kb
        np.testing.assert_allclose(np.asarray(a), b, atol=5e-5, rtol=1e-4,
                                   err_msg=f"{algorithm}: {ka}")


def test_admm_dual_ascent_parity():
    from dopt.optim import admm_dual_ascent
    p_j, p_t, _, _, worker, theta = _run_both("fedadmm")
    # jax dual ascent
    alpha0 = jax.tree.map(jnp.zeros_like, p_j)
    alpha_j = admm_dual_ascent(alpha0, p_j, theta, 0.3)
    # torch dual ascent
    theta_t = flax_cnn_params_to_torch(theta, 28)
    worker.update_duals(theta_t)
    alpha_t = torch_cnn_params_to_flax(
        {k: v for k, v in worker.alpha.items()}, 28)
    for (ka, a), (kb, b) in zip(
        sorted(_flat(alpha_j).items()), sorted(_flat(alpha_t).items()),
        strict=True,
    ):
        assert ka == kb
        np.testing.assert_allclose(np.asarray(a), b, atol=5e-5, rtol=1e-4,
                                   err_msg=ka)


def test_scaffold_local_update_and_control_refresh_parity():
    """SCAFFOLD with NONZERO control variates: the jax gradient edit
    g − c_i + c and the option-II refresh must match the torch oracle."""
    from dopt.optim import scaffold_control_update

    lr, momentum, local_ep = 0.05, 0.5, 2
    model, params, tmodel = _setup_model1(seed=4)
    ds = make_synthetic(seed=4, train_size=64, test_size=8)
    plan = make_batch_plan(np.arange(64)[None, :], batch_size=16,
                           local_ep=local_ep, seed=4)
    bx, by, bw = gather_batches(ds.train_x, ds.train_y, plan)
    bx, by, bw = bx[0], by[0], bw[0]
    steps = bx.shape[0]

    rng = np.random.default_rng(17)
    c_g = jax.tree.map(
        lambda x: jnp.asarray(rng.normal(0, 0.01, x.shape), jnp.float32),
        params)
    c_i = jax.tree.map(
        lambda x: jnp.asarray(rng.normal(0, 0.01, x.shape), jnp.float32),
        params)

    # --- jax side
    local = make_local_update(model.apply, lr=lr, momentum=momentum,
                              algorithm="scaffold")
    mom0 = jax.tree.map(jnp.zeros_like, params)
    p_j, _, _, _ = jax.jit(
        lambda p, m, a, b, c, t, al: local(p, m, a, b, c, theta=t, alpha=al)
    )(params, mom0, bx, by, bw, c_g, c_i)
    ci_new_j = scaffold_control_update(c_i, c_g, params, p_j, lr=lr,
                                       num_steps=steps)

    # --- torch side (same controls, converted through the param mapper)
    worker = OracleWorker(tmodel, lr=lr, momentum=momentum,
                          algorithm="scaffold")
    worker.control = {k: v.clone() for k, v in
                      flax_cnn_params_to_torch(c_i, 28).items()}
    cg_t = flax_cnn_params_to_torch(c_g, 28)
    theta_t = flax_cnn_params_to_torch(params, 28)
    worker.local_update(nhwc_to_nchw(bx), by, bw, c_global=cg_t)
    worker.update_controls(theta_t, cg_t, lr, steps)

    p_t = torch_cnn_params_to_flax(worker.model.state_dict(), 28)
    for (ka, a), (kb, b) in zip(sorted(_flat(p_j).items()),
                                sorted(_flat(p_t).items()), strict=True):
        assert ka == kb
        np.testing.assert_allclose(np.asarray(a), b, atol=5e-5, rtol=1e-4,
                                   err_msg=f"scaffold params: {ka}")
    ci_t = torch_cnn_params_to_flax(
        {k: v for k, v in worker.control.items()}, 28)
    for (ka, a), (kb, b) in zip(sorted(_flat(ci_new_j).items()),
                                sorted(_flat(ci_t).items()), strict=True):
        assert ka == kb
        np.testing.assert_allclose(np.asarray(a), b, atol=5e-4, rtol=1e-3,
                                   err_msg=f"scaffold control: {ka}")


def test_consensus_parity():
    # Weighted state-dict sum vs mix_dense on the stacked pytree.
    from dopt.parallel.collectives import mix_dense
    n = 4
    mm = build_mixing_matrices("circle", "stochastic", n, seed=5)
    w = mm.matrices[0]
    models = []
    flax_stack = []
    for i in range(n):
        model, params, tmodel = _setup_model1(seed=i)
        models.append(tmodel)
        flax_stack.append(params)
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *flax_stack)
    mixed_j = jax.jit(mix_dense)(stacked, w)

    for i in range(n):
        ni = [(w[i, j], models[j].state_dict()) for j in range(n) if w[i, j] > 0]
        mixed_t = consensus([(float(a), {k: v.float() for k, v in st.items()})
                             for a, st in ni])
        back = torch_cnn_params_to_flax(mixed_t, 28)
        for (ka, a), (kb, b) in zip(
            sorted(_flat(jax.tree.map(lambda x: x[i], mixed_j)).items()),
            sorted(_flat(back).items()), strict=True,
        ):
            assert ka == kb
            np.testing.assert_allclose(np.asarray(a), b, atol=5e-6, rtol=1e-5,
                                       err_msg=f"worker {i}: {ka}")


def _flat(tree):
    from dopt.engine.oracle import _flatten2
    return _flatten2(tree)


def test_full_gossip_round_parity_vs_trainer():
    """Two D-SGD rounds: GossipTrainer vs a sequential oracle loop
    replicating the reference's two-phase synchronous schedule
    (simulators.py:147-165) on identical batch plans."""
    from dopt.config import (DataConfig, ExperimentConfig, GossipConfig,
                             ModelConfig, OptimizerConfig)
    from dopt.data import partition
    from dopt.engine import GossipTrainer

    n, seed = 4, 11
    cfg = ExperimentConfig(
        name="parity", seed=seed,
        data=DataConfig(dataset="synthetic", num_users=n, iid=False, shards=2,
                        synthetic_train_size=128, synthetic_test_size=32),
        model=ModelConfig(model="model1", input_shape=(28, 28, 1), faithful=True),
        optim=OptimizerConfig(lr=0.05, momentum=0.5),
        gossip=GossipConfig(algorithm="dsgd", topology="circle",
                            mode="stochastic", rounds=2, local_ep=1,
                            local_bs=16),
    )
    tr = GossipTrainer(cfg)
    init_params = jax.device_get(jax.tree.map(lambda x: x[0], tr.params))
    mixing = tr.mixing
    index_matrix = tr.index_matrix
    ds = tr.dataset
    tr.run(rounds=2)

    # --- oracle side: same init, same mixing matrices, same batch plans
    workers = []
    for i in range(n):
        tmodel = torch_reference_cnn(1, 28, 512, faithful=True)
        tmodel.load_state_dict(flax_cnn_params_to_torch(init_params, 28))
        workers.append(OracleWorker(tmodel, lr=0.05, momentum=0.5))
    for t in range(2):
        w = mixing.for_round(t)
        states = [wk.state() for wk in workers]
        new = []
        for i in range(n):
            ni = [(float(w[i, j]), states[j]) for j in range(n) if w[i, j] > 0]
            new.append(consensus(ni))
        for wk, st in zip(workers, new):
            wk.load(st)
        plan = make_batch_plan(index_matrix, batch_size=16, local_ep=1,
                               seed=seed, round_idx=t)
        bx, by, bw = gather_batches(ds.train_x, ds.train_y, plan)
        for i, wk in enumerate(workers):
            wk.local_update(nhwc_to_nchw(bx[i]), by[i], bw[i])

    final_j = jax.device_get(tr.params)
    for i in range(n):
        p_t = torch_cnn_params_to_flax(workers[i].model.state_dict(), 28)
        p_j = jax.tree.map(lambda x: x[i], final_j)
        for (ka, a), (kb, b) in zip(sorted(_flat(p_j).items()),
                                    sorted(_flat(p_t).items()), strict=True):
            assert ka == kb
            np.testing.assert_allclose(
                np.asarray(a), b, atol=2e-4, rtol=1e-3,
                err_msg=f"round-trajectory divergence worker {i}: {ka}")


def test_trajectory_script_smoke():
    """The oracle-trajectory artifact generator stays runnable and its
    round-1 divergence stays at float-noise scale."""
    import importlib.util
    from pathlib import Path

    root = Path(__file__).resolve().parent.parent
    spec = importlib.util.spec_from_file_location(
        "oracle_trajectory", root / "scripts" / "oracle_trajectory.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    g = mod.gossip_trajectory("circle", "stochastic", 1)
    assert g["rel_l2_per_round"][0] < 0.01
    f = mod.federated_trajectory("fedavg", 1)
    assert f["rel_l2_per_round"][0] < 0.01


def test_holdout_epoch_parity():
    """The reference's epoch-structured local update (90/10 holdout,
    per-epoch local-val eval + history row — P2 clients.py:19-57)
    produces the SAME per-epoch rows and final params on the jax engine
    path (make_local_update_epochs) and the torch oracle
    (OracleWorker.local_update_epochs), on identical batch plans."""
    from dopt.data import holdout_split, stacked_eval_batches
    from dopt.engine.local import make_local_update_epochs

    seed, lr, momentum, local_ep, bs = 5, 0.05, 0.5, 3, 16
    model, params, tmodel = _setup_model1(seed)
    ds = make_synthetic(seed=seed, train_size=96, test_size=8)
    index_matrix = np.arange(96)[None, :]
    train_m, val_m = holdout_split(index_matrix, fraction=0.1, mode="random",
                                   seed=seed)
    assert val_m.shape[1] == 9 and train_m.shape[1] == 87
    plan = make_batch_plan(train_m, batch_size=bs, local_ep=local_ep,
                           seed=seed)
    vi, vw = stacked_eval_batches(val_m, batch_size=bs)

    # --- jax side (single worker, epoch-major plan)
    fn = make_local_update_epochs(model.apply, lr=lr, momentum=momentum)
    e, sp = local_ep, plan.idx.shape[1] // local_ep
    idx_e = plan.idx[0].reshape(e, sp, bs)
    bw_e = plan.weight[0].reshape(e, sp, bs)
    mom0 = jax.tree.map(jnp.zeros_like, params)
    p_j, _, em = jax.jit(fn)(params, mom0, idx_e, bw_e,
                             jnp.asarray(ds.train_x), jnp.asarray(ds.train_y),
                             vi[0], vw[0])

    # --- torch side
    worker = OracleWorker(tmodel, lr=lr, momentum=momentum)
    bx, by, bwt = gather_batches(ds.train_x, ds.train_y, plan)
    bx = nhwc_to_nchw(bx[0]).reshape(e, sp, bs, 1, 28, 28)
    by_ = by[0].reshape(e, sp, bs)
    bw_ = bwt[0].reshape(e, sp, bs)
    vx = nhwc_to_nchw(ds.train_x[vi[0]])
    rows = worker.local_update_epochs(bx, by_, bw_, vx, ds.train_y[vi[0]],
                                      vw[0], val_flavor="mean")

    # Per-epoch tolerances widen with epoch: the faithful double-softmax
    # objective is chaotic, so the ~1e-5 single-step jax/torch numerics
    # gap compounds across epochs (step-level numerics are pinned tight
    # by test_local_update_parity; THIS test pins the epoch structure —
    # holdout usage, per-epoch rows, val flavours).
    for ep in range(local_ep):
        r = rows[ep]
        tol = 3e-4 * 10 ** ep
        assert abs(float(em["train_loss"][ep]) - r["train_loss"]) < tol
        assert abs(float(em["train_acc"][ep]) - r["train_acc"]) < 0.02
        assert abs(float(em["val_acc"][ep]) - r["val_acc"]) < 0.15
        assert abs(float(em["val_loss_mean"][ep]) - r["val_loss"]) < tol
    p_t = torch_cnn_params_to_flax(worker.model.state_dict(), 28)
    for (ka, a), (kb, b) in zip(
        sorted(_flat(p_j).items()), sorted(_flat(p_t).items()), strict=True
    ):
        assert ka == kb
        np.testing.assert_allclose(np.asarray(a), b, atol=5e-3, rtol=1e-2)
