"""Worker-axis collectives: gossip mixing and federated aggregation.

This module is the TPU-native replacement for the reference's implicit
"communication layer" (SURVEY §2.4): the server handing state_dict
copies to clients (``servers.py:59-64``) and ``Simulator.Neighbors``
passing live state_dict references between peers
(``simulators.py:91-97`` + ``clients.py:61-69``).

Two execution paths for the consensus step  x_i ← Σ_j W_ij x_j :

* ``mix_dense`` — one ``tensordot`` of the [n, n] mixing matrix against
  the stacked [W, ...] pytree, written in the global view.  Under jit
  with the worker axis sharded, XLA's SPMD partitioner lowers this to
  ``all_gather`` over ICI + a local contraction — the right choice for
  complete/random/arbitrary graphs (the matrix is data, not code).
* ``mix_shifts_shardmap`` — explicit ``shard_map`` + ``lax.ppermute``
  per circulant diagonal of W (from ``dopt.topology.shift_decomposition``).
  For banded topologies (ring, dynamic single-edge) this moves only the
  neighbor shards that are actually needed: O(k·|θ|) bytes over ICI
  instead of O(n·|θ|) for the all_gather, where k = number of nonzero
  diagonals (ring: 2).

``masked_average`` is the federated path: uniform state averaging over
the sampled-client set (``servers.py:42-48``) as one weighted
reduce-sum over the worker axis, with partial participation as a 0/1
mask instead of Python-side client selection.
"""

from __future__ import annotations

import dataclasses
import re

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from dopt.parallel.mesh import WORKER_AXIS, compat_shard_map


def mix_dense(stacked, w_matrix, mesh: Mesh | None = None,
              comm_dtype=None):
    """x_i ← Σ_j W_ij x_j for every leaf of a stacked [W, ...] pytree.

    Global-view formulation; XLA inserts the collectives when the worker
    axis is sharded.  ``w_matrix`` may be [n, n] or a scalar-weighted
    stack already selected for the round.  Pass ``mesh`` to pin the
    output back onto the worker axis (XLA otherwise may choose to
    replicate the contraction result).

    ``comm_dtype`` (e.g. ``jnp.bfloat16``) is WIRE-ONLY compression:
    shards are narrowed just for the cross-device gather (halving
    ICI/DCN bytes at bf16) and everything else stays exact — the mixing
    matrix remains float32 (bf16 would break row-stochasticity by
    ~1e-3/row and compound over rounds) and the accumulation runs in
    float32.  Requires ``mesh`` (without a mesh nothing crosses a wire,
    so there is nothing to compress — it raises to avoid a silent
    no-op)."""
    w = jnp.asarray(w_matrix, dtype=jnp.float32)
    if comm_dtype is not None:
        if mesh is None:
            raise ValueError("comm_dtype compression requires a mesh")
        return _mix_dense_compressed(stacked, w, mesh, comm_dtype)

    def mix_leaf(x):
        y = jnp.tensordot(w.astype(x.dtype), x, axes=[[1], [0]])
        y = y.astype(x.dtype)
        if mesh is not None:
            from dopt.parallel.mesh import worker_sharding

            y = jax.lax.with_sharding_constraint(y, worker_sharding(mesh))
        return y

    # dopt_mix scope: phase attribution for the profiler's
    # conv/comm/update split (dopt.utils.profiling.classify_phase).
    with jax.named_scope("dopt_mix"):
        return jax.tree.map(mix_leaf, stacked)


def _mix_dense_compressed(stacked, w, mesh: Mesh, comm_dtype):
    """Wire-only compressed dense mixing as an explicit shard_map: each
    device all-gathers the OTHER workers' shards at ``comm_dtype`` (the
    only bytes that cross ICI/DCN), then contracts its f32 mixing-matrix
    rows against the f32-upcast gather — exact W, f32 accumulation,
    narrow wire."""
    from dopt.parallel.mesh import worker_axes

    ax = worker_axes(mesh)

    def per_device(wr, xl):
        # wr: [W/D, W] f32 rows; xl: [W/D, ...] local worker shard.
        xg = jax.lax.all_gather(xl.astype(comm_dtype), ax, axis=0,
                                tiled=True)
        y = jnp.tensordot(wr, xg.astype(jnp.float32), axes=[[1], [0]])
        return y.astype(xl.dtype)

    def mix_leaf(x):
        fn = compat_shard_map(per_device, mesh=mesh,
                              in_specs=(P(ax, None), P(ax)),
                              out_specs=P(ax))
        return fn(w, x)

    return jax.tree.map(mix_leaf, stacked)


def _shift_plan(shift_ids, lanes: int, num_devices: int):
    """Static routing plan for the folded shift path.

    Returns ``(plan, ship)`` where ``plan[k] = (q0, q1, r)`` decomposes
    global shift ``shift_ids[k]`` into its device rotations and lane
    offset, and ``ship[q]`` is the sorted list of source lanes that must
    actually travel for nonzero rotation q — the union over consuming
    shifts, NOT the whole lane block.  A straddling ring shift (r ≠ 0)
    needs only ``lanes − r`` lanes from rotation q and ``r`` from q+1,
    so e.g. the 32-worker ring on 8 devices ships 2 lane-shards per
    device per round instead of 8 full blocks.

    Contiguity invariant used by ``mix_shifts``: every consumer needs a
    contiguous lane range [a, b), and since ship[q] ⊇ [a, b) is a sorted
    list of distinct lanes, that range occupies contiguous positions in
    the shipped block.
    """
    plan: list[tuple[int, int, int]] = []
    need: dict[int, set[int]] = {}
    for s in shift_ids:
        q, r = divmod(int(s), lanes)
        q0, q1 = q % num_devices, (q + 1) % num_devices
        plan.append((q0, q1, r))
        if r == 0:
            if q0 != 0:
                need.setdefault(q0, set()).update(range(lanes))
        else:
            if q0 != 0:
                need.setdefault(q0, set()).update(range(r, lanes))
            if q1 != 0:
                need.setdefault(q1, set()).update(range(r))
    ship = {q: sorted(v) for q, v in need.items()}
    return plan, ship


def device_rotations(shift_ids, lanes: int, num_devices: int) -> tuple[int, ...]:
    """The nonzero device-level ring rotations (one ``lax.ppermute``
    each) the folded shift path needs for a global circulant shift set:
    shift s = q·lanes + r touches rotation q (and q+1 when r ≠ 0)."""
    _, ship = _shift_plan(shift_ids, lanes, num_devices)
    return tuple(sorted(ship))


def shift_comm_lanes(shift_ids, lanes: int, num_devices: int) -> int:
    """Total worker-lane shards each device ships per ``mix_shifts``
    call — the shift path's ICI byte cost in units of |θ|-sized lanes,
    which the engine's 'auto' heuristic compares against the dense
    all_gather's (n − lanes) remote lanes per device."""
    _, ship = _shift_plan(shift_ids, lanes, num_devices)
    return sum(len(v) for v in ship.values())


def mix_shifts(stacked, shift_ids, coeff_table, mesh: Mesh, comm_dtype=None):
    """Explicit ICI path: x_i ← Σ_s coeff_s[i] · x_{(i+s) mod n}.

    ``shift_ids`` is the STATIC tuple of circulant shifts (compiled into
    the program); ``coeff_table`` is the per-round [k, n] float32
    coefficient DATA (``dopt.topology.coeffs_for_matrix``), so
    time-varying schedules and dropout-repaired matrices reuse one
    compiled step.

    Workers fold onto devices in L = n / mesh.size contiguous lanes
    (worker i = device i//L, lane i%L — the ``shard_worker_tree``
    layout).  The [n, n] circulant then decomposes into DEVICE-level
    ring rotations plus a static lane slice: global shift s = q·L + r
    needs lanes r..L-1 from device d+q and, when r ≠ 0, lanes 0..r-1
    from device d+q+1.  Each nonzero rotation is ONE ``lax.ppermute``
    carrying only the union of lanes its consumers need (``_shift_plan``)
    — a folded ring ships 2 single-lane shards per device per round
    (e.g. 32 workers on a v5e-8, SURVEY §7's "cores=8, workers_per_core=4"
    plan) instead of the dense path's (n − L)-lane all_gather.  L = 1
    degenerates to the classic one-rotation-per-shift ring schedule.
    """
    D = mesh.size
    shift_ids = tuple(int(s) for s in shift_ids)
    coeff_table = jnp.asarray(coeff_table, dtype=jnp.float32)
    n = coeff_table.shape[1]
    if n % D:
        raise ValueError(f"{n} workers do not fold onto {D} devices evenly")
    L = n // D
    plan, ship = _shift_plan(shift_ids, L, D)
    # Shipped-block bookkeeping: lane a of rotation q sits at position
    # pos[q][a] in that rotation's payload; contiguous source ranges
    # stay contiguous (see _shift_plan docstring).
    pos = {q: {lane: i for i, lane in enumerate(lanes_q)}
           for q, lanes_q in ship.items()}

    def per_device(coeffs, x):
        # x: [L, ...] local lane block; coeffs: [k, L] this block's weights.
        # comm_dtype narrows the payload only for the ppermute hops (the
        # bytes on the wire); lane values that never cross a wire (the
        # q == 0 contributions, incl. the shift-0 self term) stay exact,
        # and accumulation stays at the leaf dtype.
        xc = x.astype(comm_dtype) if comm_dtype is not None else x
        blocks = {}
        for q, lanes_q in ship.items():
            payload = xc if len(lanes_q) == L else xc[np.asarray(lanes_q)]
            perm = [((d + q) % D, d) for d in range(D)]
            blocks[q] = jax.lax.ppermute(payload, WORKER_AXIS,
                                         perm).astype(x.dtype)

        def part(q, a, b):
            """Lanes [a, b) sourced from rotation q (0 = local/exact)."""
            if q == 0:
                return x[a:b]
            p = pos[q][a]
            return blocks[q][p:p + (b - a)]

        acc = jnp.zeros_like(x)
        for k, (q0, q1, r) in enumerate(plan):
            if r == 0:
                contrib = part(q0, 0, L)
            else:
                contrib = jnp.concatenate([part(q0, r, L), part(q1, 0, r)],
                                          axis=0)
            c = coeffs[k].reshape((L,) + (1,) * (x.ndim - 1)).astype(x.dtype)
            acc = acc + c * contrib
        return acc

    coeff_specs = P(None, WORKER_AXIS)  # [k, n] -> coeffs sharded on worker axis

    def mix_leaf(x):
        fn = compat_shard_map(
            per_device,
            mesh=mesh,
            in_specs=(coeff_specs, P(WORKER_AXIS)),
            out_specs=P(WORKER_AXIS),
        )
        return fn(coeff_table, x)

    with jax.named_scope("dopt_mix"):
        return jax.tree.map(mix_leaf, stacked)


def mix_shifts_shardmap(stacked, shifts, mesh: Mesh, comm_dtype=None):
    """``mix_shifts`` with the shifts-and-coefficients pairing of
    ``dopt.topology.shift_decomposition`` (``[(shift, coeffs[n]), ...]``)
    — the single-matrix convenience form."""
    return mix_shifts(stacked, [s for s, _ in shifts],
                      jnp.asarray([c for _, c in shifts], dtype=jnp.float32),
                      mesh, comm_dtype)


def where_mask(mask, a, b):
    """Per-worker select over stacked pytrees: mask[i] ? a_i : b_i.
    Used for client-sampling (federated) and worker-dropout (gossip)
    participation masks."""
    def sel(x, y):
        m = mask.reshape((-1,) + (1,) * (x.ndim - 1)).astype(bool)
        return jnp.where(m, x, y)
    return jax.tree.map(sel, a, b)


def masked_average(stacked, mask, mesh: Mesh | None = None, comm_dtype=None):
    """Uniform average of the masked workers' states, replicated back to
    every worker: theta ← Σ_i m_i x_i / Σ_i m_i  (reference
    ``average_weights``, servers.py:42-48, with client sampling as data).

    Returns a pytree WITHOUT the worker axis (the global model).

    ``comm_dtype`` (requires ``mesh``) is wire-only compression of the
    aggregation, mirroring ``mix_dense``: each device reduces its local
    lanes at full precision, only the per-device PARTIAL sums cross the
    wire at the narrow dtype (one psum), and the final divide runs at
    the leaf dtype."""
    m = jnp.asarray(mask, dtype=jnp.float32)
    denom = jnp.maximum(m.sum(), 1.0)
    if comm_dtype is not None:
        if mesh is None:
            raise ValueError("comm_dtype compression requires a mesh")
        return _masked_average_compressed(stacked, m, denom, mesh, comm_dtype)

    def avg_leaf(x):
        mm = m.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
        return (x * mm).sum(axis=0) / denom.astype(x.dtype)

    with jax.named_scope("dopt_mix"):
        return jax.tree.map(avg_leaf, stacked)


def mean_weight_matrix(mask):
    """The masked-mean reduce as a [W, W] contraction matrix: every row
    is mask / max(Σ mask, 1), so W_mean @ X computes ``masked_average``
    broadcast back over the worker axis (each output row is the same
    global mean).  An all-dead mask yields the zero matrix — the
    contraction contributes nothing and the caller's passthrough term
    keeps theta.  Feeds the fused epilogue (``dopt.ops.fused_mix_update``
    under ``FederatedConfig.fused_update="on"``), which needs the mean
    expressed as a mixing-matrix contraction over the flat buckets."""
    m = jnp.asarray(mask, dtype=jnp.float32).reshape(-1)
    denom = jnp.maximum(m.sum(), 1.0)
    return jnp.broadcast_to(m / denom, (m.shape[0], m.shape[0]))


def _masked_average_compressed(stacked, m, denom, mesh: Mesh, comm_dtype):
    """Wire-only compressed federated reduce: each device sums its local
    lanes at full precision, the narrow PARTIAL sums are all-gathered
    (the only bytes on the wire), and the cross-device accumulation runs
    in float32 locally — so exactly one quantization per partial, never
    a narrow-dtype summation chain that would grow error with device
    count (mirrors ``_mix_dense_compressed``'s semantics)."""
    from dopt.parallel.mesh import worker_axes

    ax = worker_axes(mesh)

    def avg_leaf(x):
        def per_device(mask_l, x_l):
            mm = mask_l.reshape((-1,) + (1,) * (x_l.ndim - 1))
            part = (x_l.astype(jnp.float32) * mm).sum(axis=0)
            parts = jax.lax.all_gather(part.astype(comm_dtype), ax)
            tot = parts.astype(jnp.float32).sum(axis=0)
            return (tot / denom).astype(x_l.dtype)

        # all_gather+local-sum yields a value that IS replicated but
        # can't be statically proven so (unlike psum); skip the static
        # varying-axes check for this one collective.
        fn = compat_shard_map(per_device, mesh=mesh,
                              in_specs=(P(ax), P(ax)), out_specs=P(),
                              check=False)
        return fn(m, x)

    return jax.tree.map(avg_leaf, stacked)


# ---------------------------------------------------------------------
# Sharded weight-update / consensus hot path (update_sharding="scatter")
# ---------------------------------------------------------------------
# "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
# Training" (Xu et al., arXiv:2004.13336) applied to the consensus
# round: instead of every lane's device redundantly materialising and
# post-processing the FULL |θ| during the mixing/aggregation phase, the
# parameter tree is flattened once into size-bounded f32/bf16 BUCKETS
# ([W, Fb] slabs), the cross-worker contraction runs as per-device
# partial sums + ``psum_scatter`` (each device produces only the 1/D
# shard it owns), the remaining update math runs on that shard, and ONE
# all-gather restores the full view.  Issuing the collectives bucket by
# bucket is what lets XLA's latency-hiding scheduler overlap bucket b's
# wire time with bucket b+1's compute
# (``dopt.parallel.mesh.enable_latency_hiding_scheduler``).


@dataclasses.dataclass(frozen=True)
class UpdateShardSpec:
    """Static flattening/bucketing plan for a stacked [W, ...] pytree.

    Built once at trainer construction (``make_update_shard_spec``);
    everything here is static python data so the bucket slicing compiles
    into the round program.  ``bounds`` are fold-aligned offsets into
    the zero-padded flat axis — every bucket's length divides evenly by
    ``fold`` (the mesh device count), which is what lets
    ``psum_scatter``/``all_gather`` split each bucket exactly."""

    treedef: object
    shapes: tuple[tuple[int, ...], ...]   # per-leaf shapes sans worker axis
    sizes: tuple[int, ...]
    dtype: object
    fold: int
    flat: int      # true flattened per-worker element count
    padded: int    # flat rounded up to a fold multiple
    bounds: tuple[int, ...]

    @property
    def num_buckets(self) -> int:
        return len(self.bounds) - 1


def make_update_shard_spec(tree, *, fold: int,
                           bucket_bytes: int = 4 << 20) -> UpdateShardSpec:
    """Plan the flat bucketing of ``tree`` (a stacked [W, ...] pytree).

    ``fold`` is the shard count (mesh size) every bucket must divide by;
    ``bucket_bytes`` bounds each bucket's per-worker payload so the
    mixing collectives are issued as a pipeline of comparable chunks
    rather than one monolithic transfer.  All leaves must share one
    dtype (the engines store params/momentum at a single param_dtype) —
    mixed dtypes would force a lossy common cast, so they are rejected."""
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        raise ValueError("cannot bucket an empty pytree")
    dtypes = {jnp.dtype(x.dtype) for x in leaves}
    if len(dtypes) != 1:
        raise ValueError(
            f"update sharding needs a uniform leaf dtype, got {dtypes}")
    dtype = dtypes.pop()
    shapes = tuple(tuple(x.shape[1:]) for x in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    flat = int(sum(sizes))
    fold = max(int(fold), 1)
    padded = -(-flat // fold) * fold
    per_elem = dtype.itemsize
    step = max(int(bucket_bytes) // per_elem // fold, 1) * fold
    bounds = tuple(range(0, padded, step)) + (padded,)
    return UpdateShardSpec(treedef=treedef, shapes=shapes, sizes=sizes,
                           dtype=dtype, fold=fold, flat=flat,
                           padded=padded, bounds=bounds)


def stacked_to_buckets(tree, spec: UpdateShardSpec) -> list:
    """Flatten a stacked [W, ...] pytree into the spec's [W, Fb] bucket
    slabs (zero-padded tail).  The inverse is ``buckets_to_stacked`` —
    the round trip is bit-exact (pure reshape/concat/slice)."""
    leaves = jax.tree.leaves(tree)
    w = leaves[0].shape[0]
    flat = jnp.concatenate([x.reshape(w, -1) for x in leaves], axis=1)
    if spec.padded != spec.flat:
        flat = jnp.pad(flat, ((0, 0), (0, spec.padded - spec.flat)))
    return [flat[:, a:b] for a, b in zip(spec.bounds, spec.bounds[1:])]


def _flat_to_tree(flat, spec: UpdateShardSpec, lead: tuple[int, ...]):
    out, off = [], 0
    for shape, size in zip(spec.shapes, spec.sizes):
        out.append(flat[..., off:off + size].reshape(lead + shape))
        off += size
    return spec.treedef.unflatten(out)


def buckets_to_stacked(buckets: list, spec: UpdateShardSpec):
    flat = jnp.concatenate(buckets, axis=1)[:, :spec.flat]
    return _flat_to_tree(flat, spec, (flat.shape[0],))


def buckets_to_tree(buckets: list, spec: UpdateShardSpec):
    """Single (no worker axis) variant: [Fb] buckets → the θ tree."""
    flat = jnp.concatenate(buckets, axis=0)[:spec.flat]
    return _flat_to_tree(flat, spec, ())


def _require_flat_mesh(mesh: Mesh | None, what: str) -> str:
    if mesh is None:
        raise ValueError(f"{what} requires a mesh")
    if len(mesh.axis_names) != 1:
        raise ValueError(
            f"{what} runs psum_scatter over ONE worker axis; hybrid "
            f"(hosts × ici) meshes are not supported — got {mesh.shape}")
    return mesh.axis_names[0]


def mix_dense_scatter(buckets, w_matrix, mesh: Mesh, comm_dtype=None):
    """Reduce-scatter formulation of ``mix_dense`` over flat buckets:
    each device contracts the mixing matrix's columns for ITS lanes
    against its local [L, Fb] slab (a partial sum of the true output for
    every worker), and one ``psum_scatter`` both completes the sum and
    hands each device exactly its own lanes' mixed rows — no device
    ever materialises the [n, Fb] gathered fleet state, and the
    per-bucket issue order gives the latency-hiding scheduler chunks to
    overlap.

    Numerics: the mixing matrix and the accumulation stay FLOAT32
    regardless of the leaf dtype.  For f32 trees that differs from
    ``mix_dense`` only by summation association (the allclose-pinned
    parity contract); for bf16 trees it is strictly MORE precise than
    the dense path, which casts the matrix to bf16 and contracts at the
    leaf dtype — so bf16 scatter-vs-dense deltas include that matrix
    quantization (~1e-3/row), not just reassociation.

    ``comm_dtype`` narrows the PARTIAL sums for the ``psum_scatter``
    hop (the only bytes on the wire) and upcasts on arrival.  Unlike
    the dense path's gather-then-sum, the reduce-scatter accumulates AT
    the wire dtype across devices — one quantization per partial plus a
    narrow-dtype add chain of depth log(D), the documented cost of
    halving the scatter path's wire bytes."""
    ax = _require_flat_mesh(mesh, "update_sharding='scatter'")
    w = jnp.asarray(w_matrix, dtype=jnp.float32)

    def per_device(w_cols, x):
        # w_cols: [n, L] — this device's lanes' columns of W;
        # x: [L, Fb] local lane slab.
        part = jnp.tensordot(w_cols, x.astype(jnp.float32),
                             axes=[[1], [0]])          # [n, Fb] partial
        if comm_dtype is not None:
            part = part.astype(comm_dtype)
        own = jax.lax.psum_scatter(part, ax, scatter_dimension=0,
                                   tiled=True)         # [L, Fb] mine
        return own.astype(x.dtype)

    fn = compat_shard_map(per_device, mesh=mesh,
                          in_specs=(P(None, ax), P(ax)),
                          out_specs=P(ax))
    with jax.named_scope("dopt_mix"):
        return [fn(w, b) for b in buckets]


def mix_update_scatter(stacked, arg, mesh: Mesh, spec: UpdateShardSpec,
                       shift_ids=None, comm_dtype=None):
    """The engine-facing scatter-mode consensus step: flatten the
    stacked tree into the spec's buckets, mix every bucket (dense
    reduce-scatter, or the sharded circulant contraction when the
    schedule decomposed into shifts — ``mix_shifts`` over flat buckets
    ships the SAME lane unions per rotation, just as size-bounded flat
    chunks instead of per-leaf payloads), and restore the tree.

    ``comm_dtype`` narrows the wire hop of whichever collective runs:
    the ppermute payloads on the shift path, the reduce-scatter
    partials on the dense path — the same one-knob wire compression the
    plain (unsharded) collectives expose."""
    buckets = stacked_to_buckets(stacked, spec)
    if shift_ids is not None:
        with jax.named_scope("dopt_mix"):
            mixed = mix_shifts(buckets, shift_ids, arg, mesh, comm_dtype)
    else:
        mixed = mix_dense_scatter(buckets, arg, mesh, comm_dtype)
    return buckets_to_stacked(mixed, spec)


def masked_average_scatter(stacked, mask, mesh: Mesh,
                           spec: UpdateShardSpec, denom=None,
                           comm_dtype=None):
    """Sharded-update formulation of ``masked_average`` (Xu et al.,
    arXiv:2004.13336): each device reduces its local lanes' masked
    partial sum per bucket, ``psum_scatter`` leaves each device owning
    a 1/D shard of the flat sum, the aggregation update (the divide)
    runs on that shard only, and ONE tiled all-gather re-forms the
    replicated θ — instead of every device redundantly computing the
    full |θ| average.  Returns the unstacked θ tree.

    ``denom`` (optional traced scalar) overrides the divisor: the
    hierarchical-aggregation path (``dopt.population``) accumulates
    per-lane weighted sums over multiple cohort WAVES and then needs
    Σ_lanes acc / total_cohort_weight — the lane mask alone no longer
    knows the true weight, so the caller supplies it (already guarded
    against zero).

    ``comm_dtype`` narrows the reduce hop (the psum_scatter of the
    masked partials) — accumulation happens AT the wire dtype across
    devices, mirroring ``mix_dense_scatter``; the 1/D update divide and
    the re-forming all-gather stay at the leaf dtype so θ itself is
    never narrowed twice."""
    ax = _require_flat_mesh(mesh, "update_sharding='scatter'")
    m = jnp.asarray(mask, dtype=jnp.float32)
    denom = (jnp.maximum(m.sum(), 1.0) if denom is None
             else jnp.asarray(denom, jnp.float32))
    buckets = stacked_to_buckets(stacked, spec)

    def per_device(mask_l, x):
        mm = mask_l.reshape((-1,) + (1,) * (x.ndim - 1))
        part = (x.astype(jnp.float32) * mm).sum(axis=0)     # [Fb] partial
        if comm_dtype is not None:
            part = part.astype(comm_dtype)
        shard = jax.lax.psum_scatter(part, ax, scatter_dimension=0,
                                     tiled=True)            # [Fb/D] mine
        with jax.named_scope("dopt_update"):
            upd = (shard.astype(jnp.float32) / denom).astype(x.dtype)
        return jax.lax.all_gather(upd, ax, axis=0, tiled=True)

    # all_gather of identical shards IS replicated but cannot be
    # statically proven so — skip the varying-axes check, mirroring
    # _masked_average_compressed.
    fn = compat_shard_map(per_device, mesh=mesh,
                          in_specs=(P(ax), P(ax)), out_specs=P(),
                          check=False)
    with jax.named_scope("dopt_mix"):
        out = [fn(m, b) for b in buckets]
    return buckets_to_tree(out, spec)


# ---------------------------------------------------------------------
# Per-bucket wire codecs (CommConfig): the communication substrate
# ---------------------------------------------------------------------
# Every compressed mode now speaks the SAME flat-bucket representation
# the scatter path already uses: a bucket's [L, Fb] lane slab is
# encoded (dopt.ops.compression.qint_encode — per-chunk-scaled
# stochastic int8, or nibble-packed int4), the PACKED payload is what
# crosses the wire, each device decodes the gathered fleet payloads
# locally and contracts its own mixing-matrix rows.  A reduce-scatter
# cannot sum packed payloads, so the codec path is a compressed
# all-gather formulation: wire bytes drop from the dense path's
# 4·|bucket| f32 to |bucket|·bits/8 + the f32 scale sidecar (~4x at
# int8, ~7.9x at int4), at the cost of materialising the decoded
# [n, Fb] slab per bucket — the classic compression/memory trade the
# bandwidth schedule only takes on buckets worth compressing.
#
# Error feedback (DeepSqueeze/CHOCO-style): v = x + e is encoded, the
# residual e' = v − decode(encode(v)) stays local and re-enters next
# round, so the quantization error is fed back instead of compounding
# — the convergence-preserving half of the contract.  The residual is
# carried scan state in the engines and checkpointed ("comm_residual").

_WIRE_KINDS = ("raw", "bf16", "f16", "q8", "q4")


@dataclasses.dataclass(frozen=True)
class BucketCodecPlan:
    """Static per-bucket wire schedule for an ``UpdateShardSpec``.

    ``kinds[i]`` names bucket i's wire format: ``raw`` (leaf dtype,
    the exact scatter path), ``bf16``/``f16`` (dtype narrowing),
    ``q8``/``q4`` (packed integer codec with error feedback).  Built
    once at trainer construction by ``make_codec_plan`` — the schedule
    is compiled structure, never data."""

    kinds: tuple[str, ...]
    chunk: int
    dense_bytes: int   # per-lane f32 wire bytes of the whole tree/round
    wire_bytes: int    # per-lane scheduled wire bytes of the same

    @property
    def any_codec(self) -> bool:
        return any(k in ("q8", "q4") for k in self.kinds)

    @property
    def compression(self) -> float:
        return self.dense_bytes / max(self.wire_bytes, 1)


def _bucket_wire_bytes(width: int, kind: str, chunk: int) -> int:
    from dopt.ops.compression import qint_wire_bytes

    if kind == "raw":
        return width * 4
    if kind in ("bf16", "f16"):
        return width * 2
    return qint_wire_bytes(width, chunk=chunk,
                           bits=8 if kind == "q8" else 4)


def make_codec_plan(spec: UpdateShardSpec, *, codec: str = "none",
                    wire_dtype=None, byte_budget: int = 0,
                    min_codec_bytes: int = 4096,
                    chunk: int = 1024) -> BucketCodecPlan:
    """Map a byte budget onto per-bucket wire formats.

    Base format: ``wire_dtype`` narrowing (or ``raw``).  With a codec
    armed and no budget, every bucket whose per-lane f32 payload is at
    least ``min_codec_bytes`` gets the codec — small norm/bias buckets
    stay exact, the big conv/matmul slabs compress.  With
    ``byte_budget`` > 0 (per lane per round, e.g. from
    ``link_byte_budget``) buckets are escalated LARGEST FIRST —
    base → q8 → q4 — until the total fits the budget or every eligible
    bucket is at q4; large buckets therefore always compress at least
    as hard as small ones, and the schedule degrades gracefully when
    the budget is unreachable."""
    if codec not in ("none", "qsgd"):
        raise ValueError(f"unknown comm codec {codec!r}; one of none|qsgd")
    base = {None: "raw", "bfloat16": "bf16", "float16": "f16"}.get(
        str(wire_dtype) if wire_dtype is not None else None)
    if base is None:
        raise ValueError(
            f"unknown comm wire_dtype {wire_dtype!r}; one of "
            "bfloat16|float16 (or None for the leaf dtype)")
    widths = [b - a for a, b in zip(spec.bounds, spec.bounds[1:])]
    dense = sum(w * 4 for w in widths)
    kinds = [base] * len(widths)
    eligible = [i for i, w in enumerate(widths)
                if codec != "none" and w * 4 >= min_codec_bytes]
    by_size = sorted(eligible, key=lambda i: -widths[i])
    if codec != "none" and byte_budget <= 0:
        for i in eligible:
            kinds[i] = "q8"
    elif codec != "none":
        def total():
            return sum(_bucket_wire_bytes(w, k, chunk)
                       for w, k in zip(widths, kinds))

        for tier in ("q8", "q4"):
            for i in by_size:
                if total() <= byte_budget:
                    break
                kinds[i] = tier
    wire = sum(_bucket_wire_bytes(w, k, chunk)
               for w, k in zip(widths, kinds))
    return BucketCodecPlan(kinds=tuple(kinds), chunk=int(chunk),
                           dense_bytes=int(dense), wire_bytes=int(wire))


def link_byte_budget(dense_bytes: int, *, msg_drop: float = 0.0,
                     msg_delay: float = 0.0,
                     msg_delay_max: int = 0) -> int:
    """Per-link per-round byte budget implied by a lossy-link model
    (``FaultConfig.msg_drop``/``msg_delay``/``msg_delay_max``): a link
    that loses fraction p of its messages and delays fraction q of the
    rest by up to D rounds delivers useful bytes at goodput factor
    (1 − p) / (1 + q·D) of its raw rate — so a round's exchange only
    fits the round if the payload shrinks by that factor.  This is the
    bandwidth-aware schedule's input: the model that MOTIVATES
    compression prices it."""
    p = min(max(float(msg_drop), 0.0), 0.99)
    q = min(max(float(msg_delay), 0.0), 1.0)
    d = max(int(msg_delay_max), 0)
    factor = (1.0 - p) / (1.0 + q * d)
    return max(int(dense_bytes * factor), 1)


def _codec_mix_bucket(w_rows, x, e, lane0, kind: str, chunk: int, key,
                      ax: str | None):
    """One bucket's compressed-gather mix on ONE device (or the dense
    reference when ``ax`` is None): encode v = x + e per local lane,
    gather the packed payloads, decode the fleet slab, contract this
    device's mixing rows.  Returns (mixed [L, Fb], residual' [L, Fb]).

    The encode keys fold the GLOBAL lane id, so the bits for lane i are
    identical whether i is encoded here (shard_map) or in the reference
    — the scatter-vs-dense parity contract for stochastic codecs."""
    from dopt.ops.compression import qint_decode, qint_encode

    l, fb = x.shape
    bits = 8 if kind == "q8" else 4
    lane_ids = lane0 + jnp.arange(l)
    v = x.astype(jnp.float32) + e
    payload, scale = qint_encode(v, lane_ids, key, chunk=chunk, bits=bits)
    vq = qint_decode(payload, scale, fb, chunk=chunk, bits=bits)
    new_e = v - vq
    if ax is not None:
        payload = jax.lax.all_gather(payload, ax, axis=0, tiled=True)
        scale = jax.lax.all_gather(scale, ax, axis=0, tiled=True)
        vg = qint_decode(payload, scale, fb, chunk=chunk, bits=bits)
    else:
        vg = vq
    y = jnp.tensordot(w_rows, vg, axes=[[1], [0]])        # [L, Fb]
    return y.astype(x.dtype), new_e


def mix_codec_gather(buckets, residuals, w_matrix, mesh: Mesh,
                     plan: BucketCodecPlan, key):
    """Compressed consensus over flat buckets: per-bucket encode →
    all-gather of the PACKED payload (+ f32 scale sidecar) →
    local decode → this device's mixing rows contracted against the
    decoded fleet slab.  ``raw``/narrowed buckets keep the exact
    reduce-scatter path (``mix_dense_scatter``) — the codec only
    replaces the wire where the schedule says it pays.

    ``key`` is the round-folded base key; bucket i folds its index on
    top, and the per-lane fold happens inside the encode — draws are a
    pure function of (round, bucket, global lane).  Returns
    ``(mixed_buckets, new_residuals)`` with residuals of codec buckets
    updated (v − decode(encode(v))) and others passed through."""
    ax = _require_flat_mesh(mesh, "comm codec")
    w = jnp.asarray(w_matrix, dtype=jnp.float32)
    n = w.shape[0]
    lanes = n // mesh.size
    mixed, new_res = [], []
    with jax.named_scope("dopt_mix"):
        for i, (b, e, kind) in enumerate(
                zip(buckets, residuals, plan.kinds)):
            if kind in ("q8", "q4"):
                bkey = jax.random.fold_in(key, i)

                def per_device(w_rows, x, er, _kind=kind, _bkey=bkey):
                    lane0 = jax.lax.axis_index(ax) * lanes
                    return _codec_mix_bucket(w_rows, x, er, lane0, _kind,
                                             plan.chunk, _bkey, ax)

                fn = compat_shard_map(
                    per_device, mesh=mesh,
                    in_specs=(P(ax, None), P(ax), P(ax)),
                    out_specs=(P(ax), P(ax)))
                y, e2 = fn(w, b, e)
                mixed.append(y)
                new_res.append(e2)
            else:
                cd = {"raw": None, "bf16": jnp.bfloat16,
                      "f16": jnp.float16}[kind]
                mixed.append(mix_dense_scatter([b], w, mesh, cd)[0])
                new_res.append(e)
    return mixed, new_res


def mix_codec_reference(buckets, residuals, w_matrix,
                        plan: BucketCodecPlan, key):
    """Dense (no-mesh) reference of ``mix_codec_gather`` — the global
    [W, Fb] view with lane ids 0..W−1, drawing the SAME per-lane bits.
    The parity oracle for tests: sharded and reference paths agree to
    f32 tolerance (bit-equal encodes; the contraction differs only by
    gather layout)."""
    w = jnp.asarray(w_matrix, dtype=jnp.float32)
    mixed, new_res = [], []
    for i, (b, e, kind) in enumerate(zip(buckets, residuals, plan.kinds)):
        if kind in ("q8", "q4"):
            y, e2 = _codec_mix_bucket(w, b, e, 0, kind, plan.chunk,
                                      jax.random.fold_in(key, i), None)
            mixed.append(y)
            new_res.append(e2)
        else:
            cd = {"raw": None, "bf16": jnp.bfloat16,
                  "f16": jnp.float16}[kind]
            x = b if cd is None else b.astype(cd).astype(jnp.float32)
            y = jnp.tensordot(w, x.astype(jnp.float32), axes=[[1], [0]])
            mixed.append(y.astype(b.dtype))
            new_res.append(e)
    return mixed, new_res


# ---------------------------------------------------------------------
# Compiled-HLO collective byte accounting
# ---------------------------------------------------------------------

_HLO_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
              "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8,
              "u64": 8, "f64": 8, "c64": 8, "c128": 16}

_HLO_COLLECTIVES = ("all-gather", "all-reduce", "reduce-scatter",
                    "collective-permute", "all-to-all")

_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]+|pred)\[([0-9,]*)\]")


def _shape_bytes(shape_text: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        if dtype not in _HLO_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _HLO_BYTES[dtype]
    return total


def _shape_bytes_by_dtype(shape_text: str) -> dict[str, int]:
    by: dict[str, int] = {}
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        if dtype not in _HLO_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        by[dtype] = by.get(dtype, 0) + n * _HLO_BYTES[dtype]
    return by


def hlo_collective_bytes(hlo_text: str) -> dict:
    """Count the result-buffer bytes of every cross-device collective in
    a compiled HLO dump (``jit(fn).lower(...).compile().as_text()``):
    ``{op_kind: bytes, ..., "total": bytes, "by_dtype": {dtype: bytes},
    "by_op_dtype": {op_kind: {dtype: bytes}}}``.

    This is the measured basis for comm-volume claims — e.g. the folded
    shift path's "2 lane-shards per device vs the dense all_gather's
    n − L" (``tests/test_collectives.py`` pins it against the compiled
    programs, not the docstring).  Result-buffer bytes upper-bound wire
    bytes proportionally (an all-gather's result includes the local
    shard), which cancels in path-vs-path comparisons.  Async pairs
    (``*-start``/``*-done``) are counted once, at the start op.

    The per-dtype attribution is what makes COMPRESSED wires auditable:
    a ``comm_dtype='bfloat16'`` run shows its gather bytes under
    ``bf16``, a packed int8/int4 codec run under ``s8``/``u8`` with the
    f32 scale sidecars accounted separately — so "4x fewer bytes" is a
    statement about the compiled program, not the docstring."""
    out: dict = {k: 0 for k in _HLO_COLLECTIVES}
    by_dtype: dict[str, int] = {}
    by_op: dict[str, dict[str, int]] = {k: {} for k in _HLO_COLLECTIVES}
    for line in hlo_text.splitlines():
        if "=" not in line:
            continue
        rhs = line.partition("=")[2].strip()
        for kind in _HLO_COLLECTIVES:
            m = re.search(rf"(^|\s){re.escape(kind)}(-start)?\(", rhs)
            if m:
                per = _shape_bytes_by_dtype(rhs[:m.start()])
                for dt, b in per.items():
                    out[kind] += b
                    by_dtype[dt] = by_dtype.get(dt, 0) + b
                    by_op[kind][dt] = by_op[kind].get(dt, 0) + b
                break
    out["total"] = sum(out[k] for k in _HLO_COLLECTIVES)
    out["by_dtype"] = by_dtype
    out["by_op_dtype"] = {k: v for k, v in by_op.items() if v}
    return out


def broadcast_to_workers(tree, num_workers: int):
    """theta → stacked [W, ...] (the server handing every client a copy
    of the global model, servers.py:63 — here a free broadcast)."""
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (num_workers,) + x.shape), tree
    )


def mix_power(stacked, w_matrix, eps: int = 1, mesh: Mesh | None = None,
              comm_dtype=None):
    """eps consensus sweeps (FedLCon, simulators.py:182-212 — with the
    stale-accumulation bug fixed: each sweep reads the previous sweep's
    output).  eps=1 is plain consensus; jit at the caller."""
    if eps == 1:
        return mix_dense(stacked, w_matrix, mesh, comm_dtype)

    def body(x, _):
        return mix_dense(x, w_matrix, mesh, comm_dtype), None

    out, _ = jax.lax.scan(body, stacked, None, length=eps)
    return out
