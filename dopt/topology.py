"""Communication graphs and mixing matrices for gossip/consensus learning.

Re-creates the semantics of the reference's ``Simulator.communication_graph``
(``Distributed Optimization/src/simulators.py:40-86``) as pure data:

* Topologies: ``circle`` (ring), ``star``, ``complete``, ``dynamic``
  (N single-edge graphs cycled per round) — plus idiomatic extras the
  reference does not have: ``random`` (time-varying Erdős–Rényi, for
  the 32-worker north-star config) and ``torus``.
* Weight modes: ``stochastic`` (random weights, column-normalised then
  transposed → row-stochastic), ``double_stochastic`` (Sinkhorn), and
  ``ones`` (raw 0/1 adjacency — what the reference's notebook "dynamic"
  mode silently falls through to).  Idiomatic extras: ``metropolis``
  (Metropolis–Hastings, doubly stochastic *with* self-loops — the
  standard D-SGD choice) and ``uniform`` (1/deg row-stochastic).

Faithful-mode invariants (SURVEY §6 numerics notes):

* **Zero diagonal** — every reference topology builds zero-diagonal
  adjacency and both weight modes preserve the zeros, so consensus
  excludes the worker's own weights.  ``self_weight=True`` opts into
  the idiomatic self-inclusive mixing instead.
* ``stochastic`` normalises *columns* then transposes (simulators.py:69-70).
* ``double_stochastic`` special-cases star to uniform 1/n weights before
  masking (simulators.py:73-74); note a zero-diagonal doubly-stochastic
  star matrix does not exist for n>2 (the reference's Sinkhorn loop never
  terminates there, which is why its star/double CSVs are empty) — we
  detect infeasibility and raise instead of hanging.

Everything here is plain numpy; matrices are *data* consumed by the
collective layer (``dopt.parallel.collectives``), never code.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

# Reference spells it "compelete" (simulators.py:54); accept both.
_TOPOLOGIES = ("circle", "ring", "star", "complete", "compelete", "dynamic",
               "random", "torus", "hierarchical", "one_peer_exp")
_MODES = ("stochastic", "double_stochastic", "ones", "metropolis", "uniform")


class Topology:
    """Namespace of adjacency builders. Each returns a list of [n, n]
    zero-diagonal 0/1 float64 matrices (len > 1 = time-varying schedule)."""

    @staticmethod
    def circle(n: int) -> list[np.ndarray]:
        g = np.zeros((n, n))
        for i in range(n):
            g[i, (i + 1) % n] = 1.0
            g[(i + 1) % n, i] = 1.0
        return [g]

    ring = circle

    @staticmethod
    def star(n: int) -> list[np.ndarray]:
        g = np.zeros((n, n))
        g[0, 1:] = 1.0
        g[1:, 0] = 1.0
        return [g]

    @staticmethod
    def complete(n: int) -> list[np.ndarray]:
        g = np.ones((n, n)) - np.eye(n)
        return [g]

    @staticmethod
    def dynamic(n: int) -> list[np.ndarray]:
        """N single-edge graphs, edge (t, t+1 mod n) active in round t
        (simulators.py:59-64)."""
        graphs = []
        for t in range(n):
            g = np.zeros((n, n))
            g[t, (t + 1) % n] = 1.0
            g[(t + 1) % n, t] = 1.0
            graphs.append(g)
        return graphs

    @staticmethod
    def random(n: int, *, p: float = 0.5, schedule_len: int = 10,
               rng: np.random.Generator | None = None) -> list[np.ndarray]:
        """Time-varying Erdős–Rényi schedule; each round's graph is
        connected-ish by construction (a random Hamiltonian cycle is
        always included so no worker is ever isolated)."""
        rng = rng or np.random.default_rng(0)
        graphs = []
        for _ in range(schedule_len):
            g = (rng.random((n, n)) < p).astype(np.float64)
            g = np.triu(g, 1)
            g = g + g.T
            perm = rng.permutation(n)
            for i in range(n):
                a, b = perm[i], perm[(i + 1) % n]
                g[a, b] = g[b, a] = 1.0
            np.fill_diagonal(g, 0.0)
            graphs.append(g)
        return graphs

    @staticmethod
    def hierarchical(n: int, *, groups: int = 2,
                     period: int = 4) -> list[np.ndarray]:
        """DCN-aware two-level schedule for hybrid (hosts × ici) meshes:
        period−1 intra-group rounds (block-diagonal complete graphs —
        zero DCN edges, pure ICI traffic) followed by one global round,
        cycling.  The global mix sits LAST in the cycle, not first: the
        engine mixes at the start of each round and all workers share
        one init, so a round-0 global mix would average identical
        parameters — a no-op that would delay the first real cross-group
        exchange by a whole period.  This is hierarchical /
        semi-decentralized averaging (HierFAVG-style) expressed purely
        as topology data — the engine needs no special casing.  Group
        layout matches ``make_hybrid_mesh``: worker i belongs to group
        i // (n // groups)."""
        if n % groups:
            raise ValueError(f"{n} workers do not split into {groups} groups")
        if period < 2:
            raise ValueError(f"period must be >= 2, got {period}")
        size = n // groups
        intra = np.zeros((n, n))
        for g in range(groups):
            s = g * size
            blk = np.ones((size, size)) - np.eye(size)
            intra[s:s + size, s:s + size] = blk
        global_g = np.ones((n, n)) - np.eye(n)
        return [intra] * (period - 1) + [global_g]

    @staticmethod
    def one_peer_exp(n: int) -> list[np.ndarray]:
        """One-peer exponential schedule (arXiv:2410.11998 / D-PSGD
        practice): log2(n) DIRECTED single-peer graphs, graph k carrying
        the edge i -> (i + 2^k) mod n, cycled per round.  Every worker
        talks to exactly ONE peer each round — the cheapest possible
        round wire — and the union over a period is the exponential
        graph, so the schedule still contracts like a well-connected
        topology.  Requires a power-of-2 worker count: that is what
        makes every per-round matrix (I + P_{2^k})/2 doubly stochastic
        (P is then a permutation with no fixed points)."""
        if n < 2 or n & (n - 1):
            raise ValueError(
                f"one_peer_exp needs a power-of-2 worker count >= 2, "
                f"got {n}")
        idx = np.arange(n)
        graphs = []
        for k in range(n.bit_length() - 1):
            g = np.zeros((n, n))
            g[idx, (idx + (1 << k)) % n] = 1.0
            graphs.append(g)
        return graphs

    @staticmethod
    def torus(n: int) -> list[np.ndarray]:
        """2D torus (matches TPU ICI physical topology when n = r*c)."""
        r = int(np.sqrt(n))
        while n % r:
            r -= 1
        c = n // r
        g = np.zeros((n, n))
        for i in range(n):
            x, y = divmod(i, c)
            for nx, ny in (((x + 1) % r, y), ((x - 1) % r, y), (x, (y + 1) % c), (x, (y - 1) % c)):
                j = nx * c + ny
                if j != i:
                    g[i, j] = 1.0
        return [g]


def build_adjacency(topology: str, n: int, *, p: float = 0.5, schedule_len: int = 10,
                    seed: int = 0, groups: int = 2,
                    period: int = 4) -> list[np.ndarray]:
    t = topology.lower()
    if t not in _TOPOLOGIES:
        raise ValueError(f"unknown topology {topology!r}; one of {_TOPOLOGIES}")
    if t == "compelete":
        t = "complete"
    if t == "ring":
        t = "circle"
    if t == "random":
        return Topology.random(n, p=p, schedule_len=schedule_len,
                               rng=np.random.default_rng(seed))
    if t == "hierarchical":
        return Topology.hierarchical(n, groups=groups, period=period)
    return getattr(Topology, t)(n)


def _with_isolated_self_loops(w: np.ndarray) -> np.ndarray:
    """Give zero-degree workers an identity row so they keep their own
    weights.  The reference instead produces NaN (stochastic mode divides
    by zero column sums, simulators.py:69) or zeroes the model (ones
    mode) for isolated nodes in ``dynamic`` schedules — which is why its
    dynamic-run CSVs are empty.  Keeping-own-weights is the only sane
    semantics and is what the time-varying-gossip literature assumes."""
    w = w.copy()
    isolated = w.sum(axis=1) == 0
    w[isolated, isolated] = 1.0
    return w


def _stochastic_weights(graphs: Sequence[np.ndarray], rng: np.random.Generator) -> list[np.ndarray]:
    """Random positive weights on edges; column-normalise then transpose
    → row-stochastic (the reference's exact recipe, simulators.py:65-70)."""
    n = graphs[0].shape[0]
    rand = rng.random((n, n))
    out = []
    for g in graphs:
        w = rand * g
        colsum = w.sum(axis=0)
        colsum = np.where(colsum == 0, 1.0, colsum)
        out.append(_with_isolated_self_loops((w / colsum).T))
    return out


def _sinkhorn(w: np.ndarray, *, tol: float = 1e-12, max_iter: int = 10_000) -> np.ndarray:
    """Alternating row/column normalisation to a doubly-stochastic matrix.

    The reference iterates until *exact* float equality of row/col sums
    (simulators.py:80-84), which can spin forever; we use a tolerance and
    an iteration cap, and raise if the support admits no doubly-stochastic
    matrix (e.g. zero-diagonal star for n > 2)."""
    w = w.astype(np.float64).copy()
    for _ in range(max_iter):
        rsum = w.sum(axis=1)
        csum = w.sum(axis=0)
        if np.all(np.abs(rsum - 1) < tol) and np.all(np.abs(csum - 1) < tol):
            return w
        w = w / np.where(csum == 0, 1.0, csum)
        rs = w.sum(axis=1, keepdims=True)
        w = w / np.where(rs == 0, 1.0, rs)
    raise ValueError(
        "Sinkhorn failed to converge: the graph support admits no "
        "doubly-stochastic matrix (zero-diagonal star graphs for n>2 are "
        "infeasible — the reference hangs here; use mode='metropolis' "
        "or self_weight=True)."
    )


def _metropolis_weights(graphs: Sequence[np.ndarray]) -> list[np.ndarray]:
    """Metropolis–Hastings: a_ij = 1/(1+max(d_i,d_j)) for edges, self-loop
    takes the remainder.  Symmetric doubly-stochastic; the standard
    provably-convergent D-SGD mixing (not in the reference)."""
    out = []
    for g in graphs:
        deg = g.sum(axis=1)
        w = np.zeros_like(g)
        idx = np.argwhere(g > 0)
        for i, j in idx:
            w[i, j] = 1.0 / (1.0 + max(deg[i], deg[j]))
        np.fill_diagonal(w, 1.0 - w.sum(axis=1))
        out.append(w)
    return out


def _uniform_weights(graphs: Sequence[np.ndarray], self_weight: bool) -> list[np.ndarray]:
    out = []
    for g in graphs:
        a = g + np.eye(g.shape[0]) if self_weight else g.copy()
        rs = a.sum(axis=1, keepdims=True)
        out.append(_with_isolated_self_loops(a / np.where(rs == 0, 1.0, rs)))
    return out


@dataclass(frozen=True)
class MixingMatrices:
    """A (possibly time-varying) schedule of n×n mixing matrices.

    ``matrices[t % len(matrices)]`` is the matrix for round t — exactly
    the reference's ``adjacent_matrix[round % len(...)]`` selector
    (simulators.py:141-142)."""

    topology: str
    mode: str
    matrices: tuple[np.ndarray, ...] = field()

    @property
    def n(self) -> int:
        return self.matrices[0].shape[0]

    def for_round(self, t: int) -> np.ndarray:
        return self.matrices[t % len(self.matrices)]

    def stacked(self) -> np.ndarray:
        """[T, n, n] array — the form consumed on-device (indexed inside
        ``lax.scan`` by round)."""
        return np.stack(self.matrices, axis=0)

    # --- diagnostics -------------------------------------------------
    def is_row_stochastic(self, tol: float = 1e-9) -> bool:
        return all(np.all(np.abs(m.sum(1) - 1) < tol) and np.all(m >= -tol)
                   for m in self.matrices)

    def is_doubly_stochastic(self, tol: float = 1e-9) -> bool:
        return self.is_row_stochastic(tol) and all(
            np.all(np.abs(m.sum(0) - 1) < tol) for m in self.matrices)

    @staticmethod
    def _gap_of(m: np.ndarray) -> float:
        ev = np.sort(np.abs(np.linalg.eigvals(m)))[::-1]
        lam2 = ev[1] if len(ev) > 1 else 0.0
        return float(1.0 - lam2)

    def spectral_gap(self, kind: str = "product") -> float:
        """Consensus-rate diagnostic: 1 - |λ₂|.

        kind='product' (default): gap of the per-period product
        ``∏_{t=T-1..0} W_t`` — for a time-varying schedule the consensus
        error after one period contracts by that product's λ₂, so this
        is the quantity that actually governs convergence (B-connected
        gossip analysis).  For a static schedule (len 1) it degenerates
        to the single-matrix gap.

        kind='mean': gap of the round-averaged matrix — the classical
        static diagnostic.  It can over- OR under-state the rate of a
        dynamic schedule (averaging single-edge graphs looks far better
        connected than any round actually is), so use it only for
        static topologies or coarse comparisons.

        Note the per-period product gap is a per-PERIOD contraction; to
        compare schedules of different lengths on a per-round basis use
        ``1 - (1 - gap)**(1/T)``.
        """
        if kind == "mean":
            return self._gap_of(np.mean(self.stacked(), axis=0))
        if kind != "product":
            raise ValueError(f"kind must be 'product' or 'mean', got {kind!r}")
        prod = np.eye(self.n)
        for m in self.matrices:
            prod = m @ prod
        return self._gap_of(prod)


def build_mixing_matrices(
    topology: str,
    mode: str,
    n: int,
    *,
    seed: int = 0,
    self_weight: bool = False,
    p: float = 0.5,
    schedule_len: int = 10,
    groups: int = 2,
    period: int = 4,
) -> MixingMatrices:
    """Build the mixing-matrix schedule for a topology/mode pair.

    Faithful reference modes: ``stochastic``, ``double_stochastic``,
    ``ones``.  Idiomatic extras: ``metropolis``, ``uniform``.
    """
    mode_l = mode.lower()
    if mode_l not in _MODES:
        # The reference silently uses the raw 0/1 adjacency when the mode
        # string matches neither branch (the notebook's 'dynamic' mode run,
        # Weighted Average.ipynb cell 29).  We accept it explicitly as
        # 'ones' but reject typos loudly.
        raise ValueError(f"unknown mode {mode!r}; one of {_MODES}")
    if topology.lower() == "one_peer_exp":
        # One-peer exponential graphs define their OWN weights: every
        # round is exactly W_t = (I + P_{2^t mod log2 n})/2 — dyadic 0.5
        # entries (bit-exact in f32/bf16), doubly stochastic, stateless
        # per round via the for_round(t) schedule selector.  The weight
        # mode is ignored (the matrix IS the algorithm) and the lazy
        # self-loop would double-apply the built-in self-weight.
        if self_weight:
            raise ValueError(
                "topology='one_peer_exp' bakes its own exact dyadic "
                "self-weights (W_t = (I + P)/2); self_weight=True only "
                "applies to the reference weight modes — drop one of "
                "the two")
        mats = [(np.eye(n) + g) / 2.0
                for g in build_adjacency(topology, n)]
        return MixingMatrices(topology="one_peer_exp", mode=mode_l,
                              matrices=tuple(mats))
    graphs = build_adjacency(topology, n, p=p, schedule_len=schedule_len,
                             seed=seed, groups=groups, period=period)
    rng = np.random.default_rng(seed)

    if mode_l == "stochastic":
        mats = _stochastic_weights(graphs, rng)
    elif mode_l == "double_stochastic":
        # Star special case: uniform 1/n base weights (simulators.py:73-74).
        base = (np.ones((n, n)) / n if topology.lower() == "star"
                else rng.random((n, n)))
        # The reference transposes the converged matrix on assignment
        # (simulators.py:85, `torch.tensor(graph).T`) — still doubly
        # stochastic, but row i holds different weights; replicate it
        # so the oracle comparison matches element-wise.
        mats = [_sinkhorn(_with_isolated_self_loops(base * g)).T.copy() for g in graphs]
    elif mode_l == "ones":
        mats = [g.copy() for g in graphs]
    elif mode_l == "metropolis":
        mats = _metropolis_weights(graphs)
    else:  # uniform
        mats = _uniform_weights(graphs, self_weight)

    if self_weight and mode_l in ("stochastic", "double_stochastic", "ones"):
        # Idiomatic self-inclusive variant: add the self-loop then
        # re-normalise (lazy gossip, W' = (W + I)/2).
        mats = [(m + np.eye(n)) / 2.0 for m in mats]

    return MixingMatrices(topology=topology, mode=mode_l, matrices=tuple(mats))


def shift_decomposition(w: np.ndarray, max_shifts: int | None = None
                        ) -> list[tuple[int, np.ndarray]] | None:
    """Decompose a mixing matrix into circulant diagonals for the
    ``ppermute`` execution path.

    Returns ``[(shift, coeffs[n]), ...]`` such that
    ``W[i, (i+shift) % n] == coeffs[i]`` covers every nonzero, or ``None``
    if the number of nonzero diagonals exceeds ``max_shifts`` (then the
    dense all_gather+einsum path is cheaper).  Ring topologies decompose
    into shifts {±1} (plus 0 with self-weight); the per-round graphs of
    ``dynamic`` schedules also fit in {±1}.
    """
    n = w.shape[0]
    shifts: list[tuple[int, np.ndarray]] = []
    for s in range(n):
        coeffs = np.array([w[i, (i + s) % n] for i in range(n)])
        if np.any(coeffs != 0):
            shifts.append((s, coeffs))
    if max_shifts is not None and len(shifts) > max_shifts:
        return None
    return shifts


def schedule_shift_decomposition(
    mixing: MixingMatrices,
    *,
    max_shifts: int | None = None,
    extra_shifts: Sequence[int] = (),
) -> tuple[int, ...] | None:
    """Union of circulant shifts covering EVERY matrix in a (possibly
    time-varying) mixing schedule.

    The gossip engine compiles ONE round step for the whole run, so the
    ppermute path needs a single static shift set that covers every
    round's matrix; per-round coefficients then become data
    (``coeffs_for_matrix``).  ``extra_shifts`` lets the engine force
    shift 0 into the set when dropout repair may add identity rows —
    the repaired matrix then stays inside the compiled set even when
    the clean schedule has a zero diagonal.  Returns ``None`` when the
    union exceeds ``max_shifts`` (the dense all_gather path is then the
    better mapping); a ``None`` bail NEVER mutates ``extra_shifts``
    (callers may hand a long-lived set) and bails as soon as the union
    blows the budget rather than decomposing the rest of the schedule.
    Shifts are canonicalised mod n, so ``extra_shifts=(-1,)`` means the
    n-1 diagonal ``shift_decomposition`` would emit."""
    n = mixing.n
    ids: set[int] = {int(s) % n for s in extra_shifts}
    for m in mixing.matrices:
        dec = shift_decomposition(m)
        assert dec is not None
        ids.update(s for s, _ in dec)
        if max_shifts is not None and len(ids) > max_shifts:
            return None
    out = tuple(sorted(ids))
    if max_shifts is not None and len(out) > max_shifts:
        return None
    return out


def coeffs_for_matrix(w: np.ndarray, shift_ids: Sequence[int]) -> np.ndarray:
    """Extract the [k, n] circulant-diagonal coefficient table of ``w``
    for a static shift set: ``coeffs[k, i] = w[i, (i + shift_ids[k]) % n]``.

    Raises if ``w`` has support outside the shift set — the engine's
    guarantee that the ppermute path computes exactly ``W @ x``."""
    n = w.shape[0]
    rows = np.arange(n)
    coeffs = np.stack([w[rows, (rows + int(s)) % n] for s in shift_ids])
    recon = np.zeros_like(w)
    for k, s in enumerate(shift_ids):
        recon[rows, (rows + int(s)) % n] = coeffs[k]
    if not np.array_equal(recon, w):
        raise ValueError(
            f"matrix support is not covered by shifts {tuple(shift_ids)}"
        )
    return coeffs.astype(np.float32)


def repair_for_dropout(w: np.ndarray, alive: np.ndarray) -> np.ndarray:
    """Rebuild a mixing matrix after worker failures (fault injection /
    elastic recovery — the subsystem SURVEY §5 notes the reference lacks
    entirely; here failures are a per-round participation mask and the
    communication layer heals itself as data).

    ``alive`` is a 0/1 vector.  Edges to dead workers are removed and
    surviving rows renormalised to keep row-stochasticity; a live worker
    whose neighbors all died keeps its own weights for the round
    (identity row), and a dead worker is frozen (identity row) so it
    rejoins with stale-but-valid parameters when it comes back.
    """
    n = w.shape[0]
    a = np.asarray(alive, dtype=w.dtype).reshape(1, n)
    return _repair_edges(w, a, force_identity=np.asarray(alive) <= 0)


def repair_for_dropout_jnp(w, alive):
    """``repair_for_dropout`` as a jittable device function.

    Used by the fused-quarantine execution path, where the round's
    alive mask is scan CARRY (the quarantine state lives on device), so
    the matrix repair must happen inside the compiled round body.  Both
    the per-round and the blocked quarantine paths call THIS function,
    which is what makes their traces bit-identical: the host numpy
    repair runs in float64, this one in the matrix dtype (f32).

    ``alive`` is a 0/1 vector (any float dtype); semantics match the
    numpy version exactly — dead edges dropped, surviving rows
    renormalised, isolated/dead rows replaced by exact identity rows.
    """
    import jax.numpy as jnp

    n = w.shape[0]
    a = jnp.asarray(alive, w.dtype).reshape(1, n)
    masked = w * a
    rowsum = masked.sum(axis=1, keepdims=True)
    safe = jnp.where(rowsum > 0, rowsum, jnp.ones_like(rowsum))
    repaired = masked / safe
    iso = (rowsum[:, 0] <= 0) | (a[0] <= 0)
    eye = jnp.eye(n, dtype=w.dtype)
    return jnp.where(iso[:, None], eye, repaired)


def _repair_edges(w: np.ndarray, edge_mask: np.ndarray,
                  force_identity: np.ndarray | None = None) -> np.ndarray:
    """Shared healing core for dropout/partition repair: drop the
    masked-out edges, renormalise surviving rows to stay stochastic,
    and give isolated rows (no surviving out-edges, or explicitly
    forced — dead workers) an exact identity row."""
    masked = w * edge_mask
    rowsum = masked.sum(axis=1, keepdims=True)
    safe = np.where(rowsum > 0, rowsum, 1.0)
    repaired = masked / safe
    iso = rowsum[:, 0] <= 0
    if force_identity is not None:
        iso = iso | force_identity
    isolated = np.nonzero(iso)[0]
    repaired[isolated, :] = 0.0
    repaired[isolated, isolated] = 1.0
    return repaired


def repair_for_link_drop(w: np.ndarray, keep: np.ndarray) -> np.ndarray:
    """Rebuild a mixing matrix under per-DIRECTED-EDGE message loss
    (the lossy-link model, ``FaultConfig.msg_drop``).

    ``keep`` is bool [n, n]: keep[i, j] = the message j -> i arrived.
    Dropped edges are removed and surviving rows renormalised (the
    receiver re-weights what it actually heard — the only thing a real
    receiver CAN do), with the ``repair_for_dropout`` healing semantics
    for rows left empty.  The self-edge always survives (a worker never
    loses its own state).

    Correctness note: because each direction drops independently, the
    repaired matrix is row-stochastic but in general NOT doubly
    stochastic even when ``w`` was — plain gossip through it converges
    to a *biased* weighted average.  ``push_sum_link_matrix`` is the
    mass-conserving counterpart that keeps the true mean recoverable.

    A worker with every in/out edge dropped is repaired exactly like a
    crashed worker (identity row) — crash = the degenerate all-links
    case, which is what lets the legacy ``GossipConfig.dropout`` alias
    route through this path (pinned in tests/test_faults.py)."""
    n = w.shape[0]
    mask = (np.asarray(keep, bool) | np.eye(n, dtype=bool)).astype(w.dtype)
    return _repair_edges(w, mask)


def push_sum_link_matrix(w: np.ndarray, keep: np.ndarray) -> np.ndarray:
    """Column-stochastic (mass-conserving) effective matrix for
    push-sum / ratio consensus under message loss.

    ``w`` is the round's (already crash/partition/churn-repaired)
    row-stochastic mixing matrix; its transpose is the column-stochastic
    out-share matrix B (sender j splits its mass by its own mixing row).
    A dropped edge j -> i returns its share to the SENDER's self-term
    (the message bounced; mass is never destroyed), so every column
    still sums to exactly 1 and the ratio estimate params/mass stays a
    convex combination of the honest values — the invariant the
    push-sum property tests pin (Σ mass, nodes + in-flight, == n at
    every round)."""
    n = w.shape[0]
    eye = np.eye(n, dtype=bool)
    b = np.asarray(w, np.float64).T
    k = (np.asarray(keep, bool) | eye)
    m = b * k
    # Undelivered share of each column back to the sender's diagonal.
    lost = (b * ~k).sum(axis=0)
    m[np.arange(n), np.arange(n)] += lost
    return m


def split_by_delay(m: np.ndarray, delay: np.ndarray,
                   delay_max: int) -> np.ndarray:
    """Split an effective mixing matrix into its per-staleness parts:
    returns [D+1, n, n] with ``out[d] = m`` masked to the edges whose
    message is d rounds stale (diagonal always d = 0; entries of
    dropped edges are already 0 in ``m``).  ``sum(out, axis=0) == m``
    exactly, so the split never changes the round's total weights —
    only WHICH snapshot each weight applies to.  The input dtype is
    preserved (push-sum's mass-conservation property tests run the
    split in float64; the engines narrow to f32 at device put)."""
    n = m.shape[0]
    d = np.where(np.eye(n, dtype=bool), 0, np.asarray(delay))
    out = np.stack([m * (d == k) for k in range(delay_max + 1)])
    return out.astype(m.dtype)


def repair_for_partition(w: np.ndarray, groups: np.ndarray) -> np.ndarray:
    """Rebuild a mixing matrix under a network partition: edges that
    cross the cut are removed and surviving rows renormalised, exactly
    the ``repair_for_dropout`` healing semantics applied edge-wise.

    ``groups`` is an int vector of partition-side ids; only same-group
    edges survive.  A worker isolated by the cut (all neighbors on the
    other side) keeps its own weights for the span (identity row), so
    every side keeps mixing internally and the fleet re-fuses when the
    partition heals — the matrix is data, nothing is recompiled.
    """
    g = np.asarray(groups).reshape(-1)
    n = w.shape[0]
    if g.shape[0] != n:
        raise ValueError(f"groups has {g.shape[0]} entries for an "
                         f"{n}-worker matrix")
    same = (g[:, None] == g[None, :]).astype(w.dtype)
    return _repair_edges(w, same)
