"""Sequence-parallel LM throughput: tokens/sec on the current device(s).

Measures steady-state training throughput of the ``seqlm`` preset
(decoder-only TransformerLM, ring attention, sequence axis sharded over
all devices).  On a single chip the ring degenerates to one block (same
code path, no hops); on an N-device mesh the KV pairs rotate over ICI.
There is no reference counterpart (the reference has no sequence axis);
the number is the framework's own long-context baseline.

Usage: python scripts/bench_seqlm.py [--steps N] [--seq-len L] [--attn ring]
Prints one JSON line: {"metric": "seqlm_tokens_per_sec", ...}.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--attn", default="ring", choices=["ring", "ulysses"])
    args = ap.parse_args()

    import jax

    from dopt.engine import SeqLMTrainer
    from dopt.presets import get_preset

    cfg = get_preset("seqlm")
    cfg = cfg.replace(seqlm=dataclasses.replace(
        cfg.seqlm, steps=args.steps, seq_len=args.seq_len, batch=args.batch,
        attn=args.attn, log_every=max(args.steps // 3, 1)))
    tr = SeqLMTrainer(cfg)
    tr.run(steps=3)                       # compile + warmup
    t0 = time.time()
    tr.run(steps=args.steps)
    jax.block_until_ready(tr.params)
    elapsed = time.time() - t0
    tokens = args.steps * args.batch * args.seq_len
    print(json.dumps({
        "metric": "seqlm_tokens_per_sec",
        "value": round(tokens / elapsed, 1),
        "unit": "tokens/sec",
        "attn": args.attn,
        "seq_len": args.seq_len,
        "batch": args.batch,
        "mesh_devices": tr.mesh.size,
        "params": tr.param_count,
        "final_loss": round(tr.history.last()["loss"], 4),
        "device": str(jax.devices()[0].device_kind),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
