"""Client population registry: cohort sampling over 1k–10k clients.

Both engines historically equated "worker" with "device lane": the
fleet topped out at the lane count the mesh could fold (16 workers on
8 devices).  Production cross-device FL samples each round's cohort
from a population orders of magnitude larger — most clients are idle
most of the time, and the interesting per-client state (which data
shard it owns, how often it participated, whether it is serving a
quarantine sentence) is kilobytes of host-side bookkeeping, not a
device lane.  This module makes that population a first-class,
host-side state object decoupled from the fixed-width lanes:

* **Registry** — per-client arrays for P clients: data-shard
  assignment (``dopt.data.partition.assign_client_shards``),
  participation counts, last-sampled round (the staleness signal),
  non-finite screen streaks and quarantine sentences — all keyed by
  CLIENT id, so a ``corrupt_max``-pinned adversary or a quarantine
  sentence persists across cohorts instead of being reshuffled with
  the lane binding.  The registry owns a client-keyed ``FaultPlan``
  (``num_workers = P``): every stateless per-round draw — crash,
  straggle, corrupt, churn, uplink loss — is a [P] vector gathered at
  the cohort's ids.
* **Cohort sampler** — seeded and STATELESS: round t's cohort is a
  function of (seed, t, eligible set) alone, drawn without replacement
  from the clients that are neither quarantined nor churned away.  No
  RNG state is carried between rounds, so sampling is bit-reproducible,
  identical under blocked execution, and crash-exact under resume
  without persisting generator state.
* **Cohort→lane binding** — the M sampled survivors are packed into
  ``ceil(cohort / lanes)`` fixed-width WAVES of the engine's
  validity-masked lanes (survivors first, wraparound padding ids,
  validity as data — the PR-4 "survivor counts are data, not shapes"
  machinery), so cohort size never retraces: one compiled program
  serves every round of a population run.
* **Hierarchical aggregation** — the engine scans the waves inside one
  jitted round: each wave trains ``lanes`` stateless clients from
  theta, per-device partial weighted sums accumulate across waves in
  f32, and ONE cross-device bucketed reduce
  (``dopt.parallel.collectives.masked_average_scatter`` with an
  explicit cohort-weight denominator) forms the aggregate — the
  per-device-partials → one-reduce tree of "Improving Efficiency in
  Large-Scale Decentralized Distributed Training" (arXiv:2002.01119)
  riding the arXiv:2004.13336 bucketed flat-tree substrate from PR 6.

Every sampled round lands one ``cohort`` row in the fault ledger
({round, worker: -1, kind: "cohort", action:
"sampled_{m}_of_{P}_digest_{crc32}_waves_{K}"}), so sampling is
auditable and replay-checkable like every fault kind.
"""

from __future__ import annotations

import zlib

import numpy as np

from dopt.config import FaultConfig, PopulationConfig, RobustConfig
from dopt.data.partition import assign_client_shards, orphan_shard_adopters
from dopt.faults import FaultPlan
from dopt.robust import quarantine_step
from dopt.utils.prng import host_rng

# Salt for the stateless cohort draws — its own namespace so arming the
# population registry never perturbs the fault or lane-sampling streams.
_COHORT_SALT = 0xC0407


def validate_population_config(cfg: PopulationConfig) -> None:
    if cfg.clients < 1:
        raise ValueError(
            f"PopulationConfig.clients={cfg.clients} must be >= 1")
    if not 1 <= cfg.cohort <= cfg.clients:
        raise ValueError(
            f"PopulationConfig.cohort={cfg.cohort} must be in "
            f"[1, clients={cfg.clients}]")
    if cfg.lanes is not None and cfg.lanes < 1:
        raise ValueError(
            f"PopulationConfig.lanes={cfg.lanes} must be >= 1")


def cohort_digest(ids: np.ndarray) -> str:
    """8-hex-char CRC32 of the cohort's SORTED client ids — the ledger's
    compact, order-independent audit key for "which clients did round t
    draw" (two runs disagree on sampling iff some digest differs)."""
    ids = np.sort(np.asarray(ids, np.int64))
    return f"{zlib.crc32(ids.tobytes()) & 0xFFFFFFFF:08x}"


class CohortBinding:
    """One round's cohort packed onto the fixed lane grid.

    ``lane_ids`` is the [waves, lanes] int32 client-id grid (survivors
    first in sorted order, wraparound padding after), ``valid`` the
    matching 0/1 f32 validity mask — the device program consumes both
    as DATA, so every cohort size (including zero survivors) shares one
    compiled program."""

    def __init__(self, round_: int, cohort: np.ndarray,
                 survivors: np.ndarray, lanes: int, waves: int):
        self.round = int(round_)
        self.cohort = np.asarray(cohort, np.int64)
        self.survivors = np.asarray(survivors, np.int64)
        self.lanes = int(lanes)
        self.waves = int(waves)
        slots = self.waves * self.lanes
        n = len(self.survivors)
        if n > slots:
            raise ValueError(
                f"{n} survivors exceed the {self.waves}x{self.lanes} "
                "lane grid")
        if n:
            pad = self.survivors[np.arange(n, slots) % n]
            grid = np.concatenate([self.survivors, pad])
        else:
            grid = np.zeros(slots, np.int64)
        self.lane_ids = grid.reshape(self.waves, self.lanes).astype(np.int32)
        valid = np.zeros(slots, np.float32)
        valid[:n] = 1.0
        self.valid = valid.reshape(self.waves, self.lanes)

    @property
    def digest(self) -> str:
        return cohort_digest(self.cohort)

    def ledger_row(self, population: int) -> dict:
        """The round's ``cohort`` audit row (worker −1: a fleet-level
        event, not any one client's)."""
        return {"round": self.round, "worker": -1, "kind": "cohort",
                "action": (f"sampled_{len(self.cohort)}_of_{population}"
                           f"_digest_{self.digest}_waves_{self.waves}")}


class ClientRegistry:
    """Host-side per-client state for a population of P clients.

    All arrays are plain numpy keyed by client id; the only
    round-to-round state is what ``state_dict`` checkpoints (sampling
    itself is stateless).  The registry is engine-agnostic: the
    federated trainer drives the full participate→train→screen cycle,
    the gossip trainer uses the sampler + shard binding only."""

    def __init__(self, cfg: PopulationConfig, *, num_shards: int,
                 seed: int, faults: FaultConfig | None = None,
                 robust: RobustConfig | None = None,
                 lanes: int | None = None):
        validate_population_config(cfg)
        self.cfg = cfg
        self.clients = int(cfg.clients)
        self.cohort_size = int(cfg.cohort)
        self.num_shards = int(num_shards)
        self.seed = int(cfg.seed) if cfg.seed is not None else int(seed)
        self.lanes = int(lanes if lanes is not None
                         else (cfg.lanes or num_shards))
        if self.lanes < 1:
            raise ValueError(f"lane width {self.lanes} must be >= 1")
        # Static wave count: the lane grid always holds the FULL
        # configured cohort; short cohorts (quarantine/churn dips) ride
        # the validity mask instead of reshaping the program.
        self.waves = -(-self.cohort_size // self.lanes)
        self.shard_of = assign_client_shards(self.clients, self.num_shards,
                                             seed=self.seed)
        # Client-keyed fault streams: the SAME FaultPlan machinery the
        # lane engines use, sized to the population — so corrupt=1.0 +
        # corrupt_max=f pins CLIENTS 0..f-1 as persistent adversaries
        # across every cohort that samples them.
        self.faults = FaultPlan(self.clients, faults, seed=seed)
        self._quarantine_after = (int(robust.quarantine_after)
                                  if robust is not None else 0)
        self._quarantine_rounds = (int(robust.quarantine_rounds)
                                   if robust is not None else 0)
        self.participation = np.zeros(self.clients, np.int64)
        self.last_sampled = np.full(self.clients, -1, np.int64)
        self.screen_streak = np.zeros(self.clients, np.int64)
        self.quarantine_until = np.zeros(self.clients, np.int64)

    # -- eligibility & sampling ----------------------------------------
    def staleness(self, t: int) -> np.ndarray:
        """[P] rounds since each client last participated (t+1 for the
        never-sampled) — the registry's per-client staleness signal."""
        return np.where(self.last_sampled < 0, int(t) + 1,
                        int(t) - self.last_sampled)

    def begin_round(self, t: int) -> list[dict]:
        """Expire quarantine sentences due at round t; returns the
        readmission ledger rows (client-keyed)."""
        rows: list[dict] = []
        expired = (self.quarantine_until != 0) & (t >= self.quarantine_until)
        for i in np.nonzero(expired)[0]:
            rows.append({"round": int(t), "worker": int(i),
                         "kind": "quarantine", "action": "readmitted"})
            self.quarantine_until[i] = 0
            self.screen_streak[i] = 0
        return rows

    def eligible(self, t: int) -> np.ndarray:
        """[P] bool: clients neither serving a quarantine sentence nor
        churned away at round t."""
        ok = ~(self.quarantine_until > t)
        away = self.faults.away_for_round(t)
        return ok & ~away

    def sample_cohort(self, t: int, *, n_draw: int | None = None,
                      eligible: np.ndarray | None = None) -> np.ndarray:
        """Round t's cohort draw, in DRAW order (the over-selection
        surplus must release uniformly — sorting happens at binding).
        Stateless: keyed by (seed, round) over the eligible ids, so a
        resumed run draws exactly what a continuous run would.  Returns
        min(n_draw, #eligible) ids; an empty draw is a valid (skipped)
        round, not an error."""
        if eligible is None:
            eligible = self.eligible(t)
        ids = np.nonzero(eligible)[0]
        n = min(int(n_draw if n_draw is not None else self.cohort_size),
                len(ids))
        if n == 0:
            return np.zeros(0, np.int64)
        rng = host_rng(self.seed, _COHORT_SALT, int(t))
        return np.asarray(rng.choice(ids, n, replace=False), np.int64)

    def bind(self, t: int, cohort: np.ndarray,
             survivors: np.ndarray) -> CohortBinding:
        """Pack the round's survivors (sorted) onto the lane grid."""
        return CohortBinding(t, cohort, np.sort(np.asarray(survivors)),
                             self.lanes, self.waves)

    def churn_ledger_rows(self, t: int, away: np.ndarray) -> list[dict]:
        """Population-keyed elastic-membership rows for round t:
        per-CLIENT leave/rejoin transitions plus per-SHARD adoption
        changes (worker −1: a shard is a fleet-level resource).  The
        worker-level ``dopt.faults.churn_ledger_rows`` cannot be reused
        here — its ``adopters_for`` assumes worker i OWNS shard i,
        which at population scale would fabricate client-id adoption
        rows while the real orphan-shard adoptions
        (``orphan_shard_adopters``, the map ``plan_matrix_for``
        actually applies) went unledgered.  Stateless in the round
        index, so per-round and resumed runs log identically."""
        rows: list[dict] = []
        prev = (self.faults.away_for_round(t - 1) if t > 0
                else np.zeros_like(away))
        for i in np.nonzero(away & ~prev)[0]:
            rows.append({"round": int(t), "worker": int(i),
                         "kind": "churn", "action": "left"})
        for i in np.nonzero(prev & ~away)[0]:
            rows.append({"round": int(t), "worker": int(i),
                         "kind": "churn", "action": "rejoined"})
        cur = orphan_shard_adopters(self.shard_of, ~away, self.num_shards)
        prv = orphan_shard_adopters(self.shard_of, ~prev, self.num_shards)
        for s, a in sorted(cur.items()):
            if prv.get(s) != a:
                rows.append({"round": int(t), "worker": -1,
                             "kind": "churn",
                             "action": f"shard_{s}_adopted_by_{a}"})
        return rows

    # -- data binding ---------------------------------------------------
    def plan_matrix_for(self, t: int,
                        train_matrix: np.ndarray) -> np.ndarray:
        """Round t's batch-plan index matrix: ``train_matrix`` with any
        ORPHANED shard (every assigned client churned away) adopted by
        the next covered shard — the population-level analog of the
        worker-level ``FaultPlan.plan_matrix_for``."""
        if not self.faults.has_churn:
            return train_matrix
        from dopt.data.partition import reassign_shards

        alive = ~self.faults.away_for_round(t)
        adopters = orphan_shard_adopters(self.shard_of, alive,
                                         self.num_shards)
        return reassign_shards(train_matrix, adopters)

    # -- feedback -------------------------------------------------------
    def record_participation(self, t: int, ids: np.ndarray) -> None:
        ids = np.asarray(ids, np.int64)
        self.participation[ids] += 1
        self.last_sampled[ids] = int(t)

    def apply_screen_feedback(self, t: int, ids: np.ndarray,
                              flags: np.ndarray, rows: list) -> None:
        """Fold the device round's non-finite-screen flags (aligned with
        ``ids``, the round's surviving clients) into the client-keyed
        ledger + quarantine streaks — the engines' rule
        (``dopt.robust.quarantine_step``) applied at population scale."""
        for j, cid in enumerate(np.asarray(ids).reshape(-1)):
            if float(flags[j]) > 0.5:
                rows.append({"round": int(t), "worker": int(cid),
                             "kind": "corrupt",
                             "action": "screened_nonfinite"})
        sentenced = quarantine_step(
            self.screen_streak, self.quarantine_until, ids, flags, t,
            after=self._quarantine_after, rounds=self._quarantine_rounds)
        for cid, until in sentenced:
            rows.append({"round": int(t), "worker": int(cid),
                         "kind": "quarantine",
                         "action": f"quarantined_until_{until}"})

    # -- checkpointing --------------------------------------------------
    def state_dict(self) -> dict:
        """JSON-able registry state (everything that is not a stateless
        function of the round index).  ``shard_of`` rides along as an
        integrity check — a resumed run must see the identical
        assignment or its cohorts would silently train different data."""
        return {
            "clients": self.clients,
            "cohort": self.cohort_size,
            "lanes": self.lanes,
            "participation": self.participation.tolist(),
            "last_sampled": self.last_sampled.tolist(),
            "screen_streak": self.screen_streak.tolist(),
            "quarantine_until": self.quarantine_until.tolist(),
            "shard_of": self.shard_of.tolist(),
        }

    def load_state(self, state: dict) -> None:
        for key, expect in (("clients", self.clients),
                            ("cohort", self.cohort_size),
                            ("lanes", self.lanes)):
            got = state.get(key)
            if got is not None and int(got) != expect:
                raise ValueError(
                    f"checkpoint registry {key}={got} does not match the "
                    f"trainer's {key}={expect}")
        p = self.clients
        self.participation = np.asarray(
            state.get("participation", [0] * p), np.int64)
        self.last_sampled = np.asarray(
            state.get("last_sampled", [-1] * p), np.int64)
        self.screen_streak = np.asarray(
            state.get("screen_streak", [0] * p), np.int64)
        self.quarantine_until = np.asarray(
            state.get("quarantine_until", [0] * p), np.int64)
        saved = state.get("shard_of")
        if saved is not None and not np.array_equal(
                np.asarray(saved, np.int32), self.shard_of):
            raise ValueError(
                "checkpoint registry shard assignment differs from this "
                "trainer's (population/shards/seed mismatch) — resuming "
                "would train different data per client")
