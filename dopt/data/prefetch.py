"""Prefetched host staging: overlap batch planning with device compute.

BENCH_r05 measured the fast leg at 2.26 wall rounds/sec against 2.51
device rounds/sec — ~10% of every block is the host serially building
batch plans and ``device_put``-ing them while the TPU idles.  The
blocked loops' host work is *prefetchable*: batch plans and the stacked
fault/link/corrupt inputs are (or split into parts that are) stateless
in ``(seed, round)``, so block b+1's payload can be built and staged to
device while block b runs.  The engines' loops become
dispatch → stage-next → fetch instead of build → dispatch → fetch.

The ordering contract that keeps prefetch-on runs BIT-IDENTICAL to
prefetch-off (History, fault ledger, canonical telemetry stream):

* **draw vs build.**  Each block's staging splits into a cheap,
  possibly-stateful *draw* (host RNG draws — the federated sampling
  stream, the gossip matching-matrix stream — plus the per-round fault
  vectors) and an expensive, *pure* build (``make_batch_plan`` over the
  drawn keys, ``np.stack``, ``jax.device_put``).  Draws always run on
  the caller's thread, in block order — exactly the sequence positions
  the unprefetched loop consumes them at — so stateful streams advance
  identically.  Only the pure build runs on the background thread.
* **replay never draws.**  The engines' post-fetch ledger/telemetry
  replay consumes the block's *drawn* inputs (``w_raw=...``,
  ``chosen=...``) rather than re-drawing, so staging block b+1 before
  block b's replay cannot perturb any stream.
* **no staging across a commit point.**  A checkpoint boundary is a
  commit: everything the checkpoint captures (RNG states, host
  mirrors, the registry) must reflect exactly the committed rounds.
  The loops therefore never stage past a scheduled checkpoint —
  equivalently, prefetched-but-uncommitted staging is discarded at
  every checkpoint/resume point — so a killed-and-resumed prefetch run
  replays bit-identically (the resumed loop simply re-stages from the
  checkpointed state).

The queue is bounded at depth 2: the block being consumed plus at most
one staged successor.  ``take()`` of an un-staged key falls back to an
inline build (the first block of every run, and the block after a
checkpoint), which is the unprefetched code path.
"""

from __future__ import annotations

import threading
import time


def timed_build(build, timers):
    """Wrap a pure block ``build`` so its runtime accumulates into
    ``timers``' ``host_batch_plan`` totals from the stager's background
    thread (the ``PhaseTimers`` tracer spans are not meant for
    concurrent cross-thread use, so the wrapper adds to the defaultdict
    totals directly — the engines' inline path uses the same key, never
    concurrently with a staged build of the same block)."""

    def wrapped(meta):
        t0 = time.perf_counter()  # dopt: allow-wallclock -- span timing only, never training math
        out = build(meta)
        timers.totals["host_batch_plan"] += time.perf_counter() - t0  # dopt: allow-wallclock -- span timing only, never training math
        timers.counts["host_batch_plan"] += 1
        return out

    return wrapped


class _Staged:
    """One in-flight background build (a bare thread per block: builds
    are long relative to thread spawn, and a pool would outlive the
    trainer)."""

    __slots__ = ("_out", "_err", "_thread")

    def __init__(self, build, meta):
        self._out = None
        self._err: BaseException | None = None
        self._thread = threading.Thread(
            target=self._run, args=(build, meta),
            name="dopt-prefetch", daemon=True)
        self._thread.start()

    def _run(self, build, meta) -> None:
        try:
            self._out = build(meta)
        except BaseException as e:  # surfaced at take()
            self._err = e

    def wait(self):
        self._thread.join()
        if self._err is not None:
            raise self._err
        out, self._out = self._out, None
        return out

    def wait_quiet(self) -> None:
        """Join and drop the result (discard path) — a failed discarded
        build is not an error, its payload was never going to be used."""
        self._thread.join()
        self._out = self._err = None


class PrefetchStager:
    """Bounded background staging queue for the blocked run loops.

    ``stage(key, build, meta)`` starts ``build(meta)`` on a background
    thread; ``take(key)`` joins and returns its payload, or ``None``
    when nothing was staged under that key (caller builds inline).
    ``build`` MUST be pure — every stateful draw belongs in the
    caller-side code that produced ``meta`` (see module docstring).
    """

    def __init__(self, *, depth: int = 2):
        if depth < 2:
            raise ValueError(f"PrefetchStager depth={depth} must be >= 2 "
                             "(the consumed block plus one staged)")
        self.depth = int(depth)
        self._pending: dict = {}

    def __len__(self) -> int:
        return len(self._pending)

    def stage(self, key, build, meta) -> None:
        """Begin building ``key``'s payload in the background."""
        if key in self._pending:
            raise RuntimeError(f"block {key!r} is already staged")
        if len(self._pending) >= self.depth - 1:
            raise RuntimeError(
                f"staging queue full ({len(self._pending)} pending, "
                f"depth {self.depth}): take() the oldest block first")
        self._pending[key] = _Staged(build, meta)

    def take(self, key):
        """The staged payload for ``key`` (blocking on its build), or
        ``None`` when it was never staged.  Any *other* pending keys
        are discarded — a key miss means the run's cursor moved (e.g.
        a resume), and stale payloads must not leak into later takes."""
        staged = self._pending.pop(key, None)
        if self._pending:
            self.discard()
        if staged is None:
            return None
        return staged.wait()

    def discard(self) -> None:
        """Drop every pending payload (checkpoint/resume points, loop
        teardown).  Joins the background builds first so no thread
        outlives the state it captured."""
        pending, self._pending = self._pending, {}
        for staged in pending.values():
            staged.wait_quiet()
