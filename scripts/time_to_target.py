"""Wall-clock-to-target-accuracy meter runs (BASELINE.json north-star
metric: "wall-clock to 90% test acc").

Runs baseline2 (16-worker D-SGD, CIFAR CNN) and baseline5 (32-worker
gossip ResNet-18) in throughput trim (native batch planner, fused round
blocks, eval every round) until the fleet-mean test accuracy crosses
the target or the preset's round budget runs out, then reports the
time-to-target via ``dopt.utils.metrics.time_to_target``.

Trim compute dtype is PER-PRESET and chosen by controlled experiment,
not by assumption (``TRIM_COMPUTE_DTYPE``): baseline2 runs float32 —
the r5 dtype control showed bf16 costs this corrected-head CNN ~2.7×
more rounds to target (bf16 0.355 vs f32 0.664 at round 10, identical
init/batches), which swamps bf16's 1.5× step-time win; baseline5's
GroupNorm ResNet shows no such tax and keeps bf16.  The bf16 trajectory
stays in the artifact as ``dtype_control`` (--dtype-control).

baseline2 additionally runs PAST the target to the full-oracle horizon
(``FULL_HORIZON``) so the artifact carries the same-round comparison
against the CONVERGED CPU baseline (oracle_final_acc_full, from
``scripts/oracle_full.py`` — ~95 min of single-core torch, run once and
merged from results/oracle_full_baseline2.json).  The meter itself is
unaffected: time-to-target is computed from the trajectory.

Data note: this environment has no network egress, so the runs use the
deterministic SYNTHETIC dataset at CIFAR scale — the artifact records
that explicitly.  Absolute accuracies are not comparable to real
CIFAR-10; the meter, cadence, and wall-clock accounting are exactly
what a real-data run would use (drop raw CIFAR under DOPT_DATA_DIR and
re-run).  seconds_per_round comes from steady-state blocks (the first,
compile-carrying block is excluded and reported separately).

Usage: python scripts/time_to_target.py [--target 0.9] [--quick]
       python scripts/time_to_target.py --dtype-control   # merge-only
Writes results/time_to_target.json.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dopt.presets import TRIM_COMPUTE_DTYPE  # noqa: E402  (evidence:
# the artifact's dtype_control block and results/README.md)

# Presets that run past the target to a fixed horizon so the artifact
# can compare accuracy AT THE FULL ORACLE'S ROUND (57 oracle rounds →
# TPU acc_by_round[57] needs 58 rounds; consensus-first eval).
FULL_HORIZON = {"baseline2": 58}


def run_preset(name: str, *, target: float, quick: bool, block: int = 5,
               compute_dtype: str | None = None,
               stop_at_target: bool = True) -> dict:
    from dopt.engine import GossipTrainer
    from dopt.presets import get_preset
    from dopt.utils.metrics import time_to_target

    dtype = compute_dtype or TRIM_COMPUTE_DTYPE.get(name, "bfloat16")
    cfg = get_preset(name)
    cfg = cfg.replace(
        model=dataclasses.replace(cfg.model, compute_dtype=dtype),
        data=dataclasses.replace(cfg.data, plan_impl="native"),
    )
    if cfg.gossip is not None:
        # Sharded per-round eval: the full mode's W·|test| sample-
        # forwards cost more device time than the baseline5 training
        # round itself (3.1 of 5.5 s/round measured); the fleet-mean
        # metric the meter reads is an unbiased |test|-forward estimate.
        cfg = cfg.replace(gossip=dataclasses.replace(
            cfg.gossip, eval_mode="sharded"))
    budget = 20 if quick else cfg.gossip.rounds
    horizon = FULL_HORIZON.get(name)
    if horizon and not quick:
        # Run to the fixed horizon regardless of the target so the
        # artifact carries acc at the full-oracle round; the meter
        # reads the trajectory, so the extra rounds never distort
        # time-to-target.
        stop_at_target = False
        budget = horizon
    trainer = GossipTrainer(cfg, eval_every=1)

    # Warmup block (UNTIMED for the steady rate, but real training —
    # its rounds count toward the trajectory and the budget): carries
    # the jit compile of the fused k-round block, so every measured
    # block below is steady-state even when the target is reached (or
    # the budget exhausted) within the first measured block.
    warm_k = min(block, budget)
    t0 = time.perf_counter()
    trainer.run(rounds=warm_k, block=warm_k)
    warm_s = time.perf_counter() - t0
    done = warm_k

    block_times: list[tuple[int, float]] = []
    reached_at = None

    def _reached():
        nonlocal reached_at
        accs = [r.get("avg_test_acc") for r in trainer.history.rows]
        if any(a is not None and a >= target for a in accs):
            reached_at = next(i for i, a in enumerate(accs)
                              if a is not None and a >= target)
            return True
        return False

    if not (stop_at_target and _reached()):
        while done < budget:
            k = min(block, budget - done)
            t0 = time.perf_counter()
            trainer.run(rounds=k, block=k)
            block_times.append((k, time.perf_counter() - t0))
            done += k
            if stop_at_target and _reached():
                break

    # Snapshot the trajectory BEFORE any extra timing-only rounds so the
    # artifact's accuracy fields describe exactly the reported run.
    history_rows = list(trainer.history.rows)
    accs = [r.get("avg_test_acc") for r in history_rows
            if r.get("avg_test_acc") is not None]
    _reached()  # fill reached_at for non-stopping runs

    # Steady-state seconds/round from the measured (post-warmup) blocks.
    # If the warmup block alone reached the target, time one extra block
    # of the same k — the trajectory is already decided, we only need an
    # honest steady rate for the seconds axis (those extra rounds are
    # excluded from the snapshot above).
    if not block_times:
        t0 = time.perf_counter()
        trainer.run(rounds=warm_k, block=warm_k)
        block_times.append((warm_k, time.perf_counter() - t0))
    sec_per_round = (sum(t for _, t in block_times)
                     / sum(k for k, _ in block_times))

    meter = time_to_target(trainer.history, target=target,
                           seconds_per_round=sec_per_round)
    return {
        "preset": name,
        "model": cfg.model.model,
        "workers": cfg.data.num_users,
        "compute_dtype": dtype,
        "data": f"synthetic ({cfg.data.dataset}-scale; no egress — real "
                "data via DOPT_DATA_DIR)",
        "target_acc": target,
        "time_to_target": meter,
        "seconds_per_round_steady": round(sec_per_round, 4),
        "warmup_block_seconds_incl_compile": round(warm_s, 2),
        "rounds_run": done,
        "reached_at_round": reached_at,
        "final_acc": round(accs[-1], 4) if accs else None,
        "best_acc": round(max(accs), 4) if accs else None,
        # per-round fleet-mean test acc (eval_every=1) — lets the oracle
        # comparison read the TPU accuracy at the oracle's round index.
        "acc_by_round": [round(a, 4) for a in accs],
    }


def oracle_baseline(cfg, rounds: int) -> dict:
    """Sequential torch-CPU run of the SAME config on the SAME synthetic
    data for ``rounds`` rounds — the CPU-baseline accuracy anchor the
    north-star phrasing compares against ("matching the CPU baseline's
    final accuracy at ≥50× speedup", BASELINE.json).  Faithful to the
    reference's round structure (two-phase consensus → local update,
    ``simulators.py:136-167``); model init is torch's own seeded init
    (distributionally equivalent — bitwise init parity is the
    reference-surface oracle's job, tests/test_oracle_parity.py)."""
    import numpy as np
    import torch

    sys.path.insert(0, str(Path(__file__).resolve().parent))
    from bench_suite import _torch_model

    from dopt.data import eval_batches, load_dataset, make_batch_plan, partition
    from dopt.engine.oracle import OracleWorker, consensus
    from dopt.topology import build_mixing_matrices

    g = cfg.gossip
    w = cfg.data.num_users
    ds = load_dataset(cfg.data.dataset, data_dir=cfg.data.data_dir,
                      train_size=cfg.data.synthetic_train_size,
                      test_size=cfg.data.synthetic_test_size, seed=cfg.seed)
    _, index_matrix = partition(ds.train_y, w, iid=cfg.data.iid,
                                shards_per_user=cfg.data.shards,
                                seed=cfg.seed)
    mixing = build_mixing_matrices(g.topology, g.mode, w, seed=cfg.seed)

    def nchw(x):
        return (np.ascontiguousarray(np.moveaxis(x, -1, -3))
                if x.ndim >= 4 else x)

    torch.manual_seed(cfg.seed)
    proto = _torch_model(cfg.model, cfg.model.input_shape)
    init = {k: v.clone() for k, v in proto.state_dict().items()}
    workers = []
    for _ in range(w):
        m = _torch_model(cfg.model, cfg.model.input_shape)
        m.load_state_dict({k: v.clone() for k, v in init.items()})
        workers.append(OracleWorker(m, lr=cfg.optim.lr,
                                    momentum=cfg.optim.momentum))

    t_start = time.perf_counter()
    for t in range(rounds):
        w_t = mixing.for_round(t)
        states = [wk.state() for wk in workers]
        new = [consensus([(float(w_t[i, j]), states[j])
                          for j in range(w) if w_t[i, j] > 0])
               for i in range(w)]
        for wk, st in zip(workers, new):
            wk.load(st)
        plan = make_batch_plan(index_matrix, batch_size=g.local_bs,
                               local_ep=g.local_ep, seed=cfg.seed,
                               round_idx=t, impl="numpy")
        bx = nchw(ds.train_x[plan.idx])
        by = ds.train_y[plan.idx]
        for i in range(w):
            workers[i].local_update(bx[i], by[i], plan.weight[i])
    # One more consensus sweep (round `rounds`' mixing) before the final
    # eval: the TPU engine's history row k is evaluated consensus-first
    # (round order consensus → eval → local, gossip.py block_fn), so the
    # comparable TPU number is acc_by_round[rounds] and this eval must
    # sit at the same trajectory position — k local updates + the
    # (k+1)-th consensus.
    w_t = mixing.for_round(rounds)
    states = [wk.state() for wk in workers]
    new = [consensus([(float(w_t[i, j]), states[j])
                      for j in range(w) if w_t[i, j] > 0])
           for i in range(w)]
    for wk, st in zip(workers, new):
        wk.load(st)
    ex, ey, ew = eval_batches(ds.test_x, ds.test_y, batch_size=256)
    exn = nchw(ex)
    accs = [wk.inference(exn, ey, ew)[0] for wk in workers]
    return {"oracle_rounds": rounds,
            "oracle_final_acc": round(float(np.mean(accs)), 4),
            "oracle_seconds": round(time.perf_counter() - t_start, 1)}


# Oracle (sequential torch-CPU) round caps: the comparison runs the
# oracle for min(rounds the TPU run needed, cap) rounds and compares
# fleet-mean accuracy AT THE SAME ROUND INDEX — apples-to-apples on
# trajectory position.  baseline5's ResNet-18 round costs minutes of
# CPU, hence the tighter cap (the truncation is recorded in the
# artifact; baseline2's FULL oracle is the separate oracle_full.py
# payload merged below).
ORACLE_CAPS = {"baseline2": 10, "baseline5": 2}

FULL_ORACLE_PAYLOAD = Path("results/oracle_full_baseline2.json")


def merge_full_oracle(row: dict) -> None:
    """Attach the full-horizon oracle payload (oracle_full.py) and the
    same-round TPU comparison to a baseline2 result row."""
    if row["preset"] != "baseline2" or not FULL_ORACLE_PAYLOAD.exists():
        return
    payload = json.loads(FULL_ORACLE_PAYLOAD.read_text())
    row.update({k: v for k, v in payload.items() if k != "preset"})
    k = payload["oracle_rounds_full"]
    acc = row.get("acc_by_round", [])
    row["tpu_acc_at_full_oracle_round"] = acc[k] if len(acc) > k else None
    if len(acc) <= k:
        print(f"warning: TPU trajectory has {len(acc)} rounds <= full "
              f"oracle horizon {k}; same-round comparison unavailable",
              file=sys.stderr)
    fa = row.get("final_acc")
    # A row whose run never reached a final eval carries final_acc=None
    # — write an explicit null delta instead of crashing the merge.
    row["tpu_final_minus_full_oracle"] = (
        round(fa - payload["oracle_final_acc_full"], 4)
        if fa is not None else None)


def add_dtype_control(out_path: Path, *, target: float, quick: bool,
                      preset: str = "baseline2",
                      dtype: str = "bfloat16") -> None:
    """Run ``preset`` once with the OTHER compute dtype over the full
    horizon and merge the trajectory into the existing artifact as the
    single-variable dtype control: same engine, same batch planner,
    same init and batch order — only the compute dtype differs.
    Settles whether per-round convergence differences are a dtype tax
    or an init/batch-order artifact (VERDICT r4)."""
    r = run_preset(preset, target=target, quick=quick,
                   compute_dtype=dtype, stop_at_target=False)
    ttt = json.loads(out_path.read_text())
    for row in ttt["results"]:
        if row["preset"] == preset:
            acc = r["acc_by_round"]
            row["dtype_control"] = {
                "compute_dtype": dtype,
                "seconds_per_round_steady": r["seconds_per_round_steady"],
                "rounds_run": r["rounds_run"],
                "reached_at_round": r["reached_at_round"],
                "final_acc": r["final_acc"],
                "best_acc": r["best_acc"],
                "acc_by_round": acc,
            }
            for key, k in [("control_acc_at_oracle_round",
                            row.get("oracle_rounds")),
                           ("control_acc_at_full_oracle_round",
                            row.get("oracle_rounds_full"))]:
                row[key] = (acc[k] if k is not None and len(acc) > k
                            else None)
    out_path.write_text(json.dumps(ttt, indent=2) + "\n")
    print(f"merged {dtype} control into {out_path}: "
          f"final {r['final_acc']}, reached@{r['reached_at_round']}")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--target", type=float, default=0.9)
    ap.add_argument("--quick", action="store_true",
                    help="cap at 20 rounds per preset (machinery check)")
    ap.add_argument("--only", nargs="*", default=None)
    ap.add_argument("--skip-oracle", action="store_true",
                    help="skip the sequential torch-CPU baseline column")
    ap.add_argument("--reuse-oracle", action="store_true",
                    help="copy the truncated-oracle column from the "
                         "existing artifact instead of re-running torch "
                         "(the oracle depends only on preset+seed, not on "
                         "the TPU trim; baseline5's column costs ~2h CPU)")
    ap.add_argument("--dtype-control", action="store_true",
                    help="run ONLY the baseline2 bf16 dtype-control and "
                         "merge it into the existing artifact")
    ap.add_argument("--out", default="results/time_to_target.json")
    args = ap.parse_args()

    if args.dtype_control:
        add_dtype_control(Path(args.out), target=args.target,
                          quick=args.quick)
        return 0

    from dopt.presets import get_preset

    names = args.only or ["baseline2", "baseline5"]
    results = [run_preset(n, target=args.target, quick=args.quick)
               for n in names]
    cached = {}
    if args.reuse_oracle and Path(args.out).exists():
        cached = {row["preset"]: row
                  for row in json.loads(Path(args.out).read_text())["results"]
                  if "oracle_final_acc" in row}
    for r in results:
        if not args.skip_oracle and r["preset"] in cached and (
                cached[r["preset"]]["oracle_rounds"] <= r["rounds_run"] - 1):
            old_row = cached[r["preset"]]
            for key in ("oracle_rounds", "oracle_final_acc",
                        "oracle_seconds"):
                r[key] = old_row[key]
            k = r["oracle_rounds"]
            r["tpu_acc_at_oracle_round"] = (
                r["acc_by_round"][k] if len(r["acc_by_round"]) > k else None)
            r["tpu_best_minus_oracle"] = round(
                r["best_acc"] - r["oracle_final_acc"], 4)
            merge_full_oracle(r)
        elif not args.skip_oracle:
            cap = ORACLE_CAPS.get(r["preset"], 5)
            # Oracle runs k rounds + the (k+1)-th consensus; the matching
            # TPU number is acc_by_round[k] (consensus-first eval), so k
            # must stay strictly below the TPU rounds run.
            orounds = max(1, min(r["rounds_run"] - 1, cap,
                                 2 if args.quick else 10**9))
            om = oracle_baseline(get_preset(r["preset"]), orounds)
            r.update(om)
            k = om["oracle_rounds"]
            tpu_at_k = (r["acc_by_round"][k]
                        if len(r["acc_by_round"]) > k else None)
            # The oracle differs from the TPU run in init (torch's own
            # seeded init) and batch order (numpy vs native planner), so
            # same-round EARLY-trajectory accuracy carries those nuisance
            # factors alongside dtype; the dtype_control block isolates
            # dtype properly.  The checkable north-star claims live in
            # tests/test_artifacts.py (best ≥ truncated oracle; final ≥
            # full oracle − 1pt on baseline2).
            r["tpu_acc_at_oracle_round"] = tpu_at_k
            r["tpu_best_minus_oracle"] = round(
                r["best_acc"] - om["oracle_final_acc"], 4)
            merge_full_oracle(r)
        m = r["time_to_target"]
        status = (f"reached at round {m['round']} "
                  f"(~{m['seconds']:.1f}s)" if m["reached"]
                  else f"not reached in {r['rounds_run']} rounds "
                       f"(best {r['best_acc']})")
        print(f"{r['preset']} [{r['compute_dtype']}]: target "
              f"{r['target_acc']} {status} "
              f"[{r['seconds_per_round_steady']*1e3:.0f} ms/round steady]"
              + (f" oracle@{r['oracle_rounds']}r={r['oracle_final_acc']}"
                 f" tpu@same={r.get('tpu_acc_at_oracle_round')}"
                 if "oracle_final_acc" in r else ""))

    import jax

    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    if args.only and out.exists():
        # Partial regeneration: replace only the re-run presets, keep
        # the rest (baseline5's truncated oracle alone costs ~2h of
        # single-core torch — never discard it incidentally).
        old = json.loads(out.read_text())["results"]
        fresh = {r["preset"]: r for r in results}
        results = [fresh.pop(r["preset"], r) for r in old]
        results += list(fresh.values())
    out.write_text(json.dumps(
        {"suite": "time_to_target", "device": str(jax.devices()[0]),
         "results": results}, indent=2) + "\n")
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
