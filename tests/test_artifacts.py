"""Tests over the committed benchmark/parity artifacts and their
harnesses.

* Real-data parity: invokes ``scripts/parity_real.py`` so the instant
  raw MNIST lands under ``$DOPT_DATA_DIR`` the BASELINE.md numbers
  (FedAvg 97.82% etc., ``Primal and Dual Decomposition.ipynb`` cell 13)
  are asserted automatically; without data the skip is VISIBLE in the
  test output rather than silently absent.
* time_to_target: the committed artifact must carry the torch-CPU
  oracle baseline column, and the accuracy the TPU run reaches must
  dominate the oracle's truncated-horizon accuracy — the internal
  completeness of the "matching CPU-baseline accuracy at ≥50×"
  north-star claim (BASELINE.json).  Same-round EARLY accuracy is
  recorded but not asserted (the oracle differs in init, batch order,
  and dtype).
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def test_parity_real_harness():
    """Run the real-data parity harness; skip VISIBLY when no raw MNIST
    is on disk (the harness exits 0 with a 'skipped' marker)."""
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "parity_real.py")],
        capture_output=True, text=True, timeout=3600,
        cwd=REPO)
    out = r.stdout + r.stderr
    if "skipped: no real data" in out:
        pytest.skip("no raw MNIST under $DOPT_DATA_DIR — parity_real "
                    "visible-skip (machinery exercised, data absent)")
    assert r.returncode == 0, f"parity_real failed:\n{out}"


def _load_time_to_target():
    path = REPO / "results" / "time_to_target.json"
    if not path.exists():
        pytest.skip("results/time_to_target.json not committed yet")
    return json.loads(path.read_text())


def test_time_to_target_has_oracle_baseline():
    art = _load_time_to_target()
    if all("oracle_final_acc" not in r for r in art["results"]):
        pytest.skip("committed artifact predates the oracle column — "
                    "regenerate with scripts/time_to_target.py (no "
                    "--skip-oracle)")
    for r in art["results"]:
        assert "oracle_final_acc" in r, (
            f"{r['preset']}: artifact lacks the torch-CPU oracle column "
            "(run scripts/time_to_target.py without --skip-oracle)")
        assert r.get("oracle_rounds", 0) >= 1


def test_time_to_target_tpu_matches_oracle():
    """The best accuracy the TPU run reaches must dominate the
    sequential CPU baseline's truncated-horizon accuracy (baseline5's
    full-horizon oracle is CPU-infeasible here — its 2-round leg alone
    costs >2h of single-core torch; the wall-clock is recorded in
    oracle_seconds)."""
    art = _load_time_to_target()
    for r in art["results"]:
        if "tpu_best_minus_oracle" not in r:
            pytest.skip(f"{r['preset']}: artifact predates the "
                        "best-vs-oracle column")
        assert r["tpu_best_minus_oracle"] >= -0.005, (
            f"{r['preset']}: best TPU acc trails the truncated "
            f"oracle ({r['oracle_final_acc']}) — "
            f"delta {r['tpu_best_minus_oracle']}")


def test_time_to_target_baseline2_matches_full_oracle():
    """The controlled north-star accuracy claim: at the FULL oracle
    horizon (57 rounds + the 58th consensus — the converged CPU
    baseline, scripts/oracle_full.py), baseline2's TPU run must be
    within 1 point of the oracle's final accuracy at the SAME round
    index.  The run is the f32 trim, so the comparison is same-dtype;
    the bf16 trajectory is the artifact's dtype_control (−1.3 pt at
    the same horizon — the measured bf16 tax)."""
    art = _load_time_to_target()
    r = next((x for x in art["results"] if x["preset"] == "baseline2"),
             None)
    if r is None or "oracle_final_acc_full" not in r:
        pytest.skip("baseline2 full-oracle column not in artifact — "
                    "run scripts/oracle_full.py then time_to_target.py")
    at_k = r.get("tpu_acc_at_full_oracle_round")
    assert at_k is not None, "TPU trajectory shorter than oracle horizon"
    assert at_k >= r["oracle_final_acc_full"] - 0.01, (
        f"TPU acc at round {r['oracle_rounds_full']} ({at_k}) trails "
        f"the converged oracle ({r['oracle_final_acc_full']}) by more "
        "than 1 point")


def test_time_to_target_has_dtype_control():
    """The baseline2 row must carry the single-variable dtype control
    (same init, batches, engine — only compute dtype differs), which is
    what turns the bf16-vs-f32 convergence claim into a controlled
    experiment instead of a confounded oracle comparison."""
    art = _load_time_to_target()
    r = next((x for x in art["results"] if x["preset"] == "baseline2"),
             None)
    if r is None or "dtype_control" not in r:
        pytest.skip("dtype_control not merged yet — run "
                    "scripts/time_to_target.py --dtype-control")
    c = r["dtype_control"]
    assert c["compute_dtype"] != r["compute_dtype"]
    assert len(c["acc_by_round"]) >= 10
