"""Multi-round trajectory parity: TPU engine vs the faithful torch oracle.

For each config, runs N full rounds on BOTH backends from the same init,
same mixing matrices / client samples, and byte-identical batch plans,
then records the worst per-round parameter divergence.  This is the
numerics-trust artifact: the step-level oracle tests
(tests/test_oracle_parity.py) pin single steps; this script shows whole
TRAJECTORIES stay glued together across rounds on every algorithm
family the reference has.

Gossip configs replicate the reference's two-phase synchronous schedule
(simulators.py:147-165); federated configs replicate the server round
(servers.py:50-81) including partial participation, persistent client
optimizers, FedProx/FedADMM gradient edits, and dual ascent.

Writes --out (default results/oracle_trajectory.json) and prints one
line per config.  CPU-heavy (sequential torch): sizes are small.

Usage: python scripts/oracle_trajectory.py [--rounds 5]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import numpy as np

from dopt.config import (DataConfig, ExperimentConfig, FederatedConfig,
                         GossipConfig, ModelConfig, OptimizerConfig)
from dopt.data import gather_batches, make_batch_plan
from dopt.engine import FederatedTrainer, GossipTrainer
from dopt.engine.oracle import (OracleWorker, consensus,
                                flax_cnn_params_to_torch, nhwc_to_nchw,
                                torch_cnn_params_to_flax, torch_reference_cnn,
                                _flatten2)
from dopt.utils.prng import host_rng

N_WORKERS = 4
LR, MOM, RHO = 0.05, 0.5, 0.1
BS, SEED = 16, 11


def _base_cfg(name: str, **kw) -> ExperimentConfig:
    return ExperimentConfig(
        name=name, seed=SEED,
        data=DataConfig(dataset="synthetic", num_users=N_WORKERS, iid=False,
                        shards=2, synthetic_train_size=128,
                        synthetic_test_size=32),
        model=ModelConfig(model="model1", input_shape=(28, 28, 1),
                          faithful=True),
        optim=OptimizerConfig(lr=LR, momentum=MOM, rho=RHO),
        **kw,
    )


def _workers(init_params, algorithm="sgd"):
    out = []
    for _ in range(N_WORKERS):
        tm = torch_reference_cnn(1, 28, 512, faithful=True)
        tm.load_state_dict(flax_cnn_params_to_torch(init_params, 28))
        out.append(OracleWorker(tm, lr=LR, momentum=MOM, rho=RHO,
                                algorithm=algorithm))
    return out


def _divergence(trainer_params, workers) -> tuple[float, float]:
    """(max absolute entry diff, global relative L2 error) across the
    fleet.  Relative L2 is the stable trajectory metric — absolute max
    lands on the largest-magnitude entries and grows with the faithful
    objective's chaotic amplification."""
    worst = 0.0
    num = den = 0.0
    final_j = jax.device_get(trainer_params)
    for i, wk in enumerate(workers):
        p_t = _flatten2(torch_cnn_params_to_flax(wk.model.state_dict(), 28))
        p_j = _flatten2(jax.tree.map(lambda x: x[i], final_j))
        for k in p_t:
            d = np.asarray(p_j[k], np.float64) - np.asarray(p_t[k], np.float64)
            worst = max(worst, float(np.abs(d).max()))
            num += float((d ** 2).sum())
            den += float((np.asarray(p_t[k], np.float64) ** 2).sum())
    return worst, float(np.sqrt(num / max(den, 1e-30)))


def gossip_trajectory(topology: str, mode: str, rounds: int,
                      local_ep: int = 1) -> dict:
    cfg = _base_cfg(
        f"traj-dsgd-{topology}-{mode}",
        gossip=GossipConfig(algorithm="dsgd", topology=topology, mode=mode,
                            rounds=rounds, local_ep=local_ep, local_bs=BS),
    )
    tr = GossipTrainer(cfg)
    init = jax.device_get(jax.tree.map(lambda x: x[0], tr.params))
    mixing, index_matrix, ds = tr.mixing, tr._train_matrix, tr.dataset
    workers = _workers(init)

    diffs = []
    for t in range(rounds):
        tr.run(rounds=1)
        w = mixing.for_round(t)
        states = [wk.state() for wk in workers]
        new = [consensus([(float(w[i, j]), states[j])
                          for j in range(N_WORKERS) if w[i, j] > 0])
               for i in range(N_WORKERS)]
        for wk, st in zip(workers, new):
            wk.load(st)
        plan = make_batch_plan(index_matrix, batch_size=BS,
                               local_ep=local_ep, seed=SEED, round_idx=t)
        bx, by, bw = gather_batches(ds.train_x, ds.train_y, plan)
        for i, wk in enumerate(workers):
            wk.local_update(nhwc_to_nchw(bx[i]), by[i], bw[i])
        diffs.append(_divergence(tr.params, workers))
    return {"config": cfg.name, "rounds": rounds,
            "max_absdiff_per_round": [round(a, 8) for a, _ in diffs],
            "rel_l2_per_round": [round(r, 8) for _, r in diffs]}


def federated_trajectory(algorithm: str, rounds: int, frac: float = 0.5,
                         cfg: ExperimentConfig | None = None) -> dict:
    cfg = cfg or _base_cfg(
        f"traj-{algorithm}",
        federated=FederatedConfig(algorithm=algorithm, frac=frac,
                                  rounds=rounds, local_ep=1, local_bs=BS),
    )
    frac = cfg.federated.frac
    local_ep = cfg.federated.local_ep
    bs = cfg.federated.local_bs
    n = cfg.data.num_users
    lr, mom, rho = cfg.optim.lr, cfg.optim.momentum, cfg.optim.rho
    tr = FederatedTrainer(cfg)
    init = jax.device_get(tr.theta)
    index_matrix, ds = tr._train_matrix, tr.dataset
    workers = []
    for _ in range(n):
        tm = torch_reference_cnn(1, 28, 512, faithful=True)
        tm.load_state_dict(flax_cnn_params_to_torch(init, 28))
        workers.append(OracleWorker(
            tm, lr=lr, momentum=mom, rho=rho,
            algorithm={"fedavg": "sgd"}.get(algorithm, algorithm)))
    import torch

    theta_t = {k: v.clone() for k, v in
               flax_cnn_params_to_torch(init, 28).items()}
    # Same sampling stream as FederatedTrainer._sample_indices.
    rng = host_rng(cfg.seed, 314159)

    diffs = []
    for t in range(rounds):
        tr.run(rounds=1)
        m = max(int(frac * n), 1)
        sel = np.sort(rng.choice(n, m, replace=False))
        plan = make_batch_plan(index_matrix, batch_size=bs,
                               local_ep=local_ep, seed=cfg.seed, round_idx=t)
        bx, by, bw = gather_batches(ds.train_x, ds.train_y, plan)
        for i in sel:
            wk = workers[i]
            wk.load(theta_t)
            needs_theta = algorithm in ("fedprox", "fedadmm")
            wk.local_update(nhwc_to_nchw(bx[i]), by[i], bw[i],
                            theta=theta_t if needs_theta else None)
            if algorithm == "fedadmm":
                wk.update_duals(theta_t)
        with torch.no_grad():
            states = [workers[i].state() for i in sel]
            theta_t = {k: sum(st[k] for st in states) / len(states)
                       for k in theta_t}
        diffs.append(_divergence(tr.params, workers))
    # Also check the global model.
    theta_flax = _flatten2(torch_cnn_params_to_flax(theta_t, 28))
    theta_j = _flatten2(jax.device_get(tr.theta))
    theta_diff = max(float(np.abs(np.asarray(theta_j[k])
                                  - np.asarray(theta_flax[k])).max())
                     for k in theta_flax)
    return {"config": cfg.name, "rounds": rounds,
            "max_absdiff_per_round": [round(a, 8) for a, _ in diffs],
            "rel_l2_per_round": [round(r, 8) for _, r in diffs],
            "final_theta_absdiff": round(theta_diff, 8)}


def reference_shaped_federated(rounds: int = 20) -> ExperimentConfig:
    """The P1 notebook config's SHAPE (20 rounds, local_ep=10,
    local_bs=50, lr=0.1, momentum=0.5, IID, deterministic 90/10 local
    holdout — cells 8/10) subsampled to 10 users / frac 0.3 so the
    sequential 1-core torch oracle stays feasible (VERDICT r1 #7)."""
    return ExperimentConfig(
        name="traj-reference-fedavg-shape", seed=SEED,
        data=DataConfig(dataset="synthetic", num_users=10, iid=True,
                        synthetic_train_size=1000, synthetic_test_size=64,
                        local_holdout=0.1, holdout_mode="deterministic"),
        model=ModelConfig(model="model1", input_shape=(28, 28, 1),
                          faithful=True),
        optim=OptimizerConfig(lr=0.1, momentum=0.5, rho=0.1),
        federated=FederatedConfig(algorithm="fedavg", frac=0.3,
                                  rounds=rounds, local_ep=10, local_bs=50),
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--long", action="store_true",
                    help="add the long-horizon reference-shaped runs: "
                         "20-round federated (local_ep=10, bs=50, lr=0.1, "
                         "90/10 holdout) and 12-round multi-epoch gossip")
    ap.add_argument("--out", default="results/oracle_trajectory.json")
    args = ap.parse_args()

    results = []
    for topo, mode in [("circle", "stochastic"),
                       ("complete", "stochastic"),
                       ("circle", "double_stochastic"),
                       ("complete", "double_stochastic")]:
        r = gossip_trajectory(topo, mode, args.rounds)
        results.append(r)
        print(f"{r['config']}: rel_l2 {max(r['rel_l2_per_round'])}")
    for algo in ("fedavg", "fedprox", "fedadmm"):
        r = federated_trajectory(algo, args.rounds)
        results.append(r)
        print(f"{r['config']}: rel_l2 {max(r['rel_l2_per_round'])} "
              f"(theta absdiff {r['final_theta_absdiff']})")
    if args.long:
        r = gossip_trajectory("circle", "stochastic", 12, local_ep=2)
        r["config"] += "-12r-2ep"
        results.append(r)
        print(f"{r['config']}: rel_l2 {max(r['rel_l2_per_round'])}")
        r = federated_trajectory("fedavg", 20,
                                 cfg=reference_shaped_federated(20))
        results.append(r)
        print(f"{r['config']}: rel_l2 {max(r['rel_l2_per_round'])} "
              f"(theta absdiff {r['final_theta_absdiff']})")

    worst = max(max(r["rel_l2_per_round"]) for r in results)
    payload = {"suite": "oracle trajectory parity",
               "workers": N_WORKERS, "rounds": args.rounds,
               "long_horizon": args.long,
               "worst_rel_l2": worst, "results": results}
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(payload, indent=2) + "\n")
    print(f"worst relative-L2 across all configs/rounds: {worst}; wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
