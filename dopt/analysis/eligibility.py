"""Eligibility-matrix extractor: ``python -m dopt.analysis.eligibility``.

The composition matrix — which feature pairs the constructors reject
(scatter × choco, population × staleness, compact × comm_dtype, ...) —
used to live twice: once as ``raise ValueError`` guards scattered over
the config/engine constructors, once as prose tables in
ARCHITECTURE.md, with nothing keeping them in sync.  This gate makes
the CODE the source of truth and the doc a checked projection of it:

* **Harvest** — a stdlib-``ast`` pass over the constructor surface
  (``dopt/config.py``, ``dopt/engine/``, ``dopt/population.py``,
  ``dopt/robust.py``, ``dopt/parallel/``) collects every
  ``raise ValueError`` site: file, line, enclosing scope, the guard
  condition, and the message template (f-string holes become ``{}``).
  Sites whose message uses the composition-rejection idiom ("does not
  compose", "incompatible", "only applies", "drop one of the two",
  ...) are classified ``composition: true`` — the feature×feature
  matrix rows.

* **Artifact** — ``--write`` serializes the harvest to
  ``results/eligibility.json`` (schema below).  The default (check)
  mode re-harvests and compares against the committed artifact by
  ``(file, scope, message)`` key — line numbers may drift freely, new
  or vanished rejections fail CI until the artifact is regenerated.

* **Doc cross-check** — ARCHITECTURE.md carries the consolidated
  matrix between ``<!-- eligibility-matrix:begin/end -->`` markers,
  one row per composition rejection keyed by a message prefix.  Check
  mode verifies both directions: every doc row's key still matches a
  harvested message, and every harvested composition site is covered
  by a doc row.  ``--update-doc`` regenerates the table in place.

Artifact schema (``results/eligibility.json``)::

    {"v": 1,
     "roots": ["dopt/config.py", ...],
     "counts": {"sites": N, "construction": M, "composition": K},
     "sites": [{"file": ..., "line": ..., "scope": ...,
                "construction": true|false, "composition": true|false,
                "guard": "pop.cohort != w" | null,
                "message": "gossip population mode does not ..."}]}

Exit codes: 0 in sync, 1 drift, 2 usage error; ``--json`` prints the
machine-readable report.
"""

from __future__ import annotations

import argparse
import ast
import json
import re
import sys
from pathlib import Path
from typing import Any, Iterable

from dopt.analysis.common import (EXIT_USAGE, Finding, emit_report,
                                  iter_py_files)

# The constructor surface the matrix lives in.  dopt/serve/daemon.py
# joins for the serve-mode construction rejections (engine choice,
# on_term); the rest of dopt/serve is command-schema validation, not
# configuration eligibility.
DEFAULT_ROOTS = ("dopt/config.py", "dopt/engine", "dopt/population.py",
                 "dopt/robust.py", "dopt/parallel", "dopt/serve/daemon.py")
DEFAULT_ARTIFACT = "results/eligibility.json"
DEFAULT_DOC = "docs/ARCHITECTURE.md"

DOC_BEGIN = "<!-- eligibility-matrix:begin -->"
DOC_END = "<!-- eligibility-matrix:end -->"

# The message idioms that mark a feature x feature composition
# rejection (vs plain value validation).  New rejections written in
# these idioms must land a doc-matrix row or the gate fails — that is
# the drift contract, so USE the idiom when rejecting a composition.
_COMPOSITION_PHRASES = (
    "does not compose", "incompatible", "only applies",
    "drop one of the two", "does not cover", "-engine knob",
    "-engine feature", "jax-backend feature", "are not supported",
    "keep the dense path", "restructures the", "no dense mixing step",
)

# Scopes that run at construction/validation time.
_CTOR_NAMES = re.compile(r"(^|\.)(__init__|__post_init__|validate\w*|"
                         r"_validate\w*|check\w*)$")

_KEY_LEN = 72


def _msg_template(node: ast.AST) -> str:
    """The message argument as a template string: constant parts kept,
    f-string holes and ``%``/``.format`` interpolations become ``{}``,
    whitespace normalized."""
    parts: list[str] = []

    def walk(n: ast.AST) -> None:
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            parts.append(n.value)
        elif isinstance(n, ast.JoinedStr):
            for v in n.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                else:
                    parts.append("{}")
        elif isinstance(n, ast.BinOp):
            for side in (n.left, n.right):
                if isinstance(side, (ast.Constant, ast.JoinedStr,
                                     ast.BinOp)):
                    walk(side)
                else:
                    parts.append("{}")
        elif isinstance(n, ast.Call):
            # "...".format(...) — keep the receiver's constants.
            if isinstance(n.func, ast.Attribute):
                walk(n.func.value)

    walk(node)
    return re.sub(r"\s+", " ", "".join(parts)).strip()


class _RaiseHarvester(ast.NodeVisitor):
    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.source = source
        self.scope: list[str] = []
        self.guards: list[ast.expr] = []
        self.sites: list[dict[str, Any]] = []

    def _enter_scoped(self, node, name: str) -> None:
        self.scope.append(name)
        self.generic_visit(node)
        self.scope.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_scoped(node, node.name)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._enter_scoped(node, node.name)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._enter_scoped(node, node.name)

    def visit_If(self, node: ast.If) -> None:
        self.guards.append(node.test)
        for stmt in node.body:
            self.visit(stmt)
        self.guards.pop()
        for stmt in node.orelse:
            self.visit(stmt)

    def visit_Raise(self, node: ast.Raise) -> None:
        exc = node.exc
        if not (isinstance(exc, ast.Call) and exc.args):
            return
        fn = exc.func
        name = (fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute) else "")
        if name != "ValueError":
            return
        message = _msg_template(exc.args[0])
        if not message:
            return
        scope = ".".join(self.scope) or "<module>"
        guard = (ast.get_source_segment(self.source, self.guards[-1])
                 if self.guards else None)
        if guard is not None:
            guard = re.sub(r"\s+", " ", guard).strip()
        self.sites.append({
            "file": self.path,
            "line": node.lineno,
            "scope": scope,
            "construction": bool(_CTOR_NAMES.search(scope)
                                 or scope == "<module>"),
            "composition": any(p in message
                               for p in _COMPOSITION_PHRASES),
            "guard": guard,
            "message": message,
        })


def harvest(roots: Iterable[str] = DEFAULT_ROOTS) -> dict[str, Any]:
    """Harvest every ``raise ValueError`` site under ``roots`` into the
    artifact dict (sorted by file, then line)."""
    sites: list[dict[str, Any]] = []
    for p in iter_py_files(roots):
        src = p.read_text()
        h = _RaiseHarvester(p.as_posix(), src)
        h.visit(ast.parse(src, filename=str(p)))
        sites.extend(h.sites)
    sites.sort(key=lambda s: (s["file"], s["line"]))
    return {
        "v": 1,
        "roots": sorted(Path(r).as_posix() for r in roots),
        "counts": {
            "sites": len(sites),
            "construction": sum(s["construction"] for s in sites),
            "composition": sum(s["composition"] for s in sites),
        },
        "sites": sites,
    }


def site_key(site: dict[str, Any]) -> tuple[str, str, str]:
    """Identity of a rejection, line-number-free: committed artifacts
    stay fresh across pure line drift."""
    return (site["file"], site["scope"], site["message"])


def doc_key(site: dict[str, Any]) -> str:
    """The message prefix a doc-matrix row carries (word-boundary
    trimmed, interpolation holes stripped at the cut)."""
    msg = site["message"]
    if len(msg) <= _KEY_LEN:
        return msg
    cut = msg[:_KEY_LEN]
    cut = cut[:cut.rfind(" ")] if " " in cut else cut
    return cut.rstrip(" {")


def render_doc_table(art: dict[str, Any]) -> str:
    """The consolidated composition matrix as a markdown table, one row
    per composition-rejection site."""
    lines = [
        "| enforced at | rejected composition (message key) |",
        "|---|---|",
    ]
    for s in art["sites"]:
        if not s["composition"]:
            continue
        where = f"`{s['file'].removeprefix('dopt/')}` · `{s['scope']}`"
        lines.append(f"| {where} | `{doc_key(s)}` |")
    return "\n".join(lines)


def parse_doc_rows(doc_text: str) -> list[str] | None:
    """Message keys from the marker-delimited doc table (the backticked
    cell of each data row); None when the markers are absent."""
    try:
        start = doc_text.index(DOC_BEGIN) + len(DOC_BEGIN)
        end = doc_text.index(DOC_END, start)
    except ValueError:
        return None
    keys: list[str] = []
    for line in doc_text[start:end].splitlines():
        line = line.strip()
        if not line.startswith("|") or set(line) <= {"|", "-", " "}:
            continue
        cells = [c.strip() for c in line.strip("|").split("|")]
        if not cells or cells[-1] in ("rejected composition (message key)",):
            continue
        m = re.findall(r"`([^`]+)`", cells[-1])
        if m:
            keys.append(m[-1])
    return keys


def update_doc(doc_path: Path, art: dict[str, Any]) -> None:
    text = doc_path.read_text()
    if DOC_BEGIN not in text or DOC_END not in text:
        raise ValueError(
            f"{doc_path}: missing {DOC_BEGIN}/{DOC_END} markers")
    head, rest = text.split(DOC_BEGIN, 1)
    _, tail = rest.split(DOC_END, 1)
    table = render_doc_table(art)
    doc_path.write_text(
        f"{head}{DOC_BEGIN}\n{table}\n{DOC_END}{tail}")


def cross_check(art: dict[str, Any], committed: dict[str, Any] | None,
                doc_keys: list[str] | None,
                artifact_path: str, doc_path: str) -> list[Finding]:
    """Both drift directions for both projections (artifact and doc)."""
    findings: list[Finding] = []
    if committed is None:
        findings.append(Finding(
            "artifact-missing", artifact_path, 0,
            "no committed eligibility artifact — run `python -m "
            "dopt.analysis.eligibility --write` and commit it"))
    else:
        have = {site_key(s): s for s in committed.get("sites", ())}
        want = {site_key(s): s for s in art["sites"]}
        for k in sorted(set(want) - set(have)):
            s = want[k]
            findings.append(Finding(
                "artifact-stale", s["file"], s["line"],
                f"rejection not in {artifact_path} (run --write): "
                f"{doc_key(s)!r}"))
        for k in sorted(set(have) - set(want)):
            s = have[k]
            findings.append(Finding(
                "artifact-stale", artifact_path, 0,
                f"committed rejection no longer in the code "
                f"({s['file']}:{s['scope']}): {doc_key(s)!r}"))
    if doc_keys is None:
        findings.append(Finding(
            "doc-missing", doc_path, 0,
            f"no {DOC_BEGIN} table in the doc — add the markers and "
            "run `python -m dopt.analysis.eligibility --update-doc`"))
        return findings
    messages = [s["message"] for s in art["sites"]]
    for key in doc_keys:
        if not any(key in m for m in messages):
            findings.append(Finding(
                "doc-without-code", doc_path, 0,
                f"doc matrix row matches no code rejection: {key!r}"))
    for s in art["sites"]:
        if not s["composition"]:
            continue
        if not any(key in s["message"] for key in doc_keys):
            findings.append(Finding(
                "code-without-doc", s["file"], s["line"],
                f"composition rejection has no doc matrix row "
                f"(run --update-doc): {doc_key(s)!r}"))
    return findings


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dopt.analysis.eligibility",
        description="Harvest construction-time eligibility rejections "
                    "and cross-check code / artifact / doc.")
    ap.add_argument("roots", nargs="*", metavar="PATH",
                    help=f"harvest roots (default: {' '.join(DEFAULT_ROOTS)})")
    ap.add_argument("--artifact", default=DEFAULT_ARTIFACT,
                    help=f"committed JSON artifact (default: "
                         f"{DEFAULT_ARTIFACT})")
    ap.add_argument("--doc", default=DEFAULT_DOC,
                    help=f"doc carrying the matrix table (default: "
                         f"{DEFAULT_DOC})")
    ap.add_argument("--write", action="store_true",
                    help="(re)write the artifact instead of checking it")
    ap.add_argument("--update-doc", action="store_true",
                    help="regenerate the doc table between the markers")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)
    roots = args.roots or list(DEFAULT_ROOTS)
    missing = [r for r in roots if not Path(r).exists()]
    if missing:
        print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
        return EXIT_USAGE

    art = harvest(roots)
    wrote = []
    if args.write:
        out = Path(args.artifact)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(art, indent=1, sort_keys=True) + "\n")
        wrote.append(args.artifact)
    if args.update_doc:
        try:
            update_doc(Path(args.doc), art)
        except (OSError, ValueError) as e:
            print(str(e), file=sys.stderr)
            return EXIT_USAGE
        wrote.append(args.doc)

    committed: dict[str, Any] | None = None
    try:
        committed = json.loads(Path(args.artifact).read_text())
    except (OSError, ValueError):
        pass
    doc_keys: list[str] | None = None
    try:
        doc_keys = parse_doc_rows(Path(args.doc).read_text())
    except OSError:
        pass
    findings = cross_check(art, committed, doc_keys,
                           args.artifact, args.doc)
    extra = {"counts": art["counts"], "wrote": wrote}
    return emit_report(findings, as_json=args.json,
                       tool="dopt.analysis.eligibility",
                       checked=art["counts"]["sites"], unit="site",
                       extra=extra)


if __name__ == "__main__":
    raise SystemExit(main())
