"""Streaming health monitor + rules + serve/watch/regress (dopt.obs).

All tier-1-lean: synthetic event streams only — no engine runs, no jax.
The engine-level alert-sequence equality (per-round vs fused-blocked vs
killed-and-resumed on real runs) is pinned by scripts/chaos_soak.py,
which rides the canonical-stream guarantee tests/test_obs.py pins; here
the monitor's own determinism (same stream -> same alerts, chunked or
resumed) is what's under test.
"""

from __future__ import annotations

import json
import re
import urllib.error
import urllib.request

import pytest

from dopt.obs import (HealthMonitor, JsonlTail, MemorySink, PrometheusSink,
                      Telemetry, build_rules, default_rules, make_event,
                      validate_event)
from dopt.obs.monitor import HealthReport
from dopt.obs.rules import (RULES, CheckpointCadenceRule, ConsensusStallRule,
                            DropRateRule, HostGapRule, LossDivergenceRule,
                            NonFiniteLossRule, QuarantineStormRule,
                            RunContext, StalenessSaturationRule)

WORKERS = 8


def header(round_=0, workers=WORKERS, engine="gossip"):
    return make_event("run", engine=engine, name="synthetic", round=round_,
                      workers=workers)


def round_ev(t, loss=0.5, engine="gossip", **metrics):
    metrics.setdefault("avg_train_loss", loss)
    return make_event("round", round=t, engine=engine, metrics=metrics)


def gauge_ev(t, name, value, engine="gossip"):
    return make_event("gauge", round=t, name=name, value=float(value),
                      engine=engine)


def fault_ev(t, worker=0, fault="crash", action="skipped"):
    return make_event("fault", round=t, worker=worker, fault=fault,
                      action=action)


def diverging_stream(n=12, diverge_at=8):
    evs = [header()]
    for t in range(n):
        loss = 0.5 if t < diverge_at else 100.0 * (t - diverge_at + 1)
        evs.append(round_ev(t, loss))
    return evs


def clean_stream(n=12):
    return [header()] + [round_ev(t, 0.5 - 0.01 * t) for t in range(n)]


def ctx(workers=WORKERS):
    c = RunContext(workers=workers)
    return c


# ------------------------------------------------------------- rule units
def test_nonfinite_loss_fires_once_after_finite():
    r = NonFiniteLossRule()
    c = ctx()
    assert r.update(round_ev(0, loss=None), c) == []   # never saw finite
    assert r.update(round_ev(1, loss=0.5), c) == []
    fired = r.update(round_ev(2, loss=None), c)
    assert len(fired) == 1 and fired[0]["round"] == 2
    # still bad: edge-triggered, no re-fire inside the episode
    assert r.update(round_ev(3, loss=None), c) == []
    # recovers, then fails again -> a second episode fires
    assert r.update(round_ev(4, loss=0.4), c) == []
    assert len(r.update(round_ev(5, loss=None), c)) == 1


def test_loss_divergence_fires_and_respects_min_delta():
    r = LossDivergenceRule(window=4, factor=3.0, min_delta=0.5)
    c = ctx()
    for t in range(4):
        assert r.update(round_ev(t, 0.5), c) == []
    fired = r.update(round_ev(4, 50.0), c)
    assert len(fired) == 1 and fired[0]["value"] == 50.0
    # near-zero-loss jitter stays under the absolute min_delta guard
    r2 = LossDivergenceRule(window=4, factor=3.0, min_delta=0.5)
    for t in range(4):
        r2.update(round_ev(t, 1e-4), c)
    assert r2.update(round_ev(4, 4e-4), c) == []   # 4x ratio, tiny delta
    # a null (non-finite) loss counts as past every threshold
    r3 = LossDivergenceRule(window=4, factor=3.0, min_delta=0.5)
    for t in range(3):
        r3.update(round_ev(t, 0.5), c)
    assert len(r3.update(round_ev(3, loss=None), c)) == 1


def test_consensus_stall_on_rising_gauge():
    r = ConsensusStallRule(patience=3, tol=0.25)
    c = ctx()
    for t, v in enumerate([1.0, 0.9, 0.8, 0.7]):
        assert r.update(gauge_ev(t, "consensus_distance", v), c) == []
    r2 = ConsensusStallRule(patience=3, tol=0.25)
    fired = []
    for t, v in enumerate([1.0, 1.2, 1.5, 2.0]):
        fired += r2.update(gauge_ev(t, "consensus_distance", v), c)
    assert len(fired) == 1 and fired[0]["value"] == 2.0


def test_quarantine_storm_uses_denominator():
    r = QuarantineStormRule(frac=0.5)
    c = ctx(workers=8)
    assert r.update(gauge_ev(0, "quarantine_active", 3), c) == []
    assert len(r.update(gauge_ev(1, "quarantine_active", 4), c)) == 1
    # no denominator -> rule stays silent rather than guessing
    assert QuarantineStormRule().update(
        gauge_ev(0, "quarantine_active", 99), ctx(workers=None)) == []


def test_quarantine_storm_population_universe():
    # Lane-counted quarantine_active judges against the LANE count even
    # in population mode (8/8 lanes out must fire though cohort=64);
    # client-counted population_quarantined judges against
    # population_size; the two universes edge independently.
    r = QuarantineStormRule(frac=0.5)
    c = ctx(workers=8)
    c.cohort = 64.0
    c.population = 1000.0
    fired = r.update(gauge_ev(0, "quarantine_active", 8), c)
    assert len(fired) == 1 and "8/8 workers" in fired[0]["message"]
    assert r.update(gauge_ev(1, "population_quarantined", 400), c) == []
    fired2 = r.update(gauge_ev(2, "population_quarantined", 600), c)
    assert len(fired2) == 1 and "600/1000 clients" in fired2[0]["message"]
    # lane episode still latched: no re-fire while the client one fires
    assert r.update(gauge_ev(3, "quarantine_active", 8), c) == []


def test_drop_rate_uses_live_participating_lanes():
    # The monitor feeds the participating_lanes gauge into the
    # denominator: 2 losses/round over 4 LIVE lanes (8 - 4 quarantined)
    # is a 0.5 rate and must fire a 0.4 SLO that the static 8-lane
    # denominator (rate 0.25) would never breach.
    m = HealthMonitor(build_rules([{"rule": "drop_rate", "max_rate": 0.4,
                                    "window": 4, "min_rounds": 2}]))
    evs = [header(workers=8)]
    for t in range(4):
        evs += [gauge_ev(t, "participating_lanes", 4),
                fault_ev(t, worker=0), fault_ev(t, worker=1), round_ev(t)]
    m.feed(evs)
    assert len(m.alerts) == 1 and m.alerts[0]["rule"] == "drop_rate"


def test_consensus_stall_checkpoint_source_opt_in():
    rising = [1.0, 1.2, 1.5, 2.0]
    # Default: checkpoint-embedded snapshots are ignored (determinism).
    r = ConsensusStallRule(patience=3, tol=0.25)
    c = ctx()
    for t, v in enumerate(rising):
        assert r.update(make_event("checkpoint", round=t,
                                   consensus_distance=v), c) == []
    # Opt-in: the same snapshots drive the rule.
    r2 = ConsensusStallRule(patience=3, tol=0.25, use_checkpoints=True)
    fired = []
    for t, v in enumerate(rising):
        fired += r2.update(make_event("checkpoint", round=t,
                                      consensus_distance=v), c)
    assert len(fired) == 1 and fired[0]["value"] == 2.0


def test_drop_rate_slo_windowed():
    r = DropRateRule(max_rate=0.25, window=4, min_rounds=2)
    c = ctx(workers=4)
    fired = []
    for t in range(4):
        for w in range(2):   # 2 drops / 4 workers = 0.5 per round
            fired += r.update(fault_ev(t, worker=w), c)
        fired += r.update(round_ev(t), c)
    assert len(fired) == 1   # edge-triggered once the mean crosses
    # screened corrupt rows are defenses, not losses
    r2 = DropRateRule(max_rate=0.25, window=4, min_rounds=2)
    out = []
    for t in range(4):
        for w in range(4):
            out += r2.update(fault_ev(t, worker=w, fault="corrupt",
                                      action="screened"), c)
        out += r2.update(round_ev(t), c)
    assert out == []


def test_staleness_and_host_gap_and_cadence():
    c = ctx(workers=8)
    s = StalenessSaturationRule(frac=0.9)
    assert s.update(gauge_ev(0, "stale_pending", 6), c) == []
    assert len(s.update(gauge_ev(1, "stale_pending", 8), c)) == 1

    g = HostGapRule(max_pct=25.0)
    assert g.update(gauge_ev(0, "host_gap_pct", 10.0), c) == []
    assert len(g.update(gauge_ev(1, "host_gap_pct", 40.0), c)) == 1

    k = CheckpointCadenceRule(every=2, slack=1)
    fired = []
    for t in range(6):
        fired += k.update(round_ev(t), c)
        if t % 2 == 1:
            fired += k.update(make_event("checkpoint", round=t), c)
    assert fired == []       # on cadence: quiet
    k2 = CheckpointCadenceRule(every=2, slack=1)
    fired2 = []
    for t in range(6):
        fired2 += k2.update(round_ev(t), c)
    assert len(fired2) == 1  # no checkpoint ever landed
    assert CheckpointCadenceRule().update(round_ev(99), c) == []


def test_build_rules_registry():
    rules = build_rules([{"rule": "loss_divergence", "factor": 2.0},
                         {"rule": "drop_rate", "max_rate": 0.1}])
    assert rules[0].factor == 2.0 and rules[1].max_rate == 0.1
    with pytest.raises(ValueError, match="unknown rule"):
        build_rules([{"rule": "nope"}])
    # overrides reach the stock set; None drops a rule
    named = {type(r).name for r in default_rules(loss_divergence=None)}
    assert "loss_divergence" not in named and "drop_rate" in named
    assert set(RULES) == {type(r).name for r in default_rules()}


# --------------------------------------------------------------- monitor
def test_monitor_alerts_validate_and_do_not_feed_back():
    m = HealthMonitor()
    m.feed(diverging_stream())
    assert m.alerts, "divergence stream must alert"
    for a in m.alerts:
        validate_event(a)
        assert a["engine"] == "gossip"
    n = len(m.alerts)
    assert m.observe(m.alerts[0]) == []   # alerts are output, not input
    assert len(m.alerts) == n


def test_monitor_deterministic_across_chunking():
    evs = diverging_stream()
    whole = HealthMonitor()
    whole.feed(evs)
    chunked = HealthMonitor()
    for i in range(0, len(evs), 3):
        chunked.feed(evs[i:i + 3])
    assert chunked.canonical_alerts() == whole.canonical_alerts()
    assert whole.canonical_alerts()   # non-vacuous


def test_monitor_segment_reset_but_resume_continuation():
    # A fresh segment header (round=0) re-arms the rules: two bench
    # legs in one file each get their own divergence alert.
    evs = diverging_stream(n=10, diverge_at=8)
    m = HealthMonitor()
    m.feed(evs + evs)
    assert len(m.alerts) == 2 and m.segments == 2
    # A resume CONTINUATION header (round>0) keeps the windows: the
    # split stream alerts exactly like the continuous one.
    cont = HealthMonitor()
    cont.feed(diverging_stream(n=12, diverge_at=8))
    split = diverging_stream(n=12, diverge_at=8)
    resumed = split[:6] + [header(round_=5)] + split[6:]
    m2 = HealthMonitor()
    m2.feed(resumed)
    assert m2.canonical_alerts() == cont.canonical_alerts()
    assert m2.segments == 1


def test_monitor_report_verdicts():
    assert HealthMonitor().report().verdict == "empty"
    m = HealthMonitor()
    m.feed(clean_stream())
    rep = m.report()
    assert rep.verdict == "healthy" and rep.ok and rep.rounds == 12
    crit = HealthMonitor()
    crit.feed(diverging_stream())
    assert crit.report().verdict == "critical" and not crit.report().ok
    warn = HealthMonitor()
    warn.feed([header()] + [gauge_ev(0, "host_gap_pct", 90.0)]
              + [round_ev(0)])
    assert warn.report().verdict == "warn" and warn.report().ok
    assert HealthReport(**warn.report().to_dict()).verdict == "warn"


def test_monitor_attach_forwards_alerts_in_stream_order():
    mem = MemorySink()
    tele = Telemetry([mem])
    mon = HealthMonitor().attach(tele)
    assert mon in tele.sinks
    tele.emit("run", engine="fed", name="t", round=0, workers=4)
    for t in range(6):
        tele.emit_round_bundle(t, engine="fed",
                               metrics={"train_loss": 0.5})
    tele.emit_round_bundle(6, engine="fed",
                           metrics={"train_loss": 500.0})
    kinds = [e["kind"] for e in mem.events]
    # The alert lands just after its triggering round, trailed by its
    # measured alert_latency observation (the SLO latency channel).
    assert kinds[-3:] == ["round", "alert", "latency"]
    assert mem.events[-1]["name"] == "alert_latency"
    assert mon.alerts and mon.alerts[0]["rule"] == "loss_divergence"


# ---------------------------------------------------------------- tailing
def test_jsonl_tail_partial_lines(tmp_path):
    p = tmp_path / "m.jsonl"
    tail = JsonlTail(p)
    assert tail.poll() == []          # absent file: nothing yet
    with open(p, "w") as f:
        f.write(json.dumps(round_ev(0)) + "\n")
        f.write('{"v": 1, "kind": "rou')   # torn mid-write
    evs = tail.poll()
    assert [e["round"] for e in evs] == [0]
    with open(p, "a") as f:           # the writer finishes the line
        f.write('nd", "ts": 1.0, "round": 1, "engine": "g", '
                '"metrics": {}}\n')
    assert [e["round"] for e in tail.poll()] == [1]
    assert tail.poll() == []
    # complete mid-file garbage raises instead of desyncing
    p2 = tmp_path / "bad.jsonl"
    p2.write_text("not json\n")
    with pytest.raises(ValueError, match="not JSON"):
        JsonlTail(p2).poll()


def test_jsonl_tail_survives_repair_shrink(tmp_path):
    # JsonlSink.repair_tail rewrites the file SHORTER on kill-and-resume
    # (dropping torn-tail / orphan lines); a live tail must clamp its
    # offset instead of stalling past EOF or desyncing mid-line.
    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        for t in range(3):
            f.write(json.dumps(round_ev(t)) + "\n")
        f.write(json.dumps(gauge_ev(3, "stale_pending", 1)) + "\n")  # orphan
    tail = JsonlTail(p)
    assert len(tail.poll()) == 4
    lines = p.read_text().splitlines()[:3]          # repair drops the orphan
    p.write_text("\n".join(lines) + "\n")
    assert tail.poll() == []                        # clamped, no error
    with open(p, "a") as f:                         # resumed producer appends
        f.write(json.dumps(round_ev(3)) + "\n")
    assert [e["round"] for e in tail.poll()] == [3]


def test_watermark_resume_tail_no_duplicate_alerts(tmp_path):
    p = tmp_path / "m.jsonl"
    evs = diverging_stream(n=14, diverge_at=6)
    with open(p, "w") as f:
        for e in evs[:8]:
            f.write(json.dumps(e) + "\n")
    m1 = HealthMonitor()
    first = m1.poll_file(p)
    state = json.loads(json.dumps(m1.state()))   # JSON round-trip
    with open(p, "a") as f:
        for e in evs[8:]:
            f.write(json.dumps(e) + "\n")
    m2 = HealthMonitor(state=state)
    second = m2.poll_file(p)
    cont = HealthMonitor()
    cont.feed(evs)
    drop_ts = lambda alerts: [{k: v for k, v in a.items() if k != "ts"}
                              for a in alerts]
    assert (drop_ts(first) + drop_ts(second)
            == cont.canonical_alerts())
    assert cont.canonical_alerts(), "non-vacuous: the stream must alert"
    # the resumed monitor's report carries the TOTAL round count
    assert m2.rounds_seen == 14


# ----------------------------------------------------------- prometheus
def test_prometheus_exposition_correctness():
    prom = PrometheusSink()
    prom.emit(round_ev(3, loss=0.25, engine="gossip"))
    prom.emit(gauge_ev(3, "host.gap-pct", 7.5, engine="gossip"))
    prom.emit(gauge_ev(3, "quarantine_active", 1.0, engine="federated"))
    prom.emit(fault_ev(3, fault="crash"))
    prom.emit(make_event("alert", round=3, rule="loss_divergence",
                         severity="critical", message="x"))
    text = prom.render()
    name_re = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        name = line.split("{")[0].split(" ")[0]
        assert name_re.fullmatch(name), f"illegal metric name: {line!r}"
    assert "dopt_host_gap_pct" in text          # dotted/hyphen sanitized
    assert 'engine_kind="gossip"' in text       # label, not name-baked
    assert 'engine_kind="federated"' in text
    assert text.count("# HELP") >= 4
    assert ('dopt_alerts_total{rule="loss_divergence",'
            'severity="critical"} 1') in text
    assert 'dopt_faults_total{kind="crash"} 1' in text


# ---------------------------------------------------------------- serve
def test_serve_scrape_and_healthz(tmp_path):
    from dopt.obs.serve import MetricsServer

    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        for e in clean_stream(6):
            f.write(json.dumps(e) + "\n")
    srv = MetricsServer(p).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(f"{base}/metrics",
                                      timeout=10).read().decode()
        assert "dopt_round" in text and "# TYPE" in text
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            body = json.loads(r.read())
            assert r.status == 200 and body["verdict"] == "healthy"
            assert body["rounds"] == 6
        # divergence appended to the live file flips /healthz to 503
        with open(p, "a") as f:
            for t, loss in ((6, 100.0), (7, 200.0), (8, 400.0)):
                f.write(json.dumps(round_ev(t, loss)) + "\n")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/healthz", timeout=10)
        assert err.value.code == 503
        assert json.loads(err.value.read())["verdict"] == "critical"
        text = urllib.request.urlopen(f"{base}/metrics",
                                      timeout=10).read().decode()
        assert "dopt_alerts_total" in text
    finally:
        srv.shutdown()


# ---------------------------------------------------------------- watch
def test_watch_once_snapshot(tmp_path, capsys):
    from dopt.obs.watch import main as watch_main

    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        for e in clean_stream(5) + [gauge_ev(4, "quarantine_active", 2.0),
                                    fault_ev(4, fault="straggle")]:
            f.write(json.dumps(e) + "\n")
    assert watch_main([str(p), "--once"]) == 0
    out = capsys.readouterr().out
    assert "round 4" in out and "HEALTHY" in out
    assert "quarantine_active=2" in out and "straggle=1" in out
    with open(p, "a") as f:
        for e in diverging_stream():
            f.write(json.dumps(e) + "\n")
    assert watch_main([str(p), "--once"]) == 1   # critical -> rc 1
    assert "ALERT" in capsys.readouterr().out


def test_watch_surfaces_stream_embedded_alerts(tmp_path, capsys):
    # A file written by a producer-side monitor carries `alert` events;
    # the watcher must surface THOSE (and factor them into the exit
    # code), not just what its own stock rules fire.
    from dopt.obs.watch import main as watch_main

    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        for e in clean_stream(4) + [
                make_event("alert", round=3, rule="custom_slo",
                           severity="critical",
                           message="producer-side rule fired")]:
            f.write(json.dumps(e) + "\n")
    assert watch_main([str(p), "--once"]) == 1
    out = capsys.readouterr().out
    assert "CRITICAL" in out and "custom_slo" in out


def test_watch_dedupes_rederived_stream_alerts(tmp_path, capsys):
    # A producer-side monitor with the STOCK rules wrote its alerts into
    # the stream; the watcher's own stock monitor re-derives the same
    # firings from the same events — each condition must count once.
    from dopt.obs.watch import main as watch_main

    m = HealthMonitor()
    evs = diverging_stream()
    embedded = m.feed(evs)
    assert len(embedded) == 1
    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        for e in evs + embedded:
            f.write(json.dumps(e) + "\n")
    assert watch_main([str(p), "--once"]) == 1
    out = capsys.readouterr().out
    assert "(1 alerts" in out and out.count("ALERT") == 1


# ----------------------------------------------------------- check CLI
def test_check_summary_inventory(tmp_path, capsys):
    from dopt.obs.check import main as check_main

    p = tmp_path / "m.jsonl"
    with open(p, "w") as f:
        for e in ([header()] + [round_ev(0), gauge_ev(0, "stale_pending", 1),
                                fault_ev(0), round_ev(1)]):
            f.write(json.dumps(e) + "\n")
    assert check_main([str(p), "--summary"]) == 0
    out = capsys.readouterr().out
    assert "rounds 0..1" in out
    assert "stale_pending: 1 obs" in out
    assert "crash=1" in out and "avg_train_loss" in out


# --------------------------------------------------------------- regress
def _mk_history(tmp_path, values, name="hist.jsonl", **extra):
    from dopt.obs.regress import append_entry

    p = tmp_path / name
    for i, v in enumerate(values):
        head = {"metric": "m", "value": v, "unit": "rounds/sec",
                "device_kind": "cpu", **extra}
        append_entry(p, head, run_id=f"r{i}", sha="0" * 40)
    return p


def test_regress_flags_20pct_slowdown_quiet_in_band(tmp_path):
    from dopt.obs.regress import (append_entry, check_regression,
                                  format_report, read_ledger)

    p = _mk_history(tmp_path, [2.0] * 5)
    append_entry(p, {"metric": "m", "value": 1.6, "device_kind": "cpu"},
                 run_id="slow")
    res = check_regression(read_ledger(p))
    assert res["status"] == "regression"
    (chk,) = res["checks"]
    assert chk["regressed"] and chk["delta_pct"] == -20.0
    assert "REGRESSED" in format_report(res)
    # inside the 5% noise-band floor: quiet
    p2 = _mk_history(tmp_path, [2.0] * 5, name="h2.jsonl")
    append_entry(p2, {"metric": "m", "value": 1.94, "device_kind": "cpu"},
                 run_id="ok")
    assert check_regression(read_ledger(p2))["status"] == "ok"
    # an improvement is never a regression
    p3 = _mk_history(tmp_path, [2.0] * 5, name="h3.jsonl")
    append_entry(p3, {"metric": "m", "value": 3.0, "device_kind": "cpu"},
                 run_id="fast")
    assert check_regression(read_ledger(p3))["status"] == "ok"


def test_regress_band_widens_with_noisy_history(tmp_path):
    from dopt.obs.regress import append_entry, check_regression, read_ledger

    # ±25% historical wobble -> half-spread band swallows a -10% step
    p = _mk_history(tmp_path, [1.6, 2.0, 1.7, 2.2, 2.1])
    append_entry(p, {"metric": "m", "value": 1.8, "device_kind": "cpu"},
                 run_id="wobble")
    res = check_regression(read_ledger(p))
    assert res["status"] == "ok"
    assert res["checks"][0]["band_pct"] > 5.0


def test_regress_keys_by_metric_and_device(tmp_path):
    from dopt.obs.regress import append_entry, check_regression, read_ledger

    p = _mk_history(tmp_path, [2.0] * 5)
    # same metric name, different device: no baseline, never judged
    append_entry(p, {"metric": "m", "value": 0.1,
                     "device_kind": "TPU v5 lite"}, run_id="tpu")
    assert check_regression(read_ledger(p))["status"] == "no_baseline"


def test_regress_lower_is_better_metrics(tmp_path):
    from dopt.obs.regress import append_entry, check_regression, read_ledger

    p = _mk_history(tmp_path, [2.0] * 5, host_gap_pct=5.0)
    append_entry(p, {"metric": "m", "value": 2.0, "host_gap_pct": 25.0,
                     "device_kind": "cpu"}, run_id="gap")
    res = check_regression(read_ledger(p))
    assert res["status"] == "regression"
    by = {c["metric"]: c for c in res["checks"]}
    assert by["host_gap_pct"]["regressed"] and not by["value"]["regressed"]


def test_regress_committed_trajectory_and_cli(tmp_path):
    """The acceptance criterion, against the REAL committed ledger:
    results/bench_history.jsonl + a synthetic -20% rounds/sec entry
    exits non-zero with a per-metric delta report."""
    from pathlib import Path

    from dopt.obs.regress import main, make_entry, read_ledger
    from dopt.utils.metrics import trimmed_stats

    ledger = Path(__file__).resolve().parent.parent / "results" \
        / "bench_history.jsonl"
    entries = read_ledger(ledger)
    assert [e["run_id"] for e in entries][:5] == [f"r{i:02d}"
                                                 for i in range(1, 6)]
    # Windows are keyed (metric, device_kind): the synthetic slowdown
    # must land in the r01-r05 TPU headline window, not in a fresh
    # single-entry key like r06's CPU topology-modes ablation (that
    # one is correctly judged NO_BASELINE).
    headline = [e for e in entries if e["bench"]["metric"]
                == "gossip_rounds_per_sec_dsgd_mnist_6workers_model1_bf16"]
    assert len(headline) >= 5
    slow = dict(headline[-1]["bench"])
    # -20% against the trailing trimmed MEDIAN (the regressor's
    # baseline), not against the newest point — r05 sits above the
    # median, so scaling it would understate the injected slowdown.
    med, _, _ = trimmed_stats([e["bench"]["value"] for e in headline])
    slow["value"] = round(0.8 * med, 4)
    cand = tmp_path / "cand.json"
    cand.write_text(json.dumps(make_entry(slow, run_id="synthetic-20")))
    rc = main([str(ledger), "--candidate", str(cand),
               "--json", str(tmp_path / "rep.json")])
    assert rc == 1
    rep = json.loads((tmp_path / "rep.json").read_text())
    assert rep["status"] == "regression"
    assert any(c["metric"] == "value" and c["regressed"]
               for c in rep["checks"])
    # advisory mode reports but exits 0 (the CI annotation contract)
    assert main([str(ledger), "--candidate", str(cand),
                 "--advisory"]) == 0
    # a bench stdout capture (comments + JSON line) loads as candidate
    cap = tmp_path / "quick.json"
    cap.write_text("# comment\n" + json.dumps(slow) + "\n")
    assert main([str(ledger), "--candidate", str(cap)]) == 1
