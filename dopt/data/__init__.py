from dopt.data.datasets import Dataset, load_dataset
from dopt.data.partition import (assign_client_shards, holdout_split,
                                 iid_split, noniid_split,
                                 orphan_shard_adopters, partition,
                                 reassign_shards)
from dopt.data.pipeline import (BatchPlan, eval_batches, make_batch_plan,
                                gather_batches, sharded_eval_batches,
                                stacked_eval_batches)
from dopt.data.prefetch import PrefetchStager, timed_build

__all__ = [
    "Dataset",
    "load_dataset",
    "holdout_split",
    "iid_split",
    "noniid_split",
    "partition",
    "reassign_shards",
    "assign_client_shards",
    "orphan_shard_adopters",
    "BatchPlan",
    "eval_batches",
    "make_batch_plan",
    "gather_batches",
    "sharded_eval_batches",
    "stacked_eval_batches",
    "PrefetchStager",
    "timed_build",
]
