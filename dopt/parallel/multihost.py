"""Multi-host distributed backend: DCN × ICI hybrid meshes.

The reference has NO communication backend at all — its "multi-node"
story is N objects in one Python process (SURVEY §2.4).  dopt's
equivalent of a NCCL/MPI launcher is the jax runtime itself:

* ``initialize_distributed()`` wires ``jax.distributed`` from standard
  cluster environment variables (one call per host process; afterwards
  ``jax.devices()`` spans every host and collectives ride ICI within a
  slice and DCN across slices).
* ``make_hybrid_mesh()`` builds a 2-D ``Mesh`` with a slow outer axis
  (``hosts`` — DCN) and a fast inner axis (``ici``), so shardings can
  keep bandwidth-hungry collectives on ICI.
* the generic ``dopt.parallel.mesh.worker_sharding`` folds the engine's
  single logical worker axis over BOTH mesh axes (workers = hosts × ici
  lanes): neighboring workers land on the same slice, which means
  ring/dynamic gossip topologies cross DCN only at slice boundaries —
  exactly 2 of N edges for a ring, the minimum possible.

Single-process this degrades gracefully: ``initialize_distributed`` is a
no-op without cluster env vars, and the hybrid mesh reshapes the local
devices, which is also how the 8-virtual-CPU-device tests exercise the
full multi-host code path without a cluster (SURVEY §4's answer to
"test distributed without one").
"""

from __future__ import annotations

import json
import os
import socket
import time
from pathlib import Path

import jax
import numpy as np
from jax.sharding import Mesh

HOST_AXIS = "hosts"   # slow axis: crosses DCN on a real multi-slice job
ICI_AXIS = "ici"      # fast axis: stays on-slice


def pick_ephemeral_port(host: str = "127.0.0.1") -> int:
    """Bind port 0, read back the kernel's choice, release it."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def write_handoff(path: str | Path, address: str) -> None:
    """Publish the coordinator address atomically (tmp + rename): a
    waiter never reads a half-written file."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps({"coordinator": address}))
    os.replace(tmp, path)


def wait_handoff(path: str | Path, *, poll_s: float = 0.05,
                 max_polls: int = 2400) -> str:
    """Poll until the handoff file appears; returns the coordinator
    address.  Bounded by poll COUNT (default ~2 minutes at 50 ms) so
    an orphaned waiter fails loudly instead of hanging forever."""
    path = Path(path)
    for _ in range(max_polls):
        if path.exists():
            try:
                return str(json.loads(path.read_text())["coordinator"])
            except (ValueError, KeyError):
                pass   # racing the rename of a stale tmp: retry
        time.sleep(poll_s)
    raise TimeoutError(
        f"no coordinator handoff at {path} after {max_polls} polls "
        "(did process 0 die before binding?)")


def bootstrap_child_backend(handoff_path: str | Path, process_id: int,
                            num_processes: int, devices_per_proc: int, *,
                            host: str = "127.0.0.1",
                            collectives: str = "gloo") -> str:
    """The ONE fleet-child jax bootstrap, shared by
    ``scripts/multiprocess_demo.py`` and ``python -m dopt.serve``:
    REPLACE any inherited virtual-device-count flag (test harnesses
    export their own N and last-one-wins is not contractual), pin the
    CPU platform + collectives implementation before the backend
    initialises, rendezvous on the port-0 handoff coordinator, wire
    ``jax.distributed``, and sanity-check the resulting process/device
    topology.  Returns the coordinator address.  Must run before
    anything touches a jax backend in this process."""
    import re

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count="
        f"{devices_per_proc}")
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", collectives)
    address = coordinator_handoff(handoff_path, process_id, host=host)
    if not initialize_distributed(address, num_processes, process_id):
        raise RuntimeError(
            "initialize_distributed returned False with explicit args")
    if jax.process_count() != num_processes:
        raise RuntimeError(
            f"expected {num_processes} processes, backend reports "
            f"{jax.process_count()}")
    if jax.local_device_count() != devices_per_proc:
        raise RuntimeError(
            f"expected {devices_per_proc} local devices, backend "
            f"reports {jax.local_device_count()}")
    return address


def coordinator_handoff(path: str | Path, process_id: int, *,
                        host: str = "127.0.0.1",
                        poll_s: float = 0.05,
                        max_polls: int = 2400) -> str:
    """Ephemeral-port coordinator bootstrap for multi-process CPU
    fleets: process 0 picks a port-0 ephemeral port IN ITS OWN PROCESS
    and publishes ``host:port`` through an atomic handoff file; every
    other process waits on the file.  This replaces the parent-probed
    fixed-port scheme whose bind raced everything on the machine for
    the whole child-interpreter startup (seconds) — the remaining
    TOCTOU window is the microseconds between the probe socket closing
    and the coordinator's gRPC server binding, inside one process."""
    path = Path(path)
    if int(process_id) == 0:
        address = f"{host}:{pick_ephemeral_port(host)}"
        write_handoff(path, address)
        return address
    return wait_handoff(path, poll_s=poll_s, max_polls=max_polls)


def _distributed_initialized() -> bool:
    """Whether ``jax.distributed`` is already wired, across jax
    versions: new jax exposes ``jax.distributed.is_initialized``; 0.4.x
    only carries the module-level client state.  Double-initialising
    raises, so this probe gates ``initialize_distributed``."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:  # pragma: no cover - jax internals moved
        return False


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialise ``jax.distributed`` for a multi-host job.

    Explicit args win; otherwise standard env vars are used
    (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``,
    or the TPU-pod metadata jax autodetects).  Returns True if the
    distributed runtime was (or already is) initialised, False when
    nothing indicates a multi-process job (single-host: no-op).
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None and os.environ.get("JAX_NUM_PROCESSES"):
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and os.environ.get("JAX_PROCESS_ID"):
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        return False
    if _distributed_initialized():
        return True   # a launcher/framework already wired the runtime
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def make_hybrid_mesh(num_hosts: int | None = None, *, devices=None) -> Mesh:
    """2-D (hosts × ici) mesh.

    On a real multi-host job ``num_hosts`` defaults to
    ``jax.process_count()`` and rows follow device locality (each row =
    one host's devices, so the inner axis is pure ICI).  Single-process,
    ``num_hosts`` partitions the local devices into virtual hosts —
    bit-identical program, no cluster needed.
    """
    if devices is None:
        devices = jax.devices()
    if num_hosts is None:
        num_hosts = max(jax.process_count(), 1)
    n = len(devices)
    if n % num_hosts:
        raise ValueError(f"{n} devices not divisible into {num_hosts} hosts")
    per_host = n // num_hosts
    # jax.devices() orders by process index first, so a row-major reshape
    # groups each host's devices into one row.
    grid = np.asarray(devices).reshape(num_hosts, per_host)
    return Mesh(grid, (HOST_AXIS, ICI_AXIS))


def dcn_edge_count(w_matrix: np.ndarray, num_hosts: int) -> int:
    """Diagnostic: how many nonzero mixing-matrix edges cross a host
    (DCN) boundary under the contiguous worker→host fold.  A ring over
    H hosts should report exactly 2·H·(H>1) directed crossings; dense
    graphs report O(N²·(1−1/H)) — use it to pick topologies that keep
    gossip on ICI."""
    n = w_matrix.shape[0]
    if n % num_hosts:
        raise ValueError(f"{n} workers not divisible into {num_hosts} hosts")
    per = n // num_hosts
    host_of = np.arange(n) // per
    i, j = np.nonzero(w_matrix)
    return int(np.sum(host_of[i] != host_of[j]))
