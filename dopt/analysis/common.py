"""Shared conventions for the ``dopt.analysis`` CLIs.

Exit codes (the ``dopt.obs.check`` contract, now shared by every
analysis gate): ``EXIT_CLEAN`` (0) — no findings; ``EXIT_FINDINGS``
(1) — the gate found violations; ``EXIT_USAGE`` (2) — bad invocation
(argparse's own convention, so ``--help`` typos and gate failures are
distinguishable in CI).

Findings are plain records with a stable JSON form (``--json`` on every
CLI) so CI can annotate them; the text form is one grep-able line per
finding (``path:line: [rule] message``).

Pragmas: a finding is suppressed by an end-of-line comment on the
flagged line (or the line above, for multi-line statements)::

    t0 = time.time()  # dopt: allow-wallclock -- span timing, not math

The justification after ``--`` is REQUIRED — a bare ``allow-<rule>``
still fails, with a finding pointing at the pragma itself.  This module
is stdlib-only so the linter/extractor run anywhere (no jax import).
"""

from __future__ import annotations

import dataclasses
import json
import re
import sys
from pathlib import Path
from typing import Any, Iterable, Iterator

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_USAGE = 2

# ``# dopt: allow-<rule>`` with an optional ``-- justification`` tail.
_PRAGMA_RE = re.compile(
    r"#\s*dopt:\s*allow-(?P<rule>[a-z0-9-]+)"
    r"(?:\s*--\s*(?P<why>.*\S))?")


@dataclasses.dataclass(frozen=True)
class Pragma:
    rule: str
    line: int
    justification: str | None


@dataclasses.dataclass(frozen=True)
class Finding:
    """One gate violation, pointing at a file:line."""

    rule: str
    path: str
    line: int
    message: str

    def text(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def parse_pragmas(source: str) -> dict[int, list[Pragma]]:
    """All ``# dopt: allow-*`` pragmas in ``source``, keyed by the
    1-based line they sit on.  Parsed textually (not via the AST) so a
    pragma on a continuation line or above a decorator still counts."""
    out: dict[int, list[Pragma]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        for m in _PRAGMA_RE.finditer(line):
            out.setdefault(i, []).append(
                Pragma(rule=m.group("rule"), line=i,
                       justification=m.group("why")))
    return out


def pragma_for(pragmas: dict[int, list[Pragma]], rule: str,
               line: int, end_line: int | None = None) -> Pragma | None:
    """The pragma covering ``rule`` for a statement spanning
    ``line``..``end_line``: any line of the statement itself (so a
    pragma at the natural end of a multi-line call counts) or the line
    directly above it."""
    for ln in range(line - 1, max(end_line or line, line) + 1):
        for p in pragmas.get(ln, ()):
            if p.rule == rule:
                return p
    return None


def iter_py_files(roots: Iterable[str | Path],
                  exclude: tuple[str, ...] = ()) -> Iterator[Path]:
    """Yield ``.py`` files under each root (a file root yields itself),
    sorted for deterministic output; ``exclude`` drops any file whose
    posix path contains one of the fragments."""
    seen: set[Path] = set()
    for root in roots:
        root = Path(root)
        paths = [root] if root.is_file() else sorted(root.rglob("*.py"))
        for p in paths:
            posix = p.as_posix()
            if p in seen or any(frag in posix for frag in exclude):
                continue
            seen.add(p)
            yield p


def emit_report(findings: list[Finding], *, as_json: bool, tool: str,
                checked: int, unit: str = "file",
                extra: dict[str, Any] | None = None,
                stream=None) -> int:
    """Print findings (text or one JSON document) and return the exit
    code: ``EXIT_FINDINGS`` if any finding survived, else
    ``EXIT_CLEAN``."""
    stream = sys.stdout if stream is None else stream
    findings = sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    if as_json:
        doc: dict[str, Any] = {
            "tool": tool,
            "checked": checked,
            "findings": [f.to_json() for f in findings],
            "clean": not findings,
        }
        if extra:
            doc.update(extra)
        json.dump(doc, stream, indent=2, sort_keys=True)
        stream.write("\n")
    else:
        for f in findings:
            print(f.text(), file=stream)
        verdict = ("clean" if not findings
                   else f"{len(findings)} finding(s)")
        print(f"{tool}: {verdict} ({checked} {unit}(s) checked)",
              file=stream)
    return EXIT_FINDINGS if findings else EXIT_CLEAN
