"""Tracing / profiling (absent in the reference — SURVEY §5).

The reference's only instrumentation is ``time.time()`` around
``run()`` printed as "Total Run Time" plus tqdm bars (servers.py:51,79;
simulators.py:115-137).  dopt provides:

* ``PhaseTimers`` — named wall-clock accumulators for the round phases
  (consensus vs local step vs eval vs host batch-planning); rounds/sec
  is a north-star metric so phase attribution is first-class.
* ``trace()`` — context manager wrapping ``jax.profiler`` to dump an
  XLA trace viewable in TensorBoard/Perfetto.

Note on async dispatch: jax returns before device work finishes, so a
``phase()`` context around a jit call measures dispatch only.  Use
``measure(name, fn, *args)`` to attribute device time — it blocks on
the function's result via ``block_until_ready``.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Any, Iterator

import jax


class PhaseTimers:
    """Accumulates wall-clock per named phase."""

    def __init__(self) -> None:
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        """Host wall-clock for the block (dispatch-only for jit calls —
        use ``measure`` to include device time)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def measure(self, name: str, fn, *args, **kwargs):
        """Run fn, block on its result, attribute the time to ``name``."""
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        self.totals[name] += time.perf_counter() - t0
        self.counts[name] += 1
        return out

    def summary(self) -> dict[str, dict[str, float]]:
        return {
            name: {
                "total_s": round(self.totals[name], 4),
                "count": self.counts[name],
                "mean_s": round(self.totals[name] / max(self.counts[name], 1), 5),
            }
            for name in self.totals
        }

    def report(self) -> str:
        rows = ["phase                total_s   count   mean_s"]
        for name, s in sorted(self.summary().items(),
                              key=lambda kv: -kv[1]["total_s"]):
            rows.append(f"{name:20s} {s['total_s']:8.3f} {s['count']:7d} {s['mean_s']:9.5f}")
        return "\n".join(rows)


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """XLA profiler trace (TensorBoard/Perfetto-viewable)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
