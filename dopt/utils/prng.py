"""Deterministic seeding (reference ``setup_seed``, utils.py:53-58).

The reference seeds torch/cuda/numpy/random globally.  The jax engine
needs no global state: everything derives from explicit keys / seeded
``np.random.Generator`` streams.  ``setup_seed`` remains for the torch
oracle backend and for host-side numpy sampling.
"""

from __future__ import annotations

import random

import numpy as np


def setup_seed(seed: int) -> None:
    """Seed every global RNG the oracle backend touches."""
    random.seed(seed)      # dopt: allow-unseeded-rng -- host-side seeding of the torch oracle's globals (this IS the seeding site)
    np.random.seed(seed)   # dopt: allow-unseeded-rng -- host-side seeding of the torch oracle's globals (this IS the seeding site)
    try:
        import torch

        torch.manual_seed(seed)
        torch.backends.cudnn.deterministic = True  # no-op on CPU; faithful
    except ImportError:
        pass


def host_rng(seed: int, *salts: int) -> np.random.Generator:
    """Named deterministic numpy stream (client sampling, matchings...)."""
    return np.random.default_rng(np.random.SeedSequence([seed, *salts]))
