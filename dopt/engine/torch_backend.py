"""The faithful torch-CPU reference backend as driveable trainers.

``ExperimentConfig.backend="torch"`` selects this module: the
reference's EXECUTION MODEL — N torch workers stepped sequentially in
one process, communication as state-dict passing — run end-to-end
behind the same trainer surface (``run``, ``history``,
``client_history``, ``evaluate``) as the jax engines.  This is the
pluggable ``Worker(backend=...)`` boundary of the build plan (SURVEY
§7 step 4): ``backend="jax"`` is the TPU path, ``backend="torch"`` is
the numerics oracle, and experiments swap between them with one config
field.

Everything that defines the experiment is SHARED with the jax engines —
dataset loading, partitioning, the 90/10 local holdout, deterministic
batch plans, mixing-matrix schedules, client-sampling RNG streams, and
the flax parameter initialisation (converted to torch state dicts) — so
the two backends consume byte-identical inputs and their trajectories
are directly comparable (tests/test_torch_backend.py pins this).

Scope: the reference's surface.  Models: model1 / model3 (the reference
CNNs) plus the dense zoo extras (mlp, logistic).  Algorithms: gossip
dsgd / nocons / fedlcon; federated fedavg / fedprox / fedadmm /
scaffold.  The TPU-native extras (choco compression, dropout fault
injection, pairwise gossip matching, resnet18/transformer) have no
reference execution model to be faithful to and are rejected loudly.
Checkpointing lives on the jax side only (the oracle is a validation
backend, not a production trainer) — ``save``/``restore`` raise.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from dopt.config import ExperimentConfig
from dopt.data import (eval_batches, holdout_split, load_dataset,
                       make_batch_plan, partition, stacked_eval_batches)
from dopt.engine.local import validate_optimizer
from dopt.engine.oracle import (HAVE_TORCH, OracleWorker, consensus,
                                flax_cnn_params_to_torch,
                                flax_dense_params_to_torch, nhwc_to_nchw,
                                torch_cnn_params_to_flax,
                                torch_dense_params_to_flax, torch_logistic,
                                torch_mlp, torch_reference_cnn)
from dopt.models import build_model
from dopt.topology import build_mixing_matrices
from dopt.utils.metrics import History
from dopt.utils.profiling import PhaseTimers
from dopt.utils.prng import host_rng


def _build_torch_twin(model_cfg):
    """(torch module factory, flax→torch, torch→flax) for a zoo model."""
    name = model_cfg.model.lower()
    shape = model_cfg.input_shape
    ncls = model_cfg.num_classes
    if name in ("model1", "model3"):
        spatial, in_ch = shape[0], shape[-1]
        hidden = 512 if name == "model1" else 256

        def make():
            return torch_reference_cnn(in_ch, spatial, hidden,
                                       num_classes=ncls,
                                       faithful=model_cfg.faithful)

        return (make,
                lambda p: flax_cnn_params_to_torch(p, spatial),
                lambda s: torch_cnn_params_to_flax(s, spatial))
    if name in ("mlp", "logistic"):
        if len(shape) > 1 and shape[-1] != 1:
            raise ValueError(
                f"torch backend {name} supports flat or single-channel "
                f"inputs only (NCHW/NHWC flatten orders differ for "
                f"C={shape[-1]})")
        flat = int(np.prod(shape))

        def make():
            if name == "mlp":
                return torch_mlp(flat, num_classes=ncls,
                                 faithful=model_cfg.faithful)
            return torch_logistic(flat, num_classes=ncls,
                                  faithful=model_cfg.faithful)

        return make, flax_dense_params_to_torch, torch_dense_params_to_flax
    raise ValueError(
        f"model {name!r} has no torch reference twin (the faithful backend "
        "covers the reference surface: model1|model3|mlp|logistic)")


def _layout_converter(model_cfg):
    """NHWC→NCHW converter for image models; identity for flat-feature
    models (keyed off the MODEL's input shape — a gathered [W, S, B, F]
    flat-feature stack is 4-D too, so array rank cannot decide)."""
    if len(model_cfg.input_shape) >= 3:
        return nhwc_to_nchw
    return lambda x: x


class _TorchTrainerBase:
    """Shared setup: data, partition, holdout, eval stacks, torch fleet
    initialised from the SAME flax init the jax engines use."""

    def __init__(self, cfg: ExperimentConfig, section):
        if not HAVE_TORCH:  # pragma: no cover - torch is in the image
            raise RuntimeError("backend='torch' requires torch")
        validate_optimizer(cfg)
        self.cfg = cfg
        self.round = 0
        self.history = History(cfg.name)
        self.client_history = History(cfg.name + "-clients")
        self.timers = PhaseTimers()
        w = cfg.data.num_users
        self.num_workers = w

        self.dataset = load_dataset(
            cfg.data.dataset, data_dir=cfg.data.data_dir,
            train_size=cfg.data.synthetic_train_size,
            test_size=cfg.data.synthetic_test_size, seed=cfg.seed,
        )
        _, self.index_matrix = partition(
            self.dataset.train_y, w, iid=cfg.data.iid,
            shards_per_user=cfg.data.shards, seed=cfg.seed,
        )
        self._to_nchw = _layout_converter(cfg.model)
        self._holdout = cfg.data.local_holdout > 0.0
        if self._holdout:
            self._train_matrix, val_matrix = holdout_split(
                self.index_matrix, fraction=cfg.data.local_holdout,
                mode=cfg.data.holdout_mode, seed=cfg.seed)
            vi, vw = stacked_eval_batches(val_matrix,
                                          batch_size=section.local_bs)
            self._val_x = self._to_nchw(self.dataset.train_x[vi])  # [W,Sv,Bv,...]
            self._val_y = self.dataset.train_y[vi]
            self._val_w = vw
        else:
            self._train_matrix = self.index_matrix

        ex, ey, ew = eval_batches(self.dataset.test_x, self.dataset.test_y,
                                  batch_size=max(section.local_bs, 256))
        self._eval = (self._to_nchw(ex), ey, ew)

        # Identical init to the jax engines: flax init, converted.
        fmodel = build_model(cfg.model.model, num_classes=cfg.model.num_classes,
                             faithful=cfg.model.faithful)
        params0 = fmodel.init(jax.random.key(cfg.seed),
                              jnp.zeros((1, *cfg.model.input_shape)))["params"]
        params0 = jax.device_get(params0)
        make, self._to_torch, self._to_flax = _build_torch_twin(cfg.model)
        init_state = self._to_torch(params0)
        self.workers: list[OracleWorker] = []
        for _ in range(w):
            m = make()
            m.load_state_dict({k: v.clone() for k, v in init_state.items()})
            self.workers.append(OracleWorker(
                m, lr=cfg.optim.lr, momentum=cfg.optim.momentum,
                rho=cfg.optim.rho, l2=cfg.optim.weight_decay,
                algorithm=self._worker_algorithm()))
        self._init_state = init_state

    def _worker_algorithm(self) -> str:
        return "sgd"

    # --- shared helpers ----------------------------------------------
    def _round_batches(self, t: int, worker_ids=None):
        """NCHW [m, S, B, ...] batch stacks for round t (identical plan
        to the jax engines — same seed keying AND the same plan_impl, so
        a native-planner jax run and its torch twin still train on
        byte-identical batches)."""
        plan = make_batch_plan(
            self._train_matrix, batch_size=self._section().local_bs,
            local_ep=self._section().local_ep, seed=self.cfg.seed,
            round_idx=t, impl=self.cfg.data.plan_impl,
            workers=worker_ids,
        )
        bx = self._to_nchw(self.dataset.train_x[plan.idx])
        by = self.dataset.train_y[plan.idx]
        return bx, by, plan.weight

    def _local_round(self, i: int, bx, by, bw, t: int, *, theta=None,
                     c_global=None, schema: str = "p2") -> tuple[float, float]:
        """One worker's local epochs; returns (mean loss, train acc) and,
        with the holdout on, appends per-epoch client-history rows."""
        wk = self.workers[i]
        s = self._section()
        if self._holdout:
            e = s.local_ep
            sp = bx.shape[0] // e
            rows = wk.local_update_epochs(
                bx.reshape(e, sp, *bx.shape[1:]),
                by.reshape(e, sp, *by.shape[1:]),
                bw.reshape(e, sp, *bw.shape[1:]),
                self._val_x[i], self._val_y[i], self._val_w[i],
                theta=theta, c_global=c_global,
                val_flavor="sum" if schema == "p1" else "mean")
            for r in rows:
                if schema == "p1":
                    self.client_history.append(
                        global_round=t, epoch=r["epoch"], worker=i,
                        train_loss=r["train_loss"], train_acc=r["train_acc"],
                        val_acc=r["val_acc"], val_loss=r["val_loss"])
                else:
                    self.client_history.append(
                        round=t, iter=r["epoch"], worker=i,
                        train_loss=r["train_loss"], train_acc=r["train_acc"],
                        val_acc=r["val_acc"], val_loss=r["val_loss"])
            return (float(np.mean([r["train_loss"] for r in rows])),
                    float(np.mean([r["train_acc"] for r in rows])))
        losses: list[float] = []
        ct = [0.0, 0.0]
        wk._epoch_steps(bx, by, bw, theta, c_global, losses, ct)
        return float(np.mean(losses)), ct[0] / max(ct[1], 1.0)

    def save(self, path) -> None:
        raise ValueError(
            "backend='torch' is the validation oracle and does not "
            "checkpoint; use backend='jax' for resumable training")

    restore = save

    def params_as_flax(self):
        """Stacked [W, ...] flax pytree of the fleet's parameters — the
        cross-backend comparison hook."""
        trees = [self._to_flax(wk.model.state_dict()) for wk in self.workers]
        return jax.tree.map(lambda *xs: np.stack(xs), *trees)


class OracleGossipTrainer(_TorchTrainerBase):
    """Reference project-2 execution: sequential workers, two-phase
    synchronous consensus → per-client eval → local update
    (``simulators.py:136-167``)."""

    def __init__(self, cfg: ExperimentConfig):
        import dataclasses

        g = cfg.gossip
        if g is None:
            raise ValueError("cfg.gossip must be set")
        if g.algorithm not in ("dsgd", "nocons", "centralized", "fedlcon"):
            raise ValueError(
                f"torch backend supports gossip dsgd|nocons|centralized|"
                f"fedlcon (the reference surface), not {g.algorithm!r}")
        if g.dropout > 0:
            raise ValueError("dropout fault injection is a jax-backend "
                             "feature (the reference has no failures)")
        if g.algorithm == "centralized":
            # Same frozen-config rewrite as the jax engine (the reference
            # mutates the SHARED args object, simulators.py:171-173).
            cfg = cfg.replace(
                data=dataclasses.replace(cfg.data, num_users=1, iid=True),
                gossip=dataclasses.replace(g, local_ep=1,
                                           algorithm="nocons"),
            )
            g = cfg.gossip
        super().__init__(cfg, g)
        self.mixing = (build_mixing_matrices(
            g.topology, g.mode, self.num_workers, seed=cfg.seed,
            self_weight=g.self_weight, groups=g.hier_groups,
            period=g.hier_period)
            if g.algorithm in ("dsgd", "fedlcon") else None)

    def _section(self):
        return self.cfg.gossip

    def run(self, rounds: int | None = None, eps: int | None = None,
            **_) -> History:
        g = self.cfg.gossip
        rounds = g.rounds if rounds is None else rounds
        if eps is not None and eps != g.eps and g.algorithm == "fedlcon":
            # Mirror the jax engine: eps is config, not a run() knob.
            raise ValueError("set eps in GossipConfig (static for the "
                             "jax engine's compilation; kept consistent "
                             "here)")
        eps = g.eps if (g.algorithm == "fedlcon"
                        and not g.faithful_bugs) else 1
        t0 = time.time()  # dopt: allow-wallclock -- total_time wall meter, reporting only
        for _ in range(rounds):
            t = self.round
            if self.mixing is not None:
                w_t = self.mixing.for_round(t)
                for _sweep in range(eps):
                    states = [wk.state() for wk in self.workers]
                    new = [consensus([(float(w_t[i, j]), states[j])
                                      for j in range(self.num_workers)
                                      if w_t[i, j] > 0])
                           for i in range(self.num_workers)]
                    for wk, st in zip(self.workers, new):
                        wk.load(st)
            accs, losses_m = [], []
            for wk in self.workers:
                a, _s, m = wk.inference(*self._eval)
                accs.append(a)
                losses_m.append(m)
            bx, by, bw = self._round_batches(t)
            tl, ta = [], []
            for i in range(self.num_workers):
                l, a = self._local_round(i, bx[i], by[i], bw[i], t,
                                         schema="p2")
                tl.append(l)
                ta.append(a)
            self.history.append(
                round=t, avg_train_loss=float(np.mean(tl)),
                avg_train_acc=float(np.mean(ta)),
                avg_test_acc=float(np.mean(accs)),
                avg_test_loss=float(np.mean(losses_m)),
            )
            self.round += 1
        self.total_time = time.time() - t0  # dopt: allow-wallclock -- total_time wall meter, reporting only
        return self.history

    def evaluate(self) -> dict[str, np.ndarray]:
        out = [wk.inference(*self._eval) for wk in self.workers]
        return {"acc": np.array([o[0] for o in out]),
                "loss_sum": np.array([o[1] for o in out]),
                "loss_mean": np.array([o[2] for o in out])}


class OracleFederatedTrainer(_TorchTrainerBase):
    """Reference project-1 execution: server round with client sampling,
    sequential sampled-client updates, uniform averaging
    (``servers.py:50-81``), same sampling RNG stream as the jax engine."""

    def __init__(self, cfg: ExperimentConfig):
        f = cfg.federated
        if f is None:
            raise ValueError("cfg.federated must be set")
        if f.algorithm not in ("fedavg", "fedprox", "fedadmm", "scaffold"):
            raise ValueError(f"unknown federated algorithm {f.algorithm!r}")
        super().__init__(cfg, f)
        import torch

        self._torch = torch
        self.theta = {k: v.clone() for k, v in self._init_state.items()}
        self.c_global = ({k: torch.zeros_like(v)
                          for k, v in self._init_state.items()}
                         if f.algorithm == "scaffold" else None)
        self._sample_rng = host_rng(cfg.seed, 314159)
        # Per-worker train-split eval stacks (avg_trainig_calculator).
        ti, tw = stacked_eval_batches(self._train_matrix,
                                      batch_size=max(f.local_bs, 256))
        self._train_eval = (self._to_nchw(self.dataset.train_x[ti]),
                            self.dataset.train_y[ti], tw)

    def _section(self):
        return self.cfg.federated

    def _worker_algorithm(self) -> str:
        return {"fedavg": "sgd"}.get(self.cfg.federated.algorithm,
                                     self.cfg.federated.algorithm)

    def run(self, frac: float | None = None, rounds: int | None = None,
            **_) -> History:
        f = self.cfg.federated
        torch = self._torch
        frac = f.frac if frac is None else frac
        rounds = f.rounds if rounds is None else rounds
        algo = f.algorithm
        t0 = time.time()  # dopt: allow-wallclock -- total_time wall meter, reporting only
        for _ in range(rounds):
            t = self.round
            m = max(int(frac * self.num_workers), 1)
            sel = np.sort(self._sample_rng.choice(self.num_workers, m,
                                                  replace=False))
            bx, by, bw = self._round_batches(t, worker_ids=sel)
            local_losses = []
            theta_named = {k: v for k, v in self.theta.items()}
            # Round-start snapshot: every sampled worker trains against
            # (and refreshes its control from) the SAME server control c,
            # and the accumulated delta lands once after the loop —
            # matching the jax engine's control_delta semantics.
            c_round = ({k: v.clone() for k, v in self.c_global.items()}
                       if algo == "scaffold" else None)
            for j, i in enumerate(sel):
                wk = self.workers[i]
                wk.load(self.theta)
                if algo == "scaffold":
                    # Fresh momentum each round (matches the jax engine's
                    # scaffold semantics: theta − y reflects only this
                    # round's gradients).
                    wk.optimizer.state.clear()
                needs_theta = algo in ("fedprox", "fedadmm")
                l, _a = self._local_round(
                    int(i), bx[j], by[j], bw[j], t,
                    theta=theta_named if needs_theta else None,
                    c_global=c_round, schema="p1")
                local_losses.append(l)
                if algo == "fedadmm":
                    wk.update_duals(theta_named)
                elif algo == "scaffold":
                    steps = bw.shape[1]
                    lr_eff = self.cfg.optim.lr / max(
                        1.0 - self.cfg.optim.momentum, 1e-8)
                    delta = wk.update_controls(theta_named, c_round,
                                               lr_eff, steps)
                    with torch.no_grad():
                        for k in self.c_global:
                            self.c_global[k] += delta[k] / self.num_workers
            with torch.no_grad():
                states = [self.workers[i].state() for i in sel]
                self.theta = {k: sum(st[k] for st in states) / len(states)
                              for k in self.theta}
            # Global test eval + all-client train eval.
            probe = self.workers[0]
            saved = probe.state()
            probe.load(self.theta)
            acc, loss_sum, _lm = probe.inference(*self._eval)
            probe.load(saved)
            tl, ta = [], []
            for i, wk in enumerate(self.workers):
                a, _s, lm = wk.inference(self._train_eval[0][i],
                                         self._train_eval[1][i],
                                         self._train_eval[2][i])
                tl.append(lm)
                ta.append(a)
            self.history.append(
                round=t, test_acc=float(acc), test_loss=float(loss_sum),
                train_loss=float(np.mean(tl)), train_acc=float(np.mean(ta)),
                local_loss=float(np.mean(local_losses)),
            )
            self.round += 1
        self.total_time = time.time() - t0  # dopt: allow-wallclock -- total_time wall meter, reporting only
        return self.history

    def theta_as_flax(self):
        return self._to_flax(self.theta)

    def evaluate_global(self) -> dict[str, float]:
        probe = self.workers[0]
        saved = probe.state()
        probe.load(self.theta)
        acc, loss_sum, loss_mean = probe.inference(*self._eval)
        probe.load(saved)
        return {"acc": acc, "loss_sum": loss_sum, "loss_mean": loss_mean}


def build_torch_trainer(cfg: ExperimentConfig):
    """backend='torch' factory (mirrors ``dopt.run.build_trainer``)."""
    if cfg.seqlm is not None:
        raise ValueError("seqlm has no torch reference backend (the "
                         "reference has no sequence axis)")
    if cfg.federated is not None:
        return OracleFederatedTrainer(cfg)
    return OracleGossipTrainer(cfg)
