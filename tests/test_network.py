"""Lossy-link network model, push-sum correction, staleness & churn.

Four layers, mirroring tests/test_faults.py's structure:

* host-only link/churn draw semantics (stateless per-(round, edge),
  asymmetric, resume-exact) and the matrix builders' invariants —
  row-stochasticity after drop repair, exact mass conservation of the
  push-sum effective matrix, delay-split completeness;
* the CORRECTNESS win the tentpole exists for: under asymmetric
  message loss, plain (row-renormalised) gossip converges to a BIASED
  average while ``correction='push_sum'`` recovers the true mean to
  tolerance — asserted both on a pure-numpy packet simulation of the
  exact per-round matrices and end-to-end through ``GossipTrainer``
  on an lr=0 consensus task;
* staleness-aware aggregation beating hard straggler drop on final
  loss under a heavy-straggler federated config;
* the ledger round-trip (``--faults-json`` export == in-``History``
  ledger row-for-row, link-fault rows included) and the
  ``GossipConfig.dropout`` retirement contract (release named in the
  warning, alias routes through the link-fault repair path).

Heavyweight end-to-end soaks (full cocktail, SIGKILL resume) live in
``scripts/chaos_soak.py``; its smoke test here is marked ``slow``.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from dopt.config import (DataConfig, ExperimentConfig, FaultConfig,
                         FederatedConfig, GossipConfig, ModelConfig,
                         OptimizerConfig)
from dopt.faults import KINDS, FaultPlan, churn_ledger_rows
from dopt.topology import (build_mixing_matrices, push_sum_link_matrix,
                           repair_for_dropout, repair_for_link_drop,
                           split_by_delay)

pytestmark = pytest.mark.network


# ---------------------------------------------------------------------------
# Link/churn draw semantics (host-only, stateless)
# ---------------------------------------------------------------------------

def _link_plan(w=8, **kw):
    base = dict(msg_drop=0.3, msg_delay=0.4, msg_delay_max=2)
    base.update(kw)
    return FaultPlan(w, FaultConfig(**base), seed=5)


def test_link_draws_stateless_and_asymmetric():
    a, b = _link_plan(), _link_plan()
    saw_asym = False
    for t in (4, 0, 2, 4):
        ka, da = a.link_for_round(t)
        kb, db = b.link_for_round(t)
        np.testing.assert_array_equal(ka, kb)
        np.testing.assert_array_equal(da, db)
        # the self-edge never drops or delays
        assert ka.diagonal().all() and not da.diagonal().any()
        # a dropped message never arrives late
        assert not (da[~ka] != 0).any()
        assert da.min() >= 0 and da.max() <= 2
        saw_asym |= bool((ka != ka.T).any())
    # directed draws: loss is asymmetric somewhere in 4 rounds of a
    # 30% drop rate (probability of full symmetry is negligible)
    assert saw_asym


def test_link_inactive_is_all_kept():
    plan = FaultPlan(6, FaultConfig(crash=0.5), seed=1)
    assert not plan.has_link and plan.delay_max == 0
    keep, delay = plan.link_for_round(3)
    assert keep.all() and not delay.any()
    up_drop, up_delay = plan.uplink_for_round(3)
    assert not up_drop.any() and not up_delay.any()


def test_churn_membership_stateless_and_span():
    plan = FaultPlan(10, FaultConfig(churn=0.15, churn_span=3), seed=9)
    away = {t: plan.away_for_round(t) for t in range(30)}
    # stateless: a second plan replays the identical membership
    plan2 = FaultPlan(10, FaultConfig(churn=0.15, churn_span=3), seed=9)
    for t in range(30):
        np.testing.assert_array_equal(away[t], plan2.away_for_round(t))
    # every departure lasts at least... the union-of-spans scheme keeps
    # a worker away while ANY leave event in the last churn_span rounds
    # covers it, so each leave start implies >= churn_span away rounds
    # were it the only event — check the weaker invariant that each
    # transition to away persists while its start event is in scope.
    starts = [(t, i) for t in range(1, 30)
              for i in np.nonzero(away[t] & ~away[t - 1])[0]]
    assert starts, "expected churn events in 30 rounds"
    for t, i in starts:
        for u in range(t, min(t + 3, 30)):
            assert away[u][i], "membership flapped inside the span"


def test_adopters_and_reassign_shards():
    from dopt.data import reassign_shards

    away = np.array([False, True, True, False, False])
    ad = FaultPlan.adopters_for(away)
    assert ad == {1: 3, 2: 3}   # next alive after 1 is 3 (2 is away)
    assert FaultPlan.adopters_for(np.zeros(4, bool)) == {}
    assert FaultPlan.adopters_for(np.ones(4, bool)) == {}
    mat = np.arange(20, dtype=np.int32).reshape(4, 5) * 10
    out = reassign_shards(mat, {1: 3, 2: 3})
    np.testing.assert_array_equal(out[0], mat[0])   # untouched rows
    np.testing.assert_array_equal(out[1], mat[1])
    # adopter row: round-robin interleave of its own + both adopted
    # shards, truncated to L — covers all three evenly
    assert set(out[3]).issubset(set(mat[1]) | set(mat[2]) | set(mat[3]))
    assert len(set(out[3]) & set(mat[1])) >= 1
    assert len(set(out[3]) & set(mat[2])) >= 1
    assert len(set(out[3]) & set(mat[3])) >= 1
    np.testing.assert_array_equal(mat[3], np.arange(15, 20) * 10)  # no mutation


def test_churn_ledger_rows_transitions_only():
    plan = FaultPlan(8, FaultConfig(churn=0.2, churn_span=2), seed=3)
    seen = set()
    for t in range(20):
        for row in churn_ledger_rows(plan, t, plan.away_for_round(t)):
            assert row["kind"] == "churn"
            seen.add(row["action"].split("_")[0])
    assert "left" in seen and "rejoined" in seen


# ---------------------------------------------------------------------------
# Matrix builders: drop repair, mass conservation, delay split
# ---------------------------------------------------------------------------

def _base_matrix(n=8, seed=0):
    return build_mixing_matrices("complete", "metropolis", n,
                                 seed=seed).matrices[0]


def test_repair_for_link_drop_row_stochastic_not_doubly():
    rng = np.random.default_rng(0)
    for seed in range(6):
        w = _base_matrix(seed=seed)
        n = w.shape[0]
        keep = rng.random((n, n)) > 0.4
        r = repair_for_link_drop(w, keep)
        np.testing.assert_allclose(r.sum(axis=1), 1.0, atol=1e-9)
        off = ~(keep | np.eye(n, dtype=bool))
        assert np.all(r[off] == 0.0)
    # asymmetric drops break double-stochasticity — the bias mechanism
    w = _base_matrix(seed=1)
    keep = np.ones_like(w, bool)
    keep[0, 1] = False          # 1 -> 0 lost, 0 -> 1 survives
    r = repair_for_link_drop(w, keep)
    assert abs(r.sum(axis=0) - 1.0).max() > 1e-3


def test_full_link_drop_equals_crash_repair():
    # crash = the degenerate all-links-down case: repairing around a
    # dead worker's cut edges reproduces repair_for_dropout exactly —
    # the routing contract that lets the GossipConfig.dropout alias
    # retire onto the link-fault path.
    for seed in range(4):
        w = _base_matrix(seed=seed)
        n = w.shape[0]
        rng = np.random.default_rng(seed)
        alive = (rng.random(n) < 0.6).astype(np.float32)
        if alive.sum() == 0:
            alive[0] = 1.0
        dead = alive <= 0
        keep = ~(dead[:, None] | dead[None, :])
        np.testing.assert_allclose(repair_for_link_drop(w, keep),
                                   repair_for_dropout(w, alive),
                                   atol=1e-12)


def test_push_sum_link_matrix_conserves_mass():
    rng = np.random.default_rng(7)
    for seed in range(6):
        w = _base_matrix(seed=seed)
        keep = rng.random(w.shape) > 0.5
        m = push_sum_link_matrix(w, keep)
        np.testing.assert_allclose(m.sum(axis=0), 1.0, atol=1e-12)
        assert m.min() >= 0.0


def test_split_by_delay_partitions_exactly():
    rng = np.random.default_rng(3)
    w = _base_matrix(seed=2)
    keep = rng.random(w.shape) > 0.3
    m = push_sum_link_matrix(w, keep)
    delay = rng.integers(0, 3, size=w.shape)
    mats = split_by_delay(m, delay, 2)
    assert mats.shape == (3, *w.shape)
    np.testing.assert_allclose(mats.sum(axis=0), m, atol=1e-6)
    # the diagonal is always immediate
    np.testing.assert_allclose(np.diagonal(mats[1]), 0.0)
    np.testing.assert_allclose(np.diagonal(mats[2]), 0.0)


# ---------------------------------------------------------------------------
# The correctness win, numpy packet simulation of the exact round math
# ---------------------------------------------------------------------------

def _simulate(plan, w0, x0, rounds, correction, delay_max):
    """Pure-numpy replica of the engines' link consensus: returns
    (estimates [W], mass [W], total_mass_trace).  x0 is [W] (one scalar
    coordinate per worker — consensus is coordinate-wise linear, so one
    coordinate captures the math)."""
    n = len(x0)
    x = x0.astype(np.float64).copy()
    if correction == "push_sum":
        mass = np.ones(n)
        buf_x = np.zeros((delay_max, n)) if delay_max else None
        buf_m = np.zeros((delay_max, n)) if delay_max else None
    else:
        hist = (np.stack([x0] * delay_max) if delay_max else None)
    trace = []
    for t in range(rounds):
        keep, delay = plan.link_for_round(t)
        if correction == "push_sum":
            m = push_sum_link_matrix(w0, keep)
            mats = split_by_delay(m, delay, delay_max)
            now_x = mats[0] @ x
            now_m = mats[0] @ mass
            if delay_max:
                now_x += buf_x[0]
                now_m += buf_m[0]
                arr_x = np.stack([mats[d] @ x
                                  for d in range(1, delay_max + 1)])
                arr_m = np.stack([mats[d] @ mass
                                  for d in range(1, delay_max + 1)])
                buf_x = np.vstack([buf_x[1:], np.zeros((1, n))]) + arr_x
                buf_m = np.vstack([buf_m[1:], np.zeros((1, n))]) + arr_m
            x, mass = now_x, now_m
            inflight = buf_m.sum() if delay_max else 0.0
            trace.append(mass.sum() + inflight)
        else:
            m = repair_for_link_drop(w0, keep)
            mats = split_by_delay(m, delay, delay_max)
            nxt = mats[0] @ x
            if delay_max:
                for d in range(1, delay_max + 1):
                    nxt += mats[d] @ hist[d - 1]
                hist = np.vstack([x[None], hist[:-1]])
            x = nxt
    if correction == "push_sum":
        return x / np.maximum(mass, 1e-300), mass, np.asarray(trace)
    return x, np.ones(n), np.asarray(trace)


def test_pushsum_unbiased_plain_biased_under_asymmetric_drop():
    n = 8
    w0 = _base_matrix(n)
    plan = _link_plan(n, msg_drop=0.3, msg_delay=0.3, msg_delay_max=2)
    rng = np.random.default_rng(1)
    x0 = rng.normal(size=n)
    true_mean = x0.mean()
    est_p, mass, trace = _simulate(plan, w0, x0, 400, "push_sum", 2)
    est_n, _, _ = _simulate(plan, w0, x0, 400, "none", 2)
    # push-sum: node mass + in-flight mass conserved at exactly n every
    # round, and the ratio estimate recovers the true mean
    np.testing.assert_allclose(trace, n, rtol=1e-7)
    np.testing.assert_allclose(est_p, true_mean, atol=1e-6)
    # plain gossip reached consensus — on the WRONG value
    assert np.ptp(est_n) < 1e-6
    assert abs(est_n.mean() - true_mean) > 1e-3


def test_pushsum_fixed_theta_consensus_exact():
    # every worker already agrees: drops/delays must not move anyone
    # (each packet's value mass is theta x its weight mass)
    n = 6
    w0 = _base_matrix(n)
    plan = _link_plan(n, msg_drop=0.4, msg_delay=0.5, msg_delay_max=2)
    x0 = np.full(n, 2.5)
    est, mass, trace = _simulate(plan, w0, x0, 60, "push_sum", 2)
    np.testing.assert_allclose(est, 2.5, atol=1e-9)
    np.testing.assert_allclose(trace, n, rtol=1e-7)


# Property-based sweep (hypothesis; guarded import as in
# test_topology_properties.py — the seeded sweeps above cover the same
# invariants without the dependency).
try:
    from hypothesis import given, settings, strategies as st
    _HAVE_HYP = True
except ImportError:                                    # pragma: no cover
    _HAVE_HYP = False


if _HAVE_HYP:
    @settings(max_examples=25, deadline=None)
    @given(n=st.integers(4, 9), seed=st.integers(0, 2**16),
           drop=st.floats(0.0, 0.6), delay=st.floats(0.0, 0.8),
           dmax=st.integers(1, 3))
    def test_pushsum_mass_conserved_under_arbitrary_traces(
            n, seed, drop, delay, dmax):
        w0 = _base_matrix(n, seed=seed)
        plan = FaultPlan(n, FaultConfig(msg_drop=min(drop, 0.99),
                                        msg_delay=delay,
                                        msg_delay_max=dmax), seed=seed)
        rng = np.random.default_rng(seed)
        x0 = rng.normal(size=n)
        rounds = 12
        est, mass, trace = _simulate(plan, w0, x0, rounds, "push_sum",
                                     plan.delay_max)
        # mass (nodes + in-flight) sums to n at EVERY round
        np.testing.assert_allclose(trace, n, rtol=1e-10)
        assert mass.min() > 0
        # the ratio estimate stays inside the convex hull of x0 —
        # unbiasedness's finite-round form (exact-mean recovery is the
        # 400-round test above)
        assert est.min() >= x0.min() - 1e-8
        assert est.max() <= x0.max() + 1e-8


# ---------------------------------------------------------------------------
# Engine integration (tiny logistic configs — tier-1 budget friendly)
# ---------------------------------------------------------------------------

_LDATA = DataConfig(dataset="synthetic", num_users=6, iid=True,
                    synthetic_train_size=96, synthetic_test_size=24)
_LMODEL = ModelConfig(model="logistic", num_classes=2, input_shape=(8,),
                      faithful=False)


def _gossip_cfg(faults=None, lr=0.0, **gkw):
    g = dict(algorithm="dsgd", topology="circle", mode="metropolis",
             rounds=4, local_ep=1, local_bs=16)
    g.update(gkw)
    return ExperimentConfig(name="t", seed=11, data=_LDATA, model=_LMODEL,
                            optim=OptimizerConfig(lr=lr, momentum=0.0),
                            gossip=GossipConfig(**g), faults=faults)


def _perturbed(trainer, seed=0):
    """Give each worker distinct parameters (they all share one init) so
    consensus has something to average; returns the true mean tree."""
    import jax

    from dopt.parallel.mesh import shard_worker_tree

    rng = np.random.default_rng(seed)
    host = jax.device_get(trainer.params)
    pert = jax.tree.map(
        lambda x: (x + rng.normal(0, 1, x.shape)).astype(x.dtype), host)
    trainer.params = shard_worker_tree(pert, trainer.mesh)
    return jax.tree.map(lambda x: x.mean(0), pert)


def test_engine_pushsum_recovers_true_mean_plain_biased(devices):
    # THE acceptance criterion: an lr=0 consensus task under asymmetric
    # msg_drop.  Plain gossip reaches consensus on a biased value;
    # correction='push_sum' recovers the true initial mean to tolerance.
    import jax

    from dopt.engine import GossipTrainer

    fc = FaultConfig(msg_drop=0.3)
    errs = {}
    for corr in ("none", "push_sum"):
        tr = GossipTrainer(_gossip_cfg(fc, correction=corr))
        tm = _perturbed(tr)
        tr.run(rounds=40)
        est = tr.worker_params()
        errs[corr] = max(jax.tree.leaves(jax.tree.map(
            lambda e, m: float(np.abs(e - m[None]).max()), est, tm)))
        spread = max(jax.tree.leaves(jax.tree.map(
            lambda e: float(np.ptp(e, axis=0).max()), est)))
        assert spread < 1e-3, f"{corr}: no consensus reached"
    assert errs["push_sum"] < 1e-3, errs
    assert errs["none"] > 10 * errs["push_sum"], errs
    # mass conservation end-to-end (no delays -> no in-flight component)
    tr_mass = np.asarray(tr._mass)
    np.testing.assert_allclose(tr_mass.sum(), 6.0, rtol=1e-5)


def test_staleness_beats_hard_drop_on_final_loss(devices):
    # Heavy straggler deadline: 80% of sampled clients miss it every
    # round.  Hard drop discards their work; staleness-aware
    # aggregation admits it a round or two late with decay weighting
    # and must end at a strictly better training loss.
    from dopt.engine import FederatedTrainer

    data = dataclasses.replace(_LDATA, num_users=8,
                               synthetic_train_size=256)
    # straggle=1.0: EVERY sampled client misses the server deadline
    # every round — hard drop aggregates nothing (theta frozen at
    # init), staleness-aware admission recovers the training run.
    base = FaultConfig(straggle=1.0, straggle_frac=0.5,
                       straggler_policy="drop", msg_delay_max=2)

    def cfg(**fkw):
        f = dict(algorithm="fedavg", frac=1.0, rounds=6, local_ep=1,
                 local_bs=16)
        f.update(fkw)
        return ExperimentConfig(name="t", seed=3, data=data, model=_LMODEL,
                                optim=OptimizerConfig(lr=0.3, momentum=0.5),
                                federated=FederatedConfig(**f), faults=base)

    h_drop = FederatedTrainer(cfg()).run(rounds=6)
    h_stale = FederatedTrainer(
        cfg(staleness_max=2, staleness_decay=0.7)).run(rounds=6)
    assert any(r["kind"] == "staleness" for r in h_stale.faults)
    # the global model is what staleness admission moves (per-worker
    # carried params keep drop semantics); under the universal deadline
    # miss, hard drop's theta never leaves init
    assert h_stale.rows[-1]["test_loss"] < 0.5 * h_drop.rows[-1]["test_loss"], (
        h_stale.rows[-1], h_drop.rows[-1])


def test_ledger_roundtrip_faults_json(tmp_path, devices):
    # --faults-json export == the in-History ledger, row for row,
    # link-fault and churn rows included (History.faults_to_json is
    # exactly what the CLI flag calls).
    from dopt.engine import GossipTrainer
    from dopt.utils.metrics import History

    fc = FaultConfig(crash=0.2, msg_drop=0.25, msg_delay=0.5,
                     msg_delay_max=2, churn=0.15, churn_span=2)
    tr = GossipTrainer(_gossip_cfg(fc, lr=0.05))
    h = tr.run(rounds=5)
    assert h.faults, "cocktail produced no ledger rows"
    kinds = set(r["kind"] for r in h.faults)
    assert {"msg_drop", "msg_delay", "churn"} <= kinds, kinds
    path = tmp_path / "ledger.json"
    h.faults_to_json(path)
    reloaded = History.faults_from_json(path)
    assert reloaded == h.faults
    for row in reloaded:
        assert set(row) == {"round", "worker", "kind", "action"}
        assert row["kind"] in KINDS
    with pytest.raises(ValueError, match="fault-ledger"):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"not": "a ledger"}))
        History.faults_from_json(bad)


def test_link_mode_validation(devices):
    from dopt.config import RobustConfig
    from dopt.engine import GossipTrainer

    fc = FaultConfig(msg_drop=0.2)
    with pytest.raises(ValueError, match="single-sweep"):
        GossipTrainer(_gossip_cfg(fc, algorithm="fedlcon", eps=2))
    with pytest.raises(ValueError, match="comm_dtype"):
        GossipTrainer(_gossip_cfg(fc, comm_dtype="bfloat16"))
    with pytest.raises(ValueError, match="does not compose"):
        GossipTrainer(ExperimentConfig(
            name="t", seed=1, data=_LDATA, model=_LMODEL,
            optim=OptimizerConfig(lr=0.1),
            gossip=GossipConfig(algorithm="dsgd", topology="circle",
                                mode="metropolis"),
            faults=fc, robust=RobustConfig(clip_radius=1.0)))
    # Quarantine, by contrast, now COMPOSES with link faults (it acts
    # through the alive machinery before the link repairs) — the
    # trainer must construct.
    GossipTrainer(ExperimentConfig(
        name="t", seed=1, data=_LDATA, model=_LMODEL,
        optim=OptimizerConfig(lr=0.1),
        gossip=GossipConfig(algorithm="dsgd", topology="circle",
                            mode="metropolis"),
        faults=fc, robust=RobustConfig(quarantine_after=2)))
    with pytest.raises(ValueError, match="unknown gossip correction"):
        GossipTrainer(_gossip_cfg(None, correction="psum"))
    with pytest.raises(ValueError, match="msg_drop"):
        FaultPlan(4, FaultConfig(msg_drop=1.0), seed=0)


def test_staleness_validation(devices):
    from dopt.config import RobustConfig
    from dopt.engine import FederatedTrainer

    def cfg(faults=None, robust=None, **fkw):
        f = dict(algorithm="fedavg", frac=0.5, rounds=2, local_ep=1,
                 local_bs=16)
        f.update(fkw)
        return ExperimentConfig(name="t", seed=1, data=_LDATA,
                                model=_LMODEL,
                                optim=OptimizerConfig(lr=0.1),
                                federated=FederatedConfig(**f),
                                faults=faults, robust=robust)

    with pytest.raises(ValueError, match="stateless-client"):
        FederatedTrainer(cfg(algorithm="scaffold", staleness_max=2))
    with pytest.raises(ValueError, match="weighted mean"):
        FederatedTrainer(cfg(
            staleness_max=2,
            robust=RobustConfig(aggregator="trimmed_mean")))
    with pytest.raises(ValueError, match="staleness_decay"):
        FederatedTrainer(cfg(staleness_max=2, staleness_decay=0.0))
    # inert staleness (nothing produces late updates) keeps the exact
    # clean program: bit-identical History to no staleness at all
    from dopt.engine import FederatedTrainer as FT

    h0 = FT(cfg()).run(rounds=2)
    h1 = FT(cfg(staleness_max=3)).run(rounds=2)
    assert h0.rows == h1.rows


# ---------------------------------------------------------------------------
# Heavyweight end-to-end (full cocktail) — outside the tier-1 budget
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("engine", ["gossip", "federated"])
def test_cocktail_resume_bit_exact(engine, tmp_path, devices):
    # Full degraded-network cocktail, killed at round 2 and resumed:
    # History rows AND fault ledger bit-identical to the continuous
    # run (push-sum mass, staleness buffers and link history all ride
    # the checkpoint).
    from dopt.engine import FederatedTrainer, GossipTrainer

    if engine == "gossip":
        fc = FaultConfig(crash=0.1, msg_drop=0.2, msg_delay=0.3,
                         msg_delay_max=2, churn=0.1, churn_span=2)

        def mk():
            return GossipTrainer(_gossip_cfg(fc, lr=0.1,
                                             correction="push_sum"))
    else:
        fc = FaultConfig(crash=0.1, straggle=0.5, straggle_frac=0.5,
                         straggler_policy="drop", msg_drop=0.1,
                         msg_delay=0.3, msg_delay_max=2, churn=0.1,
                         churn_span=2)

        def mk():
            return FederatedTrainer(ExperimentConfig(
                name="t", seed=7, data=_LDATA, model=_LMODEL,
                optim=OptimizerConfig(lr=0.1, momentum=0.5),
                federated=FederatedConfig(algorithm="fedavg", frac=0.5,
                                          rounds=4, local_ep=1,
                                          local_bs=16, staleness_max=2),
                faults=fc))

    path = os.fspath(tmp_path / engine)
    hc = mk().run(rounds=4)
    part = mk()
    part.run(rounds=2, checkpoint_every=2, checkpoint_path=path)
    res = mk()
    res.restore(path)
    assert res.round == 2
    hr = res.run(rounds=2)
    assert hr.rows == hc.rows
    assert hr.faults == hc.faults


@pytest.mark.slow
def test_gossip_churn_blocked_matches_per_round(devices):
    # Churn without link faults rides the ordinary consensus path, so
    # fused-block execution must stay bit-identical to per-round.
    from dopt.engine import GossipTrainer

    fc = FaultConfig(churn=0.2, churn_span=2, crash=0.1)
    ha = GossipTrainer(_gossip_cfg(fc, lr=0.1)).run(rounds=4, block=1)
    hb = GossipTrainer(_gossip_cfg(fc, lr=0.1)).run(rounds=4, block=4)
    assert ha.rows == hb.rows
    assert ha.faults == hb.faults
    assert any(r["kind"] == "churn" for r in ha.faults)


@pytest.mark.slow
def test_chaos_soak_smoke(tmp_path):
    # The shipped harness end-to-end: convergence + ledger + checkpoint
    # invariants under the randomized cocktail, both engines.
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "chaos_soak", os.path.join(os.path.dirname(__file__), "..",
                                   "scripts", "chaos_soak.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main(["--rounds", "4", "--seed", "0",
                     "--ckpt-dir", os.fspath(tmp_path)]) == 0


@pytest.mark.slow
def test_cli_faults_json_roundtrip(tmp_path, devices):
    # The real CLI flag: --faults-json writes a ledger a reconstructed
    # identical run reproduces row-for-row (stateless draws).
    from dopt.run import main
    from dopt.utils.metrics import History

    out = tmp_path / "ledger.json"
    rc = main(["--preset", "baseline1-lossy", "--rounds", "2",
               "--num-users", "4", "--synthetic-scale", "0.005",
               "--faults-json", os.fspath(out)])
    assert rc == 0 and out.exists()
    exported = History.faults_from_json(out)
    assert exported and all(r["kind"] in KINDS for r in exported)
    # reconstruct the CLI's exact config and rerun: identical ledger
    import dataclasses as dc

    from dopt.engine import GossipTrainer
    from dopt.presets import get_preset

    cfg = get_preset("baseline1-lossy")
    cfg = cfg.replace(data=dc.replace(
        cfg.data, num_users=4,
        synthetic_train_size=max(int(cfg.data.synthetic_train_size * 0.005),
                                 4 * 8),
        synthetic_test_size=max(int(cfg.data.synthetic_test_size * 0.005),
                                64)))
    h = GossipTrainer(cfg).run(rounds=2)
    assert h.faults == exported
