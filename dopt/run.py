"""CLI experiment runner: ``python -m dopt.run --preset reference-fedavg``.

The typed replacement for the reference's notebook driver cells: pick a
preset (or override fields), run, print per-round metrics, export the
history CSV in the reference's results layout, optionally checkpoint.
"""

from __future__ import annotations

import argparse
import json
import sys


def build_trainer(cfg):
    from dopt.engine import FederatedTrainer, GossipTrainer

    if cfg.federated is not None:
        return FederatedTrainer(cfg)
    return GossipTrainer(cfg)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--preset", required=True,
                    help="preset name (see dopt.presets.PRESETS) or 'list'")
    ap.add_argument("--rounds", type=int, default=None,
                    help="override round count")
    ap.add_argument("--num-users", type=int, default=None)
    ap.add_argument("--synthetic-scale", type=float, default=None,
                    help="scale synthetic dataset sizes (e.g. 0.1 for smoke)")
    ap.add_argument("--csv", default=None, help="write history CSV here")
    ap.add_argument("--checkpoint", default=None,
                    help="save a checkpoint here after the run")
    ap.add_argument("--resume", default=None,
                    help="restore this checkpoint before running")
    ap.add_argument("--timers", action="store_true",
                    help="print phase-timer report")
    ap.add_argument("--trace", default=None, metavar="DIR",
                    help="capture a jax/XLA profiler trace of the run "
                         "into DIR (view with tensorboard or xprof)")
    args = ap.parse_args(argv)

    from dopt.presets import PRESETS, get_preset

    if args.preset == "list":
        for name in sorted(PRESETS):
            print(name)
        return 0

    import dataclasses

    cfg = get_preset(args.preset)
    if args.num_users is not None:
        cfg = cfg.replace(data=dataclasses.replace(cfg.data,
                                                   num_users=args.num_users))
    if args.synthetic_scale is not None:
        cfg = cfg.replace(data=dataclasses.replace(
            cfg.data,
            synthetic_train_size=max(int(cfg.data.synthetic_train_size
                                         * args.synthetic_scale),
                                     cfg.data.num_users * 8),
            synthetic_test_size=max(int(cfg.data.synthetic_test_size
                                        * args.synthetic_scale), 64),
        ))

    from dopt.config import exp_details

    print(exp_details(cfg), file=sys.stderr)
    trainer = build_trainer(cfg)
    if args.resume:
        trainer.restore(args.resume)
        print(f"resumed at round {trainer.round}", file=sys.stderr)

    rounds = args.rounds
    if rounds is None:
        rounds = (cfg.federated.rounds if cfg.federated is not None
                  else cfg.gossip.rounds)
    if args.trace:
        from dopt.utils.profiling import trace

        with trace(args.trace):
            trainer.run(rounds=rounds)
        print(f"wrote XLA trace to {args.trace}", file=sys.stderr)
    else:
        trainer.run(rounds=rounds)
    for row in trainer.history.rows[-min(rounds, len(trainer.history)):]:
        print(json.dumps(row))
    print(f"total_time_s={trainer.total_time:.2f}", file=sys.stderr)

    if args.timers:
        print(trainer.timers.report(), file=sys.stderr)
    if args.csv:
        trainer.history.to_csv(args.csv)
        print(f"wrote {args.csv}", file=sys.stderr)
    if args.checkpoint:
        trainer.save(args.checkpoint)
        print(f"checkpointed to {args.checkpoint}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
