"""History plotting (reference ``plot``/``servers_plot`` equivalents).

Recreates the reference's comparison plots — ``Server.plot`` per-client
grids (servers.py:95-120) and ``servers_plot`` cross-experiment curves
(P1 utils.py:29-51, P2 utils.py:26-48) — from ``History`` objects.
Matplotlib only; import is deferred so headless/metric-only use never
pays for it.
"""

from __future__ import annotations

from pathlib import Path
from typing import Mapping, Sequence

from dopt.utils.metrics import History


def compare_histories(
    histories: Mapping[str, History] | Sequence[tuple[str, History]],
    *,
    metrics: Sequence[str] = ("avg_test_acc", "avg_test_loss", "avg_train_loss"),
    title: str = "",
    save: str | Path | None = None,
):
    """Cross-experiment comparison grid (the ``servers_plot`` shape:
    one panel per metric, one labelled curve per experiment)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    items = list(histories.items()) if isinstance(histories, Mapping) else list(histories)
    n = len(metrics)
    fig, axes = plt.subplots(1, n, figsize=(5 * n, 4))
    if n == 1:
        axes = [axes]
    for ax, metric in zip(axes, metrics):
        for label, h in items:
            xs = [r["round"] for r in h if metric in r]
            ys = [r[metric] for r in h if metric in r]
            if xs:
                ax.plot(xs, ys, marker="o", markersize=3, label=label)
        ax.set_xlabel("round")
        ax.set_ylabel(metric)
        ax.grid(alpha=0.3)
        ax.legend(fontsize=8)
    if title:
        fig.suptitle(title)
    fig.tight_layout()
    if save is not None:
        fig.savefig(save, dpi=120)
        plt.close(fig)
        return Path(save)
    return fig
